"""Gradient-accumulation microbatching (--grad_accum_steps, PR 1) on the
8-virtual-device CPU mesh at fp32: K=4 must reproduce the K=1 trajectory
(losses AND final params) on the dense, MoE-aux, ZeRO-2, and remat-window
paths — accumulation must not change the math, only the peak memory.
Plus validate()-rejection cases and the K=1 no-scan-wrapper guarantee.
"""

import jax
import numpy as np
import pytest

from tests.test_train_smoke import (build_train_objects, random_batch,
                                    run_steps, tiny_cfg)


def _params_close(a, b, rtol=1e-5, atol=1e-6):
    flat_a = jax.tree.leaves(a)
    flat_b = jax.tree.leaves(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=atol)


def _equivalence(cfg_kw, n_steps=3):
    # batch 32 so the K=4 microbatch (8) still covers the 8 batch devices
    state_1, losses_1 = run_steps(tiny_cfg(batch_size=32, **cfg_kw),
                                  n_steps=n_steps)
    state_k, losses_k = run_steps(tiny_cfg(batch_size=32, grad_accum_steps=4,
                                           **cfg_kw), n_steps=n_steps)
    assert all(np.isfinite(losses_k))
    np.testing.assert_allclose(losses_k, losses_1, rtol=1e-5)
    _params_close(state_k.params, state_1.params)


def test_dense_equivalence(devices8):
    """Manual fp32 accumulation: exact vs K=1 by linearity of the gradient
    in the per-sample loss mean."""
    _equivalence({})


def test_moe_equivalence(devices8):
    """The load-balance aux couples microbatches (full-batch ingredient
    means before the frac*prob product) — the through-scan objective must
    still match K=1 exactly, not just approximately."""
    _equivalence(dict(moe_experts=4))


def test_moe_remat_window_equivalence(devices8):
    """MoE + --remat_window under accumulation: the windowed forward's raw
    aux-ingredient stacks feed the through-scan objective."""
    _equivalence(dict(moe_experts=4, remat_window=2))


def test_zero2_equivalence(devices8):
    """ZeRO-2: the step-top full gather is scan-invariant (one gather, K
    reuses) and grads accumulate at the SHARDED layout."""
    _equivalence(dict(reshard_after_forward=False))


def test_remat_window_equivalence(devices8):
    _equivalence(dict(remat_window=2))


def test_dropout_deterministic_per_microbatch(devices8):
    """Under dropout each microbatch folds its index into the step rng:
    the K>1 trajectory is deterministic given the seed, and differs from
    K=1 (different masks — by design, not a bug)."""
    kw = dict(att_dropout=0.1, mlp_dropout=0.1, pos_dropout=0.1)
    _, a = run_steps(tiny_cfg(grad_accum_steps=2, **kw), n_steps=2)
    _, b = run_steps(tiny_cfg(grad_accum_steps=2, **kw), n_steps=2)
    np.testing.assert_array_equal(a, b)
    _, base = run_steps(tiny_cfg(**kw), n_steps=2)
    assert all(np.isfinite(a))
    assert not np.allclose(a, base, rtol=1e-6)


def test_k1_compiles_without_scan_wrapper(devices8):
    """grad_accum_steps=1 must trace the exact pre-accumulation program: no
    accumulation while-loop in the lowered step (scan_blocks/remat off so
    the only possible loop would be the accumulation scan), while K=2
    introduces one."""
    def lowered_text(cfg):
        mesh, state, step_fn, _ = build_train_objects(cfg)
        batch = random_batch(cfg, mesh)
        return step_fn.lower(state, batch, jax.random.key(0)).as_text()

    base = dict(scan_blocks=False, grad_ckpt=False)
    assert "stablehlo.while" not in lowered_text(tiny_cfg(**base))
    assert "stablehlo.while" in lowered_text(
        tiny_cfg(grad_accum_steps=2, **base))


def test_validate_rejects_bad_accum():
    with pytest.raises(AssertionError, match="grad_accum_steps"):
        tiny_cfg(grad_accum_steps=0)
    with pytest.raises(AssertionError, match="not divisible"):
        tiny_cfg(grad_accum_steps=3)  # 16 % 3 != 0
    with pytest.raises(AssertionError, match="pipeline already microbatches"):
        tiny_cfg(grad_accum_steps=2, pp_size=2)


def test_validate_rejects_bad_dropout_rates():
    # rate >= 1 would turn the kernels' 1/(1-rate) rescale into inf/NaN
    for kw in (dict(att_dropout=1.0), dict(pos_dropout=-0.1),
               dict(mlp_dropout=1.5)):
        with pytest.raises(AssertionError, match="must be in"):
            tiny_cfg(**kw)
    tiny_cfg(att_dropout=0.0, mlp_dropout=0.999)  # boundary values pass
