"""vitax.serve.fleet: replica rotation, least-loaded routing, admission.

Fast tier pins the fleet behaviors against in-process fakes (stdlib HTTP
stubs as replicas, injected spawn/clock/http_get for the manager — no jax,
no subprocesses): least-loaded dispatch, ejection on failing /healthz,
re-admission, one-retry-on-dispatch-failure, 429 + Retry-After under
overload, fleet /metrics aggregation, plus the single-engine satellites
(readiness split, bounded queue -> 503 queue_full, configurable request
timeout, graceful drain). One `slow` e2e runs 2 real replicas from a
2-step fake-data checkpoint, kills one mid-burst, and asserts zero
client-visible errors, re-admission after the supervised restart, and
clean SIGTERM drains (exit 0).
"""

import base64
import io
import json
import os
import socket
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from vitax import faults
from vitax.config import Config
from vitax.serve.fleet import (
    DEAD,
    EJECTED,
    READY,
    STARTING,
    AdmissionController,
    Autoscaler,
    PredictionCache,
    ReplicaManager,
    Router,
    start_router,
    stop_router,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_cfg(**kw):
    base = dict(
        image_size=16, patch_size=8, embed_dim=32, num_heads=2, num_blocks=2,
        num_classes=4, batch_size=16, dtype="float32", lr=1e-3, warmup_steps=2,
        serve_max_batch=4, serve_topk=3, max_batch_wait_ms=10.0, seed=0,
    )
    base.update(kw)
    return Config(**base).validate()


def get_json(url: str, timeout: float = 30.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.load(resp)


def post_bytes(url: str, body: bytes, content_type: str = "image/png",
               timeout: float = 60.0) -> dict:
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": content_type})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


def png_bytes(size: int = 16, seed: int = 0) -> bytes:
    from PIL import Image
    rng = np.random.default_rng(seed)
    arr = rng.integers(0, 256, size=(size, size, 3), dtype=np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr, "RGB").save(buf, "PNG")
    return buf.getvalue()


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class DummyRecorder:
    """Captures telemetry events: [(kind, payload), ...]."""

    def __init__(self):
        self.events = []

    def event(self, kind, **payload):
        self.events.append((kind, payload))

    def kinds(self):
        return [k for k, _ in self.events]

    def close(self):
        pass


class FakeReplica:
    """In-process stand-in for one `python -m vitax.serve` replica: the same
    three endpoints, with dials for every failure mode the fleet must
    handle (dead healthz, ready: false, 500 predicts, queue-full 503,
    slow predicts, held predicts)."""

    def __init__(self, name: str):
        self.name = name
        self.live = True            # False: /healthz answers 500
        self.ready = True           # healthz "ready" field
        self.fail_predicts = False  # /predict answers 500
        self.bad_request = False    # /predict answers 400 (client's fault)
        self.queue_full = False     # /predict answers 503 reason queue_full
        self.batch_unsupported = False  # /predict_batch answers 404 (old binary)
        self.latency_s = 0.0
        self.hold = None            # Event: /predict blocks until set
        self.predict_started = threading.Event()
        self.predict_count = 0
        self._lock = threading.Lock()
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003
                pass

            def _reply(self, code, payload, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    if not fake.live:
                        self._reply(500, {"error": "unhealthy"})
                    else:
                        self._reply(200, {"status": "ok",
                                          "ready": fake.ready})
                elif self.path == "/metrics":
                    self._reply(200, {"requests_total": fake.predict_count,
                                      "marker": fake.name,
                                      "weights_dtype": "int8",
                                      "param_bytes": 1000})
                else:
                    self._reply(404, {})

            def do_POST(self):  # noqa: N802
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                if self.path == "/predict_batch" and fake.batch_unsupported:
                    self._reply(404, {"error": f"unknown path {self.path}"})
                    return
                if fake.queue_full:
                    self._reply(503, {"error": "overloaded",
                                      "reason": "queue_full"},
                                headers={"Retry-After": "2"})
                    return
                if fake.bad_request:
                    self._reply(400, {"error": "bad request: not an image"})
                    return
                if fake.fail_predicts:
                    self._reply(500, {"error": "replica exploded"})
                    return
                fake.predict_started.set()
                if fake.hold is not None:
                    fake.hold.wait(timeout=30)
                if fake.latency_s:
                    time.sleep(fake.latency_s)
                with fake._lock:
                    fake.predict_count += 1
                self._reply(200, {"classes": [1, 0, 2],
                                  "probs": [0.5, 0.3, 0.2],
                                  "latency_ms": 1.0,
                                  "replica": fake.name})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture
def fleet_factory():
    """Builds (manager, router, url, fakes) fleets over FakeReplicas and
    tears everything down afterwards."""
    cleanup = []

    def build(n=2, admission=None, recorder=None, **manager_kw):
        manager_kw.setdefault("fail_threshold", 2)
        fakes = [FakeReplica("abcdefgh"[i]) for i in range(n)]
        manager = ReplicaManager(recorder=recorder, **manager_kw)
        for f in fakes:
            manager.adopt(f.url, name=f.name)
        manager.poll_once()  # admit everyone
        router = Router(manager, admission=admission, recorder=recorder,
                        request_timeout_s=10.0)
        httpd = start_router(router, 0)
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        cleanup.append((httpd, fakes))
        return manager, router, url, fakes

    yield build
    for httpd, fakes in cleanup:
        stop_router(httpd)
        for f in fakes:
            f.stop()


# --- shared supervise seams ---------------------------------------------------


def test_backoff_delay_sequence():
    """The fleet restarts replicas on the exact capped-exponential schedule
    vitax.supervise pins for training restarts (shared seam)."""
    from vitax.supervise import backoff_delay
    assert [backoff_delay(n, 1.0, 60.0) for n in range(1, 9)] == \
        [1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 60.0, 60.0]
    assert backoff_delay(1, 0.5, 30.0) == 0.5
    assert backoff_delay(2, 0.5, 30.0) == 1.0


# --- admission control --------------------------------------------------------


def test_admission_admits_before_first_observation():
    a = AdmissionController(deadline_ms=100.0)
    assert a.check(depth=50, ready_replicas=1) is None
    assert a.admitted_total == 1 and a.shed_total == 0


def test_admission_disabled_when_deadline_zero():
    a = AdmissionController(deadline_ms=0.0)
    a.observe(5.0)
    assert a.check(depth=1000, ready_replicas=1) is None
    assert a.shed_total == 0


def test_admission_sheds_with_retry_after():
    rec = DummyRecorder()
    a = AdmissionController(deadline_ms=100.0, recorder=rec)
    a.observe(1.0)  # EWMA service time 1s
    # predicted wait = 3 * 1.0 / 2 = 1.5s > 0.1s deadline -> shed,
    # Retry-After = ceil(1.5 - 0.1) = 2
    assert a.check(depth=3, ready_replicas=2) == 2
    assert a.shed_total == 1
    kind, payload = rec.events[-1]
    assert kind == "admission" and payload["decision"] == "shed"
    assert payload["retry_after_s"] == 2
    # empty fleet queue admits (predicted 0)
    assert a.check(depth=0, ready_replicas=2) is None
    # more replicas absorb the same depth
    a2 = AdmissionController(deadline_ms=600.0)
    a2.observe(1.0)
    assert a2.check(depth=1, ready_replicas=2) is None   # 0.5s <= 0.6s
    assert a2.check(depth=4, ready_replicas=2) is not None  # 2.0s > 0.6s


def test_admission_ewma_and_record_shed():
    a = AdmissionController(deadline_ms=100.0, ewma_alpha=0.2)
    a.observe(1.0)
    a.observe(0.0)
    assert abs(a.ewma_service_s - 0.8) < 1e-9
    rec_before = a.shed_total
    a.record_shed(reason="replica_queue_full", replica="a")
    assert a.shed_total == rec_before + 1
    snap = a.snapshot()
    assert snap["shed_total"] == a.shed_total
    assert snap["deadline_ms"] == 100.0


def test_admission_warming_capacity_discount():
    """Mid-scale-out the shed rate drops: a live-but-warming replica counts
    at --warming_capacity_frac (it will be serving within one warmup), so
    the prediction relaxes toward the NEW capacity instead of shedding at
    the old estimate until the first replica flips ready."""
    a = AdmissionController(deadline_ms=800.0)
    a.observe(1.0)  # EWMA service time 1s
    # 1 ready, no scale-out in progress: predicted 1.0s > 0.8s -> shed
    assert a.check(depth=1, ready_replicas=1) is not None
    # same load mid-scale-out: the warming replica counts at 0.5, so
    # predicted = 1 * 1.0 / 1.5 = 0.67s <= 0.8s -> admitted again
    assert a.check(depth=1, ready_replicas=1, warming_replicas=1) is None
    assert a.shed_total == 1  # the warming credit IS the shed-rate drop
    # the shed event records how many warming replicas were credited
    rec = DummyRecorder()
    b = AdmissionController(deadline_ms=800.0, recorder=rec)
    b.observe(1.0)
    assert b.check(depth=3, ready_replicas=1, warming_replicas=1) is not None
    assert rec.events[-1][1]["warming_replicas"] == 1
    # frac 0 restores the pre-autoscale behavior: warming buys nothing
    c = AdmissionController(deadline_ms=800.0, warming_capacity_frac=0.0)
    c.observe(1.0)
    assert c.check(depth=1, ready_replicas=1, warming_replicas=5) is not None
    assert a.snapshot()["warming_capacity_frac"] == 0.5
    with pytest.raises(AssertionError):
        AdmissionController(deadline_ms=100.0, warming_capacity_frac=1.5)


# --- replica manager (injected seams; no sockets, no processes) ---------------


def _never(url, timeout):
    raise ConnectionError("unreachable")


def test_manager_acquire_least_loaded_and_release_accounting():
    m = ReplicaManager(http_get=_never)
    a = m.adopt("http://a", name="a")
    b = m.adopt("http://b", name="b")
    a.state = b.state = READY
    a.ewma_latency_s, b.ewma_latency_s = 0.5, 0.1
    # tie on in_flight (0) -> lower EWMA wins
    assert m.acquire() is b and b.in_flight == 1
    # now a is least-loaded
    assert m.acquire() is a
    # exclusion (the one-retry path) skips a
    assert m.acquire(exclude={"a"}) is b and b.in_flight == 2
    assert m.total_in_flight() == 3
    # successful release: EWMA folds in, counters move
    m.release(b, latency_s=0.3, ok=True)
    assert b.in_flight == 1 and b.requests_total == 1
    assert abs(b.ewma_latency_s - (0.2 * 0.3 + 0.8 * 0.1)) < 1e-9
    # failed release: no EWMA pollution, failure counted
    m.release(a, ok=False)
    assert a.dispatch_failures == 1 and a.requests_total == 0
    assert a.ewma_latency_s == 0.5
    # first observation seeds the EWMA directly
    m.release(b, latency_s=0.2, ok=True)
    c = m.adopt("http://c", name="c")
    c.state = READY
    got = m.acquire(exclude={"a", "b"})
    m.release(got, latency_s=0.7, ok=True)
    assert c.ewma_latency_s == 0.7
    # nothing READY -> None
    a.state = b.state = c.state = EJECTED
    assert m.acquire() is None


def test_manager_eject_and_readmit_via_healthz():
    rec = DummyRecorder()
    state = {"resp": {"status": "ok", "ready": True}}

    def http_get(url, timeout):
        if isinstance(state["resp"], Exception):
            raise state["resp"]
        return state["resp"]

    m = ReplicaManager(recorder=rec, http_get=http_get, fail_threshold=2)
    r = m.adopt("http://a", name="a")
    assert r.state == STARTING
    m.poll_once()
    assert r.state == READY
    # one failed poll tolerated (fail_threshold=2), second ejects
    state["resp"] = ConnectionError("down")
    m.poll_once()
    assert r.state == READY and r.health_failures == 1
    m.poll_once()
    assert r.state == EJECTED
    # live but warming/draining (ready: false) stays out of rotation and
    # does NOT count as a health failure
    state["resp"] = {"status": "ok", "ready": False}
    m.poll_once()
    assert r.state == EJECTED and r.health_failures == 0
    # recovered: re-admitted
    state["resp"] = {"status": "ok", "ready": True}
    m.poll_once()
    assert r.state == READY
    kinds = rec.kinds()
    assert kinds.count("replica_eject") == 1
    assert kinds.count("replica_admit") == 2  # initial admit + re-admit


def test_manager_ready_not_ready_ejects_ready_replica():
    """A READY replica reporting ready: false (it started draining) is
    ejected immediately — not after fail_threshold polls."""
    rec = DummyRecorder()
    state = {"resp": {"status": "ok", "ready": True}}
    m = ReplicaManager(recorder=rec, fail_threshold=5,
                       http_get=lambda url, t: state["resp"])
    r = m.adopt("http://a", name="a")
    m.poll_once()
    assert r.state == READY
    state["resp"] = {"status": "ok", "ready": False}
    m.poll_once()
    assert r.state == EJECTED
    assert ("replica_eject", {"replica": "a", "reason": "not_ready"}) \
        in rec.events


class FakeProc:
    """Popen stand-in with a settable return code."""

    def __init__(self):
        self.rc = None
        self.signals = []

    def poll(self):
        return self.rc

    def send_signal(self, sig):
        self.signals.append(sig)
        self.rc = 0

    def kill(self):
        self.rc = -9


def test_manager_restarts_dead_replica_with_backoff():
    rec = DummyRecorder()
    spawned = []

    def spawn(argv):
        p = FakeProc()
        spawned.append(p)
        return p

    m = ReplicaManager(recorder=rec, spawn=spawn, http_get=_never,
                       backoff_s=0.5, backoff_max_s=30.0, max_restarts=2,
                       clock=lambda: 0.0)
    r = m.manage(["serve", "cmd"], "http://a", name="a")
    assert len(spawned) == 1 and r.managed
    # death -> DEAD immediately, respawn gated behind backoff_delay(1)=0.5s
    spawned[0].rc = 1
    m.poll_once(now=100.0)
    assert r.state == DEAD and r.exit_code == 1
    m.poll_once(now=100.2)
    assert len(spawned) == 1  # still inside the backoff window
    m.poll_once(now=100.6)
    assert len(spawned) == 2 and r.state == STARTING
    assert r.restarts == 1 and m.restart_total == 1
    # second death -> backoff doubles to 1.0s
    spawned[1].rc = -9
    m.poll_once(now=200.0)
    assert r.state == DEAD
    m.poll_once(now=200.7)
    assert len(spawned) == 2
    m.poll_once(now=201.1)
    assert len(spawned) == 3 and r.restarts == 2
    # max_restarts=2 exhausted: a third death is final
    spawned[2].rc = 1
    m.poll_once(now=300.0)
    m.poll_once(now=400.0)
    assert len(spawned) == 3 and r.state == DEAD
    kinds = rec.kinds()
    assert kinds.count("replica_spawn") == 1
    assert kinds.count("replica_exit") == 3
    assert kinds.count("replica_restart") == 2


def test_manager_adopted_replicas_are_never_restarted():
    spawned = []
    m = ReplicaManager(spawn=lambda argv: spawned.append(argv),
                       http_get=_never)
    r = m.adopt("http://a", name="a")
    assert not r.managed
    for now in (0.0, 10.0, 1000.0):
        m.poll_once(now=now)
    assert spawned == []


# --- fleet CLI argv plumbing ---------------------------------------------------


def test_strip_flags_and_replica_argv():
    from vitax.serve.fleet.__main__ import (
        _FLEET_ONLY_FLAGS, replica_argv, strip_flags)
    argv = ["--replicas", "3", "--embed_dim", "32", "--slo_p99_ms=250",
            "--serve_port", "8000", "--metrics_dir=/m", "--fake_data",
            "--base_port", "9000"]
    assert strip_flags(argv, _FLEET_ONLY_FLAGS) == \
        ["--embed_dim", "32", "--fake_data"]
    child = replica_argv(argv, 8101, metrics_dir="/m/replica_1")
    assert child[:3] == [sys.executable, "-m", "vitax.serve"]
    assert "--replicas" not in child and "--slo_p99_ms" not in child
    i = child.index("--serve_port")
    assert child[i + 1] == "8101"
    j = child.index("--metrics_dir")
    assert child[j + 1] == "/m/replica_1"
    # no per-replica metrics dir -> flag not re-issued
    assert "--metrics_dir" not in replica_argv(argv, 8102)


# --- router over fake replicas --------------------------------------------------


def test_router_round_trip_healthz_and_404(fleet_factory):
    manager, router, url, fakes = fleet_factory(n=2)
    resp = post_bytes(url + "/predict", b"anything",
                      content_type="application/octet-stream")
    assert resp["classes"] == [1, 0, 2]
    assert resp["replica"] in ("a", "b")
    health = get_json(url + "/healthz")
    assert health["status"] == "ok" and health["ready"] is True
    assert health["replicas"] == {"a": READY, "b": READY}
    with pytest.raises(urllib.error.HTTPError) as e:
        get_json(url + "/nope")
    assert e.value.code == 404


def test_router_least_loaded_dispatch(fleet_factory):
    manager, router, url, fakes = fleet_factory(n=2)
    a, b = fakes
    a.hold = threading.Event()  # a's next predict blocks
    held = threading.Thread(
        target=lambda: post_bytes(url + "/predict", b"x"), daemon=True)
    held.start()
    assert a.predict_started.wait(timeout=10)  # the first pick is a
    # with a busy (in_flight 1), the next request must go to b
    resp = post_bytes(url + "/predict", b"y")
    assert resp["replica"] == "b"
    a.hold.set()
    held.join(timeout=10)
    assert a.predict_count == 1 and b.predict_count == 1
    assert manager.total_in_flight() == 0  # every acquire was released


def test_router_ejection_and_readmission(fleet_factory):
    rec = DummyRecorder()
    manager, router, url, fakes = fleet_factory(n=2, recorder=rec)
    a, b = fakes
    a.live = False
    manager.poll_once()
    manager.poll_once()  # fail_threshold=2
    assert manager.ready_count() == 1
    assert get_json(url + "/healthz")["replicas"]["a"] == EJECTED
    # every dispatch lands on b while a is out of rotation
    for _ in range(4):
        assert post_bytes(url + "/predict", b"x")["replica"] == "b"
    # recovery: one live-and-ready poll re-admits
    a.live = True
    manager.poll_once()
    assert manager.ready_count() == 2
    # a is cold (in_flight 0, no EWMA) so the next pick is a
    assert post_bytes(url + "/predict", b"x")["replica"] == "a"
    assert "replica_eject" in rec.kinds() and "replica_admit" in rec.kinds()


def test_router_one_retry_on_dispatch_failure(fleet_factory):
    manager, router, url, fakes = fleet_factory(n=2)
    a, b = fakes
    a.fail_predicts = True  # both idle -> a is picked first (list order)
    resp = post_bytes(url + "/predict", b"x")
    assert resp["replica"] == "b"  # retried on the other replica
    assert router.metrics.retries_total == 1
    assert manager.replicas[0].dispatch_failures == 1
    # both failing -> 503 dispatch_failed (one retry, not an infinite loop)
    b.fail_predicts = True
    with pytest.raises(urllib.error.HTTPError) as e:
        post_bytes(url + "/predict", b"x")
    assert e.value.code == 503
    assert json.load(e.value)["reason"] == "dispatch_failed"
    assert manager.total_in_flight() == 0


def test_router_503_when_no_ready_replicas(fleet_factory):
    manager, router, url, fakes = fleet_factory(n=1)
    fakes[0].live = False
    manager.poll_once()
    manager.poll_once()
    with pytest.raises(urllib.error.HTTPError) as e:
        post_bytes(url + "/predict", b"x")
    assert e.value.code == 503
    assert json.load(e.value)["reason"] == "no_ready_replicas"


def test_router_admission_shed_429_with_retry_after(fleet_factory):
    rec = DummyRecorder()
    admission = AdmissionController(deadline_ms=100.0, recorder=rec)
    manager, router, url, fakes = fleet_factory(n=2, admission=admission)
    admission.observe(1.0)  # slow fleet: EWMA service 1s
    # fake a deep queue: 3 in flight over 2 replicas -> predicted 1.5s
    for _ in range(3):
        manager.acquire()
    with pytest.raises(urllib.error.HTTPError) as e:
        post_bytes(url + "/predict", b"x")
    assert e.value.code == 429
    assert int(e.value.headers["Retry-After"]) >= 1
    assert json.load(e.value)["reason"] == "admission"
    assert admission.shed_total == 1 and router.metrics.shed_total == 1
    assert "admission" in rec.kinds()
    # the shed never reached a replica
    assert fakes[0].predict_count == 0 and fakes[1].predict_count == 0


def test_router_maps_replica_queue_full_to_429(fleet_factory):
    admission = AdmissionController(deadline_ms=0.0)  # shedding off
    manager, router, url, fakes = fleet_factory(n=1, admission=admission)
    fakes[0].queue_full = True
    with pytest.raises(urllib.error.HTTPError) as e:
        post_bytes(url + "/predict", b"x")
    assert e.value.code == 429
    # the replica's own Retry-After passes through
    assert e.value.headers["Retry-After"] == "2"
    assert json.load(e.value)["reason"] == "replica_queue_full"
    assert admission.shed_total == 1  # counted in fleet shed accounting


def test_router_passes_client_errors_through(fleet_factory):
    """A replica 4xx is the client's fault: passed through verbatim, never
    retried on another replica (a retry would just fail the same way)."""
    manager, router, url, fakes = fleet_factory(n=2)
    a, b = fakes
    a.bad_request = True  # both idle -> a is picked first (list order)
    with pytest.raises(urllib.error.HTTPError) as e:
        post_bytes(url + "/predict", b"not an image")
    assert e.value.code == 400
    assert "bad request" in json.load(e.value)["error"]
    assert b.predict_count == 0  # never retried elsewhere
    assert router.metrics.retries_total == 0
    assert router.metrics.errors_total == 1
    assert manager.total_in_flight() == 0


def test_fleet_metrics_aggregation(fleet_factory):
    admission = AdmissionController(deadline_ms=500.0)
    manager, router, url, fakes = fleet_factory(n=2, admission=admission)
    for i in range(6):
        post_bytes(url + "/predict", b"x")
    snap = get_json(url + "/metrics")
    assert snap["requests_total"] == 6 and snap["errors_total"] == 0
    for key in ("latency_s_p50", "latency_s_p95", "latency_s_p99"):
        assert snap[key] is not None and snap[key] > 0
    assert snap["fleet"] == {"size": 2, "ready": 2, "warming": 0,
                             "in_flight": 0,
                             "replica_restarts": 0, "degraded": 0,
                             "degraded_seconds": 0.0,
                             # weight footprint summed over the replicas
                             # that report it, dtype/mode sets for mixed
                             # rollouts (replicas without the tier-2 keys
                             # aggregate at the defaults)
                             "param_bytes": 2000,
                             "weights_dtypes": ["int8"],
                             "act_quants": ["off"],
                             "fused_dequants": ["False"]}
    assert set(snap["replicas"]) == {"a", "b"}
    total = 0
    for name, rsnap in snap["replicas"].items():
        assert rsnap["state"] == READY
        assert rsnap["server"]["marker"] == name  # replica /metrics folded in
        total += rsnap["requests_total"]
    assert total == 6
    assert snap["admission"]["admitted_total"] == 6
    assert snap["request_timeout_s"] == 10.0


def test_overload_drill_bounded_and_contractual(fleet_factory):
    """Under sustained overload every answer is 200 or 429-with-Retry-After,
    the fleet's in-flight depth stays bounded by the client concurrency,
    and no successful request waits unboundedly."""
    admission = AdmissionController(deadline_ms=1.0)  # brutal deadline
    manager, router, url, fakes = fleet_factory(n=1, admission=admission)
    fakes[0].latency_s = 0.05
    post_bytes(url + "/predict", b"seed")  # seed the admission EWMA
    codes, latencies = [], []
    depth_samples = []
    lock = threading.Lock()
    stop_sampling = threading.Event()

    def sampler():
        while not stop_sampling.wait(timeout=0.01):
            depth_samples.append(manager.total_in_flight())

    def worker():
        for _ in range(3):
            t0 = time.time()
            try:
                post_bytes(url + "/predict", b"x", timeout=30)
                code = 200
            except urllib.error.HTTPError as e:
                code = e.code
                assert e.headers.get("Retry-After") is not None
            with lock:
                codes.append(code)
                latencies.append(time.time() - t0)

    threading.Thread(target=sampler, daemon=True).start()
    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    stop_sampling.set()
    assert len(codes) == 12
    assert set(codes) <= {200, 429}
    assert 429 in codes  # the drill actually overloaded
    assert max(depth_samples, default=0) <= 4  # bounded by concurrency
    assert all(dt < 30 for dt in latencies)  # nothing waited out the timeout
    assert admission.shed_total == codes.count(429)


# --- single-engine satellites (real server, fake engine) -----------------------


class FakeEngine:
    """InferenceEngine stand-in: same surface the server/batcher touch."""

    def __init__(self, delay_s=0.0):
        self.buckets = (1, 2, 4)
        self.topk = 3
        self.compile_count = 3
        self.ready = True
        self.delay_s = delay_s
        self.hold = None
        self.predict_started = threading.Event()

    def predict(self, images):
        self.predict_started.set()
        if self.hold is not None:
            self.hold.wait(timeout=30)
        if self.delay_s:
            time.sleep(self.delay_s)
        n = images.shape[0]
        return (np.tile(np.arange(3, dtype=np.int32), (n, 1)),
                np.tile(np.array([0.5, 0.3, 0.2], np.float32), (n, 1)))


def _start(cfg, engine):
    from vitax.serve import start_server
    httpd, ctx = start_server(cfg, engine, port=0)
    return httpd, ctx, f"http://127.0.0.1:{httpd.server_address[1]}"


def test_server_not_ready_until_warmup():
    from vitax.serve import stop_server
    engine = FakeEngine()
    engine.ready = False  # pre-warmup
    httpd, ctx, url = _start(tiny_cfg(), engine)
    try:
        health = get_json(url + "/healthz")
        assert health["status"] == "ok"   # live the moment it binds
        assert health["ready"] is False   # but not routable
        assert health["draining"] is False
        with pytest.raises(urllib.error.HTTPError) as e:
            post_bytes(url + "/predict", png_bytes())
        assert e.value.code == 503
        body = json.load(e.value)
        assert body["reason"] == "warming_up"
        assert e.value.headers["Retry-After"] == "1"
        # warmup completes -> ready flips, traffic flows
        engine.ready = True
        assert get_json(url + "/healthz")["ready"] is True
        resp = post_bytes(url + "/predict", png_bytes())
        assert len(resp["classes"]) == 3
        assert get_json(url + "/metrics")["ready"] is True
    finally:
        stop_server(httpd, ctx)


def test_server_queue_full_503_then_recovers():
    from vitax.serve import stop_server
    engine = FakeEngine()
    engine.hold = threading.Event()
    cfg = tiny_cfg(serve_max_batch=1, serve_queue_max=1,
                   max_batch_wait_ms=1.0)
    httpd, ctx, url = _start(cfg, engine)
    results, errors = [], []

    def bg():
        try:
            results.append(post_bytes(url + "/predict", png_bytes()))
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    try:
        t1 = threading.Thread(target=bg)
        t1.start()
        assert engine.predict_started.wait(timeout=10)  # r1 inside predict
        t2 = threading.Thread(target=bg)
        t2.start()
        deadline = time.time() + 10
        while ctx.batcher.queue_depth() < 1 and time.time() < deadline:
            time.sleep(0.01)
        assert ctx.batcher.queue_depth() == 1  # r2 queued, queue now full
        with pytest.raises(urllib.error.HTTPError) as e:
            post_bytes(url + "/predict", png_bytes())
        assert e.value.code == 503
        body = json.load(e.value)
        assert body["reason"] == "queue_full"
        assert "serve_queue_max" in body["error"]
        assert e.value.headers["Retry-After"] == "1"
        # recovery: unblock the engine, everything queued answers, and new
        # requests are admitted again
        engine.hold.set()
        t1.join(timeout=30)
        t2.join(timeout=30)
        assert not errors and len(results) == 2
        resp = post_bytes(url + "/predict", png_bytes())
        assert len(resp["classes"]) == 3
    finally:
        engine.hold.set()
        stop_server(httpd, ctx)


def test_server_request_timeout_configurable():
    from vitax.serve import stop_server
    engine = FakeEngine(delay_s=1.0)  # slower than the timeout below
    cfg = tiny_cfg(serve_request_timeout_s=0.2)
    httpd, ctx, url = _start(cfg, engine)
    try:
        assert get_json(url + "/metrics")["request_timeout_s"] == 0.2
        t0 = time.time()
        with pytest.raises(urllib.error.HTTPError) as e:
            post_bytes(url + "/predict", png_bytes())
        assert e.value.code == 503
        assert "inference failed" in json.load(e.value)["error"]
        assert time.time() - t0 < 5.0  # answered at the timeout, not at 60s
    finally:
        stop_server(httpd, ctx)


def test_server_graceful_drain_answers_inflight():
    from vitax.serve import drain
    engine = FakeEngine()
    engine.hold = threading.Event()
    httpd, ctx, url = _start(tiny_cfg(), engine)
    results = []
    t1 = threading.Thread(
        target=lambda: results.append(post_bytes(url + "/predict",
                                                 png_bytes())))
    t1.start()
    assert engine.predict_started.wait(timeout=10)
    assert ctx.inflight() == 1
    # draining flips readiness off: new requests are refused while the
    # in-flight one is still being answered
    with ctx._flight_cond:
        ctx.draining = True
    with pytest.raises(urllib.error.HTTPError) as e:
        post_bytes(url + "/predict", png_bytes())
    assert e.value.code == 503
    assert json.load(e.value)["reason"] == "draining"
    # release the engine just after drain starts waiting
    threading.Timer(0.2, engine.hold.set).start()
    assert drain(httpd, ctx, timeout_s=30.0) is True  # drained clean
    t1.join(timeout=10)
    assert len(results) == 1  # the accepted request WAS answered
    assert len(results[0]["classes"]) == 3


# --- config validation (satellite) ---------------------------------------------


@pytest.mark.parametrize("kw,match", [
    (dict(serve_queue_max=-1), "serve_queue_max"),
    (dict(serve_request_timeout_s=0.0), "serve_request_timeout_s"),
    (dict(serve_request_timeout_s=-5.0), "serve_request_timeout_s"),
])
def test_config_fleet_validation_rejects(kw, match):
    with pytest.raises(AssertionError, match=match):
        tiny_cfg(**kw)


def test_config_fleet_defaults():
    cfg = Config().validate()
    assert cfg.serve_queue_max == 1024
    assert cfg.serve_request_timeout_s == 60.0


def test_batcher_queue_full_typed_and_recovers():
    from vitax.serve import DynamicBatcher, QueueFull
    release = threading.Event()
    started = threading.Event()

    def predict(images):
        started.set()
        release.wait(timeout=30)
        n = images.shape[0]
        return (np.zeros((n, 3), np.int32), np.zeros((n, 3), np.float32))

    b = DynamicBatcher(predict, max_batch=1, max_wait_ms=1.0, queue_max=1)
    try:
        f1 = b.submit(np.zeros((4, 4, 3), np.uint8))
        assert started.wait(timeout=10)  # worker busy on f1
        f2 = b.submit(np.zeros((4, 4, 3), np.uint8))  # fills the queue
        with pytest.raises(QueueFull, match="serve_queue_max"):
            b.submit(np.zeros((4, 4, 3), np.uint8))
        release.set()
        assert f1.result(timeout=30).batch_size == 1
        assert f2.result(timeout=30).batch_size == 1
        # queue drained: submissions flow again
        assert b.submit(np.zeros((4, 4, 3), np.uint8)).result(
            timeout=30) is not None
    finally:
        release.set()
        b.close()


# --- cross-replica continuous batching (tentpole) -------------------------------


def _b64(raw: bytes) -> str:
    return base64.b64encode(raw).decode("ascii")


def test_server_predict_batch_matches_single_contract():
    """/predict_batch answers each item with the byte-identical JSON a lone
    /predict would have produced (modulo the latency field), per-item
    failures settle that item alone, and only an unparseable envelope
    400s the whole call."""
    from vitax.serve import stop_server
    engine = FakeEngine()
    httpd, ctx, url = _start(tiny_cfg(), engine)
    try:
        body = png_bytes(16, seed=2)
        req = urllib.request.Request(
            url + "/predict", data=body,
            headers={"Content-Type": "image/png"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            single_raw = resp.read()
        env = json.dumps({
            "items": [_b64(body), _b64(png_bytes(16, seed=3)),
                      _b64(b"not an image")],
            "content_types": ["image/png", "image/png", "image/png"],
        }).encode("utf-8")
        out = post_bytes(url + "/predict_batch", env,
                         content_type="application/json")
        results = out["results"]
        assert len(results) == 3
        assert results[0]["status"] == 200 and results[1]["status"] == 200
        # byte-identical up to latency_ms: same serializer, same engine
        assert (results[0]["body"].encode("utf-8").split(b'"latency_ms"')[0]
                == single_raw.split(b'"latency_ms"')[0])
        parsed = json.loads(results[1]["body"])
        assert len(parsed["classes"]) == 3 and len(parsed["probs"]) == 3
        # the bad item 400s alone; the rest of the batch still answered
        assert results[2]["status"] == 400
        assert "bad request" in json.loads(results[2]["body"])["error"]
        # malformed envelopes fail the whole call, not silently half of it
        for bad in (b"not json{",
                    json.dumps({"items": [_b64(body)],
                                "content_types": ["image/png", "image/png"]
                                }).encode("utf-8")):
            with pytest.raises(urllib.error.HTTPError) as e:
                post_bytes(url + "/predict_batch", bad,
                           content_type="application/json")
            assert e.value.code == 400
    finally:
        stop_server(httpd, ctx)


class RecordingEngine(FakeEngine):
    """FakeEngine that records every predict's batch size — the direct
    measure of bucket fill the composer exists to raise."""

    def __init__(self, delay_s=0.0):
        super().__init__(delay_s)
        self.batch_sizes = []
        self._sizes_lock = threading.Lock()

    def predict(self, images):
        with self._sizes_lock:
            self.batch_sizes.append(int(images.shape[0]))
        return super().predict(images)


def test_composer_two_replica_drill_raises_batch_fill():
    """The acceptance drill: 4 sequential requests through the plain router
    land as four batch-of-1 predicts (least-loaded spreading starves every
    replica's batcher); the same 4 requests concurrent through the
    composer ride ONE /predict_batch into one replica's batcher and fill a
    bucket — with bitwise-identical classes/probs either way."""
    from vitax.serve import stop_server
    engines = [RecordingEngine(), RecordingEngine()]
    servers = [_start(tiny_cfg(max_batch_wait_ms=100.0), e) for e in engines]
    manager = ReplicaManager()
    for i, (_, _, url) in enumerate(servers):
        manager.adopt(url, name=f"r{i}")
    manager.poll_once()
    direct = Router(manager, request_timeout_s=30.0)
    composed = Router(manager, request_timeout_s=30.0,
                      batch_window_ms=400.0, batch_max=4)
    body = png_bytes(16, seed=7)
    try:
        base = [direct.dispatch(body, "image/png") for _ in range(4)]
        assert all(s == 200 for s, _, _ in base)
        base_sizes = engines[0].batch_sizes + engines[1].batch_sizes
        assert sorted(base_sizes) == [1, 1, 1, 1]  # every bucket ran at 1
        for e in engines:
            with e._sizes_lock:
                e.batch_sizes.clear()
        results = [None] * 4

        def worker(i):
            results[i] = composed.dispatch(body, "image/png")

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert all(r is not None and r[0] == 200 for r in results)
        comp_sizes = engines[0].batch_sizes + engines[1].batch_sizes
        assert sum(comp_sizes) == 4
        # fill rose: fewer dispatches than items, and the MEDIAN engine
        # batch went from 1 to >= 2 (the batch-fill p50 acceptance bar)
        assert len(comp_sizes) < 4 and max(comp_sizes) >= 2
        assert sorted(comp_sizes)[len(comp_sizes) // 2] >= 2
        snap = composed._composer.snapshot()
        assert snap["items_total"] == 4 and snap["batches_total"] >= 1
        assert snap["batch_fill_p50"] >= 0.5
        assert snap["disabled"] is False
        # composed answers are bitwise the direct answers (latency aside)
        base_prefix = base[0][2].split(b'"latency_ms"')[0]
        base_parsed = json.loads(base[0][2])
        for status, _, payload in results:
            assert payload.split(b'"latency_ms"')[0] == base_prefix
            got = json.loads(payload)
            assert got["classes"] == base_parsed["classes"]
            assert got["probs"] == base_parsed["probs"]
    finally:
        composed.close()
        for httpd, ctx, _ in servers:
            stop_server(httpd, ctx)


def test_composer_falls_back_when_batch_dispatch_fails():
    """A failed or malformed /predict_batch never costs availability: the
    group re-drives through the per-request direct path (FakeReplica
    answers /predict_batch with a single-predict body — malformed as an
    envelope — so every composed group falls back)."""
    fake = FakeReplica("a")
    manager = ReplicaManager()
    manager.adopt(fake.url, name="a")
    manager.poll_once()
    router = Router(manager, request_timeout_s=10.0,
                    batch_window_ms=50.0, batch_max=4)
    try:
        status, headers, payload = router.dispatch(png_bytes(), "image/png")
        assert status == 200
        assert json.loads(payload)["classes"] == [1, 0, 2]
        snap = router._composer.snapshot()
        assert snap["fallback_items_total"] == 1
        assert snap["disabled"] is False  # malformed != unsupported
        assert fake.predict_count == 2    # the bad batch try + the fallback
    finally:
        router.close()
        fake.stop()


def test_composer_disabled_on_unsupported_replica():
    """A replica without /predict_batch (404 — mixed-version fleet) turns
    composition off permanently for this router; later requests skip the
    grouping wait and dispatch directly."""
    rec = DummyRecorder()
    fake = FakeReplica("a")
    fake.batch_unsupported = True
    manager = ReplicaManager()
    manager.adopt(fake.url, name="a")
    manager.poll_once()
    router = Router(manager, recorder=rec, request_timeout_s=10.0,
                    batch_window_ms=50.0, batch_max=4)
    try:
        status, _, payload = router.dispatch(png_bytes(), "image/png")
        assert status == 200            # settled via fallback
        snap = router._composer.snapshot()
        assert snap["disabled"] is True
        assert ("continuous_batching",
                {"event": "disabled",
                 "detail": "replica lacks /predict_batch"}) in rec.events
        batches_before = snap["batches_total"]
        status, _, _ = router.dispatch(png_bytes(seed=1), "image/png")
        assert status == 200
        assert (router._composer.snapshot()["batches_total"]
                == batches_before)      # bypassed, not grouped
        assert fake.predict_count == 2  # both served via the direct path
        # the 404 was not charged as a dispatch failure
        assert manager.find("a").dispatch_failures == 0
    finally:
        router.close()
        fake.stop()


def test_fleet_metrics_reports_continuous_batching(fleet_factory):
    """A composer-enabled router surfaces its fill histogram in /metrics;
    plain routers omit the block entirely (schema stays stable)."""
    manager, router, url, fakes = fleet_factory(n=1)
    assert "continuous_batching" not in router.fleet_metrics()
    composed = Router(manager, request_timeout_s=10.0,
                      batch_window_ms=25.0, batch_max=8)
    try:
        snap = composed.fleet_metrics()["continuous_batching"]
        assert snap["window_ms"] == 25.0 and snap["batch_max"] == 8
        assert snap["batches_total"] == 0 and snap["disabled"] is False
    finally:
        composed.close()


# --- serve_bench fleet contract --------------------------------------------------


def _import_tool(name):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def test_serve_bench_counts_sheds_separately():
    """429s are contract behavior: counted as sheds, not errors, and the
    worker honors Retry-After."""
    serve_bench = _import_tool("serve_bench")

    class Shedder(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: A003
            pass

        def do_POST(self):  # noqa: N802
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            body = b'{"error": "shed", "reason": "admission"}'
            self.send_response(429)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.send_header("Retry-After", "0")
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Shedder)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        summary = serve_bench.run_bench(
            url, concurrency=2, requests_per_worker=2, image_size=16,
            timeout=10.0, slo_p99_ms=100.0)
        assert summary["shed"] == 4 and summary["errors"] == 0
        assert summary["completed"] == 0
        assert summary["shed_fraction"] == 1.0
        assert summary["slo"]["attained"] is False  # nothing completed
        json.dumps(summary)  # --json stays one serializable object
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_serve_bench_fleet_slo_report(fleet_factory):
    """run_bench against a 2-replica fleet: SLO verdict + rotation report
    from the router's /metrics."""
    serve_bench = _import_tool("serve_bench")
    manager, router, url, fakes = fleet_factory(n=2)
    summary = serve_bench.run_bench(
        url, concurrency=4, requests_per_worker=3, image_size=16,
        timeout=30.0, target_rps=100.0, slo_p99_ms=5000.0, replicas=2)
    assert summary["completed"] == 12 and summary["errors"] == 0
    assert summary["shed"] == 0
    assert summary["slo"]["attained"] is True
    assert summary["fleet"]["replicas"] == 2
    assert summary["fleet"]["ready_end"] == 2
    assert summary["fleet"]["ready_min"] == 2
    assert summary["fleet"]["replica_restarts"] == 0
    assert summary["achieved_rps"] > 0
    # both replicas actually served (least-loaded spreads a 4-way burst)
    assert fakes[0].predict_count > 0 and fakes[1].predict_count > 0


def test_metrics_report_fleet_counters(tmp_path):
    """tools/metrics_report.py --json surfaces admission sheds and replica
    restarts out of serve.jsonl."""
    metrics_report = _import_tool("metrics_report")
    path = tmp_path / "serve.jsonl"
    records = [
        {"schema": 1, "time": 1.0, "kind": "admission", "decision": "shed"},
        {"schema": 1, "time": 2.0, "kind": "admission", "decision": "shed"},
        {"schema": 1, "time": 3.0, "kind": "replica_restart", "replica": "a",
         "restart": 1},
        {"schema": 1, "time": 4.0, "kind": "serve_request", "latency_s": 0.1},
    ]
    path.write_text("\n".join(json.dumps(r) for r in records) + "\n")
    summary = metrics_report.summarize(str(path))
    assert summary["admission_shed_count"] == 2
    assert summary["replica_restarts"] == 1


def test_serve_bench_ramp_stages(fleet_factory):
    """--ramp runs each stage against a wall-clock deadline and reports a
    per-stage breakdown; the overall counters span all stages."""
    serve_bench = _import_tool("serve_bench")
    manager, router, url, fakes = fleet_factory(n=2)
    summary = serve_bench.run_bench(
        url, concurrency=2, requests_per_worker=0, image_size=16,
        timeout=30.0, slo_p99_ms=5000.0, replicas=2, ramp="20:1")
    assert len(summary["ramp"]) == 1
    stage = summary["ramp"][0]
    assert stage["target_rps"] == 20.0 and stage["duration_s"] == 1.0
    assert stage["completed"] > 0 and stage["errors"] == 0
    assert stage["latency_s_p50"] is not None
    # overall counters are the sum of the stage counters
    assert summary["requests"] == (summary["completed"] + summary["shed"]
                                   + summary["unavailable"]
                                   + summary["errors"])
    assert summary["completed"] == stage["completed"]
    # growth counters ride along whenever --replicas samples the router
    assert summary["fleet"]["cache_hits"] == 0
    assert summary["fleet"]["scale_events"] == 0
    assert summary["slo"]["attained"] is True
    json.dumps(summary)  # --json stays one serializable object


def test_serve_bench_ramp_spec_validation():
    serve_bench = _import_tool("serve_bench")
    assert serve_bench.parse_ramp("5:2, 10:3") == [(5.0, 2.0), (10.0, 3.0)]
    for bad in ("", "5", "0:1", "5:0", "5:-1", "rps:secs"):
        with pytest.raises(ValueError):
            serve_bench.parse_ramp(bad)


def test_metrics_report_growth_counters(tmp_path):
    """The growth telemetry round-trips through the JSONL: autoscale
    actions bucketed by outcome, the cache hit rate recovered from the
    LAST hit event's running totals, and batch fill percentiles from the
    per-request batch_size/bucket fields."""
    metrics_report = _import_tool("metrics_report")
    path = tmp_path / "serve.jsonl"
    records = [
        {"schema": 1, "time": 1.0, "kind": "autoscale", "event": "scale_out",
         "reason": "shed_rate", "size": 2},
        {"schema": 1, "time": 2.0, "kind": "autoscale",
         "event": "scale_out_failed", "detail": "agent down"},
        {"schema": 1, "time": 3.0, "kind": "autoscale", "event": "retire",
         "replica": "r0"},
        {"schema": 1, "time": 4.0, "kind": "autoscale", "event": "scale_in",
         "replica": "r0", "forced": False, "size": 1},
        {"schema": 1, "time": 5.0, "kind": "autoscale", "event": "scale_in",
         "replica": "r1", "forced": True, "size": 1},
        {"schema": 1, "time": 6.0, "kind": "cache", "decision": "hit",
         "hits_total": 2, "misses_total": 6},
        {"schema": 1, "time": 7.0, "kind": "serve_request", "latency_s": 0.1,
         "batch_size": 1, "bucket": 4},
        {"schema": 1, "time": 8.0, "kind": "serve_request", "latency_s": 0.1,
         "batch_size": 4, "bucket": 4, "batched": True},
        {"schema": 1, "time": 9.0, "kind": "serve_request", "latency_s": 0.1,
         "batch_size": 4, "bucket": 4, "batched": True},
    ]
    path.write_text("\n".join(json.dumps(r) for r in records) + "\n")
    summary = metrics_report.summarize(str(path))
    assert summary["autoscale_events"] == {
        "scale_out": 1, "scale_in": 2, "retires": 1,
        "scale_out_failures": 1, "forced_drains": 1, "escalations": 0}
    assert summary["cache_hits"] == 2
    assert summary["cache_hit_rate"] == 0.25
    assert summary["batch_fill_p50"] == 1.0   # median of [0.25, 1.0, 1.0]
    assert summary["batch_fill_p95"] == 1.0
    # a log with no growth events keeps the old schema quiet
    bare = tmp_path / "bare.jsonl"
    bare.write_text(json.dumps(
        {"schema": 1, "time": 1.0, "kind": "serve_request",
         "latency_s": 0.1}) + "\n")
    bsum = metrics_report.summarize(str(bare))
    assert not any(bsum["autoscale_events"].values())
    assert "cache_hits" not in bsum and "batch_fill_p50" not in bsum


# --- e2e: real replicas, kill one mid-burst (slow) --------------------------------


@pytest.mark.slow
def test_fleet_e2e_kill_replica_zero_client_errors(devices8,
                                                   tmp_path_factory):
    """2 real `python -m vitax.serve` replicas from a 2-step fake-data
    checkpoint behind the router; SIGKILL one mid-burst. Zero
    client-visible errors (one-retry hides the death), the supervised
    restart re-warms and re-admits it, and manager.stop() SIGTERM-drains
    both replicas to exit 0."""
    from vitax.train.loop import train

    root = tmp_path_factory.mktemp("fleet_e2e")
    ckpt_dir = str(root / "ckpt")
    cfg = tiny_cfg(fake_data=True, num_epochs=1, steps_per_epoch=2,
                   log_step_interval=1, ckpt_dir=ckpt_dir,
                   ckpt_epoch_interval=1, num_workers=2, eval_max_batches=1)
    train(cfg)
    assert os.path.isdir(os.path.join(ckpt_dir, "epoch_1"))

    model_flags = [
        "--image_size", "16", "--patch_size", "8", "--embed_dim", "32",
        "--num_heads", "2", "--num_blocks", "2", "--num_classes", "4",
        "--dtype", "float32", "--serve_max_batch", "4", "--serve_topk", "3",
        "--max_batch_wait_ms", "10.0", "--ckpt_dir", ckpt_dir,
        "--epoch", "1",
    ]
    manager = ReplicaManager(health_interval_s=0.25, backoff_s=0.5)
    httpd = None
    try:
        for i in range(2):
            port = free_port()
            argv = ([sys.executable, "-m", "vitax.serve"] + model_flags
                    + ["--serve_port", str(port)])
            manager.manage(argv, f"http://127.0.0.1:{port}",
                           name=f"replica_{i}")
        manager.start()
        deadline = time.time() + 300
        while manager.ready_count() < 2 and time.time() < deadline:
            time.sleep(0.5)
        assert manager.ready_count() == 2, manager.snapshot()

        router = Router(manager, request_timeout_s=60.0)
        httpd = start_router(router, 0)
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        body = png_bytes(16, seed=4)
        results, errors, lock = [], [], threading.Lock()

        def worker():
            for _ in range(4):
                try:
                    r = post_bytes(url + "/predict", body, timeout=90)
                    with lock:
                        results.append(r)
                except Exception as e:  # noqa: BLE001 — any error fails the drill
                    with lock:
                        errors.append(repr(e))

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        manager.replicas[0].proc.kill()  # SIGKILL mid-burst
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
        assert len(results) == 16  # zero client-visible errors

        # the health loop restarts + re-warms + re-admits the dead replica
        deadline = time.time() + 300
        while time.time() < deadline and not (
                manager.ready_count() == 2 and manager.restart_total >= 1):
            time.sleep(0.5)
        assert manager.restart_total >= 1
        assert manager.ready_count() == 2, manager.snapshot()
        resp = post_bytes(url + "/predict", body, timeout=90)
        assert len(resp["classes"]) == 3
    finally:
        if httpd is not None:
            stop_router(httpd)
        manager.stop()  # SIGTERM drain
        for r in manager.replicas:
            if r.proc is not None and r.proc.poll() is None:
                r.proc.kill()
    # the graceful-drain contract: SIGTERM -> in-flight answered -> exit 0
    for r in manager.replicas:
        assert r.exit_code == 0, manager.snapshot()


@pytest.mark.slow
def test_fleet_autoscale_ramp_drill(devices8, tmp_path_factory):
    """The growth acceptance drill: one real replica with a slow-accelerator
    fault plan (every engine predict hangs 250ms) behind an admission-
    controlled router; a serve_bench ramp overloads it; the autoscaler
    reads the sustained pressure and provisions a second real replica —
    which enters through STARTING and is never served cold (zero errors,
    zero restarts, SLO attained on everything completed). A flaky
    health-probe chaos window runs in the router during the ramp and stays
    invisible to clients. Afterwards the prediction cache is armed and a
    repeated body is answered verbatim with ZERO extra engine predicts."""
    from vitax.train.loop import train
    serve_bench = _import_tool("serve_bench")

    root = tmp_path_factory.mktemp("fleet_autoscale")
    ckpt_dir = str(root / "ckpt")
    cfg = tiny_cfg(fake_data=True, num_epochs=1, steps_per_epoch=2,
                   log_step_interval=1, ckpt_dir=ckpt_dir,
                   ckpt_epoch_interval=1, num_workers=2, eval_max_batches=1)
    train(cfg)

    model_flags = [
        "--image_size", "16", "--patch_size", "8", "--embed_dim", "32",
        "--num_heads", "2", "--num_blocks", "2", "--num_classes", "4",
        "--dtype", "float32", "--serve_max_batch", "4", "--serve_topk", "3",
        "--max_batch_wait_ms", "10.0", "--ckpt_dir", ckpt_dir,
        "--epoch", "1",
    ]
    # the seed replica's chaos: a slow accelerator (every predict +250ms),
    # so offered load beyond ~1 batch in flight predictably queues
    slow_plan = json.dumps({"site": "engine_predict", "at": 1,
                            "times": 1000000, "action": "hang",
                            "seconds": 0.25})
    rec = DummyRecorder()
    manager = ReplicaManager(health_interval_s=0.25, backoff_s=0.5)
    admission = AdmissionController(deadline_ms=400.0, ewma_alpha=0.0,
                                    recorder=rec)
    admission.observe(0.2)  # alpha 0: the service-time estimate stays 0.2s

    def spawn_second():
        port = free_port()
        argv = ([sys.executable, "-m", "vitax.serve"] + model_flags
                + ["--serve_port", str(port)])
        return manager.manage(argv, f"http://127.0.0.1:{port}",
                              name="scaled_1")

    auto = Autoscaler(manager, admission=admission, min_replicas=1,
                      max_replicas=2, scale_out=spawn_second,
                      interval_s=0.25, dwell_s=0.75, cooldown_s=60.0,
                      shed_rate_per_s=0.5, recorder=rec)
    router = Router(manager, admission=admission, autoscaler=auto,
                    request_timeout_s=60.0)
    httpd = None
    try:
        port = free_port()
        argv = ([sys.executable, "-m", "vitax.serve"] + model_flags
                + ["--serve_port", str(port), "--fault_plan", slow_plan])
        manager.manage(argv, f"http://127.0.0.1:{port}", name="replica_0")
        manager.start()
        deadline = time.time() + 300
        while manager.ready_count() < 1 and time.time() < deadline:
            time.sleep(0.5)
        assert manager.ready_count() == 1, manager.snapshot()

        httpd = start_router(router, 0)
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        auto.start()
        # router-side chaos: one health probe fails mid-ramp — below the
        # ejection threshold, so clients must never notice
        faults.install(json.dumps({"site": "replica_health", "at": 8,
                                   "action": "oserror"}))
        try:
            summary = serve_bench.run_bench(
                url, concurrency=6, requests_per_worker=0, image_size=16,
                timeout=60.0, slo_p99_ms=5000.0, replicas=2, ramp="40:10")
        finally:
            faults.uninstall()
            auto.stop()  # no idle scale-in racing the cache phase below

        # zero cold serves / zero client-visible errors under chaos
        assert summary["errors"] == 0, summary["error_samples"]
        assert summary["completed"] > 0
        assert summary["fleet"]["replica_restarts"] == 0
        assert summary["slo"]["attained"] is True
        # the ramp actually overloaded the seed replica...
        assert summary["shed"] > 0
        # ...and the autoscaler answered: scale-out visible in the bench
        assert summary["fleet"]["scale_out"] >= 1
        assert summary["fleet"]["scale_events"] >= 1
        assert auto.scale_out_total == 1  # cooldown + max clamp: exactly one
        out_events = [p for k, p in rec.events
                      if k == "autoscale" and p.get("event") == "scale_out"]
        assert out_events and out_events[0]["replica"] == "scaled_1"

        # the provisioned replica finishes AOT warmup and joins rotation
        # through the front door (STARTING until its own /healthz is ready)
        deadline = time.time() + 300
        while manager.ready_count() < 2 and time.time() < deadline:
            time.sleep(0.5)
        assert manager.ready_count() == 2, manager.snapshot()

        # arm the cache and pin the replay contract on the live fleet:
        # a repeated body costs zero engine predicts
        router.cache = PredictionCache(max_entries=16)
        body = png_bytes(16, seed=9)

        def raw_post():
            req = urllib.request.Request(
                url + "/predict", data=body,
                headers={"Content-Type": "image/png"})
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, dict(resp.headers), resp.read()
        s1, h1, b1 = raw_post()
        assert s1 == 200 and "X-Vitax-Cache" not in h1
        dispatched = router.metrics.requests_total
        s2, h2, b2 = raw_post()
        assert s2 == 200 and h2.get("X-Vitax-Cache") == "hit"
        assert b2 == b1                                   # bitwise replay
        assert router.metrics.requests_total == dispatched  # no dispatch
        assert router.cache.snapshot()["hits_total"] == 1
    finally:
        faults.uninstall()
        auto.stop()
        if httpd is not None:
            stop_router(httpd)
        manager.stop()  # SIGTERM drain
        for r in manager.replicas:
            if r.proc is not None and r.proc.poll() is None:
                r.proc.kill()
    for r in manager.replicas:
        assert r.exit_code == 0, manager.snapshot()
