"""Ring attention (sequence parallelism) on the 8-virtual-device CPU mesh:
numerics vs dense attention, gradient parity, and a full sequence-parallel
train step matching the FSDP-only trajectory."""

import jax
import jax.numpy as jnp
import numpy as np

from vitax.config import Config
from vitax.parallel.mesh import build_mesh
from vitax.parallel.ring_attention import make_ring_attention
from vitax.ops.attention import reference_attention


def sp_cfg(**kw):
    base = dict(image_size=32, patch_size=8, embed_dim=32, num_heads=2,
                num_blocks=2, num_classes=4, batch_size=8, dtype="float32",
                sp_size=4, fsdp_size=2, warmup_steps=0)
    base.update(kw)
    return Config(**base).validate()


def test_ring_matches_dense(devices8):
    cfg = sp_cfg()
    mesh = build_mesh(cfg)  # dp1 x fsdp2 x tp1 x sp4
    ring = make_ring_attention(mesh)
    b, n, h, dh = 4, 16, 2, 8
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (b, n, h, dh), jnp.float32)
    k = jax.random.normal(kk, (b, n, h, dh), jnp.float32)
    v = jax.random.normal(kv, (b, n, h, dh), jnp.float32)
    out_ring = jax.jit(ring)(q, k, v)
    out_ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_grad_matches_dense(devices8):
    cfg = sp_cfg()
    mesh = build_mesh(cfg)
    ring = make_ring_attention(mesh)
    shape = (2, 16, 2, 8)
    kq, kk, kv = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    gr_ring = jax.jit(jax.grad(loss(ring), argnums=(0, 1, 2)))(q, k, v)
    gr_ref = jax.grad(loss(reference_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr_ring, gr_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3)


def test_ring_kernel_block_matches_dense(devices8):
    """Pallas block product path (interpret mode on CPU): numerics + grads must
    match the dense reference — this is the path real TPU SP training takes."""
    cfg = sp_cfg()
    mesh = build_mesh(cfg)
    ring = make_ring_attention(mesh, use_kernel=True)
    shape = (2, 16, 2, 8)
    kq, kk, kv = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(jax.jit(ring)(q, k, v)),
        np.asarray(reference_attention(q, k, v)), rtol=2e-4, atol=2e-4)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    gr_ring = jax.jit(jax.grad(loss(ring), argnums=(0, 1, 2)))(q, k, v)
    gr_ref = jax.grad(loss(reference_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr_ring, gr_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_ring_issues_exactly_sp_minus_one_permutes(devices8):
    """The K/V rotation must run exactly sp-1 times (the last block needs no
    next-block fetch), as ONE collective per ring step: K and V ride a single
    stacked buffer because XLA does not reliably merge distinct ppermutes
    into one transfer (same lesson as ulysses.py's stacked all-to-all;
    VERDICT r3 weak #6). sp=4 here: expect sp-1 = 3 permutes in the forward
    HLO — not 2*(sp-1) = 6 (separate K and V hops), not 2*sp = 8."""
    cfg = sp_cfg()
    mesh = build_mesh(cfg)  # dp1 x fsdp2 x tp1 x sp4
    ring = make_ring_attention(mesh)
    shape = (2, 16, 2, 8)
    q = jnp.ones(shape, jnp.float32)
    hlo = jax.jit(ring).lower(q, q, q).as_text()
    n_permutes = hlo.count("collective_permute")
    assert n_permutes == 3, (
        f"expected 3 collective_permutes (stacked K/V x sp-1), got {n_permutes}")


def test_sequence_parallel_train_step_equivalence(devices8):
    """Full train step with sp=4 must match the sp=1 FSDP trajectory — sequence
    parallelism must not change the math."""
    from tests.test_train_smoke import run_steps

    cfg_sp = sp_cfg(num_heads=2)
    cfg_base = sp_cfg(sp_size=1, fsdp_size=-1)
    _, losses_sp = run_steps(cfg_sp, n_steps=4)
    _, losses_base = run_steps(cfg_base, n_steps=4)
    assert all(np.isfinite(losses_sp))
    np.testing.assert_allclose(losses_sp, losses_base, rtol=2e-4)


import pytest


@pytest.mark.parametrize("use_kernel", [False, True])
def test_ring_dropout_matches_masked_dense(devices8, use_kernel):
    """Ring in-kernel dropout (round 5) == dense attention with the global
    counter-hash mask: each (q-shard, kv-block) product masks its numerator
    at GLOBAL (q0, k0) token offsets and every (q, k) element is computed by
    exactly one shard, so the lse merge reconstructs dense softmax-then-drop
    exactly — for both the dense and the Pallas (interpret) block products,
    grads included."""
    from vitax.ops.attention import dropout_keep_mask
    from vitax.parallel.ring_attention import make_ring_dropout

    cfg = sp_cfg(sp_size=2, fsdp_size=1, att_dropout=0.3)
    mesh = build_mesh(cfg, devices=jax.devices()[:2])  # pure sp2
    rate = cfg.att_dropout
    ring_drop = make_ring_dropout(mesh, rate, use_kernel=use_kernel)

    b, n, h, dh = 3, 16, 2, 8
    kq, kk, kv = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(kq, (b, n, h, dh), jnp.float32)
    k = jax.random.normal(kk, (b, n, h, dh), jnp.float32)
    v = jax.random.normal(kv, (b, n, h, dh), jnp.float32)
    seed = jnp.uint32(31)

    def dense_masked(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * dh ** -0.5
        probs = jax.nn.softmax(s, axis=-1)
        mask = jnp.stack([jnp.stack([
            dropout_keep_mask(seed, jnp.uint32(bi * h + hi), n, n, rate)
            for hi in range(h)]) for bi in range(b)])
        return jnp.einsum("bhqk,bkhd->bqhd", probs * mask / (1 - rate), v)

    out = jax.jit(lambda q, k, v: ring_drop(q, k, v, seed))(q, k, v)
    want = dense_masked(q, k, v)
    assert not np.allclose(np.asarray(out),
                           np.asarray(reference_attention(q, k, v)),
                           atol=1e-3)  # the mask actually bit
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    got = jax.grad(loss(lambda q, k, v: ring_drop(q, k, v, seed)),
                   argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(loss(dense_masked), argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, ref):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-3, atol=2e-3)
