"""Fused clip+AdamW optimizer (vitax/ops/fused_optimizer.py).

Covers the ISSUE-15 acceptance bars: per-leaf kernel numerics against a
closed-form AdamW reference (zero-grad and all-zero-channel leaves
included), both clip branches, in-place aliasing (buffer identity through
jit donation), 3-step fused-vs-optax equivalence on all six parallelism
arms, the flag-off program identity, and the single-norm-reduction jaxpr
pin (the satellite fix: grad_norm is no longer re-reduced for the metric).
"""

import functools
import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from vitax.config import Config
from vitax.ops.fused_optimizer import (FUSED_KERNEL_NAME, find_adam_state,
                                       fused_clip_adamw,
                                       fused_optimizer_active)
from vitax.train.state import ADAMW_HPARAMS

B1, B2, EPS = ADAMW_HPARAMS["b1"], ADAMW_HPARAMS["b2"], ADAMW_HPARAMS["eps"]


def closed_form_adamw(p, g, mu, nu, *, count, lr, wd, clip_scale=1.0):
    """Textbook clip+AdamW in fp64 — independent of both optax and the
    kernel's operand ordering; the shared ≤1e-6 oracle."""
    p, g, mu, nu = (np.asarray(x, np.float64) for x in (p, g, mu, nu))
    g = g * clip_scale
    mu2 = (1 - B1) * g + B1 * mu
    nu2 = (1 - B2) * g * g + B2 * nu
    t = count + 1
    upd = (mu2 / (1 - B1 ** t)) / (np.sqrt(nu2 / (1 - B2 ** t)) + EPS)
    return p - lr * (upd + wd * p), mu2, nu2


def run_fused(params, grads, mu, nu, *, count=0, lr=1e-3, wd=0.01,
              clip_norm=0.0):
    opt_state = (optax.ScaleByAdamState(
        count=jnp.int32(count), mu=mu, nu=nu),)
    gnorm = optax.global_norm(grads)
    new_p, new_s = jax.jit(lambda g, s, p, n: fused_clip_adamw(
        g, s, p, grad_norm=n, schedule=lambda c: lr, clip_norm=clip_norm,
        weight_decay=wd, b1=B1, b2=B2, eps=EPS))(grads, opt_state, params,
                                                 gnorm)
    adam = find_adam_state(new_s)
    return new_p, adam


def assert_tree_close(got, want, rtol=1e-6, atol=1e-8):
    for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(g, np.float64),
                                   np.asarray(w, np.float64),
                                   rtol=rtol, atol=atol)


class TestKernelNumerics:
    def _tree(self, seed=0):
        # 3-D (ragged rows vs the 8-row tile), matrix, vector, scalar
        shapes = [(3, 37, 96), (257, 40), (33,), ()]
        ks = jax.random.split(jax.random.key(seed), 3 * len(shapes))
        params = {f"l{i}": jax.random.normal(ks[3 * i], s, jnp.float32)
                  for i, s in enumerate(shapes)}
        grads = {f"l{i}": jax.random.normal(ks[3 * i + 1], s, jnp.float32)
                 for i, s in enumerate(shapes)}
        mu = {f"l{i}": 0.1 * jax.random.normal(ks[3 * i + 2], s, jnp.float32)
              for i, s in enumerate(shapes)}
        nu = {k: v * v for k, v in mu.items()}
        return params, grads, mu, nu

    def test_matches_closed_form(self):
        params, grads, mu, nu = self._tree()
        new_p, adam = run_fused(params, grads, mu, nu, count=5)
        assert int(adam.count) == 6
        for k in params:
            want = closed_form_adamw(params[k], grads[k], mu[k], nu[k],
                                     count=5, lr=1e-3, wd=0.01)
            for g, w in zip((new_p[k], adam.mu[k], adam.nu[k]), want):
                # atol: one f32 ulp of the O(1) outputs — the oracle is
                # fp64, so near-zero elements differ by result rounding
                np.testing.assert_allclose(np.asarray(g, np.float64), w,
                                           rtol=1e-6, atol=2e-7)

    def test_zero_grads(self):
        params, _, mu, nu = self._tree(1)
        zeros = jax.tree.map(jnp.zeros_like, params)
        new_p, adam = run_fused(params, zeros, mu, nu)
        for k in params:
            want = closed_form_adamw(params[k], np.zeros(params[k].shape),
                                     mu[k], nu[k], count=0, lr=1e-3, wd=0.01)
            for g, w in zip((new_p[k], adam.mu[k], adam.nu[k]), want):
                np.testing.assert_allclose(np.asarray(g, np.float64), w,
                                           rtol=1e-6, atol=1e-8)
            assert np.all(np.isfinite(new_p[k]))

    def test_all_zero_channel(self):
        # a dead channel (grad AND moments zero) must step by pure weight
        # decay — no 0/0 from the sqrt(nu) denominator
        p = jnp.ones((16, 8), jnp.float32)
        g = jnp.ones((16, 8), jnp.float32).at[:, 3].set(0.0)
        mu = jnp.zeros((16, 8), jnp.float32)
        nu = jnp.zeros((16, 8), jnp.float32)
        new_p, adam = run_fused({"w": p}, {"w": g}, {"w": mu}, {"w": nu},
                                lr=1e-2, wd=0.1)
        assert np.all(np.isfinite(new_p["w"]))
        want = closed_form_adamw(p, g, mu, nu, count=0, lr=1e-2, wd=0.1)
        np.testing.assert_allclose(np.asarray(new_p["w"], np.float64),
                                   want[0], rtol=1e-6, atol=1e-8)
        # the dead channel moved by exactly -lr*wd*p
        np.testing.assert_allclose(
            np.asarray(new_p["w"][:, 3]), (1 - 1e-2 * 0.1) * np.ones(16),
            rtol=1e-6)


class TestClipBranches:
    def _setup(self, gscale):
        k1, k2 = jax.random.split(jax.random.key(2))
        params = {"w": jax.random.normal(k1, (64, 32), jnp.float32)}
        grads = {"w": gscale * jax.random.normal(k2, (64, 32), jnp.float32)}
        mu = jax.tree.map(jnp.zeros_like, params)
        nu = jax.tree.map(jnp.zeros_like, params)
        return params, grads, mu, nu

    def test_clip_inactive_is_identity(self):
        params, grads, mu, nu = self._setup(1e-3)  # norm << 1
        assert float(optax.global_norm(grads)) < 1.0
        clipped, _ = run_fused(params, grads, mu, nu, clip_norm=1.0)
        unclipped, _ = run_fused(params, grads, mu, nu, clip_norm=0.0)
        assert_tree_close(clipped, unclipped, rtol=0, atol=0)

    def test_clip_active_scales(self):
        params, grads, mu, nu = self._setup(10.0)
        gnorm = float(optax.global_norm(grads))
        assert gnorm > 1.0
        new_p, adam = run_fused(params, grads, mu, nu, clip_norm=1.0)
        want = closed_form_adamw(params["w"], grads["w"], mu["w"], nu["w"],
                                 count=0, lr=1e-3, wd=0.01,
                                 clip_scale=1.0 / gnorm)
        np.testing.assert_allclose(np.asarray(new_p["w"], np.float64),
                                   want[0], rtol=1e-6, atol=1e-8)
        # the post-clip grad norm the moments saw is ~clip_norm
        np.testing.assert_allclose(
            float(np.sqrt(np.sum(np.square(
                np.asarray(adam.mu["w"]) / (1 - B1))))), 1.0, rtol=1e-5)

    def test_matches_optax_chain(self):
        # vs the actual optax chain the flag replaces, both branches
        for gscale in (1e-3, 10.0):
            params, grads, mu, nu = self._setup(gscale)
            new_p, adam = run_fused(params, grads, mu, nu, count=2,
                                    clip_norm=1.0)
            tx = optax.chain(optax.clip_by_global_norm(1.0),
                             optax.adamw(lambda c: 1e-3, weight_decay=0.01,
                                         **ADAMW_HPARAMS))
            opt_state = jax.tree.map(
                lambda x: x,
                (optax.EmptyState(),
                 (optax.ScaleByAdamState(count=jnp.int32(2), mu=mu, nu=nu),
                  optax.EmptyState(),
                  optax.ScaleByScheduleState(count=jnp.int32(2)))))
            updates, _ = tx.update(grads, opt_state, params)
            want = optax.apply_updates(params, updates)
            assert_tree_close(new_p, want, rtol=1e-6, atol=1e-8)


class TestAliasing:
    def test_inplace_buffer_identity(self):
        """param/mu/nu outputs land on the donated input buffers — the
        input_output_aliases contract survives jit donation to the runtime
        (unsafe_buffer_pointer equality, not just program metadata)."""
        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(p, mu, nu, g):
            opt_state = (optax.ScaleByAdamState(
                count=jnp.int32(0), mu=mu, nu=nu),)
            new_p, new_s = fused_clip_adamw(
                g, opt_state, p, grad_norm=optax.global_norm(g),
                schedule=lambda c: 1e-3, clip_norm=1.0, weight_decay=0.01,
                b1=B1, b2=B2, eps=EPS)
            adam = find_adam_state(new_s)
            return new_p, adam.mu, adam.nu

        k = jax.random.key(3)
        mk = lambda key: jax.device_put(  # noqa: E731
            jax.random.normal(key, (256, 128), jnp.float32))
        p, mu, nu, g = (mk(x) for x in jax.random.split(k, 4))
        donated = {x.unsafe_buffer_pointer() for x in (p, mu, nu)}
        outs = step(p, mu, nu, g)
        out_ptrs = {x.unsafe_buffer_pointer() for x in outs}
        assert out_ptrs <= donated, (
            f"outputs allocated fresh buffers: {out_ptrs - donated}")
        assert len(out_ptrs) == 3  # three distinct in-place destinations

    def test_aliasing_in_lowered_program(self):
        # structural check: the donated params carry tf.aliasing_output in
        # the lowered MLIR (the program-level half of the contract)
        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(p, g):
            opt_state = (optax.ScaleByAdamState(
                count=jnp.int32(0), mu=jnp.zeros_like(p),
                nu=jnp.zeros_like(p)),)
            new_p, _ = fused_clip_adamw(
                g, opt_state, p, grad_norm=optax.global_norm(g),
                schedule=lambda c: 1e-3, clip_norm=0.0, weight_decay=0.0,
                b1=B1, b2=B2, eps=EPS)
            return new_p

        x = jnp.ones((64, 64), jnp.float32)
        mlir = step.lower(x, x).as_text()
        assert "tf.aliasing_output" in mlir


# ---------------------------------------------------------------------------
# end-to-end train-step arms

# the six ISSUE-15 parallelism arms (CPU, 8 virtual devices)
EQUIV_ARMS = {
    "dp": dict(run_without_fsdp=True, dtype="float32"),
    "zero2": dict(reshard_after_forward=False),
    "zero3": dict(gather_overlap="off"),
    "zero3_overlap": dict(gather_overlap="on"),
    "accum2": dict(batch_size=128, grad_accum_steps=2, gather_overlap="off"),
    "bf16comm": dict(gather_overlap="off", param_gather_dtype="bfloat16",
                     grad_reduce_dtype="bfloat16"),
}

GEOMETRY = dict(image_size=16, patch_size=8, embed_dim=32, num_heads=2,
                num_blocks=2, num_classes=4, batch_size=64, warmup_steps=2)


def _build(cfg):
    from vitax.models import build_model
    from vitax.ops.attention import make_attention_impl
    from vitax.parallel.mesh import build_mesh
    from vitax.train.state import build_optimizer, make_train_state
    from vitax.train.step import make_train_step

    mesh = build_mesh(cfg)
    model = build_model(cfg, attention_impl=make_attention_impl(cfg, mesh))
    tx, schedule = build_optimizer(cfg, max_iteration=100)
    state, sspecs, _ = make_train_state(cfg, model, tx, mesh,
                                        jax.random.key(0))
    step = make_train_step(cfg, model, tx, mesh, sspecs, schedule=schedule)
    return mesh, state, step


def _run_steps(arm_overrides, fused_mode, steps=3):
    from jax.sharding import NamedSharding
    from vitax.parallel.mesh import batch_pspec

    kw = dict(GEOMETRY)
    kw.update(arm_overrides)
    kw["fused_optimizer"] = fused_mode
    cfg = Config(**kw).validate()
    mesh, state, step = _build(cfg)
    sh = NamedSharding(mesh, batch_pspec())
    rng_img = np.random.default_rng(0)
    metrics = []
    for _ in range(steps):
        batch = {
            "image": jax.device_put(rng_img.standard_normal(
                (cfg.batch_size, cfg.image_size, cfg.image_size, 3),
                dtype=np.float32), sh),
            "label": jax.device_put(
                (np.arange(cfg.batch_size) % cfg.num_classes).astype(
                    np.int32), sh),
        }
        state, m = step(state, batch, jax.random.key(42))
        metrics.append({k: float(jax.device_get(m[k]))
                        for k in ("loss", "grad_norm")})
    return state, metrics


@pytest.mark.parametrize("arm", sorted(EQUIV_ARMS))
def test_fused_matches_optax_3_steps(arm):
    """≤1e-6-relative fused-vs-optax agreement after 3 real train steps on
    every parallelism arm (the ISSUE-15 acceptance bar). atol floors the
    comparison for near-zero elements, where an elementwise ratio would
    amplify 1-ulp XLA fusion reassociation into meaceless percentages."""
    s_fused, m_fused = _run_steps(EQUIV_ARMS[arm], "on")
    s_optax, m_optax = _run_steps(EQUIV_ARMS[arm], "off")
    for mf, mo in zip(m_fused, m_optax):
        assert mf["loss"] == pytest.approx(mo["loss"], rel=1e-6)
        assert mf["grad_norm"] == pytest.approx(mo["grad_norm"], rel=1e-6)
    assert_tree_close(s_fused.params, s_optax.params)
    adam_f = find_adam_state(s_fused.opt_state)
    adam_o = find_adam_state(s_optax.opt_state)
    assert int(adam_f.count) == int(adam_o.count) == 3
    assert_tree_close(adam_f.mu, adam_o.mu)
    assert_tree_close(adam_f.nu, adam_o.nu)
    # state tree structure (checkpoint/state_specs contract) unchanged
    assert (jax.tree_util.tree_structure(s_fused.opt_state)
            == jax.tree_util.tree_structure(s_optax.opt_state))


def _trace_text(cfg):
    from vitax.analysis.hlo import train_step_jaxpr
    return train_step_jaxpr(cfg, max_iteration=100)


def test_flag_off_program_identity():
    """--fused_optimizer off traces the SAME program as the CPU default
    (auto resolves off where the kernels would interpret): byte-identical
    jaxpr — the flag's off position cannot perturb production numerics."""
    kw = dict(GEOMETRY, gather_overlap="off")
    off = _trace_text(Config(**kw, fused_optimizer="off").validate())
    auto = _trace_text(Config(**kw, fused_optimizer="auto").validate())
    assert not fused_optimizer_active(
        Config(**kw, fused_optimizer="auto").validate())
    assert off == auto
    assert FUSED_KERNEL_NAME not in off


def test_fused_on_enters_program():
    kw = dict(GEOMETRY, gather_overlap="off")
    on = _trace_text(Config(**kw, fused_optimizer="on").validate())
    assert FUSED_KERNEL_NAME in on


def test_single_norm_reduction_in_jaxpr():
    """Satellite regression pin: ONE scalar sqrt (the global-norm
    reduction) in the traced step on BOTH paths — the old program paid a
    second full-tree norm pass for the grad_norm metric."""
    kw = dict(GEOMETRY, gather_overlap="off")
    for mode in ("off", "on"):
        text = _trace_text(Config(**kw, fused_optimizer=mode).validate())
        if mode == "on":
            from vitax.analysis.hlo import strip_bracketed
            text = strip_bracketed(text, "pallas_call")
        scalar_sqrts = re.findall(r":f32\[\] = sqrt\b", text)
        assert len(scalar_sqrts) == 1, (mode, len(scalar_sqrts))


def test_fused_requires_schedule():
    from vitax.parallel.mesh import build_mesh
    from vitax.train.state import build_optimizer, make_train_state
    from vitax.train.step import make_train_step
    from vitax.models import build_model
    from vitax.ops.attention import make_attention_impl

    cfg = Config(**dict(GEOMETRY, gather_overlap="off",
                        fused_optimizer="on")).validate()
    mesh = build_mesh(cfg)
    model = build_model(cfg, attention_impl=make_attention_impl(cfg, mesh))
    tx, _ = build_optimizer(cfg, max_iteration=100)
    _, sspecs, _ = make_train_state(cfg, model, tx, mesh, jax.random.key(0),
                                    materialize=False)
    with pytest.raises(ValueError, match="schedule"):
        make_train_step(cfg, model, tx, mesh, sspecs)


def test_opt_probe_runs():
    """make_opt_probe (the opt_update_s telemetry program): zero grads ->
    zero grad_norm, finite state outputs, params stepped by decay only —
    and it is a separate non-donating program, so the input state's buffers
    survive the call."""
    from vitax.parallel.mesh import build_mesh
    from vitax.train.state import build_optimizer, make_train_state
    from vitax.train.step import make_opt_probe
    from vitax.models import build_model
    from vitax.ops.attention import make_attention_impl

    cfg = Config(**dict(GEOMETRY, gather_overlap="off")).validate()
    mesh = build_mesh(cfg)
    model = build_model(cfg, attention_impl=make_attention_impl(cfg, mesh))
    tx, schedule = build_optimizer(cfg, max_iteration=100)
    state, sspecs, _ = make_train_state(cfg, model, tx, mesh,
                                        jax.random.key(0))
    probe = make_opt_probe(cfg, tx, mesh, sspecs, schedule=schedule)
    new_params, new_opt_state, grad_norm = jax.block_until_ready(
        probe(state))
    assert float(grad_norm) == 0.0
    for leaf in jax.tree.leaves(new_params):
        assert np.all(np.isfinite(leaf))
    # non-donating: the live state is still usable afterwards
    assert np.all(np.isfinite(jax.tree.leaves(state.params)[0]))
    assert (jax.tree_util.tree_structure(new_opt_state)
            == jax.tree_util.tree_structure(state.opt_state))
