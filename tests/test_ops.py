"""Kernel numerics: Pallas fused attention (interpret mode on CPU) vs the dense
reference path, forward and gradients; data-pipeline transform parity checks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vitax.ops.attention import flash_attention, reference_attention


@pytest.mark.parametrize("shape", [(2, 64, 2, 32), (1, 128, 3, 16)])
def test_flash_matches_reference_fwd(devices8, shape):
    b, n, h, dh = shape
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    out_f = flash_attention(q, k, v)
    out_r = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_r), rtol=2e-4, atol=2e-4)


def test_flash_matches_reference_grad(devices8):
    shape = (2, 64, 2, 32)
    kq, kk, kv = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    gf = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(reference_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3)


def test_model_with_flash_attention_matches_dense(devices8):
    """The full model with the kernel plugged in must match the dense path."""
    from vitax.config import Config
    from vitax.models import build_model

    cfg = Config(image_size=32, patch_size=8, embed_dim=32, num_heads=2,
                 num_blocks=2, num_classes=4, batch_size=8, dtype="float32").validate()
    model_d = build_model(cfg, attention_impl=None)
    model_f = build_model(cfg, attention_impl=flash_attention)
    x = jax.random.normal(jax.random.key(2), (2, 32, 32, 3), jnp.float32)
    params = model_d.init(jax.random.key(0), x, True)
    out_d = model_d.apply(params, x, True)
    out_f = model_f.apply(params, x, True)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d), rtol=2e-3, atol=2e-3)


class TestTransforms:
    def test_val_transform_shapes_and_normalization(self):
        from PIL import Image
        from vitax.data.transforms import ValTransform, IMAGENET_MEAN, IMAGENET_STD
        t = ValTransform(64)
        img = Image.new("RGB", (300, 200), (124, 116, 104))  # ~ImageNet mean*255
        out = t(img)
        assert out.shape == (64, 64, 3)
        # uniform mean-colored image normalizes to ~0
        assert np.abs(out).max() < 0.1

    def test_train_transform_deterministic_per_index_epoch(self):
        from PIL import Image
        from vitax.data.transforms import TrainTransform
        rng = np.random.default_rng(0)
        arr = rng.integers(0, 255, size=(80, 100, 3), dtype=np.uint8)
        img = Image.fromarray(arr)
        t = TrainTransform(32, seed=1)
        t.set_epoch(1)
        a = t(img, index=7)
        b = t(img, index=7)
        np.testing.assert_array_equal(a, b)  # same epoch+index -> same crop
        t.set_epoch(2)
        c = t(img, index=7)
        assert not np.array_equal(a, c)  # new epoch -> new randomness
        assert a.shape == (32, 32, 3)

    def test_imagefolder_scan(self, tmp_path):
        from PIL import Image
        from vitax.data.imagefolder import ImageFolderDataset
        for cls in ["n01", "n02"]:
            d = tmp_path / "train" / cls
            d.mkdir(parents=True)
            for i in range(3):
                Image.new("RGB", (40, 40), (i * 40, 0, 0)).save(d / f"img{i}.jpg")
        from vitax.data.transforms import val_transform
        ds = ImageFolderDataset(str(tmp_path / "train"), val_transform(32))
        assert len(ds) == 6
        assert ds.classes == ["n01", "n02"]
        img, label = ds[0]
        assert img.shape == (32, 32, 3) and label == 0
        _, label5 = ds[5]
        assert label5 == 1

    def test_imagefolder_missing_dir_raises(self, tmp_path):
        from vitax.data.imagefolder import ImageFolderDataset
        with pytest.raises(FileNotFoundError):
            ImageFolderDataset(str(tmp_path / "nope"))


def test_real_data_end_to_end(devices8, tmp_path):
    """Tiny ImageFolder -> full train() epoch: the non-fake-data path works."""
    from PIL import Image
    from vitax.config import Config
    from vitax.train.loop import train

    rng = np.random.default_rng(0)
    for split, n in [("train", 4), ("val", 2)]:
        for cls in ["a", "b"]:
            d = tmp_path / "data" / split / cls
            d.mkdir(parents=True)
            for i in range(n):
                arr = rng.integers(0, 255, size=(48, 48, 3), dtype=np.uint8)
                Image.fromarray(arr).save(d / f"{i}.jpg")

    cfg = Config(
        image_size=16, patch_size=8, embed_dim=32, num_heads=2, num_blocks=2,
        num_classes=2, batch_size=8, dtype="float32", warmup_steps=0,
        data_dir=str(tmp_path / "data"), num_epochs=1, log_step_interval=1,
        ckpt_dir=str(tmp_path / "ckpt"), ckpt_epoch_interval=1,
        test_epoch_interval=99, num_workers=2,
    ).validate()
    state = train(cfg)
    assert int(jax.device_get(state.step)) == 1  # 8 images // batch 8


def test_att_dropout_kernel_bypass_warning(devices8, capsys):
    """--att_dropout runs fused on the whole-N AND streaming kernels (round
    5); only sp and pp-under-tp still bypass to dense under dropout, and
    make_attention_impl must warn loudly for exactly those cases — and NOT
    where the cliff is gone."""
    from vitax.config import Config
    from vitax.ops.attention import make_attention_impl

    # whole-N shape with dropout: fused dropout variant, no warning
    cfg = Config(image_size=32, patch_size=16, embed_dim=32, num_heads=2,
                 num_blocks=1, att_dropout=0.1).validate()
    impl = make_attention_impl(cfg, mesh=None, force_tpu_kernels=True)
    assert getattr(impl, "vitax_dropout", None) is not None
    assert "WARNING" not in capsys.readouterr().out

    # streaming shape (4096 tokens > MAX_SEQ_IN_VMEM): fused too (round 5)
    cfg_s = Config(image_size=1024, patch_size=16, embed_dim=32, num_heads=2,
                   num_blocks=1, att_dropout=0.1).validate()
    impl_s = make_attention_impl(cfg_s, mesh=None, force_tpu_kernels=True)
    assert getattr(impl_s, "vitax_dropout", None) is not None
    assert "WARNING" not in capsys.readouterr().out

    # pipeline body under tp has no dropout kernel (vitax_pp_impl is None
    # there — dense einsum path): pp x tp with dropout must warn
    from vitax.parallel.mesh import build_mesh
    cfg_pp = Config(image_size=32, patch_size=16, embed_dim=32, num_heads=2,
                    num_blocks=2, pp_size=2, tp_size=2, dp_size=2,
                    att_dropout=0.1).validate()
    make_attention_impl(cfg_pp, build_mesh(cfg_pp),
                        force_tpu_kernels=True)
    out = capsys.readouterr().out
    assert "WARNING" in out and "pipeline" in out

    # no warning at the reference default (att_dropout == 0)
    cfg0 = Config(image_size=32, patch_size=16, embed_dim=32, num_heads=2,
                  num_blocks=1, att_dropout=0.0).validate()
    make_attention_impl(cfg0, mesh=None)
    assert "WARNING" not in capsys.readouterr().out


# ---------------------------------------------------------------------------
# in-kernel attention dropout (vitax/ops/attention.py dropout variants)
# ---------------------------------------------------------------------------

def _dropout_oracle(q, k, v, seed, rate):
    """Dense attention with the EXACT mask the kernels generate (the
    counter-hash RNG is pure jnp, so the oracle shares its code path)."""
    from vitax.ops.attention import dropout_keep_mask
    b, n, h, dh = q.shape
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * dh ** -0.5
    probs = jax.nn.softmax(s, axis=-1)
    mask = jnp.stack([jnp.stack([
        dropout_keep_mask(seed, jnp.uint32(bi * h + hi), n, n, rate)
        for hi in range(h)]) for bi in range(b)])    # (B, H, N, N)
    a = (probs * mask / (1.0 - rate)).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", a, v)


@pytest.mark.parametrize("family", ["4d", "bh"])
def test_flash_dropout_matches_masked_dense(devices8, family):
    """Kernel-path dropout == dense attention with the identical mask, for
    outputs AND grads — both kernel families, real drops in play."""
    from vitax.ops.attention import flash4_dropout, flash_bh_dropout, _to_bh, _from_bh

    shape, rate = (2, 64, 2, 32), 0.35
    kq, kk, kv = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    seed = jnp.uint32(1234)
    scale = shape[-1] ** -0.5

    if family == "4d":
        fn = lambda q, k, v: flash4_dropout(q, k, v, seed, scale, rate)  # noqa: E731
    else:
        fn = lambda q, k, v: _from_bh(flash_bh_dropout(  # noqa: E731
            _to_bh(q), _to_bh(k), _to_bh(v), seed, scale, rate), q.shape)

    out_k = fn(q, k, v)
    out_d = _dropout_oracle(q, k, v, seed, rate)
    # sanity: the mask actually dropped something (kernel != no-dropout path)
    assert not np.allclose(np.asarray(out_k),
                           np.asarray(reference_attention(q, k, v)), atol=1e-3)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_d),
                               rtol=2e-4, atol=2e-4)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    gk = jax.grad(loss(fn), argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss(lambda q, k, v: _dropout_oracle(q, k, v, seed, rate)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gk, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_dropout_mask_statistics_and_determinism():
    """Empirical drop rate ~ rate; same (seed, block) -> identical mask;
    different seed or block index -> different mask; 4D's transposed layout
    holds the same element decisions."""
    from vitax.ops.attention import dropout_keep_mask

    n, rate = 256, 0.3
    seed = jnp.uint32(77)
    m = dropout_keep_mask(seed, jnp.uint32(5), n, n, rate)
    drop_frac = 1.0 - float(jnp.mean(m))
    # binomial std at n^2 = 65536 draws: ~0.0018; allow 5 sigma
    assert abs(drop_frac - rate) < 0.01, drop_frac
    m2 = dropout_keep_mask(seed, jnp.uint32(5), n, n, rate)
    assert np.array_equal(np.asarray(m), np.asarray(m2))
    m3 = dropout_keep_mask(jnp.uint32(78), jnp.uint32(5), n, n, rate)
    m4 = dropout_keep_mask(seed, jnp.uint32(6), n, n, rate)
    assert not np.array_equal(np.asarray(m), np.asarray(m3))
    assert not np.array_equal(np.asarray(m), np.asarray(m4))
    mt = dropout_keep_mask(seed, jnp.uint32(5), n, n, rate, transposed=True)
    assert np.array_equal(np.asarray(m), np.asarray(mt).T)


def test_model_train_att_dropout_keeps_kernel_and_is_deterministic(devices8):
    """Full model: --att_dropout > 0 training routes through the in-kernel
    dropout variant (impl.vitax_dropout) and is reproducible given the same
    dropout rng — nn.Dropout's determinism contract, now on the fused path."""
    from vitax.config import Config
    from vitax.models import build_model
    from vitax.ops.attention import make_attention_impl

    cfg = Config(image_size=32, patch_size=8, embed_dim=32, num_heads=2,
                 num_blocks=2, num_classes=4, batch_size=8, dtype="float32",
                 att_dropout=0.2).validate()
    impl = make_attention_impl(cfg, mesh=None, force_tpu_kernels=True)
    assert getattr(impl, "vitax_dropout", None) is not None
    model = build_model(cfg, attention_impl=impl)
    x = jax.random.normal(jax.random.key(4), (4, 32, 32, 3), jnp.float32)
    params = model.init(jax.random.key(0), x, True)

    rngs = {"dropout": jax.random.key(9)}
    out1 = model.apply(params, x, False, rngs=rngs)
    out2 = model.apply(params, x, False, rngs=rngs)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    out3 = model.apply(params, x, False, rngs={"dropout": jax.random.key(10)})
    assert not np.array_equal(np.asarray(out1), np.asarray(out3))
    # eval path (deterministic) unaffected by the dropout hook
    out_eval = model.apply(params, x, True)
    assert np.all(np.isfinite(np.asarray(out_eval)))

    def loss_fn(p):
        return jnp.sum(model.apply(p, x, False, rngs=rngs) ** 2)

    grads = jax.grad(loss_fn)(params)
    assert all(np.all(np.isfinite(np.asarray(g)))
               for g in jax.tree_util.tree_leaves(grads))


@pytest.mark.parametrize("shape", [(2, 64, 2, 32), (1, 128, 4, 16)])
def test_flash4d_matches_reference_fwd(devices8, shape):
    from vitax.ops.attention import flash_attention_4d
    kq, kk, kv = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(flash_attention_4d(q, k, v)),
        np.asarray(reference_attention(q, k, v)), rtol=2e-4, atol=2e-4)


def test_flash4d_matches_reference_grad(devices8):
    from vitax.ops.attention import flash_attention_4d
    shape = (2, 64, 2, 32)
    kq, kk, kv = jax.random.split(jax.random.key(4), 3)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    gf = jax.grad(loss(flash_attention_4d), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(reference_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_flash4d_odd_head_count(devices8):
    """Head counts with no nice divisors still work (per-head lane slicing)."""
    from vitax.ops.attention import flash_attention_4d
    shape = (1, 64, 6, 16)  # h=6, dh=16: narrow odd-count lane slices
    kq, kk, kv = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(flash_attention_4d(q, k, v)),
        np.asarray(reference_attention(q, k, v)), rtol=2e-4, atol=2e-4)


def _check_flash4d_matches_reference(shape, seed):
    from vitax.ops.attention import flash_attention_4d
    kq, kk, kv = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    np.testing.assert_allclose(
        np.asarray(flash_attention_4d(q, k, v)),
        np.asarray(reference_attention(q, k, v)), rtol=2e-4, atol=2e-4)
    gf = jax.grad(loss(flash_attention_4d), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(reference_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_flash4d_head_grouping(devices8):
    """Shapes whose full head set busts the VMEM budget split into head
    groups; numerics must be identical to the dense reference. Groupings
    whose sublane count is legal (hb % 8 == 0) use the plain (B, H, N) lse
    layout; no padding involved."""
    from vitax.ops.attention import _heads_per_program, _lse_pad_rows
    shape = (1, 256, 16, 64)  # f32: full set needs ~21 MB -> splits to hb=8
    assert _heads_per_program(256, 16, 64, 4) == 8
    assert _lse_pad_rows(8, 16) == 0
    _check_flash4d_matches_reference(shape, seed=6)


def test_flash4d_padded_lse_grouping(devices8):
    """Groupings with hb % 8 != 0 (the 10B family: h=32, dh=160 -> hb=4)
    store lse in the grouped-padded (B, H/hb, 8, N) layout so every block
    satisfies Mosaic's sublane rule — the layout that keeps the 4D kernel
    (640-lane blocks, no (8,128)-tile padding) on the flagship shapes where
    the BH kernel's Dh=160 operands pad 1.6x in HBM. Numerics must match
    the dense reference through fwd AND the padded-lse backward."""
    from vitax.ops.attention import _heads_per_program, _lse_pad_rows
    assert _heads_per_program(256, 32, 160, 2) == 4   # flagship, bf16
    assert _lse_pad_rows(4, 32) == 8
    # f32 version of the same head geometry at n=128 picks hb=4 too
    assert _heads_per_program(128, 32, 160, 4) == 4
    _check_flash4d_matches_reference((1, 128, 32, 160), seed=7)


def test_tpu_kernel_selection_uses_local_heads(devices8):
    """Under tp, the shard_map'd kernel sees num_heads/tp heads — 4D-kernel
    support must be judged on the LOCAL count, falling back to the BH kernel
    when the local grouping has no VMEM fit (review finding, round 3)."""
    from vitax.config import Config
    from vitax.ops.attention import (_tpu_kernel, flash4_supported,
                                     flash_attention, flash_attention_4d)

    # n=324, dh=80, bf16: global h=24 has a legal grouping (hb=8: lane
    # 8*80=640 % 128 == 0, fits the VMEM budget), local h=12 has none
    # (hb=12 full-array busts the budget; every proper divisor's lane dim
    # hb*80 is not a multiple of 128)
    assert flash4_supported(324, 24, 80, 2)
    assert not flash4_supported(324, 12, 80, 2)
    cfg = Config(image_size=144, patch_size=8, embed_dim=1920, num_heads=24,
                 num_blocks=1, dtype="bfloat16").validate()
    k_global, _ = _tpu_kernel(cfg, cfg.num_patches, force=True)
    k_local, name = _tpu_kernel(cfg, cfg.num_patches, force=True,
                                local_heads=12)
    assert k_global is flash_attention_4d
    assert k_local is flash_attention and "BH relayout" in name
