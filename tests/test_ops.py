"""Kernel numerics: Pallas fused attention (interpret mode on CPU) vs the dense
reference path, forward and gradients; data-pipeline transform parity checks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vitax.ops.attention import flash_attention, reference_attention


@pytest.mark.parametrize("shape", [(2, 64, 2, 32), (1, 128, 3, 16)])
def test_flash_matches_reference_fwd(devices8, shape):
    b, n, h, dh = shape
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    out_f = flash_attention(q, k, v)
    out_r = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_r), rtol=2e-4, atol=2e-4)


def test_flash_matches_reference_grad(devices8):
    shape = (2, 64, 2, 32)
    kq, kk, kv = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    gf = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(reference_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3)


def test_model_with_flash_attention_matches_dense(devices8):
    """The full model with the kernel plugged in must match the dense path."""
    from vitax.config import Config
    from vitax.models import build_model

    cfg = Config(image_size=32, patch_size=8, embed_dim=32, num_heads=2,
                 num_blocks=2, num_classes=4, batch_size=8, dtype="float32").validate()
    model_d = build_model(cfg, attention_impl=None)
    model_f = build_model(cfg, attention_impl=flash_attention)
    x = jax.random.normal(jax.random.key(2), (2, 32, 32, 3), jnp.float32)
    params = model_d.init(jax.random.key(0), x, True)
    out_d = model_d.apply(params, x, True)
    out_f = model_f.apply(params, x, True)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d), rtol=2e-3, atol=2e-3)


class TestTransforms:
    def test_val_transform_shapes_and_normalization(self):
        from PIL import Image
        from vitax.data.transforms import ValTransform, IMAGENET_MEAN, IMAGENET_STD
        t = ValTransform(64)
        img = Image.new("RGB", (300, 200), (124, 116, 104))  # ~ImageNet mean*255
        out = t(img)
        assert out.shape == (64, 64, 3)
        # uniform mean-colored image normalizes to ~0
        assert np.abs(out).max() < 0.1

    def test_train_transform_deterministic_per_index_epoch(self):
        from PIL import Image
        from vitax.data.transforms import TrainTransform
        rng = np.random.default_rng(0)
        arr = rng.integers(0, 255, size=(80, 100, 3), dtype=np.uint8)
        img = Image.fromarray(arr)
        t = TrainTransform(32, seed=1)
        t.set_epoch(1)
        a = t(img, index=7)
        b = t(img, index=7)
        np.testing.assert_array_equal(a, b)  # same epoch+index -> same crop
        t.set_epoch(2)
        c = t(img, index=7)
        assert not np.array_equal(a, c)  # new epoch -> new randomness
        assert a.shape == (32, 32, 3)

    def test_imagefolder_scan(self, tmp_path):
        from PIL import Image
        from vitax.data.imagefolder import ImageFolderDataset
        for cls in ["n01", "n02"]:
            d = tmp_path / "train" / cls
            d.mkdir(parents=True)
            for i in range(3):
                Image.new("RGB", (40, 40), (i * 40, 0, 0)).save(d / f"img{i}.jpg")
        from vitax.data.transforms import val_transform
        ds = ImageFolderDataset(str(tmp_path / "train"), val_transform(32))
        assert len(ds) == 6
        assert ds.classes == ["n01", "n02"]
        img, label = ds[0]
        assert img.shape == (32, 32, 3) and label == 0
        _, label5 = ds[5]
        assert label5 == 1

    def test_imagefolder_missing_dir_raises(self, tmp_path):
        from vitax.data.imagefolder import ImageFolderDataset
        with pytest.raises(FileNotFoundError):
            ImageFolderDataset(str(tmp_path / "nope"))


def test_real_data_end_to_end(devices8, tmp_path):
    """Tiny ImageFolder -> full train() epoch: the non-fake-data path works."""
    from PIL import Image
    from vitax.config import Config
    from vitax.train.loop import train

    rng = np.random.default_rng(0)
    for split, n in [("train", 4), ("val", 2)]:
        for cls in ["a", "b"]:
            d = tmp_path / "data" / split / cls
            d.mkdir(parents=True)
            for i in range(n):
                arr = rng.integers(0, 255, size=(48, 48, 3), dtype=np.uint8)
                Image.fromarray(arr).save(d / f"{i}.jpg")

    cfg = Config(
        image_size=16, patch_size=8, embed_dim=32, num_heads=2, num_blocks=2,
        num_classes=2, batch_size=8, dtype="float32", warmup_steps=0,
        data_dir=str(tmp_path / "data"), num_epochs=1, log_step_interval=1,
        ckpt_dir=str(tmp_path / "ckpt"), ckpt_epoch_interval=1,
        test_epoch_interval=99, num_workers=2,
    ).validate()
    state = train(cfg)
    assert int(jax.device_get(state.step)) == 1  # 8 images // batch 8
