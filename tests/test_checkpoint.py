"""Checkpoint tests: sharded round-trip, resume-by-epoch through the full loop,
cross-topology (resharded) restore, consolidation export (SURVEY.md section 4)."""

import os

import jax
import jax.numpy as jnp
import numpy as np

from vitax.checkpoint import restore_state, save_state, latest_epoch
from vitax.checkpoint.consolidate import consolidate
from vitax.config import Config
from vitax.models import build_model
from vitax.parallel.mesh import build_mesh
from vitax.parallel.sharding import shardings_of
from vitax.train.state import build_optimizer, make_train_state


def tiny_cfg(**kw):
    base = dict(image_size=16, patch_size=8, embed_dim=32, num_heads=2, num_blocks=2,
                num_classes=4, batch_size=16, dtype="float32", warmup_steps=2)
    base.update(kw)
    return Config(**base).validate()


def make_state(cfg):
    mesh = build_mesh(cfg)
    model = build_model(cfg)
    tx, _ = build_optimizer(cfg, max_iteration=100)
    state, sspecs, _ = make_train_state(cfg, model, tx, mesh, jax.random.key(cfg.seed))
    return mesh, state, sspecs


def abstract_of(state, mesh, sspecs):
    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        jax.eval_shape(lambda: state), shardings_of(mesh, sspecs))


def test_round_trip(devices8, tmp_path):
    cfg = tiny_cfg(ckpt_dir=str(tmp_path))
    mesh, state, sspecs = make_state(cfg)
    save_state(cfg.ckpt_dir, 1, state, wait=True)
    assert latest_epoch(cfg.ckpt_dir) == 1
    restored = restore_state(cfg.ckpt_dir, 1, abstract_of(state, mesh, sspecs))
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # restored arrays carry the sharded layout
    qkv = restored.params["params"]["blocks"]["attn"]["qkv"]["kernel"]
    assert qkv.addressable_shards[0].data.size == qkv.size // 8


def test_cross_topology_restore(devices8, tmp_path):
    """Save under fsdp=8, restore under dp=2 x fsdp=4 — Orbax reshards on load.
    The reference cannot do this without offline consolidation (utils.py:27-29)."""
    cfg_a = tiny_cfg(ckpt_dir=str(tmp_path))
    mesh_a, state_a, _ = make_state(cfg_a)
    save_state(cfg_a.ckpt_dir, 3, state_a)

    cfg_b = tiny_cfg(ckpt_dir=str(tmp_path), dp_size=2, fsdp_size=4)
    mesh_b, state_b, sspecs_b = make_state(cfg_b)
    restored = restore_state(cfg_b.ckpt_dir, 3, abstract_of(state_b, mesh_b, sspecs_b))
    for a, b in zip(jax.tree.leaves(state_a), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    qkv = restored.params["params"]["blocks"]["attn"]["qkv"]["kernel"]
    assert qkv.sharding.mesh.shape["fsdp"] == 4

    # and onto a pp x tp mesh (round-4 composition): same param tree, the
    # blocks' layer axis resharded over "pp" and Megatron dims over "tp"
    cfg_c = tiny_cfg(ckpt_dir=str(tmp_path), pp_size=2, tp_size=2,
                     dp_size=2, fsdp_size=1)
    mesh_c, state_c, sspecs_c = make_state(cfg_c)
    restored_c = restore_state(cfg_c.ckpt_dir, 3,
                               abstract_of(state_c, mesh_c, sspecs_c))
    for a, b in zip(jax.tree.leaves(state_a), jax.tree.leaves(restored_c)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    qkv_c = restored_c.params["params"]["blocks"]["attn"]["qkv"]["kernel"]
    assert qkv_c.sharding.mesh.shape["pp"] == 2
    assert "pp" in tuple(qkv_c.sharding.spec) and (
        "tp" in tuple(qkv_c.sharding.spec))


def test_cross_topology_restore_moe_pp_ep(devices8, tmp_path):
    """MoE expert params (stacked (L, E, ...) leaves) save under the
    ep-sharded scan mesh and restore onto the pp x ep mesh (round-5: the
    manual-a2a pipeline body). The GLOBAL tree is identical across the two
    — MoeMlp declares local (E/ep, ...) shapes only INSIDE the pipeline
    shard_map, never in the checkpoint — so Orbax reshard-on-load covers
    the composition with no consolidation step."""
    moe_kw = dict(moe_experts=4, ckpt_dir=str(tmp_path))
    cfg_a = tiny_cfg(ep_size=2, dp_size=2, fsdp_size=2, **moe_kw)
    mesh_a, state_a, _ = make_state(cfg_a)
    save_state(cfg_a.ckpt_dir, 2, state_a, wait=True)

    cfg_b = tiny_cfg(pp_size=2, ep_size=2, dp_size=2, fsdp_size=1, **moe_kw)
    mesh_b, state_b, sspecs_b = make_state(cfg_b)
    restored = restore_state(cfg_b.ckpt_dir, 2,
                             abstract_of(state_b, mesh_b, sspecs_b))
    for a, b in zip(jax.tree.leaves(state_a), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    w1 = restored.params["params"]["blocks"]["moe"]["w1"]
    assert w1.sharding.mesh.shape["pp"] == 2
    spec = tuple(w1.sharding.spec)
    assert "pp" in spec and "ep" in spec, spec


def test_resume_through_loop(devices8, tmp_path):
    """Train 2 epochs saving each; resume from epoch 1 and confirm the step
    counter and params continue from the checkpoint (reference --resume_epoch,
    run_vit_training.py:246-248,254)."""
    from vitax.train.loop import train
    common = dict(
        fake_data=True, steps_per_epoch=2, log_step_interval=10,
        ckpt_dir=str(tmp_path / "ckpt"), ckpt_epoch_interval=1,
        test_epoch_interval=99, num_workers=2, eval_max_batches=2,
    )
    state2 = train(tiny_cfg(num_epochs=2, **common))
    assert int(jax.device_get(state2.step)) == 4

    # resume from epoch 1: runs epoch 2 only, starting at step 2
    state_resumed = train(tiny_cfg(num_epochs=2, resume_epoch=1, **common))
    assert int(jax.device_get(state_resumed.step)) == 4
    for a, b in zip(jax.tree.leaves(state2.params), jax.tree.leaves(state_resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_auto_resume_latest(devices8, tmp_path):
    """--resume_epoch -1 resumes from the newest complete checkpoint; with an
    empty ckpt_dir it starts fresh (failure-recovery convenience beyond the
    reference's manual epoch numbering, SURVEY.md section 5)."""
    from vitax.train.loop import train
    common = dict(
        fake_data=True, steps_per_epoch=2, log_step_interval=10,
        ckpt_dir=str(tmp_path / "ckpt"), ckpt_epoch_interval=1,
        test_epoch_interval=99, num_workers=2, eval_max_batches=2,
    )
    # empty dir -> fresh start, trains both epochs
    state = train(tiny_cfg(num_epochs=2, resume_epoch=-1, **common))
    assert int(jax.device_get(state.step)) == 4
    # now epoch_1 and epoch_2 exist -> auto-resume picks epoch 2 (no new steps)
    state2 = train(tiny_cfg(num_epochs=2, resume_epoch=-1, **common))
    assert int(jax.device_get(state2.step)) == 4
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(state2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_async_save_does_not_block(devices8, tmp_path, monkeypatch):
    """save_state (wait=False) must NOT drain the background write — the whole
    point is that the commit overlaps the next epoch's training (VERDICT
    round-1 item 4)."""
    from vitax.checkpoint import orbax_io

    cfg = tiny_cfg(ckpt_dir=str(tmp_path))
    _, state, _ = make_state(cfg)
    ckptr = orbax_io._checkpointer()
    # orbax's save() legitimately drains the PREVIOUS save before starting a
    # new one; what must NOT happen is a drain after this save's background
    # commit starts — so track the event order
    events = []
    orig_wait = ckptr.wait_until_finished
    monkeypatch.setattr(ckptr, "wait_until_finished",
                        lambda: (events.append("wait"), orig_wait())[1])
    mgr = ckptr._async_manager
    orig_start = mgr.start_async_commit
    monkeypatch.setattr(
        mgr, "start_async_commit",
        lambda *a, **k: (events.append("commit"), orig_start(*a, **k))[1])
    save_state(cfg.ckpt_dir, 1, state)
    assert "commit" in events, "save did not go through the async commit path"
    assert "wait" not in events[events.index("commit"):], (
        "async save_state drained its own write before returning")
    orbax_io.wait_until_finished()
    assert events[-1] == "wait" and latest_epoch(cfg.ckpt_dir) == 1


def test_async_save_overlaps_training_and_snapshots_values(devices8, tmp_path):
    """A save in flight must (a) coexist with further jitted train steps and
    (b) have snapshotted the state values at save time — later updates to the
    (potentially donated) buffers must not leak into the checkpoint."""
    cfg = tiny_cfg(ckpt_dir=str(tmp_path))
    mesh, state, sspecs = make_state(cfg)
    saved_qkv = np.asarray(state.params["params"]["blocks"]["attn"]["qkv"]["kernel"])

    save_state(cfg.ckpt_dir, 7, state)  # async, returns immediately

    # training continues while the write commits; donation reuses the buffers
    bump = jax.jit(
        lambda s: s.replace(step=s.step + 1,
                            params=jax.tree.map(lambda x: x * 2.0, s.params)),
        donate_argnums=(0,))
    for _ in range(3):
        state = bump(state)
    assert int(jax.device_get(state.step)) == 3

    restored = restore_state(cfg.ckpt_dir, 7, abstract_of(state, mesh, sspecs))
    np.testing.assert_array_equal(
        np.asarray(restored.params["params"]["blocks"]["attn"]["qkv"]["kernel"]),
        saved_qkv)  # values from save time, not the x8 post-update buffers
    assert int(jax.device_get(restored.step)) == 0


def test_consolidate_export(devices8, tmp_path):
    cfg = tiny_cfg(ckpt_dir=str(tmp_path))
    _, state, _ = make_state(cfg)
    save_state(cfg.ckpt_dir, 5, state)
    out = str(tmp_path / "full.npz")
    flat = consolidate(cfg.ckpt_dir, 5, out, params_only=True)
    assert os.path.exists(out)
    loaded = np.load(out)
    key = "params/blocks/attn/qkv/kernel"
    assert key in loaded
    np.testing.assert_array_equal(
        loaded[key], np.asarray(state.params["params"]["blocks"]["attn"]["qkv"]["kernel"]))
    total = sum(loaded[k].size for k in loaded.files)
    from vitax.models.vit import expected_param_count
    assert total == expected_param_count(cfg)


def test_step_granular_preemption_resume(devices8, tmp_path, monkeypatch):
    """Preempt mid-epoch at step k, auto-resume, and prove the resumed run's
    final state EQUALS an uninterrupted run's — no data skipped or repeated
    (improves on the reference's epoch-granular --resume_epoch contract,
    run_vit_training.py:246-248). The sampler order is a pure function of
    (seed, epoch), so the sidecar's step count pins the exact position."""
    from vitax.train import preempt
    from vitax.train.loop import train
    from vitax.checkpoint.orbax_io import load_resume_step

    common = dict(
        fake_data=True, num_epochs=2, steps_per_epoch=5, log_step_interval=10,
        ckpt_epoch_interval=99, test_epoch_interval=99, num_workers=2,
        eval_max_batches=1,
    )
    base = train(tiny_cfg(ckpt_dir=str(tmp_path / "base"), **common))
    assert int(jax.device_get(base.step)) == 10

    # interrupted run: the preemption flag fires after the 4th poll — i.e.
    # right after step 4 of epoch 1 completes (one poll per step)
    calls = {"n": 0}

    def fire_on_4th():
        calls["n"] += 1
        return calls["n"] >= 4

    pre_dir = str(tmp_path / "pre")
    monkeypatch.setattr(preempt, "requested", fire_on_4th)
    state_pre = train(tiny_cfg(ckpt_dir=pre_dir, **common))
    monkeypatch.undo()
    assert int(jax.device_get(state_pre.step)) == 4
    assert load_resume_step(pre_dir, 1) == 4  # sidecar recorded 4 done steps

    # auto-resume re-enters epoch 1 at step 5 and finishes both epochs
    resumed = train(tiny_cfg(ckpt_dir=pre_dir, resume_epoch=-1, **common))
    assert int(jax.device_get(resumed.step)) == 10
    for a, b in zip(jax.tree.leaves(base.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)

    # an epoch-boundary save of the same epoch clears the stale sidecar
    save_state(pre_dir, 1, resumed, wait=True)
    assert load_resume_step(pre_dir, 1) is None
