"""Zero-stall checkpointing + peer-replicated state (PR 11).

Covers vitax/checkpoint/snapshot.py (staged device->host snapshots, the
background write pipeline, the ckpt_stall_s accounting pin) and
vitax/checkpoint/peer.py (pack/unpack, the local PeerStore, restore
negotiation, checksum-failure fallback to Orbax), plus the satellites:
checkpoint GC (--keep_checkpoints), the ControlPlane's default exit
deadline, the VTX108 ast-lint rule, metrics_report's new fields, and the
supervisor's peer-aware progress frontier. The slow 2-process drill at the
bottom is the acceptance test: SIGKILL one of two hosts mid-epoch, resume
from peer shards with ZERO shared-storage checkpoint reads, and pin bitwise
parameter equality against the uninterrupted run.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import zlib

import jax
import numpy as np
import pytest

from tests.test_checkpoint import abstract_of, make_state, tiny_cfg
from tests.test_multiprocess import (REPO, _free_port, _tiny_train_argv,
                                     _two_proc_env)
from vitax.checkpoint import peer, snapshot
from vitax.checkpoint.orbax_io import (
    committed_epochs, epoch_ckpt_path, prune_checkpoints, restore_state,
    save_state)
from vitax.train.control import (
    BIT_PEER_RESTORE, EXIT_HANG, ControlPlane, agree_peer_restore)


def _leaves_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _loop_common(tmp_path, **kw):
    base = dict(
        fake_data=True, steps_per_epoch=4, log_step_interval=1,
        ckpt_dir=str(tmp_path / "ckpt"), ckpt_epoch_interval=1,
        test_epoch_interval=99, num_workers=2, eval_max_batches=1,
        metrics_dir=str(tmp_path / "metrics"),
    )
    base.update(kw)
    return base


def _read_metrics(tmp_path):
    recs = []
    with open(tmp_path / "metrics" / "metrics.jsonl") as f:
        for line in f:
            recs.append(json.loads(line))
    steps = [r for r in recs if not r.get("kind") and "loss" in r]
    events = [r for r in recs if r.get("kind")]
    return steps, events


# --- unit: ring math, progress keys, the agreement fold ----------------------

def test_ring_buddy_and_guard():
    assert peer.ring_buddy(0, 2) == 1 and peer.ring_buddy(1, 2) == 0
    assert peer.ring_guard(0, 2) == 1 and peer.ring_guard(1, 2) == 0
    # at n=4 the ring is a proper cycle: buddy(guard(i)) == i
    for i in range(4):
        assert peer.ring_buddy(peer.ring_guard(i, 4), 4) == i
    assert peer.ring_buddy(3, 4) == 0  # wraps


def test_progress_key_orders_boundary_above_mid_epoch():
    # boundary save of epoch e (step 0) means e is COMPLETE
    assert peer.progress_key(2, 0) == (3, 0)
    assert peer.progress_key(2, 7) == (2, 7)
    assert peer.progress_key(2, 0) > peer.progress_key(2, 99)
    assert peer.progress_key(3, 1) > peer.progress_key(2, 0)


def test_agree_peer_restore_fold():
    # single process: the local verdict stands, no collective
    assert agree_peer_restore(True, process_count=1)
    assert not agree_peer_restore(False, process_count=1)
    # multi process: one raised veto bit in the OR-fold kills the restore
    assert agree_peer_restore(
        True, process_count=2, collective=lambda w: w | 0)
    assert not agree_peer_restore(
        True, process_count=2, collective=lambda w: w | BIT_PEER_RESTORE)
    assert not agree_peer_restore(
        False, process_count=2, collective=lambda w: w)


def test_bit_peer_restore_is_out_of_band():
    """The veto bit must NOT join the in-loop signal word: unpack_word still
    rejects it (it never travels on the step-boundary cadence)."""
    from vitax.train.control import _ALL_BITS, unpack_word
    assert not (BIT_PEER_RESTORE & _ALL_BITS)
    with pytest.raises(ValueError):
        unpack_word(BIT_PEER_RESTORE)


# --- staging + pipeline ------------------------------------------------------

def test_staging_roundtrip_reuses_buffers(devices8):
    cfg = tiny_cfg()
    _, state, _ = make_state(cfg)
    pipe = snapshot.SnapshotPipeline()
    try:
        snap = pipe.stage(state, epoch=1, step_in_epoch=3)
        assert snap.version == (1, 3, 1)
        _leaves_equal(state, snap.rebuild())
        # the staged copies are OWNED buffers, not views of device memory:
        # a post-stage state update must not leak into the snapshot
        saved = np.array(snap.buffers(0)[0], copy=True)
        bufs_first = [id(snap.buffers(i)[0])
                      for i in range(len(snap.specs))]
        snap.release()
        # the freed buffer set is REUSED by the next stage (no per-save
        # allocation churn — the CheckFreq staging discipline)
        snap2 = pipe.stage(state, epoch=1, step_in_epoch=4)
        assert [id(snap2.buffers(i)[0])
                for i in range(len(snap2.specs))] == bufs_first
        np.testing.assert_array_equal(snap2.buffers(0)[0], saved)
        snap2.release()
    finally:
        pipe.close()


def test_pipeline_persist_matches_state(devices8, tmp_path):
    """submit(persist_to=...) + drain commits an Orbax checkpoint equal to
    the live state — the background write path loses nothing."""
    cfg = tiny_cfg(ckpt_dir=str(tmp_path))
    mesh, state, sspecs = make_state(cfg)
    pipe = snapshot.SnapshotPipeline()
    try:
        pipe.submit(state, epoch=3, persist_to=cfg.ckpt_dir)
        pipe.drain()
    finally:
        pipe.close()
    from vitax.checkpoint.orbax_io import wait_until_finished
    wait_until_finished()
    assert committed_epochs(cfg.ckpt_dir) == [3]
    restored = restore_state(cfg.ckpt_dir, 3, abstract_of(state, mesh, sspecs))
    _leaves_equal(state, restored)


def test_submit_returns_before_slow_write(devices8, tmp_path, monkeypatch):
    """The zero-stall contract at the API level: with the Orbax write made
    artificially slow, submit() must still return in staging time (the loop
    dispatches step N+1 immediately), and drain() must still commit."""
    cfg = tiny_cfg(ckpt_dir=str(tmp_path))
    _, state, _ = make_state(cfg)
    calls = []

    def slow_save(ckpt_dir, epoch, tree, **kw):
        time.sleep(0.5)
        calls.append((ckpt_dir, epoch))

    import vitax.checkpoint.orbax_io as orbax_io_mod
    monkeypatch.setattr(orbax_io_mod, "save_state", slow_save)
    pipe = snapshot.SnapshotPipeline()
    try:
        t0 = time.perf_counter()
        pipe.submit(state, epoch=1, persist_to=cfg.ckpt_dir)
        elapsed = time.perf_counter() - t0
        assert elapsed < 0.25, (
            f"submit took {elapsed:.3f}s — the slow write leaked onto the "
            f"loop thread")
        assert pipe.last_stall_s < 0.25
        assert not calls  # the write had not even started synchronously
        pipe.drain()
        assert calls == [(cfg.ckpt_dir, 1)]
        # VITAX_CKPT_SYNC=1 forces the old synchronous behavior (debug seam)
        monkeypatch.setenv("VITAX_CKPT_SYNC", "1")
        t0 = time.perf_counter()
        pipe.submit(state, epoch=2, persist_to=cfg.ckpt_dir)
        assert time.perf_counter() - t0 >= 0.5
        assert len(calls) == 2
    finally:
        pipe.close()


def test_step_program_identical_with_snapshot_flags(devices8):
    """Snapshotting is host-side by construction: the lowered step program
    must be bit-identical with --zero_stall_ckpt/--replicate_steps on or
    off (the same pin telemetry and the control plane carry)."""
    from tests.test_train_smoke import build_train_objects, random_batch

    def lowered(cfg):
        mesh, state, step_fn, _ = build_train_objects(cfg)
        batch = random_batch(cfg, mesh)
        return step_fn.lower(state, batch, jax.random.key(0)).as_text()

    assert lowered(tiny_cfg()) == lowered(
        tiny_cfg(zero_stall_ckpt=True, replicate_steps=2))


# --- peer store + negotiation ------------------------------------------------

def test_peer_store_roundtrip_and_checksum_failure(devices8, tmp_path):
    cfg = tiny_cfg()
    _, state, _ = make_state(cfg)
    pipe = snapshot.SnapshotPipeline()
    try:
        snap = pipe.stage(state, epoch=1, step_in_epoch=2)
        meta, payload = peer.pack_snapshot(snap, src=0)
        snap.release()
    finally:
        pipe.close()
    store = peer.PeerStore(str(tmp_path / "store"))
    store.put(meta, payload)
    assert tuple(store.holdings()[0]["version"]) == (1, 2, 1)
    got_meta, got_payload = store.load(0, expect_version=(1, 2, 1))
    parts = peer.unpack_payload(got_meta, got_payload)
    want_keys = {sh["key"] for leaf in meta["leaves"] for sh in leaf["shards"]}
    assert set(parts) == want_keys

    # version mismatch is loud
    with pytest.raises(peer.PeerRestoreError):
        store.load(0, expect_version=(9, 9, 1))
    # flipped payload bytes fail the crc32 end-to-end check
    blob = store_path = os.path.join(store.root, "host_0", "shard.npz")
    raw = bytearray(open(blob, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(store_path, "wb") as f:
        f.write(bytes(raw))
    with pytest.raises(peer.PeerRestoreError):
        store.load(0)


def test_negotiate_single_proc_respects_frontier(devices8, tmp_path):
    cfg = tiny_cfg()
    mesh, state, sspecs = make_state(cfg)
    pipe = snapshot.SnapshotPipeline()
    try:
        snap = pipe.stage(state, epoch=2, step_in_epoch=6)
        meta, payload = peer.pack_snapshot(snap, src=0)
        snap.release()
    finally:
        pipe.close()
    store = peer.PeerStore(str(tmp_path / "store"))
    store.put(meta, payload)

    # peer version (2, 6) loses to an Orbax frontier already past it
    assert peer.negotiate_restore(
        store, process_index=0, process_count=1,
        orbax_frontier=peer.progress_key(2, 0)) is None
    # ...and wins against an older frontier; the plan restores bitwise
    plan = peer.negotiate_restore(
        store, process_index=0, process_count=1,
        orbax_frontier=peer.progress_key(2, 3))
    assert plan is not None and plan.version == (2, 6, 1)
    assert plan.epoch == 2 and plan.meta["step_in_epoch"] == 6
    restored = peer.restore_from_store(
        store, plan, abstract_of(state, mesh, sspecs))
    _leaves_equal(state, restored)


def test_restore_falls_back_to_orbax_on_bad_peer(devices8, tmp_path):
    """Satellite 3, unit half: a buddy shard failing its checksum must fall
    back LOUDLY to the last committed Orbax epoch — kind:"control" event,
    info records the fallback — and still return a usable state."""
    cfg = tiny_cfg(ckpt_dir=str(tmp_path / "ckpt"))
    mesh, state, sspecs = make_state(cfg)
    save_state(cfg.ckpt_dir, 1, state, wait=True)

    pipe = snapshot.SnapshotPipeline()
    try:
        snap = pipe.stage(state, epoch=1, step_in_epoch=2)
        meta, payload = peer.pack_snapshot(snap, src=0)
        snap.release()
    finally:
        pipe.close()
    store = peer.PeerStore(str(tmp_path / "store"))
    store.put(meta, payload)
    # corrupt the stored payload AFTER the meta committed
    blob = os.path.join(store.root, "host_0", "shard.npz")
    raw = bytearray(open(blob, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(blob, "wb") as f:
        f.write(bytes(raw))

    plan = peer.negotiate_restore(store, process_index=0, process_count=1)
    assert plan is not None  # negotiation reads metas, not payloads
    events = []
    restored, info = peer.restore_state_preferring_peers(
        store, plan, cfg.ckpt_dir, 1, abstract_of(state, mesh, sspecs),
        on_event=lambda kind, payload: events.append((kind, payload)))
    assert info["path"] == "orbax" and info["epoch"] == 1
    assert "fallback_from" in info
    _leaves_equal(state, restored)
    kinds = [(k, p.get("event")) for k, p in events]
    assert ("control", "peer_restore_failed") in kinds

    # with NO Orbax epoch to fall back to, the failure is fatal (loud, not
    # a silent from-scratch restart)
    with pytest.raises(RuntimeError):
        peer.restore_state_preferring_peers(
            store, plan, cfg.ckpt_dir, 0, abstract_of(state, mesh, sspecs))


# --- multi-host negotiation (fake KV + OR-fold, two threads) -----------------

class _FakeKV:
    """In-memory stand-in for the coordination-service KV client."""

    def __init__(self):
        self._d = {}
        self._cond = threading.Condition()

    def key_value_set(self, key, value, allow_overwrite=False):
        with self._cond:
            self._d[key] = value
            self._cond.notify_all()

    def blocking_key_value_get(self, key, timeout_ms):
        deadline = time.monotonic() + timeout_ms / 1000.0
        with self._cond:
            while key not in self._d:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(key)
                self._cond.wait(remaining)
            return self._d[key]


class _OrFold:
    """A per-round OR collective across n threads (the BIT_PEER_RESTORE
    agreement fold) — every participant blocks until all n contributed."""

    def __init__(self, n):
        self.n = n
        self._cond = threading.Condition()
        self._words = []
        self._done = []

    def __call__(self, word):
        with self._cond:
            rnd = len(self._done)
            self._words.append(int(word))
            if len(self._words) == self.n:
                folded = 0
                for w in self._words:
                    folded |= w
                self._done.append(folded)
                self._words = []
                self._cond.notify_all()
            else:
                if not self._cond.wait_for(lambda: len(self._done) > rnd,
                                           timeout=30):
                    raise TimeoutError("OR-fold never completed")
            return self._done[rnd]


def _put_fake_shard(store, src, version, corrupt=False):
    """A minimal valid peer blob (negotiation only reads meta + crc32)."""
    payload = json.dumps({"src": src, "v": list(version)}).encode() * 7
    store.put({"version": list(version), "src": int(src),
               "step_in_epoch": int(version[1]),
               "process_count": int(version[2]), "leaves": [],
               "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
               "nbytes": len(payload)}, payload)
    if corrupt:
        blob = os.path.join(store.root, f"host_{src}", "shard.npz")
        raw = bytearray(open(blob, "rb").read())
        raw[0] ^= 0xFF
        with open(blob, "wb") as f:
            f.write(bytes(raw))


def _negotiate_two(stores, timeout_s=5.0):
    kv, fold = _FakeKV(), _OrFold(2)
    results, errors = [None, None], [None, None]

    def run(pid):
        try:
            results[pid] = peer.negotiate_restore(
                stores[pid], process_index=pid, process_count=2,
                client=kv, collective=fold, timeout_s=timeout_s)
        except BaseException as e:  # noqa: BLE001 — surfaced by the assert below
            errors[pid] = e

    threads = [threading.Thread(target=run, args=(pid,)) for pid in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert errors == [None, None], errors
    return results


def test_negotiate_verifies_held_shards_and_refetches(tmp_path):
    """A host whose LOCALLY HELD copy of the agreed version is corrupt must
    detect it during negotiation and replace it from the serving holder —
    not sail through the agreement and then strand itself alone on the
    Orbax fallback at restore time (the divergent-replica hazard the
    BIT_PEER_RESTORE gate exists to prevent)."""
    v = (1, 4, 2)
    stores = [peer.PeerStore(str(tmp_path / "p0")),
              peer.PeerStore(str(tmp_path / "p1"))]
    _put_fake_shard(stores[0], 0, v)
    _put_fake_shard(stores[0], 1, v)          # host 0 guards host 1 too
    _put_fake_shard(stores[1], 1, v, corrupt=True)  # host 1's own copy rots

    plans = _negotiate_two(stores)
    assert all(p is not None and p.version == v for p in plans), plans
    # the corrupt copy was REPLACED during negotiation: every shard in
    # every store now load-verifies for the agreed version
    for store in stores:
        for src in (0, 1):
            if src in store.holdings():
                store.load(src, expect_version=v)
    stores[1].load(1, expect_version=v)  # specifically the refetched one


def test_negotiate_declines_together_when_sole_copy_corrupt(tmp_path):
    """When the ONLY copy of a shard is corrupt, no host can serve it: both
    hosts must decline the peer path together (None == Orbax fallback for
    the whole pod), not split."""
    v = (1, 4, 2)
    stores = [peer.PeerStore(str(tmp_path / "p0")),
              peer.PeerStore(str(tmp_path / "p1"))]
    _put_fake_shard(stores[0], 0, v)
    _put_fake_shard(stores[1], 1, v, corrupt=True)  # sole copy of shard 1
    plans = _negotiate_two(stores, timeout_s=1.0)
    assert plans == [None, None], plans


def test_negotiate_counts_mixed_version_coverage(tmp_path):
    """The common steady state: each host's self-spill is one replication
    window ahead of the replica it mirrors for its guard. The newest
    version IS fully covered across hosts — negotiation must find it
    rather than flattening each host to a single version and declining."""
    v_new, v_old = (1, 4, 2), (1, 2, 2)
    stores = [peer.PeerStore(str(tmp_path / "p0")),
              peer.PeerStore(str(tmp_path / "p1"))]
    _put_fake_shard(stores[0], 0, v_new)  # fresh self-spill
    _put_fake_shard(stores[0], 1, v_old)  # buddy replica lags one window
    _put_fake_shard(stores[1], 1, v_new)
    _put_fake_shard(stores[1], 0, v_old)

    plans = _negotiate_two(stores)
    assert all(p is not None and p.version == v_new for p in plans), plans
    # both hosts completed their stores: every shard of v_new everywhere
    for store in stores:
        for src in (0, 1):
            store.load(src, expect_version=v_new)


def test_post_agreement_veto_drops_to_orbax(devices8, tmp_path):
    """The second fold: even when THIS host's peer load succeeds, a peer's
    post-agreement veto must drop it to the Orbax fallback with the pod —
    and with no veto the peer path stands."""
    cfg = tiny_cfg(ckpt_dir=str(tmp_path / "ckpt"))
    mesh, state, sspecs = make_state(cfg)
    save_state(cfg.ckpt_dir, 1, state, wait=True)
    pipe = snapshot.SnapshotPipeline()
    try:
        snap = pipe.stage(state, epoch=1, step_in_epoch=2)
        meta, payload = peer.pack_snapshot(snap, src=0)
        snap.release()
    finally:
        pipe.close()
    store = peer.PeerStore(str(tmp_path / "store"))
    store.put(meta, payload)
    plan = peer.negotiate_restore(store, process_index=0, process_count=1)
    assert plan is not None

    events = []
    restored, info = peer.restore_state_preferring_peers(
        store, plan, cfg.ckpt_dir, 1, abstract_of(state, mesh, sspecs),
        on_event=lambda kind, payload: events.append((kind, payload)),
        process_count=2, collective=lambda w: w | BIT_PEER_RESTORE)
    assert info["path"] == "orbax" and info["epoch"] == 1
    assert "fallback_from" in info
    _leaves_equal(state, restored)
    assert ("control", "peer_restore_failed") in [
        (k, p.get("event")) for k, p in events]

    restored2, info2 = peer.restore_state_preferring_peers(
        store, plan, cfg.ckpt_dir, 1, abstract_of(state, mesh, sspecs),
        process_count=2, collective=lambda w: w)
    assert info2["path"] == "peer"
    _leaves_equal(state, restored2)


# --- rebuild HBM gate --------------------------------------------------------

def test_rebuild_gates_on_hbm_headroom(devices8, monkeypatch):
    """The persist path's transient second device copy must be refused —
    loudly, with guidance — when device memory_stats say it cannot fit;
    the escape hatch and the roomy case both proceed."""
    cfg = tiny_cfg()
    _, state, _ = make_state(cfg)
    pipe = snapshot.SnapshotPipeline()
    try:
        snap = pipe.stage(state, epoch=1)
        monkeypatch.setenv("VITAX_SNAPSHOT_HBM_WAIT_S", "0")
        monkeypatch.setattr(
            snapshot, "_device_memory_stats",
            lambda device: {"bytes_limit": 1024, "bytes_in_use": 1024})
        with pytest.raises(RuntimeError, match="HBM"):
            snap.rebuild()
        monkeypatch.setenv("VITAX_SNAPSHOT_HBM_CHECK", "0")
        _leaves_equal(state, snap.rebuild())
        monkeypatch.delenv("VITAX_SNAPSHOT_HBM_CHECK")
        monkeypatch.setattr(
            snapshot, "_device_memory_stats",
            lambda device: {"bytes_limit": 1 << 40, "bytes_in_use": 0})
        _leaves_equal(state, snap.rebuild())
        snap.release()
    finally:
        pipe.close()


# --- checkpoint GC (--keep_checkpoints) --------------------------------------

def _fake_committed(ckpt_dir, epoch, sidecar=False):
    d = epoch_ckpt_path(str(ckpt_dir), epoch)
    os.makedirs(d)
    open(os.path.join(d, "_CHECKPOINT_METADATA"), "w").close()
    if sidecar:
        with open(d + ".resume.json", "w") as f:
            json.dump({"step_in_epoch": 3}, f)


def test_prune_checkpoints_spares_torn_dirs(tmp_path):
    ckpt = tmp_path / "ckpt"
    for ep in (1, 2, 3, 4):
        _fake_committed(ckpt, ep, sidecar=(ep == 2))
    torn = epoch_ckpt_path(str(ckpt), 5)  # crashed mid-write: NO marker
    os.makedirs(torn)
    open(os.path.join(torn, "partial.bin"), "w").close()

    assert prune_checkpoints(str(ckpt), 2) == [1, 2]
    assert committed_epochs(str(ckpt)) == [3, 4]
    assert not os.path.exists(epoch_ckpt_path(str(ckpt), 1))
    assert not os.path.exists(epoch_ckpt_path(str(ckpt), 2) + ".resume.json")
    # the torn dir is crash forensics — GC must never touch it
    assert os.path.exists(os.path.join(torn, "partial.bin"))
    # keep <= 0 keeps everything; keep >= count prunes nothing
    assert prune_checkpoints(str(ckpt), 0) == []
    assert prune_checkpoints(str(ckpt), 5) == []
    assert committed_epochs(str(ckpt)) == [3, 4]


def test_loop_gc_keeps_newest(devices8, tmp_path, monkeypatch):
    from vitax.train.loop import train
    monkeypatch.setenv("VITAX_CKPT_SYNC", "1")  # GC needs committed dirs
    torn = epoch_ckpt_path(str(tmp_path / "ckpt"), 9)
    os.makedirs(torn)
    common = _loop_common(tmp_path, keep_checkpoints=1, metrics_dir="")
    train(tiny_cfg(num_epochs=3, **common))
    assert committed_epochs(common["ckpt_dir"]) == [3]
    assert os.path.isdir(torn)


# --- ControlPlane default exit deadline (satellite 1) ------------------------

def test_arm_exit_deadline_default_bounded():
    exits = []
    plane = ControlPlane(process_index=0, process_count=2,
                         collective=lambda w: w,
                         hard_exit=lambda code: exits.append(code))
    plane.arm_exit_deadline(deadline_s=0.05)
    first = plane._exit_timer
    assert first is not None
    plane.arm_exit_deadline(deadline_s=99.0)  # idempotent: first timer wins
    assert plane._exit_timer is first
    deadline = time.monotonic() + 5.0
    while not exits and time.monotonic() < deadline:
        time.sleep(0.01)
    assert exits == [EXIT_HANG]


def test_arm_exit_deadline_prefers_running_watchdog():
    class FakeWatchdog:
        running = True
        armed = 0

        def arm_exit_deadline(self):
            self.armed += 1

    wd = FakeWatchdog()
    plane = ControlPlane(process_index=0, process_count=2,
                         watchdog=wd, collective=lambda w: w,
                         hard_exit=lambda code: pytest.fail("own timer used"))
    plane.arm_exit_deadline()
    assert wd.armed == 1 and plane._exit_timer is None


def test_arm_exit_deadline_noop_and_cancel():
    exits = []
    # single host: nothing to wait on, no timer
    solo = ControlPlane(process_index=0, process_count=1,
                        hard_exit=lambda code: exits.append(code))
    solo.arm_exit_deadline(deadline_s=0.01)
    assert solo._exit_timer is None
    # stop() cancels an armed timer before it fires
    plane = ControlPlane(process_index=0, process_count=2,
                         collective=lambda w: w,
                         hard_exit=lambda code: exits.append(code))
    plane.arm_exit_deadline(deadline_s=0.2)
    plane.stop()
    time.sleep(0.3)
    assert exits == []


# --- VTX108 lint rule (satellite 6) ------------------------------------------

def test_vtx108_flags_synchronous_save_in_loop():
    from vitax.analysis.ast_lint import lint_source
    src = (
        "def run(state):\n"
        "    for step in range(10):\n"
        "        save_state(d, 1, state, wait=True)\n"
    )
    findings = lint_source(src, "vitax/train/loop.py")
    assert [f.code for f in findings] == ["VTX108"]
    assert findings[0].severity == "ERROR" and findings[0].line == 3


def test_vtx108_escapes_and_non_matches():
    from vitax.analysis.ast_lint import lint_source
    clean = (
        "def run(state):\n"
        "    save_state(d, 1, state, wait=True)\n"       # not in a loop
        "    for step in range(10):\n"
        "        save_state(d, 1, state, wait=False)\n"  # async: fine
        "        save_state(d, 1, state, wait=w)\n"      # variable: fine
        "        orbax_io.save_state(d, 1, state, wait=True)"
        "  # vtx: ignore[VTX108] drill needs the stall\n"
    )
    assert lint_source(clean, "vitax/train/loop.py") == []
    # attribute-qualified calls in a while loop are still caught
    caught = (
        "def run(state):\n"
        "    while True:\n"
        "        orbax_io.save_state(d, 1, state, wait=True)\n"
    )
    assert [f.code for f in lint_source(caught, "x.py")] == ["VTX108"]


# --- metrics_report fields (satellite 4) -------------------------------------

def test_metrics_report_surfaces_ckpt_fields(tmp_path):
    path = tmp_path / "metrics.jsonl"
    records = [
        {"schema": 1, "step": 1, "loss": 2.0, "sec_per_iter": 0.1,
         "data_wait_s": 0.0, "ckpt_stall_s": 0.001},
        {"schema": 1, "step": 2, "loss": 1.9, "sec_per_iter": 0.1,
         "data_wait_s": 0.0, "ckpt_stall_s": 0.003},
        {"schema": 1, "kind": "peer_replication", "bytes": 1000,
         "version": [1, 2, 2], "src": 0, "buddy": 1},
        {"schema": 1, "kind": "peer_replication", "bytes": 2000,
         "version": [1, 4, 2], "src": 0, "buddy": 1},
        {"schema": 1, "kind": "restore", "path": "peer", "epoch": 1,
         "orbax_reads": 0},
        {"schema": 1, "kind": "control", "event": "peer_restore_failed",
         "version": [1, 4, 2], "error": "crc32 mismatch",
         "fallback_epoch": 1},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in records))
    r = subprocess.run(
        [sys.executable, os.path.join("tools", "metrics_report.py"),
         str(path), "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    summary = json.loads(r.stdout)
    assert summary["ckpt_stall_s_p50"] == pytest.approx(0.002)
    assert summary["ckpt_stall_s_p95"] == pytest.approx(0.0029, abs=1e-4)
    assert summary["peer_replication_bytes"] == 3000
    assert summary["peer_replication_windows"] == 2
    assert summary["peer_restores"] == 1
    assert summary["restore_path"] == "peer"
    assert summary["control_events"]["peer_restore_failures"] == 1

    human = subprocess.run(
        [sys.executable, os.path.join("tools", "metrics_report.py"),
         str(path)],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert human.returncode == 0
    assert "ckpt stall: p50" in human.stdout
    assert "peer replication: 2 window(s)" in human.stdout
    assert "restore path: peer (1 peer restore(s))" in human.stdout
    assert "peer restores that fell back to Orbax: 1" in human.stdout


# --- supervisor peer-aware progress frontier ---------------------------------

def test_supervisor_counts_peer_progress(tmp_path):
    from vitax.supervise import peer_store_root, run_progress
    root = tmp_path / "peers"
    host = root / "p0" / "host_0"
    os.makedirs(host)
    with open(host / "meta.json", "w") as f:
        json.dump({"version": [3, 5, 2], "src": 0}, f)
    ckpt = tmp_path / "ckpt"  # no Orbax commits at all
    assert run_progress(str(ckpt)) == (0, 0)
    assert run_progress(str(ckpt), str(root)) == (3, 5)

    # gating: the root only resolves for commands that replicate
    child = ["run.py", "--replicate_steps", "2", "--peer_dir", str(root)]
    assert peer_store_root(child, str(ckpt)) == str(root)
    assert peer_store_root(["run.py"], str(ckpt)) == ""
    assert peer_store_root(["run.py", "--replicate_steps", "0"],
                           str(ckpt)) == ""
    assert peer_store_root(["run.py", "--replicate_steps=2"],
                           str(ckpt)).endswith("peerstore")


def test_run_progress_normalizes_boundary_saves(tmp_path):
    """A peer BOUNDARY version (e, 0) means epoch e is COMPLETE: it must
    outrank a stale mid-epoch Orbax frontier (e, s) — both sides of the
    crash-loop progress check compare in progress_key space."""
    from vitax.supervise import run_progress
    ckpt = tmp_path / "ckpt"
    _fake_committed(ckpt, 3)
    with open(epoch_ckpt_path(str(ckpt), 3) + ".resume.json", "w") as f:
        json.dump({"step_in_epoch": 5}, f)  # mid-epoch-3 Orbax frontier
    root = tmp_path / "peers"
    host = root / "p1" / "host_1"
    os.makedirs(host)
    with open(host / "meta.json", "w") as f:
        json.dump({"version": [3, 0, 2], "src": 1}, f)  # epoch 3 COMPLETE

    assert peer.store_frontier(str(root)) == (4, 0)
    assert run_progress(str(ckpt)) == (3, 5)
    # the epoch-completing peer version wins over the mid-epoch frontier
    assert run_progress(str(ckpt), str(root)) == (4, 0)
    # an empty store still reads as no progress, not as (1, 0)
    assert run_progress(str(tmp_path / "none"), str(tmp_path / "no_peers")) \
        == (0, 0)


# --- loop integration --------------------------------------------------------

def test_loop_zero_stall_pin_and_peer_resume(devices8, tmp_path):
    """The in-loop acceptance pins: (a) every step record carries a
    ckpt_stall_s under the stall budget even with per-epoch saves and
    2-step replication windows; (b) a fresh auto-resume prefers the peer
    store and touches shared storage ZERO times (the counter seam)."""
    from vitax.train.loop import train
    common = _loop_common(tmp_path, zero_stall_ckpt=True, replicate_steps=2)
    state = train(tiny_cfg(num_epochs=2, **common))
    assert int(jax.device_get(state.step)) == 8

    steps, events = _read_metrics(tmp_path)
    assert len(steps) == 8
    # per-step: <5% of step time with an absolute floor (tiny CPU steps are
    # dominated by scheduler jitter, not the staging copy); the central pin
    # is tight — a synchronous Orbax write leaking onto the loop thread
    # costs hundreds of ms and fails both
    stalls = sorted(r["ckpt_stall_s"] for r in steps)
    for r in steps:
        budget = max(0.05 * r["sec_per_iter"], 0.1)
        assert r["ckpt_stall_s"] <= budget, (
            f"step {r['step']}: stall {r['ckpt_stall_s']:.4f}s over "
            f"{budget:.4f}s budget")
    assert stalls[len(stalls) // 2] <= 0.02
    repl = [e for e in events if e["kind"] == "peer_replication"]
    # 2 epochs x 2 in-loop windows, plus the 2 boundary saves mirror too
    assert len(repl) >= 4
    assert all(e["bytes"] > 0 for e in repl)
    assert os.path.isdir(os.path.join(common["ckpt_dir"], "peerstore", "p0"))

    # resume: the peer store's frontier matches the final boundary save, so
    # the restore comes from the LOCAL store — zero Orbax reads
    state2 = train(tiny_cfg(num_epochs=2, resume_epoch=-1, **common))
    assert int(jax.device_get(state2.step)) == 8
    _leaves_equal(state.params, state2.params)
    _, events2 = _read_metrics(tmp_path)
    restores = [e for e in events2 if e["kind"] == "restore"]
    assert restores and restores[-1]["path"] == "peer"
    assert restores[-1]["orbax_reads"] == 0


def test_loop_checksum_fallback_completes(devices8, tmp_path):
    """Satellite 3, integration half: resume with a CORRUPTED peer store
    must fall back to the last committed Orbax epoch, emit the control
    event, and still complete the run."""
    import glob

    from vitax.train.loop import train
    common = _loop_common(tmp_path, zero_stall_ckpt=True, replicate_steps=2)
    train(tiny_cfg(num_epochs=1, **common))

    for blob in glob.glob(os.path.join(common["ckpt_dir"], "peerstore",
                                       "p*", "host_*", "shard.npz")):
        raw = bytearray(open(blob, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        with open(blob, "wb") as f:
            f.write(bytes(raw))

    state = train(tiny_cfg(num_epochs=2, resume_epoch=-1, **common))
    assert int(jax.device_get(state.step)) == 8  # epoch 2 ran to completion
    _, events = _read_metrics(tmp_path)
    failed = [e for e in events if e.get("kind") == "control"
              and e.get("event") == "peer_restore_failed"]
    assert failed, "checksum failure must surface as a control event"
    restores = [e for e in events if e.get("kind") == "restore"]
    assert restores and restores[-1]["path"] == "orbax"
    assert restores[-1]["epoch"] == 1


# --- the acceptance drill: kill a host, resume from peers, bitwise ----------

def _consolidated(ckpt_dir, epoch, out):
    """Host-side full-param export of a committed epoch (runs in THIS
    process — single host, no mesh: consolidate host-restores the shards)."""
    from vitax.checkpoint.consolidate import consolidate
    consolidate(str(ckpt_dir), epoch, str(out), params_only=True)
    return {k: v for k, v in np.load(str(out)).items()}


def _drill_argv(ckpt_dir, peers, metrics_dir):
    return _tiny_train_argv(12, ckpt_dir) + [
        "--zero_stall_ckpt", "--replicate_steps", "2",
        "--peer_dir", str(peers), "--metrics_dir", str(metrics_dir)]


@pytest.mark.slow
def test_two_process_kill_and_peer_restore_bitwise(tmp_path):
    """The PR's acceptance drill. Baseline: an uninterrupted 2-process run.
    Drill: the same run with host 1 SIGKILLed right after dispatching step 5
    (both hosts mirrored the step-4 window; host 0 then wedges in step 5/6's
    collective and the liveness monitor exits it 42, well before any Orbax
    commit), host 1's LOCAL store deleted (the lost machine's scratch is
    gone), then a 2-process relaunch that must restore host 1's shard from
    host 0's surviving replica — ZERO shared-storage checkpoint reads (no
    committed Orbax dir even exists) — and finish the epoch with final
    parameters BITWISE equal to the baseline's."""
    # baseline ---------------------------------------------------------------
    port = _free_port()
    base_ckpt = tmp_path / "base_ckpt"
    base_argv = _drill_argv(base_ckpt, tmp_path / "base_peers",
                            tmp_path / "base_metrics")
    procs, logs = _spawn_two(base_argv, port, tmp_path, prefix="base")
    _wait_all(procs, logs)
    base_params = _consolidated(base_ckpt, 1, tmp_path / "base.npz")

    # interrupted run --------------------------------------------------------
    port = _free_port()
    ckpt = tmp_path / "ckpt"
    peers = tmp_path / "peers"
    argv = _drill_argv(ckpt, peers, tmp_path / "metrics") + [
        "--fault_plan",
        '[{"site": "step", "action": "peer_loss", "at": 5, "process": 1}]',
        "--peer_heartbeat_s", "0.5", "--peer_grace_s", "5.0"]
    env = {"VITAX_PEER_POLL_S": "0.05"}
    procs, logs = _spawn_two(argv, port, tmp_path, extra_env=env,
                             prefix="drill")
    try:
        procs[1].wait(timeout=540)
        assert procs[1].returncode == -signal.SIGKILL, \
            logs[1].read_text()[-3000:]
        procs[0].wait(timeout=120)  # bounded by liveness grace + deadline
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    out0 = logs[0].read_text()
    assert procs[0].returncode == EXIT_HANG == 42, out0[-3000:]
    assert "peer 1 lost" in out0, out0[-3000:]
    # no Orbax COMMIT ever happened — the run died mid-epoch (a torn
    # emergency-save dir without the commit marker is fine)
    assert committed_epochs(str(ckpt)) == []
    # host 0's store holds BOTH shards of the step-4 window: its own spill
    # plus the replica it received as host 1's ring guard
    holdings = peer.PeerStore(str(peers / "p0")).holdings()
    assert tuple(holdings[0]["version"]) == (1, 4, 2), holdings
    assert tuple(holdings[1]["version"]) == (1, 4, 2), holdings

    # the lost host's scratch dies with it
    import shutil
    shutil.rmtree(peers / "p1")

    # relaunch: same topology, no fault plan ---------------------------------
    port = _free_port()
    resume_argv = _drill_argv(ckpt, peers, tmp_path / "metrics2") + [
        "--resume_epoch", "-1"]
    procs, logs = _spawn_two(resume_argv, port, tmp_path, prefix="resume")
    _wait_all(procs, logs)

    steps, events = [], []
    with open(tmp_path / "metrics2" / "metrics.jsonl") as f:
        for line in f:
            rec = json.loads(line)
            (events if rec.get("kind") else steps).append(rec)
    restores = [e for e in events if e["kind"] == "restore"]
    assert restores and restores[-1]["path"] == "peer", restores
    assert restores[-1]["orbax_reads"] == 0  # the counter seam: ZERO reads
    assert restores[-1]["resume_step"] == 4
    # only steps 5..12 re-ran
    assert [r["step_in_epoch"] for r in steps
            if "loss" in r] == list(range(5, 13))

    drill_params = _consolidated(ckpt, 1, tmp_path / "drill.npz")
    assert set(drill_params) == set(base_params)
    for key in base_params:
        assert np.array_equal(base_params[key], drill_params[key]), (
            f"{key}: peer-restored run diverged from the baseline")


def _spawn_two(argv, port, tmp_path, extra_env=None, prefix="rank"):
    logs = [tmp_path / f"{prefix}{i}.log" for i in range(2)]
    procs = []
    for pid in range(2):
        env = _two_proc_env(port, pid)
        env.update(extra_env or {})
        with open(logs[pid], "w") as log_f:
            procs.append(subprocess.Popen(
                argv, cwd=REPO, env=env, stdout=log_f,
                stderr=subprocess.STDOUT, text=True))
    return procs, logs


def _wait_all(procs, logs, timeout=540):
    try:
        for p in procs:
            p.wait(timeout=timeout)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for pid, (p, lg) in enumerate(zip(procs, logs)):
        assert p.returncode == 0, (
            f"process {pid} failed:\n{lg.read_text()[-3000:]}")
