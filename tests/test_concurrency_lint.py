"""vitax.analysis.concurrency: VTX200-series thread-safety lint + the
vitax.telemetry.threads crash/join primitives + thread-fuzz stress.

Every rule gets one fixture that fires and one that stays silent; the
firing fixtures double as the "deliberately-broken negative arms" of the
CI pin — un-suppressed they fail, suppressed with a reason they pass.
The stress tests pin DynamicBatcher and SnapshotPipeline end-to-end
under forced GIL churn (sys.setswitchinterval(1e-5)) with barrier-started
submitters: every future resolves and every save lands exactly once.
"""

import os
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from vitax.analysis import concurrency
from vitax.serve.batcher import DynamicBatcher
from vitax.telemetry import threads as vthreads

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(src):
    return concurrency.lint_source(textwrap.dedent(src), "fixture.py")


def codes(findings):
    return sorted({f.code for f in findings})


# --- VTX200: unguarded shared attribute --------------------------------------

VTX200_FIRING = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0
            self._t = threading.Thread(target=self._run)
            self._t.start()

        def _run(self):
            self._count += 1

        def read(self):
            return self._count

        def stop(self):
            self._t.join(timeout=1.0)
"""


def test_vtx200_fires_on_unguarded_shared_attr():
    findings = lint(VTX200_FIRING)
    assert codes(findings) == ["VTX200"]
    assert "_count" in findings[0].message


def test_vtx200_silent_when_both_sides_hold_the_lock():
    findings = lint("""
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                with self._lock:
                    self._count += 1

            def read(self):
                with self._lock:
                    return self._count

            def stop(self):
                self._t.join(timeout=1.0)
    """)
    assert findings == []


def test_vtx200_silent_for_init_only_writes():
    # config attrs written once in __init__ and read everywhere are the
    # happens-before-publish pattern, not a race
    findings = lint("""
        import threading

        class Reader:
            def __init__(self):
                self.limit = 7
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                return self.limit

            def stop(self):
                self._t.join(timeout=1.0)
    """)
    assert findings == []


def test_vtx200_guard_context_propagates_through_calls():
    # the helper never takes the lock itself — every call site does; the
    # call-context fixpoint must see that and stay silent
    findings = lint("""
        import threading

        class Ctx:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _bump(self):
                self._n += 1

            def _run(self):
                with self._lock:
                    self._bump()

            def bump(self):
                with self._lock:
                    self._bump()

            def stop(self):
                self._t.join(timeout=1.0)
    """)
    assert findings == []


# --- VTX201: Condition.wait outside a while loop -----------------------------

VTX201_FIRING = """
    import threading

    class Waiter:
        def __init__(self):
            self._cond = threading.Condition()
            self._ready = False

        def get(self):
            with self._cond:
                if not self._ready:
                    self._cond.wait()
                return self._ready
"""


def test_vtx201_fires_on_if_guarded_wait():
    findings = lint(VTX201_FIRING)
    assert codes(findings) == ["VTX201"]


def test_vtx201_silent_inside_while():
    findings = lint("""
        import threading

        class Waiter:
            def __init__(self):
                self._cond = threading.Condition()
                self._ready = False

            def get(self):
                with self._cond:
                    while not self._ready:
                        self._cond.wait(timeout=1.0)
                    return self._ready
    """)
    assert findings == []


# --- VTX202: lock-order cycle ------------------------------------------------

VTX202_FIRING = """
    import threading

    class TwoLocks:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def one(self):
            with self._a:
                with self._b:
                    pass

        def two(self):
            with self._b:
                with self._a:
                    pass
"""


def test_vtx202_fires_on_opposite_order():
    findings = lint(VTX202_FIRING)
    assert codes(findings) == ["VTX202"]


def test_vtx202_fires_transitively_through_a_helper():
    findings = lint("""
        import threading

        class TwoLocks:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def _take_a(self):
                with self._a:
                    pass

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    self._take_a()
    """)
    assert codes(findings) == ["VTX202"]


def test_vtx202_silent_on_consistent_order():
    findings = lint("""
        import threading

        class TwoLocks:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
    """)
    assert findings == []


# --- VTX203: blocking call while holding a lock ------------------------------

VTX203_FIRING = """
    import threading

    class Joiner:
        def __init__(self):
            self._lock = threading.Lock()
            self._t = threading.Thread(target=self._run)
            self._t.start()

        def _run(self):
            pass

        def stop(self):
            with self._lock:
                self._t.join()
"""


def test_vtx203_fires_on_join_under_lock():
    findings = lint(VTX203_FIRING)
    assert codes(findings) == ["VTX203"]


def test_vtx203_fires_on_blocking_queue_get_under_lock():
    findings = lint("""
        import queue
        import threading

        class Drainer:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def drain_one(self):
                with self._lock:
                    return self._q.get()
    """)
    assert codes(findings) == ["VTX203"]


def test_vtx203_silent_with_timeout_or_without_lock():
    findings = lint("""
        import queue
        import threading

        class Joiner:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                pass

            def drain_one(self):
                with self._lock:
                    return self._q.get(timeout=1.0)

            def stop(self):
                self._t.join(timeout=1.0)
    """)
    assert findings == []


# --- VTX204: JAX dispatch on a thread path -----------------------------------

VTX204_FIRING = """
    import threading
    import jax

    class Dispatcher:
        def __init__(self):
            self._t = threading.Thread(target=self._run)
            self._t.start()

        def _run(self):
            jax.device_put(1)

        def stop(self):
            self._t.join(timeout=1.0)
"""


def test_vtx204_fires_on_thread_side_jax():
    findings = lint(VTX204_FIRING)
    assert codes(findings) == ["VTX204"]
    assert "jax.device_put" in findings[0].message


def test_vtx204_silent_for_caller_side_jax():
    findings = lint("""
        import threading
        import jax

        class Dispatcher:
            def __init__(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                pass

            def predict(self, x):
                return jax.device_put(x)

            def stop(self):
                self._t.join(timeout=1.0)
    """)
    assert findings == []


# --- VTX205: leaked thread ---------------------------------------------------

VTX205_FIRING = """
    import threading

    class Leaker:
        def start(self):
            self._t = threading.Thread(target=self._run)
            self._t.start()

        def _run(self):
            pass
"""


def test_vtx205_fires_on_never_joined_attr_thread():
    findings = lint(VTX205_FIRING)
    assert codes(findings) == ["VTX205"]


def test_vtx205_fires_on_local_and_anonymous_threads():
    findings = lint("""
        import threading

        def fire_and_forget(fn):
            t = threading.Thread(target=fn)
            t.start()
    """)
    assert codes(findings) == ["VTX205"]
    findings = lint("""
        import threading

        def fire_and_forget(fn):
            threading.Thread(target=fn).start()
    """)
    assert codes(findings) == ["VTX205"]


def test_vtx205_silent_with_join_or_stop_event():
    findings = lint("""
        import threading

        class Joined:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                pass

            def close(self):
                self._t.join(timeout=1.0)
    """)
    assert findings == []
    findings = lint("""
        import threading

        class Evented:
            def start(self):
                self._stop = threading.Event()
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                while not self._stop.wait(0.1):
                    pass

            def shutdown(self):
                self._stop.set()
    """)
    assert findings == []
    # a joined local thread in a module function is fine too
    findings = lint("""
        import threading

        def run_and_wait(fn):
            t = threading.Thread(target=fn)
            t.start()
            t.join(timeout=5.0)
    """)
    assert findings == []


# --- suppression machinery ---------------------------------------------------

def test_suppression_with_reason_silences_and_wrong_code_does_not():
    src = VTX200_FIRING.replace(
        "self._count += 1",
        "self._count += 1  # vtx: ignore[VTX200] fixture: benign test race")
    assert lint(src) == []
    wrong = VTX200_FIRING.replace(
        "self._count += 1",
        "self._count += 1  # vtx: ignore[VTX205] wrong code, still fires")
    assert codes(lint(wrong)) == ["VTX200"]


def test_every_firing_fixture_fails_unsuppressed():
    # the acceptance contract: each deliberately-broken arm fails CI until
    # it carries a reasoned suppression on the reported line
    for src, code in [(VTX200_FIRING, "VTX200"), (VTX201_FIRING, "VTX201"),
                      (VTX202_FIRING, "VTX202"), (VTX203_FIRING, "VTX203"),
                      (VTX204_FIRING, "VTX204"), (VTX205_FIRING, "VTX205")]:
        findings = lint(src)
        assert codes(findings) == [code]
        lines = textwrap.dedent(src).splitlines()
        lines[findings[0].line - 1] += (
            f"  # vtx: ignore[{code}] fixture: deliberately broken")
        assert concurrency.lint_source("\n".join(lines), "fixture.py") == []


def test_bare_suppressions_are_not_reported_here():
    # VTX100 policing belongs to ast_lint (which runs first in lint.sh);
    # the concurrency pass must not double-report it
    findings = lint("""
        x = 1  # vtx: ignore[]
    """)
    assert findings == []


# --- repo pin ----------------------------------------------------------------

def test_repo_and_tools_are_clean():
    findings = concurrency.lint_paths([os.path.join(REPO, "vitax"),
                                       os.path.join(REPO, "tools")])
    assert [f.format() for f in findings] == []


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(VTX205_FIRING), encoding="utf-8")
    assert concurrency.main([str(bad)]) == 1
    assert concurrency.main([str(bad), "--json"]) == 1
    good = tmp_path / "good.py"
    good.write_text("x = 1\n", encoding="utf-8")
    assert concurrency.main([str(good)]) == 0


# --- telemetry.threads: excepthook + bounded joins ---------------------------

class _Recorder:
    def __init__(self):
        self.events = []

    def event(self, kind, **payload):
        self.events.append((kind, payload))


def test_thread_excepthook_records_crash(capfd):
    rec = _Recorder()
    vthreads.install_thread_excepthook(rec, rank=3)
    before = vthreads.thread_crash_count()
    t = threading.Thread(target=lambda: 1 / 0, name="crasher")
    t.start()
    t.join(timeout=5.0)
    assert vthreads.thread_crash_count() == before + 1
    assert rec.events and rec.events[-1][0] == "thread_crash"
    payload = rec.events[-1][1]
    assert payload["rank"] == 3 and payload["thread"] == "crasher"
    assert "ZeroDivisionError" in payload["error"]
    err = capfd.readouterr().err
    assert "rank 3" in err and "crasher" in err and "ZeroDivisionError" in err


def test_thread_excepthook_ignores_system_exit(capfd):
    vthreads.install_thread_excepthook(None, rank=0)
    before = vthreads.thread_crash_count()
    t = threading.Thread(target=lambda: sys.exit(1))
    t.start()
    t.join(timeout=5.0)
    assert vthreads.thread_crash_count() == before
    assert "uncaught exception" not in capfd.readouterr().err


def test_join_or_warn_bounds_a_wedged_join(capfd):
    release = threading.Event()
    t = threading.Thread(target=release.wait, name="wedged")
    t.start()
    try:
        assert vthreads.join_or_warn(t, timeout=0.05) is False
        err = capfd.readouterr().err
        assert "wedged" in err and "still alive" in err
    finally:
        release.set()
        t.join(timeout=5.0)
    assert vthreads.join_or_warn(t, timeout=1.0) is True


# --- thread-fuzz stress ------------------------------------------------------

@pytest.fixture
def gil_churn():
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)
    yield
    sys.setswitchinterval(old)


def test_batcher_exactly_once_under_contention(gil_churn):
    def predict(images):
        n = len(images)
        time.sleep(0.0005)  # widen the flush window the races live in
        return (np.tile(np.arange(3, dtype=np.int32), (n, 1)),
                np.ones((n, 3), np.float32))

    batcher = DynamicBatcher(predict, max_batch=4, max_wait_ms=1.0)
    n_threads, per_thread = 8, 25
    barrier = threading.Barrier(n_threads)
    futures = [[] for _ in range(n_threads)]

    def submitter(i):
        barrier.wait()
        for _ in range(per_thread):
            futures[i].append(batcher.submit(np.zeros((2, 2, 3), np.float32)))

    workers = [threading.Thread(target=submitter, args=(i,))
               for i in range(n_threads)]
    for t in workers:
        t.start()
    for t in workers:
        t.join(timeout=30.0)
    flat = [f for per in futures for f in per]
    assert len(flat) == n_threads * per_thread
    # exactly once: every future resolves (a double set_result would crash
    # the worker with InvalidStateError and strand the rest on timeout)
    results = [f.result(timeout=30.0) for f in flat]
    assert all(1 <= r.batch_size <= 4 for r in results)
    batcher.close()
    assert not batcher._worker.is_alive()


def test_snapshot_pipeline_exactly_once_under_contention(
        gil_churn, tmp_path, monkeypatch):
    jax = pytest.importorskip("jax")
    from vitax.checkpoint import snapshot as snap_mod
    import vitax.checkpoint.orbax_io as orbax_io_mod

    lock = threading.Lock()
    saved = []

    def fake_save(ckpt_dir, epoch, tree, **kw):
        with lock:
            saved.append(int(epoch))

    monkeypatch.setattr(orbax_io_mod, "save_state", fake_save)
    state = {"w": jax.device_put(np.arange(8, dtype=np.float32))}
    pipe = snap_mod.SnapshotPipeline(max_buffer_sets=2)
    n_threads, per_thread = 4, 6
    barrier = threading.Barrier(n_threads)
    errors = []

    def submitter(i):
        barrier.wait()
        for j in range(per_thread):
            try:
                pipe.submit(state, epoch=i * 100 + j,
                            persist_to=str(tmp_path))
            except Exception as e:  # noqa: BLE001 — collected and asserted
                with lock:
                    errors.append(e)

    workers = [threading.Thread(target=submitter, args=(i,))
               for i in range(n_threads)]
    for t in workers:
        t.start()
    for t in workers:
        t.join(timeout=60.0)
    pipe.drain()
    pipe.close()
    assert errors == []
    expected = sorted(i * 100 + j for i in range(n_threads)
                      for j in range(per_thread))
    assert sorted(saved) == expected  # every save exactly once, none lost
