"""True multi-process distributed training test: 2 processes x 4 CPU devices.

Exercises the control plane nothing else touches — jax.distributed.initialize
via the explicit env bring-up (vitax/distributed.py:maybe_initialize), the
named barriers, per-process data sharding (ShardedSampler with
process_count=2), global-batch assembly via make_array_from_process_local_data,
and cross-process Gloo collectives inside the compiled step. This is the
multi-host capability the reference gets from xla_dist + the XRT mesh service
(reference README.md:99-101; SURVEY.md section 2.4), validated without TPUs.
"""

import os
import re
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]



def _tiny_train_argv(steps_per_epoch, ckpt_dir, num_blocks=2):
    return [sys.executable, "run_vit_training.py", "--fake_data",
            "--image_size", "32", "--patch_size", "8", "--embed_dim", "32",
            "--num_heads", "2", "--num_blocks", str(num_blocks),
            "--num_classes", "4",
            "--batch_size", "16", "--dtype", "float32", "--num_epochs", "1",
            "--steps_per_epoch", str(steps_per_epoch),
            "--log_step_interval", "1", "--warmup_steps", "0",
            "--eval_max_batches", "1", "--test_epoch_interval", "99",
            "--ckpt_epoch_interval", "99", "--ckpt_dir", str(ckpt_dir)]


def _run_two_procs(argv, port, timeout=600):
    """Spawn the same argv as 2 coordinated processes; return their merged
    stdout+stderr logs after asserting both exited 0 (kills orphans on
    timeout/assert — e.g. a wedged barrier)."""
    procs = []
    for pid in range(2):
        procs.append(subprocess.Popen(
            argv, cwd=REPO, env=_two_proc_env(port, pid),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"process {pid} failed:\n{out[-3000:]}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    return outs


def _two_proc_env(port, pid):
    return dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        JAX_COORDINATOR_ADDRESS=f"localhost:{port}",
        JAX_NUM_PROCESSES="2",
        JAX_PROCESS_ID=str(pid),
    )


@pytest.mark.slow
def test_two_process_training(tmp_path):
    port = _free_port()
    outs = _run_two_procs(_tiny_train_argv(3, tmp_path / "ckpt"), port)

    # rank 0 logs; the loop must have seen 2 processes and 8 global devices
    log = outs[0]
    assert "(2 host(s))" in log, log[-2000:]
    assert "over 8 devices" in log, log[-2000:]
    assert "training completed" in log
    # rank 1 stays quiet (master_print) but must also complete
    assert "training completed" not in outs[1]

    # the logged loss is the global-batch mean reduced across processes —
    # grab the last step's loss and check it is finite
    losses = re.findall(r"loss: ([0-9.]+)", log)
    assert losses, log[-2000:]
    assert all(float(x) > 0 for x in losses)


@pytest.mark.slow
def test_two_process_preemption_agreement(tmp_path):
    """SIGTERM delivered to ONLY rank 1 must stop BOTH processes at an agreed
    step with a committed preemption checkpoint — the collective flag sync in
    vitax/train/control.py (ControlPlane.poll). Without agreement, rank 1 entering
    the save while rank 0 keeps stepping would deadlock the pod."""
    import signal
    import time

    port = _free_port()
    logs = [tmp_path / f"rank{i}.log" for i in range(2)]
    procs = []
    for pid in range(2):
        with open(logs[pid], "w") as log_f:  # child holds its own dup'd fd
            procs.append(subprocess.Popen(
                _tiny_train_argv(2000, tmp_path / "ckpt"),
                cwd=REPO, env=_two_proc_env(port, pid), stdout=log_f,
                stderr=subprocess.STDOUT, text=True))
    try:
        # wait until rank 0 logs a training step, then SIGTERM rank 1 ONLY
        deadline = time.time() + 540
        while time.time() < deadline:
            if "step 1," in logs[0].read_text():
                break
            if any(p.poll() is not None for p in procs):
                raise AssertionError(
                    f"a process died early:\n{logs[0].read_text()[-2000:]}\n"
                    f"{logs[1].read_text()[-2000:]}")
            time.sleep(1)
        else:
            raise AssertionError("rank 0 never reached step 1: "
                                 + logs[0].read_text()[-2000:])
        procs[1].send_signal(signal.SIGTERM)
        for p in procs:
            p.wait(timeout=300)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()

    out0 = logs[0].read_text()
    assert procs[0].returncode == 0, out0[-3000:]
    assert procs[1].returncode == 0, logs[1].read_text()[-3000:]
    # rank 0 never saw the signal locally, yet announces the agreed stop
    assert "SIGTERM received: saving preemption checkpoint" in out0, out0[-3000:]
    assert (tmp_path / "ckpt" / "epoch_1").is_dir()
    assert "training completed" in out0  # clean exit path, not a crash


@pytest.mark.slow
def test_two_process_pipeline_training(tmp_path):
    """GPipe under a MULTI-HOST mesh: dp2 x fsdp2 x pp2 on 2 processes x 4
    devices. By construction pp's mesh stride is 1 (it is the second-to-last
    axis), so stage hops ride intra-process links — the deliberate topology
    placement (vitax/parallel/pipeline.py: stage hops belong on the closest
    links) — while the dp gradient reduction crosses the Gloo transport
    AROUND the pipeline's shard_map. That composition (multi-host data
    parallelism over a pipelined step program) is what single-process pp
    tests cannot cover. Logged losses must match a single-process run of
    the SAME global config."""
    port = _free_port()
    argv = _tiny_train_argv(3, tmp_path / "ckpt", num_blocks=4) + [
        "--dp_size", "2", "--fsdp_size", "2", "--pp_size", "2"]
    outs = _run_two_procs(argv, port)

    log = outs[0]
    assert "'pp': 2" in log and "(2 host(s))" in log, log[-2000:]
    assert "training completed" in log
    losses_2p = [float(x) for x in re.findall(r"loss: ([0-9.]+)", log)]
    assert losses_2p and all(x > 0 for x in losses_2p)

    # single-process reference: same global mesh on 8 local devices
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    ref = subprocess.run(
        _tiny_train_argv(3, tmp_path / "ckpt_ref", num_blocks=4) + [
            "--dp_size", "2", "--fsdp_size", "2", "--pp_size", "2"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=600)
    assert ref.returncode == 0, ref.stdout[-3000:]
    losses_1p = [float(x) for x in re.findall(r"loss: ([0-9.]+)", ref.stdout)]
    assert len(losses_1p) == len(losses_2p)
    for a, b in zip(losses_2p, losses_1p):
        assert abs(a - b) < 2e-4 * max(abs(b), 1.0), (losses_2p, losses_1p)
