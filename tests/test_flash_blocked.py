"""Blocked (streaming) flash attention vs the dense reference core.

Runs in Pallas interpret mode on the CPU test mesh; covers non-divisible
sequence lengths (padding + masking path) and all three gradients through the
custom VJP. Long-sequence capability beyond the reference (SURVEY.md section 5:
the reference's sequence length is fixed at 256 tokens, dense O(N^2) timm
attention)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vitax.ops.attention import reference_attention
from vitax.ops.flash_blocked import blocked_flash_attention


@pytest.mark.parametrize("b,n,h,dh,blk", [
    (2, 256, 4, 64, 128),    # multiple blocks, divisible
    (1, 300, 2, 64, 128),    # padding: 300 -> 384
    (1, 1024, 2, 128, 512),  # larger head dim
    (1, 130, 1, 64, 256),    # N smaller than the block
])
def test_blocked_fwd_matches_reference(devices8, b, n, h, dh, blk):
    _check_fwd(b, n, h, dh, blk, blk)


def test_blocked_unequal_blocks(devices8):
    # unequal block_q/block_k must pad to their lcm so both grids tile evenly
    _check_fwd(1, 500, 2, 64, 512, 384)


def _check_fwd(b, n, h, dh, bq, bk):
    rng = np.random.default_rng(n)
    q, k, v = (jnp.asarray(rng.normal(size=(b, n, h, dh)), jnp.float32)
               for _ in range(3))
    ref = reference_attention(q, k, v)
    out = blocked_flash_attention(q, k, v, block_q=bq, block_k=bk)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("n,blk", [(256, 128), (300, 128)])
def test_blocked_grads_match_reference(devices8, n, blk):
    rng = np.random.default_rng(n)
    q, k, v = (jnp.asarray(rng.normal(size=(1, n, 2, 64)), jnp.float32)
               for _ in range(3))

    def loss(attn):
        return lambda q, k, v: (attn(q, k, v) ** 2).sum()

    got = jax.grad(loss(lambda q, k, v: blocked_flash_attention(
        q, k, v, block_q=blk, block_k=blk)), argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss(reference_attention), argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        scale = float(jnp.abs(w).max())
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   atol=3e-5 * scale, rtol=2e-4)


def test_blocked_bf16_activations(devices8):
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 256, 2, 64)), jnp.bfloat16)
               for _ in range(3))
    out = blocked_flash_attention(q, k, v, block_q=128, block_k=128)
    ref = reference_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=2e-2)


@pytest.mark.parametrize("n,bq,bk", [(256, 128, 128), (300, 128, 256)])
def test_blocked_dropout_matches_masked_dense(devices8, n, bq, bk):
    """Streaming in-kernel dropout (round 5) == dense attention with the
    identical global-coordinate mask, outputs AND grads, including a padded
    N and unequal blocks — the fwd's kv-streaming tiles and the two
    backward kernels' differently-shaped tiles must regenerate the same
    keep decisions."""
    from vitax.ops.attention import dropout_keep_mask
    from vitax.ops.flash_blocked import blocked_dropout_attention

    b, h, dh, rate = 1, 2, 64, 0.3
    rng = np.random.default_rng(7)
    q, k, v = (jnp.asarray(rng.normal(size=(b, n, h, dh)), jnp.float32)
               for _ in range(3))
    seed = jnp.uint32(99)

    def dense_masked(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * dh ** -0.5
        probs = jax.nn.softmax(s, axis=-1)
        mask = jnp.stack([jnp.stack([
            dropout_keep_mask(seed, jnp.uint32(bi * h + hi), n, n, rate)
            for hi in range(h)]) for bi in range(b)])
        return jnp.einsum("bhqk,bkhd->bqhd", probs * mask / (1 - rate), v)

    def stream(q, k, v):
        return blocked_dropout_attention(q, k, v, seed, rate,
                                         block_q=bq, block_k=bk)

    out_s = stream(q, k, v)
    out_d = dense_masked(q, k, v)
    assert not np.allclose(np.asarray(out_s),
                           np.asarray(reference_attention(q, k, v)),
                           atol=1e-3)  # the mask actually bit
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_d),
                               atol=2e-5, rtol=2e-5)
    # same (seed, inputs) -> identical output (determinism)
    np.testing.assert_array_equal(np.asarray(stream(q, k, v)),
                                  np.asarray(out_s))

    def loss(attn):
        return lambda q, k, v: (attn(q, k, v) ** 2).sum()

    got = jax.grad(loss(stream), argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss(dense_masked), argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        scale = float(jnp.abs(w).max())
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   atol=3e-5 * scale, rtol=2e-4)
