"""Test harness: force an 8-virtual-device CPU mesh before JAX initializes.

This is the multi-device test capability the reference lacks (SURVEY.md section 4):
sharding/collective behavior is validated on a faked 8-device host mesh, no TPUs
required.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
from vitax.platform import force_cpu_if_requested  # noqa: E402

force_cpu_if_requested()

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: subprocess entry-point smoke tests (~30s each)")


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs
