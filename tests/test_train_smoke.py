"""End-to-end training smoke tests on the 8-virtual-device CPU mesh:
loss decreases under FSDP, DP-vs-FSDP equivalence (the property the reference's
A/B flag implies but never asserts — SURVEY.md section 4), ZeRO-2 equivalence,
max_steps stop, and eval.
"""

import numpy as np
import pytest

import jax
import os

from vitax.config import Config
from vitax.models import build_model
from vitax.parallel.mesh import build_mesh
from vitax.train.state import build_optimizer, make_train_state
from vitax.train.step import make_eval_step, make_train_step


def tiny_cfg(**kw):
    base = dict(
        image_size=16, patch_size=8, embed_dim=32, num_heads=2, num_blocks=2,
        num_classes=4, batch_size=16, dtype="float32", lr=1e-3, warmup_steps=2,
        clip_grad_norm=1.0, seed=0,
    )
    base.update(kw)
    return Config(**base).validate()


def random_batch(cfg, mesh, seed=0):
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from vitax.parallel.mesh import batch_pspec
    rng = np.random.default_rng(seed)
    images = rng.normal(size=(cfg.batch_size, cfg.image_size, cfg.image_size, 3)).astype(np.float32)
    labels = (rng.integers(0, cfg.num_classes, size=(cfg.batch_size,))).astype(np.int32)
    sh = NamedSharding(mesh, batch_pspec())
    return {"image": jax.device_put(jnp.asarray(images), sh),
            "label": jax.device_put(jnp.asarray(labels), sh)}


def build_train_objects(cfg, max_iteration=100):
    """Build the full sharded training machinery exactly as the training loop
    does (attention impl + token sharding selection included)."""
    from vitax.ops.attention import make_attention_impl
    from vitax.train.loop import _token_sharding
    mesh = build_mesh(cfg)
    model = build_model(cfg, attention_impl=make_attention_impl(cfg, mesh),
                        token_sharding=_token_sharding(cfg, mesh))
    tx, _ = build_optimizer(cfg, max_iteration=max_iteration)
    state, sspecs, _ = make_train_state(cfg, model, tx, mesh, jax.random.key(cfg.seed))
    step_fn = make_train_step(cfg, model, tx, mesh, sspecs)
    eval_fn = make_eval_step(cfg, model, mesh, sspecs)
    return mesh, state, step_fn, eval_fn


def run_steps(cfg, n_steps=8, seed=0):
    mesh, state, step_fn, _ = build_train_objects(cfg)
    rng = jax.random.key(cfg.seed + 1)
    losses = []
    for i in range(n_steps):
        batch = random_batch(cfg, mesh, seed=seed + i % 2)  # two alternating batches
        state, metrics = step_fn(state, batch, rng)
        losses.append(float(jax.device_get(metrics["loss"])))
    return state, losses


def test_profile_trace_written(devices8, tmp_path):
    """--profile_dir captures a jax.profiler trace of steps 3-7 through the
    full loop (SURVEY.md section 5, tracing/profiling subsystem)."""
    import os
    from vitax.train.loop import train
    prof_dir = str(tmp_path / "trace")
    # the final-epoch save/eval clause still fires on num_epochs=1 — cap eval
    train(tiny_cfg(fake_data=True, num_epochs=1, steps_per_epoch=8,
                   profile_dir=prof_dir, log_step_interval=10,
                   ckpt_dir=str(tmp_path / "ckpt"), ckpt_epoch_interval=99,
                   test_epoch_interval=99, num_workers=2, eval_max_batches=1))
    found = [os.path.join(dp, f) for dp, _, fs in os.walk(prof_dir) for f in fs]
    assert any(f.endswith((".pb", ".json.gz", ".trace.json.gz")) for f in found), (
        f"no trace artifacts under {prof_dir}: {found}")


def test_fsdp_loss_decreases(devices8):
    _, losses = run_steps(tiny_cfg(), n_steps=10)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], f"loss did not fall: {losses}"


def test_dp_fsdp_zero2_equivalence(devices8):
    """Same seed -> same loss trajectory across DP, ZeRO-3 and ZeRO-2 paths.
    This is the correctness property of sharded training: sharding must not
    change the math."""
    _, fsdp = run_steps(tiny_cfg(), n_steps=5)
    _, dp = run_steps(tiny_cfg(run_without_fsdp=True), n_steps=5)
    _, zero2 = run_steps(tiny_cfg(reshard_after_forward=False), n_steps=5)
    np.testing.assert_allclose(fsdp, dp, rtol=2e-4)
    np.testing.assert_allclose(fsdp, zero2, rtol=2e-4)


def test_no_grad_ckpt_equivalence(devices8):
    _, with_ckpt = run_steps(tiny_cfg(grad_ckpt=True), n_steps=4)
    _, without = run_steps(tiny_cfg(grad_ckpt=False), n_steps=4)
    np.testing.assert_allclose(with_ckpt, without, rtol=2e-4)


def test_grad_clipping_applied(devices8):
    """With a tiny clip norm, the update magnitude must shrink accordingly."""
    # warmup_steps=0: lr would be 0 at step 0 otherwise (schedule parity) and
    # no update would happen at all
    cfg_free = tiny_cfg(clip_grad_norm=0.0, warmup_steps=0)   # 0 disables clipping (reference :269)
    cfg_clip = tiny_cfg(clip_grad_norm=1e-4, warmup_steps=0)
    mesh = build_mesh(cfg_free)
    model = build_model(cfg_free)

    def one_update_norm(cfg):
        tx, _ = build_optimizer(cfg, max_iteration=100)
        state, sspecs, _ = make_train_state(cfg, model, tx, mesh, jax.random.key(0))
        step_fn = make_train_step(cfg, model, tx, mesh, sspecs)
        batch = random_batch(cfg, mesh)
        # state is donated to step_fn — snapshot params to host first
        old_params = jax.tree.map(lambda x: np.asarray(x), state.params)
        new_state, metrics = step_fn(state, batch, jax.random.key(1))
        import optax
        delta = jax.tree.map(lambda a, b: np.asarray(a) - b, new_state.params, old_params)
        return float(jax.device_get(optax.global_norm(delta))), float(
            jax.device_get(metrics["grad_norm"]))

    free_delta, free_gn = one_update_norm(cfg_free)
    clip_delta, clip_gn = one_update_norm(cfg_clip)
    assert free_gn > 1e-3  # unclipped grad norm is substantial
    # grad_norm metric reports the pre-clip norm in both cases
    np.testing.assert_allclose(free_gn, clip_gn, rtol=1e-4)
    assert clip_delta < free_delta  # clipped update is smaller


def test_eval_step_counts_correct(devices8):
    cfg = tiny_cfg()
    mesh = build_mesh(cfg)
    model = build_model(cfg)
    tx, _ = build_optimizer(cfg, max_iteration=10)
    state, sspecs, _ = make_train_state(cfg, model, tx, mesh, jax.random.key(0))
    eval_fn = make_eval_step(cfg, model, mesh, sspecs)
    batch = random_batch(cfg, mesh)
    counts = jax.device_get(eval_fn(state, batch))
    correct = int(counts["correct"])
    correct5 = int(counts["correct_top5"])
    assert 0 <= correct <= cfg.batch_size
    # top-5 dominates top-1; with num_classes=4 < 5, k clamps to 4 and
    # every sample's label is in the top-4 by construction
    assert correct <= correct5 <= cfg.batch_size
    assert correct5 == cfg.batch_size


def test_full_loop_fake_data(devices8, tmp_path):
    """The whole train() orchestration: fake data, 1 epoch of 3 steps, ckpt
    save, eval — BASELINE.json config 1 shape."""
    from vitax.train.loop import train
    cfg = tiny_cfg(
        fake_data=True, num_epochs=1, steps_per_epoch=3, log_step_interval=1,
        ckpt_dir=str(tmp_path / "ckpt"), ckpt_epoch_interval=1,
        test_epoch_interval=1, num_workers=2, batch_size=16, eval_max_batches=4,
    )
    state = train(cfg)
    assert int(jax.device_get(state.step)) == 3
    import os
    assert os.path.isdir(os.path.join(str(tmp_path / "ckpt"), "epoch_1"))


def test_compile_cache_dir_populates(tmp_path):
    """--compile_cache_dir persists compiled step programs so restarts
    (launcher --restart, preemption resume) skip recompilation. Runs the
    REAL CLI in a subprocess: enabling the persistent cache mutates global
    jax.config and serializes executables, and doing that inside this
    process after ~200 suite tests aborted the interpreter twice (native
    crash in the cache write path with accumulated XLA state) — subprocess
    isolation matches how the flag is actually used (one cache per run)."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cache = tmp_path / "xla_cache"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="0")
    r = subprocess.run(
        [sys.executable, "run_vit_training.py", "--fake_data",
         "--image_size", "32", "--patch_size", "8", "--embed_dim", "32",
         "--num_heads", "4", "--num_blocks", "2", "--batch_size", "16",
         "--num_epochs", "1", "--steps_per_epoch", "2",
         "--log_step_interval", "1", "--test_epoch_interval", "10",
         "--num_workers", "1", "--ckpt_dir", str(tmp_path / "ckpt"),
         "--compile_cache_dir", str(cache)],
        cwd=repo, env=env, capture_output=True, text=True, timeout=1500)
    assert r.returncode == 0, r.stderr[-2000:]
    assert cache.is_dir() and os.listdir(cache), (
        "compile cache dir was never populated")


def test_sigterm_preemption_save(devices8, tmp_path):
    """SIGTERM mid-training -> committed checkpoint + clean exit + auto-resume
    (the preemption story the async checkpointer enables; vitax/train/preempt.py)."""
    import os
    import signal

    from vitax.train import preempt
    from vitax.train.loop import train

    preempt.reset()
    assert preempt.install()  # main thread in pytest
    # deliver a real SIGTERM; Python runs the handler at the next bytecode
    # boundary, so the flag is set before train() begins stepping
    os.kill(os.getpid(), signal.SIGTERM)
    try:
        cfg = tiny_cfg(
            fake_data=True, num_epochs=3, steps_per_epoch=50, log_step_interval=99,
            ckpt_dir=str(tmp_path / "ckpt"), ckpt_epoch_interval=99,
            test_epoch_interval=99, num_workers=2, eval_max_batches=1,
        )
        state = train(cfg)
        # exited after ONE step of epoch 1 (not 3 epochs x 50 steps)
        assert int(jax.device_get(state.step)) == 1
        assert os.path.isdir(os.path.join(str(tmp_path / "ckpt"), "epoch_1"))
        # train() restored the pre-install SIGTERM disposition on exit, so
        # post-training work (and this pytest process) keeps normal semantics
        assert signal.getsignal(signal.SIGTERM) is not preempt._handler
    finally:
        preempt.uninstall()
        preempt.reset()

    # auto-resume re-enters epoch 1 AT STEP 2 (step-granular: the sidecar
    # recorded 1 completed step) and finishes it under the new
    # steps_per_epoch=2, then runs epoch 2 in full
    cfg2 = tiny_cfg(
        fake_data=True, num_epochs=2, steps_per_epoch=2, log_step_interval=99,
        resume_epoch=-1, ckpt_dir=str(tmp_path / "ckpt"), ckpt_epoch_interval=99,
        test_epoch_interval=99, num_workers=2, eval_max_batches=1,
    )
    state2 = train(cfg2)
    # 1 saved + epoch-1's remaining 1 step + epoch-2's 2 steps
    assert int(jax.device_get(state2.step)) == 4


@pytest.mark.slow
def test_model_actually_learns(devices8):
    """Beyond loss-decreases: on a linearly-separable synthetic task (class =
    dominant color channel) the full sharded train step must reach high train
    accuracy from random init — end-to-end learning evidence (model + loss +
    optimizer + schedule + sharding all correct together), not just a falling
    scalar."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from vitax.parallel.mesh import batch_pspec

    cfg = tiny_cfg(num_classes=3, batch_size=32, lr=3e-3, warmup_steps=5)
    mesh, state, step_fn, eval_fn = build_train_objects(cfg, max_iteration=200)
    sh = NamedSharding(mesh, batch_pspec())

    def color_batch(seed):
        rng = np.random.default_rng(seed)
        labels = rng.integers(0, 3, size=(cfg.batch_size,))
        imgs = rng.normal(0, 0.3, size=(
            cfg.batch_size, cfg.image_size, cfg.image_size, 3))
        for i, c in enumerate(labels):
            imgs[i, :, :, c] += 2.0  # dominant channel = class
        return {"image": jax.device_put(jnp.asarray(imgs, jnp.float32), sh),
                "label": jax.device_put(jnp.asarray(labels, jnp.int32), sh)}

    rng_key = jax.random.key(1)
    for i in range(60):
        state, metrics = step_fn(state, color_batch(i), rng_key)

    # held-out batches (seeds never trained on)
    correct = sum(
        int(jax.device_get(eval_fn(state, color_batch(1000 + j))["correct"]))
        for j in range(4))
    accuracy = correct / (4 * cfg.batch_size)
    assert accuracy > 0.9, f"model failed to learn a separable task: {accuracy=}"
