"""PredictionCache: content-addressed router cache, exact by construction.

Classification over an AOT-pinned engine is deterministic, so the cache
contract is EXACT replay, not approximation — and that is what these tests
pin: a hit is bitwise-identical to the first miss's 200 body, distinct
topk values never alias (same image at topk 1 and topk 5 are different
keys), TTL and LRU bounds hold under an injected clock (no real time),
and — through a real Router front door — a repeated body is answered from
the cache without the replica seeing a second predict (the predict-count
pin), even when the fleet has zero ready replicas.
"""

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from vitax.serve.fleet import (
    PredictionCache,
    ReplicaManager,
    Router,
    start_router,
    stop_router,
)

PNG_A = b"\x89PNG-fake-image-bytes-a"
PNG_B = b"\x89PNG-fake-image-bytes-b"


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class DummyRecorder:
    def __init__(self):
        self.events = []

    def event(self, kind, **payload):
        self.events.append((kind, payload))


# --- key semantics -------------------------------------------------------------


def test_key_separates_bytes_and_topk():
    """The content address is (sha256(bytes), topk): either component
    changing changes the key, and equal inputs collide on purpose."""
    assert PredictionCache.key(PNG_A, 3) == PredictionCache.key(PNG_A, 3)
    assert PredictionCache.key(PNG_A, 1) != PredictionCache.key(PNG_A, 5)
    assert PredictionCache.key(PNG_A, 3) != PredictionCache.key(PNG_B, 3)
    assert (PredictionCache.key(PNG_A, "default")
            != PredictionCache.key(PNG_A, 1))


def test_distinct_topk_never_alias():
    c = PredictionCache(max_entries=8)
    c.put(PNG_A, 1, b'{"classes": [1]}')
    c.put(PNG_A, 5, b'{"classes": [1, 0, 2, 3, 4]}')
    assert c.get(PNG_A, 1) == b'{"classes": [1]}'
    assert c.get(PNG_A, 5) == b'{"classes": [1, 0, 2, 3, 4]}'
    assert c.get(PNG_A, 3) is None  # never served a topk it never stored


def test_hit_is_bitwise_exact():
    """A hit replays the stored 200 payload verbatim — byte-for-byte, not
    a re-serialization (key ordering, float formatting all preserved)."""
    payload = json.dumps({"classes": [2, 0, 1],
                          "probs": [0.5000001, 0.3, 0.1999999],
                          "latency_ms": 12.345}).encode("utf-8")
    c = PredictionCache(max_entries=4)
    c.put(PNG_A, "default", payload)
    got = c.get(PNG_A, "default")
    assert got == payload
    assert isinstance(got, bytes)


# --- TTL / LRU under an injected clock -----------------------------------------


def test_ttl_expiry_with_injected_clock():
    clock = FakeClock(t=100.0)
    c = PredictionCache(max_entries=4, ttl_s=10.0, clock=clock)
    c.put(PNG_A, 3, b"fresh")
    clock.t = 109.9
    assert c.get(PNG_A, 3) == b"fresh"   # inside the TTL
    clock.t = 110.0
    assert c.get(PNG_A, 3) is None       # at the boundary: expired
    assert c.size() == 0                 # expiry drops the entry
    assert c.expirations_total == 1
    # a re-put restarts the clock
    c.put(PNG_A, 3, b"refilled")
    clock.t = 115.0
    assert c.get(PNG_A, 3) == b"refilled"


def test_ttl_zero_means_never_expires():
    clock = FakeClock(t=0.0)
    c = PredictionCache(max_entries=4, ttl_s=0.0, clock=clock)
    c.put(PNG_A, 3, b"eternal")
    clock.t = 1e9
    assert c.get(PNG_A, 3) == b"eternal"
    assert c.expirations_total == 0


def test_lru_eviction_bounded_and_recency_refreshed():
    c = PredictionCache(max_entries=2, ttl_s=0.0)
    c.put(b"a", 3, b"A")
    c.put(b"b", 3, b"B")
    assert c.get(b"a", 3) == b"A"   # refreshes a's recency -> b is LRU
    c.put(b"c", 3, b"C")            # past the bound: evicts b
    assert c.size() == 2
    assert c.get(b"b", 3) is None
    assert c.get(b"a", 3) == b"A"
    assert c.get(b"c", 3) == b"C"
    assert c.evictions_total == 1


def test_disabled_at_zero_entries():
    c = PredictionCache(max_entries=0)
    assert c.enabled is False
    c.put(PNG_A, 3, b"never stored")
    assert c.get(PNG_A, 3) is None
    assert c.size() == 0
    assert c.hits_total == 0 and c.misses_total == 0  # off = not even counted


# --- telemetry + snapshot ------------------------------------------------------


def test_hit_event_carries_running_totals():
    """tools/metrics_report.py derives the hit rate from the JSONL alone,
    so every hit event must carry the running totals (misses emit no
    per-event record by design)."""
    rec = DummyRecorder()
    c = PredictionCache(max_entries=4, recorder=rec)
    assert c.get(PNG_A, 3) is None        # miss: counted, no event
    assert rec.events == []
    c.put(PNG_A, 3, b"X")
    assert c.get(PNG_A, 3) == b"X"
    kind, payload = rec.events[-1]
    assert kind == "cache" and payload["decision"] == "hit"
    assert payload["hits_total"] == 1 and payload["misses_total"] == 1
    snap = c.snapshot()
    assert snap["hits_total"] == 1 and snap["misses_total"] == 1
    assert snap["hit_rate"] == 0.5
    assert snap["size"] == 1 and snap["enabled"] is True


# --- through the router: hits bypass dispatch (predict-count pin) --------------


class CountingReplica:
    """Minimal serve stand-in: /healthz ready, every POST is a predict that
    bumps predict_count and answers the single-engine 200 contract."""

    def __init__(self):
        self.ready = True
        self.predict_count = 0
        self._lock = threading.Lock()
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003
                pass

            def _reply(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                self._reply(200, {"status": "ok", "ready": fake.ready})

            def do_POST(self):  # noqa: N802
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                with fake._lock:
                    fake.predict_count += 1
                self._reply(200, {"classes": [1, 0, 2],
                                  "probs": [0.5, 0.3, 0.2],
                                  "latency_ms": 1.0})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture
def cached_fleet():
    fake = CountingReplica()
    manager = ReplicaManager()
    manager.adopt(fake.url, name="a")
    manager.poll_once()
    cache = PredictionCache(max_entries=64)
    router = Router(manager, cache=cache, request_timeout_s=10.0)
    httpd = start_router(router, 0)
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield manager, router, cache, url, fake
    stop_router(httpd, router)
    fake.stop()


def _post_raw(url, body, content_type="image/png", timeout=30.0):
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": content_type})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), resp.read()


def test_router_repeated_bytes_pin_predict_count(cached_fleet):
    """The acceptance pin: the second identical request never reaches the
    replica — predict_count stays at 1, the response bytes are identical,
    and the hit is flagged (header + counters)."""
    manager, router, cache, url, fake = cached_fleet
    s1, h1, body1 = _post_raw(url + "/predict", PNG_A)
    assert s1 == 200 and fake.predict_count == 1
    assert "X-Vitax-Cache" not in h1
    s2, h2, body2 = _post_raw(url + "/predict", PNG_A)
    assert s2 == 200
    assert body2 == body1                     # bitwise replay
    assert fake.predict_count == 1            # zero extra engine predicts
    assert h2.get("X-Vitax-Cache") == "hit"
    assert router.metrics.cache_hits_total == 1
    # distinct bytes miss and dispatch normally
    s3, _, _ = _post_raw(url + "/predict", PNG_B)
    assert s3 == 200 and fake.predict_count == 2
    snap = router.fleet_metrics()
    assert snap["cache_hits"] == 1
    assert snap["cache"]["misses_total"] == 2
    assert snap["cache_hit_rate"] == round(1 / 3, 4)
    # cache hits are not replica work: requests_total counts dispatches only
    assert snap["requests_total"] == 2


def test_router_cache_hits_survive_zero_ready_replicas(cached_fleet):
    """Hits bypass readiness and admission entirely: cached answers keep
    flowing while the whole fleet is down; novel bytes get the 503."""
    manager, router, cache, url, fake = cached_fleet
    _post_raw(url + "/predict", PNG_A)        # seed the cache
    fake.ready = False
    manager.poll_once()                       # ejects the only replica
    assert manager.ready_count() == 0
    status, headers, body = _post_raw(url + "/predict", PNG_A)
    assert status == 200 and headers.get("X-Vitax-Cache") == "hit"
    with pytest.raises(urllib.error.HTTPError) as e:
        _post_raw(url + "/predict", PNG_B)    # novel bytes: no replica
    assert e.value.code == 503
    assert json.load(e.value)["reason"] == "no_ready_replicas"
    assert fake.predict_count == 1


def test_router_never_caches_degraded_answers(cached_fleet):
    """A browned-out fleet clamps topk to 1 — replaying those answers
    after recovery would be wrong, so degraded responses are never
    stored (and re-dispatch once the brownout lifts)."""
    manager, router, cache, url, fake = cached_fleet
    replica = manager.find("a")
    with manager._lock:
        replica.last_health = {"status": "ok", "ready": True,
                               "degraded": True}
    s1, _, _ = _post_raw(url + "/predict", PNG_A)
    assert s1 == 200 and cache.size() == 0    # answered, not stored
    with manager._lock:
        replica.last_health = {"status": "ok", "ready": True,
                               "degraded": False}
    s2, h2, _ = _post_raw(url + "/predict", PNG_A)
    assert s2 == 200 and "X-Vitax-Cache" not in h2
    assert fake.predict_count == 2            # the miss re-dispatched
    assert cache.size() == 1                  # healthy answer cached now
    _, h3, _ = _post_raw(url + "/predict", PNG_A)
    assert h3.get("X-Vitax-Cache") == "hit"


def test_request_topk_keying():
    """JSON bodies may carry a per-request topk: it becomes the key's topk
    component; raw images and malformed JSON key as the replica default."""
    assert Router._request_topk(b'{"topk": 5}', "application/json") == 5
    assert Router._request_topk(b'{"topk": "2"}', "application/json") == 2
    assert Router._request_topk(b'{"image": "..."}',
                                "application/json") == "default"
    assert Router._request_topk(b"not json{", "application/json") == "default"
    assert Router._request_topk(PNG_A, "image/png") == "default"
    assert Router._request_topk(PNG_A, "") == "default"
