"""Autoscaler + placement: hysteretic fleet sizing, drain-safe scale-in.

Everything time-driven runs against tick(now=...) with injected clocks —
no sleeps, no real sockets for the scaling logic itself. The pins:

- scale-OUT fires only after a pressure signal (shed rate, predicted-wait
  overshoot, brownout) sustains for `dwell_s`, and `cooldown_s` dead time
  separates consecutive actions (blips never scale);
- a warming replica relieves predicted-wait pressure at the admission
  discount, so one scale-out doesn't cascade into N;
- scale-IN retires first and discards ONLY once the victim's in-flight
  count reaches zero (the drain-before-terminate acceptance pin) — and
  even the drain-timeout force path still SIGTERM-drains;
- the [min_replicas, max_replicas] clamps hold, and a fleet below the
  floor is repaired immediately (no dwell);
- the placement agent provisions/releases replicas over real HTTP with an
  injected spawn, and the client round-trips the contract.
"""

import signal
import sys
import threading
import time
import urllib.error

import pytest

from vitax.serve.fleet import (
    EJECTED,
    READY,
    AdmissionController,
    Autoscaler,
    PlacementAgent,
    PlacementClient,
    ReplicaManager,
    start_agent,
    stop_agent,
)


class DummyRecorder:
    def __init__(self):
        self.events = []

    def event(self, kind, **payload):
        self.events.append((kind, payload))

    def kinds(self):
        return [k for k, _ in self.events]


class FakeProc:
    """Popen stand-in with a settable return code."""

    def __init__(self):
        self.rc = None
        self.signals = []

    def poll(self):
        return self.rc

    def send_signal(self, sig):
        self.signals.append(sig)
        self.rc = 0

    def kill(self):
        self.rc = -9


def _never(url, timeout):
    raise ConnectionError("unreachable")


def mk_manager(n_ready=1, managed=False, **kw):
    """A manager with n replicas forced READY (no health loop running);
    managed=True backs each with a FakeProc so discard() drains it."""
    procs = []

    def spawn(argv):
        p = FakeProc()
        procs.append(p)
        return p

    m = ReplicaManager(http_get=_never, spawn=spawn, **kw)
    for i in range(n_ready):
        if managed:
            r = m.manage(["serve", "cmd"], f"http://r{i}", name=f"r{i}")
        else:
            r = m.adopt(f"http://r{i}", name=f"r{i}")
        r.state = READY
    return m, procs


def adopt_scaler(manager):
    """A scale_out fn that grows the fleet like the CLI closure does
    (adopt -> STARTING until health admits it), counting calls."""
    calls = []

    def scale_out():
        r = manager.adopt(f"http://new{len(calls)}")
        calls.append(r)
        return r

    return scale_out, calls


# --- scale-out signals ---------------------------------------------------------


def test_scale_out_on_sustained_shed_rate_with_dwell_and_cooldown():
    m, _ = mk_manager(n_ready=1)
    adm = AdmissionController(deadline_ms=0.0)  # sheds counted, check off
    scale_out, calls = adopt_scaler(m)
    a = Autoscaler(m, adm, min_replicas=1, max_replicas=3,
                   scale_out=scale_out, dwell_s=2.0, cooldown_s=5.0,
                   shed_rate_per_s=1.0)

    def shed(n):
        for _ in range(n):
            adm.record_shed(reason="test")

    assert a.tick(now=0.0) is None            # baseline sample
    shed(5)
    assert a.tick(now=1.0) is None            # rate 5/s: pressure starts
    shed(5)
    assert a.tick(now=2.0) is None            # sustained 1s < dwell 2s
    shed(5)
    assert a.tick(now=3.0) == "scale_out"     # dwell met
    assert len(calls) == 1 and a.scale_out_total == 1
    assert m.active_count() == 2
    # cooldown: pressure keeps firing but no action until now >= 8
    shed(5)
    assert a.tick(now=4.0) is None
    shed(10)
    assert a.tick(now=6.0) is None            # dwell met again, in cooldown
    shed(10)
    assert a.tick(now=8.0) == "scale_out"
    assert len(calls) == 2 and m.active_count() == 3
    # a blip never scales: rate collapses to zero, streak resets
    assert a.tick(now=9.0) is None
    assert a._pressure_since is None
    # at max_replicas the clamp holds no matter the pressure
    shed(20)
    a.tick(now=14.0)
    shed(20)
    assert a.tick(now=16.0) is None
    assert len(calls) == 2 and m.active_count() == 3


def test_scale_out_on_predicted_wait_and_warming_relief():
    rec = DummyRecorder()
    m, _ = mk_manager(n_ready=1)
    adm = AdmissionController(deadline_ms=800.0)
    adm.observe(1.0)                          # EWMA service time 1s
    m.find("r0").in_flight = 1                # predicted 1.0s > 0.8s
    scale_out, calls = adopt_scaler(m)
    a = Autoscaler(m, adm, min_replicas=1, max_replicas=3,
                   scale_out=scale_out, dwell_s=2.0, cooldown_s=0.0,
                   recorder=rec)
    assert a.tick(now=0.0) is None
    assert a.tick(now=2.0) == "scale_out"
    assert len(calls) == 1
    assert rec.events[-1][1]["reason"] == "predicted_wait"
    # the new replica is warming (STARTING): admission counts it at the
    # 0.5 discount, predicted drops to 1/1.5 = 0.67s <= 0.8s -> pressure
    # gone, so one scale-out does not cascade into a second
    assert m.warming_count() == 1
    for now in (3.0, 5.0, 8.0):
        assert a.tick(now=now) is None
    assert len(calls) == 1


def test_scale_out_on_brownout_dwell():
    rec = DummyRecorder()
    m, _ = mk_manager(n_ready=1)
    degraded = m.find("r0")
    with m._lock:
        degraded.last_health = {"degraded": True}
    scale_out, calls = adopt_scaler(m)
    a = Autoscaler(m, min_replicas=1, max_replicas=2, scale_out=scale_out,
                   dwell_s=1.0, cooldown_s=0.0, recorder=rec)
    assert a.tick(now=0.0) is None            # brownout seen, not sustained
    assert a.tick(now=0.5) is None
    assert a.tick(now=1.0) == "scale_out"
    assert len(calls) == 1
    assert rec.events[-1][1]["reason"] == "brownout"


def test_floor_repair_is_immediate():
    """A fleet below min_replicas (restart budget exhausted) grows back on
    the next tick — no dwell, regardless of traffic."""
    rec = DummyRecorder()
    m, _ = mk_manager(n_ready=1)
    scale_out, calls = adopt_scaler(m)
    a = Autoscaler(m, min_replicas=2, max_replicas=3, scale_out=scale_out,
                   dwell_s=60.0, cooldown_s=0.0, recorder=rec)
    assert a.tick(now=0.0) == "scale_out"     # first tick, no streak needed
    assert len(calls) == 1 and m.active_count() == 2
    assert rec.events[-1][1]["reason"] == "below_min"
    assert a.tick(now=1.0) is None            # floor met, nothing more


def test_scale_out_failure_contained_and_cooled_down():
    """A failed provision must not kill the loop — it records the failure,
    takes the cooldown, and tries again after it."""
    rec = DummyRecorder()
    m, _ = mk_manager(n_ready=1)
    degraded = m.find("r0")
    with m._lock:
        degraded.last_health = {"degraded": True}
    attempts = []

    def scale_out():
        attempts.append(1)
        raise RuntimeError("placement agent unreachable")

    a = Autoscaler(m, min_replicas=1, max_replicas=2, scale_out=scale_out,
                   dwell_s=2.0, cooldown_s=5.0, recorder=rec)
    a.tick(now=0.0)
    assert a.tick(now=2.0) is None            # attempt #1 failed
    assert len(attempts) == 1 and a.scale_out_total == 0
    assert ("autoscale", rec.events[-1][1])[1]["event"] == "scale_out_failed"
    a.tick(now=3.0)                           # streak restarts
    assert a.tick(now=5.0) is None            # dwell met, still cooling down
    assert len(attempts) == 1
    assert a.tick(now=7.0) is None            # cooldown ends at 7.0, retried
    assert len(attempts) == 2


# --- arbiter escalation (request_capacity) -------------------------------------


def _browned_out_manager():
    """One READY replica advertising brownout: a pressure signal that needs
    no admission controller plumbing."""
    m, _ = mk_manager(n_ready=1)
    r = m.find("r0")
    with m._lock:
        r.last_health = {"degraded": True}
    return m


def test_escalates_at_max_replicas_instead_of_stalling():
    """Sustained pressure at the --max_replicas ceiling used to cool down
    silently; with a request_capacity closure it asks the arbiter, counts
    the escalation, and emits the autoscale event with outcome
    "escalated"."""
    rec = DummyRecorder()
    m = _browned_out_manager()
    asks = []
    a = Autoscaler(m, min_replicas=1, max_replicas=1,  # already AT ceiling
                   scale_out=lambda: (_ for _ in ()).throw(
                       AssertionError("must not spawn at the ceiling")),
                   request_capacity=lambda reason: asks.append(reason),
                   dwell_s=2.0, cooldown_s=5.0, recorder=rec)
    assert a.tick(now=0.0) is None            # pressure streak starts
    assert a.tick(now=2.0) == "escalated"     # dwell met -> ask the arbiter
    assert asks == ["brownout"]
    assert a.escalations_total == 1
    assert a.snapshot()["escalations_total"] == 1
    event = dict(rec.events[-1][1])
    assert rec.events[-1][0] == "autoscale"
    assert event["event"] == "scale_out"
    assert event["outcome"] == "escalated"
    assert event["reason"] == "brownout"
    # the ask opens the normal cooldown: no repeat spam while waiting for
    # the borrowed capacity to arrive via /fleet/adopt
    assert a.tick(now=4.0) is None
    assert a.tick(now=7.0) == "escalated"     # cooldown over, still starved
    assert len(asks) == 2


def test_escalates_when_every_agent_slot_is_full():
    """A scale-out that fails below the ceiling (every placement agent
    409'd) escalates too — same starvation, different shape."""
    rec = DummyRecorder()
    m = _browned_out_manager()
    asks = []

    def scale_out():
        from vitax.serve.fleet.placement import AgentFullError
        raise AgentFullError("agent at capacity")

    a = Autoscaler(m, min_replicas=1, max_replicas=3, scale_out=scale_out,
                   request_capacity=lambda reason: asks.append(reason),
                   dwell_s=2.0, cooldown_s=5.0, recorder=rec)
    a.tick(now=0.0)
    assert a.tick(now=2.0) == "escalated"
    assert asks == ["brownout"] and a.escalations_total == 1
    kinds = [p.get("event") for k, p in rec.events if k == "autoscale"]
    assert kinds == ["scale_out_failed", "scale_out"]
    assert rec.events[-1][1]["outcome"] == "escalated"


def test_escalation_failure_contained_and_cooled_down():
    """An unreachable arbiter must not kill the loop: the failure is
    recorded, the cooldown still opens, and nothing counts as escalated."""
    rec = DummyRecorder()
    m = _browned_out_manager()

    def request_capacity(reason):
        raise ConnectionError("arbiter unreachable")

    a = Autoscaler(m, min_replicas=1, max_replicas=1,
                   request_capacity=request_capacity,
                   dwell_s=2.0, cooldown_s=5.0, recorder=rec)
    a.tick(now=0.0)
    assert a.tick(now=2.0) is None
    assert a.escalations_total == 0
    assert rec.events[-1][1]["event"] == "escalate_failed"
    assert a.tick(now=4.0) is None            # cooling down, no retry spam


def test_no_escalation_without_request_capacity():
    """Without the closure the old behavior holds: the ceiling just
    clamps (covered above), and a failed provision only records
    scale_out_failed."""
    m = _browned_out_manager()
    a = Autoscaler(m, min_replicas=1, max_replicas=1, dwell_s=2.0)
    a.tick(now=0.0)
    assert a.tick(now=2.0) is None
    assert a.escalations_total == 0


# --- scale-in: drain before terminate ------------------------------------------


def test_scale_in_drains_before_terminate():
    """The acceptance pin: the victim is retired (out of rotation), and the
    process sees NO signal until its in-flight count reaches zero — only
    then is it SIGTERM-drained and removed."""
    rec = DummyRecorder()
    m, procs = mk_manager(n_ready=2, managed=True)
    released = []
    a = Autoscaler(m, min_replicas=1, max_replicas=2,
                   release=released.append, dwell_s=2.0, cooldown_s=0.0,
                   idle_occupancy=0.25, drain_timeout_s=100.0, recorder=rec)
    a.tick(now=0.0)                           # idle streak opens
    assert a.tick(now=2.0) == "retire"
    victim = m.find("r0")                     # least loaded (tie -> first)
    assert victim.retired and victim.state == EJECTED
    assert m.ready_count() == 1 and m.active_count() == 1
    # a request is still draining on the victim: no signal, no discard
    victim.in_flight = 1
    assert a.tick(now=3.0) is None
    assert a.tick(now=4.0) is None
    assert procs[0].signals == []             # untouched while in flight
    assert victim in m.replicas
    # drain completes -> SIGTERM-drain + removal, release() for remotes
    victim.in_flight = 0
    assert a.tick(now=5.0) == "scale_in"
    assert procs[0].signals == [signal.SIGTERM]
    assert victim not in m.replicas
    assert victim.exit_code == 0              # the drain contract
    assert a.scale_in_total == 1
    assert released == [victim]
    assert rec.events[-1][1] == {"event": "scale_in", "replica": "r0",
                                 "forced": False, "size": 1}
    # the survivor keeps the fleet at the floor: no further retire
    a.tick(now=7.0)
    assert a.tick(now=9.0) is None
    assert m.active_count() == 1


def test_scale_in_forced_after_drain_timeout_still_drains():
    m, procs = mk_manager(n_ready=2, managed=True)
    a = Autoscaler(m, min_replicas=1, max_replicas=2, dwell_s=1.0,
                   cooldown_s=0.0, drain_timeout_s=10.0)
    a.tick(now=0.0)
    assert a.tick(now=1.0) == "retire"        # drain deadline = 11.0
    victim = m.find("r0")
    victim.in_flight = 1                      # never drains
    assert a.tick(now=5.0) is None
    assert procs[0].signals == []
    assert a.tick(now=11.0) == "scale_in"     # forced at the deadline
    assert a.last_event["forced"] is True
    # even forced, the exit is a SIGTERM drain, not a kill
    assert procs[0].signals == [signal.SIGTERM]
    assert victim not in m.replicas


def test_idle_blip_never_scales_in():
    m, _ = mk_manager(n_ready=2)
    a = Autoscaler(m, min_replicas=1, max_replicas=2, dwell_s=2.0,
                   cooldown_s=0.0, idle_occupancy=0.25)
    a.tick(now=0.0)                           # idle streak opens
    m.find("r0").in_flight = 2                # load arrives mid-streak
    assert a.tick(now=1.9) is None            # occupancy 1.0: streak reset
    m.find("r0").in_flight = 0
    assert a.tick(now=2.0) is None            # streak reopens at 2.0
    assert a.tick(now=3.9) is None            # 1.9s < dwell
    assert a.tick(now=4.0) == "retire"


def test_snapshot_shape():
    m, _ = mk_manager(n_ready=1)
    a = Autoscaler(m, min_replicas=1, max_replicas=4)
    snap = a.snapshot()
    assert snap == {"min_replicas": 1, "max_replicas": 4,
                    "scale_out_total": 0, "scale_in_total": 0,
                    "escalations_total": 0,
                    "shed_rate_per_s": 0.0, "draining": None,
                    "last_event": None}


def test_loop_start_stop_clean():
    m, _ = mk_manager(n_ready=1)
    a = Autoscaler(m, min_replicas=1, max_replicas=1, interval_s=0.02)
    a.start()
    time.sleep(0.1)                           # a few real ticks, no action
    a.stop()
    assert a._thread is None
    assert a.scale_out_total == 0 and a.scale_in_total == 0


# --- placement agent + client ---------------------------------------------------


def test_agent_provision_release_http_roundtrip():
    """Real HTTP against a real agent, injected spawn: provision boots a
    `python -m vitax.serve` argv on the agent-assigned port, release
    SIGTERM-drains it, and the error contract (400 duplicate / 404
    unknown) round-trips through the client."""
    spawned, procs = [], []

    def spawn(argv):
        spawned.append(argv)
        p = FakeProc()
        procs.append(p)
        return p

    mgr = ReplicaManager(spawn=spawn, http_get=_never,
                         health_interval_s=0.05)
    agent = PlacementAgent(advertise_host="127.0.0.1", base_port=9200,
                           manager=mgr)
    httpd = start_agent(agent, port=0)
    client = PlacementClient(
        f"http://127.0.0.1:{httpd.server_address[1]}", timeout_s=10.0)
    try:
        health = client.healthz()
        assert health["status"] == "ok" and health["replicas"] == 0
        out = client.provision(["--ckpt_dir", "/tmp/x"], name="r1")
        assert out == {"name": "r1", "url": "http://127.0.0.1:9200",
                       "port": 9200}
        assert spawned[0] == [sys.executable, "-m", "vitax.serve",
                              "--ckpt_dir", "/tmp/x",
                              "--serve_port", "9200"]
        out2 = client.provision(["--ckpt_dir", "/tmp/x"])  # agent names it
        assert out2["name"] == "agent_replica_1" and out2["port"] == 9201
        snap = client.replicas()
        assert snap["provisions_total"] == 2
        assert set(snap["replicas"]) == {"r1", "agent_replica_1"}
        with pytest.raises(urllib.error.HTTPError) as e:
            client.provision(["--ckpt_dir", "/tmp/x"], name="r1")
        assert e.value.code == 400            # duplicate name refused
        assert client.release("r1") == {"released": "r1"}
        assert procs[0].signals == [signal.SIGTERM]  # drained, not killed
        assert mgr.find("r1") is None
        with pytest.raises(urllib.error.HTTPError) as e:
            client.release("r1")              # now unknown
        assert e.value.code == 404
    finally:
        stop_agent(httpd, agent)
    assert procs[1].signals == [signal.SIGTERM]  # stop drains the rest


def test_placement_client_injected_transport():
    calls = []

    def http_json(url, payload, timeout):
        calls.append((url, payload, timeout))
        return {"ok": True}

    c = PlacementClient("http://agent:7070/", timeout_s=3.0,
                        http_json=http_json)
    assert c.healthz() == {"ok": True}
    assert calls[-1] == ("http://agent:7070/healthz", None, 3.0)
    c.replicas()
    assert calls[-1] == ("http://agent:7070/replicas", None, 3.0)
    c.provision(["--x"], name="n", port=5)
    assert calls[-1] == ("http://agent:7070/provision",
                         {"argv": ["--x"], "name": "n", "port": 5}, 3.0)
    c.release("n")
    assert calls[-1] == ("http://agent:7070/release", {"name": "n"}, 3.0)


def test_agent_rejects_bad_provision_payloads():
    agent = PlacementAgent(manager=ReplicaManager(
        spawn=lambda argv: FakeProc(), http_get=_never))
    with pytest.raises(ValueError, match="list of strings"):
        agent.provision("--not-a-list")
    with pytest.raises(ValueError, match="list of strings"):
        agent.provision([1, 2, 3])


def test_autoscaler_bounds_validated():
    m, _ = mk_manager(n_ready=1)
    with pytest.raises(AssertionError):
        Autoscaler(m, min_replicas=3, max_replicas=2)
    with pytest.raises(AssertionError):
        Autoscaler(m, min_replicas=0, max_replicas=2)
