"""HLO-level proof of ZeRO-3 memory behavior (VERDICT round-1 item 2).

The whole scan+GSPMD design bets that XLA keeps per-layer all-gathers INSIDE
the scan's while loop instead of hoisting a full-model gather before it — the
property nested FSDP wrapping guarantees by construction in the reference
(run_vit_training.py:177-181; SURVEY.md section 7 hard-part #2). These tests
discharge that bet from the compiled (optimized, SPMD-partitioned) HLO of the
real ViT-L/14 train step on the 8-device mesh:

1. per-device argument memory is shard-bound (== global state / 8);
2. transient (temp) memory is far below full-model size — no hoisted gather;
3. every all-gather's output is per-layer/activation sized, never the stacked
   24-block parameter tensor;
4. the block-weight all-gathers carry `while/body` scope metadata in both the
   forward and the rematted backward scan — they run once per layer step,
   inside the loop.

Plus a 10B-shape (BASELINE config 4) eval_shape + AOT lowering smoke: the
flagship config traces and lowers without materializing anything.
"""

import re

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from vitax.config import Config
from vitax.models import build_model, count_params
from vitax.parallel.mesh import batch_pspec, build_mesh
from vitax.train.state import build_optimizer, make_train_state
from vitax.train.step import make_train_step


def _lower_train_step(cfg, n_steps_sched=100):
    mesh = build_mesh(cfg)
    model = build_model(cfg)
    tx, _ = build_optimizer(cfg, max_iteration=n_steps_sched)
    state, sspecs, _ = make_train_state(
        cfg, model, tx, mesh, jax.random.key(0), materialize=False)
    step = make_train_step(cfg, model, tx, mesh, sspecs)
    sh = NamedSharding(mesh, batch_pspec())
    batch = {
        "image": jax.ShapeDtypeStruct(
            (cfg.batch_size, cfg.image_size, cfg.image_size, 3),
            jnp.float32, sharding=sh),
        "label": jax.ShapeDtypeStruct((cfg.batch_size,), jnp.int32, sharding=sh),
    }
    return state, step.lower(state, batch, jax.random.key(0))


def _state_bytes(abstract_state) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(abstract_state))


_DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "pred": 1,
                "u8": 1, "s8": 1, "f64": 8, "s64": 8, "u64": 8}


def _shape_bytes(shape_str: str) -> int:
    m = re.match(r"(\w+)\[([\d,]*)\]", shape_str)
    if not m:
        return 0
    dt, dims = m.groups()
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dt, 4)


@pytest.fixture(scope="module")
def l14(devices8):
    """Compiled ViT-L/14 FSDP train step (the BASELINE config-3 shape) on the
    8-device mesh, with its abstract state."""
    cfg = Config(image_size=224, patch_size=14, embed_dim=1024, num_heads=16,
                 num_blocks=24, num_classes=1000, batch_size=8,
                 warmup_steps=0).validate()
    state, lowered = _lower_train_step(cfg)
    compiled = lowered.compile()
    return cfg, state, compiled


def test_per_device_state_is_shard_bound(l14):
    """Each device's input (params + both AdamW moments + batch shard) must be
    ~1/8 of the global state — ZeRO-1/2/3 all hold simultaneously."""
    cfg, state, compiled = l14
    ma = compiled.memory_analysis()
    global_bytes = _state_bytes(state)
    batch_bytes = cfg.batch_size * cfg.image_size ** 2 * 3 * 4
    bound = global_bytes / 8 + batch_bytes
    assert ma.argument_size_in_bytes < bound * 1.10, (
        f"per-device args {ma.argument_size_in_bytes/1e6:.0f} MB exceed the "
        f"shard-bound {bound/1e6:.0f} MB — state is not fully sharded")


def test_temp_memory_is_not_model_bound(l14):
    """Transient memory must stay far below the full parameter tensor: a
    hoisted whole-model all-gather would show up here at >= 1.2 GB."""
    cfg, state, compiled = l14
    ma = compiled.memory_analysis()
    full_param_bytes = count_params_bytes(cfg)
    assert ma.temp_size_in_bytes < 0.5 * full_param_bytes, (
        f"temp {ma.temp_size_in_bytes/1e6:.0f} MB vs full params "
        f"{full_param_bytes/1e6:.0f} MB — looks like a hoisted full gather")


def count_params_bytes(cfg) -> int:
    from vitax.models.vit import expected_param_count
    return expected_param_count(cfg) * 4  # f32 master params


def test_no_all_gather_is_stack_sized(l14):
    """Every all-gather output must be per-layer/per-activation sized; the
    stacked (24, ...) block parameters must never be gathered whole."""
    cfg, state, compiled = l14
    txt = compiled.as_text()
    ags = re.findall(r"= (\S+) all-gather\(", txt)
    assert ags, "no all-gathers found — sharding did not engage"
    # largest legitimate gather: one layer's fc weights gathered as activations
    # (B, N, mlp_hidden) f32 = 8*256*4096*4 = 33.5 MB; the stacked fc1 kernel
    # would be 24*1024*4096*4 = 402 MB
    per_layer_bound = 64 * 1024 * 1024
    sizes = sorted((_shape_bytes(s) for s in ags), reverse=True)
    assert sizes[0] < per_layer_bound, (
        f"largest all-gather is {sizes[0]/1e6:.0f} MB — full-stack gather "
        "(ZeRO-3 memory bet violated)")


def test_block_all_gathers_are_inside_scan_loop(l14):
    """XLA preserves source scope in op_name metadata: the block-weight
    gathers must carry `while/body` scope in BOTH the forward scan and the
    rematted backward scan, and every gather outside a while body must be a
    non-block (patchify / pos-embed / head / batch) tensor."""
    cfg, state, compiled = l14
    txt = compiled.as_text()
    ag_lines = [l for l in txt.splitlines() if re.search(r"= \S+ all-gather\(", l)]
    scoped = []
    for line in ag_lines:
        m = re.search(r'op_name="([^"]*)"', line)
        scoped.append(m.group(1) if m else "")
    fwd_in_loop = [s for s in scoped
                   if "while/body" in s and "transpose" not in s and "blocks" in s]
    bwd_in_loop = [s for s in scoped
                   if "while/body" in s and "transpose" in s and "blocks" in s]
    outside = [s for s in scoped if "while/body" not in s]
    assert fwd_in_loop, f"no forward in-loop block gathers; scopes: {scoped}"
    assert bwd_in_loop, f"no backward in-loop block gathers; scopes: {scoped}"
    for s in outside:
        assert "blocks" not in s, (
            f"block-parameter all-gather hoisted out of the scan loop: {s}")


@pytest.mark.slow
def test_10b_shape_traces_and_lowers(devices8):
    """BASELINE config 4 (the 10.078B flagship): eval_shape the sharded state
    and AOT-lower the full train step — no array is ever materialized, proving
    the 10B path is traceable end-to-end on any host."""
    cfg = Config(image_size=224, patch_size=14, embed_dim=5120, num_heads=32,
                 num_blocks=32, num_classes=1000, batch_size=8,
                 warmup_steps=0).validate()
    state, lowered = _lower_train_step(cfg)
    from vitax.models.vit import expected_param_count
    n = sum(x.size for x in jax.tree.leaves(state.params))
    assert n == expected_param_count(cfg) == 10_077_917_160
    txt = lowered.as_text()
    assert "stablehlo.while" in txt  # the 32-block scan survived lowering
