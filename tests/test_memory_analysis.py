"""HLO-level proof of ZeRO-3 memory behavior (VERDICT round-1 item 2).

The whole scan+GSPMD design bets that XLA keeps per-layer all-gathers INSIDE
the scan's while loop instead of hoisting a full-model gather before it — the
property nested FSDP wrapping guarantees by construction in the reference
(run_vit_training.py:177-181; SURVEY.md section 7 hard-part #2). These tests
discharge that bet from the compiled (optimized, SPMD-partitioned) HLO of the
real ViT-L/14 train step on the 8-device mesh:

1. per-device argument memory is shard-bound (== global state / 8);
2. transient (temp) memory is far below full-model size — no hoisted gather;
3. every all-gather's output is per-layer/activation sized, never the stacked
   24-block parameter tensor;
4. the block-weight all-gathers carry `while/body` scope metadata in both the
   forward and the rematted backward scan — they run once per layer step,
   inside the loop.

Plus a 10B-shape (BASELINE config 4) eval_shape + AOT lowering smoke: the
flagship config traces and lowers without materializing anything.
"""

import os
import re
import sys

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from vitax.config import Config
from vitax.models import build_model, count_params
from vitax.parallel.mesh import batch_pspec, build_mesh
from vitax.train.state import build_optimizer, make_train_state
from vitax.train.step import make_train_step


def _lower_train_step(cfg, n_steps_sched=100, n_devices=None):
    mesh = build_mesh(cfg, devices=jax.devices()[:n_devices]
                      if n_devices else None)
    model = build_model(cfg)
    tx, _ = build_optimizer(cfg, max_iteration=n_steps_sched)
    state, sspecs, _ = make_train_state(
        cfg, model, tx, mesh, jax.random.key(0), materialize=False)
    step = make_train_step(cfg, model, tx, mesh, sspecs)
    sh = NamedSharding(mesh, batch_pspec())
    batch = {
        "image": jax.ShapeDtypeStruct(
            (cfg.batch_size, cfg.image_size, cfg.image_size, 3),
            jnp.float32, sharding=sh),
        "label": jax.ShapeDtypeStruct((cfg.batch_size,), jnp.int32, sharding=sh),
    }
    return state, step.lower(state, batch, jax.random.key(0))


def _state_bytes(abstract_state) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(abstract_state))


_DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "pred": 1,
                "u8": 1, "s8": 1, "f64": 8, "s64": 8, "u64": 8}


def _shape_bytes(shape_str: str) -> int:
    m = re.match(r"(\w+)\[([\d,]*)\]", shape_str)
    if not m:
        return 0
    dt, dims = m.groups()
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dt, 4)


@pytest.fixture(scope="module")
def l14(devices8):
    """Compiled ViT-L/14 FSDP train step (the BASELINE config-3 shape) on the
    8-device mesh, with its abstract state."""
    cfg = Config(image_size=224, patch_size=14, embed_dim=1024, num_heads=16,
                 num_blocks=24, num_classes=1000, batch_size=8,
                 warmup_steps=0).validate()
    state, lowered = _lower_train_step(cfg)
    compiled = lowered.compile()
    return cfg, state, compiled


def test_per_device_state_is_shard_bound(l14):
    """Each device's input (params + both AdamW moments + batch shard) must be
    ~1/8 of the global state — ZeRO-1/2/3 all hold simultaneously."""
    cfg, state, compiled = l14
    ma = compiled.memory_analysis()
    global_bytes = _state_bytes(state)
    batch_bytes = cfg.batch_size * cfg.image_size ** 2 * 3 * 4
    bound = global_bytes / 8 + batch_bytes
    assert ma.argument_size_in_bytes < bound * 1.10, (
        f"per-device args {ma.argument_size_in_bytes/1e6:.0f} MB exceed the "
        f"shard-bound {bound/1e6:.0f} MB — state is not fully sharded")


def test_temp_memory_is_not_model_bound(l14):
    """Transient memory must stay far below the full parameter tensor: a
    hoisted whole-model all-gather would show up here at >= 1.2 GB."""
    cfg, state, compiled = l14
    ma = compiled.memory_analysis()
    full_param_bytes = count_params_bytes(cfg)
    assert ma.temp_size_in_bytes < 0.5 * full_param_bytes, (
        f"temp {ma.temp_size_in_bytes/1e6:.0f} MB vs full params "
        f"{full_param_bytes/1e6:.0f} MB — looks like a hoisted full gather")


def count_params_bytes(cfg) -> int:
    from vitax.models.vit import expected_param_count
    return expected_param_count(cfg) * 4  # f32 master params


def test_no_all_gather_is_stack_sized(l14):
    """Every all-gather output must be per-layer/per-activation sized; the
    stacked (24, ...) block parameters must never be gathered whole."""
    cfg, state, compiled = l14
    txt = compiled.as_text()
    ags = re.findall(r"= (\S+) all-gather\(", txt)
    assert ags, "no all-gathers found — sharding did not engage"
    # largest legitimate gather: one layer's fc weights gathered as activations
    # (B, N, mlp_hidden) f32 = 8*256*4096*4 = 33.5 MB; the stacked fc1 kernel
    # would be 24*1024*4096*4 = 402 MB
    per_layer_bound = 64 * 1024 * 1024
    sizes = sorted((_shape_bytes(s) for s in ags), reverse=True)
    assert sizes[0] < per_layer_bound, (
        f"largest all-gather is {sizes[0]/1e6:.0f} MB — full-stack gather "
        "(ZeRO-3 memory bet violated)")


def _hlo_computations(txt: str) -> dict:
    """Parse compiled HLO text into {computation_name: [instruction lines]}.
    Computation definitions start at column 0 as `%name (params) -> type {`
    (optionally prefixed with ENTRY)."""
    comps = {}
    name = None
    for line in txt.splitlines():
        m = re.match(r"(?:ENTRY\s+)?(%[\w.\-]+)\s*\(", line)
        if m and line.rstrip().endswith("{"):
            name = m.group(1)
            comps[name] = []
        elif name is not None:
            if line.startswith("}"):
                name = None
            else:
                comps[name].append(line)
    return comps


def _while_body_names(txt: str) -> set:
    """Computation names referenced as `body=` by while ops — the structural
    (metadata-independent) definition of 'inside the scan loop'."""
    return set(re.findall(r"body=(%[\w.\-]+)", txt))


def _check_block_gathers_inside_loop(txt: str) -> None:
    """Assert the ZeRO-3 scheduling property from compiled HLO structure:
    block-weight all-gathers live inside while-loop bodies (fwd AND rematted
    bwd), and no gather outside a loop body touches the stacked block params.

    Loop membership is STRUCTURAL (the gather's enclosing computation is some
    while op's `body=`), not an op_name substring match. op_name metadata is
    still used to classify fwd vs rematted-bwd and to name outside gathers —
    so its presence is asserted first: if XLA ever stops emitting it, this
    fails loudly instead of silently green-lighting a regression."""
    comps = _hlo_computations(txt)
    bodies = _while_body_names(txt)
    assert bodies, "no while loops found in compiled HLO — scan disappeared"

    in_loop, outside = [], []
    for cname, lines in comps.items():
        for line in lines:
            if re.search(r"= \S+ all-gather", line):
                (in_loop if cname in bodies else outside).append(line)
    assert in_loop, "no all-gathers inside any while body — ZeRO-3 bet violated"

    def op_name(line):
        m = re.search(r'op_name="([^"]*)"', line)
        return m.group(1) if m else ""

    in_scopes = [op_name(l) for l in in_loop]
    out_scopes = [op_name(l) for l in outside]
    # metadata guard: every gather must carry a real op_name before we trust
    # any classification built on it
    assert all(in_scopes) and all(out_scopes), (
        f"all-gather missing op_name metadata — cannot verify scheduling; "
        f"in-loop: {in_scopes}, outside: {out_scopes}")

    fwd = [s for s in in_scopes if "blocks" in s and "transpose" not in s]
    bwd = [s for s in in_scopes if "blocks" in s and "transpose" in s]
    assert fwd, f"no forward in-loop block gathers; in-loop scopes: {in_scopes}"
    assert bwd, f"no rematted-backward in-loop block gathers; in-loop scopes: {in_scopes}"
    for s in out_scopes:
        assert "blocks" not in s, (
            f"block-parameter all-gather hoisted out of the scan loop: {s}")


def test_block_all_gathers_are_inside_scan_loop(l14):
    """The block-weight gathers run once per layer step inside the scan's
    while loop — forward and rematted backward — never hoisted whole."""
    cfg, state, compiled = l14
    _check_block_gathers_inside_loop(compiled.as_text())


def test_scope_check_fails_when_metadata_stripped(l14):
    """Negative control: with op_name metadata stripped from the HLO the
    checker must FAIL (not silently pass) — the round-2 weakness where the
    `outside` check green-lit metadata-free text."""
    cfg, state, compiled = l14
    txt = re.sub(r',?\s*op_name="[^"]*"', "", compiled.as_text())
    with pytest.raises(AssertionError, match="op_name"):
        _check_block_gathers_inside_loop(txt)


@pytest.mark.slow
@pytest.mark.parametrize("scan_unroll", [1, 4])
def test_10b_shape_traces_and_lowers(devices8, scan_unroll):
    """BASELINE config 4 (the 10.078B flagship): eval_shape the sharded state,
    AOT-lower AND compile the full train step on the 8-mesh — no array is ever
    materialized — then assert the ZeRO-3 memory bet AT FLAGSHIP SHAPE from
    the compiled memory analysis: per-device arguments are exactly the
    1/8 state shard (15.12 GB of the 120.94 GB global f32 state) and temps
    stay far below the full 40.3 GB parameter tensor (no hoisted whole-model
    gather).

    Parametrized over --scan_unroll because a K-block scan window all-gathers
    K blocks' params at once (K x 314.6M x 4 B here) — the wgrad-fusion
    throughput lever must not silently regress the flagship memory story,
    including the structural per-block-gather-inside-the-loop property."""
    cfg = Config(image_size=224, patch_size=14, embed_dim=5120, num_heads=32,
                 num_blocks=32, num_classes=1000, batch_size=8,
                 warmup_steps=0, scan_unroll=scan_unroll).validate()
    state, lowered = _lower_train_step(cfg)
    from vitax.models.vit import expected_param_count
    n = sum(x.size for x in jax.tree.leaves(state.params))
    assert n == expected_param_count(cfg) == 10_077_917_160
    txt = lowered.as_text()
    assert "stablehlo.while" in txt  # the 32-block scan survived lowering

    compiled = lowered.compile()
    ma = compiled.memory_analysis()
    global_bytes = _state_bytes(state)
    batch_bytes = cfg.batch_size * cfg.image_size ** 2 * 3 * 4
    assert ma.argument_size_in_bytes < (global_bytes / 8 + batch_bytes) * 1.05, (
        f"10B per-device args {ma.argument_size_in_bytes/1e9:.2f} GB exceed "
        f"the shard bound {global_bytes/8/1e9:.2f} GB")
    full_param_bytes = count_params_bytes(cfg)  # 40.3 GB f32
    assert ma.temp_size_in_bytes < 0.5 * full_param_bytes, (
        f"10B temps {ma.temp_size_in_bytes/1e9:.2f} GB look like a hoisted "
        f"whole-model gather (full params {full_param_bytes/1e9:.1f} GB)")
    # and the structural scheduling property holds at this scale too
    _check_block_gathers_inside_loop(compiled.as_text())


@pytest.mark.slow
def test_60b_shape_readiness(devices8):
    """BASELINE config 5 (60B-class, reference README.md:122 "e.g. 60B"):

    1. eval_shape the full train state at 8192-dim/80-block (~64.5B params) —
       nothing materializes;
    2. every >=2D parameter's spec actually shards over a virtual 256-way fsdp
       axis (v5p-256), and the per-device state bytes fit v5p HBM (95 GB) with
       a large margin;
    3. the shard_on_cpu (host-offload) init path's host-RAM requirement is
       computed and sane to document;
    4. the train step AOT-lowers end-to-end at this shape on the test mesh.
    """
    from vitax.models.vit import expected_param_count
    from vitax.parallel.sharding import param_pspec, state_specs_like
    from vitax.parallel.sharding import _path_names

    cfg = Config(image_size=224, patch_size=14, embed_dim=8192, num_heads=64,
                 num_blocks=80, num_classes=1000, batch_size=8,
                 warmup_steps=0).validate()

    state, lowered = _lower_train_step(cfg)
    n = sum(x.size for x in jax.tree.leaves(state.params))
    assert n == expected_param_count(cfg)
    assert n > 60e9, f"{n/1e9:.1f}B params is not 60B-class"
    assert "stablehlo.while" in lowered.as_text()  # 80-block scan intact
    # compile on the 8-mesh and confirm the per-device shard bound holds at
    # this scale too (args == global state / 8; nothing materializes)
    ma = lowered.compile().memory_analysis()
    global_bytes = _state_bytes(state)
    batch_bytes = cfg.batch_size * cfg.image_size ** 2 * 3 * 4
    assert ma.argument_size_in_bytes < (global_bytes / 8 + batch_bytes) * 1.05

    # --- virtual v5p-256: specs computed analytically, no 256 devices needed
    VIRT = (1, 256, 1, 1, 1, 1)  # (dp, fsdp, tp, sp, pp, ep)
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    pspecs = {}
    for path, leaf in flat:
        spec = param_pspec(path, leaf.shape, cfg, VIRT, cfg.scan_blocks)
        pspecs[_path_names(path)] = spec
        if leaf.ndim >= 2:  # every matrix/stacked tensor must shard
            assert "fsdp" in tuple(spec), (
                f"{_path_names(path)} {leaf.shape} unsharded at fsdp=256")

    def shard_bytes(leaf, spec):
        denom = 1
        for axis in tuple(spec):
            if axis == "fsdp":
                denom *= 256
        return leaf.size * leaf.dtype.itemsize / denom

    # state = f32 params + AdamW mu + nu (all param-shaped, same specs —
    # state_specs_like) + scalar step
    params_tree = state.params
    spec_tree = jax.tree_util.tree_map_with_path(
        lambda path, leaf: pspecs[_path_names(path)], params_tree)
    state_specs = state_specs_like(state, spec_tree)
    per_device = sum(
        shard_bytes(leaf, spec) for (path, leaf), (_, spec) in zip(
            jax.tree_util.tree_flatten_with_path(state)[0],
            jax.tree_util.tree_flatten_with_path(
                state_specs, is_leaf=lambda x: isinstance(
                    x, jax.sharding.PartitionSpec))[0]))
    V5P_HBM = 95e9
    assert per_device < 0.10 * V5P_HBM, (
        f"per-device 60B state {per_device/1e9:.1f} GB leaves too little HBM "
        "headroom for activations/temps on v5p")

    # --- shard_on_cpu path: full f32 params materialize in host RAM first
    # (reference run_vit_training.py:175-181 semantics; README.md:122 tcmalloc
    # note). Documented in BASELINE.md row 5; born-sharded init needs none.
    host_bytes = sum(x.size * x.dtype.itemsize
                     for x in jax.tree.leaves(state.params))
    assert 2.3e11 < host_bytes < 3.0e11  # ~258 GB — host-RAM sized, not HBM


@pytest.mark.slow
def test_10b_slice_fits_single_chip_hbm(devices8):
    """The 10b_slice bench preset's claim — "params+moments+activations stay
    under 16 GB HBM" on one v5e chip (bench.py train_presets) — asserted from
    the compiled single-device step's memory analysis instead of a comment.

    Resident bytes = arguments (params + mu + nu + batch) + temps
    (activations, grads, stacking buffers) + any output bytes NOT aliased
    back onto donated inputs — so the check also fails if state donation
    ever breaks (vitax/train/step.py donate_argnums).

    Caveat: this compiles on the CPU test backend with the dense jnp
    attention; TPU layout padding and Pallas scratch can shift temps by some
    margin — the on-chip bench run is the ground truth, this test is the
    regression guard (it caught the depth-4 preset overflowing by 9+ GB).
    The dense-attention divergence is why the batch is pinned to the
    flagship's pod operating point (8/chip, the reference's per-core batch)
    rather than the preset's default: the preset ships the measured
    single-chip throughput frontier (64/chip, fused kernel), which fits and
    runs on the real chip but whose dense-path CPU estimate inflates to
    ~29 GB of score tensors the Pallas kernel never materializes."""
    from bench import default_remat_policy, train_presets

    # the preset's own batch is chip-proven, not CPU-estimable: pin it here
    # so a future bump past the measured OOM frontier (96/chip OOMs on v5e)
    # forces an on-chip re-measurement instead of silently shipping
    assert train_presets(1)["10b_slice"]["batch_size"] == 64, (
        "10b_slice preset batch changed — re-run bench.py --preset 10b_slice "
        "on the TPU to re-prove the HBM fit, then update this pin")
    kw = train_presets(1)["10b_slice"] | dict(batch_size=8)
    cfg = Config(num_classes=1000, warmup_steps=0,
                 # allow_tuned=False: the HBM byte thresholds below were
                 # measured under the pinned reference policy — a TUNED.json
                 # policy flip must not silently change what this guard pins
                 remat_policy=default_remat_policy("10b_slice",
                                                   allow_tuned=False),
                 fsdp_size=1, **kw).validate()
    state, lowered = _lower_train_step(cfg, n_devices=1)
    compiled = lowered.compile()
    ma = compiled.memory_analysis()
    unaliased_out = ma.output_size_in_bytes - ma.alias_size_in_bytes
    resident = (ma.argument_size_in_bytes + ma.temp_size_in_bytes
                + unaliased_out)
    V5E_HBM = 16e9
    assert resident < V5E_HBM, (
        f"10b_slice single-chip resident {resident/1e9:.2f} GB exceeds v5e "
        f"HBM (args {ma.argument_size_in_bytes/1e9:.2f} + temps "
        f"{ma.temp_size_in_bytes/1e9:.2f} + unaliased out "
        f"{unaliased_out/1e9:.2f} — small metrics outputs are expected here; "
        f"a STATE-SIZED value (~{_state_bytes(state)/1e9:.1f} GB) means "
        f"donation broke)")
    # arguments alone are the f32 state: params + 2 AdamW moments + batch
    assert ma.argument_size_in_bytes > 0.9 * _state_bytes(state)


@pytest.mark.slow
def test_10b_shape_lowers_under_pipeline_fsdp(devices8):
    """The flagship composes with pipeline parallelism for pods: the full
    10.078B shape AOT-lowers and compiles on a pp2 x fsdp4 mesh (16 layers
    per stage, ZeRO-3 shards gathered just-in-time inside the GPipe body —
    vitax/parallel/pipeline.py), with the same per-device memory bet: the
    compiled arguments are one (pp x fsdp)-shard of the state, and temps
    stay far below the whole 40.3 GB parameter tensor. Guards the real
    hazard this test caught: XLA LICM hoisting the per-block gathers out of
    the layer scan, materializing the whole stage (28.7 GB vs 12.6 GB
    temps). The 1F1B schedule is excluded HERE because this test compiles on
    the CPU backend, where its per-block remat stays disabled (the jax-0.9
    CPU compiler intermittently aborts on the rematted engine —
    pipeline_1f1b.py `_remat_blocks`); the TPU-target proof of 1F1B's
    GPipe-level temps is tools/aot_topology.py --configs 10b_1f1b
    (AOT_TOPOLOGY.json), compiled against a v5p topology."""
    cfg = Config(image_size=224, patch_size=14, embed_dim=5120, num_heads=32,
                 num_blocks=32, num_classes=1000, batch_size=8,
                 warmup_steps=0, pp_size=2, fsdp_size=4, dp_size=1,
                 remat_policy="none_saveable").validate()
    state, lowered = _lower_train_step(cfg)
    from vitax.models.vit import expected_param_count
    n = sum(x.size for x in jax.tree.leaves(state.params))
    assert n == expected_param_count(cfg) == 10_077_917_160

    compiled = lowered.compile()
    ma = compiled.memory_analysis()
    global_bytes = _state_bytes(state)
    batch_bytes = cfg.batch_size * cfg.image_size ** 2 * 3 * 4
    # blocks shard over pp AND fsdp (8-way for block state); embed/head
    # shard over fsdp only (4-way) — bound by the looser 4-way shard plus
    # slack rather than exactly global/8
    assert ma.argument_size_in_bytes < (global_bytes / 4 + batch_bytes) * 1.05, (
        f"10B pp x fsdp per-device args {ma.argument_size_in_bytes/1e9:.2f} "
        f"GB exceed the 4-way shard bound {global_bytes/4/1e9:.2f} GB")
    full_param_bytes = count_params_bytes(cfg)  # 40.3 GB f32
    assert ma.temp_size_in_bytes < 0.5 * full_param_bytes, (
        f"10B pp temps {ma.temp_size_in_bytes/1e9:.2f} GB look like a "
        f"hoisted whole-model gather ({full_param_bytes/1e9:.1f} GB full)")


@pytest.mark.slow
def test_topology_aot_kernel_true_smoke():
    """Round-5 capability pin: the FULL train step with REAL Mosaic kernels
    (VITAX_FORCE_MOSAIC, not interpret mode) AOT-compiles against a real
    TPU topology target with no hardware attached — the mechanism behind
    AOT_TOPOLOGY.json's flagship rows (tools/aot_topology.py). Runs in a
    subprocess (libtpu allows one process; skip cleanly on lock contention
    with a concurrent topology compile)."""
    import subprocess

    code = """
import os, sys
sys.path.insert(0, '.')
from vitax.platform import force_cpu_if_requested
force_cpu_if_requested()
import jax, jax.numpy as jnp
from jax.experimental import topologies
from jax.sharding import NamedSharding
from vitax.config import Config
from vitax.models import build_model
from vitax.ops.attention import make_attention_impl
from vitax.parallel.mesh import batch_pspec, build_mesh
from vitax.train.state import build_optimizer, make_train_state
from vitax.train.step import make_train_step

td = topologies.get_topology_desc('v5e:2x4', 'tpu')
cfg = Config(image_size=224, patch_size=16, embed_dim=128, num_heads=2,
             num_blocks=2, num_classes=16, batch_size=16,
             fsdp_size=-1).validate()
mesh = build_mesh(cfg, devices=list(td.devices))
impl = make_attention_impl(cfg, mesh, force_tpu_kernels=True)
assert impl is not None, 'kernel selection bailed'
model = build_model(cfg, attention_impl=impl)
tx, _ = build_optimizer(cfg, max_iteration=10)
state, sspecs, _ = make_train_state(cfg, model, tx, mesh,
                                    jax.random.key(0), materialize=False)
step = make_train_step(cfg, model, tx, mesh, sspecs)
sh = NamedSharding(mesh, batch_pspec())
batch = {'image': jax.ShapeDtypeStruct((16, 224, 224, 3), jnp.float32,
                                       sharding=sh),
         'label': jax.ShapeDtypeStruct((16,), jnp.int32, sharding=sh)}
key = jax.eval_shape(lambda: jax.random.key(0))
compiled = step.lower(state, batch, key).compile()
ma = compiled.memory_analysis()
assert ma.argument_size_in_bytes > 0
print('AOT_OK', ma.temp_size_in_bytes)
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu", VITAX_FORCE_MOSAIC="1")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=420, env=env,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))))
    if r.returncode != 0 and "libtpu_lockfile" in (r.stderr or ""):
        pytest.skip("libtpu lockfile held by a concurrent topology compile")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "AOT_OK" in r.stdout, r.stdout
