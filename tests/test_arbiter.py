"""vitax.arbiter: chip-ledger arbitration for co-located train + serve.

Fast tier pins the whole subsystem socketless (injected clocks, fake
procs, recorded seams — the test_autoscale.py discipline): the versioned
host ledger with atomic persistence and restart recovery, the hysteretic
borrow/return policy in all three modes, the TrainDirector's
drain-then-relaunch resize over supervise.topology_env, the Arbiter's
borrow/return executor with rollback and deny-dedupe, the train-side
ArbiterReporter heartbeat, the real-HTTP daemon surface, a two-agent
placement soak (round-robin boots, AgentFullError on a full pod,
release-on-drain slot accounting), and the metrics_report / serve_bench
schema growth. One `slow` drill runs the acceptance scenario end to end:
a chaos-armed serve_bench ramp against a live 2-process fake-data
training job; the surge borrows one host (agreed-preemption drain, 2->1
elastic resume from peer stores with zero Orbax reads, replica
provisioned + adopted), the ramp ends, the host returns and training
re-expands to 2 — all visible in one metrics_report.
"""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from vitax.arbiter import Arbiter, ArbiterPolicy, HostLedger, TrainDirector
from vitax.arbiter.daemon import (JsonlRecorder, free_port, start_arbiter,
                                  stop_arbiter)
from vitax.arbiter.ledger import LEDGER_SCHEMA
from vitax.arbiter.policy import POLICIES, _QUIET_MULT
from vitax.config import Config
from vitax.serve.fleet import (AdmissionController, Autoscaler,
                               PlacementAgent, PlacementClient,
                               ReplicaManager, Router, start_agent,
                               start_router, stop_agent, stop_router)
from vitax.serve.fleet.placement import AgentFullError
from vitax.train.control import ArbiterReporter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TRAIN_COUNTS = {"train": 2, "serve": 0, "free": 0}


def _import_tool(name):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


class DummyRecorder:
    def __init__(self):
        self.events = []

    def event(self, kind, **payload):
        self.events.append((kind, payload))

    def of(self, event):
        return [p for k, p in self.events
                if k == "arbiter" and p.get("event") == event]


class FakeProc:
    """Popen stand-in; exits with `exit_code` on the first SIGTERM."""

    def __init__(self, exit_code=0, on_signal=None):
        self.rc = None
        self.signals = []
        self._exit_code = exit_code
        self._on_signal = on_signal

    def poll(self):
        return self.rc

    def send_signal(self, sig):
        self.signals.append(sig)
        if self._on_signal is not None:
            self._on_signal(self)
        self.rc = self._exit_code

    def kill(self):
        self.rc = -9


class FakeTrain:
    """TrainDirector stand-in recording resize() calls."""

    term_grace_s = 5.0

    def __init__(self, n=2):
        self.n = n
        self.resizes = []
        self.is_healthy = True

    @property
    def process_count(self):
        return self.n

    def alive(self):
        return self.n

    def healthy(self):
        return self.is_healthy

    def resize(self, n):
        self.resizes.append(n)
        self.n = n
        return {"to_processes": n}


def _never(url, timeout):
    raise ConnectionError("unreachable")


# --- host ledger -------------------------------------------------------------

def test_ledger_seed_counts_and_owner():
    led = HostLedger(["h0", "h1"], owner="train")
    assert led.counts() == {"train": 2, "serve": 0, "free": 0}
    assert led.owner_of("h0") == "train"
    assert led.owner_of("nope") is None
    assert led.version == 2
    assert led.recovered is False
    snap = led.snapshot()
    assert snap["schema"] == LEDGER_SCHEMA
    assert set(snap["hosts"]) == {"h0", "h1"}


def test_ledger_assign_bumps_version_and_lease():
    led = HostLedger(["h0", "h1"])
    lease = led.assign("h1", "serve")
    assert lease["owner"] == "serve"
    assert lease["version"] == lease["lease_version"] == 3
    assert lease["host"] == "h1"
    assert led.counts() == {"train": 1, "serve": 1, "free": 0}
    with pytest.raises(KeyError):
        led.assign("nope", "serve")
    with pytest.raises(AssertionError):
        led.assign("h0", "cryptominer")


def test_ledger_hosts_owned_is_lease_ordered():
    """Oldest lease first; the borrow path peels hosts_owned()[-1], so a
    host that bounced through serve and back is the NEXT borrow victim."""
    led = HostLedger(["h0", "h1", "h2"])
    assert led.hosts_owned("train") == ["h0", "h1", "h2"]
    led.assign("h0", "serve")
    led.assign("h0", "train")   # h0 now holds the newest train lease
    assert led.hosts_owned("train") == ["h1", "h2", "h0"]
    assert led.hosts_owned("serve") == []


def test_ledger_persists_and_recovers(tmp_path):
    path = str(tmp_path / "ledger.json")
    led = HostLedger(["h0", "h1"], path=path)
    led.assign("h1", "serve")
    with open(path, encoding="utf-8") as f:
        on_disk = json.load(f)
    assert on_disk["schema"] == LEDGER_SCHEMA
    assert on_disk["version"] == 3
    assert on_disk["hosts"]["h1"]["owner"] == "serve"
    assert not list(tmp_path.glob("*.tmp.*"))  # atomic: no torn temps

    # a restarted arbiter re-derives the exact granted state
    led2 = HostLedger(path=path)
    assert led2.recovered is True
    assert led2.version == 3
    assert led2.owner_of("h1") == "serve"
    assert led2.counts() == led.counts()


def test_ledger_recovery_merges_new_hosts(tmp_path):
    path = str(tmp_path / "ledger.json")
    HostLedger(["h0"], path=path).assign("h0", "serve")
    led = HostLedger(["h0", "h1"], path=path)
    assert led.recovered is True
    assert led.owner_of("h0") == "serve"   # recovered lease wins
    assert led.owner_of("h1") == "train"   # new host seeded fresh


def test_ledger_corrupt_file_starts_fresh(tmp_path):
    path = str(tmp_path / "ledger.json")
    with open(path, "w", encoding="utf-8") as f:
        f.write("{not json")
    led = HostLedger(["h0"], path=path)
    assert led.recovered is False
    assert led.owner_of("h0") == "train"
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"hosts": [], "version": "x"}, f)  # wrong shapes
    assert HostLedger(["h0"], path=path).recovered is False


def test_ledger_in_memory_mode(tmp_path):
    led = HostLedger(["h0"])  # path="" -> no persistence
    led.assign("h0", "free")
    assert led.counts()["free"] == 1
    assert not list(tmp_path.iterdir())


# --- policy ------------------------------------------------------------------

def test_policy_dwell_then_borrow_then_cooldown():
    pol = ArbiterPolicy("slo_bounded", dwell_s=2.0, cooldown_s=5.0)
    sig = {"shed_rate_per_s": 3.0}
    d = pol.tick(sig, TRAIN_COUNTS, 0, 0.0)
    assert (d.action, d.reason, d.deny) == (None, "dwell", False)
    d = pol.tick(sig, TRAIN_COUNTS, 0, 2.0)
    assert (d.action, d.reason) == ("borrow", "shed_rate")
    pol.action_taken(2.0)   # executed: cooldown until 7.0, streaks reset
    assert pol.tick(sig, TRAIN_COUNTS, 1, 2.5).reason == "dwell"
    d = pol.tick(sig, TRAIN_COUNTS, 1, 4.5)   # dwell met, cooldown open
    assert (d.reason, d.deny) == ("cooldown", True)
    assert pol.tick(sig, TRAIN_COUNTS, 1, 7.5).action == "borrow"


def test_policy_deny_reasons_ordered():
    pol = ArbiterPolicy("slo_bounded", dwell_s=0.0, min_train_hosts=1)
    sig = {"shed_rate_per_s": 9.0, "train_progressing": False}
    # the floor outranks everything: a one-host train job is never drained
    one = {"train": 1, "serve": 1, "free": 0}
    d = pol.tick(sig, one, 1, 0.0)
    assert (d.reason, d.deny) == ("min_train_hosts", True)
    # above the floor, a stalled step loop blocks the drain
    d = pol.tick(sig, TRAIN_COUNTS, 0, 1.0)
    assert (d.reason, d.deny) == ("train_stalled", True)


def test_policy_train_priority_requires_backed_escalation():
    pol = ArbiterPolicy("train_priority", dwell_s=0.0)
    assert pol.tick({"shed_rate_per_s": 9.0}, TRAIN_COUNTS,
                    0, 0.0).reason == "idle"
    assert pol.tick({"escalations": 1}, TRAIN_COUNTS, 0, 1.0).reason == "idle"
    d = pol.tick({"escalations": 1, "shed_rate_per_s": 9.0},
                 TRAIN_COUNTS, 0, 2.0)
    assert (d.action, d.reason) == ("borrow", "escalation")


def test_policy_quiet_dwell_multiples():
    for name in POLICIES:
        pol = ArbiterPolicy(name, dwell_s=2.0)
        assert pol.quiet_dwell_s == 2.0 * _QUIET_MULT[name], name
    assert ArbiterPolicy(dwell_s=2.0, quiet_dwell_s=1.5).quiet_dwell_s == 1.5


def test_policy_return_after_quiet_streak():
    pol = ArbiterPolicy("slo_bounded", dwell_s=1.0)   # quiet dwell 2.0
    assert pol.tick({}, TRAIN_COUNTS, 0, 0.0).reason == "idle"
    assert pol.tick({}, TRAIN_COUNTS, 1, 0.0).reason == "quiet_dwell"
    assert pol.tick({}, TRAIN_COUNTS, 1, 1.5).reason == "quiet_dwell"
    d = pol.tick({}, TRAIN_COUNTS, 1, 2.0)
    assert (d.action, d.reason) == ("return", "pressure_cleared")
    # pressure mid-streak resets the quiet clock
    pol.tick({"predicted_wait_overshoot": True}, TRAIN_COUNTS, 1, 2.5)
    assert pol.tick({}, TRAIN_COUNTS, 1, 3.0).reason == "quiet_dwell"


def test_policy_set_policy_resets_streaks_and_snapshot():
    pol = ArbiterPolicy("slo_bounded", dwell_s=2.0, cooldown_s=5.0)
    sig = {"shed_rate_per_s": 9.0}
    pol.tick(sig, TRAIN_COUNTS, 0, 0.0)
    pol.set_policy("serve_priority")
    assert pol.tick(sig, TRAIN_COUNTS, 0, 3.0).reason == "dwell"  # re-earned
    assert pol.snapshot() == {
        "policy": "serve_priority", "min_train_hosts": 1, "dwell_s": 2.0,
        "quiet_dwell_s": 8.0, "cooldown_s": 5.0, "cooldown_until": 0.0}


# --- TrainDirector -----------------------------------------------------------

def mk_director(exit_code=0, argv=("train.py",), order=None):
    spawned = []
    order = order if order is not None else []

    def spawn(child_argv, env, tag):
        proc = FakeProc(exit_code,
                        on_signal=lambda p: order.append(spawned_index(p)))
        spawned.append({"argv": list(child_argv), "env": env, "tag": tag,
                        "proc": proc})
        return proc

    def spawned_index(proc):
        return next(i for i, s in enumerate(spawned) if s["proc"] is proc)

    director = TrainDirector(list(argv), term_grace_s=2.0,
                             env={"BASE": "1"}, spawn=spawn,
                             sleep=lambda s: None, port_fn=lambda: 4321)
    return director, spawned, order


def test_director_start_builds_topology_env():
    director, spawned, _ = mk_director()
    director.start(2)
    assert [s["tag"] for s in spawned] == ["g0_p0", "g0_p1"]
    for pid, s in enumerate(spawned):
        assert s["env"]["JAX_COORDINATOR_ADDRESS"] == "localhost:4321"
        assert s["env"]["JAX_NUM_PROCESSES"] == "2"
        assert s["env"]["JAX_PROCESS_ID"] == str(pid)
        assert s["env"]["BASE"] == "1"
        # ensure_auto_resume: a relaunch must adopt the committed epoch
        assert s["argv"][-2:] == ["--resume_epoch", "-1"]
    assert director.process_count == 2
    assert director.alive() == 2 and director.healthy()


def test_director_resize_signals_all_before_waiting():
    """The preemption fold needs every rank alive to agree: drain SIGTERMs
    ALL processes first, then waits each out; the relaunch drops the
    coordinator vars for a 1-process topology."""
    director, spawned, order = mk_director()
    director.start(2)
    out = director.resize(1)
    assert out == {"from_processes": 2, "to_processes": 1,
                   "exit_codes": [0, 0]}
    # first wave hits both procs before any terminate-wait re-signals
    assert order[:2] == [0, 1]
    assert director.process_count == 1 and director.resizes_total == 1
    new = spawned[2]
    assert new["tag"] == "g1_p0"
    assert "JAX_NUM_PROCESSES" not in new["env"]
    assert "JAX_COORDINATOR_ADDRESS" not in new["env"]


def test_director_resize_relaunches_old_count_on_dirty_exit():
    """A dirty drain raises AND restores the previous topology: the last
    committed checkpoint is intact, and a director left at zero processes
    would make every later resize compute from 0."""
    director, spawned, _ = mk_director(exit_code=1)
    director.start(2)
    with pytest.raises(RuntimeError, match="exit codes.*relaunched at 2"):
        director.resize(1)
    assert director.process_count == 2   # relaunched, not left empty
    assert [s["tag"] for s in spawned[2:]] == ["g1_p0", "g1_p1"]
    assert director.last_start_t is not None


def test_director_healthy_sees_dead_rank():
    director, spawned, _ = mk_director()
    director.start(2)
    spawned[0]["proc"].rc = 1   # one rank crashed
    assert director.alive() == 1
    assert director.healthy() is False


# --- arbiter executor (socketless) -------------------------------------------

def mk_arbiter(hosts=("h0", "h1"), n_train=2, policy="slo_bounded",
               dwell_s=0.0, cooldown_s=0.0, quiet_dwell_s=0.0,
               min_train_hosts=1, clock=None, **seams):
    ledger = HostLedger(list(hosts))
    pol = ArbiterPolicy(policy, min_train_hosts=min_train_hosts,
                        dwell_s=dwell_s, cooldown_s=cooldown_s,
                        quiet_dwell_s=quiet_dwell_s)
    train = FakeTrain(n_train)
    rec = DummyRecorder()
    arb = Arbiter(ledger, pol, train=train, recorder=rec,
                  clock=clock or (lambda: 0.0), **seams)
    return arb, train, rec


def test_arbiter_borrow_then_return_full_sequence():
    order = []
    arb, train, rec = mk_arbiter(
        provision=lambda host: (order.append(("provision", host))
                                or "http://b:1"),
        release=lambda host, url: order.append(("release", host, url)),
        fleet_adopt=lambda url: order.append(("adopt", url)),
        fleet_release=lambda url: order.append(("fleet_release", url)),
        signals_fn=lambda: {"shed_rate_per_s": 9.0})
    assert arb.tick(now=0.0) == "borrow"
    # serve side engaged in order, against the NEWEST train lease
    assert order == [("provision", "h1"), ("adopt", "http://b:1")]
    assert train.resizes == [1]
    assert arb.ledger.owner_of("h1") == "serve"
    m = arb.metrics()
    assert m["borrows_total"] == 1
    assert m["borrowed"] == {"h1": "http://b:1"}
    assert [p["event"] for p in rec.of("borrow")] == ["borrow"]
    assert rec.of("borrow")[0]["ledger_version"] == arb.ledger.version

    # pressure gone: drain the loan back in reverse order of acquisition
    order.clear()
    arb._signals_fn = lambda: {}
    assert arb.tick(now=1.0) == "return"
    assert order == [("fleet_release", "http://b:1"),
                     ("release", "h1", "http://b:1")]
    assert train.resizes == [1, 2]
    assert arb.ledger.owner_of("h1") == "train"
    assert arb.metrics()["returns_total"] == 1
    assert arb.metrics()["borrowed"] == {}


def test_arbiter_borrow_rollback_on_provision_failure():
    def provision(host):
        raise RuntimeError("agent down")

    arb, train, rec = mk_arbiter(
        provision=provision,
        signals_fn=lambda: {"shed_rate_per_s": 9.0})
    assert arb.tick(now=0.0) is None
    # unwound: ledger restored, training re-expanded, loudly reported
    assert arb.ledger.owner_of("h1") == "train"
    assert train.resizes == [1, 2]
    assert arb.metrics()["borrows_total"] == 0
    fails = rec.of("borrow_failed")
    assert fails and "RuntimeError: agent down" in fails[0]["detail"]


def test_arbiter_borrow_rollback_releases_provisioned_replica():
    order = []
    arb, train, _ = mk_arbiter(
        provision=lambda host: "http://b:1",
        release=lambda host, url: order.append(("release", host, url)),
        fleet_adopt=lambda url: (_ for _ in ()).throw(OSError("router")),
        signals_fn=lambda: {"shed_rate_per_s": 9.0})
    assert arb.tick(now=0.0) is None
    # the orphaned replica is released before the ledger flips back
    assert order == [("release", "h1", "http://b:1")]
    assert arb.ledger.owner_of("h1") == "train"
    assert train.resizes == [1, 2]


def test_arbiter_deny_dedupe_and_cooldown_after_failure():
    attempts = []

    def provision(host):
        attempts.append(host)
        if len(attempts) == 1:
            raise RuntimeError("first attempt dies")
        return "http://b:1"

    arb, _, rec = mk_arbiter(provision=provision, cooldown_s=10.0,
                             signals_fn=lambda: {"shed_rate_per_s": 9.0})
    assert arb.tick(now=0.0) is None          # borrow_failed -> cooldown
    assert arb.tick(now=1.0) is None          # denied: cooldown
    assert arb.tick(now=2.0) is None          # same reason: deduped
    assert arb.metrics()["denies_total"] == 1
    assert len(rec.of("deny")) == 1
    assert rec.of("deny")[0]["reason"] == "cooldown"
    assert arb.tick(now=11.0) == "borrow"     # cooldown over: retried
    assert arb.metrics()["borrows_total"] == 1


def test_arbiter_escalation_drives_borrow_and_clears():
    arb, _, rec = mk_arbiter(provision=lambda host: "http://b:1")
    out = arb.request_capacity("autoscaler_max")
    assert out == {"accepted": True, "status": "pending"}
    assert arb.metrics()["requests_total"] == 1
    assert rec.of("request")[0]["reason"] == "autoscaler_max"
    assert arb.tick(now=0.0) == "borrow"
    assert rec.of("borrow")[0]["reason"] == "escalation"
    # the escalation was consumed: next tick sees quiet and returns
    assert arb.tick(now=1.0) == "return"


def test_arbiter_return_failure_keeps_loan_then_retries():
    state = {"fail": True}

    def fleet_release(url):
        if state["fail"]:
            raise OSError("router drain wedged")

    arb, train, rec = mk_arbiter(
        provision=lambda host: "http://b:1", fleet_release=fleet_release,
        signals_fn=lambda: {"shed_rate_per_s": 9.0})
    assert arb.tick(now=0.0) == "borrow"
    arb._signals_fn = lambda: {}
    assert arb.tick(now=1.0) is None           # return failed: loan kept
    assert rec.of("return_failed")
    assert arb.metrics()["borrowed"] == {"h1": "http://b:1"}
    assert arb.metrics()["returns_total"] == 0
    state["fail"] = False
    assert arb.tick(now=2.0) == "return"
    assert train.resizes == [1, 2]


def test_arbiter_telemetry_outranks_director_liveness():
    """A fresh step heartbeat proves progress even when the director's
    process view says unhealthy (mid-recovery); a stale one falls back."""
    clock = lambda: 100.0  # noqa: E731 — trivially injected clock
    arb, train, rec = mk_arbiter(
        hosts=("h0", "h1", "h2"), n_train=3, clock=clock,
        provision=lambda host: "http://b:1", cooldown_s=0.0,
        signals_fn=lambda: {"shed_rate_per_s": 9.0})
    train.is_healthy = False
    assert arb.tick(now=0.0) is None
    assert rec.of("deny")[0]["reason"] == "train_stalled"

    arb.observe_train({"step": 7, "epoch": 0, "process_count": 3,
                       "junk": "dropped"})
    tel = arb.metrics()["train_telemetry"]
    assert tel["step"] == 7 and "junk" not in tel
    assert tel["observed_at"] == 100.0
    assert arb.tick(now=110.0) == "borrow"     # heartbeat 10s old: fresh
    assert arb.tick(now=200.0) is None         # 100s old: stale again
    assert rec.of("deny")[-1]["reason"] == "train_stalled"


def test_arbiter_heartbeat_must_postdate_generation():
    """After a resize, the PREVIOUS generation's heartbeat no longer
    vouches for progress: the relaunched ranks must post a step of their
    own before any further drain — a booting rank has no preemption
    handler installed and would die dirty on SIGTERM."""
    t = {"now": 100.0}
    arb, train, rec = mk_arbiter(
        hosts=("h0", "h1", "h2"), n_train=3, cooldown_s=0.0,
        clock=lambda: t["now"], provision=lambda host: "http://b:1",
        signals_fn=lambda: {"shed_rate_per_s": 9.0})
    arb.observe_train({"step": 9, "epoch": 0, "process_count": 3})
    t["now"] = 105.0                         # clock at the resize moment
    assert arb.tick(now=110.0) == "borrow"   # stamps _gen_start_t = 105
    assert arb._gen_start_t == 105.0
    assert arb.tick(now=111.0) is None       # fresh, but pre-resize post
    deny = rec.of("deny")[-1]
    assert deny["reason"] == "train_stalled"
    # the deny carries its inputs: the heartbeat predates the resize by 5s
    assert deny["generation_lag_s"] == 5.0
    assert deny["telemetry_age_s"] == 11.0
    t["now"] = 120.0
    arb.observe_train({"step": 1, "epoch": 0, "process_count": 2})
    assert arb.tick(now=121.0) == "borrow"  # the new generation reported


def test_policy_return_blocked_while_train_stalled():
    """The return's re-expand drains the current generation too, so a
    stalled (or still-booting) train job defers the return as well."""
    pol = ArbiterPolicy("slo_bounded", dwell_s=1.0)   # quiet dwell 2.0
    pol.tick({}, TRAIN_COUNTS, 1, 0.0)
    d = pol.tick({"train_progressing": False}, TRAIN_COUNTS, 1, 2.5)
    assert (d.reason, d.deny) == ("train_stalled", True)
    d = pol.tick({}, TRAIN_COUNTS, 1, 3.0)
    assert (d.action, d.reason) == ("return", "pressure_cleared")


def test_arbiter_metrics_shape_and_policy_gate():
    arb, _, rec = mk_arbiter()
    assert set(arb.metrics()) == {
        "borrows_total", "returns_total", "denies_total", "requests_total",
        "borrowed", "last_event", "train_telemetry", "policy", "ledger",
        "train_processes", "train_alive"}
    with pytest.raises(ValueError, match="unknown policy"):
        arb.set_policy("cryptomining")
    assert arb.set_policy("serve_priority") == {"policy": "serve_priority"}
    assert arb.metrics()["policy"]["policy"] == "serve_priority"
    assert rec.of("policy_change")[0]["policy"] == "serve_priority"


# --- train-side heartbeat (ArbiterReporter) ----------------------------------

def test_arbiter_reporter_posts_latest_and_dedupes():
    posts = []
    reporter = ArbiterReporter(
        "http://a:1/", process_count=2,
        http_json=lambda url, payload, timeout: posts.append((url, payload)))
    assert reporter.post_once() is False       # nothing observed yet
    reporter.update(5, 0)
    reporter.update(6, 0)                      # only the LATEST posts
    assert reporter.post_once() is True
    assert posts == [("http://a:1/telemetry",
                      {"step": 6, "epoch": 0, "process_count": 2})]
    assert reporter.post_once() is False       # unchanged: deduped
    reporter.update(7, 0)
    assert reporter.post_once() is True
    assert reporter.posts_total == 2
    # the heartbeat refresh: an UNCHANGED snapshot still re-posts on
    # force — a slow trainer must not read as a stalled one
    assert reporter.post_once() is False
    assert reporter.post_once(force=True) is True
    assert posts[-1][1] == {"step": 7, "epoch": 0, "process_count": 2}
    assert reporter.posts_total == 3


def test_arbiter_reporter_swallows_transport_failures():
    reporter = ArbiterReporter("http://a:1", http_json=_never)
    reporter.update(1, 0)
    assert reporter.post_once() is False
    assert reporter.post_failures == 1
    assert reporter.posts_total == 0


def test_arbiter_reporter_thread_flushes_on_stop():
    posts = []
    reporter = ArbiterReporter(
        "http://a:1", interval_s=30.0,   # too slow to fire: stop() flushes
        http_json=lambda url, payload, timeout: posts.append(payload))
    reporter.start()
    reporter.update(3, 1)
    reporter.stop()
    assert posts == [{"step": 3, "epoch": 1, "process_count": 1}]
    assert not any(t.name == "vitax-arbiter-report"
                   for t in threading.enumerate())


# --- daemon HTTP surface -----------------------------------------------------

def _http(url, payload=None, timeout=10.0):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


def test_arbiter_http_surface():
    ledger = HostLedger(["h0", "h1"])
    arb = Arbiter(ledger, ArbiterPolicy(dwell_s=3600.0), interval_s=3600.0)
    httpd = start_arbiter(arb, 0)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        assert _http(base + "/healthz") == {"status": "ok"}
        led = _http(base + "/ledger")
        assert led["schema"] == LEDGER_SCHEMA and set(led["hosts"]) == {
            "h0", "h1"}
        out = _http(base + "/request", {"reason": "surge"})
        assert out == {"accepted": True, "status": "pending"}
        assert _http(base + "/telemetry",
                     {"step": 3, "epoch": 0,
                      "process_count": 2}) == {"ok": True}
        m = _http(base + "/metrics")
        assert m["requests_total"] == 1
        assert m["train_telemetry"]["step"] == 3
        # POST /policy is an operator action: hard 403 until opted in
        with pytest.raises(urllib.error.HTTPError) as err:
            _http(base + "/policy", {"policy": "serve_priority"})
        assert err.value.code == 403
        arb.allow_admin = True
        assert _http(base + "/policy", {"policy": "serve_priority"}) == {
            "policy": "serve_priority"}
        with pytest.raises(urllib.error.HTTPError) as err:
            _http(base + "/policy", {"policy": "bogus"})
        assert err.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as err:
            _http(base + "/nope")
        assert err.value.code == 404
    finally:
        stop_arbiter(httpd, arb)


# --- two-agent placement soak (multi-host pod) -------------------------------

def _mk_loopback_agent(max_slots=1):
    """A real-HTTP placement agent whose manager spawns FakeProcs (no
    health loop verdicts: http_get always fails, states stay STARTING —
    slot accounting is what this soak pins)."""
    spawned = []

    def spawn(argv):
        proc = FakeProc()
        spawned.append((argv, proc))
        return proc

    manager = ReplicaManager(spawn=spawn, http_get=_never,
                             health_interval_s=0.05)
    agent = PlacementAgent(advertise_host="127.0.0.1", base_port=9300,
                           manager=manager, max_slots=max_slots)
    httpd = start_agent(agent, port=0)
    client = PlacementClient(
        f"http://127.0.0.1:{httpd.server_address[1]}")
    return agent, httpd, client, spawned


def test_two_agent_soak_round_robin_full_pod_and_release():
    """Two loopback `fleet.agent` instances, one slot each: round-robin
    boots land one replica per host, a third provision 409s on BOTH
    agents (AgentFullError — the autoscaler's escalation trigger), and a
    release-on-drain frees the slot for the next provision. Slot
    accounting (/healthz "slots") pins every transition."""
    agent_a, httpd_a, client_a, spawned_a = _mk_loopback_agent()
    agent_b, httpd_b, client_b, spawned_b = _mk_loopback_agent()
    clients = [client_a, client_b]

    def spawn_replica(i, start):
        # the fleet CLI's placement loop: round-robin start, try every
        # agent, surface AgentFullError only when the whole pod is full
        last_full = None
        for k in range(len(clients)):
            client = clients[(start + k) % len(clients)]
            try:
                return client, client.provision(["--dtype", "float32"],
                                                name=f"replica_{i}")
            except AgentFullError as e:
                last_full = e
        raise last_full

    try:
        # boot: one replica per agent
        used_a = spawn_replica(0, 0)
        used_b = spawn_replica(1, 1)
        assert used_a[0] is client_a and used_b[0] is client_b
        assert agent_a.manager.find("replica_0") is not None
        assert agent_b.manager.find("replica_1") is not None
        assert client_a.healthz()["slots"] == {"used": 1, "max": 1}
        assert client_b.healthz()["slots"] == {"used": 1, "max": 1}

        # the pod is full: every agent 409s, the loop surfaces the error
        with pytest.raises(AgentFullError):
            spawn_replica(2, 0)
        # and the wire contract really is a 409, not a generic failure
        with pytest.raises(urllib.error.HTTPError) as err:
            client_a._http_json(client_a.agent_url + "/provision",
                                {"argv": ["--x", "y"]}, 5.0)
        assert err.value.code == 409

        # release-on-drain: slot freed, process SIGTERM-drained
        assert client_a.release("replica_0") == {"released": "replica_0"}
        assert 15 in spawned_a[0][1].signals
        assert client_a.healthz()["slots"] == {"used": 0, "max": 1}

        # next provision starts at the FULL agent and wraps to the free one
        client, out = spawn_replica(3, 1)
        assert client is client_a
        assert agent_a.manager.find("replica_3") is not None
        assert out["url"].startswith("http://127.0.0.1:")

        assert agent_a.provisions_total == 2
        assert agent_a.releases_total == 1
        assert agent_b.provisions_total == 1
    finally:
        stop_agent(httpd_a, agent_a)
        stop_agent(httpd_b, agent_b)


def test_agent_cli_exposes_max_replicas_flag():
    from vitax.serve.fleet.agent import build_agent_parser
    ns = build_agent_parser().parse_args([])
    assert ns.agent_max_replicas == 0   # default: unbounded (historical)
    ns = build_agent_parser().parse_args(["--agent_max_replicas", "2"])
    assert ns.agent_max_replicas == 2


# --- metrics_report + serve_bench schema growth ------------------------------

def test_metrics_report_arbiter_sections(tmp_path):
    metrics_report = _import_tool("metrics_report")
    path = tmp_path / "arbiter.jsonl"
    records = [
        {"kind": "arbiter", "event": "request", "reason": "escalation"},
        {"kind": "arbiter", "event": "deny", "reason": "min_train_hosts"},
        {"kind": "arbiter", "event": "deny", "reason": "min_train_hosts"},
        {"kind": "arbiter", "event": "deny", "reason": "cooldown"},
        {"kind": "arbiter", "event": "borrow_start", "host": "h1"},
        {"kind": "arbiter", "event": "borrow", "host": "h1"},
        {"kind": "arbiter", "event": "borrow_failed", "host": "h1"},
        {"kind": "arbiter", "event": "return", "host": "h1"},
        {"kind": "autoscale", "event": "scale_out", "outcome": "escalated"},
        {"kind": "autoscale", "event": "scale_out", "replica": "r1"},
        {"kind": "control", "event": "elastic_resume",
         "from_processes": 2, "to_processes": 1},
        {"kind": "control", "event": "topology_change",
         "from_processes": 1, "to_processes": 2},
    ]
    path.write_text("\n".join(
        json.dumps(dict({"schema": 1, "time": float(i), "rank": 0}, **r))
        for i, r in enumerate(records)) + "\n")
    summary = metrics_report.summarize(str(path))
    assert summary["arbiter_events"] == {
        "requests": 1, "borrows": 1, "returns": 1, "borrow_failures": 1,
        "return_failures": 0,
        "denies": {"min_train_hosts": 2, "cooldown": 1}}
    assert summary["autoscale_events"]["escalations"] == 1
    assert summary["train_topology_timeline"] == [
        {"event": "elastic_resume", "from_processes": 2, "to_processes": 1},
        {"event": "topology_change", "from_processes": 1,
         "to_processes": 2}]
    metrics_report.print_human(summary)   # human arm renders without error


def test_serve_bench_ramp_stage_slo_verdict():
    """Each ramp stage now carries its own SLO verdict, so a surge-stage
    miss is visible even when the whole-profile aggregate attains."""
    serve_bench = _import_tool("serve_bench")

    class Instant(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: A003
            pass

        def do_POST(self):  # noqa: N802
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            body = json.dumps({"classes": [0], "probs": [1.0]}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Instant)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        summary = serve_bench.run_bench(
            url, concurrency=2, requests_per_worker=0, image_size=16,
            timeout=10.0, slo_p99_ms=5000.0, ramp="4:1")
        stage = summary["ramp"][0]
        assert stage["slo_attained"] is True
        assert stage["errors"] == 0 and stage["completed"] > 0
        # without an SLO the per-stage verdict stays absent (old schema)
        bare = serve_bench.run_bench(
            url, concurrency=2, requests_per_worker=0, image_size=16,
            timeout=10.0, slo_p99_ms=0.0, ramp="4:1")
        assert "slo_attained" not in bare["ramp"][0]
    finally:
        httpd.shutdown()
        httpd.server_close()


# --- compiled-program identity ----------------------------------------------

def test_arbiter_plane_identical_step_program(devices8):
    """--arbiter_url is host-side machinery (a reporter thread): the
    lowered train-step program must be bit-identical with the arbiter
    plane on or off — same pin control knobs and telemetry carry."""
    import jax
    from tests.test_checkpoint import tiny_cfg
    from tests.test_train_smoke import build_train_objects, random_batch

    def lowered(cfg):
        mesh, state, step_fn, _ = build_train_objects(cfg)
        batch = random_batch(cfg, mesh)
        return step_fn.lower(state, batch, jax.random.key(0)).as_text()

    assert lowered(tiny_cfg()) == lowered(
        tiny_cfg(arbiter_url="http://127.0.0.1:9"))


# --- the acceptance drill ----------------------------------------------------

def _drill_tiny_cfg(**kw):
    base = dict(
        image_size=16, patch_size=8, embed_dim=32, num_heads=2, num_blocks=2,
        num_classes=4, batch_size=16, dtype="float32", lr=1e-3,
        warmup_steps=2, serve_max_batch=4, serve_topk=3,
        max_batch_wait_ms=10.0, seed=0,
    )
    base.update(kw)
    return Config(**base).validate()


def _drill_train_argv(ckpt_dir, peers, metrics_dir, arbiter_url, cache_dir):
    return [
        sys.executable, os.path.join(REPO, "run_vit_training.py"),
        "--fake_data", "--image_size", "32", "--patch_size", "8",
        "--embed_dim", "32", "--num_heads", "2", "--num_blocks", "2",
        "--num_classes", "4", "--batch_size", "16", "--dtype", "float32",
        "--num_epochs", "1", "--steps_per_epoch", "100000",
        "--log_step_interval", "1", "--warmup_steps", "0",
        "--eval_max_batches", "1", "--test_epoch_interval", "99",
        "--ckpt_epoch_interval", "99", "--ckpt_dir", str(ckpt_dir),
        "--zero_stall_ckpt", "--replicate_steps", "2",
        "--peer_dir", str(peers), "--metrics_dir", str(metrics_dir),
        "--control_sync_steps", "2", "--compile_cache_dir", str(cache_dir),
        "--arbiter_url", arbiter_url,
    ]


def _wait_for(predicate, deadline_s, what):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(0.5)
    raise AssertionError(f"timed out after {deadline_s}s waiting for {what}")


@pytest.mark.slow
def test_arbiter_borrow_return_drill(devices8, tmp_path_factory):
    """The tentpole acceptance drill. A chaos-armed serve_bench ramp
    overloads a one-replica fleet whose autoscaler is at max_replicas;
    the escalation reaches the arbiter, which borrows a host from a LIVE
    2-process fake-data training job: agreed-preemption drain (both ranks
    exit 0 on a joint checkpoint), 2->1 elastic resume from the surviving
    peer store with ZERO Orbax reads, a real replica provisioned on the
    freed host through the placement agent and adopted by the router.
    The ramp's quiet tail holds the SLO on the grown fleet; once pressure
    clears the arbiter returns the host (router release -> agent drain ->
    ledger flip) and training re-expands 1->2 — the whole story visible
    in one shared metrics_report."""
    from vitax.train.loop import train
    serve_bench = _import_tool("serve_bench")
    metrics_report = _import_tool("metrics_report")

    root = tmp_path_factory.mktemp("arbiter_drill")
    metrics_dir = root / "metrics"
    cache_dir = root / "xla_cache"
    os.makedirs(metrics_dir, exist_ok=True)

    # a committed tiny checkpoint for the serve replicas
    serve_ckpt = str(root / "serve_ckpt")
    train(_drill_tiny_cfg(fake_data=True, num_epochs=1, steps_per_epoch=2,
                          log_step_interval=1, ckpt_dir=serve_ckpt,
                          ckpt_epoch_interval=1, num_workers=2,
                          eval_max_batches=1))
    model_flags = [
        "--image_size", "16", "--patch_size", "8", "--embed_dim", "32",
        "--num_heads", "2", "--num_blocks", "2", "--num_classes", "4",
        "--dtype", "float32", "--serve_max_batch", "4", "--serve_topk", "3",
        "--max_batch_wait_ms", "10.0", "--ckpt_dir", serve_ckpt,
        "--epoch", "1",
    ]
    # the seed replica is a slow accelerator: every predict hangs 250ms,
    # so ramp load beyond ~1 batch in flight predictably sheds
    slow_plan = json.dumps({"site": "engine_predict", "at": 1,
                            "times": 1000000, "action": "hang",
                            "seconds": 0.25})

    jrec = JsonlRecorder(str(metrics_dir))   # shared stream with the ranks
    arb_port = free_port()
    arb_url = f"http://127.0.0.1:{arb_port}"

    # the live tenant: 2-process training, peer-replicated, heartbeating
    director = TrainDirector(
        _drill_train_argv(root / "train_ckpt", root / "peers", metrics_dir,
                          arb_url, cache_dir),
        term_grace_s=240.0, log_dir=str(root / "train_logs"),
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 XLA_FLAGS="--xla_force_host_platform_device_count=4"))

    # the serving tenant: router + admission + maxed-out autoscaler
    manager = ReplicaManager(health_interval_s=0.25, backoff_s=0.5)
    admission = AdmissionController(deadline_ms=400.0, ewma_alpha=0.0)
    admission.observe(0.2)

    def request_capacity(reason):
        return _http(arb_url + "/request", {"reason": reason}, timeout=5.0)

    autoscaler = Autoscaler(manager, admission=admission, min_replicas=1,
                            max_replicas=1, interval_s=0.25, dwell_s=0.75,
                            cooldown_s=2.0, shed_rate_per_s=0.5,
                            request_capacity=request_capacity, recorder=jrec)
    router = Router(manager, admission=admission, autoscaler=autoscaler,
                    request_timeout_s=60.0)

    # the freed host's replica factory: one real placement agent
    agent_manager = ReplicaManager(health_interval_s=0.5, backoff_s=1.0)
    agent = PlacementAgent(advertise_host="127.0.0.1",
                           base_port=free_port(), manager=agent_manager,
                           max_slots=1)
    agent_httpd = start_agent(agent, port=0)
    agent_client = PlacementClient(
        f"http://127.0.0.1:{agent_httpd.server_address[1]}")

    def provision(host):
        return agent_client.provision(model_flags,
                                      name=f"borrow_{host}")["url"]

    def release(host, url):
        for name, snap in agent_client.replicas()["replicas"].items():
            if snap.get("url") == url:
                agent_client.release(name)
                return
        raise RuntimeError(f"no agent replica at {url}")

    adopt_seq = {"n": 0}

    def fleet_adopt(url):
        adopt_seq["n"] += 1
        manager.adopt(url, name=f"borrowed_{adopt_seq['n']}")

    def fleet_release(url):
        target = next((manager.find(name)
                       for name, snap in manager.snapshot().items()
                       if snap.get("url") == url), None)
        if target is None:
            return  # already out of rotation (a prior partial return)
        manager.retire(target)
        deadline = time.time() + 60.0
        while manager.in_flight_of(target) > 0 and time.time() < deadline:
            time.sleep(0.05)
        manager.discard(target)

    from vitax.arbiter.daemon import FleetSignals
    ledger = HostLedger(["h0", "h1"], path=str(root / "ledger.json"))
    policy = ArbiterPolicy("slo_bounded", min_train_hosts=1, dwell_s=1.0,
                           cooldown_s=5.0, quiet_dwell_s=6.0,
                           shed_rate_per_s=0.5)
    arb = Arbiter(ledger, policy, train=director, provision=provision,
                  release=release, fleet_adopt=fleet_adopt,
                  fleet_release=fleet_release, recorder=jrec,
                  interval_s=0.5)

    router_httpd = None
    try:
        # seed replica on h0's chips, then open the router
        port = free_port()
        manager.manage([sys.executable, "-m", "vitax.serve"] + model_flags
                       + ["--serve_port", str(port), "--fault_plan",
                          slow_plan],
                       f"http://127.0.0.1:{port}", name="replica_0")
        manager.start()
        _wait_for(lambda: manager.ready_count() >= 1, 300,
                  "seed replica ready")
        router_httpd = start_router(router, 0)
        fleet_url = f"http://127.0.0.1:{router_httpd.server_address[1]}"
        arb._signals_fn = FleetSignals(fleet_url)
        autoscaler.start()

        arb_httpd = start_arbiter(arb, arb_port)
        try:
            director.start(2)
            # training must be PROGRESSING (heartbeats landing) before the
            # surge: the policy's train_stalled gate reads this telemetry
            _wait_for(
                lambda: arb.metrics()["train_telemetry"] is not None,
                600, "first train step heartbeat")

            # surge long enough for escalation -> borrow -> drain ->
            # provision -> AOT warmup; then a quiet tail on the grown fleet
            summary = serve_bench.run_bench(
                fleet_url, concurrency=6, requests_per_worker=0,
                image_size=16, timeout=60.0, slo_p99_ms=5000.0, replicas=2,
                ramp="40:150,2:45")

            # the surge really overloaded the seed replica...
            assert summary["ramp"][0]["shed"] > 0, summary["ramp"]
            # ...the maxed-out autoscaler escalated instead of stalling...
            assert autoscaler.escalations_total >= 1
            # ...and the arbiter borrowed the host for serving
            assert arb.borrows_total >= 1, arb.metrics()
            assert summary["errors"] == 0, summary["error_samples"]
            # SLO verdict on the grown fleet: the quiet tail attains
            assert summary["ramp"][-1]["slo_attained"] is True, (
                summary["ramp"])

            # pressure is gone: the loan comes home and training re-expands
            _wait_for(lambda: arb.returns_total >= 1, 300,
                      "the borrowed host to return")
            _wait_for(lambda: director.process_count == 2
                      and director.alive() == 2, 300,
                      "training re-expanded to 2 processes")
            assert ledger.counts()["train"] == 2
            assert len(agent_manager.snapshot()) == 0  # replica drained
        finally:
            stop_arbiter(arb_httpd, arb)

        # drain the training job deliberately: every rank exits 0
        codes = director.stop()
        assert codes == [0, 0], codes
    finally:
        autoscaler.stop()
        if router_httpd is not None:
            stop_router(router_httpd)
        manager.stop()
        stop_agent(agent_httpd, agent)
        director.stop()

    # one report tells the whole story: the ranks, the autoscaler and the
    # arbiter all appended to the same metrics.jsonl
    summary = metrics_report.summarize(str(metrics_dir / "metrics.jsonl"))
    arb_ev = summary["arbiter_events"]
    assert arb_ev["borrows"] >= 1 and arb_ev["returns"] >= 1, arb_ev
    assert summary["autoscale_events"]["escalations"] >= 1

    # topology timeline: the pod shrank to 1 and grew back to 2
    timeline = summary["train_topology_timeline"]
    tos = [t["to_processes"] for t in timeline]
    assert 1 in tos and tos[-1] == 2, timeline

    # the 2->1 resume came from the surviving peer store: ZERO committed
    # steps lost, ZERO shared-storage checkpoint reads
    with open(metrics_dir / "metrics.jsonl") as f:
        events = [json.loads(line) for line in f if line.strip()]
    peer_restores = [e for e in events if e.get("kind") == "restore"
                     and e.get("path") == "peer"]
    assert peer_restores, [e for e in events if e.get("kind") == "restore"]
    assert all(e["orbax_reads"] == 0 for e in peer_restores)
    assert all(e["resume_step"] > 0 for e in peer_restores)


# --- borrowed-host int8 warm boot (PR 19 residue, exercised) -----------------

@pytest.mark.slow
def test_borrowed_host_boots_int8_npz_replica(devices8, tmp_path):
    """Warming int8 images on borrowed hosts: the freed host's replica
    factory (the arbiter's `provision` callback is exactly
    `agent.provision(model_flags, ...)`) boots a REAL `python -m
    vitax.serve` replica from a quantized consolidated npz, through the
    registry's engine constructor (vitax/programs/builder.py:build_engine).
    The replica warms, and its /metrics pins weights_dtype == "int8" —
    the borrowed chips hold int8 weights, not a full-precision fallback."""
    import numpy as np
    from vitax.checkpoint.consolidate import flatten_tree, save_npz
    from vitax.config import Config
    from vitax.models import build_model
    from vitax.parallel.mesh import build_mesh
    from vitax.train.state import build_optimizer, make_train_state

    cfg = _drill_tiny_cfg()
    mesh = build_mesh(cfg)
    model = build_model(cfg)
    tx, _ = build_optimizer(cfg, max_iteration=10)
    import jax
    state, _, _ = make_train_state(cfg, model, tx, mesh, jax.random.key(0))
    npz = str(tmp_path / "int8.npz")
    save_npz(npz, {k: np.asarray(v)
                   for k, v in flatten_tree(state.params).items()},
             dtype="int8")

    model_flags = [
        "--image_size", "16", "--patch_size", "8", "--embed_dim", "32",
        "--num_heads", "2", "--num_blocks", "2", "--num_classes", "4",
        "--dtype", "float32", "--serve_max_batch", "4", "--serve_topk", "3",
        "--max_batch_wait_ms", "10.0",
        "--npz", npz, "--serve_quant_dtype", "int8",
    ]
    agent = PlacementAgent(advertise_host="127.0.0.1",
                           base_port=free_port(),
                           manager=ReplicaManager(health_interval_s=0.5,
                                                  backoff_s=1.0),
                           max_slots=1)
    try:
        out = agent.provision(model_flags, name="borrow_int8")
        url = out["url"]

        def ready():
            try:
                return _http(url + "/healthz", timeout=5.0)["ready"]
            except Exception:
                return False

        _wait_for(ready, 240.0, "int8 replica warm")
        snap = _http(url + "/metrics", timeout=5.0)
        assert snap["weights_dtype"] == "int8", snap
    finally:
        agent.release("borrow_int8")
