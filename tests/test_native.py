"""Native (C++) data-path tests: PIL parity of the libjpeg decode + bicubic
resample pipeline, batch API with fallback, and loader integration.

The native library replaces the reference's DataLoader worker-process decode
(reference run_vit_training.py:65-73 + torchvision transforms :39-55); these
tests pin its numerics to the PIL implementation within 1 uint8 LSB.
"""

import os

import numpy as np
import pytest
from PIL import Image

from vitax.data import native
from vitax.data.imagefolder import ImageFolderDataset
from vitax.data.transforms import train_transform, val_transform

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++/libjpeg)")

# 1 uint8 LSB after normalization: (1/255)/min(std) = 0.0171..., rounded up
LSB_TOL = 0.018


def _save_jpeg(path, w, h, seed=0, quality=95):
    rng = np.random.default_rng(seed)
    arr = rng.integers(0, 256, size=(h, w, 3), dtype=np.uint8)
    Image.fromarray(arr).save(path, quality=quality)


def test_jpeg_size(tmp_path):
    p = str(tmp_path / "x.jpg")
    _save_jpeg(p, 317, 211)
    assert native.jpeg_size(p) == (317, 211)
    assert native.jpeg_size(str(tmp_path / "missing.jpg")) is None


# (512, 1025) pins the resize-shorter rounding: 256*1025/512 = 512.5 must
# round half-to-even (512) like Python round(), not half-away (513)
@pytest.mark.parametrize("w,h", [(400, 300), (180, 523), (224, 224), (97, 101),
                                 (512, 1025)])
def test_val_pipeline_matches_pil(tmp_path, w, h):
    p = str(tmp_path / "x.jpg")
    _save_jpeg(p, w, h, seed=w)
    vt = val_transform(224)
    with Image.open(p) as im:
        ref = vt(im.convert("RGB"))
    out = native.process_file(p, vt.native_params(w, h, 0), 224, vt.resize_to)
    assert out is not None and out.shape == (224, 224, 3)
    assert np.abs(out - ref).max() <= LSB_TOL


def test_train_pipeline_matches_pil(tmp_path):
    p = str(tmp_path / "x.jpg")
    _save_jpeg(p, 400, 300)
    tt = train_transform(224, seed=3)
    tt.set_epoch(2)
    for index in (0, 7, 123):
        with Image.open(p) as im:
            ref = tt(im.convert("RGB"), index=index)
        out = native.process_file(p, tt.native_params(400, 300, index), 224, 0)
        assert out is not None
        assert np.abs(out - ref).max() <= LSB_TOL


def test_train_params_shared_with_pil_path(tmp_path):
    """native_params must consume the SAME rng stream as the PIL __call__ —
    same (crop, flip) decisions for the same (seed, epoch, index)."""
    tt = train_transform(224, seed=11)
    a = tt.native_params(640, 480, 5)
    b = tt.native_params(640, 480, 5)
    assert a == b  # deterministic per (seed, epoch, index)
    tt.set_epoch(1)
    assert tt.native_params(640, 480, 5) != a  # varies across epochs


def test_process_file_corrupt_returns_none(tmp_path):
    p = str(tmp_path / "bad.jpg")
    with open(p, "wb") as f:
        f.write(b"\xff\xd8\xff\xe0 this is not a real jpeg")
    assert native.process_file(p, (1, 0, 0, 0, 0, 0), 224, 256) is None


def test_batch_matches_single_calls(tmp_path):
    paths = []
    vt = val_transform(64)
    for i in range(6):
        p = str(tmp_path / f"{i}.jpg")
        _save_jpeg(p, 100 + 17 * i, 120 + 11 * i, seed=i)
        paths.append(p)
    params = [vt.native_params(0, 0, i) for i in range(6)]
    batch, failed = native.process_batch(paths, params, 64, vt.resize_to, n_threads=3)
    assert failed == []
    for i, p in enumerate(paths):
        single = native.process_file(p, params[i], 64, vt.resize_to)
        np.testing.assert_array_equal(batch[i], single)


def test_batch_reports_failures(tmp_path):
    good = str(tmp_path / "good.jpg")
    bad = str(tmp_path / "bad.jpg")
    _save_jpeg(good, 128, 128)
    with open(bad, "wb") as f:
        f.write(b"nope")
    vt = val_transform(64)
    params = [vt.native_params(0, 0, i) for i in range(2)]
    batch, failed = native.process_batch([good, bad], params, 64, vt.resize_to)
    assert failed == [1]
    assert np.isfinite(batch[0]).all()


def test_uint8_output_matches_device_normalize(tmp_path):
    """Raw-uint8 output + on-device normalization == float output: the
    device_normalize transport optimization must not change numerics."""
    import jax.numpy as jnp
    from vitax.train.step import prepare_images

    p = str(tmp_path / "x.jpg")
    _save_jpeg(p, 200, 150)
    vt = val_transform(64)
    params = vt.native_params(200, 150, 0)
    f32 = native.process_file(p, params, 64, vt.resize_to, normalize=True)
    u8 = native.process_file(p, params, 64, vt.resize_to, normalize=False)
    assert u8.dtype == np.uint8
    on_device = np.asarray(prepare_images(jnp.asarray(u8)))
    np.testing.assert_allclose(on_device, f32, atol=1e-6)
    # float input passes through untouched
    assert prepare_images(jnp.asarray(f32)).dtype == jnp.float32


def test_uint8_pil_and_native_paths_agree(tmp_path):
    root = tmp_path / "train"
    os.makedirs(root / "a")
    _save_jpeg(str(root / "a" / "0.jpg"), 300, 200, seed=1)
    tt = train_transform(64, seed=0, normalize=False)
    ds_native = ImageFolderDataset(str(root), tt, use_native=True)
    ds_pil = ImageFolderDataset(str(root), tt, use_native=False)
    img_n, _ = ds_native[0]
    img_p, _ = ds_pil[0]
    assert img_n.dtype == np.uint8 and img_p.dtype == np.uint8
    assert np.abs(img_n.astype(int) - img_p.astype(int)).max() <= 1  # 1 LSB
    imgs, _ = ds_native.load_batch([0])
    assert imgs.dtype == np.uint8


def test_imagefolder_native_matches_pil_dataset(tmp_path):
    root = tmp_path / "train"
    for cls in ("a", "b"):
        os.makedirs(root / cls)
    _save_jpeg(str(root / "a" / "0.jpg"), 300, 200, seed=1)
    _save_jpeg(str(root / "b" / "0.jpg"), 250, 260, seed=2)
    # non-JPEG falls back to PIL inside the native dataset
    Image.fromarray(np.zeros((90, 90, 3), np.uint8)).save(root / "b" / "1.png")

    tt = train_transform(64, seed=0)
    ds_native = ImageFolderDataset(str(root), tt, use_native=True)
    ds_pil = ImageFolderDataset(str(root), tt, use_native=False)
    assert ds_native.use_native and not ds_pil.use_native
    assert len(ds_native) == 3

    for i in range(3):
        img_n, lbl_n = ds_native[i]
        img_p, lbl_p = ds_pil[i]
        assert lbl_n == lbl_p
        assert np.abs(img_n - img_p).max() <= LSB_TOL

    imgs, labels = ds_native.load_batch([2, 0, 1], n_threads=2)
    assert imgs.shape == (3, 64, 64, 3) and labels.tolist() == [1, 0, 1]
    assert np.abs(imgs[1] - ds_pil[0][0]).max() <= LSB_TOL
    assert np.abs(imgs[0] - ds_pil[2][0]).max() <= LSB_TOL  # the PNG fallback slot


def test_decode_releases_gil(tmp_path):
    """A pure-Python counter thread must keep advancing while the main thread
    runs native decode: ctypes CDLL calls drop the GIL, which is what makes
    the loader's in-process thread pool a valid substitute for the
    reference's DataLoader worker processes (run_vit_training.py:65-73).
    Even on one core, OS timeslicing keeps the counter at a healthy fraction
    of its idle rate (~0.5 measured); a GIL-holding decode pins it near 0.
    The measurement harness is bench.py's counter_rate (one implementation,
    bench --preset data_scaling records the same ratios)."""
    import time

    from bench import counter_rate

    paths, params = [], []
    tt = train_transform(224, seed=0)
    for i in range(32):
        p = str(tmp_path / f"{i}.jpg")
        _save_jpeg(p, 350, 300, seed=i)
        paths.append(p)
        params.append(tt.native_params(350, 300, i))

    idle = counter_rate(lambda: time.sleep(0.02), min_time=0.4)
    during = counter_rate(
        lambda: native.process_batch(paths, params, 224, 0, n_threads=1),
        min_time=0.4)
    # 0.15 is deliberately far below the ~0.5 timeslicing expectation to
    # stay robust under CI load; a held GIL measures < 0.01
    assert during / idle > 0.15, (
        f"counter starved during native decode: {during:.0f}/s vs "
        f"{idle:.0f}/s idle — is the GIL being held across the C call?")
