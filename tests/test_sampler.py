"""ShardedSampler unit tests (DistributedSampler parity, reference
run_vit_training.py:62-64,76-78,258): per-process shards are disjoint, cover
the epoch, interleave rank::world, drop the remainder, and reshuffle
deterministically per epoch."""

import numpy as np

from vitax.data.loader import ShardedSampler


def make(world, dataset_len=103, batch=20, shuffle=True, seed=7):
    return [
        ShardedSampler(dataset_len, batch, shuffle=shuffle, seed=seed,
                       process_index=r, process_count=world)
        for r in range(world)
    ]


def test_shards_disjoint_and_cover_epoch():
    world, dataset_len, batch = 4, 103, 20
    samplers = make(world, dataset_len, batch)
    per_rank = [s.epoch_indices(epoch=3) for s in samplers]
    for m in per_rank:
        assert m.shape == (dataset_len // batch, batch // world)  # (5, 5)
    all_idx = np.concatenate([m.ravel() for m in per_rank])
    assert len(all_idx) == len(set(all_idx.tolist()))          # disjoint
    assert len(all_idx) == (dataset_len // batch) * batch      # drop-last: 100
    assert set(all_idx.tolist()) <= set(range(dataset_len))


def test_rank_interleaving_matches_distributed_sampler():
    # DistributedSampler hands rank r indices[r::world] of each global batch
    world = 4
    samplers = make(world, shuffle=False)
    step0 = np.stack([s.epoch_indices(0)[0] for s in samplers])  # (world, local)
    global_batch = np.arange(20)
    for r in range(world):
        np.testing.assert_array_equal(step0[r], global_batch[r::world])


def test_epoch_seeded_reshuffle():
    s = make(1, dataset_len=64, batch=8)[0]
    e1, e1b, e2 = s.epoch_indices(1), s.epoch_indices(1), s.epoch_indices(2)
    np.testing.assert_array_equal(e1, e1b)      # deterministic per epoch
    assert not np.array_equal(e1, e2)           # varies across epochs
    # same permutation on every process (only the shard differs)
    a, b = make(2, dataset_len=64, batch=8)
    union1 = np.sort(np.concatenate(
        [a.epoch_indices(5).ravel(), b.epoch_indices(5).ravel()]))
    np.testing.assert_array_equal(union1, np.arange(64))


def test_no_shuffle_is_identity_order():
    s = make(1, dataset_len=40, batch=10, shuffle=False)[0]
    np.testing.assert_array_equal(s.epoch_indices(0).ravel(), np.arange(40))


def test_loader_start_step_skips_exactly(devices8):
    """ShardedLoader.epoch(e, start_step=k) must yield exactly the tail of the
    same epoch's batch stream — the index matrix is a pure function of
    (seed, epoch), the basis of step-granular preemption resume."""
    from vitax.config import Config
    from vitax.data.fake import FakeImageNetDataset
    from vitax.data.loader import ShardedLoader, ShardedSampler
    from vitax.parallel.mesh import build_mesh

    cfg = Config(image_size=16, patch_size=8, embed_dim=32, num_heads=2,
                 num_blocks=2, num_classes=4, batch_size=8).validate()
    mesh = build_mesh(cfg)
    ds = FakeImageNetDataset(cfg.image_size, length=64)
    sampler = ShardedSampler(len(ds), cfg.batch_size, shuffle=True, seed=0)
    loader = ShardedLoader(ds, sampler, mesh, num_workers=2)
    try:
        full = [np.asarray(b["label"]) for b in loader.epoch(3)]
        tail = [np.asarray(b["label"]) for b in loader.epoch(3, start_step=5)]
    finally:
        loader.close()
    assert len(tail) == len(full) - 5
    for a, b in zip(full[5:], tail):
        np.testing.assert_array_equal(a, b)
