"""Scenario registry tests: the unified program builder (vitax/programs/),
the declarative sharding-rule table (vitax/parallel/rules.py), and the three
transfer workloads (finetune / probe / distill) it carries.

Three pin families live here:

- rule-table parity: `rules.rule_pspec` reproduces the reference dispatcher
  `sharding.param_pspec` leaf-for-leaf on real model trees across the
  dp / zero2 / zero3 / tp / pp / ep arms;
- bitwise identity: the builder's train / eval / serve-bucket programs lower
  to the same bytes as the pre-registry direct assembly paths
  (analysis/hlo.py, train/step.py, serve/engine.py);
- workload semantics: warm-start key discipline, the probe's head-only
  optimizer state and bitwise-frozen backbone, the distill program's
  single-jit teacher+student with decreasing loss, and the VTX-R010
  frozen-params invariant over both scenario arms.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from vitax.checkpoint.consolidate import flatten_tree, save_npz
from vitax.config import Config, parse_config
from vitax.models import build_model
from vitax.parallel import rules as prules
from vitax.parallel.mesh import build_mesh
from vitax.parallel.sharding import param_pspec, param_specs
from vitax.programs import TASKS, get_scenario
from vitax.programs import builder
from vitax.programs.workloads import warm_start_from_npz
from vitax.train.state import make_train_state

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_cfg(**kw):
    base = dict(image_size=16, patch_size=8, embed_dim=32, num_heads=2,
                num_blocks=2, num_classes=4, batch_size=16, dtype="float32",
                lr=1e-3, warmup_steps=2, clip_grad_norm=1.0, seed=0)
    base.update(kw)
    return Config(**base).validate()


def abstract_params(cfg):
    model = build_model(cfg)
    x = jnp.zeros((2, cfg.image_size, cfg.image_size, 3))
    return jax.eval_shape(lambda r: model.init(r, x, True),
                          jax.random.key(0))


def random_batch(cfg, mesh, seed=0):
    from jax.sharding import NamedSharding
    from vitax.parallel.mesh import batch_pspec
    rng = np.random.default_rng(seed)
    images = rng.normal(size=(cfg.batch_size, cfg.image_size,
                              cfg.image_size, 3)).astype(np.float32)
    labels = rng.integers(0, cfg.num_classes,
                          size=(cfg.batch_size,)).astype(np.int32)
    sh = NamedSharding(mesh, batch_pspec())
    return {"image": jax.device_put(jnp.asarray(images), sh),
            "label": jax.device_put(jnp.asarray(labels), sh)}


def export_params_npz(cfg, path, seed=42):
    """Consolidated params-only npz from a fresh sharded init (the export
    vitax.checkpoint.consolidate would produce)."""
    from vitax.train.state import build_optimizer
    mesh = build_mesh(cfg)
    model = build_model(cfg)
    tx, _ = build_optimizer(cfg, max_iteration=10)
    state, _, _ = make_train_state(cfg, model, tx, mesh,
                                   jax.random.key(seed))
    flat = {k: np.asarray(v) for k, v in flatten_tree(state.params).items()}
    save_npz(path, flat)
    return flat


# --- declarative sharding rules (vitax/parallel/rules.py) --------------------


# the mesh/config arms the table is pinned against (mirrors the sharding and
# pipeline test configs; 8 virtual CPU devices)
PARITY_ARMS = {
    "dp": dict(run_without_fsdp=True),
    "zero2": dict(reshard_after_forward=False),
    "zero3": dict(),
    "tp": dict(tp_size=2, fsdp_size=4),
    "pp": dict(pp_size=2, dp_size=2, fsdp_size=2, grad_ckpt=True),
    "ep": dict(moe_experts=4, ep_size=2, dp_size=2, fsdp_size=2),
}


class TestRuleTable:
    @pytest.mark.parametrize("arm", sorted(PARITY_ARMS))
    def test_parity_with_param_pspec(self, devices8, arm):
        """The rule table reproduces the reference dispatcher leaf-for-leaf
        on the real model tree (satellite: pinned bitwise across arms)."""
        cfg = tiny_cfg(**PARITY_ARMS[arm])
        mesh = build_mesh(cfg)
        mesh_shape = tuple(mesh.shape[a] for a in prules.MESH_AXES)
        flat = jax.tree_util.tree_flatten_with_path(abstract_params(cfg))[0]
        assert flat
        for path, leaf in flat:
            names = prules._leaf_path_names(path)
            ref = param_pspec(path, leaf.shape, cfg, mesh_shape,
                              cfg.scan_blocks)
            got = prules.rule_pspec(names, leaf.shape, cfg, mesh_shape,
                                    cfg.scan_blocks)
            assert got == ref, (
                f"[{arm}] {'/'.join(names)} {leaf.shape}: "
                f"table says {got}, param_pspec says {ref}")

    def test_param_specs_routes_through_table(self, devices8):
        """The live spec constructor and the table agree tree-for-tree."""
        cfg = tiny_cfg(tp_size=2, fsdp_size=4)
        mesh = build_mesh(cfg)
        tree = abstract_params(cfg)
        via_live = param_specs(tree, cfg, mesh)
        via_table = prules.specs_from_rules(tree, cfg, mesh)
        assert jax.tree_util.tree_all(
            jax.tree.map(lambda a, b: a == b, via_live, via_table,
                         is_leaf=lambda x: isinstance(x, P)))

    def test_strict_match_raises_on_unknown_param(self):
        with pytest.raises(ValueError, match="Partition rule not found"):
            prules.match_rule("params/blocks/attn/mystery_weight")

    def test_scalar_exemption_skips_matching(self):
        """0-dim / size-1 leaves replicate without needing a rule — even a
        path no table entry matches."""
        cfg = tiny_cfg()
        shape6 = (1, 8, 1, 1, 1, 1)
        assert prules.rule_pspec(("params", "temperature"), (), cfg,
                                 shape6, False) == P()
        assert prules.rule_pspec(("params", "temperature"), (1, 1), cfg,
                                 shape6, False) == P(None, None)

    def test_rule_order_first_match_wins(self):
        assert prules.match_rule(
            "params/blocks/attn/qkv/kernel").name == "megatron-column-qkv-fc1"
        assert prules.match_rule(
            "params/blocks/attn/proj/kernel").name == "megatron-row-attn-proj"
        assert prules.match_rule(
            "params/blocks/moe/w1").name == "moe-expert-weights"
        assert prules.match_rule(
            "params/head/kernel").name == "dense-default"

    def test_describe_table_names_every_rule(self):
        text = prules.describe_table()
        for r in prules.RULE_TABLE:
            assert r.name in text


# --- scenario registry (vitax/programs/registry.py) --------------------------


class TestRegistry:
    def test_task_set(self):
        assert TASKS == ("train", "finetune", "probe", "distill")

    def test_unknown_task_raises_naming_valid_set(self):
        with pytest.raises(ValueError, match="train"):
            get_scenario("pretrain")

    def test_cli_task_flag_round_trips(self):
        cfg = parse_config(["--task", "probe", "--init_npz", "/x.npz",
                            "--image_size", "16", "--patch_size", "8",
                            "--embed_dim", "32", "--num_heads", "2",
                            "--num_blocks", "2", "--num_classes", "4"])
        assert cfg.task == "probe" and cfg.init_npz == "/x.npz"

    def test_validators_reject_bad_combos(self):
        # train must not carry transfer-source flags
        with pytest.raises(AssertionError):
            tiny_cfg(init_npz="/x.npz")
        # finetune requires a source export
        with pytest.raises(AssertionError):
            tiny_cfg(task="finetune")
        # probe cannot run the fused optimizer (masking happens in optax)
        with pytest.raises(AssertionError):
            tiny_cfg(task="probe", init_npz="/x.npz", fused_optimizer="on")
        # distill composes with dense models only
        with pytest.raises(AssertionError):
            tiny_cfg(task="distill", moe_experts=4, ep_size=2,
                     dp_size=2, fsdp_size=2)

    def test_builder_enforces_scenario_program_set(self, devices8):
        geom = builder.Geometry.from_config(tiny_cfg())
        with pytest.raises(ValueError, match="does not build"):
            builder.build_program("distill", geom)
        with pytest.raises(ValueError, match="unknown program kind"):
            builder.build_program("serve", geom)


# --- bitwise identity pins (satellite 1) -------------------------------------


class TestIdentityPins:
    def test_train_program_identical_to_hlo_path(self, devices8):
        """builder.lower_step == analysis/hlo.lower_train_step, byte for
        byte, at the HEAD train geometry (the refactor moved the assembly,
        not the program)."""
        from vitax.analysis import hlo
        cfg = tiny_cfg()
        ref, n_ref = hlo.lower_train_step(cfg)
        got, n_got = builder.lower_step(cfg)
        assert n_ref == n_got
        assert ref.as_text() == got.as_text()

    def test_eval_program_identical_to_direct_assembly(self, devices8):
        """build_program("eval") lowers to the same bytes as a direct
        make_eval_step call on the same geometry (loop.py's historical
        wiring), and the owned-geometry program cache returns one object."""
        from jax.sharding import NamedSharding
        from vitax.parallel.mesh import batch_pspec
        from vitax.train.step import make_eval_step
        cfg = tiny_cfg()
        geom = builder.Geometry.from_config(cfg)
        via_builder = builder.build_program("eval", geom)
        assert builder.build_program("eval", geom) is via_builder
        direct = make_eval_step(cfg, geom.model, geom.mesh, geom.state_specs)
        sh = NamedSharding(geom.mesh, batch_pspec())
        batch = {
            "image": jax.ShapeDtypeStruct(
                (cfg.batch_size, cfg.image_size, cfg.image_size, 3),
                jnp.float32, sharding=sh),
            "label": jax.ShapeDtypeStruct((cfg.batch_size,), jnp.int32,
                                          sharding=sh),
        }
        assert (via_builder.lower(geom.abstract_state, batch).as_text()
                == direct.lower(geom.abstract_state, batch).as_text())

    def test_serve_bucket_identical_to_direct_engine(self, devices8,
                                                     tmp_path):
        """build_engine routes to the same InferenceEngine; the lowered
        bucket module is byte-identical to the pre-registry from_npz path."""
        from vitax.serve.engine import InferenceEngine
        cfg = tiny_cfg()
        npz = str(tmp_path / "w.npz")
        export_params_npz(cfg, npz)
        via_builder = builder.build_engine(cfg, npz=npz)
        direct = InferenceEngine.from_npz(cfg, npz)
        assert (via_builder.lower_bucket_mlir(8)
                == direct.lower_bucket_mlir(8))


# --- warm start (finetune source discipline) ---------------------------------


@pytest.mark.slow
class TestWarmStart:
    def test_loads_backbone_bitwise(self, devices8, tmp_path):
        cfg = tiny_cfg()
        npz = str(tmp_path / "init.npz")
        flat_src = export_params_npz(cfg, npz, seed=42)
        cfg_ft = tiny_cfg(task="finetune", init_npz=npz)
        mesh = build_mesh(cfg_ft)
        from vitax.train.state import build_optimizer
        model = build_model(cfg_ft)
        tx, _ = build_optimizer(cfg_ft, max_iteration=10)
        state, _, _ = make_train_state(cfg_ft, model, tx, mesh,
                                       jax.random.key(7))
        state, info = warm_start_from_npz(cfg_ft, state, mesh)
        flat = {k: np.asarray(v)
                for k, v in flatten_tree(state.params).items()}
        assert set(flat) == set(flat_src)
        for k in flat_src:  # same num_classes: the head loads too
            assert np.array_equal(flat[k], flat_src[k]), k
        assert info["loaded"] == len(flat_src) and info["reinit"] == []

    def test_head_reinit_on_new_num_classes(self, devices8, tmp_path):
        npz = str(tmp_path / "init.npz")
        flat_src = export_params_npz(tiny_cfg(), npz, seed=42)
        cfg_ft = tiny_cfg(task="finetune", init_npz=npz, num_classes=7)
        mesh = build_mesh(cfg_ft)
        from vitax.train.state import build_optimizer
        model = build_model(cfg_ft)
        tx, _ = build_optimizer(cfg_ft, max_iteration=10)
        state, _, _ = make_train_state(cfg_ft, model, tx, mesh,
                                       jax.random.key(7))
        state, info = warm_start_from_npz(cfg_ft, state, mesh)
        assert info["reinit"] == ["params/head/bias", "params/head/kernel"]
        flat = {k: np.asarray(v)
                for k, v in flatten_tree(state.params).items()}
        assert flat["params/head/kernel"].shape == (32, 7)
        for k in flat_src:
            if "head" not in k.split("/"):
                assert np.array_equal(flat[k], flat_src[k]), k

    def test_loud_failures_on_key_mismatch(self, devices8, tmp_path):
        cfg = tiny_cfg()
        flat_src = export_params_npz(cfg, str(tmp_path / "ok.npz"))
        mesh = build_mesh(cfg)
        from vitax.train.state import build_optimizer
        model = build_model(cfg)
        tx, _ = build_optimizer(cfg, max_iteration=10)
        state, _, _ = make_train_state(cfg, model, tx, mesh,
                                       jax.random.key(7))

        unknown = dict(flat_src)
        unknown["params/extra/kernel"] = np.zeros((2, 2), np.float32)
        save_npz(str(tmp_path / "unknown.npz"), unknown)
        cfg_u = tiny_cfg(task="finetune",
                         init_npz=str(tmp_path / "unknown.npz"))
        with pytest.raises(ValueError, match="keys absent"):
            warm_start_from_npz(cfg_u, state, mesh)

        missing = {k: v for k, v in flat_src.items()
                   if k != "params/pos_embed"}
        save_npz(str(tmp_path / "missing.npz"), missing)
        cfg_m = tiny_cfg(task="finetune",
                         init_npz=str(tmp_path / "missing.npz"))
        with pytest.raises(ValueError, match="missing param"):
            warm_start_from_npz(cfg_m, state, mesh)

        wrong = dict(flat_src)
        wrong["params/pos_embed"] = np.zeros((1, 3, 32), np.float32)
        save_npz(str(tmp_path / "wrong.npz"), wrong)
        cfg_w = tiny_cfg(task="finetune",
                         init_npz=str(tmp_path / "wrong.npz"))
        with pytest.raises(ValueError, match="has shape"):
            warm_start_from_npz(cfg_w, state, mesh)


# --- workloads end-to-end (the acceptance runs) ------------------------------


def loop_cfg(**kw):
    base = dict(fake_data=True, num_epochs=1, steps_per_epoch=3,
                log_step_interval=1, ckpt_epoch_interval=99,
                test_epoch_interval=99, num_workers=2, eval_max_batches=1)
    base.update(kw)
    return tiny_cfg(**base)


@pytest.mark.slow
class TestWorkloadsE2E:
    def test_finetune_and_probe_full_loop(self, devices8, tmp_path):
        """--task finetune and --task probe through the real training loop
        on fake data: finetune re-initializes the head for a new
        --num_classes and trains 3 steps; the probe's backbone stays
        bitwise at the warm-start values while the head moves, and the
        optimizer state carries moments for the head ONLY."""
        from vitax.train.loop import train
        npz = str(tmp_path / "init.npz")
        flat_src = export_params_npz(tiny_cfg(), npz, seed=42)

        st = train(loop_cfg(task="finetune", init_npz=npz, num_classes=7,
                            ckpt_dir=str(tmp_path / "ft"), seed=1))
        assert int(jax.device_get(st.step)) == 3
        assert np.asarray(
            flatten_tree(st.params)["params/head/kernel"]).shape == (32, 7)

        st = train(loop_cfg(task="probe", init_npz=npz,
                            ckpt_dir=str(tmp_path / "pr"), seed=2))
        assert int(jax.device_get(st.step)) == 3
        flat = {k: np.asarray(v)
                for k, v in flatten_tree(st.params).items()}
        for k in flat_src:
            if "head" not in k.split("/"):
                assert np.array_equal(flat[k], flat_src[k]), (
                    f"probe moved frozen backbone leaf {k}")
        assert not np.array_equal(flat["params/head/kernel"],
                                  flat_src["params/head/kernel"])
        # head-only optimizer state, pinned by tree inspection
        moment_paths = [
            "/".join(prules._leaf_path_names(p))
            for p, _ in jax.tree_util.tree_leaves_with_path(st.opt_state)]
        moments = [p for p in moment_paths
                   if {"mu", "nu"} & set(p.split("/"))]
        assert moments, "probe opt_state carries no AdamW moments at all"
        assert all("head" in p.split("/") for p in moments), moments

    def test_distill_loss_decreases_single_program(self, devices8,
                                                   tmp_path):
        """--task distill: ONE jitted program holds the frozen teacher
        forward and the student update; on a fixed batch the combined
        CE+KL loss decreases, and the traced jaxpr carries the teacher
        under stop_gradient."""
        from vitax.programs.registry import get_scenario as scen
        from vitax.ops.attention import make_attention_impl
        from vitax.train.loop import _moe_dispatch_sharding, _token_sharding
        npz = str(tmp_path / "teacher.npz")
        export_params_npz(tiny_cfg(), npz, seed=42)
        cfg = tiny_cfg(task="distill", teacher_npz=npz, lr=1e-2,
                       gather_overlap="off")
        mesh = build_mesh(cfg)
        model = build_model(
            cfg, attention_impl=make_attention_impl(cfg, mesh),
            token_sharding=_token_sharding(cfg, mesh),
            moe_dispatch_sharding=_moe_dispatch_sharding(cfg, mesh))
        tx, schedule = scen(cfg.task).make_optimizer(cfg, 100)
        state, sspecs, _ = make_train_state(cfg, model, tx, mesh,
                                            jax.random.key(3))
        geom = builder.Geometry(cfg=cfg, mesh=mesh, model=model, tx=tx,
                                schedule=schedule, state_specs=sspecs)
        step = builder.build_program("distill", geom)
        batch = random_batch(cfg, mesh, seed=0)
        losses = []
        for i in range(10):
            state, metrics = step(state, batch, jax.random.key(i))
            losses.append(float(jax.device_get(metrics["loss"])))
        assert losses[-1] < losses[0], losses
        for key in ("ce", "kl", "teacher_top1", "student_top1"):
            assert key in metrics, key

    def test_distill_full_loop(self, devices8, tmp_path):
        from vitax.train.loop import train
        npz = str(tmp_path / "teacher.npz")
        export_params_npz(tiny_cfg(), npz, seed=42)
        st = train(loop_cfg(task="distill", teacher_npz=npz,
                            ckpt_dir=str(tmp_path / "kd"), seed=3,
                            gather_overlap="off"))
        assert int(jax.device_get(st.step)) == 3


# --- VTX-R010 + scenario analysis arms (satellite 2) -------------------------


class TestFrozenInvariant:
    def test_freeze_report_probe_and_distill(self, devices8):
        frozen_p, moments_p = builder.freeze_report(
            tiny_cfg(task="probe", init_npz="/x.npz"))
        assert frozen_p and all("head" not in f.split("/")
                                for f in frozen_p)
        assert sorted(moments_p) == ["params/head/bias",
                                     "params/head/kernel"]
        frozen_d, _ = builder.freeze_report(
            tiny_cfg(task="distill", gather_overlap="off"))
        assert frozen_d and all(f.startswith("teacher/") for f in frozen_d)

    def test_r010_negative_moment_on_frozen_leaf(self):
        """A mu/nu slot appearing under a frozen path is an ERROR finding —
        the mask silently stopped covering that leaf."""
        from vitax.analysis.rules import FROZEN_NOT_UPDATED, Program
        cfg = tiny_cfg(task="probe", init_npz="/x.npz")
        broken = Program(
            kind="train", arm="probe", config=cfg, mlir="m",
            frozen_paths=("params/blocks/attn/qkv/kernel",),
            opt_moment_paths=("params/blocks/attn/qkv/kernel",
                              "params/head/kernel"))
        findings = FROZEN_NOT_UPDATED.check(broken, cfg)
        assert findings and findings[0].severity == "ERROR"
        ok = Program(
            kind="train", arm="probe", config=cfg, mlir="m",
            frozen_paths=("params/blocks/attn/qkv/kernel",),
            opt_moment_paths=("params/head/kernel",))
        assert FROZEN_NOT_UPDATED.check(ok, cfg) == []

    def test_r010_distill_requires_stop_gradient_marker(self):
        from vitax.analysis.rules import FROZEN_NOT_UPDATED, Program
        cfg = tiny_cfg(task="distill", gather_overlap="off")
        no_marker = Program(kind="train", arm="distill", config=cfg,
                            mlir="m", jaxpr="add mul",
                            frozen_paths=("teacher/params/head/kernel",),
                            opt_moment_paths=())
        assert FROZEN_NOT_UPDATED.check(no_marker, cfg)
        with_marker = Program(kind="train", arm="distill", config=cfg,
                              mlir="m", jaxpr="stop_gradient add",
                              frozen_paths=("teacher/params/head/kernel",),
                              opt_moment_paths=())
        assert FROZEN_NOT_UPDATED.check(with_marker, cfg) == []

    @pytest.mark.slow
    def test_check_invariants_scenario_arms(self, devices8):
        # the same rules_ran pin also runs in-process above and in
        # tools/lint.sh's fast-arm subset; this is the CLI-contract mirror
        import json
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "check_invariants.py"),
             "--arms", "probe", "distill", "--json"],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr[-2000:]
        doc = json.loads(proc.stdout)
        assert doc["ok"] is True and doc["errors"] == {}
        for arm_name in ("probe", "distill"):
            arm = doc["arms"][arm_name]
            assert arm["rules_ran"] == ["VTX-R001", "VTX-R002", "VTX-R003",
                                        "VTX-R005", "VTX-R010"]
            assert arm["findings"] == []
