"""Driver entry-point smoke tests: bench.py and __graft_entry__.py must keep
working — the round's benchmark and compile checks run through them.

Both run in subprocesses with JAX_PLATFORMS=cpu so the forced-platform guard
(vitax/platform.py) is exercised exactly as the driver exercises it."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd, extra_env=None, timeout=1500):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    env.update(extra_env or {})
    return subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=timeout)


@pytest.mark.slow
def test_bench_prints_one_json_line():
    r = _run([sys.executable, "bench.py", "--preset", "tiny", "--batch_size", "8",
              "--steps", "2", "--warmup", "1"])
    assert r.returncode == 0, r.stderr[-2000:]
    line = r.stdout.strip().splitlines()[-1]
    result = json.loads(line)
    # the four contract keys must be present (extra fields — "knobs", and
    # "error"/"last_measured" on failure paths — are part of the design)
    assert {"metric", "value", "unit", "vs_baseline"} <= set(result)
    assert result["unit"] == "images/sec/chip"
    assert result["value"] > 0
    assert result["knobs"]["batch_per_chip"] == 1  # global 8 over 8 devices


@pytest.mark.slow
def test_graft_dryrun_multichip():
    r = _run([sys.executable, "-c",
              "import __graft_entry__ as g; g.dryrun_multichip(8)"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "dryrun_multichip ok" in r.stdout


@pytest.mark.slow
def test_graft_entry_compiles_single_chip():
    r = _run([sys.executable, "-c", (
        "import jax, __graft_entry__ as g\n"
        "fn, args = g.entry()\n"
        "out = jax.jit(fn).lower(*args).compile()(*args)\n"
        "print('entry ok', out.shape)\n")],
        extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=1"})
    assert r.returncode == 0, r.stderr[-2000:]
    assert "entry ok" in r.stdout


def test_apply_ladder_picks_measured_winners(tmp_path, monkeypatch):
    """tools/apply_ladder.py closes the measure->tune loop: ladder rows +
    the stored default-config baseline in, per-preset knob winners out
    (TUNED.json), which bench.py's default_* functions then consult — the
    chip watcher can flip defaults to measured winners autonomously.
    Safety rules under test: never flip away from an UNMEASURED current
    default; ignore errored/truncated/non-knob rows; small wins below
    min_gain don't flip; a policy win flips the policy."""
    import json
    import importlib

    def knobs(sb, su, rw, policy, batch):
        # per-chip batch must equal the preset's default (train_presets(1))
        # or the row is deliberately non-comparable to the current default
        return {"scan_blocks": sb, "scan_unroll": su, "remat_window": rw,
                "remat_policy": policy, "batch_per_chip": batch}

    ladder = tmp_path / "ladder.jsonl"
    rows = [
        # l14 code default is the unrolled path: measure it, then beat it
        {"args": "--preset l14",
         "result": {"value": 250.0,
                    "knobs": knobs(False, 1, 0, "dots_attn_saveable", 32)}},
        {"args": "--preset l14 --remat_window 8",
         "result": {"value": 280.0,
                    "knobs": knobs(True, 1, 8, "dots_attn_saveable", 32)}},
        # b16: alternative beats the measured default by < min_gain -> keep
        {"args": "--preset b16 --no_scan_blocks",
         "result": {"value": 100.0,
                    "knobs": knobs(False, 1, 0, "dots_attn_saveable", 64)}},
        # 10b_slice: a policy-only win must flip the policy along (the
        # family code default is window-2 — LADDER_r04 — so the default
        # and alternative rows both carry it)
        {"args": "--preset 10b_slice --remat_policy dots_saveable",
         "result": {"value": 130.0,
                    "knobs": knobs(True, 1, 2, "dots_saveable", 64)}},
        # ignored rows: truncated, errored-with-positive-value, non-knob
        {"args": "--preset l14 --scan_unroll", "result": {"value": 999.0}},
        {"args": "--preset l14 --remat_window 16",
         "result": {"value": 999.0, "error": "watchdog killed",
                    "knobs": knobs(True, 1, 16, "dots_attn_saveable", 32)}},
        {"args": "--preset tiny --batch_size 8", "result": {"value": 999.0}},
    ]
    ladder.write_text("\n".join(json.dumps(r) for r in rows) + "\n")

    sys.path.insert(0, str(REPO))
    sys.path.insert(0, str(os.path.join(REPO, "tools")))
    apply_ladder = importlib.import_module("apply_ladder")
    baselines = {
        # b16's stored row IS its current default (scan path), measured
        "b16": {"images_per_sec_chip": 99.0, "scan_blocks": True,
                "scan_unroll": 1, "remat_window": 0,
                "remat_policy": "dots_attn_saveable"},
        # 10b_slice default (scan, window-2, none_saveable) measured at 116
        "10b_slice": {"images_per_sec_chip": 116.0, "scan_blocks": True,
                      "scan_unroll": 1, "remat_window": 2,
                      "remat_policy": "none_saveable"},
        # tiny default measured — but tiny has no eligible ladder rows
        "tiny": {"images_per_sec_chip": 3827.0, "scan_blocks": True,
                 "scan_unroll": 1, "remat_window": 0,
                 "remat_policy": "dots_attn_saveable"},
    }
    base_file = tmp_path / "BASELINE_MEASURED.json"
    base_file.write_text(json.dumps(baselines))
    out = tmp_path / "TUNED.json"
    monkeypatch.setattr(apply_ladder, "REPO", str(tmp_path))
    import bench
    monkeypatch.setattr(bench, "TUNED_FILE", str(out))  # pre-flip: absent
    monkeypatch.setattr(sys, "argv",
                        ["apply_ladder", "--ladder", str(ladder),
                         "--out", str(out)])
    apply_ladder.main()

    tuned = json.loads(out.read_text())
    # l14: windowed row beats the measured unrolled default (280 > 250*1.02)
    assert tuned["l14"]["remat_window"] == 8
    assert tuned["l14"]["scan_blocks"] is True
    # b16: 100.0 < 1.02 * 99.0 -> no entry, default stands
    assert "b16" not in tuned
    # tiny: default measured, no alternatives -> no entry
    assert "tiny" not in tuned
    # 10b_slice: the policy win rides into TUNED (window-2 rides along)
    assert tuned["10b_slice"]["remat_policy"] == "dots_saveable"
    assert tuned["10b_slice"]["remat_window"] == 2

    # bench.py defaults consult TUNED.json
    assert bench.default_remat_window("l14") == 8
    assert bench.default_scan_blocks("l14") is True
    assert bench.default_scan_blocks("b16") is True   # untouched fallback
    assert bench.default_remat_policy("10b_slice") == "dots_saveable"
    assert bench.default_remat_policy("l14") == "dots_attn_saveable"
    # explicit knob A/Bs pin the pre-TUNED policy
    assert bench.default_remat_policy("10b_slice",
                                      allow_tuned=False) == "none_saveable"
