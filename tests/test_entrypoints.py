"""Driver entry-point smoke tests: bench.py and __graft_entry__.py must keep
working — the round's benchmark and compile checks run through them.

Both run in subprocesses with JAX_PLATFORMS=cpu so the forced-platform guard
(vitax/platform.py) is exercised exactly as the driver exercises it."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd, extra_env=None, timeout=1500):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    env.update(extra_env or {})
    return subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=timeout)


@pytest.mark.slow
def test_bench_prints_one_json_line():
    r = _run([sys.executable, "bench.py", "--preset", "tiny", "--batch_size", "8",
              "--steps", "2", "--warmup", "1"])
    assert r.returncode == 0, r.stderr[-2000:]
    line = r.stdout.strip().splitlines()[-1]
    result = json.loads(line)
    assert set(result) == {"metric", "value", "unit", "vs_baseline"}
    assert result["unit"] == "images/sec/chip"
    assert result["value"] > 0


@pytest.mark.slow
def test_graft_dryrun_multichip():
    r = _run([sys.executable, "-c",
              "import __graft_entry__ as g; g.dryrun_multichip(8)"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "dryrun_multichip ok" in r.stdout


@pytest.mark.slow
def test_graft_entry_compiles_single_chip():
    r = _run([sys.executable, "-c", (
        "import jax, __graft_entry__ as g\n"
        "fn, args = g.entry()\n"
        "out = jax.jit(fn).lower(*args).compile()(*args)\n"
        "print('entry ok', out.shape)\n")],
        extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=1"})
    assert r.returncode == 0, r.stderr[-2000:]
    assert "entry ok" in r.stdout
