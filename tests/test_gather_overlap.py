"""Overlapped ZeRO-3 gather schedule (--gather_overlap) correctness.

The double-buffered prefetch schedule (vitax/models/vit.py:make_overlap_forward
+ vitax/parallel/sharding.py:prefetch_gather) must be a pure SCHEDULING change:
same collectives, same math, different placement. These tests pin that down:

- bitwise loss equality over 3 steps, on vs off, across the zero3 /
  zero3+bf16-gather / grad-accum arms;
- `off` dispatches to the exact pre-overlap forward (identical jaxpr);
- Config.validate rejects `on` under pipeline parallelism;
- the comm_audit structural verdict: per-iteration forward gather count
  unchanged, and under `on` every in-loop forward gather sits on the scan
  carry's prefetch slot instead of a parameter use site.

Geometry note: the bitwise arms use batch_size=64 (B*N=320 tokens). At the
smoke default of 16, B*N=80 < 4*embed_dim=128 and GSPMD partitions the MLP as
activation-gather + hidden-sharded partial dot + all-reduce — the baseline
never gathers the MLP weights, so a weight-gather schedule cannot match its
accumulation order bitwise. Above that threshold the baseline flips to plain
use-site weight gathers and bitwise equality is well-defined.
"""

import numpy as np
import pytest

import jax

from vitax.config import Config

from tests.test_train_smoke import build_train_objects, random_batch, tiny_cfg


def _run_losses(cfg, n_steps=3):
    mesh, state, step_fn, _ = build_train_objects(cfg)
    rng = jax.random.key(cfg.seed + 1)
    losses = []
    for i in range(n_steps):
        batch = random_batch(cfg, mesh, seed=i % 2)
        state, metrics = step_fn(state, batch, rng)
        losses.append(jax.device_get(metrics["loss"]))
    return np.asarray(losses)


OVERLAP_ARMS = {
    # plain ZeRO-3, f32 end to end
    "zero3": dict(batch_size=64),
    # bf16 compute + bf16 gather policy: the prefetched slices go through
    # cast_to_compute exactly like use-site gathers do
    "zero3_bf16_gather": dict(batch_size=64, dtype="bfloat16",
                              param_gather_dtype="bfloat16"),
    # in-step gradient accumulation: the overlap forward runs inside the
    # accum microbatch scan (microbatches of 64 stay above the GSPMD
    # MLP-strategy threshold)
    "accum2": dict(batch_size=128, grad_accum_steps=2, dtype="bfloat16"),
}


@pytest.mark.parametrize("arm", sorted(OVERLAP_ARMS))
def test_overlap_bitwise_vs_off(devices8, arm):
    """`on` must produce bit-identical losses to `off` over 3 steps (2 full
    optimizer updates): the schedule moves gathers, not math."""
    kw = OVERLAP_ARMS[arm]
    off = _run_losses(tiny_cfg(gather_overlap="off", **kw))
    on = _run_losses(tiny_cfg(gather_overlap="on", **kw))
    assert np.array_equal(off, on), (
        f"{arm}: overlap changed the numerics: off={off!r} on={on!r}")


@pytest.mark.parametrize("arm_kw", [
    dict(),                          # zero3
    dict(reshard_after_forward=False),  # zero2
    dict(run_without_fsdp=True),     # pure DP
], ids=["zero3", "zero2", "dp"])
def test_off_traces_identical_program(devices8, arm_kw):
    """gather_overlap=off must trace the exact pre-overlap forward — the
    dispatch in vitax/train/step.py:_forward_fn may not wrap or perturb the
    program in any way (same jaxpr as a direct model.apply closure)."""
    from vitax.models import build_model
    from vitax.ops.attention import make_attention_impl
    from vitax.parallel.mesh import build_mesh
    from vitax.train.loop import _token_sharding
    from vitax.train.state import build_optimizer, make_train_state
    from vitax.train.step import _forward_fn

    cfg = tiny_cfg(gather_overlap="off", **arm_kw)
    mesh = build_mesh(cfg)
    model = build_model(cfg, attention_impl=make_attention_impl(cfg, mesh),
                        token_sharding=_token_sharding(cfg, mesh))
    tx, _ = build_optimizer(cfg, max_iteration=10)
    state, sspecs, _ = make_train_state(cfg, model, tx, mesh,
                                        jax.random.key(0))
    images = random_batch(cfg, mesh)["image"]

    dispatched = _forward_fn(cfg, model, mesh, sspecs)
    direct = lambda p, x: model.apply(p, x, True)
    jaxpr_dispatched = str(jax.make_jaxpr(
        lambda p, x: dispatched(p, x, True))(state.params, images))
    jaxpr_direct = str(jax.make_jaxpr(direct)(state.params, images))
    assert jaxpr_dispatched == jaxpr_direct


def test_overlap_auto_selection(devices8):
    """auto == on exactly when the schedule is sound: ZeRO-3 + scanned
    blocks + full remat, no pipeline, sharded fsdp axis."""
    from vitax.parallel.mesh import build_mesh
    from vitax.parallel.sharding import gather_overlap_active

    zero3 = tiny_cfg()  # gather_overlap defaults to auto
    assert gather_overlap_active(zero3, build_mesh(zero3))
    zero2 = tiny_cfg(reshard_after_forward=False)
    assert not gather_overlap_active(zero2, build_mesh(zero2))
    dp = tiny_cfg(run_without_fsdp=True)
    assert not gather_overlap_active(dp, build_mesh(dp))
    off = tiny_cfg(gather_overlap="off")
    assert not gather_overlap_active(off, build_mesh(off))


def test_overlap_rejects_pipeline():
    """The prefetch carry threads through the single layer scan; under
    pp_size>1 blocks live on pipeline stages and the schedule is undefined —
    validate() must reject the combination outright."""
    with pytest.raises(AssertionError):
        Config(image_size=16, patch_size=8, embed_dim=32, num_heads=2,
               num_blocks=4, num_classes=4, batch_size=16,
               pp_size=2, gather_overlap="on").validate()


def test_comm_audit_overlap_verdict(devices8):
    """Structural HLO check via tools/comm_audit.py: the per-iteration
    forward gather count is unchanged between off and on, and under `on`
    every forward in-loop gather feeds the scan carry (prefetch slot) while
    under `off` none do."""
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools.comm_audit import audit_config

    base = dict(image_size=16, patch_size=8, embed_dim=32, num_heads=2,
                num_blocks=2, num_classes=4, batch_size=64, warmup_steps=2)
    off = audit_config(Config(**base, gather_overlap="off").validate())["overlap"]
    on = audit_config(Config(**base, gather_overlap="on").validate())["overlap"]

    # the first while body in program order is the forward scan
    off_fwd_body = next(iter(off["per_iteration_gather_count"]))
    on_fwd_body = next(iter(on["per_iteration_gather_count"]))
    off_fwd = off["per_iteration_gather_count"][off_fwd_body]
    on_fwd = on["per_iteration_gather_count"][on_fwd_body]

    # 12 block-param leaves -> 12 gathers per iteration, both schedules
    assert off_fwd == on_fwd > 0, (off, on)
    # off: all use-site (consumed by compute); on: all on the prefetch slot
    assert off["prefetch_slot_gathers"] == 0, off
    assert on["prefetch_slot_by_body"][on_fwd_body] == on_fwd, on
