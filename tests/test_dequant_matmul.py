"""Pallas fused dequant-matmul (vitax/ops/dequant_matmul.py) numerics.

Everything here runs in interpret mode on CPU (the `interpret=True` flag),
which emulates the kernel math faithfully — Mosaic lowering legality is the
on-chip tool's job (tools/check_kernels_on_chip.py check_dequant_matmul).
The oracle is the closed-form quantized math, NOT the float matmul: the
kernel's contract is "same integer sums, scales applied once after the
k-loop", so agreement with the closed form is tight (1e-5 relative) while
agreement with the float matmul is bounded only by quantization error.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import ml_dtypes

from vitax.ops.dequant_matmul import (
    DEQUANT_KERNEL_NAME,
    dequant_matmul,
    fused_dequant_active,
    quantize_activations,
)

# shapes cover: block-aligned, ragged in every dim (padding correctness),
# sub-block tiny, and a >1-block k so the k-loop accumulates across steps
SHAPES = [(64, 128, 256), (5, 33, 17), (130, 257, 96), (1, 8, 4)]


def _quantize_w(w, qmax, qdtype):
    scale = (np.abs(w).max(axis=0, keepdims=True) / qmax).astype(np.float32)
    scale[scale == 0] = 1.0
    if qdtype == np.int8:
        return np.clip(np.round(w / scale), -127, 127).astype(np.int8), scale
    return (w / scale).astype(qdtype), scale


def _rel_err(got, want):
    got = np.asarray(got, np.float32)
    return float(np.max(np.abs(got - want))
                 / max(1e-6, float(np.max(np.abs(want)))))


@pytest.mark.parametrize("m,k,n", SHAPES)
def test_weight_only_int8_matches_closed_form(m, k, n):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((m, k)).astype(np.float32)
    w = rng.standard_normal((k, n)).astype(np.float32) * 2.0
    w_q, scale = _quantize_w(w, 127.0, np.int8)
    want = x @ (w_q.astype(np.float32) * scale)
    fused = dequant_matmul(x, jnp.asarray(w_q), jnp.asarray(scale),
                           act=False, fused=True, interpret=True)
    unfused = dequant_matmul(x, jnp.asarray(w_q), jnp.asarray(scale),
                             act=False, fused=False)
    assert _rel_err(fused, want) < 1e-5
    assert _rel_err(unfused, want) < 1e-5


@pytest.mark.parametrize("m,k,n", SHAPES)
def test_weight_only_fp8_matches_closed_form(m, k, n):
    rng = np.random.default_rng(1)
    x = rng.standard_normal((m, k)).astype(np.float32)
    w = rng.standard_normal((k, n)).astype(np.float32) * 2.0
    w_q, scale = _quantize_w(w, 240.0, ml_dtypes.float8_e4m3)
    want = x @ (w_q.astype(np.float32) * scale)
    fused = dequant_matmul(x, jnp.asarray(w_q), jnp.asarray(scale),
                           act=False, fused=True, interpret=True)
    assert _rel_err(fused, want) < 1e-5


@pytest.mark.parametrize("m,k,n", SHAPES)
def test_act_quant_fused_matches_unfused_bitwise(m, k, n):
    """Fused and unfused act-quant paths compute the SAME int32 sums and
    apply the same scales, so they agree bit-for-bit — the strongest form
    of the <= 1e-2 acceptance bound."""
    rng = np.random.default_rng(2)
    x = rng.standard_normal((m, k)).astype(np.float32)
    w = rng.standard_normal((k, n)).astype(np.float32)
    w_q, scale = _quantize_w(w, 127.0, np.int8)
    fused = dequant_matmul(x, jnp.asarray(w_q), jnp.asarray(scale),
                           act=True, fused=True, interpret=True)
    unfused = dequant_matmul(x, jnp.asarray(w_q), jnp.asarray(scale),
                             act=True, fused=False)
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(unfused))
    # and both match the closed-form quantized oracle exactly
    xq, sx = jax.device_get(quantize_activations(jnp.asarray(x)))
    want = ((xq.astype(np.int32) @ w_q.astype(np.int32)).astype(np.float32)
            * float(sx) * scale)
    assert _rel_err(fused, want) < 1e-5


def test_leading_dims_reshape():
    """(B, N, K) inputs flatten through the 2-D kernel and reshape back."""
    rng = np.random.default_rng(3)
    x = rng.standard_normal((2, 7, 33)).astype(np.float32)
    w = rng.standard_normal((33, 12)).astype(np.float32)
    w_q, scale = _quantize_w(w, 127.0, np.int8)
    out = dequant_matmul(x, jnp.asarray(w_q), jnp.asarray(scale),
                         act=False, fused=True, interpret=True)
    assert out.shape == (2, 7, 12)
    want = x.reshape(14, 33) @ (w_q.astype(np.float32) * scale)
    assert _rel_err(np.asarray(out).reshape(14, 12), want) < 1e-5


def test_quantize_activations_zeros_and_range():
    # all-zero input: scale clamps to 1.0, no division by zero
    xq, sx = jax.device_get(quantize_activations(jnp.zeros((4, 8))))
    assert float(sx) == 1.0 and np.all(xq == 0)
    # range: symmetric round-to-nearest within the +-127 grid
    x = np.linspace(-3.0, 3.0, 64, dtype=np.float32).reshape(8, 8)
    xq, sx = jax.device_get(quantize_activations(jnp.asarray(x)))
    assert xq.dtype == np.int8 and np.abs(xq).max() <= 127
    np.testing.assert_allclose(xq.astype(np.float32) * float(sx), x,
                               atol=float(sx) / 2 + 1e-7)


def test_kernel_launch_visible_in_jaxpr():
    """The pallas_call carries DEQUANT_KERNEL_NAME — the marker VTX-R009
    greps for in the traced serve program."""
    rng = np.random.default_rng(4)
    x = rng.standard_normal((8, 32)).astype(np.float32)
    w_q, scale = _quantize_w(
        rng.standard_normal((32, 16)).astype(np.float32), 127.0, np.int8)
    jaxpr = str(jax.make_jaxpr(
        lambda a: dequant_matmul(a, jnp.asarray(w_q), jnp.asarray(scale),
                                 act=True, fused=True, interpret=True))(x))
    assert DEQUANT_KERNEL_NAME in jaxpr
    # the unfused path must NOT launch it (that's what the negative arm of
    # the rule distinguishes)
    jaxpr_u = str(jax.make_jaxpr(
        lambda a: dequant_matmul(a, jnp.asarray(w_q), jnp.asarray(scale),
                                 act=True, fused=False))(x))
    assert DEQUANT_KERNEL_NAME not in jaxpr_u


def test_fused_dequant_active_policy():
    """auto = quantized dense model on TPU; on forces; off kills."""
    from vitax.config import Config
    base = dict(image_size=16, patch_size=8, embed_dim=32, num_heads=2,
                num_blocks=2, num_classes=4, batch_size=16, dtype="float32",
                warmup_steps=2, serve_max_batch=4)
    cfg = Config(**base, serve_quant_dtype="int8").validate()
    # auto on CPU (interpret mode): stays off — the XLA fallback is faster
    # than an emulated kernel
    assert fused_dequant_active(cfg) is False
    cfg_on = Config(**base, serve_quant_dtype="int8",
                    fused_dequant="on").validate()
    assert fused_dequant_active(cfg_on) is True
    cfg_off = Config(**base, serve_quant_dtype="int8",
                     fused_dequant="off").validate()
    assert fused_dequant_active(cfg_off) is False
    # no quantized weights -> nothing to fuse, auto resolves False
    assert fused_dequant_active(Config(**base).validate()) is False
