"""vitax.telemetry tier-1 tests: analytic FLOPs model (closed-form), JSONL
sink round-trip, recorder fail-soft, watchdog fire/silence, telemetry-off
step-program identity, the instrumented train smoke, and
tools/metrics_report.py --json.
"""

import json
import math
import os
import subprocess
import sys
import time

import pytest

import jax

from vitax.config import Config
from vitax.telemetry import (
    REQUIRED_STEP_KEYS, SCHEMA_VERSION, Watchdog, build_recorder,
    detect_peak_tflops, make_tensorboard_sink, model_flops_per_image)
from vitax.telemetry.flops import mfu as mfu_of
from vitax.utils.metrics import SmoothedValue

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_cfg(**kw):
    base = dict(
        image_size=16, patch_size=8, embed_dim=32, num_heads=2, num_blocks=2,
        num_classes=4, batch_size=16, dtype="float32", lr=1e-3, warmup_steps=2,
        clip_grad_norm=1.0, seed=0,
    )
    base.update(kw)
    return Config(**base).validate()


# --- satellite: SmoothedValue.get_latest on an empty window ---

def test_get_latest_empty_returns_nan():
    sv = SmoothedValue(window_size=3)
    assert math.isnan(sv.get_latest())  # raised IndexError before
    sv.update(3.5)
    assert sv.get_latest() == 3.5
    sv.reset()
    assert math.isnan(sv.get_latest())


# --- analytic FLOPs model: closed-form checks ---

def test_flops_closed_form_dense():
    cfg = tiny_cfg()
    d, L, n, h = 32, 2, 4, 128  # embed, blocks, patches (16/8)^2, mlp hidden
    per_token = 2 * (3 * d * d + d * d) + 2 * (d * h + h * d)
    attn = 2 * 2 * n * n * d
    fwd = L * (per_token * n + attn)
    fwd += 2 * n * (3 * 8 ** 2) * d          # patchify
    fwd += 2 * d * cfg.num_classes           # head
    assert model_flops_per_image(cfg) == pytest.approx(3.0 * fwd)


def test_flops_closed_form_moe_top_k():
    cfg = tiny_cfg(moe_experts=4, moe_top_k=2)
    d, L, n, h = 32, 2, 4, 128
    per_token = (2 * (3 * d * d + d * d)          # qkv + proj
                 + 2 * 2 * (d * h + h * d)        # top-2 expert MLPs
                 + 2 * d * 4)                     # router logits
    attn = 2 * 2 * n * n * d
    fwd = L * (per_token * n + attn) + 2 * n * (3 * 8 ** 2) * d + 2 * d * 4
    assert model_flops_per_image(cfg) == pytest.approx(3.0 * fwd)
    # top-2 MoE does strictly more useful work per image than dense
    assert model_flops_per_image(cfg) > model_flops_per_image(tiny_cfg())


def test_flops_invariant_under_grad_accum():
    # accumulation reshapes where samples flow, not the per-step FLOPs
    assert model_flops_per_image(tiny_cfg()) == model_flops_per_image(
        tiny_cfg(grad_accum_steps=4))


def test_peak_tflops_table_and_override():
    assert detect_peak_tflops("TPU v5e") == 197.0
    assert detect_peak_tflops("TPU v4") == 275.0
    assert detect_peak_tflops("cpu") == 1.0
    assert detect_peak_tflops("unknown accelerator") == 197.0
    assert detect_peak_tflops("TPU v5e", override=300.0) == 300.0  # --peak_tflops


def test_mfu_bounds():
    cfg = tiny_cfg()
    assert mfu_of(cfg, sec_per_iter=0.0, n_devices=8, peak_tflops_per_chip=1.0) == 0.0
    v = mfu_of(cfg, sec_per_iter=1.0, n_devices=8, peak_tflops_per_chip=1.0)
    assert 0.0 < v <= 1.0


# --- config validation of the new flags ---

def test_validate_rejects_bad_telemetry_flags():
    with pytest.raises(AssertionError):
        tiny_cfg(profile_num_steps=0)
    with pytest.raises(AssertionError):
        tiny_cfg(profile_start_step=-1)
    with pytest.raises(AssertionError):
        tiny_cfg(hang_timeout_s=-1.0)
    with pytest.raises(AssertionError):
        tiny_cfg(peak_tflops=-5.0)
    with pytest.raises(AssertionError):
        tiny_cfg(tensorboard=True)  # needs --metrics_dir


# --- recorder + JSONL sink round-trip ---

def test_jsonl_roundtrip(tmp_path):
    cfg = tiny_cfg(metrics_dir=str(tmp_path / "m"))
    rec = build_recorder(cfg, n_devices=8, device_kind="cpu", rank=0)
    assert rec is not None
    for i in range(1, 4):
        rec.record_step(step=i, epoch=1, step_in_epoch=i, loss=2.0 - 0.1 * i,
                        lr=1e-3, sec_per_iter=0.5, data_wait_s=0.01,
                        grad_norm=1.5)
    rec.event("hang", stalled_s=12.0, stacks="fake")
    rec.close()

    lines = (tmp_path / "m" / "metrics.jsonl").read_text().splitlines()
    records = [json.loads(ln) for ln in lines]  # every line must parse
    steps = [r for r in records if "kind" not in r]
    events = [r for r in records if r.get("kind") == "hang"]
    assert len(steps) == 3 and len(events) == 1
    for r in steps:
        assert set(REQUIRED_STEP_KEYS) <= set(r), r
        assert r["schema"] == SCHEMA_VERSION
        assert 0.0 < r["mfu"] <= 1.0
    assert [r["step"] for r in steps] == sorted(r["step"] for r in steps)
    assert steps[0]["images_per_sec"] == pytest.approx(16 / 0.5)
    assert steps[0]["tokens_per_sec"] == pytest.approx(16 * 4 / 0.5)


def test_recorder_none_when_off_or_nonzero_rank(tmp_path):
    assert build_recorder(tiny_cfg(), 8, "cpu", rank=0) is None  # no dir
    cfg = tiny_cfg(metrics_dir=str(tmp_path / "m"))
    assert build_recorder(cfg, 8, "cpu", rank=1) is None  # rank 0 owns records


def test_recorder_fail_soft_on_unwritable_dir(tmp_path, capsys):
    blocker = tmp_path / "file"
    blocker.write_text("not a dir")
    cfg = tiny_cfg(metrics_dir=str(blocker / "sub"))  # mkdir will fail
    assert build_recorder(cfg, 8, "cpu", rank=0) is None  # warned, no raise
    assert "not" in capsys.readouterr().err.lower()


def test_tensorboard_sink_degrades_without_package(tmp_path, monkeypatch):
    monkeypatch.setitem(sys.modules, "tensorboard", None)
    monkeypatch.setitem(sys.modules, "tensorboard.summary", None)
    assert make_tensorboard_sink(str(tmp_path / "tb")) is None


def test_tensorboard_sink_writes_events(tmp_path):
    pytest.importorskip("tensorboard")
    sink = make_tensorboard_sink(str(tmp_path / "tb"))
    assert sink is not None
    sink.write({"schema": 1, "step": 1, "loss": 2.0, "mfu": 0.1})
    sink.write({"schema": 1, "kind": "hang", "rank": 0})  # events: TB no-op
    sink.close()
    files = os.listdir(tmp_path / "tb")
    assert any("tfevents" in f for f in files), files


# --- watchdog ---

def test_watchdog_fires_on_stall():
    fired = []
    wd = Watchdog(timeout_s=0.15, on_fire=fired.append, rank=3,
                  poll_s=0.02).start()
    try:
        time.sleep(0.6)  # never petted
        assert wd.fire_count == 1, "must fire once per stall, not per poll"
        payload = fired[0]
        assert payload["stalled_s"] >= 0.15
        assert "vitax-watchdog" in payload["stacks"]  # all-thread dump
        assert "MainThread" in payload["stacks"]
        wd.pet()  # progress re-arms it
        time.sleep(0.4)
        assert wd.fire_count == 2
    finally:
        wd.stop()


def test_watchdog_silent_on_healthy_loop(capsys):
    wd = Watchdog(timeout_s=0.3, poll_s=0.02).start()
    try:
        for _ in range(30):
            wd.pet()
            time.sleep(0.02)
    finally:
        wd.stop()
    assert wd.fire_count == 0
    assert "watchdog" not in capsys.readouterr().err


# --- step program identity + host-side work counts ---

def test_telemetry_off_traces_identical_step_program(devices8):
    """--metrics_dir / --hang_timeout_s / --peak_tflops are host-side only:
    the lowered step program must be bit-identical with telemetry on or off
    (the acceptance pin against new device ops / extra syncs)."""
    from tests.test_train_smoke import build_train_objects, random_batch

    def lowered(cfg):
        mesh, state, step_fn, _ = build_train_objects(cfg)
        batch = random_batch(cfg, mesh)
        return step_fn.lower(state, batch, jax.random.key(0)).as_text()

    off = lowered(tiny_cfg())
    on = lowered(tiny_cfg(metrics_dir="/tmp/vitax_metrics_identity_test",
                          hang_timeout_s=300.0, peak_tflops=197.0))
    assert off == on


def test_step_metrics_carry_work_counts(devices8):
    from tests.test_train_smoke import build_train_objects, random_batch
    cfg = tiny_cfg()
    mesh, state, step_fn, _ = build_train_objects(cfg)
    _, metrics = step_fn(state, random_batch(cfg, mesh), jax.random.key(0))
    # host-side statics (no device ops): batch images, patches per image
    assert metrics["images"] == cfg.batch_size
    assert metrics["tokens"] == cfg.batch_size * cfg.num_patches


# --- instrumented train smoke: the acceptance JSONL contract ---

def _smoke_cfg(tmp_path, **kw):
    base = dict(
        fake_data=True, num_epochs=1, steps_per_epoch=3, log_step_interval=1,
        ckpt_dir=str(tmp_path / "ckpt"), ckpt_epoch_interval=99,
        test_epoch_interval=99, num_workers=2, eval_max_batches=1,
        metrics_dir=str(tmp_path / "metrics"), hang_timeout_s=120.0,
    )
    base.update(kw)
    return tiny_cfg(**base)


def test_train_smoke_emits_jsonl_and_report(tmp_path, devices8):
    from vitax.train.loop import train
    train(_smoke_cfg(tmp_path))

    path = tmp_path / "metrics" / "metrics.jsonl"
    records = [json.loads(ln) for ln in path.read_text().splitlines()]
    steps = [r for r in records if "kind" not in r]
    events = [r for r in records if "kind" in r]
    assert len(steps) == 3  # log_step_interval=1 -> one record per step
    for r in steps:
        for key in ("step", "loss", "sec_per_iter", "data_wait_s", "mfu",
                    "mem_used_bytes"):
            assert key in r, (key, r)
        assert r["schema"] == SCHEMA_VERSION
        assert 0.0 < r["mfu"] <= 1.0
        assert r["data_wait_s"] >= 0.0
        assert r["sec_per_iter"] > 0.0
    assert [r["step"] for r in steps] == [1, 2, 3]  # monotonic global steps
    # the watchdog observed the whole healthy run and never fired
    assert not [e for e in events if e.get("kind") == "hang"]
    assert any(e.get("kind") == "run_start" for e in events)

    # metrics_report --json over the run: the CI summary contract
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "metrics_report.py"),
         str(path), "--json"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    summary = json.loads(r.stdout)
    assert summary["records"] == 3
    assert summary["hang_events"] == 0
    assert 0.0 < summary["mfu_last"] <= 1.0
    assert summary["sec_per_iter_p50"] > 0
    assert summary["sec_per_iter_p95"] >= summary["sec_per_iter_p50"]
    assert summary["data_wait_fraction"] is not None
    assert len(summary["loss_curve"]) == 3


def test_profile_window_configurable(tmp_path, devices8):
    """--profile_start_step/--profile_num_steps move the trace window (the
    hardcoded steps-3..7 satellite); a window starting at step 0 still
    produces trace artifacts on a 2-step run (the old constants could not)."""
    from vitax.train.loop import train
    prof_dir = str(tmp_path / "trace")
    train(_smoke_cfg(tmp_path, steps_per_epoch=2, profile_dir=prof_dir,
                     profile_start_step=0, profile_num_steps=2,
                     metrics_dir="", hang_timeout_s=0.0))
    found = [f for _, _, fs in os.walk(prof_dir) for f in fs]
    assert any(f.endswith((".pb", ".json.gz", ".trace.json.gz"))
               for f in found), found


# --- metrics_report over a synthetic run (accelerator-free) ---

def test_metrics_report_synthetic(tmp_path):
    path = tmp_path / "run.jsonl"
    with open(path, "w") as f:
        for i in range(1, 21):
            f.write(json.dumps({
                "schema": 1, "time": 1000.0 + i, "step": i, "epoch": 1,
                "step_in_epoch": i, "loss": 3.0 - 0.1 * i, "lr": 1e-3,
                "sec_per_iter": 0.5 + (0.5 if i == 20 else 0.0),
                "images_per_sec": 32.0, "tokens_per_sec": 8192.0,
                "data_wait_s": 0.05, "mfu": 0.4, "mem_used_bytes": 123456,
                "mem_peak_bytes": 234567}) + "\n")
        f.write(json.dumps({"schema": 1, "kind": "hang", "rank": 0,
                            "stalled_s": 99.0, "stacks": "..."}) + "\n")
        f.write("{corrupt json\n")

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import metrics_report
    finally:
        sys.path.pop(0)
    summary = metrics_report.summarize(str(path))
    assert summary["records"] == 20
    assert summary["corrupt_lines"] == 1
    assert summary["hang_events"] == 1
    assert summary["sec_per_iter_p50"] == pytest.approx(0.5)
    assert summary["sec_per_iter_p95"] > 0.5  # the slow tail is visible
    assert summary["data_wait_fraction"] == pytest.approx(
        (19 * 0.1 + 0.05) / 20)
    assert summary["loss_first"] == pytest.approx(2.9)
    assert summary["loss_last"] == pytest.approx(1.0)
    assert summary["mem_peak_bytes"] == 234567

    # human mode renders without crashing and flags the hang
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "metrics_report.py"),
         str(path)], capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "watchdog hang events: 1" in r.stdout

    # empty file -> exit 2 (CI must notice a run that recorded nothing)
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "metrics_report.py"),
         str(empty), "--json"], capture_output=True, text=True, timeout=120)
    assert r.returncode == 2
