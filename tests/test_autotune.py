"""Autotuner + perf-regression CI tests (vitax/tune/, tools/autotune.py,
tools/perf_gate.py, vitax/telemetry/schema.py).

Fast tier: the compile-only cost model's ranking pins, successive-halving
budget math, trial-JSONL schema round-trips, preset apply semantics, and the
perf_gate pass/fail/exit-code contract on synthetic trajectories — all pure
host-side code, no compiles. Slow tier: the off-TPU degradation path end to
end — `tools/autotune.py --compile_only` must produce a deterministic ranked
shortlist and a committable preset that `bench.py --preset_file` reproduces
knob-for-knob."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from vitax.config import Config  # noqa: E402
from vitax.telemetry.schema import (  # noqa: E402
    validate_autotune_trial, validate_bench_file, validate_bench_payload,
    validate_trials_file)
from vitax.tune.cost import analytic_cost, check_ranking  # noqa: E402
from vitax.tune.driver import (  # noqa: E402
    TrialLog, plan_successive_halving, run_search)
from vitax.tune.knobs import (  # noqa: E402
    KNOB_PAYLOAD_KEYS, add_knob_args, knob_payload)
from vitax.tune.preset import (  # noqa: E402
    apply_preset_to_args, config_defaults_from_preset, load_preset,
    make_preset, preset_path, save_preset)
from vitax.tune.space import candidate_space, rank_serve_geometries  # noqa: E402

import perf_gate  # noqa: E402  (tools/perf_gate.py)

TINY_KW = dict(image_size=224, patch_size=16, embed_dim=192, num_heads=3,
               num_blocks=12)


def _tiny_cfg(n_dev=1, **over):
    kw = dict(TINY_KW, num_classes=1000, warmup_steps=0,
              batch_size=32 * n_dev)
    kw.update(over)
    return Config(**kw).validate()


def _tiny_knobs(n_dev=1, **over):
    return knob_payload(_tiny_cfg(n_dev, **over), n_dev)


# ---------------------------------------------------------------- cost model

def test_cost_model_ranking_pins_all_green():
    """The compile-only cost model must order every known-ordered knob pair
    correctly (the perf_gate --check_ranking CI arm)."""
    results = check_ranking()
    assert len(results) >= 5
    bad = [r for r in results if not r["ok"]]
    assert not bad, bad


def test_gather_overlap_off_never_outranks_auto_on_zero3():
    """The ISSUE's named example, pinned directly (not only through the
    KNOWN_ORDERED_PAIRS table)."""
    base = dict(TINY_KW, num_classes=1000, warmup_steps=0,
                batch_size=32 * 8, fsdp_size=-1, scan_blocks=True,
                grad_ckpt=True, remat_policy="none_saveable")
    auto = Config(**base, gather_overlap="auto").validate()
    off = Config(**base, gather_overlap="off").validate()
    c_auto = analytic_cost(auto, 8, 197.0)
    c_off = analytic_cost(off, 8, 197.0)
    assert c_auto["overlap_active"]
    assert not c_off["overlap_active"]
    assert c_auto["sec_per_image_chip"] <= c_off["sec_per_image_chip"]


def test_analytic_cost_fields():
    c = analytic_cost(_tiny_cfg(8), 8, 197.0)
    for key in ("step_s", "sec_per_image_chip", "recompute_flops",
                "gather_bytes", "reduce_bytes", "live_bytes_estimate"):
        assert key in c and c[key] >= 0, key
    assert c["step_s"] > 0


# ------------------------------------------------------- successive halving

def test_plan_halving_exact_budget_when_min_not_binding():
    plan = plan_successive_halving(8, 800, min_steps=5)
    assert plan == [(8, 25), (4, 50), (2, 100), (1, 200)]
    assert sum(n * s for n, s in plan) == 800


def test_plan_halving_min_steps_floor():
    plan = plan_successive_halving(8, 240, min_steps=10)
    assert plan[0] == (8, 10)  # 240/4 rounds // 8 = 7 -> clamped to 10
    assert [n for n, _ in plan] == [8, 4, 2, 1]
    assert all(s >= 10 for _, s in plan)


def test_plan_halving_single_candidate_gets_whole_budget():
    assert plan_successive_halving(1, 100, min_steps=10) == [(1, 100)]


def test_plan_halving_rejects_bad_args():
    with pytest.raises(AssertionError):
        plan_successive_halving(0, 100)
    with pytest.raises(AssertionError):
        plan_successive_halving(4, 100, eta=1)


# ------------------------------------------------------ trial JSONL schema

def test_trial_log_roundtrip_validates(tmp_path):
    path = str(tmp_path / "trials.jsonl")
    log = TrialLog(path)
    knobs = _tiny_knobs()
    log.write("tiny", "cpu:1", "analytic", knobs, rank=0,
              cost={"step_s": 0.1})
    log.write("tiny", "cpu:1", "compile", knobs, compile_s=1.5,
              compile={"live_bytes": 123})
    log.write("tiny", "cpu:1", "measure", knobs, pruned_by="halving",
              round=0)
    log.close()
    assert validate_trials_file(path) == []
    recs = [json.loads(line) for line in open(path)]
    assert [r["trial_id"] for r in recs] == [0, 1, 2]
    assert all(r["kind"] == "autotune_trial" and r["schema"] == 1
               for r in recs)


def test_trials_file_rejects_non_monotone_and_corrupt(tmp_path):
    knobs = _tiny_knobs()

    def rec(tid):
        return json.dumps({"schema": 1, "kind": "autotune_trial",
                           "trial_id": tid, "time": 1.0,
                           "model_preset": "tiny", "topology": "cpu:1",
                           "phase": "analytic", "knobs": knobs,
                           "pruned_by": None})

    path = str(tmp_path / "bad.jsonl")
    with open(path, "w") as f:
        f.write(rec(0) + "\n" + rec(2) + "\n" + rec(1) + "\n")
    errs = validate_trials_file(path)
    assert any("not monotone" in e for e in errs)

    path2 = str(tmp_path / "corrupt.jsonl")
    with open(path2, "w") as f:
        f.write(rec(0) + "\n{not json\n")
    assert any("invalid JSON" in e for e in validate_trials_file(path2))


def test_validate_autotune_trial_rejects_bad_records():
    knobs = _tiny_knobs()
    good = {"schema": 1, "kind": "autotune_trial", "trial_id": 0,
            "time": 1.0, "model_preset": "tiny", "topology": "cpu:1",
            "phase": "analytic", "knobs": knobs, "pruned_by": None}
    assert validate_autotune_trial(good) == []
    assert validate_autotune_trial({**good, "phase": "searching"})
    assert validate_autotune_trial({**good, "pruned_by": "vibes"})
    assert validate_autotune_trial({**good, "trial_id": True})
    assert validate_autotune_trial({**good, "schema": 2})
    missing = {k: v for k, v in good.items() if k != "pruned_by"}
    assert validate_autotune_trial(missing)
    incomplete = dict(good, knobs={"batch_per_chip": 32})
    assert validate_autotune_trial(incomplete)


def test_validate_bench_payload_contract():
    good = {"metric": "images/sec/chip (ViT-tiny, train step)",
            "value": 100.0, "unit": "images/sec/chip", "vs_baseline": None,
            "knobs": _tiny_knobs()}
    assert validate_bench_payload(good) == []
    assert validate_bench_payload({k: v for k, v in good.items()
                                   if k != "vs_baseline"})
    assert validate_bench_payload({**good, "value": "fast"})
    assert validate_bench_payload({**good, "knobs": [1, 2]})


def test_repo_bench_trajectory_validates():
    """Every committed BENCH_r*.json must pass the schema validator (the
    lint.sh / perf_gate --validate guard, run in-process)."""
    import glob
    files = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    assert files
    for path in files:
        assert validate_bench_file(path) == [], path


# -------------------------------------------------------- candidate space

def test_candidate_space_deterministic_and_valid():
    kw = dict(TINY_KW)
    a, inv_a = candidate_space("tiny", 8, kw)
    b, inv_b = candidate_space("tiny", 8, kw)
    assert a == b and inv_a == inv_b
    assert len(a) > 50
    for cand in a[:5]:
        Config(**cand).validate()


def test_serve_geometry_ranking_deterministic():
    r1 = rank_serve_geometries()
    r2 = rank_serve_geometries()
    assert r1 == r2
    assert r1[0]["serve_max_batch"] >= 1
    assert r1 == sorted(r1, key=lambda r: (r["score"], r["serve_max_batch"],
                                           r["max_batch_wait_ms"]))


# ------------------------------------------------- run_search (off-TPU path)

def _search(tmp_path, n_dev, tag):
    log = TrialLog(str(tmp_path / f"trials_{tag}.jsonl"))
    try:
        return run_search("tiny", f"cpu:{n_dev}", dict(TINY_KW), n_dev, log,
                          peak_tflops=1.0, max_candidates=48, shortlist=4,
                          compile_top=0, measure=False,
                          log_fn=lambda *_: None)
    finally:
        log.close()


def test_run_search_deterministic_across_runs_and_topologies(tmp_path):
    """The off-TPU degradation contract: same ranked shortlist on repeat
    runs, for more than one topology, with schema-valid trial logs."""
    for n_dev in (1, 8):
        r1 = _search(tmp_path, n_dev, f"{n_dev}a")
        r2 = _search(tmp_path, n_dev, f"{n_dev}b")
        assert [e["knobs"] for e in r1["ranked"]] == \
               [e["knobs"] for e in r2["ranked"]]
        assert r1["winner"]["knobs"] == r2["winner"]["knobs"]
        assert len(r1["ranked"]) == 4
        errs = validate_trials_file(str(tmp_path / f"trials_{n_dev}a.jsonl"))
        assert errs == []


def test_run_search_trial_log_covers_all_candidates(tmp_path):
    r = _search(tmp_path, 1, "cov")
    path = str(tmp_path / "trials_cov.jsonl")
    recs = [json.loads(line) for line in open(path)]
    assert len(recs) == r["n_candidates"]  # every candidate logged
    pruned = [x for x in recs if x["pruned_by"] == "cost_rank"]
    assert len(pruned) == r["n_candidates"] - len(r["ranked"])


# ----------------------------------------------------------------- presets

def test_preset_emit_load_bitwise(tmp_path):
    knobs = _tiny_knobs()
    preset = make_preset("tiny", "cpu:1", knobs,
                         serve={"serve_max_batch": 8,
                                "max_batch_wait_ms": 5.0},
                         source={"mode": "compile_only"})
    path = save_preset(preset_path(str(tmp_path), "tiny", "cpu:1"), preset)
    assert path.endswith("tiny_cpu-1.json")
    loaded = load_preset(path)
    assert loaded == preset
    # byte-stable on re-save (sort_keys + fixed indent)
    with open(path, "rb") as f:
        first = f.read()
    save_preset(path, loaded)
    with open(path, "rb") as f:
        assert f.read() == first


def test_load_preset_rejects_malformed(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"kind": "other"}))
    with pytest.raises(ValueError):
        load_preset(str(p))
    p.write_text(json.dumps({"kind": "vitax_preset", "schema": 1,
                             "knobs": {"batch_per_chip": 8}}))
    with pytest.raises(ValueError):
        load_preset(str(p))


def test_apply_preset_explicit_cli_wins():
    import argparse
    knobs = _tiny_knobs(remat_policy="none_saveable",
                        param_gather_dtype="float32")
    preset = make_preset("tiny", "cpu:1", knobs)
    parser = add_knob_args(argparse.ArgumentParser())
    # explicit --remat_policy must survive the preset; everything else fills
    args = parser.parse_args(["--remat_policy", "dots_saveable"])
    applied = apply_preset_to_args(preset, args, n_dev=4)
    assert args.remat_policy == "dots_saveable"
    assert "remat_policy" not in applied
    assert args.batch_size == knobs["batch_per_chip"] * 4
    assert args.param_gather_dtype == "float32"
    assert args.gather_overlap == knobs["gather_overlap"]


def test_config_defaults_from_preset_clamps_sentinels():
    knobs = _tiny_knobs()
    knobs = dict(knobs, scan_unroll=0, remat_window=-1)
    preset = make_preset("tiny", "cpu:1", knobs,
                         serve={"serve_max_batch": 16,
                                "max_batch_wait_ms": 2.0})
    d = config_defaults_from_preset(preset)
    assert d["scan_unroll"] == 1 and d["remat_window"] == 0
    assert d["serve_max_batch"] == 16
    assert "batch_size" not in d  # per-chip batch never maps blind


# --------------------------------------------------------------- perf gate

def _bench_round(n, value, knobs=None, error=None):
    parsed = {"metric": f"images/sec/chip (ViT-l14, train step, TPU v5 lite,"
                        f" mfu=0.5, step_time=1ms, remat=x)",
              "value": value, "unit": "images/sec/chip", "vs_baseline": None}
    if knobs:
        parsed["knobs"] = knobs
    if error:
        parsed["error"] = error
    return {"n": n, "cmd": "bench", "rc": 0, "tail": "", "parsed": parsed}


def test_perf_gate_passes_then_fails_on_regression(tmp_path):
    root = str(tmp_path)
    knobs = _tiny_knobs()
    with open(os.path.join(root, "BENCH_r01.json"), "w") as f:
        json.dump(_bench_round(1, 100.0, knobs), f)
    with open(os.path.join(root, "BENCH_r02.json"), "w") as f:
        json.dump(_bench_round(2, 99.0, knobs), f)
    assert perf_gate.main(["--root", root, "--json"]) == 0

    # an outage round must be skipped, not treated as a 100% regression
    with open(os.path.join(root, "BENCH_r03.json"), "w") as f:
        json.dump(_bench_round(3, 0.0, error="backend unavailable"), f)
    assert perf_gate.main(["--root", root, "--json"]) == 0

    # >5% below best -> exit 1, and the --json contract names the series
    with open(os.path.join(root, "BENCH_r04.json"), "w") as f:
        json.dump(_bench_round(4, 80.0, knobs), f)
    assert perf_gate.main(["--root", root, "--json"]) == 1
    # a looser threshold passes again
    assert perf_gate.main(["--root", root, "--threshold_pct", "25"]) == 0


def test_perf_gate_json_contract(tmp_path, capsys):
    root = str(tmp_path)
    with open(os.path.join(root, "BENCH_r01.json"), "w") as f:
        json.dump(_bench_round(1, 100.0), f)
    rc = perf_gate.main(["--root", root, "--json"])
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 0
    assert out["kind"] == "perf_gate" and out["ok"] is True
    assert out["series"][0]["model"] == "l14"
    assert out["series"][0]["best"] == 100.0


def test_perf_gate_folds_autotune_trials(tmp_path):
    """A measured autotune trial extends the trajectory: a later slow trial
    for the same (preset, topology) trips the gate."""
    root = str(tmp_path)
    knobs = _tiny_knobs()
    trials = os.path.join(root, "trials.jsonl")
    base = {"schema": 1, "kind": "autotune_trial", "time": 1.0,
            "model_preset": "tiny", "topology": "cpu:1",
            "phase": "measure", "knobs": knobs, "pruned_by": None}
    with open(trials, "w") as f:
        f.write(json.dumps({**base, "trial_id": 0,
                            "images_per_sec_chip": 100.0}) + "\n")
        f.write(json.dumps({**base, "trial_id": 1,
                            "images_per_sec_chip": 50.0}) + "\n")
    assert perf_gate.main(["--root", root, "--trials", trials,
                           "--json"]) == 1
    assert perf_gate.main(["--root", root, "--trials", trials,
                           "--threshold_pct", "60"]) == 0


def test_perf_gate_validate_catches_bad_trials(tmp_path):
    root = str(tmp_path)
    trials = os.path.join(root, "trials.jsonl")
    with open(trials, "w") as f:
        f.write(json.dumps({"schema": 1, "kind": "autotune_trial",
                            "trial_id": 0}) + "\n")
    assert perf_gate.main(["--root", root, "--trials", trials,
                           "--validate", "--json"]) == 1


def test_perf_gate_check_ranking_green_at_head(tmp_path):
    assert perf_gate.main(["--root", str(tmp_path), "--check_ranking",
                           "--json"]) == 0


def test_perf_gate_passes_on_committed_trajectory():
    """HEAD must be green: the repo's own BENCH files + ranking pins."""
    assert perf_gate.main(["--root", REPO, "--trials", "--validate",
                           "--check_ranking", "--json"]) == 0


# ------------------------------------------------- end-to-end (subprocess)

def _run(cmd, timeout=1500, n_dev=8):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=f"--xla_force_host_platform_device_count={n_dev}")
    return subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=timeout)


@pytest.mark.slow
def test_autotune_compile_only_end_to_end(tmp_path):
    """The acceptance path: off-TPU `tools/autotune.py --compile_only`
    emits a deterministic ranked shortlist + schema-valid trial JSONL +
    committable presets across 2 topologies, and `bench.py --preset_file`
    reproduces the winning knob set exactly."""
    def go(tag):
        trials = str(tmp_path / f"trials_{tag}.jsonl")
        pdir = str(tmp_path / f"presets_{tag}")
        r = _run([sys.executable, "tools/autotune.py", "--preset", "tiny",
                  "--topologies", "cpu:1", "cpu:8", "--compile_only",
                  "--max_candidates", "24", "--shortlist", "4",
                  "--trials", trials, "--presets_dir", pdir, "--json"])
        assert r.returncode == 0, r.stderr[-2000:]
        summaries = [json.loads(line) for line in r.stdout.splitlines()
                     if line.startswith("{")]
        assert [s["topology"] for s in summaries] == ["cpu:1", "cpu:8"]
        assert validate_trials_file(trials) == []
        return summaries, pdir

    s1, pdir1 = go("a")
    s2, _ = go("b")
    # deterministic: identical shortlists and winners run-to-run
    assert [s["shortlist"] for s in s1] == [s["shortlist"] for s in s2]
    assert [s["winner_knobs"] for s in s1] == [s["winner_knobs"] for s in s2]

    preset_file = os.path.join(pdir1, "tiny_cpu-1.json")
    preset = load_preset(preset_file)
    assert preset["knobs"] == s1[0]["winner_knobs"]
    assert set(preset["knobs"]) == set(KNOB_PAYLOAD_KEYS)

    # one forced host device so the CPU step stays affordable; the preset
    # stores per-chip batch, so the payload's resolved knobs must equal the
    # preset's knobs EXACTLY
    r = _run([sys.executable, "bench.py", "--preset", "tiny",
              "--preset_file", preset_file, "--steps", "2", "--warmup", "1"],
             timeout=1500, n_dev=1)
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads(r.stdout.strip().splitlines()[-1])
    assert "error" not in payload, payload
    assert payload["knobs"] == preset["knobs"]


@pytest.mark.slow
def test_train_entrypoint_accepts_preset_file(tmp_path):
    """python -m vitax.train --preset_file: preset knobs become parser
    defaults; explicit flags still win (checked via a dry parse)."""
    knobs = _tiny_knobs(remat_policy="dots_saveable")
    preset = make_preset("tiny", "cpu:1", knobs)
    pfile = save_preset(str(tmp_path / "p.json"), preset)
    r = _run([sys.executable, "-c", (
        "from vitax.config import parse_config\n"
        f"cfg = parse_config(['--fake_data', '--preset_file', {pfile!r},\n"
        "                    '--remat_window', '0'])\n"
        "print('remat', cfg.remat_policy, cfg.remat_window)\n")])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "remat dots_saveable 0" in r.stdout
