"""Tier-1 lint guard: flake8 over vitax/ tests/ tools/ bench.py with the
repo's .flake8 settings (max-line-length 120). Skips cleanly when flake8 is
not installed (the bench/CI images don't ship it); tools/lint.sh is the
equivalent shell entry point.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_flake8_clean():
    pytest.importorskip("flake8")
    r = subprocess.run(
        [sys.executable, "-m", "flake8", "vitax/", "tests/", "tools/",
         "bench.py"],
        cwd=REPO, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"flake8 findings:\n{r.stdout}\n{r.stderr}"


def test_max_line_length_120():
    """flake8's E501 at 120, enforced without flake8 present: the one lint
    rule cheap enough to check directly, so the guard still bites on images
    where test_flake8_clean skips."""
    bad = []
    targets = [os.path.join(REPO, "bench.py")]
    for sub in ("vitax", "tests", "tools"):
        for dirpath, _, files in os.walk(os.path.join(REPO, sub)):
            targets += [os.path.join(dirpath, f) for f in files
                        if f.endswith(".py")]
    for path in targets:
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f, 1):
                if len(line.rstrip("\n")) > 120:
                    bad.append(f"{os.path.relpath(path, REPO)}:{i} "
                               f"({len(line.rstrip())} chars)")
    assert not bad, "lines over 120 chars:\n" + "\n".join(bad)
