"""Tier-1 lint guard: flake8 over vitax/ tests/ tools/ bench.py with the
repo's .flake8 settings (max-line-length 120), plus firing/silent fixtures
for VTX109 (network calls without an explicit timeout=). Skips the flake8
arm cleanly when flake8 is not installed (the bench/CI images don't ship
it); tools/lint.sh is the equivalent shell entry point.
"""

import os
import subprocess
import sys
import textwrap

import pytest

from vitax.analysis.ast_lint import lint_source

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_flake8_clean():
    pytest.importorskip("flake8")
    r = subprocess.run(
        [sys.executable, "-m", "flake8", "vitax/", "tests/", "tools/",
         "bench.py"],
        cwd=REPO, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"flake8 findings:\n{r.stdout}\n{r.stderr}"


def test_max_line_length_120():
    """flake8's E501 at 120, enforced without flake8 present: the one lint
    rule cheap enough to check directly, so the guard still bites on images
    where test_flake8_clean skips."""
    bad = []
    targets = [os.path.join(REPO, "bench.py")]
    for sub in ("vitax", "tests", "tools"):
        for dirpath, _, files in os.walk(os.path.join(REPO, sub)):
            targets += [os.path.join(dirpath, f) for f in files
                        if f.endswith(".py")]
    for path in targets:
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f, 1):
                if len(line.rstrip("\n")) > 120:
                    bad.append(f"{os.path.relpath(path, REPO)}:{i} "
                               f"({len(line.rstrip())} chars)")
    assert not bad, "lines over 120 chars:\n" + "\n".join(bad)


def _codes(source: str):
    return [(f.code, f.severity)
            for f in lint_source(textwrap.dedent(source), "fixture.py")]


def test_vtx109_fires_on_network_calls_without_timeout():
    src = """
    import socket
    import urllib.request

    def probe(url, addr):
        urllib.request.urlopen(url)
        socket.create_connection(addr)
    """
    assert _codes(src) == [("VTX109", "ERROR"), ("VTX109", "ERROR")]


def test_vtx109_silent_with_explicit_timeout():
    src = """
    import socket
    import urllib.request
    from http.client import HTTPConnection

    def probe(url, addr, host):
        urllib.request.urlopen(url, timeout=5.0)
        urllib.request.urlopen(url, None, 5.0)   # positional timeout
        socket.create_connection(addr, 2.0)
        HTTPConnection(host, 80, timeout=1.0)
    """
    assert _codes(src) == []


def test_vtx109_suppression_comment():
    src = """
    import urllib.request

    def probe(url):
        urllib.request.urlopen(url)  # vtx: ignore[VTX109] caller owns deadline
    """
    assert _codes(src) == []


def test_vtx109_production_tree_clean():
    """Every urlopen/create_connection/HTTPConnection in vitax/ and tools/
    carries an explicit timeout (or a reasoned suppression)."""
    findings = []
    for sub in ("vitax", "tools"):
        for dirpath, _, files in os.walk(os.path.join(REPO, sub)):
            for f in files:
                if not f.endswith(".py"):
                    continue
                path = os.path.join(dirpath, f)
                with open(path, encoding="utf-8") as fh:
                    findings += [x for x in lint_source(fh.read(), path)
                                 if x.code == "VTX109"]
    assert not findings, "\n".join(str(f) for f in findings)
