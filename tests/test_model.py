"""Model math tests: parameter-count parity with the reference's closed forms,
init statistics, forward shapes, scan-vs-loop equivalence, remat gradient parity.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vitax.config import Config
from vitax.models.vit import VisionTransformer, build_model, count_params, expected_param_count


def tiny_cfg(**kw):
    base = dict(
        image_size=32, patch_size=16, embed_dim=64, num_heads=2, num_blocks=2,
        mlp_ratio=4.0, num_classes=10, batch_size=8, dtype="float32",
    )
    base.update(kw)
    return Config(**base).validate()


def init_params(cfg, rng=0):
    model = build_model(cfg)
    x = jnp.zeros((2, cfg.image_size, cfg.image_size, 3), jnp.float32)
    return model, model.init(jax.random.key(rng), x, True)


def test_param_count_closed_form_10b():
    """The flagship config must hit the reference's exact 10,077,917,160
    (SURVEY.md section 6; reference README.md:3 '10 billion')."""
    cfg = Config()  # defaults = the 10B config
    assert expected_param_count(cfg) == 10_077_917_160


def test_param_count_tiny_matches_closed_form():
    cfg = tiny_cfg()
    _, params = init_params(cfg)
    assert count_params(params) == expected_param_count(cfg)


def test_param_count_vit_tiny_16():
    """BASELINE.json config 1: ViT-Tiny/16 (192 dim, 3 heads, 12 blocks)."""
    cfg = Config(image_size=224, patch_size=16, embed_dim=192, num_heads=3,
                 num_blocks=12, num_classes=1000, dtype="float32").validate()
    _, params = init_params(cfg)
    n = count_params(params)
    assert n == expected_param_count(cfg)
    # ViT-Tiny/16 is ~5.7M params
    assert 5_000_000 < n < 6_500_000


def test_forward_shape_and_dtype():
    cfg = tiny_cfg()
    model, params = init_params(cfg)
    x = jnp.ones((4, 32, 32, 3), jnp.float32)
    logits = model.apply(params, x, True)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32


def test_scan_and_unrolled_blocks_agree():
    """lax.scan over stacked params must compute the same function as an
    unrolled per-block loop (same per-layer weights)."""
    cfg_scan = tiny_cfg(scan_blocks=True, grad_ckpt=False)
    cfg_loop = tiny_cfg(scan_blocks=False, grad_ckpt=False)
    model_s, params_s = init_params(cfg_scan)
    model_l = build_model(cfg_loop)

    # Rebuild loop params from the stacked scan params.
    stacked = params_s["params"]["blocks"]
    loop_params = {k: v for k, v in params_s["params"].items() if k != "blocks"}
    for i in range(cfg_loop.num_blocks):
        loop_params[f"blocks_{i}"] = jax.tree.map(lambda a: a[i], stacked)

    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3), jnp.float32)
    out_s = model_s.apply(params_s, x, True)
    out_l = model_l.apply({"params": loop_params}, x, True)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_l), rtol=1e-5, atol=1e-5)


def test_scan_unroll_matches_unroll1():
    """--scan_unroll > 1 (multi-block windows inside lax.scan, the wgrad-
    fusion lever) must not change values or the stacked param tree; a
    non-divisor unroll exercises lax.scan's remainder handling."""
    cfg1 = tiny_cfg(grad_ckpt=True, num_blocks=5)
    model1, params = init_params(cfg1)
    x = jax.random.normal(jax.random.key(3), (2, 32, 32, 3), jnp.float32)

    def loss(model):
        return lambda p: jnp.sum(model.apply(p, x, True) ** 2)

    l1, g1 = jax.value_and_grad(loss(model1))(params)
    for unroll in (3, 64):  # non-divisor of num_blocks; > num_blocks clamps
        cfgu = tiny_cfg(grad_ckpt=True, num_blocks=5, scan_unroll=unroll)
        modelu = build_model(cfgu)
        assert jax.tree.structure(
            modelu.init(jax.random.key(0), x[:1], True)) == jax.tree.structure(
            params), "scan_unroll must keep the stacked param tree"
        lu, gu = jax.value_and_grad(loss(modelu))(params)
        np.testing.assert_allclose(float(l1), float(lu), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(gu)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


def test_remat_matches_no_remat():
    """Activation checkpointing must not change forward or gradient values."""
    cfg_a = tiny_cfg(grad_ckpt=True)
    cfg_b = tiny_cfg(grad_ckpt=False)
    model_a, params = init_params(cfg_a)
    model_b = build_model(cfg_b)
    x = jax.random.normal(jax.random.key(2), (2, 32, 32, 3), jnp.float32)

    def loss_fn(model):
        def f(p):
            return jnp.sum(model.apply(p, x, True) ** 2)
        return f

    la, ga = jax.value_and_grad(loss_fn(model_a))(params)
    lb, gb = jax.value_and_grad(loss_fn(model_b))(params)
    np.testing.assert_allclose(float(la), float(lb), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(ga), jax.tree.leaves(gb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_init_statistics():
    """trunc-normal(0.02) weights, zero biases, LN ones/zeros
    (timm _init_vit_weights semantics, reference run_vit_training.py:125-152)."""
    cfg = tiny_cfg(embed_dim=128, num_blocks=2)
    _, params = init_params(cfg)
    p = params["params"]

    qkv_kernel = p["blocks"]["attn"]["qkv"]["kernel"]
    std = float(jnp.std(qkv_kernel))
    assert 0.015 < std < 0.025, f"qkv kernel std {std} not ~0.02"
    # truncated at 2 sigma (bound leaves headroom for rescaling jax versions)
    assert float(jnp.max(jnp.abs(qkv_kernel))) < 0.046

    assert float(jnp.max(jnp.abs(p["blocks"]["attn"]["qkv"]["bias"]))) == 0.0
    np.testing.assert_array_equal(np.asarray(p["norm"]["scale"]), 1.0)
    np.testing.assert_array_equal(np.asarray(p["norm"]["bias"]), 0.0)

    pos = p["pos_embed"]
    assert pos.shape == (1, cfg.num_patches, cfg.embed_dim)
    std = float(jnp.std(pos))
    assert 0.015 < std < 0.025


def test_dropout_active_in_train_mode():
    cfg = tiny_cfg(pos_dropout=0.5, mlp_dropout=0.5)
    model, params = init_params(cfg)
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    out1 = model.apply(params, x, False, rngs={"dropout": jax.random.key(1)})
    out2 = model.apply(params, x, False, rngs={"dropout": jax.random.key(2)})
    assert not np.allclose(np.asarray(out1), np.asarray(out2))
    # deterministic mode is rng-independent
    out3 = model.apply(params, x, True)
    out4 = model.apply(params, x, True)
    np.testing.assert_array_equal(np.asarray(out3), np.asarray(out4))


def test_mean_pool_not_cls():
    """No CLS token: sequence length stays (image/patch)^2 and the head sees the
    mean-pooled sequence (reference run_vit_training.py:127,159-161)."""
    cfg = tiny_cfg()
    _, params = init_params(cfg)
    assert params["params"]["pos_embed"].shape[1] == (32 // 16) ** 2


def test_windowed_remat_matches_scan_path(devices8):
    """--remat_window w: the functional group-remat scan (make_windowed_forward)
    consumes the SAME stacked param tree and must reproduce the per-block
    scan path exactly — forward, grads, and a short training trajectory (the
    wgrad dus-stacking experiment must not change the math)."""
    import numpy as np
    from tests.test_train_smoke import run_steps
    from vitax.config import Config
    from vitax.models.vit import make_windowed_forward

    kw = dict(image_size=32, patch_size=8, embed_dim=32, num_heads=4,
              num_blocks=4, num_classes=4, batch_size=16, dtype="float32",
              fsdp_size=-1, warmup_steps=0, grad_ckpt=True)
    cfg_w = Config(remat_window=2, **kw).validate()
    cfg_ref = Config(**kw).validate()

    model = build_model(cfg_ref)
    x = jax.random.normal(jax.random.key(1),
                          (16, 32, 32, 3), jnp.float32)
    params = jax.jit(lambda k: model.init(k, x[:1], True))(jax.random.key(0))
    fwd_w = make_windowed_forward(cfg_w, model)

    ref = model.apply(params, x, True)
    got = jax.jit(fwd_w)(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    g_ref = jax.grad(lambda p: jnp.sum(model.apply(p, x, True) ** 2))(params)
    g_w = jax.grad(lambda p: jnp.sum(fwd_w(p, x) ** 2))(params)
    for (ka, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g_ref)[0],
            jax.tree_util.tree_flatten_with_path(g_w)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(ka)}")

    _, losses_w = run_steps(cfg_w, n_steps=3)
    _, losses_ref = run_steps(cfg_ref, n_steps=3)
    np.testing.assert_allclose(losses_w, losses_ref, rtol=2e-4)


@pytest.mark.parametrize("variant", ["moe", "dropout", "sp"])
def test_windowed_remat_v2_moe_and_dropout(devices8, variant):
    """--remat_window v2 (VERDICT r4 weak #3): the 10B family's measured
    winner must compose with the flagship's own flags. MoE is deterministic
    -> exact trajectory parity with the nn.scan path (incl. the aux loss
    riding the functional scan as ys). Dropout is keyed differently than
    flax's lifted split, so the assertable properties are nn.Dropout's
    contract: same (seed, step) -> identical trajectory, and the masks
    actually bite."""
    import numpy as np
    from tests.test_train_smoke import run_steps
    from vitax.config import Config

    kw = dict(image_size=32, patch_size=8, embed_dim=32, num_heads=4,
              num_blocks=4, num_classes=4, batch_size=16, dtype="float32",
              fsdp_size=-1, warmup_steps=0, grad_ckpt=True)
    if variant == "moe":
        kw.update(moe_experts=4, moe_top_k=2)
        _, losses_w = run_steps(Config(remat_window=2, **kw).validate(),
                                n_steps=3)
        _, losses_ref = run_steps(Config(**kw).validate(), n_steps=3)
        assert all(np.isfinite(losses_w))
        np.testing.assert_allclose(losses_w, losses_ref, rtol=2e-4)
        # and on the expert-sharded mesh: the windowed functional scan's
        # block.apply carries the same dispatch/token anchors, so ep
        # sharding must not change the trajectory either
        kw_ep = {**kw, "fsdp_size": 2, "dp_size": 2}
        _, losses_ep = run_steps(
            Config(remat_window=2, ep_size=2, **kw_ep).validate(), n_steps=3)
        np.testing.assert_allclose(losses_ep, losses_ref, rtol=2e-4)
    elif variant == "sp":
        # ring sequence parallelism: the windowed functional scan applies
        # the same shard_map'd attention impl the nn.scan path uses — the
        # sp trajectory must match the scan path's exactly
        kw_sp = {**kw, "fsdp_size": 2, "dp_size": 2, "sp_size": 2}
        _, losses_w = run_steps(Config(remat_window=2, **kw_sp).validate(),
                                n_steps=3)
        _, losses_ref = run_steps(Config(**kw_sp).validate(), n_steps=3)
        assert all(np.isfinite(losses_w))
        np.testing.assert_allclose(losses_w, losses_ref, rtol=2e-4)
    else:
        drop = dict(att_dropout=0.2, mlp_dropout=0.1, pos_dropout=0.1)
        cfg_w = Config(remat_window=2, **kw, **drop).validate()
        _, l1 = run_steps(cfg_w, n_steps=3)
        _, l2 = run_steps(cfg_w, n_steps=3)
        assert all(np.isfinite(l1))
        np.testing.assert_array_equal(l1, l2)  # deterministic given seed
        _, l0 = run_steps(Config(remat_window=2, **kw).validate(), n_steps=3)
        assert l1 != l0, "dropout had no effect under the windowed scan"
