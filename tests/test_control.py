"""Coordinated failure control plane tests (PR 10): control-word pack/agree
semantics, peer-liveness verdicts, elastic (topology-change) resume planning,
and the wiring around them (watchdog escalation requests, fault-plan process
gating, supervisor topology detection, metrics surfacing, VTX107).

Unit arms run tier-1 with injected collectives / fake KV clients / fake
children — no multi-process runtime. The true 2-process drills (agreed
escalation, peer death, N->M elastic resume) are `slow` subprocess tests on
the same harness as tests/test_multiprocess.py.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from vitax import faults
from vitax.supervise import Supervisor, checkpoint_topology
from vitax.telemetry.watchdog import EXIT_HANG, Watchdog
from vitax.train.control import (BIT_ESCALATE, BIT_FAULT, BIT_PEER_LOST,
                                 BIT_PREEMPT, ControlPlane, PeerLiveness,
                                 Signals, elastic_resume_plan, pack_word,
                                 unpack_word)

from tests.test_multiprocess import (_free_port, _tiny_train_argv,
                                     _two_proc_env)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_global_flags():
    """Neither a fault plan nor a delivered-SIGTERM flag may leak across
    tests (both registries are module-global)."""
    yield
    faults.uninstall()
    from vitax.train import preempt
    preempt.reset()


def _wait_until(cond, timeout_s=5.0, poll_s=0.01):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(poll_s)
    return cond()


# --- control word: pack / unpack / describe ---------------------------------

def test_pack_unpack_roundtrip_all_combinations():
    for word in range(16):
        sig = unpack_word(word)
        assert sig.word == word
        assert pack_word(sig.preempt, sig.escalate, sig.fault,
                         sig.peer_lost) == word
    assert pack_word(preempt=True) == BIT_PREEMPT == 1
    assert pack_word(escalate=True) == BIT_ESCALATE == 2
    assert pack_word(fault=True) == BIT_FAULT == 4
    assert pack_word(peer_lost=True) == BIT_PEER_LOST == 8


@pytest.mark.parametrize("bad", [16, 32, -1, 0x1F, 1 << 40])
def test_unpack_rejects_unknown_bits(bad):
    # garbage from a version-skewed peer must fail loudly, not mask to "none"
    with pytest.raises(ValueError):
        unpack_word(bad)


def test_signals_emergency_and_describe():
    assert not Signals().any
    assert Signals().describe() == "none"
    # preempt alone is the CLEAN drain (exit 0), never the emergency path
    assert Signals(preempt=True).any
    assert not Signals(preempt=True).emergency
    for kw in ({"escalate": True}, {"fault": True}, {"peer_lost": True}):
        assert Signals(**kw).emergency
    assert Signals(preempt=True, fault=True).describe() == "preempt+fault"
    assert unpack_word(pack_word(escalate=True, peer_lost=True)).describe() \
        == "escalate+peer_lost"


# --- ControlPlane: local word folding ---------------------------------------

class _FakeWatchdog:
    def __init__(self):
        self.escalated = False
        self.requests = []

    def escalation_requested(self):
        return self.escalated

    def request_escalation(self, reason=""):
        self.escalated = True
        self.requests.append(reason)


def test_local_word_folds_all_four_signals(monkeypatch):
    wd = _FakeWatchdog()
    plane = ControlPlane(process_index=0, process_count=1, watchdog=wd)
    assert plane.local_word() == 0
    from vitax.train import preempt
    monkeypatch.setattr(preempt, "requested", lambda: True)
    assert plane.local_word() == BIT_PREEMPT
    wd.escalated = True
    assert plane.local_word() == BIT_PREEMPT | BIT_ESCALATE
    plane.set_fault("test")
    plane._peer_lost.set()
    assert plane.local_word() == (BIT_PREEMPT | BIT_ESCALATE
                                  | BIT_FAULT | BIT_PEER_LOST)


def test_single_host_poll_is_every_step_and_collective_free():
    # process_count=1: the local word is read on EVERY call (PR 7 semantics
    # preserved exactly) and no collective ever runs
    def boom(word):
        raise AssertionError("single-host poll must not run a collective")

    plane = ControlPlane(sync_steps=10, process_index=0, process_count=1,
                         collective=boom)
    for step in range(7):  # all off the sync cadence
        assert plane.poll(step_in_epoch=step) == Signals()
    plane.set_fault("boom")
    assert plane.poll(step_in_epoch=3).fault  # off-cadence, still seen
    assert plane.poll(step_in_epoch=None).fault


# --- ControlPlane: multi-host cadence + OR-fold agreement --------------------

def test_multi_host_cadence_gates_the_collective():
    calls = []

    def fold(word):
        calls.append(word)
        return word

    plane = ControlPlane(sync_steps=5, process_index=0, process_count=2,
                         collective=fold)
    plane.set_fault("local")
    # steps 0..3 are off-cadence: no collective, and the verdict is withheld
    for step in range(4):
        assert plane.poll(step_in_epoch=step) == Signals()
    assert calls == []
    # step 4 -> (4+1) % 5 == 0: exactly one fold of the local word
    assert plane.poll(step_in_epoch=4).fault
    assert calls == [BIT_FAULT]
    # the epoch boundary always syncs, whatever the step cadence
    assert plane.poll(step_in_epoch=None).fault
    assert len(calls) == 2


def test_warmup_runs_one_fold_multi_host_and_none_single_host():
    # warmup pre-compiles the agreement collective OUTSIDE the watchdog's
    # hang-deadline window (the first fold carries XLA compile + transport
    # setup); it must fold a zero word and discard the result
    calls = []
    plane = ControlPlane(sync_steps=5, process_index=0, process_count=2,
                         collective=lambda w: calls.append(w) or w)
    plane.warmup()
    assert calls == [0]

    def boom(word):
        raise AssertionError("single-host warmup must not run a collective")

    solo = ControlPlane(process_index=0, process_count=1, collective=boom)
    solo.warmup()  # no-op


def test_agreement_is_a_bitwise_or_across_hosts():
    # this host has nothing raised; the peer contributes ESCALATE|PREEMPT.
    # A max() fold would keep only one host's word — OR keeps every bit.
    peer_word = BIT_PREEMPT | BIT_ESCALATE
    plane = ControlPlane(sync_steps=1, process_index=1, process_count=2,
                         collective=lambda w: w | peer_word)
    sig = plane.poll(step_in_epoch=0)
    assert sig.preempt and sig.escalate and sig.emergency
    assert sig.word == peer_word


def test_agreed_word_is_announced_once_with_payload():
    events = []
    plane = ControlPlane(sync_steps=1, process_index=0, process_count=2,
                         collective=lambda w: w,
                         on_event=events.append)
    plane.set_fault("drill")
    assert plane.poll(step_in_epoch=4, epoch=2).fault
    assert plane.poll(step_in_epoch=5, epoch=2).fault  # seen again, not re-announced
    agreed = [e for e in events if e["event"] == "agreed_escalation"]
    assert len(agreed) == 1
    assert agreed[0]["word"] == BIT_FAULT
    assert agreed[0]["signals"] == "fault"
    assert agreed[0]["epoch"] == 2 and agreed[0]["step_in_epoch"] == 5


def test_preempt_only_announces_the_clean_drain(monkeypatch):
    from vitax.train import preempt
    monkeypatch.setattr(preempt, "requested", lambda: True)
    events = []
    plane = ControlPlane(sync_steps=1, process_index=0, process_count=2,
                         collective=lambda w: w, on_event=events.append)
    sig = plane.poll(step_in_epoch=0)
    assert sig.preempt and not sig.emergency
    assert [e["event"] for e in events] == ["agreed_preempt"]


def test_barrier_timeout_fault_site_fires_inside_the_agreement():
    faults.install('{"site": "barrier_timeout", "action": "oserror", "at": 1}')
    plane = ControlPlane(sync_steps=1, process_index=0, process_count=2,
                         collective=lambda w: w)
    with pytest.raises(OSError):
        plane.poll(step_in_epoch=0)


# --- peer liveness -----------------------------------------------------------

class _FakeKV:
    """In-memory stand-in for the coordination-service KV client."""

    def __init__(self):
        self.store = {}
        self.lock = threading.Lock()

    def key_value_set(self, key, value, allow_overwrite=False):
        with self.lock:
            self.store[key] = value

    def blocking_key_value_get(self, key, timeout_in_ms):
        with self.lock:
            if key in self.store:
                return self.store[key]
        raise KeyError(key)


def test_liveness_declares_a_silent_peer_lost_once_with_cause():
    kv = _FakeKV()
    kv.key_value_set("vitax/fault/1", "hang_hard_exit")
    losses = []
    live = PeerLiveness(process_index=0, process_count=2, interval_s=0.05,
                        grace_s=0.25, client=kv,
                        on_loss=lambda *a: losses.append(a))
    live.start()
    try:
        # the peer beats for a while: no verdict
        for seq in range(3):
            kv.key_value_set("vitax/hb/1", str(seq))
            time.sleep(0.08)
        assert losses == []
        # ...then goes silent: lost after the grace window, exactly once
        assert _wait_until(lambda: losses, timeout_s=3.0)
        time.sleep(0.3)
        assert len(losses) == 1
        peer, silent_s, cause = losses[0]
        assert peer == 1 and silent_s >= 0.25
        assert cause == "hang_hard_exit"
        assert live.lost == {1}
        # our own beater side kept writing its key
        assert "vitax/hb/0" in kv.store
    finally:
        live.stop()


def test_liveness_flags_a_peer_that_never_wrote_at_all():
    # death during compile, before the first beat: the grace clock starts at
    # monitor start, so the verdict still arrives
    losses = []
    live = PeerLiveness(process_index=0, process_count=2, interval_s=0.05,
                        grace_s=0.2, client=_FakeKV(),
                        on_loss=lambda *a: losses.append(a))
    live.start()
    try:
        assert _wait_until(lambda: losses, timeout_s=3.0)
        assert losses[0][0] == 1 and losses[0][2] is None
    finally:
        live.stop()


def test_peer_loss_escalates_and_hard_exits_within_the_deadline():
    events, exits = [], []
    wd = _FakeWatchdog()
    plane = ControlPlane(sync_steps=1, process_index=0, process_count=2,
                         watchdog=wd, collective=lambda w: w,
                         on_event=events.append, hard_exit=exits.append)
    kv = _FakeKV()  # peer 1 never beats: lost after grace
    assert plane.start_liveness(interval_s=0.05, grace_s=0.2, client=kv)
    try:
        assert _wait_until(lambda: exits, timeout_s=5.0)
    finally:
        plane.stop()
    # the verdict raised the sticky bit, asked the watchdog to escalate,
    # emitted the event, and the independent timer exited EXIT_HANG
    assert plane.local_word() & BIT_PEER_LOST
    assert wd.requests and "peer 1 lost" in wd.requests[0]
    loss = [e for e in events if e["event"] == "peer_loss"]
    assert len(loss) == 1
    assert loss[0]["peer"] == 1 and loss[0]["exit_code"] == EXIT_HANG
    assert exits == [EXIT_HANG]


def test_peer_loss_suspected_classifies_collective_errors():
    # no liveness running: every error is a genuine bug (caller re-raises)
    assert ControlPlane(process_index=0, process_count=2,
                        collective=lambda w: w).peer_loss_suspected() is None
    # liveness running and the peer silent: the error is the death itself —
    # the classifier waits for the monitor's verdict and names the peer
    exits = []
    plane = ControlPlane(sync_steps=1, process_index=0, process_count=2,
                         collective=lambda w: w, hard_exit=exits.append)
    assert plane.start_liveness(interval_s=0.05, grace_s=0.2,
                                client=_FakeKV())
    try:
        assert plane.peer_loss_suspected() == 1
    finally:
        plane.stop()


def test_peer_loss_suspected_none_while_peers_keep_beating():
    kv = _FakeKV()
    plane = ControlPlane(sync_steps=1, process_index=0, process_count=2,
                         collective=lambda w: w)
    assert plane.start_liveness(interval_s=0.05, grace_s=10.0, client=kv)
    try:
        # a healthy peer beats throughout: the classifier must not blame it
        stop = threading.Event()

        def beat():
            seq = 0
            while not stop.is_set():
                seq += 1
                kv.key_value_set("vitax/hb/1", str(seq))
                time.sleep(0.02)

        t = threading.Thread(target=beat, daemon=True)
        t.start()
        try:
            assert plane.peer_loss_suspected(wait=False) is None
        finally:
            stop.set()
            t.join()
    finally:
        plane.stop()


def test_liveness_refused_without_peers_or_client():
    plane = ControlPlane(process_index=0, process_count=1)
    assert plane.start_liveness(0.1, 1.0, client=_FakeKV()) is False
    plane2 = ControlPlane(process_index=0, process_count=2)
    # no coordination service reachable in-process: off, loudly, not fatal
    assert plane2.start_liveness(0.1, 1.0) is False


# --- elastic resume planning -------------------------------------------------

def test_elastic_resume_plan_no_meta_is_epoch_boundary():
    plan = elastic_resume_plan(None, process_count=4)
    assert plan.resume_step == 0 and not plan.topology_changed
    assert not plan.epoch_rounded and plan.from_processes == 0


def test_elastic_resume_plan_same_topology_is_exact():
    meta = {"step_in_epoch": 7, "process_count": 2,
            "stream_cursor": {"shard": "s0", "record_offset": 3}}
    plan = elastic_resume_plan(meta, process_count=2)
    assert plan.resume_step == 7
    assert not plan.topology_changed and not plan.epoch_rounded


def test_elastic_resume_plan_topology_change_without_cursor_is_exact():
    # index-sampled loaders partition rank-interleaved: step-exact under N->M
    plan = elastic_resume_plan({"step_in_epoch": 7, "process_count": 2},
                               process_count=1)
    assert plan.topology_changed and not plan.epoch_rounded
    assert plan.resume_step == 7 and plan.from_processes == 2


def test_elastic_resume_plan_topology_change_with_cursor_rounds_down():
    # a stream cursor's shard assignment is disjoint per topology: N->M must
    # re-enter at the epoch boundary, loudly dropping the partial progress
    meta = {"step_in_epoch": 7, "process_count": 2,
            "stream_cursor": {"shard": "s0", "record_offset": 3}}
    plan = elastic_resume_plan(meta, process_count=1)
    assert plan.topology_changed and plan.epoch_rounded
    assert plan.resume_step == 0 and plan.skipped_steps == 7


def test_elastic_resume_plan_tolerates_pre_pr10_sidecars():
    # sidecars written before process_count existed: never "changed"
    plan = elastic_resume_plan({"step_in_epoch": 4}, process_count=8)
    assert plan.resume_step == 4 and not plan.topology_changed


def test_sidecar_records_topology_and_checkpoint_topology_reads_it(tmp_path):
    import numpy as np
    from vitax.checkpoint.orbax_io import load_resume_meta, save_state
    tree = {"w": np.arange(8, dtype=np.float32)}
    save_state(str(tmp_path), 1, tree, wait=True, step_in_epoch=3)
    meta = load_resume_meta(str(tmp_path), 1)
    assert meta["step_in_epoch"] == 3
    assert meta["process_count"] == 1  # single-process test runtime
    assert checkpoint_topology(str(tmp_path)) == 1
    # a boundary save has no sidecar: topology unknown, not "changed"
    save_state(str(tmp_path), 2, tree, wait=True)
    assert checkpoint_topology(str(tmp_path)) is None


# --- fault-plan process designation ------------------------------------------

def test_fault_spec_process_gates_by_process_index(monkeypatch):
    plan = ('{"site": "step", "action": "oserror", "at": 1, "times": 99, '
            '"process": 1}')
    monkeypatch.setenv("JAX_PROCESS_ID", "0")
    faults.install(plan)
    faults.fire("step", index=1)  # designated for process 1: silent here
    monkeypatch.setenv("JAX_PROCESS_ID", "1")
    with pytest.raises(OSError):
        faults.fire("step", index=2)


def test_fault_spec_process_validation_and_describe():
    spec = faults.FaultSpec(site="step", action="peer_loss", at=6, process=1)
    assert spec.describe() == "step:peer_loss@p1(at=6)"
    assert "peer_loss" in faults.ACTIONS
    assert "barrier_timeout" in faults.SITES
    with pytest.raises(ValueError):
        faults.FaultSpec(site="step", action="crash", process=-2)
    parsed = faults.parse_plan(
        '{"site": "barrier_timeout", "action": "hang", "process": 0}')
    assert parsed.specs[0].process == 0


# --- watchdog: external escalation + last-words hook -------------------------

def test_watchdog_request_escalation_arms_flag_and_deadline():
    escalations, exits = [], []
    wd = Watchdog(timeout_s=100.0, poll_s=0.02, action="checkpoint_exit",
                  hard_deadline_s=0.15, on_escalate=escalations.append,
                  hard_exit=exits.append).start()
    try:
        assert not wd.escalation_requested()
        wd.request_escalation("peer 1 lost (heartbeat silent 2.0s)")
        assert wd.escalation_requested()
        wd.request_escalation("again")  # idempotent: one escalation event
        assert len(escalations) == 1
        assert escalations[0]["reason"].startswith("peer 1 lost")
        assert escalations[0]["exit_code"] == EXIT_HANG
        # the loop never acknowledges: the hard deadline bounds the exit
        assert _wait_until(lambda: exits == [EXIT_HANG], timeout_s=3.0)
    finally:
        wd.stop()


def test_watchdog_hard_exit_speaks_last_words_first():
    order = []
    wd = Watchdog(timeout_s=0.05, poll_s=0.02, action="checkpoint_exit",
                  hard_deadline_s=0.1, rank=3,
                  on_hard_exit=lambda p: order.append(("words", p)),
                  hard_exit=lambda code: order.append(("exit", code))).start()
    try:
        assert _wait_until(lambda: ("exit", EXIT_HANG) in order, timeout_s=3.0)
    finally:
        wd.stop()
    words = [p for tag, p in order if tag == "words"]
    assert words and words[0]["exit_code"] == EXIT_HANG
    assert words[0]["rank"] == 3
    # the hook ran BEFORE the exit, so a real run's flushed telemetry event
    # and fault publication land even under os._exit
    assert order.index(("words", words[0])) < order.index(("exit", EXIT_HANG))


# --- supervisor: elastic (topology-change) restart detection -----------------

class _DoneChild:
    def __init__(self, rc=0):
        self.rc = rc

    def poll(self):
        return self.rc


def _control_events(metrics_dir):
    path = os.path.join(str(metrics_dir), "metrics.jsonl")
    if not os.path.exists(path):
        return []
    return [json.loads(ln) for ln in open(path, encoding="utf-8")
            if json.loads(ln).get("kind") == "control"]


def test_supervisor_announces_topology_change_before_launch(tmp_path):
    sup = Supervisor(["python", "train.py"], ckpt_dir=str(tmp_path),
                     metrics_dir=str(tmp_path),
                     spawn=lambda argv: _DoneChild(0),
                     progress_fn=lambda: (0, 0), sleep=lambda s: None,
                     expect_processes=1, topology_fn=lambda: 2)
    assert sup.run() == 0
    assert sup.topology_changes == 1
    events = _control_events(tmp_path)
    assert len(events) == 1
    assert events[0]["event"] == "topology_change"
    assert events[0]["from_processes"] == 2
    assert events[0]["to_processes"] == 1


def test_supervisor_topology_check_quiet_when_matching_or_off(tmp_path):
    for expect, recorded in ((1, 1), (1, None), (0, 7)):
        sup = Supervisor(["python", "t.py"], ckpt_dir=str(tmp_path),
                         metrics_dir=str(tmp_path / f"m{expect}_{recorded}"),
                         spawn=lambda argv: _DoneChild(0),
                         progress_fn=lambda: (0, 0), sleep=lambda s: None,
                         expect_processes=expect,
                         topology_fn=lambda r=recorded: r)
        assert sup.run() == 0
        assert sup.topology_changes == 0
        assert _control_events(tmp_path / f"m{expect}_{recorded}") == []


def test_supervisor_announces_each_distinct_mismatch_once(tmp_path):
    children = iter([_DoneChild(13), _DoneChild(0)])
    progresses = iter([(0, 0), (1, 0), (1, 0)])
    sup = Supervisor(["python", "t.py"], ckpt_dir=str(tmp_path),
                     metrics_dir=str(tmp_path),
                     spawn=lambda argv: next(children),
                     progress_fn=lambda: next(progresses),
                     sleep=lambda s: None,
                     expect_processes=1, topology_fn=lambda: 4)
    assert sup.run() == 0
    # two launches saw the same recorded topology: one announcement
    assert sup.restart_count == 1 and sup.topology_changes == 1
    assert len(_control_events(tmp_path)) == 1


# --- metrics_report: control-plane counters ----------------------------------

def test_metrics_report_folds_control_events(tmp_path):
    path = tmp_path / "metrics.jsonl"
    records = [
        {"schema": 1, "step": 1, "loss": 2.0, "sec_per_iter": 0.1},
        {"schema": 1, "kind": "control", "event": "agreed_preempt", "word": 1},
        {"schema": 1, "kind": "control", "event": "agreed_escalation",
         "word": 2},
        {"schema": 1, "kind": "control", "event": "peer_loss", "peer": 1},
        {"schema": 1, "kind": "control", "event": "topology_change",
         "from_processes": 2, "to_processes": 1},
        {"schema": 1, "kind": "control", "event": "elastic_resume",
         "from_processes": 2, "to_processes": 1, "resume_step": 12},
        {"schema": 1, "kind": "hang_hard_exit", "exit_code": 42},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in records))
    r = subprocess.run(
        [sys.executable, os.path.join("tools", "metrics_report.py"),
         str(path), "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    summary = json.loads(r.stdout)
    assert summary["control_events"] == {
        "agreed_preemptions": 1, "agreed_escalations": 1,
        "peer_loss_detections": 1, "topology_changes": 1,
        "elastic_resumes": 1, "peer_restore_failures": 0}
    assert summary["hang_hard_exits"] == 1

    human = subprocess.run(
        [sys.executable, os.path.join("tools", "metrics_report.py"),
         str(path)],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert human.returncode == 0
    assert ("control plane: 1 agreed preemption(s), 1 agreed escalation(s), "
            "1 peer loss(es), 1 topology change(s), "
            "1 elastic resume(s)") in human.stdout
    assert "watchdog hard-deadline exits: 1" in human.stdout


# --- VTX107: raw failure-signal polls are fenced to the control plane --------

def test_ast_lint_vtx107_flags_raw_signal_polls():
    from vitax.analysis import ast_lint

    def _codes(findings):
        return [f.code for f in findings]

    src = ("from vitax.train import preempt\n"
           "def loop(wd):\n"
           "    if preempt.requested():\n"
           "        return 1\n"
           "    if wd.escalation_requested():\n"
           "        return 2\n")
    assert _codes(ast_lint.lint_source(src, "vitax/train/foo.py")) == \
        ["VTX107", "VTX107"]

    suppressed = (
        "from vitax.train import preempt\n"
        "def loop(wd):\n"
        "    if preempt.requested():  # vtx: ignore[VTX107] sanctioned\n"
        "        return 1\n")
    assert ast_lint.lint_source(suppressed, "vitax/train/foo.py") == []

    # a bare name (not an attribute access) is not the fenced pattern
    plain = ("def loop(escalation_requested):\n"
             "    return escalation_requested()\n")
    assert ast_lint.lint_source(plain, "vitax/train/foo.py") == []


def test_control_module_itself_passes_the_ast_lint():
    # the two sanctioned raw polls in ControlPlane.local_word carry reasons
    from vitax.analysis import ast_lint
    path = os.path.join(REPO, "vitax", "train", "control.py")
    with open(path, encoding="utf-8") as f:
        findings = ast_lint.lint_source(f.read(), "vitax/train/control.py")
    assert findings == []


# --- step-program identity: the control plane is host-side only --------------

def test_control_knobs_trace_identical_step_program(devices8):
    """--control_sync_steps / --peer_heartbeat_s are host-side machinery:
    the lowered train-step program must be bit-identical with them at any
    setting (same acceptance pin faults and telemetry carry)."""
    import jax
    from tests.test_checkpoint import tiny_cfg
    from tests.test_train_smoke import build_train_objects, random_batch

    def lowered(cfg):
        mesh, state, step_fn, _ = build_train_objects(cfg)
        batch = random_batch(cfg, mesh)
        return step_fn.lower(state, batch, jax.random.key(0)).as_text()

    off = lowered(tiny_cfg())
    on = lowered(tiny_cfg(control_sync_steps=3, peer_heartbeat_s=0.5,
                          peer_grace_s=2.0))
    assert off == on


# --- slow 2-process drills ---------------------------------------------------

def _spawn_two(argv, port, tmp_path, extra_env=None):
    """Start the same argv as 2 coordinated processes with per-rank log
    files; returns (procs, logs). Caller owns waiting + cleanup."""
    logs = [tmp_path / f"rank{i}.log" for i in range(2)]
    procs = []
    for pid in range(2):
        env = _two_proc_env(port, pid)
        env.update(extra_env or {})
        with open(logs[pid], "w") as log_f:
            procs.append(subprocess.Popen(
                argv, cwd=REPO, env=env, stdout=log_f,
                stderr=subprocess.STDOUT, text=True))
    return procs, logs


def _kill_all(procs):
    for p in procs:
        if p.poll() is None:
            p.kill()
            p.wait()


@pytest.mark.slow
def test_two_process_agreed_escalation_exits_42_at_the_same_step(tmp_path):
    """A hang on ONE host must take down BOTH hosts through the agreed
    emergency path: host 0's watchdog escalates locally, the next control
    sync folds ESCALATE into the agreed word, and both processes commit the
    SAME mid-epoch checkpoint and exit EXIT_HANG (42) — the supervisor then
    sees one uniform verdict instead of one wedged and one dead host."""
    port = _free_port()
    # timing: per-step jitter on 2-proc CPU/Gloo can top 1s, so the timeout
    # must clear it (3s) and the injected hang must clear the timeout (7s)
    # while the agreement lands inside the hard deadline (escalation ~+3s,
    # deadline 2x3s later at ~+9s, wake at +7s -> ~2s of margin)
    plan = ('[{"site": "step", "action": "hang", "at": 8, "seconds": 7.0, '
            '"process": 0}]')
    argv = _tiny_train_argv(2000, tmp_path / "ckpt") + [
        "--fault_plan", plan, "--hang_timeout_s", "3.0",
        "--hang_action", "checkpoint_exit", "--control_sync_steps", "2"]
    procs, logs = _spawn_two(argv, port, tmp_path)
    try:
        for p in procs:
            p.wait(timeout=540)
    finally:
        _kill_all(procs)

    out0, out1 = (lg.read_text() for lg in logs)
    assert procs[0].returncode == EXIT_HANG == 42, out0[-3000:]
    assert procs[1].returncode == EXIT_HANG == 42, out1[-3000:]
    # rank 0 (the hung host) announces the agreed escalation verdict
    assert "agreed signals: escalate" in out0, out0[-3000:]
    assert "saving emergency checkpoint" in out0, out0[-3000:]
    # the jointly committed checkpoint carries ONE agreed step + topology
    from vitax.checkpoint.orbax_io import latest_epoch, load_resume_meta
    assert latest_epoch(str(tmp_path / "ckpt")) == 1
    meta = load_resume_meta(str(tmp_path / "ckpt"), 1)
    assert meta is not None and meta["step_in_epoch"] >= 8
    assert meta["process_count"] == 2


@pytest.mark.slow
def test_two_process_peer_death_bounded_survivor_exit(tmp_path):
    """SIGKILL one host mid-run (fault action `peer_loss` on process 1): the
    survivor must NOT block forever in the agreement collective — the
    peer-liveness monitor declares the peer lost after the grace window and
    the survivor exits EXIT_HANG within the liveness deadline, well before
    the coordination service's own (much longer) failure detection."""
    port = _free_port()
    plan = '[{"site": "step", "action": "peer_loss", "at": 6, "process": 1}]'
    argv = _tiny_train_argv(2000, tmp_path / "ckpt") + [
        "--fault_plan", plan, "--peer_heartbeat_s", "0.5",
        "--peer_grace_s", "5.0"]
    procs, logs = _spawn_two(argv, port, tmp_path)
    try:
        # rank 1 kills itself abruptly: SIGKILL, no drains
        procs[1].wait(timeout=540)
        assert procs[1].returncode == -signal.SIGKILL, \
            logs[1].read_text()[-3000:]
        # the survivor's exit is BOUNDED: grace (5s) + deadline timer (5s)
        # + slack, nowhere near a wedged-collective forever
        procs[0].wait(timeout=120)
    finally:
        _kill_all(procs)

    out0 = logs[0].read_text()
    assert procs[0].returncode == EXIT_HANG == 42, out0[-3000:]
    assert "peer 1 lost" in out0, out0[-3000:]


@pytest.mark.slow
def test_elastic_two_to_one_supervised_resume(tmp_path):
    """The N->M drill: a 2-process run is preempted mid-epoch (committed
    sidecar records process_count=2), then a 1-process run under
    tools/supervise.py resumes the SAME checkpoint — the supervisor announces
    the topology change, the loop's elastic plan keeps the step-granular
    resume exact (rank-interleaved sampling, no stream cursor), and training
    completes without cursor or shape errors."""
    port = _free_port()
    ckpt = tmp_path / "ckpt"
    plan = '[{"site": "step", "action": "sigterm", "at": 12, "process": 0}]'
    argv = _tiny_train_argv(2000, ckpt) + ["--fault_plan", plan]
    procs, logs = _spawn_two(argv, port, tmp_path)
    try:
        for p in procs:
            p.wait(timeout=540)
    finally:
        _kill_all(procs)
    out0 = logs[0].read_text()
    assert procs[0].returncode == 0, out0[-3000:]
    assert procs[1].returncode == 0, logs[1].read_text()[-3000:]
    assert "SIGTERM received: saving preemption checkpoint" in out0
    from vitax.checkpoint.orbax_io import load_resume_meta
    meta = load_resume_meta(str(ckpt), 1)
    assert meta is not None and meta["process_count"] == 2
    resume_step = meta["step_in_epoch"]
    assert resume_step >= 12

    # resume on ONE process (8 local devices), supervised, a few more steps
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    env.pop("JAX_NUM_PROCESSES", None)
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env.pop("JAX_PROCESS_ID", None)
    metrics_dir = tmp_path / "metrics"
    r = subprocess.run(
        [sys.executable, os.path.join("tools", "supervise.py"),
         "--expect_processes", "1", "--",
         *_tiny_train_argv(2000, ckpt), "--max_steps", "3",
         "--metrics_dir", str(metrics_dir)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, (r.stdout[-3000:], r.stderr[-3000:])
    # the supervisor said what was about to happen...
    assert "TOPOLOGY CHANGE" in r.stderr, r.stderr[-3000:]
    # ...and the loop's elastic plan kept the resume step-exact
    assert ("elastic resume: checkpoint epoch 1 was written by 2 "
            "process(es), this run has 1") in r.stdout, r.stdout[-3000:]
    assert (f"re-entering epoch 1 at step {resume_step + 1}"
            in r.stdout), r.stdout[-3000:]
    assert "training completed" in r.stdout
    # the control event landed in the metrics stream for the report to count
    mr = subprocess.run(
        [sys.executable, os.path.join("tools", "metrics_report.py"),
         str(metrics_dir / "metrics.jsonl"), "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    summary = json.loads(mr.stdout)
    # one observation (supervisor) + one action (the loop's elastic plan)
    assert summary["control_events"]["topology_changes"] == 1
    assert summary["control_events"]["elastic_resumes"] == 1
