"""Comm-precision policy tests (--param_gather_dtype / --grad_reduce_dtype).

Three layers of guarantee:

1. Numerics: the bf16 gather policy is equivalent to the f32 policy — the
   shard-side cast commutes with the gather, so losses are bitwise-identical
   over 3 steps on every sharding arm (ZeRO-3, ZeRO-2, DP, grad-accum K=2).
   Params match bitwise on the accum arm; on the K=1 arms they agree to
   float32 ulps (raw grads ARE bitwise-identical between the two programs —
   verified separately — but XLA fuses the identical-valued grads into the
   clip+adamw update with different convert placements, which reassociates a
   couple of update-math ops; losses stay bitwise through 3 steps).

2. Grad-reduce dtype: float32 (default) reproduces the f32-policy arm's
   losses exactly; bfloat16 reduces on bf16 bits and only agrees to ~1e-2.

3. HLO: via tools/comm_audit.py on the post-SPMD-partitioning module (the
   backend-independent ground truth — XLA:CPU's float normalization rewrites
   bf16 collectives to f32+converts in the FINAL executable, so the final HLO
   can never show a bf16 collective on CPU). Asserts the policy leaves no f32
   all-gather of block-param-sized operands and halves total gather bytes
   (>= 1.9x) at the ZeRO-2 step — the step whose f32 arm actually moves f32.
   (Under ZeRO-3, GSPMD already sinks the compute-dtype convert below the
   per-use gathers, so both policies emit bf16 per-block gathers there; the
   audit asserts that invariant too.)
"""

import os
import sys

import numpy as np
import pytest

import jax

from test_train_smoke import run_steps, tiny_cfg
from vitax.config import Config

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

ARMS = {
    "zero3": {},
    "zero2": {"reshard_after_forward": False},
    "dp": {"run_without_fsdp": True},
    "accum2": {"grad_accum_steps": 2},
}

_runs = {}


def _run(arm, **overrides):
    """3 training steps at dtype=bfloat16; cached so the bf16/f32 arms are
    trained once each across the parametrized tests below."""
    key = (arm, tuple(sorted(overrides.items())))
    if key not in _runs:
        cfg = tiny_cfg(dtype="bfloat16", **ARMS[arm], **overrides)
        state, losses = run_steps(cfg, n_steps=3)
        _runs[key] = (jax.device_get(state.params), losses)
    return _runs[key]


@pytest.mark.parametrize("arm", list(ARMS))
def test_bf16_gather_policy_bitwise_equivalent(devices8, arm):
    params_a, losses_a = _run(arm, param_gather_dtype="bfloat16")
    params_b, losses_b = _run(arm, param_gather_dtype="float32")
    assert losses_a == losses_b, (
        f"{arm}: losses diverged under the bf16 gather policy: "
        f"{losses_a} vs {losses_b}")
    leaves_a = jax.tree_util.tree_leaves_with_path(params_a)
    leaves_b = jax.tree.leaves(params_b)
    for (path, la), lb in zip(leaves_a, leaves_b):
        name = jax.tree_util.keystr(path)
        xa, xb = np.asarray(la), np.asarray(lb)
        if arm == "accum2":
            # the accum scan compiles update math identically in both arms
            assert np.array_equal(xa, xb), f"{arm} {name} not bitwise"
        else:
            # see module docstring: grads are bitwise, a couple of f32 ulps
            # creep in from XLA fusing the update math differently
            np.testing.assert_allclose(xa, xb, rtol=0, atol=1e-7,
                                       err_msg=f"{arm} {name}")


def test_grad_reduce_f32_default_matches_f32_policy_exactly(devices8):
    """--grad_reduce_dtype float32 (the default): bf16-policy losses equal the
    f32-policy losses bitwise — the policy changes gather traffic only."""
    _, losses_bf16 = _run("zero3", param_gather_dtype="bfloat16",
                          grad_reduce_dtype="float32")
    _, losses_f32 = _run("zero3", param_gather_dtype="float32")
    assert losses_bf16 == losses_f32


def test_grad_reduce_bf16_agrees_loosely(devices8):
    """--grad_reduce_dtype bfloat16 pins the grad reduction to bf16 bits:
    the trajectory must stay within ~1e-2 of the f32-policy arm. (On this
    tiny CPU topology GSPMD already resolves the wgrad partial sums in the
    bf16 cotangent dtype under BOTH settings — the audit shows bf16 wgrad
    all-reduces in every arm — so the trajectories may even coincide; the
    flag is the explicit contract that the reduction may round to bf16,
    not a guarantee that it otherwise wouldn't.)"""
    _, losses_bf16 = _run("zero3", param_gather_dtype="bfloat16",
                          grad_reduce_dtype="bfloat16")
    _, losses_f32 = _run("zero3", param_gather_dtype="float32")
    np.testing.assert_allclose(losses_bf16, losses_f32, rtol=0, atol=1e-2)


def _audit(**kw):
    import comm_audit
    cfg = tiny_cfg(dtype="bfloat16", **kw)
    return comm_audit.audit_config(cfg)


def test_audit_zero3_all_param_gathers_bf16(devices8):
    """Acceptance: on the compiled ZeRO-3 step every fsdp block-param
    all-gather moves bf16 — no f32 gather of a block-param-sized operand
    survives the bf16 policy."""
    rep = _audit(param_gather_dtype="bfloat16")
    assert not rep["f32_block_param_gathers"], rep["f32_block_param_gathers"]
    bf16 = [r for r in rep["collectives"]
            if r["op"] == "all-gather" and r["dtype"] == "bf16"]
    assert bf16, "expected bf16 per-block all-gathers under ZeRO-3"


def test_audit_zero2_gather_bytes_halve(devices8):
    """Acceptance: >= 1.9x reduction in audited all-gather bytes vs the f32
    policy, measured at the ZeRO-2 step-top gather of the whole param tree
    (the collective whose dtype the policy structurally changes; ZeRO-3
    per-use gathers are bf16 under BOTH policies via GSPMD convert-sinking)."""
    import comm_audit
    rep_bf16 = _audit(param_gather_dtype="bfloat16",
                      reshard_after_forward=False)
    rep_f32 = _audit(param_gather_dtype="float32",
                     reshard_after_forward=False)
    bytes_bf16 = rep_bf16["all_gather_bytes"]
    bytes_f32 = rep_f32["all_gather_bytes"]
    assert bytes_bf16 and bytes_f32
    ratio = bytes_f32 / bytes_bf16
    assert ratio >= 1.9, (
        f"gather bytes {bytes_f32} -> {bytes_bf16}, only {ratio:.2f}x")
    # the f32 arm's step-top gather really is f32 (the thing being halved)
    assert comm_audit.gather_bytes(rep_f32["collectives"], dtype="f32",
                                   min_numel=tiny_cfg().embed_dim ** 2) > 0


def test_validate_rejects_bad_policies():
    with pytest.raises(AssertionError):
        tiny_cfg(dtype="float32", param_gather_dtype="bfloat16")
    with pytest.raises(AssertionError):
        # bf16 reduce needs the bf16 gather policy active
        tiny_cfg(dtype="float32", grad_reduce_dtype="bfloat16")
    with pytest.raises(AssertionError):
        tiny_cfg(dtype="bfloat16", param_gather_dtype="float32",
                 grad_reduce_dtype="bfloat16")


def test_resolved_gather_dtype_follows_dtype():
    assert tiny_cfg().resolved_param_gather_dtype == "float32"
    assert not tiny_cfg().comm_cast_active
    bf = tiny_cfg(dtype="bfloat16")
    assert bf.resolved_param_gather_dtype == "bfloat16"
    assert bf.comm_cast_active
    pinned = tiny_cfg(dtype="bfloat16", param_gather_dtype="float32")
    assert not pinned.comm_cast_active
    assert isinstance(pinned, Config)
