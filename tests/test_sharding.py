"""Sharding-layer tests on the 8-virtual-device CPU mesh — the multi-device
test capability the reference lacks (SURVEY.md section 4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from vitax.config import Config
from vitax.models import build_model
from vitax.parallel.mesh import build_mesh, resolve_mesh_shape
from vitax.parallel.sharding import (
    gather_over_fsdp,
    init_sharded_params,
    param_specs,
    shardings_of,
    state_specs_like,
)


def tiny_cfg(**kw):
    base = dict(image_size=32, patch_size=8, embed_dim=64, num_heads=2, num_blocks=2,
                num_classes=10, batch_size=16, dtype="float32")
    base.update(kw)
    return Config(**base).validate()


class TestMeshResolution:
    def test_default_fsdp_all_devices(self):
        assert resolve_mesh_shape(tiny_cfg(), 8) == (1, 8, 1, 1, 1, 1)

    def test_run_without_fsdp_is_pure_dp(self):
        assert resolve_mesh_shape(tiny_cfg(run_without_fsdp=True), 8) == (8, 1, 1, 1, 1, 1)

    def test_mixed_axes(self):
        assert resolve_mesh_shape(tiny_cfg(tp_size=2, fsdp_size=-1), 8) == (1, 4, 2, 1, 1, 1)
        assert resolve_mesh_shape(tiny_cfg(dp_size=2, fsdp_size=2, tp_size=2), 8) == (2, 2, 2, 1, 1, 1)

    def test_pp_mesh_resolution(self):
        # default: remaining devices go to fsdp (ZeRO-3 inside the pipeline)
        assert resolve_mesh_shape(tiny_cfg(pp_size=2), 8) == (1, 4, 1, 1, 2, 1)
        # pure dp x pp: explicit fsdp=1 defaults the remainder onto dp
        assert resolve_mesh_shape(tiny_cfg(pp_size=2, fsdp_size=1), 8) == (4, 1, 1, 1, 2, 1)
        # explicit three-way dp x fsdp x pp
        assert resolve_mesh_shape(tiny_cfg(pp_size=2, fsdp_size=2, dp_size=2), 8) == (2, 2, 1, 1, 2, 1)

    def test_bad_shapes_raise(self):
        with pytest.raises(ValueError):
            resolve_mesh_shape(tiny_cfg(fsdp_size=3), 8)
        with pytest.raises(ValueError):
            resolve_mesh_shape(tiny_cfg(dp_size=-1, fsdp_size=-1), 8)
        with pytest.raises(ValueError):
            resolve_mesh_shape(tiny_cfg(run_without_fsdp=True, fsdp_size=4), 8)
        # pp composes with tp/sp since round 4 (vitax/parallel/pipeline.py)
        shape = resolve_mesh_shape(tiny_cfg(pp_size=2, tp_size=2), 8)
        assert shape[2] == 2 and shape[4] == 2 and int(np.prod(shape)) == 8


class TestParamSpecs:
    def _abstract(self, cfg):
        model = build_model(cfg)
        x = jnp.zeros((2, cfg.image_size, cfg.image_size, 3))
        return jax.eval_shape(lambda r: model.init(r, x, True), jax.random.key(0))

    def test_fsdp_shards_every_large_param(self, devices8):
        cfg = tiny_cfg()
        mesh = build_mesh(cfg)
        specs = param_specs(self._abstract(cfg), cfg, mesh)
        flat = jax.tree_util.tree_flatten_with_path(specs)[0]
        for path, spec in flat:
            names = [str(getattr(p, "key", p)) for p in path]
            if "head" in names and "bias" in names:
                assert spec == P(None,)  # 10 not divisible by 8 -> replicated
            else:
                assert "fsdp" in [a for a in spec if a], f"{names} unsharded: {spec}"

    def test_scanned_layer_dim_never_sharded(self, devices8):
        cfg = tiny_cfg()
        mesh = build_mesh(cfg)
        specs = param_specs(self._abstract(cfg), cfg, mesh)
        flat = jax.tree_util.tree_flatten_with_path(specs)[0]
        for path, spec in flat:
            names = [str(getattr(p, "key", p)) for p in path]
            if "blocks" in names:
                assert spec[0] is None, f"layer dim of {names} sharded: {spec}"

    def test_dp_mode_replicates_params(self, devices8):
        cfg = tiny_cfg(run_without_fsdp=True)
        mesh = build_mesh(cfg)
        specs = param_specs(self._abstract(cfg), cfg, mesh)
        for spec in jax.tree.leaves(specs):
            pass  # leaves of a spec tree are the specs themselves below
        flat = jax.tree_util.tree_flatten_with_path(specs)[0]
        for _, spec in flat:
            assert all(a is None for a in spec), f"param sharded in DP mode: {spec}"

    def test_tp_megatron_layout(self, devices8):
        cfg = tiny_cfg(tp_size=2, fsdp_size=4)
        mesh = build_mesh(cfg)
        specs = param_specs(self._abstract(cfg), cfg, mesh)
        p = specs["params"]["blocks"]
        # column-parallel: qkv/fc1 shard output dim on tp
        assert p["attn"]["qkv"]["kernel"][-1] == "tp"
        assert p["mlp"]["fc1"]["kernel"][-1] == "tp"
        # row-parallel: proj/fc2 shard input dim on tp
        assert p["attn"]["proj"]["kernel"][-2] == "tp"
        assert p["mlp"]["fc2"]["kernel"][-2] == "tp"

    def test_gather_over_fsdp_strips_only_fsdp(self):
        specs = {"a": P(None, "fsdp"), "b": P("tp", "fsdp"), "c": P()}
        out = gather_over_fsdp(specs)
        assert out["a"] == P(None, None)
        assert out["b"] == P("tp", None)
        assert out["c"] == P()


class TestShardedInit:
    def test_init_lands_sharded(self, devices8):
        cfg = tiny_cfg()
        mesh = build_mesh(cfg)
        model = build_model(cfg)
        x = jnp.zeros((2, 32, 32, 3))
        params, specs = init_sharded_params(
            lambda r: model.init(r, x, True), jax.random.key(0), cfg, mesh)
        qkv = params["params"]["blocks"]["attn"]["qkv"]["kernel"]
        assert qkv.sharding.spec == specs["params"]["blocks"]["attn"]["qkv"]["kernel"]
        # each device holds 1/8 of the elements
        assert qkv.addressable_shards[0].data.size == qkv.size // 8

    def test_shard_on_cpu_init_matches_jit_init(self, devices8):
        """Host-side init + per-shard device_put must produce identical values
        to direct sharded init (same rng stream)."""
        cfg_a = tiny_cfg()
        cfg_b = tiny_cfg(shard_on_cpu=True)
        mesh = build_mesh(cfg_a)
        model = build_model(cfg_a)
        x = jnp.zeros((2, 32, 32, 3))
        init = lambda r: model.init(r, x, True)
        pa, _ = init_sharded_params(init, jax.random.key(0), cfg_a, mesh)
        pb, _ = init_sharded_params(init, jax.random.key(0), cfg_b, mesh)
        for a, b in zip(jax.tree.leaves(pa), jax.tree.leaves(pb)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
            assert a.sharding.spec == b.sharding.spec

    def test_state_specs_like_maps_moments(self, devices8):
        import optax
        cfg = tiny_cfg()
        mesh = build_mesh(cfg)
        model = build_model(cfg)
        x = jnp.zeros((2, 32, 32, 3))
        abstract_p = jax.eval_shape(lambda r: model.init(r, x, True), jax.random.key(0))
        pspecs = param_specs(abstract_p, cfg, mesh)
        tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(lambda s: 1e-3))
        abstract_o = jax.eval_shape(tx.init, abstract_p)
        ospecs = state_specs_like(abstract_o, pspecs)
        flat_o = jax.tree_util.tree_flatten_with_path(ospecs)[0]
        checked = 0
        for path, spec in flat_o:
            names = [str(getattr(p, "key", getattr(p, "name", getattr(p, "idx", p)))) for p in path]
            if "mu" in names or "nu" in names:
                if "qkv" in names and "kernel" in names:
                    assert spec == pspecs["params"]["blocks"]["attn"]["qkv"]["kernel"]
                    checked += 1
            elif spec != P():
                raise AssertionError(f"non-moment leaf {names} got {spec}")
        assert checked == 2  # mu and nu

    def test_state_specs_exact_path_beats_name_collision(self):
        """Two branches ending in the same leaf names (dense/kernel) with
        DIFFERENT specs: each moment must inherit its own branch's spec.
        (Suffix matching — the round-1 implementation — would give both the
        first branch's spec; VERDICT round-1 weak item 4.)"""
        pspecs = {"params": {
            "enc": {"dense": {"kernel": P("fsdp", None)}},
            "dec": {"dense": {"kernel": P(None, "fsdp")}},
        }}
        leaf = lambda shape: jax.ShapeDtypeStruct(shape, jnp.float32)
        abstract = {"mu": {"params": {
            "enc": {"dense": {"kernel": leaf((8, 4))}},
            "dec": {"dense": {"kernel": leaf((4, 8))}},
        }}}
        specs = state_specs_like(abstract, pspecs)
        assert specs["mu"]["params"]["enc"]["dense"]["kernel"] == P("fsdp", None)
        assert specs["mu"]["params"]["dec"]["dense"]["kernel"] == P(None, "fsdp")

    def test_state_specs_unknown_param_subpath_raises(self):
        pspecs = {"params": {"w": P("fsdp")}}
        abstract = {"mu": {"params": {"w_new": jax.ShapeDtypeStruct((8,), jnp.float32)}}}
        with pytest.raises(ValueError, match="no parameter at subpath"):
            state_specs_like(abstract, pspecs)

    def test_state_specs_rank_mismatch_raises(self):
        """A param-path leaf whose rank differs from the param (e.g. a factored
        second moment) must fail loudly, not silently replicate."""
        pspecs = {"params": {"w": P("fsdp", None)}}
        abstract = {"nu": {"params": {"w": jax.ShapeDtypeStruct((8,), jnp.float32)}}}
        with pytest.raises(ValueError, match="rank"):
            state_specs_like(abstract, pspecs)
