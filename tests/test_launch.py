"""Launcher tests (xla_dist parity surface, reference README.md:94-119):
remote command construction and the dry-run path — no gcloud needed."""

import shlex

from vitax.launch import RESTART_CMD, build_remote_command, main


def test_build_remote_command_quotes_and_env():
    remote = build_remote_command(
        ["python3", "run_vit_training.py", "--data_dir", "/data/image net"],
        env=["PYTHONUNBUFFERED=1", "XLA_FLAGS=--flag_a --flag_b"],
        workdir="~/vitax")
    assert remote.startswith("cd ~/vitax && ")
    assert "export PYTHONUNBUFFERED=1;" in remote
    assert "export 'XLA_FLAGS=--flag_a --flag_b';" in remote  # space -> quoted
    assert "'/data/image net'" in remote  # spaces survive the remote shell


def test_workdir_tilde_expansion_preserved():
    assert build_remote_command(["true"], [], "~").startswith("cd ~ && ")
    assert build_remote_command(["true"], [], "~/a b").startswith("cd ~/'a b' && ")
    assert build_remote_command(["true"], [], "/opt/x").startswith("cd /opt/x && ")


def test_restart_pattern_does_not_match_itself():
    # the bracketed-first-char idiom: the pkill regex must not match the
    # shell command carrying it, or the launcher kills its own SSH round
    import re
    pattern = "[r]un_vit_training.py"
    assert pattern in RESTART_CMD
    assert re.search(pattern, RESTART_CMD) is None


def test_restart_on_failure_relaunches_with_kill_round(monkeypatch, capsys):
    """A nonzero worker exit must trigger a kill-stale round + relaunch, up to
    --max_restarts times (xla_dist restart-on-failure parity); the retry must
    succeed without exhausting the budget."""
    import vitax.launch as launch

    launches = []
    restarts = []
    monkeypatch.setattr(launch, "_run_launch",
                        lambda gcloud, logfile: 1 if not launches.append(gcloud)
                        and len(launches) == 1 else 0)
    monkeypatch.setattr(launch.subprocess, "call",
                        lambda argv: restarts.append(argv) or 0)
    rc = main(["--tpu", "my-pod", "--max_restarts", "3",
               "--", "python3", "run_vit_training.py"])
    assert rc == 0
    assert len(launches) == 2          # failed once, relaunched once
    assert len(restarts) == 1          # kill-stale round before the relaunch
    assert RESTART_CMD in " ".join(restarts[0])
    out = capsys.readouterr().out
    assert "worker exited with rc=1" in out


def test_restart_budget_exhausted_returns_failure(monkeypatch, capsys):
    import vitax.launch as launch

    calls = []
    monkeypatch.setattr(launch, "_run_launch",
                        lambda gcloud, logfile: calls.append(1) or 7)
    monkeypatch.setattr(launch.subprocess, "call", lambda argv: 0)
    rc = main(["--tpu", "my-pod", "--max_restarts", "2", "--", "python3", "x.py"])
    assert rc == 7
    assert len(calls) == 3             # initial + 2 restarts
    assert "giving up" in capsys.readouterr().out


def test_max_restarts_zero_disables_retry(monkeypatch):
    import vitax.launch as launch

    calls = []
    monkeypatch.setattr(launch, "_run_launch",
                        lambda gcloud, logfile: calls.append(1) or 3)
    monkeypatch.setattr(launch.subprocess, "call", lambda argv: 0)
    rc = main(["--tpu", "my-pod", "--max_restarts", "0", "--", "python3", "x.py"])
    assert rc == 3 and len(calls) == 1


def test_dry_run_prints_gcloud_command(capsys):
    rc = main(["--tpu", "my-pod", "--zone", "us-central2-b", "--restart",
               "--env", "PYTHONUNBUFFERED=1", "--dry_run",
               "--", "python3", "run_vit_training.py", "--fake_data"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "launching:" in out
    launch_line = [l for l in out.splitlines() if l.startswith("launching:")][0]
    argv = shlex.split(launch_line[len("launching:"):])
    assert argv[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "ssh", "my-pod"]
    assert "--worker=all" in argv
    assert "--zone=us-central2-b" in argv
    command = [a for a in argv if a.startswith("--command=")][0]
    assert "run_vit_training.py" in command and "--fake_data" in command
