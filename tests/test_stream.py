"""Streaming data plane (vitax/data/stream/): container round-trip, per-host
disjointness, epoch-seeded shuffle determinism, mid-epoch cursor resume
(loader-level exact-record-set and full kill-and-resume through train()),
native-vs-PIL decode parity for the serve path, the stream_read fault drill,
and the ImageFolder-equivalence guard (streaming and directory-scan pipelines
deliver identical sample sets per epoch).
"""

import hashlib
import json
import os
import sys

import numpy as np
import pytest
from PIL import Image

import jax

from vitax import faults
from vitax.config import Config
from vitax.data.loader import LoaderWorkerError
from vitax.data.stream.format import (MAGIC, ShardFormatError, ShardReader,
                                      ShardWriter, load_split_meta)
from vitax.data.stream.sampler import StreamSampler, assign_shards

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TRAIN_N = 32          # per split sizes are divisible by the global batch so
VAL_N = 16            # every record is consumed each epoch (drop_last == nothing)
BATCH = 8
SEED = 3


def _make_imagefolder(root, n_per_class, classes=("cat", "dog"), seed=0,
                      size=40):
    """Tiny ImageFolder tree of unique random JPEGs (pixels identify records)."""
    rng = np.random.default_rng(seed)
    for cls in classes:
        d = os.path.join(root, cls)
        os.makedirs(d, exist_ok=True)
        for i in range(n_per_class):
            arr = rng.integers(0, 255, (size, size + 4, 3), np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"img_{i:03d}.jpg"),
                                      quality=90)


@pytest.fixture(scope="module")
def data_dirs(tmp_path_factory):
    """(imagefolder_root, shard_root) with train/ + val/ splits, packed small
    enough that each split spans several shards."""
    src = tmp_path_factory.mktemp("imagefolder")
    dst = tmp_path_factory.mktemp("shards")
    _make_imagefolder(str(src / "train"), TRAIN_N // 2, seed=1)
    _make_imagefolder(str(src / "val"), VAL_N // 2, seed=2)
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import make_shards
    finally:
        sys.path.pop(0)
    for split in ("train", "val"):
        make_shards.pack_split(str(src / split), str(dst / split),
                               shard_size_mb=0.01, quiet=True)
    return str(src), str(dst)


def _tiny_cfg(**kw):
    base = dict(
        image_size=16, patch_size=8, embed_dim=32, num_heads=2, num_blocks=2,
        num_classes=4, batch_size=BATCH, dtype="float32", lr=1e-3,
        warmup_steps=2, clip_grad_norm=1.0, seed=SEED, num_workers=2,
    )
    base.update(kw)
    return Config(**base).validate()


def _batch_hashes(batch):
    """One hash per (image, label) sample of a host batch dict."""
    images = np.asarray(batch["image"])
    labels = np.asarray(batch["label"])
    return [hashlib.sha1(images[i].tobytes()
                         + int(labels[i]).to_bytes(4, "little")).hexdigest()
            for i in range(images.shape[0])]


def _build_stream(cfg, split="train"):
    from vitax.parallel.mesh import build_mesh
    from vitax.data.stream import build_stream_datasets
    mesh = build_mesh(cfg)
    train_ds, train_loader, val_ds, val_loader = build_stream_datasets(cfg,
                                                                       mesh)
    if split == "train":
        val_loader.close()
        return train_ds, train_loader
    train_loader.close()
    return val_ds, val_loader


# --- container format ------------------------------------------------------


def test_writer_reader_round_trip(data_dirs):
    """Every payload byte and label comes back exactly, in listing order,
    across shard boundaries."""
    src, dst = data_dirs
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from make_shards import list_imagefolder
    finally:
        sys.path.pop(0)
    classes, samples = list_imagefolder(os.path.join(src, "train"))
    reader = ShardReader(os.path.join(dst, "train"))
    assert len(reader.shards) > 1, "fixture should span multiple shards"
    got = []
    for sid in range(len(reader.shards)):
        got.extend(reader.iter_shard(sid))
    assert len(got) == len(samples) == TRAIN_N
    for (payload, label), (path, want_label) in zip(got, samples):
        with open(path, "rb") as f:
            assert payload == f.read()
        assert label == want_label
    meta = reader.meta
    assert meta["classes"] == classes
    assert meta["num_records"] == TRAIN_N
    reader.close()


def test_reader_rejects_torn_shard(tmp_path):
    split = tmp_path / "train"
    writer = ShardWriter(str(split))
    writer.add(b"payload-bytes", 1)
    writer.close()
    reader = ShardReader(str(split))
    assert reader.read_record(0, 0) == (b"payload-bytes", 1)
    reader.close()
    # corrupt the magic -> loud format error, not garbage pixels
    shard_path = split / reader.shards[0]["name"]
    data = shard_path.read_bytes()
    shard_path.write_bytes(b"X" * len(MAGIC) + data[len(MAGIC):])
    reader2 = ShardReader(str(split))
    with pytest.raises(ShardFormatError, match="bad magic"):
        reader2.read_record(0, 0)
    reader2.close()


def test_missing_meta_is_loud(tmp_path):
    with pytest.raises(FileNotFoundError, match="make_shards"):
        load_split_meta(str(tmp_path))


def test_make_shards_cli(tmp_path, data_dirs):
    src, _ = data_dirs
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import make_shards
    finally:
        sys.path.pop(0)
    rc = make_shards.main(["--src", src, "--dst", str(tmp_path / "out"),
                           "--shard_size_mb", "0.01"])
    assert rc == 0
    for split in ("train", "val"):
        meta = load_split_meta(str(tmp_path / "out" / split))
        assert meta["num_records"] == (TRAIN_N if split == "train" else VAL_N)
    with pytest.raises(SystemExit):
        make_shards.main(["--src", src, "--dst", str(tmp_path / "bad"),
                          "--shard_size_mb", "0"])


# --- sampler: disjointness, determinism, cursor ----------------------------


def test_two_process_disjointness(data_dirs):
    """Fake 2-process topology: shard assignment and the per-epoch record
    streams are disjoint and jointly cover the shard set (ShardedSampler
    contract at shard granularity)."""
    _, dst = data_dirs
    meta = load_split_meta(os.path.join(dst, "train"))
    s0 = StreamSampler(meta, BATCH, shuffle=True, seed=SEED,
                       process_index=0, process_count=2)
    s1 = StreamSampler(meta, BATCH, shuffle=True, seed=SEED,
                       process_index=1, process_count=2)
    assert set(s0.my_shards).isdisjoint(s1.my_shards)
    assert sorted(s0.my_shards + s1.my_shards) == list(
        range(len(meta["shards"])))
    assert s0.steps_per_epoch == s1.steps_per_epoch
    for epoch in (1, 2):
        g0 = {s0.global_id(s, r)
              for s, r in s0.epoch_entries(epoch).reshape(-1, 2)}
        g1 = {s1.global_id(s, r)
              for s, r in s1.epoch_entries(epoch).reshape(-1, 2)}
        assert g0.isdisjoint(g1)
    counts = [int(s["records"]) for s in meta["shards"]]
    for world in (2, 3, 4):
        hosts = assign_shards(counts, world)
        flat = sorted(i for h in hosts for i in h)
        assert flat == list(range(len(counts)))


def test_epoch_shuffle_determinism(data_dirs):
    """Same (seed, epoch) -> identical plan; different epoch reshuffles both
    the shard order and the within-shard record order."""
    _, dst = data_dirs
    meta = load_split_meta(os.path.join(dst, "train"))
    s = StreamSampler(meta, BATCH, shuffle=True, seed=SEED,
                      process_index=0, process_count=1)
    twin = StreamSampler(meta, BATCH, shuffle=True, seed=SEED,
                         process_index=0, process_count=1)
    assert np.array_equal(s.epoch_entries(1), twin.epoch_entries(1))
    assert not np.array_equal(s.epoch_entries(1), s.epoch_entries(2))
    assert s.shard_order(1) != s.shard_order(2) or not np.array_equal(
        s.record_order(1, s.my_shards[0]), s.record_order(2, s.my_shards[0]))
    # both epochs cover the same record SET (a permutation, not a resample)
    ids1 = sorted(s.global_id(a, b)
                  for a, b in s.epoch_entries(1).reshape(-1, 2))
    ids2 = sorted(s.global_id(a, b)
                  for a, b in s.epoch_entries(2).reshape(-1, 2))
    assert ids1 == ids2 == list(range(TRAIN_N))
    noshuffle = StreamSampler(meta, BATCH, shuffle=False, seed=SEED,
                              process_index=0, process_count=1)
    flat = noshuffle.epoch_entries(1).reshape(-1, 2)
    assert [noshuffle.global_id(a, b) for a, b in flat] == list(range(TRAIN_N))


def test_cursor_roundtrip_and_drift(data_dirs):
    _, dst = data_dirs
    meta = load_split_meta(os.path.join(dst, "train"))
    s = StreamSampler(meta, BATCH, shuffle=True, seed=SEED,
                      process_index=0, process_count=1)
    plan = s.epoch_entries(2)
    for step in range(s.steps_per_epoch + 1):
        cur = s.cursor_for_step(2, step)
        s.check_cursor(cur, 2, step)  # self-consistent
        if step < s.steps_per_epoch:
            # the cursor names exactly the next record the plan serves
            order = s.shard_order(2)
            shard = order[cur["shard_cursor"]]
            rec = s.record_order(2, shard)[cur["record_offset"]]
            assert plan[step][0][0] == shard and plan[step][0][1] == rec
    drifted = dict(s.cursor_for_step(2, 1))
    drifted["record_offset"] += 1
    with pytest.raises(RuntimeError, match="cursor mismatch"):
        s.check_cursor(drifted, 2, 1)
    # another host's cursor is not comparable -> ignored, not a false alarm
    other = dict(s.cursor_for_step(2, 1))
    other["process_index"] = 7
    other["record_offset"] += 1
    s.check_cursor(other, 2, 1)


# --- loader: resume equivalence, ImageFolder guard -------------------------


def test_midepoch_resume_exact_records(devices8, data_dirs):
    """Kill-mid-epoch-and-resume at loader level: consume k batches, "die",
    rebuild everything from scratch (a new process would), verify the stored
    cursor, resume at start_step=k — union(seen-before, seen-after) is
    exactly one full epoch with no duplicates."""
    _, dst = data_dirs
    cfg = _tiny_cfg(data_dir=dst, data_format="stream", fake_data=False)
    epoch, kill_at = 2, 2

    _, loader = _build_stream(cfg)
    full = []
    for batch in loader.epoch(epoch):
        full.extend(_batch_hashes(batch))
    loader.close()
    assert len(full) == len(set(full)) == TRAIN_N  # divisible: full coverage

    _, loader1 = _build_stream(cfg)  # the run that gets killed
    before = []
    it = loader1.epoch(epoch)
    for _ in range(kill_at):
        before.extend(_batch_hashes(next(it)))
    cursor = loader1.cursor_for_step(epoch, kill_at)  # what the sidecar keeps
    it.close()
    loader1.close()

    _, loader2 = _build_stream(cfg)  # the resumed run (fresh build)
    loader2.check_cursor(cursor, kill_at)  # shard set unchanged -> passes
    after = []
    for batch in loader2.epoch(epoch, start_step=kill_at):
        after.extend(_batch_hashes(batch))
    loader2.close()

    assert set(before).isdisjoint(after), "resume replayed seen records"
    assert sorted(before + after) == sorted(full), (
        "union(before-kill, after-resume) != one full epoch")
    assert before == full[:len(before)] and after == full[len(before):]


def test_stream_matches_imagefolder_samples(devices8, data_dirs):
    """The equivalence guard: for the same (seed, epoch), streaming and
    ImageFolder deliver IDENTICAL sample sets — same decoded+augmented
    pixels, same labels — differing only in order (the two samplers shuffle
    differently). Val (no shuffle) matches in exact order."""
    from vitax.parallel.mesh import build_mesh
    from vitax.data.loader import ShardedLoader, ShardedSampler
    from vitax.data.imagefolder import ImageFolderDataset
    from vitax.data.transforms import train_transform, val_transform
    src, dst = data_dirs
    cfg = _tiny_cfg(data_dir=dst, data_format="stream", fake_data=False)
    mesh = build_mesh(cfg)

    _, s_loader = _build_stream(cfg)
    stream_set = []
    for batch in s_loader.epoch(1):
        stream_set.extend(_batch_hashes(batch))
    s_loader.close()

    folder_ds = ImageFolderDataset(
        os.path.join(src, "train"),
        train_transform(cfg.image_size, cfg.seed, normalize=False))
    folder_loader = ShardedLoader(
        folder_ds, ShardedSampler(len(folder_ds), BATCH, shuffle=True,
                                  seed=cfg.seed), mesh, num_workers=2)
    folder_set = []
    for batch in folder_loader.epoch(1):
        folder_set.extend(_batch_hashes(batch))
    folder_loader.close()
    assert sorted(stream_set) == sorted(folder_set)
    assert len(set(stream_set)) == TRAIN_N

    _, sv_loader = _build_stream(cfg, split="val")
    stream_val = []
    for batch in sv_loader.epoch(0):
        stream_val.extend(_batch_hashes(batch))
    sv_loader.close()
    val_ds = ImageFolderDataset(
        os.path.join(src, "val"),
        val_transform(cfg.image_size, normalize=False))
    val_loader = ShardedLoader(
        val_ds, ShardedSampler(len(val_ds), BATCH, shuffle=False,
                               seed=cfg.seed), mesh, num_workers=2)
    folder_val = []
    for batch in val_loader.epoch(0):
        folder_val.extend(_batch_hashes(batch))
    val_loader.close()
    assert stream_val == folder_val  # no shuffle: exact order too


# --- native decode parity (serve satellite) --------------------------------


def test_native_bytes_decode_parity(tmp_path):
    """The in-memory native pipeline is BITWISE-identical to the file-based
    one (same bytes, same params) — the property that lets shard records and
    /predict bodies reuse the training decode path."""
    from vitax.data import native
    from vitax.data.transforms import val_transform
    if not native.mem_available():
        pytest.skip("native memory-source API unavailable")
    rng = np.random.default_rng(7)
    arr = rng.integers(0, 255, (50, 62, 3), np.uint8)
    path = str(tmp_path / "img.jpg")
    Image.fromarray(arr).save(path, quality=92)
    with open(path, "rb") as f:
        raw = f.read()
    assert native.is_jpeg_bytes(raw)
    assert native.jpeg_size_bytes(raw) == native.jpeg_size(path) == (62, 50)
    t = val_transform(16, normalize=False)
    params = t.native_params(0, 0, 0)
    from_bytes = native.process_bytes(raw, params, 16, t.resize_to,
                                      normalize=False)
    from_file = native.process_file(path, params, 16, t.resize_to,
                                    normalize=False)
    assert from_bytes is not None and from_file is not None
    assert np.array_equal(from_bytes, from_file)
    # batch mem call agrees with per-item mem calls
    batch, failed = native.process_batch_bytes([raw, raw], [params, params],
                                               16, t.resize_to, n_threads=2,
                                               normalize=False)
    assert failed == []
    assert np.array_equal(batch[0], from_bytes)
    assert np.array_equal(batch[1], from_bytes)


def test_serve_decode_native_vs_pil(tmp_path):
    """serve decode_image_bytes: JPEG bodies take the native resize path
    (within the established native-vs-PIL resample tolerance of the training
    pipeline), non-JPEG bodies fall back to PIL exactly."""
    from vitax.data import native
    from vitax.data.transforms import val_transform
    from vitax.serve.server import decode_image_bytes
    rng = np.random.default_rng(11)
    arr = rng.integers(0, 255, (48, 56, 3), np.uint8)
    jpg = str(tmp_path / "img.jpg")
    Image.fromarray(arr).save(jpg, quality=92)
    with open(jpg, "rb") as f:
        raw = f.read()
    t = val_transform(16, normalize=False)
    out = decode_image_bytes(raw, t)
    assert out.shape == (16, 16, 3) and out.dtype == np.uint8
    with Image.open(jpg) as img:
        pil = t(img.convert("RGB"))
    if native.mem_available():
        # bitwise vs the file-based native path training eval uses...
        params = t.native_params(0, 0, 0)
        want = native.process_file(jpg, params, 16, t.resize_to,
                                   normalize=False)
        assert np.array_equal(out, want)
        # ...and within the PIL resample tolerance (test_native.py LSB bound)
        diff = np.abs(out.astype(np.int32) - pil.astype(np.int32))
        assert diff.mean() <= 255 * 0.018
    else:
        assert np.array_equal(out, pil)
    # PNG body: PIL fallback, exact
    png = str(tmp_path / "img.png")
    Image.fromarray(arr).save(png)
    with open(png, "rb") as f:
        raw_png = f.read()
    assert not native.is_jpeg_bytes(raw_png)
    with Image.open(png) as img:
        want_png = t(img.convert("RGB"))
    assert np.array_equal(decode_image_bytes(raw_png, t), want_png)


# --- fault drill -----------------------------------------------------------


def test_stream_read_fault_drill(data_dirs):
    """stream_read oserror x2 exhausts the single retry and surfaces
    LoaderWorkerError carrying the shard path; x1 is absorbed by the retry."""
    _, dst = data_dirs
    split = os.path.join(dst, "train")
    try:
        faults.install(json.dumps(
            {"site": "stream_read", "at": 1, "times": 2,
             "action": "oserror"}))
        reader = ShardReader(split)
        with pytest.raises(LoaderWorkerError) as exc_info:
            reader.read_record(0, 0)
        assert reader.shards[0]["name"] in str(exc_info.value)
        reader.close()
    finally:
        faults.uninstall()
    try:
        faults.install(json.dumps(
            {"site": "stream_read", "at": 1, "times": 1,
             "action": "oserror"}))
        reader = ShardReader(split)
        payload, label = reader.read_record(0, 0)  # retry absorbed it
        assert len(payload) > 0
        reader.close()
    finally:
        faults.uninstall()


def test_stream_read_fault_through_loader(devices8, data_dirs):
    """The same drill through the producer thread: the consumer gets a
    LoaderWorkerError with the worker traceback, not a silent stall."""
    _, dst = data_dirs
    cfg = _tiny_cfg(data_dir=dst, data_format="stream", fake_data=False)
    try:
        faults.install(json.dumps(
            {"site": "stream_read", "at": 1, "times": 2,
             "action": "oserror"}))
        _, loader = _build_stream(cfg)
        with pytest.raises(LoaderWorkerError, match="stream worker failed"):
            for _ in loader.epoch(1):
                pass
        loader.close()
    finally:
        faults.uninstall()


# --- config + tooling satellites -------------------------------------------


def test_config_validation(data_dirs):
    _, dst = data_dirs
    with pytest.raises(AssertionError, match="stream_prefetch"):
        _tiny_cfg(stream_prefetch=0)
    with pytest.raises(AssertionError, match="data_format"):
        _tiny_cfg(data_format="webdataset")
    with pytest.raises(AssertionError, match="fake_data"):
        _tiny_cfg(data_format="stream", fake_data=True)
    with pytest.raises(AssertionError, match="shard root"):
        _tiny_cfg(data_format="stream", data_dir="")
    cfg = _tiny_cfg(data_format="stream", data_dir=dst, stream_prefetch=3)
    assert cfg.stream_prefetch == 3
    # the CLI surface carries both flags
    from vitax.config import build_parser
    ns = build_parser().parse_args(
        ["--data_format", "stream", "--stream_prefetch", "4"])
    assert ns.data_format == "stream" and ns.stream_prefetch == 4


def test_metrics_report_input_bound(tmp_path, capsys):
    """--json gains input_bound: the fraction of steps whose data wait
    exceeds 10% of the step — the streaming plane's acceptance metric."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import metrics_report
    finally:
        sys.path.pop(0)
    path = tmp_path / "run.jsonl"
    with open(path, "w") as f:
        for i in range(1, 11):
            f.write(json.dumps({
                "schema": 1, "time": 1000.0 + i, "step": i, "epoch": 1,
                "step_in_epoch": i, "loss": 2.0, "lr": 1e-3,
                "sec_per_iter": 1.0,
                # 3 of 10 steps input-bound (wait > 10% of the step)
                "data_wait_s": 0.5 if i <= 3 else 0.01}) + "\n")
    summary = metrics_report.summarize(str(path))
    assert summary["input_bound"] == pytest.approx(0.3)
    metrics_report.print_human(summary)
    out = capsys.readouterr().out
    assert "input-bound steps" in out and "30.0%" in out
    # a healthy run reports 0.0, and human mode drops the (!!) flag
    with open(path, "w") as f:
        f.write(json.dumps({
            "schema": 1, "time": 1.0, "step": 1, "epoch": 1,
            "step_in_epoch": 1, "loss": 2.0, "lr": 1e-3,
            "sec_per_iter": 1.0, "data_wait_s": 0.0}) + "\n")
    healthy = metrics_report.summarize(str(path))
    assert healthy["input_bound"] == 0.0
    metrics_report.print_human(healthy)
    assert "(!!)" not in capsys.readouterr().out


# --- end-to-end through train() --------------------------------------------


def test_step_program_identical_stream_vs_imagefolder(devices8, data_dirs):
    """The input pipeline is host-side only: the compiled train-step program
    is bit-identical between --data_format stream and imagefolder configs."""
    from test_train_smoke import build_train_objects
    _, dst = data_dirs
    cfg_folder = _tiny_cfg()
    cfg_stream = _tiny_cfg(data_format="stream", data_dir=dst,
                           stream_prefetch=4)
    mesh, state, step_fn, _ = build_train_objects(cfg_folder)
    _, state2, step_fn2, _ = build_train_objects(cfg_stream)
    from test_train_smoke import random_batch
    batch = random_batch(cfg_folder, mesh)
    rng = jax.random.key(0)
    text1 = step_fn.lower(state, batch, rng).as_text()
    text2 = step_fn2.lower(state2, batch, rng).as_text()
    assert text1 == text2


def test_train_e2e_stream(devices8, data_dirs, tmp_path):
    """--data_format stream trains end-to-end through the full train()
    orchestration (epoch accounting, telemetry data_wait_s wiring, eval over
    the streaming val split, checkpoint save)."""
    from vitax.train.loop import train
    _, dst = data_dirs
    metrics_dir = str(tmp_path / "metrics")
    cfg = _tiny_cfg(
        data_format="stream", data_dir=dst, fake_data=False, num_epochs=1,
        log_step_interval=1, ckpt_dir=str(tmp_path / "ckpt"),
        ckpt_epoch_interval=1, test_epoch_interval=1, eval_max_batches=1,
        metrics_dir=metrics_dir)
    state = train(cfg)
    assert int(jax.device_get(state.step)) == TRAIN_N // BATCH
    assert os.path.isdir(os.path.join(str(tmp_path / "ckpt"), "epoch_1"))
    records = []
    with open(os.path.join(metrics_dir, "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if "step" in rec and not rec.get("kind"):
                records.append(rec)
    assert records and all("data_wait_s" in r for r in records)


def test_kill_midepoch_and_resume_e2e(devices8, data_dirs, tmp_path):
    """The full story: SIGTERM mid-epoch -> committed checkpoint whose
    sidecar carries the (epoch, shard_cursor, record_offset) cursor ->
    auto-resume verifies the cursor and consumes exactly the not-yet-seen
    steps (total step count proves no batch was repeated or skipped)."""
    import signal
    from vitax.checkpoint.orbax_io import load_resume_step, load_stream_cursor
    from vitax.train import preempt
    from vitax.train.loop import train
    _, dst = data_dirs
    ckpt = str(tmp_path / "ckpt")
    steps_per_epoch = TRAIN_N // BATCH

    preempt.reset()
    assert preempt.install()
    os.kill(os.getpid(), signal.SIGTERM)
    try:
        cfg = _tiny_cfg(
            data_format="stream", data_dir=dst, fake_data=False,
            num_epochs=2, log_step_interval=99, ckpt_dir=ckpt,
            ckpt_epoch_interval=99, test_epoch_interval=99,
            eval_max_batches=1)
        state = train(cfg)
        assert int(jax.device_get(state.step)) == 1  # killed after one step
    finally:
        preempt.uninstall()
        preempt.reset()

    assert load_resume_step(ckpt, 1) == 1
    cursor = load_stream_cursor(ckpt, 1)
    assert cursor is not None
    assert cursor["epoch"] == 1 and cursor["step"] == 1
    # the sidecar cursor is exactly what the epoch plan derives for step 1
    meta = load_split_meta(os.path.join(dst, "train"))
    sampler = StreamSampler(meta, BATCH, shuffle=True, seed=SEED,
                            process_index=0, process_count=1)
    sampler.check_cursor(cursor, 1, 1)

    cfg2 = _tiny_cfg(
        data_format="stream", data_dir=dst, fake_data=False, num_epochs=2,
        resume_epoch=-1, log_step_interval=99, ckpt_dir=ckpt,
        ckpt_epoch_interval=99, test_epoch_interval=99, eval_max_batches=1)
    state2 = train(cfg2)
    # 1 step before the kill + the rest of epoch 1 + all of epoch 2
    assert int(jax.device_get(state2.step)) == 2 * steps_per_epoch


def test_epoch_rounded_resume_reruns_the_partial_epoch(devices8, data_dirs,
                                                       tmp_path, capsys):
    """Loop integration of the EPOCH-ROUNDED elastic path (the planner alone
    is covered in tests/test_control.py): a mid-epoch stream checkpoint whose
    sidecar records a different topology must RE-ENTER the checkpointed
    epoch from step 0 — re-running the partial epoch as announced — not
    treat resume_step=0 as 'epoch done' and skip its remaining records."""
    import signal
    from vitax.train import preempt
    from vitax.train.loop import train
    _, dst = data_dirs
    ckpt = str(tmp_path / "ckpt")
    steps_per_epoch = TRAIN_N // BATCH

    # 1) SIGTERM mid-epoch: commits a step-1 checkpoint with a stream cursor
    preempt.reset()
    assert preempt.install()
    os.kill(os.getpid(), signal.SIGTERM)
    try:
        cfg = _tiny_cfg(
            data_format="stream", data_dir=dst, fake_data=False,
            num_epochs=2, log_step_interval=99, ckpt_dir=ckpt,
            ckpt_epoch_interval=99, test_epoch_interval=99,
            eval_max_batches=1)
        state = train(cfg)
        assert int(jax.device_get(state.step)) == 1
    finally:
        preempt.uninstall()
        preempt.reset()

    # 2) simulate the topology change: rewrite the sidecar's recorded
    # process_count (this single-process harness cannot really re-launch
    # under N=2; the loop only ever sees the sidecar, so this exercises
    # exactly the rounded branch a real N->M restart takes)
    sidecar = os.path.join(ckpt, "epoch_1.resume.json")
    with open(sidecar) as f:
        meta = json.load(f)
    assert meta["step_in_epoch"] == 1 and "stream_cursor" in meta
    meta["process_count"] = 2
    with open(sidecar, "w") as f:
        json.dump(meta, f)

    # 3) auto-resume under 1 process: cursor invalidated -> epoch-rounded
    cfg2 = _tiny_cfg(
        data_format="stream", data_dir=dst, fake_data=False, num_epochs=2,
        resume_epoch=-1, log_step_interval=99, ckpt_dir=ckpt,
        ckpt_epoch_interval=99, test_epoch_interval=99, eval_max_batches=1)
    state2 = train(cfg2)
    out = capsys.readouterr().out
    assert "epoch-rounding the resume (re-running 1 mid-epoch steps)" in out
    assert "epoch-rounded resume: re-running epoch 1 from step 1" in out
    # 1 pre-kill step + ALL of epoch 1 re-run from its boundary + epoch 2
    # (before the fix the loop started at epoch 2 and this read
    # 1 + steps_per_epoch: the checkpointed epoch's remainder was skipped)
    assert int(jax.device_get(state2.step)) == 1 + 2 * steps_per_epoch
