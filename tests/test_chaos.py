"""Serve-path chaos layer: fault sites, breaker, retry budget, hedging,
brownout, and the end-to-end chaos drill.

Unit tier pins the CircuitBreaker state machine (open / half-open /
re-close, never opens under threshold) and RetryBudget token accounting
with injected clocks, the BrownoutController hysteresis, the health-loop
jitter seam, and the deterministic per-site firing indices of the new
serve fault sites (engine_predict, batcher_flush, replica_health,
router_dispatch). Router tier drives dispatch() over in-process fake
replicas: budget exhaustion -> fast 503 + Retry-After, hedges firing only
past the threshold and never double-counting, breaker containment of a
replica that fails every dispatch while answering health checks.

The drill (tier-1, real HTTP on ephemeral ports, fake predict_fn): a
3-replica fleet under a paced serve_bench burst with one replica
SIGKILLed, one predict-hung (batcher_flush hang), and one health-flapped
finishes with every client response inside the 200 / 429+Retry-After /
503+Retry-After envelope while the breaker opens and re-closes and the
retry budget stays within its fraction — plus a no-fault twin pinning
that an armed-but-never-firing plan changes nothing in the request path.
"""

import io
import json
import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from vitax import faults
from vitax.config import Config
from vitax.serve.batcher import DynamicBatcher
from vitax.serve.fleet import ReplicaManager, Router, start_router, stop_router
from vitax.serve.fleet.breaker import (CLOSED, HALF_OPEN, OPEN,
                                       CircuitBreaker, RetryBudget)
from vitax.serve.server import BrownoutController

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Every test starts and ends with no plan armed (the registry is
    module-global, so a leaked plan would poison unrelated tests)."""
    faults.uninstall()
    yield
    faults.uninstall()


def tiny_cfg(**kw):
    base = dict(
        image_size=16, patch_size=8, embed_dim=32, num_heads=2, num_blocks=2,
        num_classes=4, batch_size=16, dtype="float32", lr=1e-3, warmup_steps=2,
        serve_max_batch=4, serve_topk=3, max_batch_wait_ms=10.0, seed=0,
    )
    base.update(kw)
    return Config(**base).validate()


def png_bytes(size: int = 16, seed: int = 0) -> bytes:
    from PIL import Image
    rng = np.random.default_rng(seed)
    arr = rng.integers(0, 256, size=(size, size, 3), dtype=np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr, "RGB").save(buf, "PNG")
    return buf.getvalue()


def post_bytes(url: str, body: bytes, content_type: str = "image/png",
               timeout: float = 30.0) -> dict:
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": content_type})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


def get_json(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.load(resp)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class DummyRecorder:
    def __init__(self):
        self.events = []
        self._lock = threading.Lock()

    def event(self, kind, **payload):
        with self._lock:
            self.events.append((kind, payload))

    def of_kind(self, kind):
        with self._lock:
            return [p for k, p in self.events if k == kind]

    def close(self):
        pass


class FakeReplica:
    """In-process replica endpoint with failure dials (same shape as the
    test_fleet stand-in, plus a raw hit counter so breaker tests can pin
    that an OPEN breaker never even connects)."""

    def __init__(self, name: str):
        self.name = name
        self.fail_predicts = False
        self.queue_full = False
        self.hold = None             # Event: /predict blocks until set
        self.predict_started = threading.Event()
        self.predict_count = 0
        self.post_hits = 0           # every /predict arrival, any outcome
        self._lock = threading.Lock()
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003
                pass

            def _reply(self, code, payload, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    self._reply(200, {"status": "ok", "ready": True})
                else:
                    self._reply(200, {"requests_total": fake.predict_count})

            def do_POST(self):  # noqa: N802
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                with fake._lock:
                    fake.post_hits += 1
                if fake.queue_full:
                    self._reply(503, {"error": "overloaded",
                                      "reason": "queue_full"},
                                headers={"Retry-After": "2"})
                    return
                if fake.fail_predicts:
                    self._reply(500, {"error": "replica exploded"})
                    return
                fake.predict_started.set()
                if fake.hold is not None:
                    fake.hold.wait(timeout=30)
                with fake._lock:
                    fake.predict_count += 1
                self._reply(200, {"classes": [1, 0, 2],
                                  "probs": [0.5, 0.3, 0.2],
                                  "latency_ms": 1.0,
                                  "replica": fake.name})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture
def fleet_factory():
    cleanup = []

    def build(n=2, recorder=None, **router_kw):
        fakes = [FakeReplica("abcdefgh"[i]) for i in range(n)]
        manager = ReplicaManager(recorder=recorder, fail_threshold=2,
                                 health_jitter=0.0)
        for f in fakes:
            manager.adopt(f.url, name=f.name)
        manager.poll_once()
        router_kw.setdefault("request_timeout_s", 10.0)
        router = Router(manager, recorder=recorder, **router_kw)
        cleanup.append(fakes)
        return manager, router, fakes

    yield build
    for fakes in cleanup:
        for f in fakes:
            f.stop()


# --- circuit breaker state machine -------------------------------------------


def test_breaker_never_opens_under_threshold():
    t = [0.0]
    br = CircuitBreaker("r", fail_threshold=3, cooldown_s=2.0,
                        clock=lambda: t[0])
    for _ in range(2):
        br.record_failure()
    assert br.state() == CLOSED and br.opens_total == 0
    br.record_success()  # consecutive counter resets
    for _ in range(2):
        br.record_failure()
    assert br.state() == CLOSED and br.opens_total == 0
    assert br.eligible() and br.begin()


def test_breaker_open_half_open_reclose_matrix():
    t = [0.0]
    events = []
    br = CircuitBreaker("r", fail_threshold=3, cooldown_s=2.0,
                        clock=lambda: t[0], on_event=events.append)
    for _ in range(3):
        br.record_failure()
    assert br.state() == OPEN and br.opens_total == 1
    assert not br.eligible() and not br.begin()  # cooling down
    # a straggler failure from a pre-trip dispatch is a no-op
    br.record_failure()
    assert br.state() == OPEN and br.opens_total == 1

    t[0] = 2.0  # cooldown elapsed: exactly one probe admitted
    assert br.eligible()
    assert br.begin() and br.state() == HALF_OPEN
    assert not br.eligible() and not br.begin()  # probe slot taken
    br.record_failure()  # probe failed -> reopen for another cooldown
    assert br.state() == OPEN and br.reopens_total == 1
    assert not br.begin()

    t[0] = 4.0
    assert br.begin() and br.state() == HALF_OPEN
    br.record_success()  # probe succeeded -> back in rotation
    assert br.state() == CLOSED and br.closes_total == 1
    assert [e["event"] for e in events] == \
        ["open", "half_open", "reopen", "half_open", "close"]
    assert all(e["replica"] == "r" for e in events)


def test_breaker_release_unused_frees_probe_slot():
    t = [0.0]
    br = CircuitBreaker("r", fail_threshold=1, cooldown_s=1.0,
                        clock=lambda: t[0])
    br.record_failure()
    t[0] = 1.0
    assert br.begin()           # claims the half-open probe
    br.release_unused()         # picked but never dispatched
    assert br.begin()           # slot is free again


def test_retry_budget_token_accounting():
    b = RetryBudget(ratio=0.25, cap=10.0)
    assert b.enabled
    for _ in range(10):          # starts full at cap
        assert b.withdraw()
    assert not b.withdraw()      # dry
    assert b.exhausted_total == 1 and b.granted_total == 10
    for _ in range(4):           # 4 requests earn one retry token
        b.deposit()
    assert b.withdraw() and not b.withdraw()
    snap = b.snapshot()
    assert snap["granted_total"] == 11 and snap["exhausted_total"] == 2
    # ratio 0 disables: every withdraw granted (pre-budget behavior)
    b0 = RetryBudget(ratio=0.0)
    assert not b0.enabled
    assert all(b0.withdraw() for _ in range(100))


# --- router: budget, breaker, hedging ----------------------------------------


def test_retry_budget_exhaustion_fast_503(fleet_factory):
    rec = DummyRecorder()
    _, router, fakes = fleet_factory(n=2, recorder=rec,
                                     retry_budget_ratio=0.1)
    for f in fakes:
        f.fail_predicts = True
    while router.budget.withdraw():  # drain the initial full bucket
        pass
    status, headers, payload = router.dispatch(png_bytes(), "image/png")
    assert status == 503
    assert payload["reason"] == "retry_budget_exhausted"
    assert headers["Retry-After"] == "1"
    # the first attempt went out, the RETRY did not: budget bounds
    # amplification, not first tries
    assert fakes[0].post_hits + fakes[1].post_hits == 1
    assert any(p.get("event") == "exhausted"
               for p in rec.of_kind("retry_budget"))
    snap = router.fleet_metrics()
    assert snap["retry_budget"]["exhausted_total"] >= 1


def test_breaker_contains_replica_that_fails_every_dispatch(fleet_factory):
    rec = DummyRecorder()
    _, router, fakes = fleet_factory(
        n=1, recorder=rec, breaker_threshold=2, breaker_cooldown_s=0.2)
    fakes[0].fail_predicts = True
    for _ in range(2):
        status, _, payload = router.dispatch(png_bytes(), "image/png")
        assert status == 503 and payload["reason"] == "dispatch_failed"
    br = router._breaker("a")
    assert br.state() == OPEN and br.opens_total == 1
    # while open the router never even connects (no timeout burned)
    hits = fakes[0].post_hits
    status, _, payload = router.dispatch(png_bytes(), "image/png")
    assert status == 503 and fakes[0].post_hits == hits
    # replica recovers; after the cooldown one probe re-admits it
    fakes[0].fail_predicts = False
    time.sleep(0.25)
    status, _, _ = router.dispatch(png_bytes(), "image/png")
    assert status == 200
    assert br.state() == CLOSED and br.closes_total == 1
    assert [p["event"] for p in rec.of_kind("breaker")] == \
        ["open", "half_open", "close"]
    snap = router.fleet_metrics()
    assert snap["breaker_opens"] == 1
    assert snap["breakers"]["a"]["state"] == CLOSED


def test_breaker_ignores_backpressure_and_client_errors(fleet_factory):
    """queue_full 503 and 4xx mean the replica ANSWERED: backpressure and
    client mistakes must never trip the breaker."""
    _, router, fakes = fleet_factory(n=1, breaker_threshold=2)
    fakes[0].queue_full = True
    for _ in range(4):
        status, headers, _ = router.dispatch(png_bytes(), "image/png")
        assert status == 429 and "Retry-After" in headers
    br = router._breaker("a")
    assert br.state() == CLOSED and br.opens_total == 0
    assert br.snapshot()["consecutive_failures"] == 0


def test_hedge_fires_only_past_threshold(fleet_factory):
    _, router, fakes = fleet_factory(n=2, hedge_after_ms=500.0)
    for _ in range(3):  # fast primaries: the hedge must stay holstered
        status, _, _ = router.dispatch(png_bytes(), "image/png")
        assert status == 200
    assert router.metrics.hedges_total == 0
    assert router.budget.snapshot()["granted_total"] == 0


def test_hedge_wins_and_never_double_counts(fleet_factory):
    rec = DummyRecorder()
    _, router, fakes = fleet_factory(n=2, recorder=rec, hedge_after_ms=50.0)
    fakes[0].hold = threading.Event()  # primary (first adopted) wedges
    status, _, payload = router.dispatch(png_bytes(), "image/png")
    assert status == 200
    assert json.loads(payload)["replica"] == "b"  # the hedge answered
    assert router.metrics.hedges_total == 1
    assert router.metrics.hedge_wins_total == 1
    assert router.metrics.requests_total == 1     # counted exactly once
    events = [p["event"] for p in rec.of_kind("hedge")]
    assert events == ["fired", "win"]
    # the losing primary lands later; per-request counters must not move
    fakes[0].hold.set()
    deadline = time.time() + 10
    while fakes[0].predict_count == 0 and time.time() < deadline:
        time.sleep(0.01)
    assert fakes[0].predict_count == 1
    time.sleep(0.1)
    assert router.metrics.requests_total == 1
    assert router.metrics.errors_total == 0
    assert router.manager.total_in_flight() == 0  # both slots released


def test_hedge_bounded_by_retry_budget(fleet_factory):
    _, router, fakes = fleet_factory(n=2, hedge_after_ms=30.0,
                                     retry_budget_ratio=0.1)
    while router.budget.withdraw():
        pass
    fakes[0].hold = threading.Event()
    done = []
    t = threading.Thread(target=lambda: done.append(
        router.dispatch(png_bytes(), "image/png")))
    t.start()
    time.sleep(0.3)  # well past the hedge delay: a hedge WOULD have fired
    assert router.metrics.hedges_total == 0  # budget dry -> no hedge
    fakes[0].hold.set()
    t.join(timeout=10)
    assert done and done[0][0] == 200  # primary still answers


# --- brownout hysteresis ------------------------------------------------------


def test_brownout_hysteresis_with_injected_clock():
    entered, exited = [], []
    ctl = BrownoutController(
        queue_max=10, enter_frac=0.8, exit_frac=0.2, dwell_s=2.0,
        clock=lambda: 0.0, on_enter=lambda: entered.append(1),
        on_exit=exited.append)
    assert ctl.enabled
    # pressure must SUSTAIN the dwell: a blip never flips the mode
    assert ctl.observe(9, now=0.0) is False
    assert ctl.observe(9, now=1.0) is False
    assert ctl.observe(0, now=1.5) is False    # streak broken
    assert ctl.observe(9, now=2.0) is False    # new streak starts here
    assert ctl.observe(9, now=3.9) is False
    assert ctl.observe(9, now=4.0) is True     # dwell met -> degraded
    assert entered == [1] and ctl.enters_total == 1
    # depths between the thresholds hold the current state
    assert ctl.observe(5, now=5.0) is True
    # calm must also sustain the dwell
    assert ctl.observe(1, now=6.0) is True
    assert ctl.observe(3, now=7.0) is True     # calm streak broken (3 > 2)
    assert ctl.observe(1, now=8.0) is True
    assert ctl.observe(1, now=10.0) is False   # recovered
    assert len(exited) == 1
    assert exited[0] == pytest.approx(6.0)     # degraded t=4..10
    assert ctl.degraded_seconds(now=11.0) == pytest.approx(6.0)


def test_brownout_disabled_without_queue_bound():
    assert not BrownoutController(queue_max=0, enter_frac=0.8, exit_frac=0.2,
                                  dwell_s=1.0).enabled
    assert not BrownoutController(queue_max=10, enter_frac=0.0, exit_frac=0.0,
                                  dwell_s=1.0).enabled
    ctl = BrownoutController(queue_max=0, enter_frac=0.8, exit_frac=0.2,
                             dwell_s=0.0)
    assert ctl.observe(10 ** 6) is False and ctl.degraded_seconds() == 0.0


class FakeEngine:
    """InferenceEngine stand-in (same surface the server/batcher touch)."""

    def __init__(self):
        self.buckets = (1, 2, 4)
        self.topk = 3
        self.compile_count = 3
        self.ready = True
        self.hold = None
        self.predict_started = threading.Event()

    def predict(self, images):
        self.predict_started.set()
        if self.hold is not None:
            self.hold.wait(timeout=30)
        n = images.shape[0]
        return (np.tile(np.arange(3, dtype=np.int32), (n, 1)),
                np.tile(np.array([0.5, 0.3, 0.2], np.float32), (n, 1)))


def test_brownout_server_degrades_and_recovers():
    """Real server + FakeEngine: sustained queue pressure enters degraded
    (healthz advertises it, topk clamps to 1, batcher deadline shortens);
    drain + dwell exits and restores the tuning."""
    from vitax.serve import start_server, stop_server
    engine = FakeEngine()
    engine.hold = threading.Event()
    cfg = tiny_cfg(serve_max_batch=1, serve_queue_max=4,
                   max_batch_wait_ms=50.0, serve_brownout_enter_frac=0.5,
                   serve_brownout_exit_frac=0.25, serve_brownout_dwell_s=0.15,
                   serve_brownout_wait_ms=1.0)
    httpd, ctx = start_server(cfg, engine, port=0)
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    results, threads = [], []

    def bg():
        results.append(post_bytes(url + "/predict", png_bytes()))

    try:
        assert get_json(url + "/healthz")["degraded"] is False
        for _ in range(3):  # 1 in predict + 2 queued >= enter depth 2
            t = threading.Thread(target=bg)
            t.start()
            threads.append(t)
        assert engine.predict_started.wait(timeout=10)
        deadline = time.time() + 10
        while (ctx.batcher.queue_depth() < 2 and time.time() < deadline):
            time.sleep(0.01)
        while (not get_json(url + "/healthz")["degraded"]
               and time.time() < deadline):
            time.sleep(0.02)  # healthz polls feed the pressure window
        health = get_json(url + "/healthz")
        assert health["degraded"] is True
        assert ctx.batcher.max_wait_s == pytest.approx(0.001)  # shortened
        snap = get_json(url + "/metrics")
        assert snap["degraded"] is True and snap["brownout_enters"] == 1
        assert snap["ready"] is True  # degraded != unready: still serving
        # a request admitted while degraded sheds optional work: topk -> 1
        t = threading.Thread(target=bg)
        t.start()
        threads.append(t)
        # recovery: drain, hold calm for the dwell, tuning restored
        engine.hold.set()
        for t in threads:
            t.join(timeout=30)
        assert len(results) == 4
        topks = sorted(len(r["classes"]) for r in results)
        assert topks[-1] == 3 and topks[0] == 1  # pre-brownout 3, degraded 1
        while (get_json(url + "/healthz")["degraded"]
               and time.time() < deadline):
            time.sleep(0.02)
        snap = get_json(url + "/metrics")
        assert snap["degraded"] is False
        assert snap["degraded_seconds"] > 0
        assert ctx.batcher.max_wait_s == pytest.approx(0.05)  # restored
    finally:
        engine.hold.set()
        stop_server(httpd, ctx)


# --- fault sites: wiring + determinism ---------------------------------------


def test_serve_fault_sites_registered():
    for site in ("engine_predict", "batcher_flush", "replica_health",
                 "router_dispatch"):
        assert site in faults.SITES


def test_fault_site_firing_index_deterministic_across_reinstalls():
    plan = '{"site": "router_dispatch", "at": 3, "action": "oserror"}'

    def firing_indices(calls=6):
        fired = []
        for i in range(1, calls + 1):
            try:
                faults.fire("router_dispatch")
            except OSError:
                fired.append(i)
        return fired

    faults.install(plan)
    first = firing_indices()
    faults.uninstall()
    faults.install(plan)  # fresh counters: the same plan replays exactly
    assert firing_indices() == first == [3]


def test_router_dispatch_site_deterministic_across_router_restarts(
        fleet_factory):
    """Same plan -> same firing index, through two router instances over
    the same fleet (each install resets the per-site counters)."""
    plan = '{"site": "router_dispatch", "at": 2, "action": "oserror"}'
    manager, router1, fakes = fleet_factory(n=2)
    rec = DummyRecorder()
    faults.set_reporter(lambda p: rec.event("serve_fault", **p))
    for router in (router1, Router(manager, request_timeout_s=10.0)):
        faults.install(plan)
        s1, _, _ = router.dispatch(png_bytes(), "image/png")
        s2, _, _ = router.dispatch(png_bytes(), "image/png")
        assert (s1, s2) == (200, 200)  # the injected failure was retried
        assert router.metrics.retries_total == 1
    fired = rec.of_kind("serve_fault")
    assert [p["index"] for p in fired] == [2, 2]
    assert all(p["site"] == "router_dispatch" for p in fired)


def test_replica_health_site_targets_by_sweep_order():
    """Probes sweep registration order, so with N replicas index k*N + i
    targets replica i — plans can flap ONE replica's health."""
    faults.install('{"site": "replica_health", "at": 3, "action": "oserror"}')
    manager = ReplicaManager(
        health_jitter=0.0,
        http_get=lambda url, timeout: {"status": "ok", "ready": True})
    ra = manager.adopt("http://x:1", name="a")
    rb = manager.adopt("http://x:2", name="b")
    manager.poll_once()   # indices 1, 2: both admitted
    assert ra.state == "ready" and rb.state == "ready"
    manager.poll_once()   # indices 3 (a: injected failure), 4 (b: ok)
    assert ra.health_failures == 1 and rb.health_failures == 0
    assert ra.state == "ready"  # one flap is below fail_threshold


def test_batcher_flush_site_fails_batch_without_killing_worker():
    faults.install('{"site": "batcher_flush", "at": 1, "action": "oserror"}')
    calls = []

    def predict(images):
        calls.append(images.shape[0])
        return (np.zeros((images.shape[0], 3), np.int32),
                np.zeros((images.shape[0], 3), np.float32))

    b = DynamicBatcher(predict, max_batch=2, max_wait_ms=1.0,
                       bucket_of=lambda n: 2)
    try:
        fut = b.submit(np.zeros((16, 16, 3), np.uint8))
        with pytest.raises(OSError, match="injected fault"):
            fut.result(timeout=10)
        assert calls == []  # the fault fired before predict
        # the worker survived: the next batch flows
        fut = b.submit(np.zeros((16, 16, 3), np.uint8))
        assert fut.result(timeout=10).batch_size == 1
        assert calls == [1]  # the engine pads to buckets, not the batcher
    finally:
        b.close()


def test_engine_predict_site_fires_before_any_work():
    """The engine hook is the first statement of predict(): with a plan
    armed it fires before shapes are even read (no jax needed to pin)."""
    from vitax.serve.engine import InferenceEngine
    faults.install('{"site": "engine_predict", "at": 1, "action": "oserror"}')
    with pytest.raises(OSError, match="injected fault"):
        InferenceEngine.predict(object.__new__(InferenceEngine), None)


# --- health-loop jitter (satellite) ------------------------------------------


def test_health_interval_jitter_bounded_and_seeded():
    m1 = ReplicaManager(health_interval_s=1.0, health_jitter=0.2,
                        rng=random.Random(7))
    intervals = [m1._next_interval() for _ in range(64)]
    assert all(0.8 <= v <= 1.2 for v in intervals)
    assert len(set(intervals)) > 1  # actually jittered
    m2 = ReplicaManager(health_interval_s=1.0, health_jitter=0.2,
                        rng=random.Random(7))
    assert [m2._next_interval() for _ in range(64)] == intervals  # seeded
    # jitter 0 restores the fixed cadence; invalid jitter refused
    m3 = ReplicaManager(health_interval_s=1.0, health_jitter=0.0)
    assert {m3._next_interval() for _ in range(8)} == {1.0}
    with pytest.raises(AssertionError):
        ReplicaManager(health_jitter=1.5)


# --- the chaos drill ---------------------------------------------------------


_STUB_SRC = r"""
import json, sys
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

class H(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):
        pass
    def _reply(self, code, payload):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
    def do_GET(self):
        self._reply(200, {"status": "ok", "ready": True})
    def do_POST(self):
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        self._reply(200, {"classes": [1, 0, 2], "probs": [0.5, 0.3, 0.2],
                          "latency_ms": 1.0})

httpd = ThreadingHTTPServer(("127.0.0.1", int(sys.argv[1])), H)
httpd.daemon_threads = True
print("ready", flush=True)
httpd.serve_forever()
"""


def _start_stub(port: int):
    proc = subprocess.Popen([sys.executable, "-c", _STUB_SRC, str(port)],
                            stdout=subprocess.PIPE, text=True)
    assert proc.stdout.readline().strip() == "ready"
    return proc


def _import_serve_bench():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import serve_bench
        return serve_bench
    finally:
        sys.path.pop(0)


def test_chaos_drill_contract_under_kill_hang_and_flap():
    """The acceptance drill: 3 replicas under a paced burst — one
    SIGKILLed mid-burst, one predict-hung via batcher_flush, one
    health-flapped — and every client response stays inside the
    200 / 429+Retry-After / 503+Retry-After envelope while the breaker
    opens + re-closes and the retry budget holds its fraction."""
    from vitax.serve import start_server, stop_server
    serve_bench = _import_serve_bench()

    # the hang drill rides the real server's bounded request timeout: a
    # hung batch turns into fast 503s (dispatch failures) for the breaker
    engine = FakeEngine()
    cfg = tiny_cfg(serve_max_batch=4, max_batch_wait_ms=2.0,
                   serve_request_timeout_s=0.3)
    httpd_b, ctx_b = start_server(cfg, engine, port=0)
    url_b = f"http://127.0.0.1:{httpd_b.server_address[1]}"
    stub_a = _start_stub(free_port_a := free_port())
    stub_c = _start_stub(free_port_c := free_port())

    # one combined plan, disjoint sites, armed BEFORE any counter advances:
    # - B's 2nd batch flush hangs 1.2s (its requests 503 at the 0.3s
    #   timeout -> breaker failures while /healthz still answers)
    # - health sweeps are 3 probes in adoption order (a, b, c), so indices
    #   6 and 9 flap replica c on consecutive sweeps -> eject + re-admit
    faults.install(json.dumps({"faults": [
        {"site": "batcher_flush", "at": 2, "action": "hang", "seconds": 1.2},
        {"site": "replica_health", "at": 6, "action": "oserror"},
        {"site": "replica_health", "at": 9, "action": "oserror"},
    ]}))
    rec = DummyRecorder()
    faults.set_reporter(lambda p: rec.event("serve_fault", **p))

    manager = ReplicaManager(recorder=rec, fail_threshold=2,
                             health_jitter=0.0)
    manager.adopt(f"http://127.0.0.1:{free_port_a}", name="a")
    manager.adopt(url_b, name="b")
    manager.adopt(f"http://127.0.0.1:{free_port_c}", name="c")
    manager.poll_once()  # sweep 1 (indices 1-3): everyone admitted
    assert manager.ready_count() == 3

    router = Router(manager, recorder=rec, request_timeout_s=5.0,
                    breaker_threshold=2, breaker_cooldown_s=0.2,
                    retry_budget_ratio=0.5)
    httpd_r = start_router(router, 0)
    url = f"http://127.0.0.1:{httpd_r.server_address[1]}"

    def mid_burst_chaos():
        time.sleep(0.3)
        os.kill(stub_a.pid, signal.SIGKILL)  # replica a: gone, no drain
        stub_a.wait()
        for _ in range(3):                   # sweeps 2-4: flap + eject c
            time.sleep(0.25)
            manager.poll_once()

    chaos = threading.Thread(target=mid_burst_chaos)
    chaos.start()
    try:
        summary = serve_bench.run_bench(
            url, concurrency=4, requests_per_worker=10, image_size=16,
            timeout=10.0, target_rps=25.0, replicas=3)
        chaos.join(timeout=30)

        # the whole contract: nothing leaked past 200/429/503+Retry-After
        assert summary["errors"] == 0, summary["error_samples"]
        assert summary["errors_by_class"] == {}
        assert summary["completed"] > 0
        assert (summary["completed"] + summary["shed"]
                + summary["unavailable"]) == summary["requests"]

        # replica a died for real and left rotation
        assert manager.ready_count() == 2
        # replica c was flapped out and re-admitted
        ejects = [p for p in rec.of_kind("replica_eject")
                  if p["replica"] == "c"]
        admits = [p for p in rec.of_kind("replica_admit")
                  if p["replica"] == "c"]
        assert ejects and admits
        # the hang fired on b's batcher and the flap on the health probes
        fired_sites = {p["site"] for p in rec.of_kind("serve_fault")}
        assert fired_sites == {"batcher_flush", "replica_health"}

        # breaker engaged on the hung replica AND recovered. Least-loaded
        # selection prefers the healthy c (b's EWMA carries the timeout
        # spikes), so force the half-open probe: take c out of rotation
        # and drive traffic — b is the only candidate, the hang is long
        # over, and the probe re-closes the breaker.
        br = router._breaker("b")
        assert br.opens_total >= 1, br.snapshot()
        stub_c.kill()
        stub_c.wait()
        manager.poll_once()
        manager.poll_once()  # 2 failed probes = fail_threshold: c ejected
        deadline = time.time() + 10
        while br.state() != CLOSED and time.time() < deadline:
            post_bytes(url + "/predict", png_bytes(), timeout=10.0)
            time.sleep(0.05)
        assert br.state() == CLOSED and br.closes_total >= 1

        # retry budget held its fraction: grants never exceed the earned
        # tokens (initial bucket + ratio per dispatched request)
        budget = router.budget.snapshot()
        assert budget["granted_total"] <= (
            budget["cap"] + budget["ratio"] * budget["deposits_total"])
    finally:
        faults.uninstall()
        stop_router(httpd_r)
        stop_server(httpd_b, ctx_b)
        for proc in (stub_a, stub_c):
            if proc.poll() is None:
                proc.kill()
                proc.wait()


def test_no_fault_plan_request_path_unchanged(fleet_factory):
    """The zero-overhead pin: an armed plan that never fires leaves the
    request path identical to no plan at all — same payload, no retries,
    no breaker movement, no budget spend. Single replica so load-balancing
    cannot alternate the serving replica between the two runs."""
    _, router, fakes = fleet_factory(n=1)
    httpd = start_router(router, 0)
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        def probe():
            resp = post_bytes(url + "/predict", png_bytes())
            resp.pop("latency_ms")  # wall-clock, not part of the contract
            return resp

        baseline = [probe() for _ in range(4)]
        faults.install(json.dumps(  # armed, but firing at call 10^9
            {"site": "router_dispatch", "at": 10 ** 9, "action": "crash"}))
        armed = [probe() for _ in range(4)]
        assert armed == baseline
        m = router.metrics.snapshot()
        assert m["requests_total"] == 8 and m["errors_total"] == 0
        assert m["retries_total"] == 0 and m["hedges_total"] == 0
        # closed breakers never moved and cost no dispatch
        assert all(b["state"] == CLOSED and b["opens_total"] == 0
                   for b in router.fleet_metrics()["breakers"].values())
        assert router.budget.snapshot()["granted_total"] == 0
    finally:
        stop_router(httpd)


# --- serve_bench taxonomy (satellite) ----------------------------------------


def test_serve_bench_error_taxonomy_classifier():
    serve_bench = _import_serve_bench()
    classify = serve_bench.classify_error
    assert classify(urllib.error.URLError(
        ConnectionRefusedError(111, "refused"))) == "connection_refused"
    assert classify(urllib.error.URLError(
        ConnectionResetError(104, "reset"))) == "reset_mid_body"
    assert classify(ConnectionResetError(104, "reset")) == "reset_mid_body"
    assert classify(socket.timeout("timed out")) == "timeout"
    assert classify(TimeoutError("timed out")) == "timeout"
    assert classify(urllib.error.URLError(
        socket.timeout("timed out"))) == "timeout"
    err5 = urllib.error.HTTPError("u", 500, "boom", {}, None)
    assert classify(err5) == "http_5xx"
    err4 = urllib.error.HTTPError("u", 404, "nope", {}, None)
    assert classify(err4) == "other"


def test_serve_bench_buckets_unavailable_and_classes():
    """A 503 WITH Retry-After is the fleet's bounded-degradation contract
    (counted as `unavailable`, exit 0); a bare 500 is an http_5xx error."""
    serve_bench = _import_serve_bench()
    state = {"n": 0}

    class Flaky(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: A003
            pass

        def do_POST(self):  # noqa: N802
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            state["n"] += 1
            if state["n"] % 3 == 1:
                body = b'{"error": "boom"}'
                self.send_response(500)
            elif state["n"] % 3 == 2:
                body = (b'{"error": "retry budget exhausted",'
                        b' "reason": "retry_budget_exhausted"}')
                self.send_response(503)
                self.send_header("Retry-After", "0")
            else:
                body = (b'{"classes": [1], "probs": [0.9],'
                        b' "latency_ms": 1.0}')
                self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Flaky)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        summary = serve_bench.run_bench(
            url, concurrency=1, requests_per_worker=6, image_size=16,
            timeout=10.0)
        assert summary["completed"] == 2
        assert summary["unavailable"] == 2   # 503 + Retry-After: contract
        assert summary["errors"] == 2        # bare 500s are real errors
        assert summary["errors_by_class"] == {"http_5xx": 2}
        json.dumps(summary)  # --json stays one serializable object
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_chaos_endpoint_gated_and_installs():
    """POST /chaos: 403 without --serve_allow_chaos; with it, installs a
    plan (bad plans 400, empty body disarms)."""
    from vitax.serve import start_server, stop_server
    engine = FakeEngine()
    httpd, ctx = start_server(tiny_cfg(), engine, port=0)
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    plan = b'{"site": "engine_predict", "at": 5, "action": "oserror"}'
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            post_bytes(url + "/chaos", plan, "application/json")
        assert e.value.code == 403
        assert not faults.active()
    finally:
        stop_server(httpd, ctx)

    httpd, ctx = start_server(tiny_cfg(serve_allow_chaos=True), engine,
                              port=0)
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        out = post_bytes(url + "/chaos", plan, "application/json")
        assert "engine_predict:oserror(at=5)" in out["installed"]
        assert faults.active()
        with pytest.raises(urllib.error.HTTPError) as e:
            post_bytes(url + "/chaos", b'{"site": "nope", "action": "hang"}',
                       "application/json")
        assert e.value.code == 400
        assert post_bytes(url + "/chaos", b"",
                          "application/json") == {"installed": None}
        assert not faults.active()
    finally:
        stop_server(httpd, ctx)


def test_serve_bench_chaos_forwarding(fleet_factory):
    """serve_bench --chaos discovers replica URLs from the router's
    /metrics and POSTs the plan to each /chaos endpoint."""
    from vitax.serve import start_server, stop_server
    serve_bench = _import_serve_bench()
    engine = FakeEngine()
    httpd_b, ctx_b = start_server(tiny_cfg(serve_allow_chaos=True), engine,
                                  port=0)
    url_b = f"http://127.0.0.1:{httpd_b.server_address[1]}"
    manager = ReplicaManager(health_jitter=0.0)
    manager.adopt(url_b, name="b")
    manager.poll_once()
    router = Router(manager, request_timeout_s=10.0)
    httpd_r = start_router(router, 0)
    url = f"http://127.0.0.1:{httpd_r.server_address[1]}"
    try:
        plan = '{"site": "engine_predict", "at": 7, "action": "oserror"}'
        results = serve_bench.install_chaos(url, plan)
        assert results == {
            "b": {"installed": "engine_predict:oserror(at=7)"}}
        assert faults.active()  # the replica shares this process
    finally:
        stop_router(httpd_r)
        stop_server(httpd_b, ctx_b)
