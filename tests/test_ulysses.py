"""Ulysses (all-to-all) sequence parallelism on the 8-virtual-device CPU mesh:
numerics + gradients vs dense attention, selector routing, and a full
sequence-parallel train step matching the FSDP-only trajectory — mirrors the
ring-attention suite (tests/test_ring_attention.py) for --sp_impl ulysses."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vitax.config import Config
from vitax.ops.attention import make_attention_impl, reference_attention
from vitax.parallel.mesh import build_mesh
from vitax.parallel.ulysses import make_ulysses_attention


def sp_cfg(**kw):
    base = dict(image_size=32, patch_size=8, embed_dim=32, num_heads=4,
                num_blocks=2, num_classes=4, batch_size=8, dtype="float32",
                sp_size=4, fsdp_size=2, sp_impl="ulysses", warmup_steps=0)
    base.update(kw)
    return Config(**base).validate()


def _inner_impls():
    from vitax.ops.attention import flash_attention
    # None = dense reference inner; flash = the production TPU composition
    # (Pallas kernel inside the ulysses shard_map), interpret mode on CPU
    return [pytest.param(None, id="dense"),
            pytest.param(flash_attention, id="flash")]


@pytest.mark.parametrize("inner", _inner_impls())
def test_ulysses_matches_dense(devices8, inner):
    mesh = build_mesh(sp_cfg())  # dp1 x fsdp2 x tp1 x sp4
    ulysses = make_ulysses_attention(mesh, inner=inner)
    b, n, h, dh = 4, 16, 4, 8  # h % sp == 0
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (b, n, h, dh), jnp.float32)
    k = jax.random.normal(kk, (b, n, h, dh), jnp.float32)
    v = jax.random.normal(kv, (b, n, h, dh), jnp.float32)
    out = jax.jit(ulysses)(q, k, v)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("inner", _inner_impls())
def test_ulysses_grad_matches_dense(devices8, inner):
    mesh = build_mesh(sp_cfg())
    ulysses = make_ulysses_attention(mesh, inner=inner)
    shape = (2, 16, 4, 8)
    kq, kk, kv = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    got = jax.jit(jax.grad(loss(ulysses), argnums=(0, 1, 2)))(q, k, v)
    want = jax.grad(loss(reference_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_selector_routes_by_sp_impl(devices8):
    mesh = build_mesh(sp_cfg())
    impl = make_attention_impl(sp_cfg(), mesh)
    assert getattr(impl, "vitax_name", "") == "ulysses all-to-all (sp)"
    impl = make_attention_impl(sp_cfg(sp_impl="ring"), mesh)
    assert getattr(impl, "vitax_name", "") == "ring attention (sp)"
    # heads not divisible by sp*tp -> falls back to ring
    impl = make_attention_impl(sp_cfg(num_heads=2, embed_dim=32), mesh)
    assert getattr(impl, "vitax_name", "") == "ring attention (sp)"


def test_ulysses_train_step_equivalence(devices8):
    """Full train step with sp=4 (ulysses) must match the sp=1 FSDP
    trajectory — the resharding must not change the math."""
    from tests.test_train_smoke import run_steps

    cfg_sp = sp_cfg()
    cfg_base = sp_cfg(sp_size=1, fsdp_size=-1, sp_impl="ring")
    _, losses_sp = run_steps(cfg_sp, n_steps=4)
    _, losses_base = run_steps(cfg_base, n_steps=4)
    assert all(np.isfinite(losses_sp))
    np.testing.assert_allclose(losses_sp, losses_base, rtol=2e-4)


def test_ulysses_dropout_matches_masked_dense(devices8):
    """Ulysses in-kernel dropout (round 5): the resharded inner kernel drops
    with the shared counter-hash on its full-sequence head slice, seeded per
    shard. The oracle reconstructs the exact per-(shard, local-block) masks
    from the a2a layout (shard s holds heads [s*H/sp, (s+1)*H/sp)), so this
    also pins the head-slice ordering the seed-fold assumes."""
    from vitax.ops.attention import (_GOLD_BH, _fmix32, dropout_keep_mask,
                                     make_attention_impl)

    cfg = sp_cfg(sp_size=2, fsdp_size=1, att_dropout=0.25)
    mesh = build_mesh(cfg, devices=jax.devices()[:2])  # sp2 only
    impl = make_attention_impl(cfg, mesh, force_tpu_kernels=True)
    drop = getattr(impl, "vitax_dropout", None)
    assert drop is not None

    b, n, h, dh = 4, cfg.num_patches, cfg.num_heads, 8
    h_loc = h // 2
    rng_k = jax.random.split(jax.random.key(3), 3)
    q, k, v = (jax.random.normal(kk, (b, n, h, dh), jnp.float32)
               for kk in rng_k)
    seed, rate = jnp.uint32(17), cfg.att_dropout

    out = jax.jit(lambda q, k, v: drop(q, k, v, seed))(q, k, v)

    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * dh ** -0.5
    probs = jax.nn.softmax(s, axis=-1)
    masks = []
    for g in range(h):
        shard, hl = g // h_loc, g % h_loc
        seed_s = seed ^ _fmix32(jnp.uint32(shard) * jnp.uint32(_GOLD_BH))
        masks.append(jnp.stack([
            dropout_keep_mask(seed_s, jnp.uint32(bi * h_loc + hl), n, n,
                              rate) for bi in range(b)]))
    mask = jnp.stack(masks, axis=1)                      # (B, H, N, N)
    want = jnp.einsum("bhqk,bkhd->bqhd", probs * mask / (1 - rate), v)

    assert not np.allclose(np.asarray(out),
                           np.asarray(reference_attention(q, k, v)),
                           atol=1e-3)  # dropout actually bit
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    # determinism given the seed
    out2 = jax.jit(lambda q, k, v: drop(q, k, v, seed))(q, k, v)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_ulysses_dropout_dense_inner_off_tpu(devices8):
    """Off-TPU without forced kernels the ulysses flavor now carries a DENSE
    dropout inner (PR 1 satellite, ADVICE r5) — the two sp flavors behave
    consistently anywhere ring's _dense_block_drop runs, including the
    pipeline body at tp=1. The dense inner makes the same counter-hash mask
    decisions at the same local coordinates as the kernel inner, so its
    output must match the forced-kernel path."""
    cfg = sp_cfg(sp_size=2, fsdp_size=1, att_dropout=0.25)
    mesh = build_mesh(cfg, devices=jax.devices()[:2])
    impl = make_attention_impl(cfg, mesh)  # no force: dense dropout inner
    drop = getattr(impl, "vitax_dropout", None)
    assert drop is not None
    assert getattr(impl.vitax_pp_impl, "vitax_dropout", None) is not None

    b, n, h, dh = 2, cfg.num_patches, cfg.num_heads, 8
    q, k, v = (jax.random.normal(kk, (b, n, h, dh), jnp.float32)
               for kk in jax.random.split(jax.random.key(5), 3))
    seed = jnp.uint32(29)
    out = jax.jit(lambda q, k, v: drop(q, k, v, seed))(q, k, v)

    impl_k = make_attention_impl(cfg, mesh, force_tpu_kernels=True)
    want = jax.jit(
        lambda q, k, v: impl_k.vitax_dropout(q, k, v, seed))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
