"""Forward parity against a PyTorch re-implementation of the reference model.

The reference composes timm 0.4.12 PatchEmbed/Block into its ViT
(/root/reference/run_vit_training.py:99-162); vitax claims architecture
parity via a closed-form param count and init statistics (tests/test_model.py).
This test goes further: it re-implements the reference's MODEL MATH in plain
PyTorch (torch is available CPU-only; timm itself is not installed), loads
the IDENTICAL weights from the vitax/Flax parameter tree, and requires the
logits to agree — which pins patchify layout, pre-norm order, qkv packing,
softmax axis, LN epsilons (1e-5 blocks / 1e-6 final), exact-GELU, mean-pool,
and the head, not just parameter counts. (Original re-implementation from
the architecture facts in vitax/models/vit.py's docstring — not a copy of
the reference's code.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from vitax.config import Config
from vitax.models import build_model


def torch_forward(p, images, *, patch_size, num_heads, num_blocks):
    """Reference-math forward in torch.float64 on the Flax param tree `p`
    (unstacked, scan_blocks=False layout: blocks_0, blocks_1, ...)."""
    tp = jax.tree.map(
        lambda a: torch.from_numpy(np.asarray(a, np.float64)), p)
    out = torch_forward_t(tp, np.asarray(images, np.float64),
                          patch_size=patch_size, num_heads=num_heads,
                          num_blocks=num_blocks)
    return out.detach().numpy()


def test_forward_matches_torch_reference_math(devices8):
    cfg = Config(image_size=32, patch_size=8, embed_dim=32, num_heads=2,
                 num_blocks=3, num_classes=10, batch_size=4, dtype="float32",
                 scan_blocks=False, grad_ckpt=False).validate()
    model = build_model(cfg)
    images = np.asarray(jax.random.normal(
        jax.random.key(1), (4, 32, 32, 3), jnp.float32))
    params = model.init(jax.random.key(0), jnp.asarray(images)[:1], True)

    got = np.asarray(model.apply(params, jnp.asarray(images), True))
    want = torch_forward(params["params"], images,
                         patch_size=cfg.patch_size, num_heads=cfg.num_heads,
                         num_blocks=cfg.num_blocks)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_train_step_matches_torch_reference_math(devices8):
    """FULL train-step parity: the same init, batch, and schedule stepped by
    (a) vitax's compiled step (CE mean -> global-norm clip 1.0 -> AdamW
    (0.9, 0.999, 1e-8, wd on ALL params) -> warmup-cosine lr) and (b) the
    reference's exact torch pipeline (loss.backward, clip_grad_norm_,
    torch.optim.AdamW, per-step lr from the same schedule). Losses and the
    full parameter tree must track across steps — this pins the clip-before-
    update order, AdamW bias correction/eps, decoupled weight-decay
    semantics, and the schedule application point, against torch itself."""
    from vitax.parallel.mesh import batch_pspec, build_mesh
    from vitax.train.schedule import warmup_cosine_schedule
    from vitax.train.state import build_optimizer, make_train_state
    from vitax.train.step import make_train_step
    from jax.sharding import NamedSharding

    cfg = Config(image_size=16, patch_size=8, embed_dim=32, num_heads=2,
                 num_blocks=2, num_classes=8, batch_size=8, dtype="float32",
                 scan_blocks=False, grad_ckpt=False, warmup_steps=2,
                 lr=1e-3, weight_decay=0.1, clip_grad_norm=1.0,
                 fsdp_size=2, dp_size=4).validate()
    n_steps, max_iter = 4, 10
    mesh = build_mesh(cfg)
    model = build_model(cfg)
    tx, _ = build_optimizer(cfg, max_iteration=max_iter)
    state, sspecs, _ = make_train_state(cfg, model, tx, mesh,
                                        jax.random.key(0))
    step_fn = make_train_step(cfg, model, tx, mesh, sspecs)
    params0 = jax.device_get(state.params)["params"]

    rng = np.random.default_rng(0)
    images = rng.normal(size=(cfg.batch_size, 16, 16, 3)).astype(np.float32)
    labels = rng.integers(0, cfg.num_classes,
                          size=(cfg.batch_size,)).astype(np.int32)
    sh = NamedSharding(mesh, batch_pspec())
    batch = {"image": jax.device_put(jnp.asarray(images), sh),
             "label": jax.device_put(jnp.asarray(labels), sh)}

    losses_vx = []
    key = jax.random.key(1)
    for _ in range(n_steps):
        state, metrics = step_fn(state, batch, key)
        losses_vx.append(float(jax.device_get(metrics["loss"])))
    final_vx = jax.device_get(state.params)["params"]

    # --- torch side: identical math, float64 ---
    flat0, treedef = jax.tree_util.tree_flatten_with_path(params0)
    tparams = [torch.from_numpy(np.asarray(v, np.float64)).clone()
               .requires_grad_(True) for _, v in flat0]
    sched = warmup_cosine_schedule(cfg.lr, cfg.warmup_steps, max_iter)
    opt = torch.optim.AdamW(tparams, lr=cfg.lr, betas=(0.9, 0.999),
                            eps=1e-8, weight_decay=cfg.weight_decay)
    timages = images.astype(np.float64)
    tlabels = torch.from_numpy(labels.astype(np.int64))

    def torch_tree():
        leaves = [(path, tp) for (path, _), tp in zip(flat0, tparams)]
        out = {}
        for path, tp in leaves:
            node = out
            keys = [str(getattr(k, "key", k)) for k in path]
            for k in keys[:-1]:
                node = node.setdefault(k, {})
            node[keys[-1]] = tp
        return out

    losses_t = []
    for step in range(n_steps):
        opt.zero_grad()
        # a torch-tensor tree view over the SAME leaf objects the optimizer
        # owns, so torch_forward_t's graph tracks their grads
        p = torch_tree()
        logits = torch_forward_t(p, timages, patch_size=cfg.patch_size,
                                 num_heads=cfg.num_heads,
                                 num_blocks=cfg.num_blocks)
        loss = torch.nn.functional.cross_entropy(logits, tlabels)
        losses_t.append(float(loss.detach()))
        loss.backward()
        torch.nn.utils.clip_grad_norm_(tparams, cfg.clip_grad_norm)
        # per-step lr from the SAME schedule (reference: LambdaLR over AdamW)
        lr_t = float(sched(step))
        for g in opt.param_groups:
            g["lr"] = lr_t
        opt.step()

    np.testing.assert_allclose(losses_vx, losses_t, rtol=2e-4, atol=2e-5)
    flat_vx = jax.tree_util.tree_leaves_with_path(final_vx)
    for (path, v), tp in zip(flat_vx, tparams):
        np.testing.assert_allclose(
            np.asarray(v, np.float64), tp.detach().numpy(),
            rtol=2e-3, atol=2e-5,
            err_msg=f"param drift at {jax.tree_util.keystr(path)}")


def torch_forward_t(p, images, *, patch_size, num_heads, num_blocks):
    """The reference-math forward on a tree of torch tensors (autograd-
    tracked when they require grad): conv patchify (flax (kh, kw, cin,
    cout) kernel -> torch layout), pos embed, pre-norm timm Blocks (LN eps
    1e-5, fused qkv, exact GELU), final LN eps 1e-6, mean-pool, head."""
    x = torch.from_numpy(images)

    w = p["patch_embed"]["proj"]["kernel"].permute(3, 2, 0, 1)
    b = p["patch_embed"]["proj"]["bias"]
    x = torch.nn.functional.conv2d(
        x.permute(0, 3, 1, 2), w, b, stride=patch_size)
    bsz, d, gh, gw = x.shape
    x = x.flatten(2).transpose(1, 2)
    x = x + p["pos_embed"][0]

    def ln(x, params, eps):
        return torch.nn.functional.layer_norm(
            x, (x.shape[-1],), params["scale"], params["bias"], eps)

    def dense(x, params):
        return x @ params["kernel"] + params["bias"]

    heads, dh = num_heads, d // num_heads
    for i in range(num_blocks):
        blk = p[f"blocks_{i}"]
        y = ln(x, blk["norm1"], 1e-5)
        qkv = dense(y, blk["attn"]["qkv"])
        qkv = qkv.reshape(bsz, -1, 3, heads, dh)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        s = torch.einsum("bqhd,bkhd->bhqk", q, k) * dh ** -0.5
        a = torch.softmax(s, dim=-1)
        y = torch.einsum("bhqk,bkhd->bqhd", a, v).reshape(bsz, -1, d)
        x = x + dense(y, blk["attn"]["proj"])
        y = ln(x, blk["norm2"], 1e-5)
        y = torch.nn.functional.gelu(dense(y, blk["mlp"]["fc1"]))
        x = x + dense(y, blk["mlp"]["fc2"])

    x = ln(x, p["norm"], 1e-6)
    x = x.mean(dim=1)
    return dense(x, p["head"])
