"""Forward parity against a PyTorch re-implementation of the reference model.

The reference composes timm 0.4.12 PatchEmbed/Block into its ViT
(/root/reference/run_vit_training.py:99-162); vitax claims architecture
parity via a closed-form param count and init statistics (tests/test_model.py).
This test goes further: it re-implements the reference's MODEL MATH in plain
PyTorch (torch is available CPU-only; timm itself is not installed), loads
the IDENTICAL weights from the vitax/Flax parameter tree, and requires the
logits to agree — which pins patchify layout, pre-norm order, qkv packing,
softmax axis, LN epsilons (1e-5 blocks / 1e-6 final), exact-GELU, mean-pool,
and the head, not just parameter counts. (Original re-implementation from
the architecture facts in vitax/models/vit.py's docstring — not a copy of
the reference's code.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from vitax.config import Config
from vitax.models import build_model


def torch_forward(p, images, *, patch_size, num_heads, num_blocks):
    """Reference-math forward in torch.float64 on the Flax param tree `p`
    (unstacked, scan_blocks=False layout: blocks_0, blocks_1, ...)."""
    t = lambda a: torch.from_numpy(np.asarray(a, np.float64))  # noqa: E731
    x = torch.from_numpy(np.asarray(images, np.float64))       # (B, H, W, 3)

    # conv patchify: flax kernel (kh, kw, cin, cout) -> torch (cout, cin, kh, kw)
    w = t(p["patch_embed"]["proj"]["kernel"]).permute(3, 2, 0, 1)
    b = t(p["patch_embed"]["proj"]["bias"])
    x = torch.nn.functional.conv2d(
        x.permute(0, 3, 1, 2), w, b, stride=patch_size)        # (B, D, h, w)
    bsz, d, gh, gw = x.shape
    x = x.flatten(2).transpose(1, 2)                           # (B, N, D)
    x = x + t(p["pos_embed"])[0]

    def ln(x, params, eps):
        return torch.nn.functional.layer_norm(
            x, (x.shape[-1],), t(params["scale"]), t(params["bias"]), eps)

    def dense(x, params):
        return x @ t(params["kernel"]) + t(params["bias"])

    heads, dh = num_heads, d // num_heads
    for i in range(num_blocks):
        blk = p[f"blocks_{i}"]
        # pre-norm attention (timm Block, LN eps 1e-5)
        y = ln(x, blk["norm1"], 1e-5)
        qkv = dense(y, blk["attn"]["qkv"])                     # (B, N, 3D)
        qkv = qkv.reshape(bsz, -1, 3, heads, dh)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]     # (B, N, H, Dh)
        s = torch.einsum("bqhd,bkhd->bhqk", q, k) * dh ** -0.5
        a = torch.softmax(s, dim=-1)
        y = torch.einsum("bhqk,bkhd->bqhd", a, v).reshape(bsz, -1, d)
        x = x + dense(y, blk["attn"]["proj"])
        # pre-norm MLP (exact GELU, timm Mlp)
        y = ln(x, blk["norm2"], 1e-5)
        y = torch.nn.functional.gelu(dense(y, blk["mlp"]["fc1"]))
        x = x + dense(y, blk["mlp"]["fc2"])

    x = ln(x, p["norm"], 1e-6)       # final LN eps 1e-6
    x = x.mean(dim=1)                # mean-pool (no CLS), arXiv:2106.04560
    return dense(x, p["head"]).numpy()


def test_forward_matches_torch_reference_math(devices8):
    cfg = Config(image_size=32, patch_size=8, embed_dim=32, num_heads=2,
                 num_blocks=3, num_classes=10, batch_size=4, dtype="float32",
                 scan_blocks=False, grad_ckpt=False).validate()
    model = build_model(cfg)
    images = np.asarray(jax.random.normal(
        jax.random.key(1), (4, 32, 32, 3), jnp.float32))
    params = model.init(jax.random.key(0), jnp.asarray(images)[:1], True)

    got = np.asarray(model.apply(params, jnp.asarray(images), True))
    want = torch_forward(params["params"], images,
                         patch_size=cfg.patch_size, num_heads=cfg.num_heads,
                         num_blocks=cfg.num_blocks)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
