"""Pipeline parallelism (GPipe over the "pp" mesh axis) on the 8-virtual-device
CPU mesh: forward logits parity vs the scan path, full train-step trajectory
parity vs FSDP, microbatch schedule edge cases, and the pp param sharding —
mirrors the ring/ulysses suites for the new axis (vitax/parallel/pipeline.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vitax.config import Config
from vitax.models import build_model
from vitax.parallel.mesh import build_mesh
from vitax.parallel.pipeline import make_pp_forward


_FSDP8_REF_LOSSES = None


def fsdp8_reference_losses():
    """The plain-fsdp8 4-step trajectory every pp composition is checked
    against — computed once per suite run (six parametrized cases plus three
    other tests use the byte-identical config)."""
    global _FSDP8_REF_LOSSES
    if _FSDP8_REF_LOSSES is None:
        from tests.test_train_smoke import run_steps
        _, losses = run_steps(
            pp_cfg(pp_size=1, dp_size=1, fsdp_size=-1, grad_ckpt=True),
            n_steps=4)
        _FSDP8_REF_LOSSES = tuple(losses)
    return list(_FSDP8_REF_LOSSES)


def pp_cfg(**kw):
    base = dict(image_size=32, patch_size=8, embed_dim=32, num_heads=4,
                num_blocks=4, num_classes=4, batch_size=16, dtype="float32",
                pp_size=4, fsdp_size=1, dp_size=2, warmup_steps=0)
    base.update(kw)
    return Config(**base).validate()


@pytest.mark.parametrize("microbatches", [0, 2, 8])  # 0 = default (= pp_size)
def test_pp_forward_matches_scan_path(devices8, microbatches):
    """The GPipe forward must compute the exact same function as the
    lax.scan forward on the SAME param tree (embed/head are the same modules
    applied functionally; blocks are the same stacked params applied
    stage-by-stage)."""
    cfg = pp_cfg(pp_microbatches=microbatches)
    mesh = build_mesh(cfg)
    model = build_model(cfg)
    x = jax.random.normal(jax.random.key(1),
                          (cfg.batch_size, cfg.image_size, cfg.image_size, 3),
                          jnp.float32)
    params = jax.jit(lambda k: model.init(k, x[:1], True))(jax.random.key(0))

    ref = model.apply(params, x, True)
    got = jax.jit(make_pp_forward(cfg, model, mesh))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_pp_grads_match_scan_path(devices8):
    """Backward through the pipeline (scan + ppermute + masked bubbles) must
    produce the same gradients as the scan path — bubble ticks contribute
    exactly zero."""
    cfg = pp_cfg(grad_ckpt=True)
    mesh = build_mesh(cfg)
    model = build_model(cfg)
    x = jax.random.normal(jax.random.key(2),
                          (cfg.batch_size, cfg.image_size, cfg.image_size, 3),
                          jnp.float32)
    params = jax.jit(lambda k: model.init(k, x[:1], True))(jax.random.key(0))
    pp_fwd = make_pp_forward(cfg, model, mesh)

    def loss(fwd):
        return lambda p: jnp.sum(fwd(p, x) ** 2)

    g_ref = jax.grad(loss(lambda p, x_: model.apply(p, x_, True)))(params)
    g_pp = jax.grad(loss(pp_fwd))(params)
    for (ka, a), (_, b) in zip(  # identical treedefs -> identical order
            jax.tree_util.tree_flatten_with_path(g_ref)[0],
            jax.tree_util.tree_flatten_with_path(g_pp)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(ka)}")


def test_pp_train_step_matches_fsdp(devices8):
    """Full train step on the dp2 x pp4 mesh must match the FSDP-only
    trajectory — same init, same data, same losses (the dryrun's strongest
    multi-chip correctness statement, extended to the pp axis)."""
    from tests.test_train_smoke import run_steps

    cfg_pp = pp_cfg(grad_ckpt=True)
    _, losses_pp = run_steps(cfg_pp, n_steps=4)
    losses_base = fsdp8_reference_losses()
    assert all(np.isfinite(losses_pp))
    np.testing.assert_allclose(losses_pp, losses_base, rtol=2e-4)


def test_pp_forward_with_pallas_kernels(devices8):
    """The model's attention impl is shard_map-wrapped on multi-device
    meshes; the pipeline body runs inside its OWN shard_map, so
    make_pp_forward must unwrap to the local kernel (vitax_local_impl) —
    nested shard_map over the same mesh is rejected by JAX. Interpret-mode
    Pallas on the CPU mesh, numerics vs the scan path."""
    from vitax.ops.attention import make_attention_impl

    cfg = pp_cfg(embed_dim=64, dtype="float32")
    mesh = build_mesh(cfg)
    impl = make_attention_impl(cfg, mesh, force_tpu_kernels=True)
    assert impl is not None and "shard_map" in impl.vitax_name
    model = build_model(cfg, attention_impl=impl)
    x = jax.random.normal(jax.random.key(3),
                          (cfg.batch_size, cfg.image_size, cfg.image_size, 3),
                          jnp.float32)
    # init/apply with the full batch: the wrapped impl shard_maps over
    # (dp, fsdp), so the batch must divide the mesh's data axes
    params = jax.jit(lambda k: model.init(k, x, True))(jax.random.key(0))
    ref = jax.jit(lambda p, x_: model.apply(p, x_, True))(params, x)
    got = jax.jit(make_pp_forward(cfg, model, mesh))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_pp_param_sharding(devices8):
    """Stacked block params carry P("pp", ...) on the layer axis; everything
    else stays unsharded over pp (embed/head replicated on every stage)."""
    from vitax.parallel.sharding import param_specs

    cfg = pp_cfg()
    mesh = build_mesh(cfg)
    model = build_model(cfg)
    abstract = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, 32, 32, 3), jnp.float32), True),
        jax.random.key(0))
    specs = param_specs(abstract, cfg, mesh)
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    saw_pp = False
    for path, spec in flat:
        names = [str(getattr(p, "key", p)) for p in path]
        if "blocks" in names:
            assert spec[0] == "pp", (names, spec)
            saw_pp = True
        else:
            assert "pp" not in tuple(spec), (names, spec)
    assert saw_pp


def test_pp_fsdp_train_step_matches_fsdp(devices8):
    """GPipe composed with ZeRO-3: block params carry P("pp", ..., "fsdp")
    and the pipeline body all-gathers each block's shards just-in-time
    (reduce-scattering the weight cotangents on the way back). The dp2 x
    fsdp2 x pp2 trajectory must match plain fsdp8."""
    from vitax.parallel.sharding import param_specs
    from tests.test_train_smoke import run_steps

    cfg = pp_cfg(pp_size=2, dp_size=2, fsdp_size=2, grad_ckpt=True)
    mesh = build_mesh(cfg)
    model = build_model(cfg)
    abstract = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, 32, 32, 3), jnp.float32), True),
        jax.random.key(0))
    specs = param_specs(abstract, cfg, mesh)
    qkv = specs["params"]["blocks"]["attn"]["qkv"]["kernel"]
    assert qkv[0] == "pp" and "fsdp" in tuple(qkv), qkv  # both axes placed

    _, losses_ppf = run_steps(cfg, n_steps=4)
    assert all(np.isfinite(losses_ppf))
    np.testing.assert_allclose(losses_ppf, fsdp8_reference_losses(),
                               rtol=2e-4)


def test_pp_config_validation():
    with pytest.raises(AssertionError):  # blocks not divisible by stages
        pp_cfg(num_blocks=3)
    with pytest.raises(AssertionError):  # needs the stacked tree
        pp_cfg(scan_blocks=False)
    # dropout under pp is supported in v2 (keys ride the pipeline body)
    pp_cfg(att_dropout=0.1)


def test_pp_moe_matches_non_pp(devices8):
    """MoE blocks under GPipe (experts replicated): the pipeline's aux loss
    combines the sown frac/prob ingredients across microbatches BEFORE the
    nonlinear Switch product (vitax/parallel/pipeline.py), so the pp
    trajectory must equal the non-pp one exactly — pp x moe was a v1
    exclusion (VERDICT r3 item 5)."""
    from tests.test_train_smoke import run_steps

    moe_kw = dict(moe_experts=4, ep_size=1)
    _, losses_pp = run_steps(
        pp_cfg(pp_size=2, dp_size=4, grad_ckpt=True, **moe_kw), n_steps=4)
    _, losses_ref = run_steps(
        pp_cfg(pp_size=1, dp_size=1, fsdp_size=-1, grad_ckpt=True, **moe_kw),
        n_steps=4)
    assert all(np.isfinite(losses_pp))
    np.testing.assert_allclose(losses_pp, losses_ref, rtol=2e-4)


def test_pp_moe_ep_matches_non_pp(devices8):
    """Expert parallelism INSIDE the pipeline body (VERDICT r4 weak #4 /
    next-9): the MoeMlp's manual tiled all-to-all pair over the in-scope
    "ep" axis, with expert params declared at their local (E/ep, ...) shard
    shape, must reproduce the plain fsdp trajectory exactly — same init,
    same data, same losses, aux loss included."""
    from tests.test_train_smoke import run_steps

    moe_kw = dict(moe_experts=4)
    _, losses_pp_ep = run_steps(
        pp_cfg(pp_size=2, dp_size=2, ep_size=2, fsdp_size=1, grad_ckpt=True,
               **moe_kw), n_steps=4)
    _, losses_ref = run_steps(
        pp_cfg(pp_size=1, dp_size=1, fsdp_size=-1, ep_size=1,
               grad_ckpt=True, **moe_kw), n_steps=4)
    assert all(np.isfinite(losses_pp_ep))
    np.testing.assert_allclose(losses_pp_ep, losses_ref, rtol=2e-4)


def test_pp_dropout_rides_kernel(devices8):
    """--att_dropout under pp (no tp/sp) keeps the fused path: the pipeline
    body impl carries the raw dropout kernel (vitax_local_impl
    .vitax_dropout, seeded by the body's per-(tick, layer, shard) keys), the
    trajectory is deterministic given (seed, step), and dropout bites."""
    import __graft_entry__ as g

    kw = dict(pp_size=2, dp_size=4, fsdp_size=1, att_dropout=0.2,
              grad_ckpt=True)
    _, a = g._dryrun_one(8, 2, force_interpret_kernel=True, **kw)
    _, b = g._dryrun_one(8, 2, force_interpret_kernel=True, **kw)
    assert a == b, f"pp kernel-dropout not deterministic: {a} vs {b}"
    _, c = g._dryrun_one(8, 2, force_interpret_kernel=True,
                         **{**kw, "att_dropout": 0.0})
    assert a != c, "att_dropout had no effect on the pp kernel path"

    # and the body impl really is the dropout kernel, not the dense fallback
    from vitax.config import Config
    from vitax.ops.attention import make_attention_impl
    from vitax.parallel.mesh import build_mesh

    cfg = pp_cfg(**kw)
    impl = make_attention_impl(cfg, build_mesh(cfg), force_tpu_kernels=True)
    body = getattr(impl, "vitax_pp_impl", None)
    assert body is not None
    assert getattr(body, "vitax_dropout", None) is not None


@pytest.mark.parametrize("sp_impl", ["ulysses", "ring"])
def test_pp_sp_dropout_rides_kernel(devices8, sp_impl):
    """pp x sp x --att_dropout composes via BOTH sp strategies' dropout
    bodies (ulysses: local a2a + in-kernel mask; ring: local ring with
    global-offset masked block products), seeded by the pipeline's
    per-(tick, layer, shard) keys: deterministic given (seed, step), and
    the masks actually bite."""
    import __graft_entry__ as g

    kw = dict(pp_size=2, sp_size=2, dp_size=2, fsdp_size=1,
              sp_impl=sp_impl, att_dropout=0.2, grad_ckpt=True)
    _, a = g._dryrun_one(8, 2, force_interpret_kernel=True, **kw)
    _, b = g._dryrun_one(8, 2, force_interpret_kernel=True, **kw)
    assert a == b, f"pp x sp {sp_impl} dropout not deterministic: {a} vs {b}"
    _, c = g._dryrun_one(8, 2, force_interpret_kernel=True,
                         **{**kw, "att_dropout": 0.0})
    assert a != c, f"att_dropout had no effect on the pp x sp {sp_impl} path"


def test_pp_dropout_deterministic_and_active(devices8):
    """Dropout under GPipe (v1 exclusion, VERDICT r3 item 5): per-(tick,
    layer, shard) keys folded from the step rng make the masks deterministic
    given (seed, step) — same rng twice gives identical losses, a different
    rng different ones — and dropout must actually bite (loss differs from
    the deterministic path)."""
    from tests.test_train_smoke import build_train_objects, random_batch

    cfg = pp_cfg(pp_size=2, dp_size=4, att_dropout=0.2, mlp_dropout=0.2,
                 pos_dropout=0.1, grad_ckpt=True)
    mesh, state, step_fn, _ = build_train_objects(cfg)
    batch = random_batch(cfg, mesh, seed=0)
    rng_a, rng_b = jax.random.key(1), jax.random.key(2)

    _, m1 = step_fn(state, batch, rng_a)
    l1 = float(jax.device_get(m1["loss"]))
    mesh2, state2, step_fn2, _ = build_train_objects(cfg)
    _, m2 = step_fn2(state2, batch, rng_a)
    l2 = float(jax.device_get(m2["loss"]))
    assert l1 == l2, f"dropout under pp is not deterministic: {l1} vs {l2}"

    mesh3, state3, step_fn3, _ = build_train_objects(cfg)
    _, m3 = step_fn3(state3, batch, rng_b)
    l3 = float(jax.device_get(m3["loss"]))
    assert l1 != l3, "different step rng produced identical dropout masks"

    det_cfg = pp_cfg(pp_size=2, dp_size=4, grad_ckpt=True)
    mesh4, state4, step_fn4, _ = build_train_objects(det_cfg)
    _, m4 = step_fn4(state4, batch, rng_a)
    l4 = float(jax.device_get(m4["loss"]))
    assert abs(l1 - l4) > 1e-7, "dropout under pp had no effect on the loss"


@pytest.mark.parametrize("mesh_kw", [
    dict(pp_size=2, dp_size=4),                 # pure dp x pp
    dict(pp_size=2, dp_size=2, fsdp_size=2),    # ZeRO-3 inside the schedule
])
def test_pp_1f1b_matches_non_pp(devices8, mesh_kw):
    """The 1F1B interleaved schedule (vitax/parallel/pipeline_1f1b.py) is a
    hand-built fwd/bwd engine — per-mb loss at the last stage seeds the
    backward in-tick, grads are assembled from vjp pieces with explicit
    replica psums. Its trajectory must match the plain fsdp path exactly,
    composing with ZeRO-3 gathers."""
    from tests.test_train_smoke import run_steps

    _, losses = run_steps(
        pp_cfg(pp_schedule="1f1b", grad_ckpt=True, **mesh_kw), n_steps=4)
    assert all(np.isfinite(losses))
    np.testing.assert_allclose(losses, fsdp8_reference_losses(), rtol=2e-4)


def test_pp_1f1b_validation():
    with pytest.raises(AssertionError):  # dense/deterministic only (v1)
        pp_cfg(pp_schedule="1f1b", mlp_dropout=0.1)
    with pytest.raises(AssertionError):
        pp_cfg(pp_schedule="1f1b", moe_experts=4, ep_size=1)
    pp_cfg(pp_schedule="1f1b")  # dense config accepted
    with pytest.raises(AssertionError):  # tp/sp ride gpipe only
        pp_cfg(pp_schedule="1f1b", tp_size=2, dp_size=1)
    with pytest.raises(AssertionError):  # MoE under pp is dp/fsdp-only
        pp_cfg(moe_experts=4, ep_size=1, tp_size=2, dp_size=1)


@pytest.mark.xfail(
    strict=False, reason="pp-under-tp standing debt (ROADMAP): XLA rejects\n"
    "PartitionId under SPMD partitioning in jax 0.4.x, so the tp GSPMD-auto\n"
    "axis cannot coexist with the pipeline shard_map yet")
def test_pp_tp_forward_and_grads_match_scan_path(devices8):
    """pp x tp (the round-3 v1 exclusion): the pipeline shard_map manualizes
    only (dp, fsdp, pp, ep) and leaves "tp" as a GSPMD-auto axis, so the
    block matmuls partition over tp from the weights' own Megatron
    placements — forward AND backward must equal the scan path exactly."""
    cfg = pp_cfg(pp_size=2, dp_size=2, tp_size=2, grad_ckpt=True)
    mesh = build_mesh(cfg)
    model = build_model(cfg)
    x = jax.random.normal(jax.random.key(4),
                          (cfg.batch_size, cfg.image_size, cfg.image_size, 3),
                          jnp.float32)
    params = jax.jit(lambda k: model.init(k, x[:1], True))(jax.random.key(0))
    from vitax.parallel.sharding import param_specs
    specs = param_specs(jax.eval_shape(lambda: params), cfg, mesh)
    qkv = specs["params"]["blocks"]["attn"]["qkv"]["kernel"]
    assert "tp" in tuple(qkv), qkv  # Megatron placement present
    pp_fwd = make_pp_forward(cfg, model, mesh,
                             block_specs=specs["params"]["blocks"])

    ref = model.apply(params, x, True)
    got = jax.jit(pp_fwd)(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss(fwd):
        return lambda p: jnp.sum(fwd(p, x) ** 2)

    g_ref = jax.grad(loss(lambda p, x_: model.apply(p, x_, True)))(params)
    g_pp = jax.grad(loss(pp_fwd))(params)
    for (ka, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g_ref)[0],
            jax.tree_util.tree_flatten_with_path(g_pp)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(ka)}")


# the tp_size entries carry the pp-under-tp xfail (ROADMAP standing debt):
# XLA rejects PartitionId under SPMD partitioning in jax 0.4.x, so the tp
# GSPMD-auto axis cannot coexist with the pipeline shard_map yet
_PP_TP_XFAIL = pytest.mark.xfail(
    strict=False, reason="pp-under-tp: PartitionId unimplemented in jax "
    "0.4.x SPMD partitioning (ROADMAP standing debt)")


@pytest.mark.parametrize("mesh_kw", [
    pytest.param(dict(pp_size=2, dp_size=2, tp_size=2),   # pp x tp
                 marks=_PP_TP_XFAIL),
    pytest.param(dict(pp_size=2, dp_size=1, tp_size=2,    # + ZeRO-3 gathers
                      fsdp_size=2), marks=_PP_TP_XFAIL),
    dict(pp_size=2, dp_size=2, sp_size=2),                # pp x sp (ring)
    dict(pp_size=2, dp_size=2, sp_size=2, sp_impl="ulysses"),
    pytest.param(dict(pp_size=2, tp_size=2, sp_size=2,    # pp x tp x sp
                      dp_size=1), marks=_PP_TP_XFAIL),
    # ulysses' with_tp branch: dense inner under the GSPMD-auto head axis
    pytest.param(dict(pp_size=2, tp_size=2, sp_size=2, dp_size=1,
                      sp_impl="ulysses"), marks=_PP_TP_XFAIL),
])
def test_pp_tp_sp_train_step_matches_fsdp(devices8, mesh_kw):
    """Full train step on pp x tp / pp x sp meshes must match the plain
    fsdp8 trajectory — same init, same data, same losses. sp routes through
    the ring/ulysses local bodies (vitax_pp_impl) running directly inside
    the pipeline shard_map — deliberately NOT nested maps (the jax-0.9
    Shardy constant-hoisting bug; see vitax/parallel/pipeline.py)."""
    from tests.test_train_smoke import run_steps

    _, losses = run_steps(pp_cfg(grad_ckpt=True, **mesh_kw), n_steps=4)
    assert all(np.isfinite(losses))
    np.testing.assert_allclose(losses, fsdp8_reference_losses(), rtol=2e-4)


@pytest.mark.xfail(
    strict=False, reason="pp-under-tp standing debt (ROADMAP): XLA rejects\n"
    "PartitionId under SPMD partitioning in jax 0.4.x, so the tp GSPMD-auto\n"
    "axis cannot coexist with the pipeline shard_map yet")
def test_pp_tp_forward_with_pallas_kernels(devices8):
    """Under pp x tp the Pallas kernel cannot ride into the pipeline body
    (tp is a GSPMD-auto axis there and a custom kernel cannot be
    auto-partitioned; a nested tp shard_map hits the jax-0.9 Shardy
    constant-hoisting bug) — vitax_pp_impl must be None so the body takes
    the dense einsum path, and its numerics must still match the
    kernel-based scan path."""
    from vitax.ops.attention import make_attention_impl

    cfg = pp_cfg(pp_size=2, dp_size=2, tp_size=2, embed_dim=64,
                 dtype="float32")
    mesh = build_mesh(cfg)
    impl = make_attention_impl(cfg, mesh, force_tpu_kernels=True)
    assert impl is not None and "shard_map" in impl.vitax_name
    assert impl.vitax_pp_impl is None  # dense fallback inside the pp body
    model = build_model(cfg, attention_impl=impl)
    x = jax.random.normal(jax.random.key(5),
                          (cfg.batch_size, cfg.image_size, cfg.image_size, 3),
                          jnp.float32)
    params = jax.jit(lambda k: model.init(k, x, True))(jax.random.key(0))
    ref = jax.jit(lambda p, x_: model.apply(p, x_, True))(params, x)
    got = jax.jit(make_pp_forward(cfg, model, mesh))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_pp_sp_forward_with_pallas_kernels(devices8):
    """Under pp x sp (tp = 1) the ring attention LOCAL body — including its
    Pallas block products in interpret mode — runs directly inside the
    pipeline shard_map (sp is a manual axis there). Numerics vs the scan
    path's ring attention."""
    from vitax.ops.attention import make_attention_impl

    cfg = pp_cfg(pp_size=2, dp_size=2, sp_size=2, embed_dim=64,
                 dtype="float32")
    mesh = build_mesh(cfg)
    impl = make_attention_impl(cfg, mesh, force_tpu_kernels=True)
    assert impl is not None and "ring" in impl.vitax_name
    assert impl.vitax_pp_impl is not None
    model = build_model(cfg, attention_impl=impl)
    x = jax.random.normal(jax.random.key(6),
                          (cfg.batch_size, cfg.image_size, cfg.image_size, 3),
                          jnp.float32)
    params = jax.jit(lambda k: model.init(k, x, True))(jax.random.key(0))
    ref = jax.jit(lambda p, x_: model.apply(p, x_, True))(params, x)
    got = jax.jit(make_pp_forward(cfg, model, mesh))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
