"""Quantized serving end-to-end: consolidate --dtype int8 -> from_npz ->
bucketed predict, with the CI accuracy gate.

Strategy: one module-scoped stack trains the tiny model for two real steps,
exports the epoch checkpoint both full-precision and int8-quantized, and
warms an engine over each. The tests then pin the whole contract ISSUE 14
ships: manifest schema and skip-set discipline, quantization numerics,
bitwise-deterministic quantized predictions across loads and bucket sizes,
the zero-recompile pin on the int8 engine, the >= 45% device-resident byte
cut, and the quantized-vs-f32 accuracy gate (<= 1.0 top-1 points) with its
kind:"quant_gate" telemetry event surfaced by tools/metrics_report.py.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from vitax.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the gate evaluates n=256 deterministic samples: one flipped prediction
# moves top-1 by 0.39 points, so the 1.0-point threshold tolerates two
# flips before failing — tight enough to catch a broken dequant (which
# scrambles most predictions), loose enough for round-off flips
GATE_N = 256
GATE_MAX_TOP1_DELTA_PTS = 1.0


def tiny_cfg(**kw):
    base = dict(
        image_size=16, patch_size=8, embed_dim=32, num_heads=2, num_blocks=2,
        num_classes=4, batch_size=16, dtype="float32", lr=1e-3, warmup_steps=2,
        serve_max_batch=4, serve_topk=3, max_batch_wait_ms=10.0, seed=0,
    )
    base.update(kw)
    return Config(**base).validate()


def gate_batch(cfg, n=GATE_N, seed=11):
    rng = np.random.default_rng(seed)
    images = rng.integers(
        0, 256, size=(n, cfg.image_size, cfg.image_size, 3), dtype=np.uint8)
    labels = rng.integers(0, cfg.num_classes, size=(n,))
    return images, labels


@pytest.fixture(scope="module")
def quant_stack(devices8, tmp_path_factory):
    """(cfg_f32, engine_f32, cfg_int8, engine_int8, f32_path, int8_path)."""
    from vitax.checkpoint.consolidate import consolidate
    from vitax.serve import InferenceEngine
    from vitax.train.loop import train

    root = tmp_path_factory.mktemp("quant")
    ckpt_dir = str(root / "ckpt")
    cfg = tiny_cfg(
        fake_data=True, num_epochs=1, steps_per_epoch=2, log_step_interval=1,
        ckpt_dir=ckpt_dir, ckpt_epoch_interval=1, num_workers=2,
        eval_max_batches=1,
    )
    train(cfg)  # 2 real optimizer steps; writes epoch_1
    f32_path = str(root / "f32.npz")
    int8_path = str(root / "int8.npz")
    consolidate(ckpt_dir, 1, f32_path)
    consolidate(ckpt_dir, 1, int8_path, dtype="int8")
    engine_f = InferenceEngine.from_npz(cfg, f32_path)
    engine_f.warmup()
    cfg_q = tiny_cfg(serve_quant_dtype="int8")
    engine_q = InferenceEngine.from_npz(cfg_q, int8_path)
    engine_q.warmup()
    return cfg, engine_f, cfg_q, engine_q, f32_path, int8_path


# --- manifest schema and skip discipline ------------------------------------


def test_manifest_schema_and_scales(quant_stack):
    from vitax.checkpoint.consolidate import (
        QUANT_MANIFEST_KEY, QUANT_SCHEMA_VERSION, load_npz_raw)
    *_, int8_path = quant_stack
    flat, scales, manifest = load_npz_raw(int8_path)
    assert manifest, "int8 export carries no __quant__ manifest"
    with np.load(int8_path) as data:
        doc = json.loads(str(data[QUANT_MANIFEST_KEY]))
    assert doc["schema"] == QUANT_SCHEMA_VERSION
    # dtype-keyed manifest: an int8 export names only the int8 slot (the
    # fp8 arm is pinned separately in the fp8_stack tests)
    assert set(doc["dtypes"]) == {"int8"}
    assert doc["dtypes"]["int8"] == sorted(doc["dtypes"]["int8"])
    for key, dtype in manifest.items():
        assert dtype == "int8"
        assert flat[key].dtype == np.int8
        # keepdims scales: broadcastable against the weight, one scale per
        # output channel (last axis preserved)
        s = scales[key]
        assert s.dtype == np.float32
        assert s.ndim == flat[key].ndim
        assert s.shape[-1] == flat[key].shape[-1]
        np.broadcast_shapes(s.shape, flat[key].shape)
    # the matmul weights are quantized; LN/bias leaves are not
    assert any(k.endswith("/kernel") for k in manifest)
    assert all("norm" not in k and not k.endswith("bias") for k in manifest)


def test_skip_set_tracks_keep_f32_params():
    """QUANT_SKIP_NAMES is KEEP_F32_PARAMS minus the head: the head kernel
    is a full matmul weight that dequantizes to f32 at use, so int8 storage
    does not change where its compute happens. A drift between the two
    lists is a policy change someone must make deliberately."""
    from vitax.checkpoint.consolidate import QUANT_SKIP_NAMES
    from vitax.parallel.sharding import KEEP_F32_PARAMS
    assert set(QUANT_SKIP_NAMES) == set(KEEP_F32_PARAMS) - {"head"}


# --- quantization numerics ---------------------------------------------------


def test_quantize_leaf_numerics():
    from vitax.checkpoint.consolidate import quantize_leaf
    rng = np.random.default_rng(0)
    w = rng.normal(size=(16, 8)).astype(np.float32)
    q, scale = quantize_leaf("a/kernel", w)
    assert q.dtype == np.int8 and scale.shape == (1, 8)
    assert np.abs(q).max() <= 127
    # symmetric round-to-nearest: error bounded by half a quant step/channel
    err = np.abs(q.astype(np.float32) * scale - w)
    assert np.all(err <= scale / 2 + 1e-7)
    # all-zero channels stay representable (scale 1.0, q 0)
    z = np.zeros((4, 2), np.float32)
    qz, sz = quantize_leaf("a/kernel", z)
    assert np.all(qz == 0) and np.all(sz == 1.0)


def test_fused_dequant_matmul_matches_f32():
    from vitax.checkpoint.consolidate import quantize_leaf
    from vitax.serve.quant import dequantize_leaf, fused_dequant_matmul
    rng = np.random.default_rng(1)
    x = rng.normal(size=(4, 16)).astype(np.float32)
    w = rng.normal(size=(16, 8)).astype(np.float32)
    q, scale = quantize_leaf("a/kernel", w)
    out = np.asarray(fused_dequant_matmul(x, q, scale))
    # quantization error, not matmul error: bounded by the per-channel step
    bound = (np.abs(x).sum(axis=1, keepdims=True) * (scale / 2)) + 1e-5
    assert np.all(np.abs(out - x @ w) <= bound)
    w_back = np.asarray(dequantize_leaf(q, scale))
    assert w_back.dtype == np.float32
    assert np.all(np.abs(w_back - w) <= scale / 2 + 1e-7)


# --- engine contract ---------------------------------------------------------


def test_engine_serve_contract_and_bytes(quant_stack):
    _, engine_f, _, engine_q, _, _ = quant_stack
    # identical AOT contract: same buckets, compile_count pinned at warmup
    assert engine_q.buckets == engine_f.buckets
    assert engine_q.compile_count == len(engine_q.buckets)
    assert engine_q.ready
    # weights stay int8 on device, and the footprint drops accordingly
    assert engine_q.quantized and not engine_f.quantized
    assert engine_q.weights_dtype == "int8"
    assert engine_f.weights_dtype == "float32"
    assert engine_q.param_bytes() <= 0.55 * engine_f.param_bytes(), (
        engine_q.param_bytes(), engine_f.param_bytes())
    int8_leaves = [v for v in jax.tree.leaves(engine_q.params)
                   if np.dtype(v.dtype) == np.int8]
    assert int8_leaves and len(int8_leaves) == len(engine_q.scales)


def test_quant_predictions_deterministic_across_loads(quant_stack):
    from vitax.serve import InferenceEngine
    cfg, _, cfg_q, engine_q, _, int8_path = quant_stack
    images, _ = gate_batch(cfg, n=4)
    ids_a, probs_a = engine_q.predict(images)
    engine_q2 = InferenceEngine.from_npz(cfg_q, int8_path)
    engine_q2.warmup()
    ids_b, probs_b = engine_q2.predict(images)
    # bitwise: same int8 leaves + same AOT program => identical bits
    np.testing.assert_array_equal(ids_a, ids_b)
    np.testing.assert_array_equal(probs_a, probs_b)


def test_quant_predictions_identical_across_buckets(quant_stack):
    cfg, _, _, engine_q, _, _ = quant_stack
    img = np.full((1, cfg.image_size, cfg.image_size, 3), 9, np.uint8)
    one = engine_q.predict(img)                      # bucket 1
    four = engine_q.predict(np.repeat(img, 4, axis=0))  # bucket 4
    np.testing.assert_array_equal(one[0][0], four[0][3])
    np.testing.assert_allclose(one[1][0], four[1][3], rtol=1e-5)


def test_quant_zero_recompiles_under_mixed_traffic(quant_stack):
    cfg, _, _, engine_q, _, _ = quant_stack
    before = engine_q.compile_count
    for n in (3, 1, 4, 2, 1, 3):
        engine_q.predict(
            np.zeros((n, cfg.image_size, cfg.image_size, 3), np.uint8))
    assert engine_q.compile_count == before == len(engine_q.buckets)
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        engine_q.predict(
            np.zeros((5, cfg.image_size, cfg.image_size, 3), np.uint8))


def test_from_npz_rejects_unquantized_file_when_quant_expected(quant_stack):
    from vitax.serve import InferenceEngine
    _, _, cfg_q, _, f32_path, _ = quant_stack
    with pytest.raises(ValueError, match="no __quant__ manifest"):
        InferenceEngine.from_npz(cfg_q, f32_path)


# --- accuracy gate -----------------------------------------------------------


def test_quant_gate_within_threshold_and_reported(quant_stack, tmp_path):
    from vitax.serve.quant import run_quant_gate
    from vitax.telemetry.record import build_recorder
    cfg, engine_f, _, engine_q, _, _ = quant_stack
    metrics_dir = str(tmp_path / "metrics")
    rec_cfg = tiny_cfg(metrics_dir=metrics_dir)
    recorder = build_recorder(rec_cfg, n_devices=8, device_kind="cpu")
    assert recorder is not None
    images, labels = gate_batch(cfg)
    gate = run_quant_gate(engine_f, engine_q, images, labels,
                          recorder=recorder)
    recorder.close()
    # the hard CI threshold: int8 top-1 within 1.0 points of f32
    assert abs(gate["delta_top1"]) <= GATE_MAX_TOP1_DELTA_PTS, gate
    assert gate["n"] == GATE_N
    assert gate["weights_dtype"] == "int8"
    assert gate["baseline_dtype"] == "float32"
    # the event landed in the run log with the full payload
    jsonl = os.path.join(metrics_dir, "metrics.jsonl")
    events = [json.loads(line) for line in open(jsonl)]
    gates = [e for e in events if e.get("kind") == "quant_gate"]
    assert len(gates) == 1
    for key in ("top1_f32", "top1_quant", "top5_f32", "top5_quant",
                "delta_top1", "delta_top5", "n", "weights_dtype"):
        assert key in gates[0], key
    # and metrics_report --json surfaces it
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "metrics_report.py"),
         jsonl, "--json"],
        capture_output=True, text=True, timeout=60)
    # exit 2 = "no step records", the contract for an event-only log
    assert proc.returncode == 2, proc.stderr[-2000:]
    summary = json.loads(proc.stdout)
    qg = summary["quant_gate_last"]
    assert qg["weights_dtype"] == "int8"
    assert qg["delta_top1"] == gate["delta_top1"]
    assert qg["n"] == GATE_N
    # human mode prints the gate line
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "metrics_report.py"),
         jsonl], capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    assert ("quant gate (int8 vs float32, act_quant off, "
            "fused_dequant False)" in proc.stdout)


# --- /metrics footprint keys -------------------------------------------------


def test_server_metrics_report_weight_footprint(quant_stack):
    """The single-engine /metrics surface: weights_dtype + param_bytes come
    straight from the engine accounting (scraped by serve_bench)."""
    from vitax.serve import start_server, stop_server
    import urllib.request
    _, _, cfg_q, engine_q, _, _ = quant_stack
    httpd, ctx = start_server(cfg_q, engine_q, port=0)
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}/metrics"
        with urllib.request.urlopen(url, timeout=30) as resp:
            snap = json.load(resp)
        assert snap["weights_dtype"] == "int8"
        assert snap["param_bytes"] == engine_q.param_bytes()
        # serve_bench's scraper reads the same keys
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import serve_bench
            weights = serve_bench.scrape_weights(
                f"http://127.0.0.1:{httpd.server_address[1]}")
        finally:
            sys.path.pop(0)
        assert weights == {"param_bytes": engine_q.param_bytes(),
                           "weights_dtype": "int8",
                           "act_quant": "off",
                           "fused_dequant": False}
    finally:
        stop_server(httpd, ctx)


# --- tier 2: fp8 weight arm --------------------------------------------------


@pytest.fixture(scope="module")
def fp8_stack(quant_stack):
    """(cfg_fp8, engine_fp8, fp8_path) — the float8_e4m3 export of the same
    trained checkpoint the int8 stack serves."""
    from vitax.checkpoint.consolidate import consolidate
    from vitax.serve import InferenceEngine
    *_, f32_path, _ = quant_stack
    root = os.path.dirname(f32_path)
    ckpt_dir = os.path.join(root, "ckpt")
    fp8_path = os.path.join(root, "fp8.npz")
    consolidate(ckpt_dir, 1, fp8_path, dtype="float8_e4m3")
    cfg8 = tiny_cfg(serve_quant_dtype="float8_e4m3")
    engine8 = InferenceEngine.from_npz(cfg8, fp8_path)
    engine8.warmup()
    return cfg8, engine8, fp8_path


def test_fp8_manifest_and_leaf_dtypes(fp8_stack):
    import ml_dtypes
    from vitax.checkpoint.consolidate import load_npz_raw
    _, _, fp8_path = fp8_stack
    flat, scales, manifest = load_npz_raw(fp8_path)
    assert manifest and set(manifest.values()) == {"float8_e4m3"}
    assert set(manifest) == set(scales)
    for key in manifest:
        assert flat[key].dtype == ml_dtypes.float8_e4m3
        s = scales[key]
        assert s.dtype == np.float32 and s.ndim == flat[key].ndim
        np.broadcast_shapes(s.shape, flat[key].shape)
        # absmax/240 scaling: no value leaves the e4m3 range (no inf/nan)
        back = flat[key].astype(np.float32)
        assert np.all(np.isfinite(back)) and np.abs(back).max() <= 240.0


def test_fp8_engine_contract_and_bytes(quant_stack, fp8_stack):
    import ml_dtypes
    _, engine_f, _, _, _, _ = quant_stack
    _, engine8, _ = fp8_stack
    assert engine8.buckets == engine_f.buckets
    assert engine8.compile_count == len(engine8.buckets)
    assert engine8.quantized and engine8.weights_dtype == "float8_e4m3"
    # the fp8 acceptance floor: <= 0.35x the f32 device-resident bytes at
    # this geometry (1-byte weights + f32 scales/LN/bias residue)
    assert engine8.param_bytes() <= 0.35 * engine_f.param_bytes(), (
        engine8.param_bytes(), engine_f.param_bytes())
    fp8_leaves = [v for v in jax.tree.leaves(engine8.params)
                  if v.dtype == ml_dtypes.float8_e4m3]
    assert fp8_leaves and len(fp8_leaves) == len(engine8.scales)


def test_fp8_deterministic_and_zero_recompile(fp8_stack):
    from vitax.serve import InferenceEngine
    cfg8, engine8, fp8_path = fp8_stack
    images, _ = gate_batch(cfg8, n=4)
    ids_a, probs_a = engine8.predict(images)
    engine8b = InferenceEngine.from_npz(cfg8, fp8_path)
    engine8b.warmup()
    ids_b, probs_b = engine8b.predict(images)
    # bitwise: same fp8 leaves + same AOT program => identical bits
    np.testing.assert_array_equal(ids_a, ids_b)
    np.testing.assert_array_equal(probs_a, probs_b)
    before = engine8.compile_count
    for n in (3, 1, 4, 2):
        engine8.predict(
            np.zeros((n, cfg8.image_size, cfg8.image_size, 3), np.uint8))
    assert engine8.compile_count == before == len(engine8.buckets)


def test_fp8_gate_within_threshold(quant_stack, fp8_stack):
    from vitax.serve.quant import run_quant_gate
    cfg, engine_f, _, _, _, _ = quant_stack
    _, engine8, _ = fp8_stack
    images, labels = gate_batch(cfg)
    gate = run_quant_gate(engine_f, engine8, images, labels)
    assert abs(gate["delta_top1"]) <= GATE_MAX_TOP1_DELTA_PTS, gate
    assert gate["weights_dtype"] == "float8_e4m3"
    assert gate["act_quant"] == "off" and gate["fused_dequant"] is False


# --- tier 2: dynamic activation quantization --------------------------------


@pytest.fixture(scope="module")
def act_stack(quant_stack):
    """(cfg_act, engine_act) — int8 weights + int8 activations, fused off
    (the default auto resolves off on CPU), so the int8 x int8 dots are
    visible in the lowered MLIR."""
    from vitax.serve import InferenceEngine
    *_, int8_path = quant_stack
    cfg_a = tiny_cfg(serve_quant_dtype="int8", serve_act_quant="int8")
    engine_a = InferenceEngine.from_npz(cfg_a, int8_path)
    engine_a.warmup()
    return cfg_a, engine_a


def test_act_quant_engine_flags_and_contract(quant_stack, act_stack):
    _, engine_f, _, _, _, _ = quant_stack
    cfg_a, engine_a = act_stack
    assert engine_a.act_quant == "int8"
    assert engine_a.fused_dequant is False  # auto resolves off on CPU
    assert engine_a.buckets == engine_f.buckets
    # zero recompiles under mixed traffic, same as the weight-only arm
    before = engine_a.compile_count
    for n in (3, 1, 4, 2):
        engine_a.predict(
            np.zeros((n, cfg_a.image_size, cfg_a.image_size, 3), np.uint8))
    assert engine_a.compile_count == before == len(engine_a.buckets)


def test_act_quant_int8_dots_in_lowered_program(act_stack):
    """The acceptance pin: with act-quant on (fused off), the eligible
    matmuls lower to int8 x int8 dot_generals — both dot operands i8 in the
    stablehlo text for the largest bucket."""
    import re
    cfg_a, engine_a = act_stack
    mlir = engine_a.lower_bucket_mlir(engine_a.buckets[-1])
    i8_dots = [ln for ln in mlir.splitlines()
               if "dot_general" in ln
               and len(re.findall(r"tensor<[\dx]+xi8>", ln)) >= 2]
    # qkv/proj/fc1/fc2 across the scanned blocks: at least one stacked
    # i8 x i8 dot must survive lowering (scan keeps them in the loop body)
    assert i8_dots, "no int8 x int8 dot_general in the lowered serve program"


def test_act_quant_gate_within_threshold(quant_stack, act_stack):
    from vitax.serve.quant import run_quant_gate
    cfg, engine_f, _, _, _, _ = quant_stack
    _, engine_a = act_stack
    images, labels = gate_batch(cfg)
    gate = run_quant_gate(engine_f, engine_a, images, labels)
    assert abs(gate["delta_top1"]) <= GATE_MAX_TOP1_DELTA_PTS, gate
    assert gate["act_quant"] == "int8"


def test_fused_matches_unfused_predictions(quant_stack, act_stack):
    """Forced fused kernel (interpret mode on CPU) vs the unfused act-quant
    program: same int8 math, scales applied post-accumulation — probs agree
    to 1e-2 relative (the acceptance bound) and typically far tighter."""
    from vitax.serve import InferenceEngine
    *_, int8_path = quant_stack
    _, engine_a = act_stack
    cfg_fused = tiny_cfg(serve_quant_dtype="int8", serve_act_quant="int8",
                         fused_dequant="on")
    engine_fused = InferenceEngine.from_npz(cfg_fused, int8_path)
    engine_fused.warmup()
    assert engine_fused.fused_dequant is True
    images, _ = gate_batch(engine_a.cfg, n=4)
    ids_u, probs_u = engine_a.predict(images)
    ids_f, probs_f = engine_fused.predict(images)
    np.testing.assert_allclose(probs_f, probs_u, rtol=1e-2, atol=1e-2)
    np.testing.assert_array_equal(ids_f[:, 0], ids_u[:, 0])


# --- tier 2: config validation ----------------------------------------------


def test_act_quant_config_rejections():
    # act-quant without int8 weights: nothing int8 to multiply against
    with pytest.raises(AssertionError, match="serve_quant_dtype int8"):
        tiny_cfg(serve_act_quant="int8")
    with pytest.raises(AssertionError, match="serve_quant_dtype int8"):
        tiny_cfg(serve_quant_dtype="float8_e4m3", serve_act_quant="int8")
    # unknown values rejected outright
    with pytest.raises(AssertionError, match="serve_act_quant"):
        tiny_cfg(serve_quant_dtype="int8", serve_act_quant="int4")
    with pytest.raises(AssertionError, match="fused_dequant"):
        tiny_cfg(fused_dequant="yes")
    # fused without quantized weights: no dequant to fuse
    with pytest.raises(AssertionError, match="fused_dequant on requires"):
        tiny_cfg(fused_dequant="on")
    # dense-model only
    with pytest.raises(AssertionError, match="dense-model only"):
        tiny_cfg(serve_quant_dtype="int8", serve_act_quant="int8",
                 moe_experts=2)
    with pytest.raises(AssertionError, match="dense-model only"):
        tiny_cfg(serve_quant_dtype="int8", fused_dequant="on",
                 moe_experts=2)
    # the valid tier-2 combos construct cleanly
    tiny_cfg(serve_quant_dtype="int8", serve_act_quant="int8",
             fused_dequant="on").validate()
    tiny_cfg(serve_quant_dtype="float8_e4m3", fused_dequant="on").validate()
