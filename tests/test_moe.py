"""Mixture-of-Experts + expert parallelism on the 8-virtual-device CPU mesh:
single-expert equivalence with the dense Mlp, routing/capacity semantics, the
load-balance aux loss, expert param sharding over "ep", and full train-step
trajectory equivalence between ep-sharded and data-parallel meshes —
mirrors the pp/sp suites for the last parallelism axis (vitax/models/moe.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vitax.config import Config
from vitax.models import build_model
from vitax.models.moe import MoeMlp
from vitax.models.vit import Mlp
from vitax.parallel.mesh import build_mesh


def moe_cfg(**kw):
    base = dict(image_size=32, patch_size=8, embed_dim=32, num_heads=4,
                num_blocks=2, num_classes=4, batch_size=16, dtype="float32",
                moe_experts=4, ep_size=2, dp_size=2, fsdp_size=2,
                warmup_steps=0)
    base.update(kw)
    return Config(**base).validate()


def test_single_expert_equals_dense_mlp():
    """E=1 with capacity >= N degenerates to the dense Mlp: the router's
    softmax over one expert gates everything at 1.0, so output must equal
    Mlp with the same (unstacked) weights."""
    d, h, n = 16, 32, 8
    moe = MoeMlp(num_experts=1, hidden_dim=h, out_dim=d,
                 capacity_factor=1.0, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, n, d), jnp.float32)
    params = moe.init(jax.random.key(1), x)
    dense = Mlp(hidden_dim=h, out_dim=d, dtype=jnp.float32)
    dense_params = {"params": {
        "fc1": {"kernel": params["params"]["w1"][0],
                "bias": params["params"]["b1"][0]},
        "fc2": {"kernel": params["params"]["w2"][0],
                "bias": params["params"]["b2"][0]},
    }}
    got = moe.apply(params, x)
    want = dense.apply(dense_params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_routing_and_capacity_drop():
    """Tokens route to their argmax expert weighted by the gate; tokens past
    the static capacity are dropped (zero MoE output -> residual passthrough
    at the block level)."""
    d, e, n = 8, 2, 4
    moe = MoeMlp(num_experts=e, hidden_dim=8, out_dim=d,
                 capacity_factor=0.5, dtype=jnp.float32)  # C = ceil(.5*4/2)=1
    x = jax.random.normal(jax.random.key(2), (1, n, d), jnp.float32)
    params = moe.init(jax.random.key(3), x)
    # force ALL tokens to expert 0: bias the router hard
    params["params"]["router"]["bias"] = jnp.array([10.0, -10.0])
    params["params"]["router"]["kernel"] = jnp.zeros((d, e))
    out = moe.apply(params, x)
    # capacity 1: only the FIRST token gets expert compute; rest are dropped
    assert not np.allclose(np.asarray(out[0, 0]), 0.0)
    np.testing.assert_allclose(np.asarray(out[0, 1:]), 0.0, atol=1e-7)


def test_aux_loss_uniform_router_is_one():
    """Switch aux loss = E * sum_e(frac_e * prob_e); a perfectly uniform
    router gives E * E * (1/E * 1/E) = 1 in expectation. With a zero router
    (all logits equal) prob_e = 1/E exactly; argmax ties resolve to expert 0
    so frac = onehot(0) and the loss is still exactly 1.0."""
    d, e = 8, 4
    moe = MoeMlp(num_experts=e, hidden_dim=8, out_dim=d, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(4), (2, 8, d), jnp.float32)
    params = moe.init(jax.random.key(5), x)
    params["params"]["router"]["kernel"] = jnp.zeros((d, e))
    params["params"]["router"]["bias"] = jnp.zeros((e,))
    _, cols = moe.apply(params, x, mutable=["intermediates"])
    moe_cols = cols["intermediates"]["moe_frac_tokens"], \
        cols["intermediates"]["moe_mean_prob"]
    (frac,), (prob,) = moe_cols
    aux = e * jnp.sum(frac * prob)
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-6)


def test_top2_routing_hand_case():
    """Top-2: both chosen experts contribute with renormalized gates; second
    choices queue behind ALL first choices of that expert for capacity
    (GShard order). Hand-verifiable 2-expert case with identity-ish experts."""
    d, e, n = 4, 2, 2
    moe = MoeMlp(num_experts=e, hidden_dim=4, out_dim=d, top_k=2,
                 capacity_factor=float(n), dtype=jnp.float32)  # C = n: no drops
    x = jax.random.normal(jax.random.key(6), (1, n, d), jnp.float32)
    params = moe.init(jax.random.key(7), x)
    # router: token probs fixed at [0.75, 0.25] for every token
    params["params"]["router"]["kernel"] = jnp.zeros((d, e))
    params["params"]["router"]["bias"] = jnp.log(jnp.array([3.0, 1.0]))
    out = moe.apply(params, x)

    # expected: renormalized gates 0.75/0.25; expert e applies its own MLP
    def expert(i, v):
        p = params["params"]
        h = v @ p["w1"][i] + p["b1"][i]
        h = jax.nn.gelu(h, approximate=False)
        return h @ p["w2"][i] + p["b2"][i]

    want = 0.75 * expert(0, x) + 0.25 * expert(1, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_top2_second_choice_capacity_queue():
    """First choices rank before ALL second choices for capacity (GShard
    order — the count1 offset in vitax/models/moe.py): token 0 first-chooses
    expert 0 while token 1 second-chooses it; at capacity 1, token 1's
    second choice must lose the slot to token 0's first choice EVEN THOUGH
    either alone would fit. Symmetrically for expert 1. Dropping the offset
    (plain per-choice cumsum) would instead keep both second choices and
    make this fail."""
    d, e, n = 4, 2, 2
    moe = MoeMlp(num_experts=e, hidden_dim=4, out_dim=d, top_k=2,
                 capacity_factor=1.0, dtype=jnp.float32)  # C = ceil(2/2) = 1
    # token 0 = +e1 basis, token 1 = -e1: router kernel [s, -s] makes token
    # 0's probs [.75, .25] (first choice expert 0) and token 1's [.25, .75]
    x = jnp.zeros((1, n, d)).at[0, 0, 0].set(1.0).at[0, 1, 0].set(-1.0)
    params = moe.init(jax.random.key(9), x)
    s = float(np.log(3.0) / 2.0)
    params["params"]["router"]["kernel"] = jnp.zeros((d, e)).at[0, 0].set(
        s).at[0, 1].set(-s)
    params["params"]["router"]["bias"] = jnp.zeros((e,))
    out = moe.apply(params, x)

    def expert(i, v):
        p = params["params"]
        h = v @ p["w1"][i] + p["b1"][i]
        h = jax.nn.gelu(h, approximate=False)
        return h @ p["w2"][i] + p["b2"][i]

    # each token keeps only its FIRST choice (gate .75); its second choice
    # was evicted by the other token's first choice
    want0 = 0.75 * expert(0, x[:, 0])
    want1 = 0.75 * expert(1, x[:, 1])
    np.testing.assert_allclose(np.asarray(out[0, 0]), np.asarray(want0[0]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out[0, 1]), np.asarray(want1[0]),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("top_k", [1, 2])
def test_gather_matches_einsum_oracle(top_k):
    """The gather dispatch/combine (--moe_impl gather, the fast path) must
    reproduce the GShard one-hot einsum oracle exactly: same outputs, same
    grads w.r.t. params AND inputs, with real capacity drops in play
    (capacity_factor 1.0 over a random router forces over-capacity tokens)."""
    d, e, n, b = 16, 4, 24, 3
    kw = dict(num_experts=e, hidden_dim=32, out_dim=d, top_k=top_k,
              capacity_factor=1.0, dtype=jnp.float32)
    moe_g = MoeMlp(impl="gather", **kw)
    moe_e = MoeMlp(impl="einsum", **kw)
    x = jax.random.normal(jax.random.key(11), (b, n, d), jnp.float32)
    params = moe_g.init(jax.random.key(12), x)

    out_g = moe_g.apply(params, x)
    out_e = moe_e.apply(params, x)
    assert not np.allclose(np.asarray(out_g), 0.0)  # non-degenerate case
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_e),
                               rtol=1e-6, atol=1e-6)

    def loss(m, p, xx):
        return jnp.sum(jnp.sin(m.apply(p, xx)))

    gp_g, gx_g = jax.grad(lambda p, xx: loss(moe_g, p, xx), (0, 1))(params, x)
    gp_e, gx_e = jax.grad(lambda p, xx: loss(moe_e, p, xx), (0, 1))(params, x)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), gp_g, gp_e)
    np.testing.assert_allclose(np.asarray(gx_g), np.asarray(gx_e),
                               rtol=1e-5, atol=1e-6)


def test_train_trajectory_gather_matches_einsum(devices8):
    """Full train-step trajectories must be impl-invariant (the oracle
    guarantee at the training level, mirroring the ep==dp mesh tests)."""
    from tests.test_train_smoke import run_steps

    _, losses_g = run_steps(moe_cfg(moe_impl="gather"), n_steps=3)
    _, losses_e = run_steps(moe_cfg(moe_impl="einsum"), n_steps=3)
    assert all(np.isfinite(losses_g))
    np.testing.assert_allclose(losses_g, losses_e, rtol=2e-4)


def test_top2_train_step_ep_matches_dp(devices8):
    """Top-2 trajectories must be mesh-invariant too (ep-sharded == dp)."""
    from tests.test_train_smoke import run_steps

    cfg_ep = moe_cfg(moe_top_k=2)
    cfg_dp = moe_cfg(moe_top_k=2, ep_size=1, dp_size=2, fsdp_size=-1)
    _, losses_ep = run_steps(cfg_ep, n_steps=3)
    _, losses_dp = run_steps(cfg_dp, n_steps=3)
    assert all(np.isfinite(losses_ep))
    np.testing.assert_allclose(losses_ep, losses_dp, rtol=2e-4)


def test_expert_param_sharding(devices8):
    """Expert weights carry "ep" on the experts dim (after the stacked layer
    dim under scan); the router and dense params never do."""
    from vitax.parallel.sharding import param_specs

    cfg = moe_cfg()
    mesh = build_mesh(cfg)
    model = build_model(cfg)
    abstract = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, 32, 32, 3), jnp.float32), True),
        jax.random.key(0))
    specs = param_specs(abstract, cfg, mesh)
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    saw_expert = saw_router = False
    for path, spec in flat:
        names = [str(getattr(p, "key", p)) for p in path]
        if "moe" in names and names[-1] in ("w1", "b1", "w2", "b2"):
            assert spec[1] == "ep", (names, spec)  # dim 0 is the scan axis
            saw_expert = True
        else:
            assert "ep" not in tuple(spec), (names, spec)
            if "router" in names:
                saw_router = True
    assert saw_expert and saw_router


def test_moe_train_step_ep_matches_dp(devices8):
    """Full MoE train step on the dp2 x fsdp2 x ep2 mesh must match the
    dp-only (ep=1) trajectory — expert sharding must not change the math.
    Also checks the aux loss actually moved the objective (loss differs from
    a moe_aux_weight=0 run)."""
    from tests.test_train_smoke import run_steps

    cfg_ep = moe_cfg(grad_ckpt=True)
    cfg_dp = moe_cfg(grad_ckpt=True, ep_size=1, dp_size=2, fsdp_size=-1)
    _, losses_ep = run_steps(cfg_ep, n_steps=4)
    _, losses_dp = run_steps(cfg_dp, n_steps=4)
    assert all(np.isfinite(losses_ep))
    np.testing.assert_allclose(losses_ep, losses_dp, rtol=2e-4)

    _, losses_noaux = run_steps(moe_cfg(grad_ckpt=True, moe_aux_weight=0.0),
                                n_steps=2)
    assert abs(losses_noaux[0] - losses_ep[0]) > 1e-5, (
        "aux loss had no effect on the objective")


def test_moe_loss_decreases(devices8):
    from tests.test_train_smoke import run_steps

    _, losses = run_steps(moe_cfg(), n_steps=8)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], f"MoE loss did not fall: {losses}"


def test_moe_config_validation():
    with pytest.raises(AssertionError):  # ep needs experts
        moe_cfg(moe_experts=0)
    with pytest.raises(AssertionError):  # experts % ep
        moe_cfg(moe_experts=3)
    with pytest.raises(AssertionError):  # pp x ep needs the einsum impl
        moe_cfg(ep_size=2, pp_size=2, fsdp_size=1, dp_size=2,
                moe_impl="gather")
    # moe + pp with ep=1 is supported (v2: aux ingredients ride the pipeline)
    moe_cfg(ep_size=1, pp_size=2, fsdp_size=1, dp_size=4)
    # moe + pp with ep>1 is supported under the einsum impl (v3: manual
    # all-to-all dispatch inside the pipeline body)
    moe_cfg(ep_size=2, pp_size=2, fsdp_size=1, dp_size=2)


@pytest.mark.slow
def test_moe_ep_partitioner_has_no_involuntary_remat():
    """The ep-sharded mesh must compile without GSPMD's "Involuntary full
    rematerialization" fallback (VERDICT r3 item 4: the replicate-then-
    repartition path costs real HBM bandwidth on a pod). The warning is
    emitted by XLA's C++ logging, so it is captured from a subprocess's
    stderr. Guarded by the activation anchors in vitax/models/vit.py
    (block-entry carry, qkv output, pooled head input) and moe.py
    (dispatch/combine + token re-anchor)."""
    import os
    import subprocess
    import sys

    code = (
        "import sys; sys.path.insert(0, '.')\n"
        "import jax\n"
        "from vitax.platform import force_cpu_if_requested\n"
        "force_cpu_if_requested()\n"
        "import __graft_entry__ as g\n"
        "mesh, losses = g._dryrun_one(8, 1, moe_experts=4, dp_size=2,\n"
        "                             fsdp_size=-1, ep_size=2)\n"
        "print('ok', mesh, losses)\n"
    )
    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)  # CPU run: skip TPU plugin dial
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=480, env=env,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr[-2000:]
    assert "ok" in r.stdout, r.stdout
    assert "Involuntary full rematerialization" not in r.stderr, (
        "GSPMD fell back to replicate-then-repartition under the ep mesh:\n"
        + "\n".join(l for l in r.stderr.splitlines() if "Involuntary" in l))


def test_moe_eval_step(devices8):
    """Eval under --moe_experts (VERDICT r3 weak #7): the eval step routes
    through the plain forward where the aux-loss sows are silently inert
    (no mutable collection) — it must still produce the same correct-count
    as an explicit argmax over model.apply logits."""
    from jax.sharding import NamedSharding
    from vitax.parallel.mesh import batch_pspec
    from vitax.train.state import build_optimizer, make_train_state
    from vitax.train.step import make_eval_step

    cfg = moe_cfg()
    mesh = build_mesh(cfg)
    model = build_model(cfg)
    tx, _ = build_optimizer(cfg, max_iteration=10)
    state, sspecs, _ = make_train_state(cfg, model, tx, mesh,
                                        jax.random.key(0))
    eval_step = make_eval_step(cfg, model, mesh, sspecs)

    sh = NamedSharding(mesh, batch_pspec())
    rng = np.random.default_rng(0)
    batch = {
        "image": jax.device_put(jnp.asarray(rng.normal(
            size=(cfg.batch_size, cfg.image_size, cfg.image_size, 3)),
            jnp.float32), sh),
        "label": jax.device_put(jnp.asarray(rng.integers(
            0, cfg.num_classes, size=(cfg.batch_size,)), jnp.int32), sh),
    }
    correct = int(jax.device_get(eval_step(state, batch)["correct"]))

    logits = model.apply(state.params, batch["image"], True)
    want = int(jnp.sum(jnp.argmax(logits, -1) == batch["label"]))
    assert correct == want, (correct, want)
    assert 0 <= correct <= cfg.batch_size
