"""Schedule parity with reference utils.py:11-21 at the boundary points
{0, mid-warmup, warmup, mid-decay, max} (SURVEY.md section 4)."""

import math

import numpy as np

from vitax.train.schedule import warmup_cosine_schedule


def reference_ratio(step, warmup, max_iter):
    """Literal reimplementation of reference utils.py:12-19 for comparison."""
    if step < warmup:
        return step * 1.0 / warmup
    where = (step - warmup) * 1.0 / (max_iter - warmup)
    return 0.5 * (1 + math.cos(math.pi * where))


def test_schedule_boundary_values():
    base_lr, warmup, max_iter = 1e-3, 10_000, 375_300
    sched = warmup_cosine_schedule(base_lr, warmup, max_iter)
    assert float(sched(0)) == 0.0  # lr is 0 at step 0
    np.testing.assert_allclose(float(sched(warmup // 2)), base_lr * 0.5, rtol=1e-6)
    np.testing.assert_allclose(float(sched(warmup)), base_lr, rtol=1e-6)
    mid = (warmup + max_iter) // 2
    np.testing.assert_allclose(float(sched(mid)), base_lr * reference_ratio(mid, warmup, max_iter), rtol=1e-5)
    np.testing.assert_allclose(float(sched(max_iter)), 0.0, atol=1e-9)


def test_schedule_matches_reference_everywhere():
    base_lr, warmup, max_iter = 3e-4, 100, 1000
    sched = warmup_cosine_schedule(base_lr, warmup, max_iter)
    for step in range(0, 1001, 7):
        want = base_lr * reference_ratio(step, warmup, max_iter)
        np.testing.assert_allclose(float(sched(step)), want, rtol=1e-5, atol=1e-10,
                                   err_msg=f"step {step}")


def test_schedule_zero_warmup():
    """With warmup 0 the reference never enters the warmup branch: pure cosine,
    full lr at step 0."""
    base_lr, max_iter = 1e-3, 1000
    sched = warmup_cosine_schedule(base_lr, 0, max_iter)
    np.testing.assert_allclose(float(sched(0)), base_lr, rtol=1e-6)
    for step in (0, 1, 500, 999, 1000):
        want = base_lr * reference_ratio(step, 0, max_iter)
        np.testing.assert_allclose(float(sched(step)), want, rtol=1e-5, atol=1e-10)


def test_smoothed_value_parity():
    """SmoothedValue windowed stats match the reference implementation semantics
    (reference utils.py:60-102)."""
    from vitax.utils.metrics import SmoothedValue

    sv = SmoothedValue(window_size=3)
    for v, b in [(1.0, 1), (2.0, 1), (3.0, 2), (4.0, 1)]:
        sv.update(v, b)
    # window holds (2.0,1),(3.0,2),(4.0,1): avg = (2+6+4)/4
    np.testing.assert_allclose(sv.avg, 3.0)
    np.testing.assert_allclose(sv.median, 3.0)
    np.testing.assert_allclose(sv.global_avg, (1 + 2 + 6 + 4) / 5)
    assert sv.get_latest() == 4.0
