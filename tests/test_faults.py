"""Failure-reaction tests (PR 7): deterministic fault injection, watchdog
escalation, checkpoint hardening (torn-dir skip, save retry, restore
fallback), the supervisor's restart/backoff/crash-loop logic, and the
kill-and-resume equivalence pins.

Unit arms run tier-1 (fake children, injectable clocks/exits, in-process
trainings); the subprocess drills through tools/supervise.py are `slow`.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from vitax import faults
from vitax.checkpoint.orbax_io import (committed_epochs, epoch_ckpt_path,
                                       is_committed_checkpoint, latest_epoch,
                                       load_resume_step,
                                       restore_state_with_fallback,
                                       save_state, wait_until_finished)
from vitax.config import Config
from vitax.supervise import (EXIT_BUDGET, Supervisor, ensure_auto_resume,
                             main as supervise_main, scrape_flag)
from vitax.telemetry.watchdog import EXIT_HANG, Watchdog

from tests.test_checkpoint import abstract_of, make_state, tiny_cfg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No fault plan may leak across tests (the registry is module-global)."""
    yield
    faults.uninstall()


def _wait_until(cond, timeout_s=5.0, poll_s=0.01):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(poll_s)
    return cond()


# --- fault-plan parsing + registry determinism ------------------------------

def test_parse_plan_accepts_all_three_shapes():
    one = '{"site": "step", "action": "crash", "at": 6}'
    as_list = f"[{one}]"
    wrapped = f'{{"faults": [{one}]}}'
    for text in (one, as_list, wrapped):
        plan = faults.parse_plan(text)
        assert len(plan.specs) == 1
        assert plan.specs[0].site == "step" and plan.specs[0].at == 6
        assert plan.specs[0].exit_code == faults.DEFAULT_CRASH_EXIT_CODE


@pytest.mark.parametrize("bad", [
    "not json at all",
    "42",
    '{"site": "nowhere", "action": "crash"}',
    '{"site": "step", "action": "explode"}',
    '{"site": "step"}',
    '{"site": "step", "action": "crash", "at": 0}',
    '{"site": "step", "action": "crash", "times": 0}',
    '{"site": "step", "action": "crash", "typo_key": 1}',
    "[]",
])
def test_parse_plan_rejects_bad_plans(bad):
    with pytest.raises(ValueError):
        faults.parse_plan(bad)


def test_config_validates_fault_plan_and_hang_action():
    # a bad plan fails at startup (config validation), not at step N
    with pytest.raises(AssertionError):
        Config(fault_plan="not json").validate()
    with pytest.raises(AssertionError):
        Config(hang_action="restart").validate()
    cfg = Config(fault_plan='{"site": "step", "action": "hang"}',
                 hang_action="checkpoint_exit").validate()
    assert cfg.hang_action == "checkpoint_exit"


def test_hooks_are_noops_with_no_plan():
    faults.uninstall()
    assert not faults.active()
    for _ in range(3):  # would raise/hang/exit if anything were armed
        faults.fire("step", index=1)
        faults.fire("ckpt_write")
        faults.fire("loader")


def test_oserror_fires_deterministically_in_at_times_window():
    faults.install('{"site": "ckpt_write", "action": "oserror", '
                   '"at": 2, "times": 2}')
    fired = []
    for call in range(1, 6):  # internal per-site counter: calls 2,3 fire
        try:
            faults.fire("ckpt_write")
            fired.append(False)
        except OSError:
            fired.append(True)
    assert fired == [False, True, True, False, False]


def test_explicit_index_overrides_counter_and_reporter_sees_payload():
    faults.install('{"site": "step", "action": "oserror", "at": 7}')
    events = []
    faults.set_reporter(events.append)
    faults.fire("step", index=3)  # not at 7: silent
    with pytest.raises(OSError):
        faults.fire("step", index=7)
    with pytest.raises(OSError):
        faults.fire("step", index=7)  # explicit index: re-fires, by design
    assert [e["index"] for e in events] == [7, 7]
    assert events[0]["site"] == "step" and events[0]["action"] == "oserror"


def test_install_from_config_env_fallback(monkeypatch):
    plan = '{"site": "loader", "action": "stall", "seconds": 0}'
    monkeypatch.setenv(faults.ENV_VAR, plan)
    installed = faults.install_from_config(Config())  # no --fault_plan
    assert installed is not None and installed.specs[0].site == "loader"
    monkeypatch.delenv(faults.ENV_VAR)
    assert faults.install_from_config(Config()) is None  # nothing set: disarm
    assert not faults.active()


# --- step program identity: the failure-reaction layer is host-side only ----

def test_fault_and_hang_flags_trace_identical_step_program(devices8):
    """--fault_plan / --hang_action are host-side machinery: the lowered
    train-step program must be bit-identical with them set or unset (same
    acceptance pin telemetry carries in test_telemetry.py)."""
    from tests.test_train_smoke import build_train_objects, random_batch

    def lowered(cfg):
        mesh, state, step_fn, _ = build_train_objects(cfg)
        batch = random_batch(cfg, mesh)
        return step_fn.lower(state, batch, jax.random.key(0)).as_text()

    off = lowered(tiny_cfg())
    plan = '{"site": "step", "action": "hang", "at": 999999}'
    faults.install(plan)  # armed registry during trace, for good measure
    on = lowered(tiny_cfg(fault_plan=plan, hang_action="checkpoint_exit",
                          hang_timeout_s=300.0))
    assert off == on


# --- watchdog escalation (unit: fake hard_exit, no process dies) ------------

def test_watchdog_escalates_once_with_pinned_exit_code():
    escalations, exits = [], []
    wd = Watchdog(timeout_s=0.1, poll_s=0.02, action="checkpoint_exit",
                  hard_deadline_s=30.0, on_escalate=escalations.append,
                  hard_exit=exits.append).start()
    try:
        assert _wait_until(wd.escalation_requested)
        assert len(escalations) == 1
        assert escalations[0]["exit_code"] == EXIT_HANG == 42
        # a pet after escalation re-arms the DUMP but never the escalation
        wd.pet()
        assert wd.escalation_requested()
        assert _wait_until(lambda: wd.fire_count >= 2)  # second stall dumps...
        assert len(escalations) == 1  # ...but escalates no second time
        assert exits == []  # deadline far away: no hard exit
    finally:
        wd.stop()


def test_watchdog_hard_exits_when_loop_never_polls():
    exits = []
    wd = Watchdog(timeout_s=0.1, poll_s=0.02, action="checkpoint_exit",
                  hard_deadline_s=0.15, hard_exit=exits.append).start()
    try:
        assert _wait_until(lambda: exits == [EXIT_HANG])
        time.sleep(0.1)
        assert exits == [EXIT_HANG]  # fired once, then disarmed
    finally:
        wd.stop()


def test_watchdog_acknowledge_extends_the_hard_deadline():
    exits = []
    wd = Watchdog(timeout_s=0.1, poll_s=0.02, action="checkpoint_exit",
                  hard_deadline_s=0.3, hard_exit=exits.append).start()
    try:
        assert _wait_until(wd.escalation_requested)
        # the "loop" keeps acknowledging (emergency save in progress): the
        # deadline keeps moving and the hard exit must not fire
        for _ in range(10):
            wd.acknowledge_escalation()
            time.sleep(0.05)
        assert exits == []
        assert _wait_until(lambda: exits == [EXIT_HANG], timeout_s=2.0)
    finally:
        wd.stop()


def test_watchdog_dump_action_never_escalates():
    wd = Watchdog(timeout_s=0.1, poll_s=0.02, action="dump",
                  hard_exit=lambda code: pytest.fail("hard exit under dump"),
                  ).start()
    try:
        assert _wait_until(lambda: wd.fire_count >= 1)
        assert not wd.escalation_requested()
    finally:
        wd.stop()


# --- checkpoint hardening ---------------------------------------------------

def _tiny_tree():
    return {"w": np.arange(8, dtype=np.float32)}


def test_latest_epoch_skips_torn_checkpoint_dir(tmp_path):
    ckpt = str(tmp_path)
    save_state(ckpt, 1, _tiny_tree(), wait=True)
    save_state(ckpt, 2, _tiny_tree(), wait=True)
    assert is_committed_checkpoint(epoch_ckpt_path(ckpt, 2))
    # hand-tear epoch_3 the way a crash mid-async-write does: the dir and a
    # data file exist, the commit marker does not
    torn = epoch_ckpt_path(ckpt, 3)
    os.makedirs(os.path.join(torn, "w"))
    with open(os.path.join(torn, "w", "shard_0"), "wb") as f:
        f.write(b"\x00" * 64)
    assert not is_committed_checkpoint(torn)
    assert committed_epochs(ckpt) == [1, 2]
    assert latest_epoch(ckpt) == 2  # auto-resume can never select epoch 3


def test_save_state_retries_transient_write_failures(tmp_path, monkeypatch):
    monkeypatch.setenv("VITAX_SAVE_RETRY_BACKOFF_S", "0.01")
    # 2 injected failures < 3 attempts: the save must succeed on the third
    faults.install('{"site": "ckpt_write", "action": "oserror", '
                   '"at": 1, "times": 2}')
    save_state(str(tmp_path), 1, _tiny_tree(), wait=True)
    assert latest_epoch(str(tmp_path)) == 1

    # failures >= the retry budget: the save must surface the OSError
    faults.install('{"site": "ckpt_write", "action": "oserror", '
                   '"at": 1, "times": 99}')
    with pytest.raises(OSError):
        save_state(str(tmp_path), 2, _tiny_tree(), wait=True)
    faults.uninstall()
    assert latest_epoch(str(tmp_path)) == 1


def test_restore_falls_back_to_previous_committed_epoch(devices8, tmp_path):
    cfg = tiny_cfg(ckpt_dir=str(tmp_path))
    mesh, state, sspecs = make_state(cfg)
    bumped = state.replace(params=jax.tree.map(lambda x: x * 2.0,
                                               state.params))
    save_state(cfg.ckpt_dir, 1, state, wait=True)
    save_state(cfg.ckpt_dir, 2, bumped, wait=True)
    wait_until_finished()
    # corrupt epoch_2 BEHIND its commit marker: array data gone, marker kept
    ep2 = epoch_ckpt_path(cfg.ckpt_dir, 2)
    for name in os.listdir(ep2):
        if name not in ("_CHECKPOINT_METADATA", "commit_success.txt"):
            path = os.path.join(ep2, name)
            if os.path.isdir(path):
                import shutil
                shutil.rmtree(path)
            else:
                os.remove(path)
    assert is_committed_checkpoint(ep2)  # looks fine from the outside...

    restored, epoch = restore_state_with_fallback(
        cfg.ckpt_dir, 2, abstract_of(state, mesh, sspecs))
    assert epoch == 1  # ...but restore drops, loudly, to the previous epoch
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --- loader worker-error surfacing ------------------------------------------

def test_loader_worker_exception_carries_worker_traceback(devices8):
    from vitax.data.loader import (LoaderWorkerError, ShardedLoader,
                                   ShardedSampler)
    from vitax.parallel.mesh import build_mesh

    class BrokenDataset:
        def __len__(self):
            return 32

        def __getitem__(self, idx):
            raise ValueError(f"boom-sample-{idx}")

    mesh = build_mesh(tiny_cfg())
    sampler = ShardedSampler(32, 16, shuffle=False, seed=0,
                             process_index=0, process_count=1)
    loader = ShardedLoader(BrokenDataset(), sampler, mesh, num_workers=2)
    try:
        with pytest.raises(LoaderWorkerError) as err:
            next(iter(loader.epoch(1)))
        msg = str(err.value)
        assert "boom-sample-" in msg
        assert "worker traceback" in msg and "__getitem__" in msg
        assert isinstance(err.value.__cause__, ValueError)
    finally:
        loader.close()


# --- supervisor (unit: fake children, injected clock) -----------------------

class _FakeChild:
    """A 'process' whose exit code is known in advance; `delay_polls` makes
    poll() return None that many times first (a still-running child)."""

    def __init__(self, rc, delay_polls=0):
        self.rc = rc
        self.delay_polls = delay_polls
        self.signals = []

    def poll(self):
        if self.delay_polls > 0:
            self.delay_polls -= 1
            return None
        return self.rc

    def send_signal(self, sig):
        self.signals.append(sig)
        self.rc = 0  # a drained child exits cleanly
        self.delay_polls = 0

    def kill(self):
        self.signals.append("KILL")
        self.rc = -9
        self.delay_polls = 0


def _supervisor(tmp_path, rcs, progresses, **kw):
    children = [_FakeChild(rc) for rc in rcs]
    spawned = []
    progress_it = iter(progresses)
    sleeps = []
    sup = Supervisor(
        ["python", "train.py"], ckpt_dir=str(tmp_path),
        metrics_dir=str(tmp_path),
        spawn=lambda argv: spawned.append(argv) or children[len(spawned) - 1],
        progress_fn=lambda: next(progress_it),
        sleep=sleeps.append, **kw)
    return sup, sleeps, children


def test_supervisor_clean_child_needs_no_restart(tmp_path):
    sup, sleeps, _ = _supervisor(tmp_path, [0], [(0, 0)])
    assert sup.run() == 0
    assert sup.restart_count == 0 and sleeps == []
    assert not os.path.exists(os.path.join(str(tmp_path), "metrics.jsonl"))


def test_supervisor_restarts_with_exponential_backoff(tmp_path):
    # two crashes, each with checkpoint progress, then success
    progresses = [(0, 0), (1, 0),   # run 1: before/after — epoch 1 landed
                  (1, 0), (1, 3),   # run 2: a mid-epoch save advanced step
                  (1, 3)]           # run 3 exits 0: no 'after' probe
    sup, sleeps, _ = _supervisor(tmp_path, [13, EXIT_HANG, 0], progresses,
                                 backoff_s=0.5, backoff_max_s=10.0)
    assert sup.run() == 0
    assert sup.restart_count == 2
    assert sup.last_exit_code == 0
    assert sleeps == [0.5, 1.0]  # capped exponential: 0.5 * 2^(n-1)
    # forced auto-resume on the child command
    assert sup.child_argv[-2:] == ["--resume_epoch", "-1"]
    # restart telemetry landed in metrics.jsonl with the exit codes
    lines = [json.loads(ln) for ln in
             open(os.path.join(str(tmp_path), "metrics.jsonl"))]
    assert [e["kind"] for e in lines] == ["restart", "restart"]
    assert [e["exit_code"] for e in lines] == [13, EXIT_HANG]
    assert all(e["schema"] == 1 and e["progress"] for e in lines)


def test_supervisor_detects_crash_loop(tmp_path):
    # dies repeatedly with a frozen checkpoint frontier: deterministic bug,
    # not flaky infrastructure — give up with the distinct budget code
    sup, sleeps, _ = _supervisor(
        tmp_path, [13, 13, 13, 13], [(2, 0)] * 8,
        crash_loop_tolerance=1, backoff_s=0.25, max_restarts=50)
    assert sup.run() == EXIT_BUDGET
    assert sup.restart_count == 1  # one restart burned, then the loop verdict
    assert sleeps == [0.25]


def test_supervisor_exhausts_restart_budget(tmp_path):
    # always-progressing child that still keeps dying: budget bounds it
    progresses = iter(((i, 0) for i in range(100)))
    sup = Supervisor(["python", "train.py"], ckpt_dir=str(tmp_path),
                     max_restarts=3, backoff_s=0.0, crash_loop_tolerance=99,
                     spawn=lambda argv: _FakeChild(1),
                     progress_fn=lambda: next(progresses),
                     sleep=lambda s: None)
    assert sup.run() == EXIT_BUDGET
    assert sup.restart_count == 4  # 3 allowed restarts + the over-budget try
    assert sup.last_exit_code == 1


def test_supervisor_forwards_sigterm_once_and_passes_code_through(tmp_path):
    child = _FakeChild(7, delay_polls=100)
    sup = Supervisor(["python", "train.py"], ckpt_dir=str(tmp_path),
                     spawn=lambda argv: child, progress_fn=lambda: (0, 0),
                     sleep=lambda s: None, term_grace_s=30.0)
    sup._term_requested = True  # as the SIGTERM handler would set it
    rc = sup.run()
    # the drained child's code passes through; no restart fights the scheduler
    assert rc == 0 and sup.restart_count == 0
    assert child.signals == [signal.SIGTERM]


def test_ensure_auto_resume_rewrites_every_spelling():
    assert ensure_auto_resume(["t.py"]) == ["t.py", "--resume_epoch", "-1"]
    assert ensure_auto_resume(["t.py", "--resume_epoch", "4"]) == \
        ["t.py", "--resume_epoch", "-1"]
    assert ensure_auto_resume(["t.py", "--resume_epoch=4"]) == \
        ["t.py", "--resume_epoch=-1"]
    assert scrape_flag(["--ckpt_dir=/a", "--metrics_dir", "/b"],
                       "--metrics_dir") == "/b"


def test_supervise_cli_requires_child_command():
    assert supervise_main([]) == 2
    assert supervise_main(["--max_restarts", "2", "--"]) == 2


def test_metrics_report_surfaces_restart_and_fault_events(tmp_path):
    path = tmp_path / "metrics.jsonl"
    records = [
        {"schema": 1, "step": 1, "loss": 2.0, "sec_per_iter": 0.1},
        {"schema": 1, "kind": "fault", "site": "step", "action": "hang"},
        {"schema": 1, "kind": "hang_escalation", "exit_code": 42},
        {"schema": 1, "kind": "restart", "exit_code": 42, "restart": 1},
        {"schema": 1, "kind": "restart", "exit_code": 13, "restart": 2},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in records))
    r = subprocess.run(
        [sys.executable, os.path.join("tools", "metrics_report.py"),
         str(path), "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    summary = json.loads(r.stdout)
    assert summary["restart_count"] == 2
    assert summary["last_exit_code"] == 13
    assert summary["fault_events"] == 1
    assert summary["hang_escalations"] == 1


# --- kill-and-resume equivalence: hang -> escalation -> auto-resume ---------

def test_hang_escalation_checkpoint_exit_resume_equivalence(devices8,
                                                            tmp_path):
    """The full reaction chain, in process: an injected hang starves the
    watchdog, --hang_action checkpoint_exit escalates, the loop commits an
    emergency mid-epoch checkpoint and exits EXIT_HANG; auto-resume then
    finishes the run to a state equal to an uninterrupted one."""
    from vitax.train.loop import train

    common = dict(
        fake_data=True, num_epochs=2, steps_per_epoch=5, log_step_interval=10,
        ckpt_epoch_interval=99, test_epoch_interval=99, num_workers=2,
        eval_max_batches=1,
    )
    base = train(tiny_cfg(ckpt_dir=str(tmp_path / "base"), **common))
    assert int(jax.device_get(base.step)) == 10

    # global step 8 = epoch 2, third step: sleep 2s past a 1s watchdog
    # (the watchdog arms at the first dispatch return, so compile time is
    # outside the window; the consumer wakes at 2.0s, well inside the hard
    # deadline of ~1.0..1.25 + 2.0)
    hang_dir = str(tmp_path / "hang")
    plan = ('{"site": "step", "action": "hang", "at": 8, "seconds": 2.0}')
    with pytest.raises(SystemExit) as exc:
        train(tiny_cfg(ckpt_dir=hang_dir, fault_plan=plan,
                       hang_timeout_s=1.0, hang_action="checkpoint_exit",
                       **common))
    assert exc.value.code == EXIT_HANG == 42
    assert latest_epoch(hang_dir) == 2  # emergency save committed
    assert load_resume_step(hang_dir, 2) == 3  # ...mid-epoch, 3 steps done

    # auto-resume (no fault plan) re-enters epoch 2 at step 4 and finishes
    resumed = train(tiny_cfg(ckpt_dir=hang_dir, resume_epoch=-1, **common))
    assert int(jax.device_get(resumed.step)) == 10
    for a, b in zip(jax.tree.leaves(base.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


# --- subprocess drills through tools/supervise.py (slow) --------------------

TINY_CHILD_FLAGS = [
    "--fake_data", "--image_size", "16", "--patch_size", "8",
    "--embed_dim", "32", "--num_heads", "2", "--num_blocks", "2",
    "--num_classes", "4", "--batch_size", "16", "--dtype", "float32",
    "--warmup_steps", "2", "--num_epochs", "2", "--steps_per_epoch", "5",
    "--log_step_interval", "10", "--test_epoch_interval", "99",
    "--num_workers", "2", "--eval_max_batches", "1",
]


def _run_sub(cmd, timeout=1500, **extra_env):
    # VITAX_CKPT_SYNC: every save commits before returning, so "the child
    # crashed N steps past an epoch boundary" deterministically implies the
    # boundary checkpoint is durable (no race vs the background commit)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               VITAX_CKPT_SYNC="1", **extra_env)
    return subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=timeout)


def _final_params(ckpt_dir, devices8_cfg):
    """Restore the epoch_2 checkpoint a subprocess run wrote, in this
    process (Orbax array files are not byte-comparable across writes — the
    restored values are)."""
    mesh, state, sspecs = make_state(devices8_cfg)
    from vitax.checkpoint import restore_state
    restored = restore_state(str(ckpt_dir), 2,
                             abstract_of(state, mesh, sspecs))
    return jax.tree.leaves(restored.params)


@pytest.mark.slow
def test_supervised_crash_resume_bitwise_equivalence(devices8, tmp_path):
    """THE acceptance pin: an uninterrupted 2-epoch run vs the same run
    hard-crashed (os._exit 13) mid-epoch-2 under tools/supervise.py. The
    supervisor restarts it, auto-resume picks up the committed epoch-1
    checkpoint, and the final epoch-2 states are bitwise equal."""
    base_dir = tmp_path / "base"
    r = _run_sub([sys.executable, "run_vit_training.py", *TINY_CHILD_FLAGS,
                  "--ckpt_epoch_interval", "1",
                  "--ckpt_dir", str(base_dir)])
    assert r.returncode == 0, r.stderr[-3000:]

    crash_dir = tmp_path / "crash"
    metrics_dir = tmp_path / "metrics"
    plan = '{"site": "step", "action": "crash", "at": 8, "exit_code": 13}'
    r = _run_sub([sys.executable, os.path.join("tools", "supervise.py"),
                  "--backoff_s", "0.1", "--",
                  sys.executable, "run_vit_training.py", *TINY_CHILD_FLAGS,
                  "--ckpt_epoch_interval", "1",
                  "--ckpt_dir", str(crash_dir),
                  "--metrics_dir", str(metrics_dir),
                  "--fault_plan", plan])
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-3000:])
    assert "injecting step:crash" in r.stderr  # the drill actually fired

    cfg = tiny_cfg()
    for a, b in zip(_final_params(base_dir, cfg),
                    _final_params(crash_dir, cfg)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # the restart landed in the child's own metrics stream with exit code 13
    mr = _run_sub([sys.executable, os.path.join("tools", "metrics_report.py"),
                   str(metrics_dir / "metrics.jsonl"), "--json"], timeout=60)
    summary = json.loads(mr.stdout)
    assert summary["restart_count"] >= 1
    assert summary["last_exit_code"] == 13


@pytest.mark.slow
def test_supervisor_gives_up_on_crash_loop_nonzero(tmp_path):
    """A child that crashes before any checkpoint can commit is a crash
    loop: the supervisor must exit nonzero (EXIT_BUDGET), not restart
    forever."""
    plan = '{"site": "step", "action": "crash", "at": 1, "exit_code": 13}'
    r = _run_sub([sys.executable, os.path.join("tools", "supervise.py"),
                  "--crash_loop_tolerance", "0", "--backoff_s", "0.05", "--",
                  sys.executable, "run_vit_training.py", *TINY_CHILD_FLAGS,
                  "--fault_plan", plan,
                  "--ckpt_epoch_interval", "99",
                  "--ckpt_dir", str(tmp_path / "ckpt")])
    assert r.returncode == EXIT_BUDGET == 3, (r.stdout[-2000:],
                                              r.stderr[-3000:])
    assert "CRASH LOOP" in r.stderr
