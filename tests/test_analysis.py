"""vitax.analysis: parser units, rule positive/negative cases, AST lint.

Strategy: hand-written HLO/MLIR string fixtures drive the parser units and
every rule's NEGATIVE case (deliberately broken programs — a use-site gather,
an f32 gather under the bf16 policy, an outfeed in the step, a replicated
large param), so each rule provably FAILS on the violation it polices. The
POSITIVE cases run the real rules over real lowered programs (session-scoped:
one overlap train arm, one donation-off arm, one warmed serve engine), which
doubles as the end-to-end check that HEAD itself is clean.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from vitax.analysis import ast_lint, hlo, rules
from vitax.analysis.rules import (
    COLLECTIVE_DTYPE,
    DONATION_HONORED,
    FUSED_DEQUANT,
    FUSED_OPTIMIZER,
    GATHER_OVERLAP,
    NO_HOST_TRANSFER,
    NO_REPLICATED_LARGE,
    QUANT_WEIGHTS_RESIDENT,
    SERVE_NO_RECOMPILE,
    Program,
    arm_config,
    build_serve_program,
    build_train_program,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- HLO fixtures ------------------------------------------------------------

# A minimal partitioned-style module: a while loop whose body issues one
# all-gather consumed by a dot before the carry (a USE-SITE gather — the
# serial ZeRO-3 schedule).
HLO_USE_SITE = textwrap.dedent("""\
    HloModule jit_train_step, input_output_alias={ {0}: (0, {}, may-alias), {1}: (1, {}, may-alias) }

    body.1 {
      p.1 = (f32[8,8], f32[8,8]) parameter(0)
      gte.0 = f32[8,8] get-tuple-element(p.1), index=0
      gte.1 = f32[8,8] get-tuple-element(p.1), index=1
      ag.1 = f32[8,8] all-gather(gte.0), dimensions={0}
      dot.1 = f32[8,8] dot(ag.1, gte.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
      ROOT tuple.1 = (f32[8,8], f32[8,8]) tuple(dot.1, gte.1)
    }

    cond.1 {
      cp.1 = (f32[8,8], f32[8,8]) parameter(0)
      ROOT lt.1 = pred[] constant(false)
    }

    ENTRY main.1 {
      param.0 = f32[8,8] parameter(0)
      param.1 = f32[8,8] parameter(1)
      t.0 = (f32[8,8], f32[8,8]) tuple(param.0, param.1)
      w.1 = (f32[8,8], f32[8,8]) while(t.0), condition=cond.1, body=body.1
      ROOT out.0 = f32[8,8] get-tuple-element(w.1), index=0
    }
    """)

# Same loop but the gather's result rides the carry to ROOT through nothing
# but plumbing — the prefetch-slot schedule.
HLO_PREFETCH = HLO_USE_SITE.replace(
    "ROOT tuple.1 = (f32[8,8], f32[8,8]) tuple(dot.1, gte.1)",
    "cp2.1 = f32[8,8] copy(ag.1)\n"
    "  ROOT tuple.1 = (f32[8,8], f32[8,8]) tuple(dot.1, cp2.1)")

HLO_WITH_OUTFEED = HLO_USE_SITE.replace(
    "ROOT out.0 = f32[8,8] get-tuple-element(w.1), index=0",
    "tok.0 = token[] after-all()\n"
    "  of.1 = token[] outfeed(param.0, tok.0), outfeed_config=\"x\"\n"
    "  cc.1 = () custom-call(param.1), custom_call_target=\"xla_python_cpu_callback\"\n"
    "  ROOT out.0 = f32[8,8] get-tuple-element(w.1), index=0")


def mk_mlir(args):
    """StableHLO @main skeleton from [(type, attr_dict_text or None)]."""
    rendered = ", ".join(
        f"%arg{i}: {ty}" + (f" {{{attrs}}}" if attrs else "")
        for i, (ty, attrs) in enumerate(args))
    return textwrap.dedent(f"""\
        module @jit_train_step attributes {{mhlo.num_partitions = 8 : i32}} {{
          func.func public @main({rendered}) -> (tensor<f32>) {{
            %0 = stablehlo.constant dense<0.0> : tensor<f32>
            return %0 : tensor<f32>
          }}
        }}
        """)


SHARDED = 'mhlo.sharding = "{devices=[8,1]<=[8]}"'
REPLICATED = 'mhlo.sharding = "{replicated}"'


# --- parser units ------------------------------------------------------------


def test_collect_collectives_and_bytes():
    rows = hlo.collect_collectives(
        "  a = bf16[2,32]{1,0} all-gather(x), dims={0}\n"
        "  b = bf16[2,32]{1,0} all-gather(y), dims={0}\n"
        "  c = f32[16]{0} reduce-scatter(z), dims={0}\n"
        "  d = f32[4,4]{1,0} all-reduce-start(w), to_apply=add\n")
    by_op = {r["op"]: r for r in rows}
    assert by_op["all-gather"]["count"] == 2
    assert by_op["all-gather"]["dtype"] == "bf16"
    assert by_op["all-gather"]["bytes"] == 2 * 64 * 2
    assert by_op["reduce-scatter"]["bytes"] == 16 * 4
    assert "all-reduce" in by_op  # -start folded into the base op
    assert hlo.gather_bytes(rows) == 256
    assert hlo.gather_bytes(rows, dtype="f32") == 0
    totals = hlo.summarize(rows)
    assert totals["all-gather"]["by_dtype"]["bf16"]["count"] == 2


def test_split_computations_and_inventory():
    comps = hlo.split_computations(HLO_USE_SITE)
    assert set(comps) == {"body.1", "cond.1", "main.1"}
    assert len(comps["body.1"]) == 6
    inv = hlo.while_body_op_inventory(HLO_USE_SITE)
    assert inv["body.1"]["all-gather"] == 1
    assert inv["body.1"]["dot"] == 1


def test_overlap_verdict_use_site_vs_prefetch():
    use = hlo.overlap_verdict(HLO_USE_SITE)
    assert use["per_iteration_gather_count"] == {"body.1": 1}
    assert use["prefetch_slot_gathers"] == 0
    pre = hlo.overlap_verdict(HLO_PREFETCH)
    assert pre["per_iteration_gather_count"] == {"body.1": 1}
    assert pre["prefetch_slot_gathers"] == 1


def test_input_output_aliases_header():
    aliases = hlo.input_output_aliases(HLO_USE_SITE)
    assert [(a["output_index"], a["parameter"]) for a in aliases] == \
        [((0,), 0), ((1,), 1)]
    assert hlo.input_output_aliases("HloModule bare\n") == []


def test_host_transfer_ops():
    assert hlo.host_transfer_ops(HLO_USE_SITE) == []
    ops = hlo.host_transfer_ops(HLO_WITH_OUTFEED)
    assert [o["op"] for o in ops] == ["outfeed", "custom-call"]
    assert ops[1]["detail"] == "xla_python_cpu_callback"
    mops = hlo.mlir_host_transfer_ops(
        '    stablehlo.custom_call @xla_python_cpu_callback(%1) : x\n')
    assert mops and mops[0]["detail"] == "xla_python_cpu_callback"


def test_mlir_main_args_table():
    text = mk_mlir([
        ("tensor<64x64xf32>", SHARDED + ", tf.aliasing_output = 0 : i32"),
        ("tensor<8xf32>", REPLICATED + ", tf.aliasing_output = 1 : i32"),
        ("tensor<64x16x16x3xui8>", None),
    ])
    args = hlo.mlir_main_args(text)
    assert [a["index"] for a in args] == [0, 1, 2]
    assert args[0]["bytes"] == 64 * 64 * 4
    assert args[0]["donated_to"] == 0
    assert not hlo.sharding_is_replicated(args[0]["sharding"])
    assert hlo.sharding_is_replicated(args[1]["sharding"])
    assert args[2]["donated_to"] is None
    assert args[2]["sharding"] is None
    assert hlo.sharding_is_replicated(args[2]["sharding"])  # unannotated


def test_sharding_is_replicated_tiled_forms():
    assert hlo.sharding_is_replicated(
        "{devices=[1,1,8]<=[8] last_tile_dim_replicate}")
    assert not hlo.sharding_is_replicated("{devices=[8,1]<=[8]}")


# --- real lowered programs (session-scoped: ~10s each) -----------------------


@pytest.fixture(scope="session")
def overlap_program(devices8):
    return build_train_program(
        arm_config("zero3_overlap"), arm="zero3_overlap")


@pytest.fixture(scope="session")
def no_donate_program(devices8):
    return build_train_program(
        arm_config("zero3"), arm="zero3_nodonate", donate=False)


@pytest.fixture(scope="session")
def serve_program(devices8):
    return build_serve_program(arm_config("serve"))


@pytest.fixture(scope="session")
def serve_quant_program(devices8):
    return build_serve_program(arm_config("serve_quant"), arm="serve_quant")


# --- per-rule positive + negative cases --------------------------------------


def test_r001_host_transfer_positive(overlap_program):
    assert NO_HOST_TRANSFER.check(
        overlap_program, overlap_program.config) == []


def test_r001_host_transfer_negative(overlap_program):
    broken = Program(kind="train", arm="x", config=overlap_program.config,
                     partitioned_hlo=HLO_WITH_OUTFEED)
    findings = NO_HOST_TRANSFER.check(broken, broken.config)
    assert len(findings) == 2
    assert all(f.rule == "VTX-R001" and f.severity == "ERROR"
               for f in findings)


def test_r002_donation_positive(overlap_program):
    assert overlap_program.n_state_leaves > 0
    assert DONATION_HONORED.check(
        overlap_program, overlap_program.config) == []


def test_r002_donation_negative_donate_off(no_donate_program):
    findings = DONATION_HONORED.check(
        no_donate_program, no_donate_program.config)
    assert findings, "donation disabled must trip VTX-R002"
    assert findings[0].rule == "VTX-R002"
    assert findings[0].details["donated"] == 0


def test_r003_collective_dtype_positive(overlap_program):
    assert overlap_program.config.comm_cast_active
    assert COLLECTIVE_DTYPE.check(
        overlap_program, overlap_program.config) == []


def test_r003_collective_dtype_negative():
    cfg = arm_config("zero3")  # bf16 policy active, embed_dim=32
    assert COLLECTIVE_DTYPE.applies_to(cfg)
    d = cfg.embed_dim
    broken = Program(
        kind="train", arm="x", config=cfg,
        partitioned_hlo=f"  ag = f32[{d},{d}]{{1,0}} all-gather(p), dims={{0}}\n")
    findings = COLLECTIVE_DTYPE.check(broken, cfg)
    assert len(findings) == 1 and findings[0].rule == "VTX-R003"
    # sub-threshold f32 gathers (bias-sized) stay legal
    small = Program(
        kind="train", arm="x", config=cfg,
        partitioned_hlo=f"  ag = f32[{d}]{{0}} all-gather(p), dims={{0}}\n")
    assert COLLECTIVE_DTYPE.check(small, cfg) == []


def test_r003_not_applicable_without_policy():
    assert not COLLECTIVE_DTYPE.applies_to(arm_config("dp"))


def test_r004_gather_overlap_positive(overlap_program):
    assert GATHER_OVERLAP.applicable(overlap_program)
    assert GATHER_OVERLAP.check(
        overlap_program, overlap_program.config) == []


def test_r004_gather_overlap_negative():
    cfg = arm_config("zero3_overlap")
    broken = Program(kind="train", arm="x", config=cfg,
                     partitioned_hlo=HLO_USE_SITE,
                     mesh_shape={"dp": 1, "fsdp": 8})
    findings = GATHER_OVERLAP.check(broken, cfg)
    assert len(findings) == 1 and findings[0].rule == "VTX-R004"
    assert "use-site" in findings[0].message
    ok = Program(kind="train", arm="x", config=cfg,
                 partitioned_hlo=HLO_PREFETCH,
                 mesh_shape={"dp": 1, "fsdp": 8})
    assert GATHER_OVERLAP.check(ok, cfg) == []


def test_r005_replicated_large_positive(overlap_program):
    assert NO_REPLICATED_LARGE.applicable(overlap_program)
    assert NO_REPLICATED_LARGE.check(
        overlap_program, overlap_program.config) == []


def test_r005_replicated_large_negative():
    cfg = arm_config("zero3")
    d = cfg.embed_dim  # threshold is d*d*4 bytes; a d*d f32 donated arg tips it
    broken = Program(
        kind="train", arm="x", config=cfg,
        mlir=mk_mlir([
            (f"tensor<{d}x{d}xf32>",
             REPLICATED + ", tf.aliasing_output = 0 : i32"),
            (f"tensor<{d}x{d}xf32>",
             SHARDED + ", tf.aliasing_output = 1 : i32"),
        ]),
        mesh_shape={"dp": 1, "fsdp": 8})
    findings = NO_REPLICATED_LARGE.check(broken, cfg)
    assert len(findings) == 1 and findings[0].rule == "VTX-R005"
    assert findings[0].details["arg"]["index"] == 0


def test_r006_serve_positive(serve_program):
    assert SERVE_NO_RECOMPILE.check(
        serve_program, serve_program.config) == []


def test_r006_serve_negative(serve_program):
    class LeakyEngine:
        """compile_count drifted past the bucket set: recompiles happened."""
        buckets = (1,)
        compile_count = 3
        params = None
        _compiled = {1: lambda *a, **k: None}  # accepts anything: also bad
        _batch_shardings = {1: None}

        def predict(self, images):
            return None, None

    broken = Program(kind="serve", arm="serve", config=serve_program.config,
                     engine=LeakyEngine())
    findings = SERVE_NO_RECOMPILE.check(broken, broken.config)
    codes = [f.message for f in findings]
    assert any("compile_count 3 != bucket count 1" in m for m in codes)
    assert any("accepted an unseen input shape" in m for m in codes)


def test_r007_quant_resident_positive(serve_quant_program):
    prog = serve_quant_program
    assert QUANT_WEIGHTS_RESIDENT.applicable(prog)
    assert prog.engine.scales, "serve_quant arm must carry quant scales"
    assert QUANT_WEIGHTS_RESIDENT.check(prog, prog.config) == []
    # R006 reads the quantized engine too: the AOT contract is dtype-blind
    assert SERVE_NO_RECOMPILE.check(prog, prog.config) == []


def test_r007_not_applicable_without_quant(serve_program):
    assert not QUANT_WEIGHTS_RESIDENT.applicable(serve_program)


def test_r007_quant_resident_negative():
    import numpy as np
    cfg = arm_config("serve_quant")
    d = cfg.embed_dim

    class DequantedEngine:
        """The violation R007 exists for: the scaled leaf was dequantized at
        load (f32 on device) and the lowered program takes a block-sized f32
        weight argument instead of the int8 one."""
        buckets = (1, 2, 4)
        scales = {"params/blocks/mlp/fc1/kernel": np.ones((1, 1, d * 4),
                                                          np.float32)}
        params = {"params": {"blocks": {"mlp": {"fc1": {
            "kernel": np.zeros((2, d, d * 4), np.float32)}}}}}

        def lower_bucket_mlir(self, bucket):
            return mk_mlir([(f"tensor<2x{d}x{d * 4}xf32>", SHARDED),
                            (f"tensor<4x{cfg.image_size}x{cfg.image_size}"
                             f"x3xui8>", None)])

    broken = Program(kind="serve", arm="serve_quant", config=cfg,
                     engine=DequantedEngine())
    findings = QUANT_WEIGHTS_RESIDENT.check(broken, cfg)
    msgs = [f.message for f in findings]
    assert all(f.rule == "VTX-R007" and f.severity == "ERROR"
               for f in findings)
    assert any("resident as float32, not int8" in m for m in msgs)
    assert any("0 i8 arguments for 1 scaled leaves" in m for m in msgs)
    assert any("block-sized floating argument" in m for m in msgs)

    class UnquantizedEngine(DequantedEngine):
        scales = {}

    unq = Program(kind="serve", arm="serve_quant", config=cfg,
                  engine=UnquantizedEngine())
    findings = QUANT_WEIGHTS_RESIDENT.check(unq, cfg)
    assert len(findings) == 1
    assert "no quant scales" in findings[0].message


# --- tier 2: fp8 arm + fused dequant-matmul (VTX-R009) -----------------------


@pytest.fixture(scope="session")
def serve_fp8_program(devices8):
    return build_serve_program(arm_config("serve_fp8"), arm="serve_fp8")


@pytest.fixture(scope="session")
def serve_actquant_program(devices8):
    return build_serve_program(
        arm_config("serve_actquant"), arm="serve_actquant")


def test_r007_fp8_positive(serve_fp8_program):
    """R007 is dtype-keyed: the fp8 arm passes the same residency/arg checks
    against float8_e4m3 leaves and f8E4M3 program arguments."""
    import ml_dtypes
    import numpy as np
    prog = serve_fp8_program
    assert prog.engine.weights_dtype == "float8_e4m3"
    assert QUANT_WEIGHTS_RESIDENT.applicable(prog)
    assert QUANT_WEIGHTS_RESIDENT.check(prog, prog.config) == []
    assert SERVE_NO_RECOMPILE.check(prog, prog.config) == []
    fp8 = np.dtype(ml_dtypes.float8_e4m3)
    import jax
    fp8_leaves = [v for v in jax.tree.leaves(prog.engine.params)
                  if np.dtype(v.dtype) == fp8]
    assert len(fp8_leaves) == len(prog.engine.scales)


def test_r007_fp8_negative_int8_leaves():
    """Wrong quant dtype on device (int8 leaves under an fp8 config) trips
    both the residency check and the program-argument count."""
    import numpy as np
    cfg = arm_config("serve_fp8")
    d = cfg.embed_dim

    class WrongDtypeEngine:
        buckets = (1, 2, 4)
        scales = {"params/blocks/mlp/fc1/kernel": np.ones((1, 1, d * 4),
                                                          np.float32)}
        params = {"params": {"blocks": {"mlp": {"fc1": {
            "kernel": np.zeros((2, d, d * 4), np.int8)}}}}}

        def lower_bucket_mlir(self, bucket):
            return mk_mlir([(f"tensor<2x{d}x{d * 4}xi8>", SHARDED)])

    broken = Program(kind="serve", arm="serve_fp8", config=cfg,
                     engine=WrongDtypeEngine())
    findings = QUANT_WEIGHTS_RESIDENT.check(broken, cfg)
    msgs = [f.message for f in findings]
    assert any("not float8_e4m3" in m for m in msgs)
    assert any("0 f8E4M3 arguments for 1 scaled leaves" in m for m in msgs)


def test_r009_fused_positive(serve_actquant_program):
    from vitax.ops.dequant_matmul import DEQUANT_KERNEL_NAME
    prog = serve_actquant_program
    assert prog.engine.fused_dequant is True
    assert FUSED_DEQUANT.applicable(prog)
    jaxpr = prog.engine.trace_bucket_jaxpr(prog.engine.buckets[-1])
    assert jaxpr.count(DEQUANT_KERNEL_NAME) >= 1
    assert FUSED_DEQUANT.check(prog, prog.config) == []


def test_r009_negative_unfused_build(serve_quant_program,
                                     serve_actquant_program):
    """Teeth check: the SAME rule over a deliberately unfused serve engine
    (the weight-only dequantize_tree program attached to a fused-on config)
    must fire BOTH checks — no kernel launch, and the weight-sized i8->f32
    converts at the top level of the traced program."""
    cfg_on = serve_actquant_program.config
    broken = Program(kind="serve", arm="serve_actquant", config=cfg_on,
                     engine=serve_quant_program.engine)
    findings = FUSED_DEQUANT.check(broken, cfg_on)
    msgs = [f.message for f in findings]
    assert all(f.rule == "VTX-R009" and f.severity == "ERROR"
               for f in findings)
    assert any("no dequant_matmul_kernel" in m for m in msgs)
    assert any("weight-sized dequant outside the fused kernel" in m
               for m in msgs), msgs


def test_r009_not_applicable_without_fused():
    # weight-only int8 (fused auto resolves off on CPU) and the fp8 arm:
    # the rule must not bind, keeping the serve rules_ran pins stable
    assert not FUSED_DEQUANT.applies_to(arm_config("serve_quant"))
    assert not FUSED_DEQUANT.applies_to(arm_config("serve_fp8"))
    assert FUSED_DEQUANT.applies_to(arm_config("serve_actquant"))


def test_tier2_serve_rules_ran_pins(serve_fp8_program,
                                    serve_actquant_program):
    ran8, findings8 = rules.run_rules(serve_fp8_program)
    assert ran8 == ["VTX-R006", "VTX-R007"] and findings8 == []
    ran_a, findings_a = rules.run_rules(serve_actquant_program)
    assert ran_a == ["VTX-R006", "VTX-R007", "VTX-R009"]
    assert findings_a == []


def test_jaxpr_quant_dequant_converts_unit():
    """Parser unit for the R009 helper: sub-jaxpr bodies are stripped (no
    var shadowing), only i8/f8-sourced converts count (u8 images never
    do), and the exempt-shape and min-elems filters apply."""
    text = textwrap.dedent("""\
        { lambda ; a:i8[2,32,96] b:u8[4,16,16,3] c:f8_e4m3[32,4] d:i8[8,8,3,32]
            e:i8[2,2]. let
            f:f32[2,32,96] = convert_element_type[new_dtype=float32] a
            g:f32[4,16,16,3] = convert_element_type[new_dtype=float32] b
            h:f32[32,4] = convert_element_type[new_dtype=float32] c
            i:f32[8,8,3,32] = convert_element_type[new_dtype=float32] d
            j:f32[2,2] = convert_element_type[new_dtype=float32] e
            k:f32[2,32,96] = pjit[
              jaxpr={ lambda ; a:f32[2,32,96]. let
                  b:f32[2,32,96] = mul a 2.0
                in (b,) }
            ] f
          in (k,) }
        """)
    rows = hlo.jaxpr_quant_dequant_converts(
        text, min_elems=128, exempt_shapes=((8, 8, 3, 32),))
    # a (i8, 6144 elems) and c (f8, 128 elems) fire; b is u8 (image), d is
    # the exempt conv shape, e is sub-threshold
    assert [(r["src_dtype"], tuple(r["shape"])) for r in rows] == [
        ("i8", (2, 32, 96)), ("f8_e4m3", (32, 4))]


@pytest.fixture(scope="session")
def fused_program(devices8):
    return build_train_program(arm_config("fused"), arm="fused")


def test_r008_fused_positive(fused_program):
    from vitax.ops.fused_optimizer import FUSED_KERNEL_NAME
    assert fused_program.jaxpr, "fused arm must capture the jaxpr artifact"
    assert fused_program.jaxpr.count(FUSED_KERNEL_NAME) >= 1
    assert FUSED_OPTIMIZER.check(fused_program, fused_program.config) == []


def test_r008_fused_negative_unfused_build(fused_program):
    """Teeth check: the SAME rule over a deliberately unfused build (the
    optax-chain jaxpr attached to a fused-on config) must fire BOTH checks —
    no kernel launch, and the param-sized post-clip temporary chain."""
    cfg_on = fused_program.config
    unfused_jaxpr = hlo.train_step_jaxpr(arm_config("zero3"))
    broken = Program(kind="train", arm="fused", config=cfg_on,
                     jaxpr=unfused_jaxpr)
    findings = FUSED_OPTIMIZER.check(broken, cfg_on)
    msgs = [f.message for f in findings]
    assert all(f.rule == "VTX-R008" and f.severity == "ERROR"
               for f in findings)
    assert any("no fused_adamw_kernel" in m for m in msgs)
    assert any("param-sized f32 sqrt" in m for m in msgs), msgs


def test_r008_missing_artifact_is_a_finding(fused_program):
    empty = Program(kind="train", arm="fused", config=fused_program.config)
    findings = FUSED_OPTIMIZER.check(empty, empty.config)
    assert len(findings) == 1
    assert "without a traced-jaxpr artifact" in findings[0].message


def test_r008_not_applicable_on_cpu_auto():
    # CPU default (auto -> interpret -> optax chain): the rule must not bind,
    # keeping every existing arm's rules_ran pin valid
    assert not FUSED_OPTIMIZER.applies_to(arm_config("zero3"))
    assert FUSED_OPTIMIZER.applies_to(arm_config("fused"))


def test_r008_rules_ran_pin(fused_program):
    ran, findings = rules.run_rules(fused_program)
    assert ran == ["VTX-R001", "VTX-R002", "VTX-R003", "VTX-R005",
                   "VTX-R008"]
    assert findings == []


def test_run_rules_dispatch(overlap_program, serve_program,
                            serve_quant_program):
    ran, findings = rules.run_rules(overlap_program)
    assert ran == ["VTX-R001", "VTX-R002", "VTX-R003", "VTX-R004", "VTX-R005"]
    assert findings == []
    ran_s, findings_s = rules.run_rules(serve_program)
    assert ran_s == ["VTX-R006"] and findings_s == []
    ran_q, findings_q = rules.run_rules(serve_quant_program)
    assert ran_q == ["VTX-R006", "VTX-R007"] and findings_q == []


def test_comm_audit_reexports():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import comm_audit
    for name in ("collect_collectives", "summarize", "gather_bytes",
                 "overlap_verdict", "partitioned_hlo_text", "audit_config",
                 "format_report", "main"):
        assert callable(getattr(comm_audit, name)), name
    assert comm_audit.collect_collectives is hlo.collect_collectives


# --- check_invariants CLI (subprocess: one arm, ~20s) ------------------------


def test_check_invariants_json_schema(devices8):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_invariants.py"),
         "--arms", "zero3", "--json"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.loads(proc.stdout)
    assert doc["schema"] == 1
    assert set(doc) == {"schema", "arms", "findings", "errors",
                        "concurrency", "ok"}
    assert doc["ok"] is True and doc["errors"] == {}
    assert doc["concurrency"]["ok"] is True
    assert doc["concurrency"]["findings"] == []
    arm = doc["arms"]["zero3"]
    assert set(arm) == {"ok", "rules_ran", "findings"}
    assert arm["rules_ran"] == ["VTX-R001", "VTX-R002", "VTX-R003", "VTX-R005"]
    assert arm["findings"] == []


def test_check_invariants_serve_quant_arm(devices8):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_invariants.py"),
         "--arms", "serve_quant", "--json"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.loads(proc.stdout)
    assert doc["ok"] is True and doc["errors"] == {}
    arm = doc["arms"]["serve_quant"]
    assert set(arm) == {"ok", "rules_ran", "findings"}
    assert arm["rules_ran"] == ["VTX-R006", "VTX-R007"]
    assert arm["findings"] == []


def test_check_invariants_tier2_serve_arms(devices8):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_invariants.py"),
         "--arms", "serve_fp8", "serve_actquant", "--json"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.loads(proc.stdout)
    assert doc["ok"] is True and doc["errors"] == {}
    arm8 = doc["arms"]["serve_fp8"]
    assert arm8["rules_ran"] == ["VTX-R006", "VTX-R007"]
    assert arm8["findings"] == []
    arm_a = doc["arms"]["serve_actquant"]
    assert arm_a["rules_ran"] == ["VTX-R006", "VTX-R007", "VTX-R009"]
    assert arm_a["findings"] == []


def test_check_invariants_fused_arm(devices8):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_invariants.py"),
         "--arms", "fused", "--json"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.loads(proc.stdout)
    assert doc["ok"] is True and doc["errors"] == {}
    arm = doc["arms"]["fused"]
    assert set(arm) == {"ok", "rules_ran", "findings"}
    assert arm["rules_ran"] == ["VTX-R001", "VTX-R002", "VTX-R003",
                                "VTX-R005", "VTX-R008"]
    assert arm["findings"] == []


# --- AST lint ----------------------------------------------------------------


def _codes(findings):
    return sorted(f.code for f in findings)


def test_lint_device_get_in_traced_module():
    src = "import jax\ndef f(x):\n    return jax.device_get(x)\n"
    assert _codes(ast_lint.lint_source(src, "vitax/models/vit.py")) == ["VTX101"]
    # same construct outside the traced set is fine
    assert ast_lint.lint_source(src, "vitax/telemetry/record.py") == []


def test_lint_block_until_ready_and_float_on_traced():
    src = ("import jax, jax.numpy as jnp\n"
           "def f(x):\n"
           "    y = jnp.sum(x).block_until_ready()\n"
           "    return float(jnp.mean(y))\n")
    assert _codes(ast_lint.lint_source(src, "vitax/train/step.py")) == \
        ["VTX101", "VTX102"]


def test_lint_item_on_traced():
    src = "import jax.numpy as jnp\ndef f(x):\n    return jnp.max(x).item()\n"
    assert _codes(ast_lint.lint_source(src, "vitax/ops/attention.py")) == \
        ["VTX102"]
    # .item() on a non-jax object is not flagged
    src2 = "def f(d):\n    return d.item()\n"
    assert ast_lint.lint_source(src2, "vitax/ops/attention.py") == []


def test_lint_unfenced_timing():
    src = ("import time\n"
           "def loop(step_fn, batch):\n"
           "    t0 = time.time()\n"
           "    out = step_fn(batch)\n"
           "    dt = time.time() - t0\n"
           "    return out, dt\n")
    assert _codes(ast_lint.lint_source(src, "vitax/train/loop.py")) == ["VTX103"]
    fenced = src.replace("    dt = time.time() - t0\n",
                         "    jax.block_until_ready(out)\n"
                         "    dt = time.time() - t0\n")
    assert ast_lint.lint_source(fenced, "vitax/train/loop.py") == []


def test_lint_argless_jax_devices():
    src = "import jax\ndef f():\n    return jax.devices()[0]\n"
    assert _codes(ast_lint.lint_source(src, "vitax/serve/server.py")) == \
        ["VTX104"]
    ok = "import jax\ndef f():\n    return jax.devices('cpu')[0]\n"
    assert ast_lint.lint_source(ok, "vitax/serve/server.py") == []


def test_lint_mutable_default():
    src = "def f(xs=[], m={}):\n    return xs, m\n"
    assert _codes(ast_lint.lint_source(src, "vitax/data/loader.py")) == \
        ["VTX105", "VTX105"]


def test_lint_suppression_with_reason():
    src = ("import jax\n"
           "def f():\n"
           "    return jax.devices()[0]  "
           "# vtx: ignore[VTX104] test needs the live device list\n")
    assert ast_lint.lint_source(src, "vitax/serve/server.py") == []


def test_lint_bare_suppression_is_error():
    src = ("import jax\n"
           "def f():\n"
           "    return jax.devices()[0]  # vtx: ignore[VTX104]\n")
    codes = _codes(ast_lint.lint_source(src, "vitax/serve/server.py"))
    assert "VTX100" in codes  # bare suppression flagged
    assert "VTX104" in codes  # and it does NOT suppress


def test_lint_repo_is_clean():
    findings = ast_lint.lint_paths([os.path.join(REPO, "vitax")])
    assert findings == [], "\n".join(f.format() for f in findings)


def test_lint_cli(tmp_path):
    bad = tmp_path / "mod.py"
    bad.write_text("def f(xs=[]):\n    return xs\n")
    assert ast_lint.main([str(bad)]) == 1
    assert ast_lint.main([str(bad), "--json"]) == 1
    good = tmp_path / "ok.py"
    good.write_text("def f(xs=None):\n    return xs or []\n")
    assert ast_lint.main([str(good)]) == 0
