"""End-to-end data-contract evidence at scale (VERDICT r3 missing #3, the
part a CPU sandbox can prove): the FULL real-data path — an on-disk
ImageFolder of thousands of JPEGs, native C++ batch decode + torchvision-
parity augmentation, the rank-interleaved epoch-seeded sampler, the
prefetching sharded loader, and the compiled train step on the 8-device
mesh — must LEARN from a class-correlated pixel signal. Random-data smoke
tests prove plumbing; this proves the pipeline delivers label-consistent
tensors end to end (reference data contract: run_vit_training.py:30-96,
README.md:46-74)."""

import os

import jax
import numpy as np
import pytest
from PIL import Image

from vitax.config import Config
from vitax.data import native


def _make_imagefolder(root, n_classes, per_class_train, per_class_val,
                      side=72, seed=0):
    """Class k's images share a distinctive mean color + noise — learnable
    from mean-pooled patches, invariant to crop/flip augmentation."""
    rng = np.random.default_rng(seed)
    hues = rng.uniform(40, 215, size=(n_classes, 3))
    for split, per_class in (("train", per_class_train), ("val", per_class_val)):
        for k in range(n_classes):
            d = os.path.join(root, split, f"class_{k:02d}")
            os.makedirs(d)
            for i in range(per_class):
                arr = np.clip(
                    hues[k] + rng.normal(0, 30, size=(side, side, 3)),
                    0, 255).astype(np.uint8)
                Image.fromarray(arr).save(
                    os.path.join(d, f"{i:05d}.jpg"), quality=85)


@pytest.mark.slow
@pytest.mark.skipif(not native.available(),
                    reason="native library unavailable (no g++/libjpeg)")
def test_imagefolder_training_learns_at_scale(devices8, tmp_path):
    from vitax.train.loop import train

    n_classes, per_train, per_val = 10, 200, 20  # 2,200 JPEGs on disk
    root = str(tmp_path / "imagenet_synth")
    _make_imagefolder(root, n_classes, per_train, per_val)

    cfg = Config(
        data_dir=root, fake_data=False, num_classes=n_classes,
        image_size=32, patch_size=8, embed_dim=32, num_heads=2, num_blocks=2,
        batch_size=40, num_epochs=2, lr=3e-3, warmup_steps=10,
        log_step_interval=20, ckpt_dir=str(tmp_path / "ckpt"),
        ckpt_epoch_interval=99, test_epoch_interval=2, num_workers=2,
        dtype="float32",
    ).validate()
    state = train(cfg)

    # 2 epochs x (2000 // 40) = 100 optimizer steps ran over real decoded data
    assert int(jax.device_get(state.step)) == 100

    # the signal was learned: val accuracy far beyond chance (10%). The
    # color-mean signal is linearly separable, so even this tiny ViT should
    # be near-perfect; 50% is a loose flake-proof bound.
    from vitax.data.loader import build_datasets
    from vitax.parallel.mesh import build_mesh
    from vitax.train.loop import eval_on_val
    from vitax.train.state import build_optimizer, make_train_state
    from vitax.train.step import make_eval_step
    from vitax.models import build_model

    mesh = build_mesh(cfg)
    model = build_model(cfg)
    _, _, _, val_loader = build_datasets(cfg, mesh)
    tx, _ = build_optimizer(cfg, max_iteration=100)
    _, sspecs, _ = make_train_state(cfg, model, tx, mesh, jax.random.key(0),
                                    materialize=False)
    eval_step = make_eval_step(cfg, model, mesh, sspecs)
    try:
        accuracy, top5, n_correct, total = eval_on_val(
            cfg, val_loader, eval_step, state)
    finally:
        val_loader.close()
    assert total == 200  # 10 classes x 20, batch 40 -> 5 full batches
    assert top5 >= accuracy
    assert accuracy > 0.5, (
        f"val accuracy {accuracy:.2f} barely above chance — the data path "
        f"is delivering label-inconsistent tensors")
