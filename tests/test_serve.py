"""vitax.serve end-to-end on the 8-virtual-device CPU mesh: 2-step fake-data
train -> checkpoint -> engine load (Orbax + consolidated npz) -> dynamic
batcher (flush-by-size / flush-by-timeout) -> HTTP predict round-trip on an
ephemeral port -> zero recompiles after warmup -> serve.jsonl contract ->
serve_bench summary, plus the consolidate round-trip and serve-flag
validation satellites.
"""

import base64
import io
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from vitax.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_cfg(**kw):
    base = dict(
        image_size=16, patch_size=8, embed_dim=32, num_heads=2, num_blocks=2,
        num_classes=4, batch_size=16, dtype="float32", lr=1e-3, warmup_steps=2,
        serve_max_batch=4, serve_topk=3, max_batch_wait_ms=10.0, seed=0,
    )
    base.update(kw)
    return Config(**base).validate()


def post_json(url: str, payload: dict, timeout: float = 60.0) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


def post_bytes(url: str, body: bytes, content_type: str = "image/png",
               timeout: float = 60.0) -> dict:
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": content_type})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


def get_json(url: str, timeout: float = 30.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.load(resp)


def png_bytes(size: int = 20, seed: int = 0) -> bytes:
    from PIL import Image
    rng = np.random.default_rng(seed)
    arr = rng.integers(0, 256, size=(size, size, 3), dtype=np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr, "RGB").save(buf, "PNG")
    return buf.getvalue()


# --- the served stack: train -> checkpoint -> engine -> HTTP (module-scoped:
# warmup compiles every bucket once for all tests below) ---

@pytest.fixture(scope="module")
def served(devices8, tmp_path_factory):
    from vitax.serve import InferenceEngine, start_server, stop_server
    from vitax.train.loop import train

    root = tmp_path_factory.mktemp("serve")
    ckpt_dir = str(root / "ckpt")
    metrics_dir = str(root / "metrics")
    cfg = tiny_cfg(
        fake_data=True, num_epochs=1, steps_per_epoch=2, log_step_interval=1,
        ckpt_dir=ckpt_dir, ckpt_epoch_interval=1, test_epoch_interval=1,
        num_workers=2, eval_max_batches=1, metrics_dir=metrics_dir,
        serve_port=0,
    )
    train(cfg)  # 2 real optimizer steps; writes epoch_1
    assert os.path.isdir(os.path.join(ckpt_dir, "epoch_1"))

    engine = InferenceEngine.from_checkpoint(cfg, ckpt_dir, 1)
    engine.warmup()
    httpd, ctx = start_server(cfg, engine, port=0)
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield cfg, engine, url, metrics_dir
    stop_server(httpd, ctx)


# --- engine -----------------------------------------------------------------


def test_engine_buckets_and_warmup(served):
    _, engine, _, _ = served
    assert engine.buckets == (1, 2, 4)
    # AOT warmup compiled each bucket exactly once
    assert engine.compile_count == 3


def test_engine_predict_shapes_and_padding(served):
    cfg, engine, _, _ = served
    for n in (1, 2, 3, 4):
        ids, probs = engine.predict(
            np.zeros((n, cfg.image_size, cfg.image_size, 3), np.uint8))
        assert ids.shape == (n, engine.topk)
        assert probs.shape == (n, engine.topk)
        # top-k probs are descending and valid
        assert np.all(np.diff(probs, axis=1) <= 1e-6)
        assert np.all((probs >= 0) & (probs <= 1))
    # identical rows -> identical outputs regardless of bucket padding
    img = np.full((1, cfg.image_size, cfg.image_size, 3), 7, np.uint8)
    one = engine.predict(img)
    three = engine.predict(np.repeat(img, 3, axis=0))
    np.testing.assert_array_equal(one[0][0], three[0][2])
    np.testing.assert_allclose(one[1][0], three[1][2], rtol=1e-5)


def test_engine_zero_recompiles_after_warmup(served):
    """Mixed-size bursts execute precompiled buckets only: the compile count
    is pinned at len(buckets) and an unseen batch size raises instead of
    silently recompiling."""
    cfg, engine, _, _ = served
    before = engine.compile_count
    for n in (3, 1, 4, 2, 1, 3):
        engine.predict(
            np.zeros((n, cfg.image_size, cfg.image_size, 3), np.uint8))
    assert engine.compile_count == before == len(engine.buckets)
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        engine.predict(
            np.zeros((5, cfg.image_size, cfg.image_size, 3), np.uint8))


def test_engine_npz_round_trip_matches_checkpoint(served, tmp_path):
    """consolidate -> from_npz restores the exact param tree: same compiled
    program, same input => identical predictions (the regression test of the
    shared flatten/unflatten key convention)."""
    from vitax.checkpoint.consolidate import consolidate
    from vitax.serve import InferenceEngine

    cfg, engine, _, _ = served
    out = str(tmp_path / "full.npz")
    consolidate(cfg.ckpt_dir, 1, out)
    engine2 = InferenceEngine.from_npz(cfg, out)
    engine2.warmup()
    # exact round trip: every leaf bitwise-equal to the served params
    flat_a = jax.tree.leaves(engine.params)
    flat_b = jax.tree.leaves(engine2.params)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    rng = np.random.default_rng(3)
    img = rng.integers(0, 256,
                       size=(3, cfg.image_size, cfg.image_size, 3),
                       ).astype(np.uint8)
    ids_a, probs_a = engine.predict(img)
    ids_b, probs_b = engine2.predict(img)
    np.testing.assert_array_equal(ids_a, ids_b)
    np.testing.assert_allclose(probs_a, probs_b, rtol=1e-6)


# --- consolidation round-trip (satellite) -----------------------------------


def test_flatten_unflatten_round_trip():
    from vitax.checkpoint.consolidate import flatten_tree, unflatten_tree
    tree = {"params": {"blocks": {"attn": {"kernel": np.arange(6.0).reshape(2, 3)},
                                  "bias": np.zeros(3)},
                       "head": {"kernel": np.ones((3, 4))}}}
    flat = flatten_tree(tree)
    assert set(flat) == {"params/blocks/attn/kernel", "params/blocks/bias",
                         "params/head/kernel"}
    rebuilt = unflatten_tree(flat)
    assert jax.tree_util.tree_structure(tree) == \
        jax.tree_util.tree_structure(rebuilt)
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(rebuilt)):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("dtype", [None, "float32", "bfloat16", "int8",
                                   "float8_e4m3"])
def test_save_npz_dtype_round_trip(tmp_path, dtype):
    import ml_dtypes
    from vitax.checkpoint.consolidate import load_npz, save_npz
    flat = {"a/kernel": np.arange(6, dtype=np.float32).reshape(2, 3),
            "a/b": np.ones(3, np.float32),
            "step": np.asarray(7, np.int32)}
    out = str(tmp_path / f"x_{dtype}.npz")
    save_npz(out, flat, dtype=dtype)
    back = load_npz(out)
    assert set(back) == set(flat)
    if dtype == "bfloat16":
        assert back["a/kernel"].dtype == ml_dtypes.bfloat16
        np.testing.assert_allclose(
            back["a/kernel"].astype(np.float32), flat["a/kernel"], rtol=1e-2)
    elif dtype in ("int8", "float8_e4m3"):
        # generic load dequantizes back to f32 within half a quant step
        # (fp8 has ~2 mantissa bits -> coarser bound than the int8 grid)
        assert back["a/kernel"].dtype == np.float32
        qmax = 127.0 if dtype == "int8" else 240.0
        atol = float(np.abs(flat["a/kernel"]).max()) / qmax
        rtol = 0.0 if dtype == "int8" else 0.08
        np.testing.assert_allclose(back["a/kernel"], flat["a/kernel"],
                                   atol=atol, rtol=rtol)
        # the bias is not a matmul weight: untouched
        np.testing.assert_array_equal(back["a/b"], flat["a/b"])
    else:
        assert back["a/kernel"].dtype == np.float32
        np.testing.assert_array_equal(back["a/kernel"], flat["a/kernel"])
    # non-float leaves are never cast
    assert back["step"].dtype == np.int32 and int(back["step"]) == 7


def test_save_npz_fp8_raw_view_pin(tmp_path):
    """fp8 leaves store as a uint8 bit-view + manifest entry and load back
    EXACTLY (bit-for-bit) through load_npz_raw — the serve load path.

    The npz container has no fp8 dtype, so the export convention is the
    same bit-view trick the bf16 path uses with uint16: a wrong view dtype
    or a dropped manifest entry would silently reinterpret the bytes."""
    import ml_dtypes
    from vitax.checkpoint.consolidate import load_npz_raw, save_npz
    rng = np.random.default_rng(0)
    flat = {"blocks/fc1/kernel": rng.standard_normal((8, 16)).astype(
                np.float32),
            "blocks/fc1/bias": np.ones(16, np.float32)}
    out = str(tmp_path / "fp8.npz")
    save_npz(out, flat, dtype="float8_e4m3")
    raw, scales, manifest = load_npz_raw(out)
    assert manifest == {"blocks/fc1/kernel": "float8_e4m3"}
    assert raw["blocks/fc1/kernel"].dtype == ml_dtypes.float8_e4m3
    assert set(scales) == {"blocks/fc1/kernel"}
    assert scales["blocks/fc1/kernel"].dtype == np.float32
    # the stored payload IS the uint8 view of the fp8 leaf: re-deriving the
    # quantization host-side reproduces it bit-for-bit
    s = scales["blocks/fc1/kernel"]
    want = (flat["blocks/fc1/kernel"] / s).astype(ml_dtypes.float8_e4m3)
    np.testing.assert_array_equal(
        raw["blocks/fc1/kernel"].view(np.uint8), want.view(np.uint8))
    # bias rides along untouched
    np.testing.assert_array_equal(raw["blocks/fc1/bias"],
                                  flat["blocks/fc1/bias"])
    # determinism: a second export of the same tree is byte-identical
    out2 = str(tmp_path / "fp8_b.npz")
    save_npz(out2, flat, dtype="float8_e4m3")
    raw2, _, _ = load_npz_raw(out2)
    np.testing.assert_array_equal(
        raw["blocks/fc1/kernel"].view(np.uint8),
        raw2["blocks/fc1/kernel"].view(np.uint8))


# --- batcher (engine-free: a fake predict_fn pins flush semantics) ----------


def _fake_predict(calls, delay_s=0.0):
    def predict(images):
        if delay_s:
            time.sleep(delay_s)
        calls.append(images.shape[0])
        n = images.shape[0]
        return (np.tile(np.arange(3, dtype=np.int32), (n, 1)),
                np.tile(np.array([0.5, 0.3, 0.2], np.float32), (n, 1)))
    return predict


def test_batcher_flush_by_size():
    """max_batch simultaneous submissions flush as ONE batch well before the
    (deliberately huge) deadline."""
    from vitax.serve import DynamicBatcher
    calls = []
    b = DynamicBatcher(_fake_predict(calls), max_batch=4,
                       max_wait_ms=60_000.0)
    try:
        t0 = time.time()
        futs = [b.submit(np.zeros((4, 4, 3), np.uint8)) for _ in range(4)]
        results = [f.result(timeout=30) for f in futs]
        assert time.time() - t0 < 30  # did not wait out the minute deadline
        assert calls == [4]
        assert all(r.batch_size == 4 for r in results)
        assert all(r.classes.shape == (3,) for r in results)
    finally:
        b.close()


def test_batcher_flush_by_timeout():
    """A lone request flushes at the deadline, not at bucket-full."""
    from vitax.serve import DynamicBatcher
    calls = []
    b = DynamicBatcher(_fake_predict(calls), max_batch=4, max_wait_ms=50.0)
    try:
        t0 = time.time()
        r = b.submit(np.zeros((4, 4, 3), np.uint8)).result(timeout=30)
        elapsed = time.time() - t0
        assert calls == [1]
        assert r.batch_size == 1
        assert elapsed >= 0.04  # waited (most of) the deadline for company
    finally:
        b.close()


def test_batcher_error_propagates_to_futures():
    from vitax.serve import DynamicBatcher

    def boom(images):
        raise RuntimeError("engine fell over")

    b = DynamicBatcher(boom, max_batch=2, max_wait_ms=5.0)
    try:
        fut = b.submit(np.zeros((4, 4, 3), np.uint8))
        with pytest.raises(RuntimeError, match="fell over"):
            fut.result(timeout=30)
        # the worker survived the exception and still serves
        assert b.submit is not None and b.queue_depth() == 0
    finally:
        b.close()


# --- HTTP -------------------------------------------------------------------


def test_http_predict_round_trip(served):
    cfg, engine, url, _ = served
    # raw image bytes
    resp = post_bytes(url + "/predict", png_bytes(seed=1))
    assert len(resp["classes"]) == engine.topk
    assert len(resp["probs"]) == engine.topk
    assert all(0 <= c < cfg.num_classes for c in resp["classes"])
    assert resp["probs"] == sorted(resp["probs"], reverse=True)
    # base64 JSON with a per-request topk
    resp2 = post_json(url + "/predict",
                      {"image": base64.b64encode(png_bytes(seed=2)).decode(),
                       "topk": 2})
    assert len(resp2["classes"]) == 2 and len(resp2["probs"]) == 2


def test_http_mixed_burst_zero_recompiles(served):
    """A concurrent burst of requests exercises multiple buckets through the
    batcher with zero recompiles (the acceptance-criteria check)."""
    cfg, engine, url, _ = served
    before = engine.compile_count
    results, errors = [], []
    lock = threading.Lock()

    def worker(seed):
        try:
            r = post_bytes(url + "/predict", png_bytes(seed=seed))
            with lock:
                results.append(r)
        except Exception as e:  # noqa: BLE001
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(10)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert len(results) == 10
    assert engine.compile_count == before  # zero recompiles under load
    # the burst actually batched: fewer flushes than requests
    metrics = get_json(url + "/metrics")
    assert metrics["requests_total"] >= 10
    assert metrics["compile_count"] == before


def test_http_healthz_and_metrics(served):
    _, engine, url, _ = served
    health = get_json(url + "/healthz")
    assert health["status"] == "ok"
    assert health["buckets"] == list(engine.buckets)
    metrics = get_json(url + "/metrics")
    for key in ("requests_total", "errors_total", "requests_per_sec",
                "latency_s_p50", "latency_s_p95", "latency_s_p99",
                "batch_occupancy_mean", "queue_depth"):
        assert key in metrics, key


def test_http_bad_requests(served):
    _, _, url, _ = served
    with pytest.raises(urllib.error.HTTPError) as e:
        post_bytes(url + "/predict", b"not an image")
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        post_bytes(url + "/nope", png_bytes())
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        post_json(url + "/predict",
                  {"image": base64.b64encode(png_bytes()).decode(),
                   "topk": 99})
    assert e.value.code == 400


# --- serve.jsonl contract + bench -------------------------------------------

# every serve_request record must carry these (vitax/serve/server.py
# REQUIRED_SERVE_KEYS + the Recorder envelope)
ENVELOPE_KEYS = ("schema", "time", "kind")


def test_serve_jsonl_contract(served):
    from vitax.serve import REQUIRED_SERVE_KEYS
    _, _, url, metrics_dir = served
    post_bytes(url + "/predict", png_bytes(seed=9))  # at least one record
    path = os.path.join(metrics_dir, "serve.jsonl")
    assert os.path.exists(path)
    records = [json.loads(line) for line in open(path) if line.strip()]
    kinds = {r["kind"] for r in records}
    assert "serve_start" in kinds and "serve_request" in kinds
    reqs = [r for r in records if r["kind"] == "serve_request"]
    for rec in reqs:
        for key in ENVELOPE_KEYS + REQUIRED_SERVE_KEYS:
            assert key in rec, (key, rec)
        assert rec["schema"] == 1
        assert rec["batch_size"] <= rec["bucket"]
        assert rec["queue_wait_s"] <= rec["latency_s"]


def test_serve_bench_reports(served):
    """tools/serve_bench.py --json contract: throughput + p50/p95/p99 from
    both the client loop and the server's serve.jsonl records."""
    _, _, url, metrics_dir = served
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import serve_bench
    finally:
        sys.path.pop(0)
    summary = serve_bench.run_bench(
        url, concurrency=4, requests_per_worker=3, image_size=20,
        timeout=60.0, serve_jsonl=os.path.join(metrics_dir, "serve.jsonl"))
    assert summary["completed"] == 12 and summary["errors"] == 0
    assert summary["throughput_rps"] > 0
    for key in ("latency_s_p50", "latency_s_p95", "latency_s_p99"):
        assert summary[key] > 0
        assert summary["server"][key] > 0
    assert summary["server"]["records"] >= 12
    assert 0 < summary["server"]["batch_occupancy_mean"] <= 1.0
    # --json emits one parseable object
    json.dumps(summary)


# --- eval top-5 + telemetry (satellite) -------------------------------------


def test_eval_event_in_metrics_jsonl(served):
    """The fixture's training run had --metrics_dir + test_epoch_interval=1,
    so eval_on_val must have emitted a kind:"eval" event (epoch, top1, top5,
    n) into metrics.jsonl — and metrics_report must surface it."""
    _, _, _, metrics_dir = served
    path = os.path.join(metrics_dir, "metrics.jsonl")
    assert os.path.exists(path)
    evals = [json.loads(line) for line in open(path)
             if line.strip() and '"eval"' in line]
    evals = [r for r in evals if r.get("kind") == "eval"]
    assert evals, "train() with test_epoch_interval=1 emitted no eval event"
    ev = evals[-1]
    assert ev["epoch"] == 1
    assert 0.0 <= ev["top1"] <= ev["top5"] <= 1.0
    assert ev["n"] > 0

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import metrics_report
    finally:
        sys.path.pop(0)
    summary = metrics_report.summarize(path)
    assert summary["eval_last"] == {k: ev[k]
                                    for k in ("epoch", "top1", "top5", "n")}


# --- config validation (satellite) ------------------------------------------


@pytest.mark.parametrize("kw,match", [
    (dict(eval_max_batches=-1), "eval_max_batches"),
    (dict(serve_port=-1), "serve_port"),
    (dict(serve_port=70000), "serve_port"),
    (dict(serve_max_batch=0), "serve_max_batch"),
    (dict(serve_max_batch=3), "power of two"),
    (dict(max_batch_wait_ms=-1.0), "max_batch_wait_ms"),
    (dict(serve_topk=0), "serve_topk"),
    (dict(serve_topk=-3), "serve_topk"),
])
def test_config_serve_validation_rejects(kw, match):
    with pytest.raises(AssertionError, match=match):
        tiny_cfg(**kw)


def test_config_serve_defaults_valid():
    cfg = Config().validate()
    assert cfg.serve_port == 8000 and cfg.serve_max_batch == 8
    assert cfg.serve_topk == 5 and cfg.max_batch_wait_ms == 5.0
