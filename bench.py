#!/usr/bin/env python3
"""vitax benchmark: images/sec/chip + MFU for the training step.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
(vs_baseline is null when nothing comparable exists: no stored baseline, or a
knob set differing from the stored one).
Fail-soft and outage-proof: backend init is probed in fresh subprocesses on a
wait-for-chip loop (one probe per ~60s, up to --init_patience seconds; a hung
probe is killed, never poisons the parent) plus a global watchdog that
stretches by the init wait. Every error path still emits the JSON contract
with an "error" field AND the preset's last chip-measured numbers
("last_measured", from BASELINE_MEASURED.json) — a down TPU must never cost
the round its data point.

Default config is ViT-L/14 (BASELINE.json config 3 shape) sized for one chip;
--preset tiny|b16|l14|10b selects others; --preset data benchmarks the host
input pipeline (native C++ vs PIL decode+augment) and needs no accelerator.
FLOP accounting: matmul FLOPs (patchify + qkv/proj/mlp/head) plus attention
score/value einsums, x3 for fwd+bwd (the standard 6ND convention); remat
recompute is NOT counted as useful work (true MFU).

--write_baseline persists the measured numbers into BASELINE_MEASURED.json
(merged per preset); subsequent runs report vs_baseline against that file.
"""

import argparse
import datetime
import json
import os
import subprocess
import sys
import threading
import time

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BASELINE_MEASURED.json")

# FLOPs/MFU accounting is shared with the training-loop telemetry
# (vitax/telemetry/flops.py) so bench MFU and the run-log MFU are the same
# number; the names stay importable from bench (tools/profile_step.py does).
from vitax.telemetry.flops import (  # noqa: E402
    PEAK_TFLOPS, detect_peak_tflops, model_flops_per_image)

# The perf-knob surface (argparse group / resolved payload) is shared with
# tools/profile_step.py, tools/aot_topology.py and tools/autotune.py —
# stdlib-only imports, safe before backend selection.
from vitax.tune.knobs import (  # noqa: E402
    add_knob_args, knob_payload, knobs_from_args)


def apply_preset_file(args, n_dev: int) -> None:
    """--preset_file: fill every knob still at its sentinel default from a
    committed autotune preset (presets/<model>_<topology>.json). Explicit
    CLI flags win; the preset's RESOLVED knobs pin everything else, so the
    run reproduces the winning knob set exactly (TUNED.json defaults cannot
    leak underneath). Needs the live device count: batch is stored per-chip."""
    if not getattr(args, "preset_file", ""):
        return
    from vitax.tune.preset import apply_preset_to_args, load_preset
    preset = load_preset(args.preset_file)
    applied = apply_preset_to_args(preset, args, n_dev)
    print(f"bench: preset {args.preset_file} "
          f"({preset['model_preset']}@{preset['topology']}) applied "
          f"{applied}", file=sys.stderr, flush=True)

_emitted = threading.Lock()

# --metrics_dir: also append the emitted payload to <dir>/bench.jsonl
# (schema-1 telemetry event, kind="bench"). Fail-soft by contract: an
# unwritable dir warns and never sinks the measured number.
_metrics_dir = ""


def _append_metrics_record(result: dict) -> None:
    if not _metrics_dir:
        return
    try:
        os.makedirs(_metrics_dir, exist_ok=True)
        record = dict(result, schema=1, kind="bench", time=time.time())
        with open(os.path.join(_metrics_dir, "bench.jsonl"), "a",
                  encoding="utf-8") as f:
            f.write(json.dumps(record, default=str) + "\n")
    except OSError as e:
        print(f"bench: --metrics_dir {_metrics_dir!r} is not writable "
              f"({e}); continuing without the JSONL record",
              file=sys.stderr, flush=True)


def emit(result: dict) -> None:
    """Print the ONE JSON line, exactly once per process."""
    if _emitted.acquire(blocking=False):
        print(json.dumps(result), flush=True)
        _append_metrics_record(result)


def emit_error(metric: str, error: str, unit: str = "images/sec/chip",
               preset: str = None, extra: dict = None) -> None:
    """Error JSON still carries the last chip-measured numbers for the preset
    (VERDICT r3 item 1): a dead chip must never yield a bare 0.0."""
    result = {"metric": metric, "value": 0.0, "unit": unit,
              "vs_baseline": None, "error": error}
    if preset:
        entry = read_baseline().get(preset)
        if entry:
            result["last_measured"] = entry
    if extra:
        result.update(extra)
    emit(result)


def read_baseline() -> dict:
    if os.path.exists(BASELINE_FILE):
        try:
            with open(BASELINE_FILE) as f:
                return json.load(f)
        except (json.JSONDecodeError, OSError):
            return {}
    return {}


def write_baseline(preset: str, entry: dict) -> None:
    base = read_baseline()
    entry = dict(entry, measured_at=datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds"))
    base[preset] = entry
    tmp = BASELINE_FILE + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:  # tmp+rename: a watchdog os._exit mid-write
        json.dump(base, f, indent=2, sort_keys=True)  # must not truncate the
        f.write("\n")                                 # accumulated baselines
    os.replace(tmp, BASELINE_FILE)


def _probe_backend_subprocess(timeout: float):
    """Probe backend init in a FRESH subprocess.

    A hung PJRT transport (dead axon tunnel — the round-1 and round-3 failure
    mode, BENCH_r01/r03.json) poisons the process that attempted it: the C
    call holds the backend lock, so in-process retry is pointless. A killed
    subprocess costs nothing, so the parent can keep probing until the chip
    returns. Returns ((n_devices, device_kind), None) or (None, error_str).
    """
    code = (
        "import json, sys\n"
        "from vitax.platform import force_cpu_if_requested\n"
        "force_cpu_if_requested()\n"  # probe what the parent will init
        "import jax\n"
        "out = {'n': jax.device_count(),"
        " 'kind': jax.devices()[0].device_kind}\n"
        "sys.stdout.write('\\n' + json.dumps(out) + '\\n')\n"
    )
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout,
                           cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        return None, f"probe hung >{timeout:.0f}s (killed)"
    except OSError as e:
        return None, f"probe spawn failed: {e}"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-1:] or ["<no stderr>"]
        return None, f"probe exited rc={r.returncode}: {tail[0][:300]}"
    for line in reversed((r.stdout or "").strip().splitlines()):
        try:
            out = json.loads(line)
            return (out["n"], out["kind"]), None
        except (json.JSONDecodeError, KeyError, TypeError):
            continue  # TypeError: a stray JSON-scalar line (e.g. "3")
    return None, "probe produced no parseable output"


# Seconds init_backend spent waiting for the chip; the watchdog adds this to
# its deadline so patience spent surviving an outage can't kill the run.
_init_waited = 0.0


def init_backend(metric: str, probe_timeout: float, init_patience: float,
                 preset: str = None):
    """Initialize the JAX backend fail-soft, outage-proof.

    Probes init in fresh subprocesses on a bounded wait-for-chip loop (one
    probe per ~probe_interval, up to init_patience seconds total), then — and
    only then — initializes in-process. A healthy chip pays one duplicate
    init (~10-20s, the probe subprocess) — deliberate: the parent process
    must stay virgin until a probe proves the tunnel healthy, because a hung
    in-process init leaves the backend lock held forever (the r1/r3 outage
    mode). A fast-failing in-process init (tunnel flap after a good probe)
    loops back to probing while patience remains. Returns
    (device_count, device_kind) or emits an error JSON (carrying
    last_measured + retry evidence) and exits 0.
    """
    global _init_waited
    from vitax.platform import force_cpu_if_requested, is_cpu_forced
    if is_cpu_forced():
        # pinned to host CPU: the hung-tunnel failure mode can't occur — skip
        # the subprocess probe and init directly (CI/test/dev runs)
        force_cpu_if_requested()
        import jax
        return jax.device_count(), jax.devices()[0].device_kind
    probe_interval = 60.0
    t_start = time.monotonic()
    deadline = t_start + max(init_patience, probe_timeout)
    attempt = 0
    last_err = "unknown"

    def give_up(stage: str):
        waited = time.monotonic() - t_start
        emit_error(
            metric,
            f"backend unavailable after {attempt} probe attempts over "
            f"{waited:.0f}s (patience {init_patience:.0f}s); {stage}: {last_err}",
            preset=preset,
            extra={"probe_attempts": attempt,
                   "probe_waited_sec": round(waited, 1)})
        os._exit(0)

    def credit(upcoming: float):
        # publish live progress BEFORE each blocking interval (probe, sleep):
        # the watchdog stretches by this, so patience spent waiting out an
        # outage can't convert into a watchdog kill mid-wait. Pre-crediting
        # the upcoming block is safe — on success the exact value is set.
        global _init_waited
        _init_waited = (time.monotonic() - t_start) + upcoming

    while True:
        attempt += 1
        t_probe = time.monotonic()
        credit(probe_timeout)
        ok, err = _probe_backend_subprocess(probe_timeout)
        if ok is None:
            last_err = err
            print(f"bench: backend probe {attempt} failed ({err}); "
                  f"{deadline - time.monotonic():.0f}s of patience left",
                  file=sys.stderr, flush=True)
            # next probe no sooner than probe_interval after the last one
            # STARTED (a hung probe already burned its interval)
            wait = max(0.0, probe_interval - (time.monotonic() - t_probe))
            if time.monotonic() + wait >= deadline:
                give_up("last probe")
            credit(wait)
            time.sleep(wait)
            continue

        # chip answered a fresh-process probe; init in-process (guarded — the
        # tunnel may flap between the probe and this init, and a hung
        # in-process init is unrecoverable by design)
        import jax
        result = {}

        def init():
            try:
                result["n"] = jax.device_count()
                result["kind"] = jax.devices()[0].device_kind
            except Exception as e:  # noqa: BLE001 — fail-soft by contract
                result["err"] = f"{type(e).__name__}: {e}"

        t = threading.Thread(target=init, daemon=True)
        t.start()
        credit(probe_timeout)
        t.join(probe_timeout)
        if "n" in result:
            _init_waited = time.monotonic() - t_start
            return result["n"], result["kind"]
        if t.is_alive():
            # hung in-process: the backend lock is held forever — no retry
            # is possible in this process, whatever patience remains
            last_err = f"in-process init hung >{probe_timeout:.0f}s"
            give_up(f"after good probe {attempt}")
        # fast in-process failure (flap): clear the cached failure and loop
        # back to probing while patience remains
        last_err = f"in-process init failed after good probe: " \
                   f"{result.get('err', 'unknown')}"
        print(f"bench: {last_err}; re-probing", file=sys.stderr, flush=True)
        try:
            jax.extend.backend.clear_backends()
        except Exception:  # noqa: BLE001
            pass
        if time.monotonic() + probe_interval >= deadline:
            give_up("last in-process attempt")
        credit(probe_interval)
        time.sleep(probe_interval)


def train_presets(n_dev: int) -> dict:
    """Benchmark model shapes (shared with tools/profile_step.py so traces
    explain exactly the configs the bench measures)."""
    return {
        "tiny": dict(image_size=224, patch_size=16, embed_dim=192, num_heads=3,
                     num_blocks=12, batch_size=64 * n_dev),
        # BASELINE.json config 2 shape (ViT-B/16, pure-DP benchmark)
        "b16": dict(image_size=224, patch_size=16, embed_dim=768, num_heads=12,
                    num_blocks=12, batch_size=64 * n_dev),
        # ViT-B/16 with a top-1 Switch MoE MLP (8 experts) in every block:
        # measures the routing/dispatch overhead vs the dense b16 row (per-
        # token useful FLOPs are identical under top-1 routing, so the MFU
        # accounting below stays valid; router FLOPs are negligible)
        "b16_moe": dict(image_size=224, patch_size=16, embed_dim=768,
                        num_heads=12, num_blocks=12, batch_size=64 * n_dev,
                        moe_experts=8),
        "l14": dict(image_size=224, patch_size=14, embed_dim=1024, num_heads=16,
                    num_blocks=24, batch_size=32 * n_dev),
        "10b": dict(image_size=224, patch_size=14, embed_dim=5120, num_heads=32,
                    num_blocks=32, batch_size=8 * n_dev),
        # largest 10B-family slice that fits one v5e chip: same 5120-dim
        # blocks, depth cut to 2. Depth 4 does NOT fit — measured 15.2 GB f32
        # state + 10.2 GB temps (tests/test_memory_analysis.py::
        # test_10b_slice_fits_single_chip_hbm holds the preset to the limit).
        # Batch 64/chip is the measured single-chip throughput frontier
        # (MFU 0.579 on v5e; 96 OOMs — see BASELINE.md's frontier table; the
        # flagship's pod operating point of 8/chip measures 73-79 img/s).
        "10b_slice": dict(image_size=224, patch_size=14, embed_dim=5120,
                          num_heads=32, num_blocks=2, batch_size=64 * n_dev),
    }


TUNED_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "TUNED.json")


def _tuned(preset: str) -> dict:
    """Measured per-preset knob winners (tools/apply_ladder.py writes
    TUNED.json from the chip watcher's ladder results, so defaults track
    the hardware measurements without a code edit)."""
    try:
        with open(TUNED_FILE) as f:
            return json.load(f).get(preset, {})
    except (OSError, json.JSONDecodeError):
        return {}


def default_scan_blocks(preset: str, allow_tuned: bool = True) -> bool:
    """Per-preset scan-vs-unrolled default: the TUNED.json winner when the
    ladder has been measured; else l14 measured 250.1 img/s/chip fully
    unrolled vs 194.3 under lax.scan on v5e (batch 32, dots_attn_saveable —
    the scan's per-block dus-stacking caps wgrad fusions at 85-100 TF/s vs
    164+ unconstrained), so l14 defaults to the unrolled path and other
    presets keep the scan. allow_tuned=False pins the pre-TUNED fallback."""
    t = _tuned(preset) if allow_tuned else {}
    if "scan_blocks" in t:
        return bool(t["scan_blocks"])
    return preset != "l14"


def default_scan_unroll(preset: str, allow_tuned: bool = True) -> int:
    """Per-preset scan unroll (only meaningful when the scan path is used):
    the TUNED.json winner when measured, else 1."""
    t = _tuned(preset) if allow_tuned else {}
    return int(t.get("scan_unroll", 1))


def default_remat_window(preset: str, allow_tuned: bool = True) -> int:
    """Per-preset remat window (the group-remat wgrad experiment): the
    TUNED.json winner when measured, else the family fallback. The 10B
    family keeps the none_saveable scan (it cannot unroll its residuals
    away) and its single-chip slice measured +25% from window-2 group
    remat (LADDER_r04.jsonl: 145.5 vs 116.3 img/s/chip) — the full
    flagship preset inherits that measured family winner; everything else
    defaults to per-block remat (0)."""
    t = _tuned(preset) if allow_tuned else {}
    if "remat_window" in t:
        return int(t["remat_window"])
    # measured-winner class default, so it is gated on allow_tuned exactly
    # like TUNED entries: with an explicit A/B knob pinning the others
    # (allow_tuned=False), the window must fall back to 0 — a window-2
    # default would contradict e.g. --no_grad_ckpt or --no_scan_blocks and
    # trip validate() asserts the user never opted into.
    # "10b_slice" ONLY (not the 32-block flagship): the +25% was measured on
    # the depth-2 slice where window 2 spans the whole model; the flagship's
    # single-chip fit depends on minimal none_saveable residency, so it
    # keeps 0 until a window-2 run is measured at its shape (ADVICE r4)
    return 2 if (allow_tuned and preset == "10b_slice") else 0


def resolve_bench_knobs(scan_blocks, scan_unroll: int, remat_window: int,
                        remat_policy, preset: str,
                        other_explicit: bool = False):
    """Resolve the full (scan_blocks, scan_unroll, remat_window,
    remat_policy) knob set from CLI values + per-preset defaults. Shared
    with tools/profile_step.py so traces explain exactly the configs the
    bench measures.

    ONE rule keeps A/Bs pure: tuned defaults (TUNED.json winners) apply
    ONLY when NO knob was given explicitly. Any explicit knob pins every
    other default to its pre-TUNED fallback, so an A/B run differs from
    the historical reference by exactly the knobs on its command line —
    never by a default that TUNED flipped since.

    remat_window: -1 = unset; 0 = explicit per-block remat; >1 = the
    windowed-remat experiment, which forces the scan path even for presets
    whose measured default is unrolled (l14)."""
    explicit = (scan_blocks is not None or bool(scan_unroll)
                or remat_window >= 0 or remat_policy is not None
                or other_explicit)  # any A/B lever: --no_grad_ckpt,
    # --no_flash_attention, --batch_size — tuned knobs must not leak into
    # (or crash: remat_window>1 needs grad_ckpt) a pure-knob comparison
    tuned_ok = not explicit
    if remat_window < 0:
        remat_window = default_remat_window(preset, allow_tuned=tuned_ok)
    if remat_policy is None:
        remat_policy = default_remat_policy(preset, allow_tuned=tuned_ok)
    if remat_window > 1:
        assert scan_blocks is not False, (
            "--remat_window needs the scan path (drop --no_scan_blocks)")
        assert scan_unroll in (0, 1), (
            "--remat_window subsumes --scan_unroll (the window IS the "
            "unrolled group); drop one of the two")
        # pin the unroll (Config.validate rejects the combination)
        return True, 1, remat_window, remat_policy
    assert not (scan_blocks is False and scan_unroll), (
        "--no_scan_blocks contradicts --scan_unroll (unroll is a scan knob)")
    if scan_blocks is None:
        # an explicit --scan_unroll is a request for the scan path
        scan_blocks = (True if scan_unroll
                       else default_scan_blocks(preset, allow_tuned=tuned_ok))
    if not scan_unroll:
        scan_unroll = default_scan_unroll(preset, allow_tuned=tuned_ok)
    return scan_blocks, scan_unroll, remat_window, remat_policy


def default_remat_policy(preset: str, allow_tuned: bool = True) -> str:
    """Per-preset remat default: the TUNED.json winner's policy when the
    ladder has been measured (a win under a non-default policy must flip the
    policy along with the scan knobs); else measured on v5e l14:
    dots_attn_saveable 192.9 > dots_saveable 190.2 > none_saveable
    img/s/chip; the 10B flagship keeps none_saveable — minimal HBM residency
    is what makes it fit. allow_tuned=False pins the pre-TUNED fallback
    (explicit knob A/Bs must differ from their reference by ONE knob)."""
    if allow_tuned:
        tuned = _tuned(preset).get("remat_policy")
        if tuned:
            return tuned
    return "none_saveable" if preset.startswith("10b") else "dots_attn_saveable"


def _write_random_jpegs(dir_path: str, n: int, rng):
    """The shared synthetic corpus both data benches measure on (280-500px
    random-content JPEGs, quality 90): one recipe keeps their numbers
    comparable. Returns [(path, side), ...]."""
    import numpy as np
    from PIL import Image
    out = []
    for i in range(n):
        side = int(rng.integers(280, 500))
        arr = rng.integers(0, 256, size=(side, side, 3), dtype=np.uint8)
        p = os.path.join(dir_path, f"img_{i:05d}.jpg")
        Image.fromarray(arr).save(p, quality=90)
        out.append((p, side))
    return out


def counter_rate(work, min_time: float = 0.5) -> float:
    """Counts/sec of a pure-Python spin thread while `work()` runs repeatedly
    on the calling thread for >= min_time — the GIL-release microbenchmark
    shared by the data_scaling bench and tests/test_native.py. A C call that
    drops the GIL lets the counter timeslice (~0.5x idle on one core); a
    held GIL pins it near zero."""
    box = {"n": 0, "stop": False}

    def spin():
        n = 0
        while not box["stop"]:
            n += 1
        box["n"] = n

    t = threading.Thread(target=spin, daemon=True)
    t.start()
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < min_time:
        work()
    dt = time.perf_counter() - t0
    box["stop"] = True
    t.join()
    return box["n"] / dt


def bench_data_pipeline(args) -> None:
    """Host input-pipeline throughput: native C++ batch decode+augment vs the
    threaded-PIL fallback, on synthetic JPEGs (VERDICT round-1 item 7 — proves
    SURVEY section 7 hard-part #3). Accelerator-free."""
    import tempfile

    import numpy as np
    from PIL import Image

    from vitax.data.imagefolder import ImageFolderDataset
    from vitax.data.transforms import train_transform

    if not _native_available():
        emit_error("host data pipeline images/sec (native C++ decode+augment)",
                   "native library unavailable (C++ toolchain missing or "
                   "build failed)", unit="images/sec", preset="data")
        return

    rng = np.random.default_rng(0)
    n_images = args.data_images
    batch = args.batch_size or 256
    if not args.data_threads:
        args.data_threads = os.cpu_count() or 1
    with tempfile.TemporaryDirectory() as root:
        cls = os.path.join(root, "class0")
        os.makedirs(cls)
        _write_random_jpegs(cls, n_images, rng)

        transform = train_transform(image_size=224, seed=0)

        def run(use_native: bool) -> float:
            ds = ImageFolderDataset(root, transform, use_native=use_native)
            idx = [i % n_images for i in range(batch)]
            ds.load_batch(idx[: min(16, batch)])  # warm caches / native build
            t0 = time.perf_counter()
            reps = max(1, args.steps // 10)
            for _ in range(reps):
                ds.load_batch(idx, n_threads=args.data_threads)
            return batch * reps / (time.perf_counter() - t0)

        native_ips = run(True)
        pil_ips = run(False)

    baseline = read_baseline()
    base = baseline.get("data", {})
    vs = (round(native_ips / base["native_images_per_sec"], 4)
          if base.get("native_images_per_sec") else None)
    if args.write_baseline:
        # the data->train link (VERDICT round-2 weakness 6): for every train
        # preset already measured, record whether ONE host's native pipeline
        # keeps ALL of that host's chips fed (ratio > 1 = never input-bound;
        # the host must supply images_per_sec_chip x local chip count)
        feeds = {}
        for preset, entry in baseline.items():
            ips_chip = entry.get("images_per_sec_chip") if isinstance(
                entry, dict) else None
            if ips_chip:
                host_consumption = ips_chip * entry.get("n_devices", 1)
                feeds[preset] = round(native_ips / host_consumption, 2)
        write_baseline("data", {
            "native_images_per_sec": round(native_ips, 1),
            "pil_images_per_sec": round(pil_ips, 1),
            "speedup": round(native_ips / pil_ips, 2) if pil_ips else 0.0,
            "threads": args.data_threads,
            "feed_ratio_vs_train_preset": feeds,
        })
    emit({
        "metric": f"host data pipeline images/sec (native C++ decode+augment, "
                  f"{args.data_threads} threads; PIL fallback={pil_ips:.0f})",
        "value": round(native_ips, 1),
        "unit": "images/sec",
        "vs_baseline": vs,
    })


def bench_data_scaling(args) -> None:
    """Decode-path scaling evidence (VERDICT r3 item 8), accelerator-free:

    1. thread ladder — repeated native batch decode+augment at n_threads in
       {1, 2, 4, ...} up to 2x the host's cores. On a 1-core host (this CI
       image) the ladder is honestly flat — the recorded host_cpus makes
       that caveat explicit in the JSON; run on a many-core host to see the
       C++ pool scale.
    2. GIL-release proof — a pure-Python counter thread runs while the main
       thread decodes. ctypes CDLL calls drop the GIL for the duration of
       the C call, so the counter must keep advancing at a healthy fraction
       of its idle rate even on ONE core (OS timeslicing); a GIL-holding
       decode would freeze it near zero. This is the contention property
       that makes the loader's thread-pool design valid, provable without
       multiple cores.
    """
    import tempfile
    import numpy as np

    if not _native_available():
        emit_error("host decode thread-scaling (native C++)",
                   "native library unavailable", unit="images/sec",
                   preset="data_scaling")
        return

    from vitax.data import native
    from vitax.data.transforms import train_transform

    rng = np.random.default_rng(0)
    n_images = min(args.data_images, 128)
    cores = os.cpu_count() or 1
    with tempfile.TemporaryDirectory() as root:
        transform = train_transform(image_size=224, seed=0)
        corpus = _write_random_jpegs(root, n_images, rng)
        paths = [p for p, _ in corpus]
        params = [transform.native_params(side, side, i)
                  for i, (_, side) in enumerate(corpus)]

        def ladder_point(n_threads: int) -> float:
            native.process_batch(paths[:16], params[:16], 224, 0,
                                 n_threads=n_threads)  # warm
            t0 = time.perf_counter()
            reps = 3
            for _ in range(reps):
                _, failed = native.process_batch(paths, params, 224, 0,
                                                 n_threads=n_threads)
                assert not failed, failed
            return n_images * reps / (time.perf_counter() - t0)

        threads = [1, 2, 4]
        while threads[-1] < 2 * cores and threads[-1] < 64:
            threads.append(threads[-1] * 2)
        ladder = {t: round(ladder_point(t), 1) for t in threads}

        # --- GIL-release proof (counter_rate is shared with
        # tests/test_native.py::test_decode_releases_gil) ---
        idle = counter_rate(lambda: time.sleep(0.05))
        during_batch = counter_rate(
            lambda: native.process_batch(paths, params, 224, 0, n_threads=1))
        during_single = counter_rate(
            lambda: native.process_file(paths[0], params[0], 224, 0))
        gil = {
            "counter_rate_idle": round(idle),
            "counter_rate_during_batch_decode": round(during_batch),
            "counter_rate_during_single_decode": round(during_single),
            # on 1 core a GIL-free C call timeslices with the counter
            # (ratio ~0.5); a GIL-holding call would pin this near 0
            "batch_ratio": round(during_batch / idle, 3) if idle else 0.0,
            "single_ratio": round(during_single / idle, 3) if idle else 0.0,
        }

    best = max(ladder.values())
    base = read_baseline().get("data_scaling", {})
    base_best = (max(base.get("images_per_sec_by_threads", {}).values(),
                     default=None)
                 if base.get("host_cpus") == cores else None)  # like-for-like
    if args.write_baseline:
        write_baseline("data_scaling", {
            "host_cpus": cores,
            "images_per_sec_by_threads": {str(k): v for k, v in ladder.items()},
            "gil_release": gil,
        })
    emit({
        "metric": f"host decode images/sec (native C++; {cores}-core host; "
                  f"ladder {ladder}; GIL-release ratios "
                  f"batch={gil['batch_ratio']}, single={gil['single_ratio']})",
        "value": best,
        "unit": "images/sec",
        "vs_baseline": round(best / base_best, 4) if base_best else None,
    })


def _native_available() -> bool:
    try:
        from vitax.data import native
        return native.available()
    except Exception:  # noqa: BLE001
        return False


def bench_e2e(args, metric_stub: str) -> None:
    """END-TO-END on-chip throughput: real JPEGs on disk -> native C++
    decode+augment -> ShardedLoader prefetch thread -> uint8 host->device
    transfer -> jitted train step, with host decode OVERLAPPING device
    compute — the reference's per-step reality (MpDeviceLoader feeding every
    iteration, run_vit_training.py:74,88). bench_train measures a
    device-resident constant batch (pure step time); this measures the whole
    machine. The same run takes a device-resident measurement afterwards, so
    the JSON carries the e2e/resident ratio + host_cpus — on a 1-core host
    the feed-limited presets (l14/b16) are honestly input-bound
    (BASELINE.md round-4 feed ratios: 10b_slice 0.95, l14 0.44)."""
    import tempfile

    n_dev, device_kind = init_backend(metric_stub, args.probe_timeout,
                                      args.init_patience, preset=args.preset)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding

    from vitax.config import Config
    from vitax.data.imagefolder import ImageFolderDataset
    from vitax.data.loader import ShardedLoader, ShardedSampler
    from vitax.data.transforms import train_transform
    from vitax.models import build_model
    from vitax.ops.attention import make_attention_impl
    from vitax.parallel.mesh import batch_pspec, build_mesh
    from vitax.train.state import build_optimizer, make_train_state
    from vitax.train.step import make_train_step

    train_preset = args.e2e_train_preset
    apply_preset_file(args, n_dev)
    kn = knobs_from_args(args)
    kw = kn.apply_to_preset_kw(train_presets(n_dev)[train_preset])
    (args.scan_blocks, args.scan_unroll, args.remat_window,
     args.remat_policy) = resolve_bench_knobs(
        args.scan_blocks, args.scan_unroll, args.remat_window,
        args.remat_policy, train_preset,
        other_explicit=kn.other_explicit())
    cfg = Config(num_classes=1000, warmup_steps=0,
                 remat_policy=args.remat_policy, grad_ckpt=args.grad_ckpt,
                 scan_blocks=args.scan_blocks, scan_unroll=args.scan_unroll,
                 remat_window=args.remat_window,
                 use_flash_attention=args.use_flash_attention, **kw).validate()

    mesh = build_mesh(cfg)
    model = build_model(cfg, attention_impl=make_attention_impl(cfg, mesh))
    tx, schedule = build_optimizer(cfg, max_iteration=10_000)
    state, sspecs, _ = make_train_state(cfg, model, tx, mesh, jax.random.key(0))
    step_fn = make_train_step(cfg, model, tx, mesh, sspecs, schedule=schedule)
    rng_key = jax.random.key(1)
    host_cpus = os.cpu_count() or 1
    n_threads = args.data_threads or host_cpus

    rng = np.random.default_rng(0)
    n_images = max(args.data_images, 2 * cfg.batch_size)
    with tempfile.TemporaryDirectory() as root:
        cls = os.path.join(root, "class0")
        os.makedirs(cls)
        _write_random_jpegs(cls, n_images, rng)
        # the production input path: uint8 out of the host transform,
        # normalization inside the compiled step (--device_normalize)
        ds = ImageFolderDataset(
            root, train_transform(cfg.image_size, 0, normalize=False))
        sampler = ShardedSampler(len(ds), cfg.batch_size, shuffle=True,
                                 seed=0, process_index=0, process_count=1)
        loader = ShardedLoader(ds, sampler, mesh, num_workers=n_threads)

        def batches():
            epoch = 0
            while True:
                for b in loader.epoch(epoch):
                    yield b
                epoch += 1

        it = batches()
        for _ in range(max(args.warmup // 2, 2)):  # compile + warm the pool
            state, metrics = step_fn(state, next(it), rng_key)
        float(jax.device_get(metrics["loss"]))

        steps = args.steps
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step_fn(state, next(it), rng_key)
        final_loss = float(jax.device_get(metrics["loss"]))
        dt = time.perf_counter() - t0
        loader.close()
    assert np.isfinite(final_loss), f"non-finite loss {final_loss}"
    e2e_ips = cfg.batch_size * steps / dt

    # device-resident reference on the SAME process/state: the denominator
    # for the overlap efficiency (how much of the pure step rate survives
    # when the input pipeline must feed every iteration)
    sh = NamedSharding(mesh, batch_pspec())
    const_batch = {
        "image": jax.device_put(jnp.asarray(rng.integers(
            0, 256, size=(cfg.batch_size, cfg.image_size, cfg.image_size, 3)),
            jnp.uint8), sh),
        "label": jax.device_put(jnp.asarray(rng.integers(
            0, cfg.num_classes, size=(cfg.batch_size,)), jnp.int32), sh),
    }
    for _ in range(3):
        state, metrics = step_fn(state, const_batch, rng_key)
    float(jax.device_get(metrics["loss"]))
    t0 = time.perf_counter()
    resident_steps = max(args.steps // 2, 5)
    for _ in range(resident_steps):
        state, metrics = step_fn(state, const_batch, rng_key)
    float(jax.device_get(metrics["loss"]))
    resident_ips = cfg.batch_size * resident_steps / (time.perf_counter() - t0)

    overlap_eff = e2e_ips / resident_ips if resident_ips else 0.0
    peak = detect_peak_tflops(device_kind)
    e2e_mfu = (e2e_ips * model_flops_per_image(cfg)) / (peak * 1e12 * n_dev)
    base = read_baseline().get("e2e", {})
    same = (base.get("train_preset") == train_preset
            and base.get("host_cpus") == host_cpus
            and base.get("batch_size") == cfg.batch_size
            and base.get("data_threads") == n_threads)
    vs = (round(e2e_ips / base["e2e_images_per_sec_chip"] / n_dev, 4)
          if same and base.get("e2e_images_per_sec_chip") else None)
    if args.write_baseline:
        write_baseline("e2e", {
            "train_preset": train_preset,
            "e2e_images_per_sec_chip": round(e2e_ips / n_dev, 2),
            "resident_images_per_sec_chip": round(resident_ips / n_dev, 2),
            "overlap_efficiency": round(overlap_eff, 4),
            "host_cpus": host_cpus,
            "data_threads": n_threads,
            "n_devices": n_dev,
            "batch_size": cfg.batch_size,
            "device_kind": device_kind,
        })
    emit({
        "metric": f"end-to-end images/sec/chip (JPEG decode+augment -> "
                  f"train step, {train_preset}, {device_kind}, "
                  f"overlap_eff={overlap_eff:.3f}, host_cpus={host_cpus}, "
                  f"resident={resident_ips / n_dev:.1f}/s)",
        "value": round(e2e_ips / n_dev, 2),
        "unit": "images/sec/chip",
        "vs_baseline": vs,
        "mfu": round(e2e_mfu, 4),
        "peak_tflops_per_chip": peak,
        # same resolved-knob contract as bench_train: an e2e number must
        # also say what it ran (historically this payload had no knobs)
        "knobs": knob_payload(cfg, n_dev),
    })


def bench_train(args, metric_stub: str) -> None:
    import jax

    n_dev, device_kind = init_backend(metric_stub, args.probe_timeout,
                                      args.init_patience, preset=args.preset)

    import jax.numpy as jnp
    import numpy as np

    from vitax.config import Config
    from vitax.models import build_model
    from vitax.ops.attention import make_attention_impl
    from vitax.parallel.mesh import build_mesh, batch_pspec
    from vitax.train.state import build_optimizer, make_train_state
    from vitax.train.step import make_train_step
    from jax.sharding import NamedSharding

    apply_preset_file(args, n_dev)
    kn = knobs_from_args(args)
    kw = kn.apply_to_preset_kw(train_presets(n_dev)[args.preset])
    (args.scan_blocks, args.scan_unroll, args.remat_window,
     args.remat_policy) = resolve_bench_knobs(
        args.scan_blocks, args.scan_unroll, args.remat_window,
        args.remat_policy, args.preset,
        other_explicit=kn.other_explicit())
    cfg = Config(num_classes=1000, warmup_steps=0, remat_policy=args.remat_policy,
                 grad_ckpt=args.grad_ckpt, scan_blocks=args.scan_blocks,
                 scan_unroll=args.scan_unroll, remat_window=args.remat_window,
                 use_flash_attention=args.use_flash_attention, **kw).validate()

    mesh = build_mesh(cfg)
    model = build_model(cfg, attention_impl=make_attention_impl(cfg, mesh))
    tx, schedule = build_optimizer(cfg, max_iteration=10_000)
    state, sspecs, _ = make_train_state(cfg, model, tx, mesh, jax.random.key(0))
    step_fn = make_train_step(cfg, model, tx, mesh, sspecs, schedule=schedule)

    sh = NamedSharding(mesh, batch_pspec())
    rng = np.random.default_rng(0)
    batch = {
        "image": jax.device_put(jnp.asarray(
            rng.normal(size=(cfg.batch_size, cfg.image_size, cfg.image_size, 3)),
            jnp.float32), sh),
        "label": jax.device_put(jnp.asarray(
            rng.integers(0, cfg.num_classes, size=(cfg.batch_size,)), jnp.int32), sh),
    }
    rng_key = jax.random.key(1)

    # NOTE: sync via device_get, not block_until_ready — some PJRT transports
    # (axon tunnel) return immediately from block_until_ready; fetching the
    # value is the reliable fence.
    for _ in range(max(args.warmup, 1)):  # >=1: compile before the timed loop
        state, metrics = step_fn(state, batch, rng_key)
    float(jax.device_get(metrics["loss"]))

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, metrics = step_fn(state, batch, rng_key)
    final_loss = float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), f"non-finite loss {final_loss}"

    step_time = dt / args.steps
    images_per_sec = cfg.batch_size / step_time
    images_per_sec_chip = images_per_sec / n_dev
    flops_per_image = model_flops_per_image(cfg)
    peak = detect_peak_tflops(device_kind)
    mfu = (images_per_sec * flops_per_image) / (peak * 1e12 * n_dev)

    base_entry = read_baseline().get(args.preset, {})
    knobs = ("batch_size", "remat_policy", "scan_blocks", "scan_unroll",
             "remat_window", "grad_ckpt", "use_flash_attention",
             "moe_impl", "att_dropout", "grad_accum_steps",
             "param_gather_dtype", "grad_reduce_dtype", "gather_overlap",
             "fused_optimizer")
    # compare only like-for-like: a knob change (e.g. the scan->unrolled
    # default flip) must not masquerade as a same-config speedup. Entries
    # written before a knob existed compare at the Config FIELD DEFAULT —
    # that is the value they were actually measured at — never at the
    # current run's value (which would make every experiment "match")
    field_defaults = Config()
    same_config = all(
        base_entry.get(k, getattr(field_defaults, k, None)) == getattr(cfg, k)
        for k in knobs)
    base = base_entry.get("images_per_sec_chip") if same_config else None
    # None (JSON null) whenever there is nothing comparable: differing knob
    # sets AND missing/never-measured baselines must be visible, not
    # masquerade as "exactly matches baseline" (ADVICE r3)
    vs_baseline = round(images_per_sec_chip / base, 4) if base else None
    if args.write_baseline:
        write_baseline(args.preset, {
            "images_per_sec_chip": round(images_per_sec_chip, 2),
            "step_time_ms": round(step_time * 1e3, 2),
            "mfu": round(mfu, 4),
            "device_kind": device_kind,
            "n_devices": n_dev,
            "batch_size": cfg.batch_size,
            "remat_policy": cfg.remat_policy,
            # record every A/B knob so an experiment run can never
            # masquerade as the default-config baseline in the JSON
            "scan_blocks": cfg.scan_blocks,
            "scan_unroll": cfg.scan_unroll,
            "remat_window": cfg.remat_window,
            "grad_ckpt": cfg.grad_ckpt,
            "use_flash_attention": cfg.use_flash_attention,
            "moe_impl": cfg.moe_impl,
            "att_dropout": cfg.att_dropout,
            "grad_accum_steps": cfg.grad_accum_steps,
            "param_gather_dtype": cfg.param_gather_dtype,
            "grad_reduce_dtype": cfg.grad_reduce_dtype,
            "gather_overlap": cfg.gather_overlap,
            "fused_optimizer": cfg.fused_optimizer,
        })

    # optional collective audit: same report as `tools/comm_audit.py --json`,
    # landed in the BENCH payload next to the perf knobs so a measured number
    # always records what dtype its collectives moved (ISSUE: comm-precision
    # observability). Costs one extra AOT compile, hence opt-in.
    comm = None
    if args.comm_audit:
        try:
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "tools"))
            import comm_audit as comm_audit_mod
            rep = comm_audit_mod.audit_config(cfg)
            comm = {
                "param_gather_dtype": cfg.resolved_param_gather_dtype,
                "grad_reduce_dtype": cfg.grad_reduce_dtype,
                "all_gather_bytes": rep["all_gather_bytes"],
                "collective_bytes": {
                    op: t["bytes"] for op, t in rep["totals"].items()},
                "f32_block_param_gathers": len(rep["f32_block_param_gathers"]),
                "overlap": rep["overlap"],
            }
        except Exception as e:  # audit must never sink a measured number
            comm = {"error": f"{type(e).__name__}: {e}"}

    emit({
        "metric": f"images/sec/chip (ViT-{args.preset}, train step, "
                  f"{device_kind}, mfu={mfu:.3f}, "
                  f"step_time={step_time * 1e3:.1f}ms, remat={cfg.remat_policy})",
        "value": round(images_per_sec_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": vs_baseline,
        # headline efficiency number, machine-readable (same analytic FLOPs
        # model as the training-loop telemetry, vitax/telemetry/flops.py)
        "mfu": round(mfu, 4),
        "peak_tflops_per_chip": peak,
        # the RESOLVED knob set this number was measured under — ground
        # truth for tools/apply_ladder.py and tools/perf_gate.py
        # (reconstructing knobs from CLI flags drifts once TUNED.json
        # changes the defaults). KNOB_PAYLOAD_KEYS exactly; batch is
        # PER-CHIP: img/s/chip numbers only compare at equal per-chip batch,
        # independent of how many devices the host had
        "knobs": knob_payload(cfg, n_dev),
        **({"comm": comm} if comm is not None else {}),
    })


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="l14",
                   choices=["tiny", "b16", "b16_moe", "l14", "10b", "10b_slice",
                            "data", "data_scaling", "e2e"])
    p.add_argument("--e2e_train_preset", default="10b_slice",
                   choices=["tiny", "b16", "b16_moe", "l14", "10b_slice"],
                   help="which train preset --preset e2e drives from the "
                        "native JPEG loader (default: the preset this "
                        "host's core count can feed)")
    # the shared knob-flag group (vitax/tune/knobs.py): same surface as
    # tools/profile_step.py, tools/aot_topology.py and tools/autotune.py,
    # plus --preset_file to replay a committed autotune winner
    add_knob_args(p)
    p.add_argument("--comm_audit", action="store_true",
                   help="embed the tools/comm_audit.py collective report "
                        "(op/dtype/bytes per step) in the BENCH payload; "
                        "costs one extra AOT compile")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--warmup", type=int, default=8)
    p.add_argument("--data_images", type=int, default=256,
                   help="synthetic JPEG count for --preset data")
    p.add_argument("--data_threads", type=int, default=0,
                   help="0 = one per CPU core (oversubscription only hurts)")
    p.add_argument("--write_baseline", action="store_true",
                   help="persist measured numbers into BASELINE_MEASURED.json")
    p.add_argument("--metrics_dir", type=str, default="",
                   help="also append the emitted payload to "
                        "<metrics_dir>/bench.jsonl (schema-1 telemetry "
                        "event); fail-soft: an unwritable dir warns and "
                        "never sinks the measurement")
    p.add_argument("--probe_timeout", type=float, default=120.0,
                   help="seconds to wait for backend init per probe attempt")
    p.add_argument("--init_patience", type=float, default=900.0,
                   help="total seconds to keep re-probing a down backend in "
                        "fresh subprocesses before giving up (outage-proofing:"
                        " the axon tunnel returns mid-window)")
    p.add_argument("--watchdog", type=float, default=1500.0,
                   help="hard deadline: emit an error JSON and exit if the "
                        "bench has not finished by then (0 disables)")
    args = p.parse_args()

    global _metrics_dir
    _metrics_dir = args.metrics_dir

    if args.preset in ("data", "data_scaling"):
        metric_stub = "host data pipeline images/sec (native C++ decode+augment)"
        unit = "images/sec"
    elif args.preset == "e2e":
        metric_stub = ("end-to-end images/sec/chip (JPEG decode+augment -> "
                       "train step)")
        unit = "images/sec/chip"
    else:
        metric_stub = f"images/sec/chip (ViT-{args.preset}, train step)"
        unit = "images/sec/chip"

    if args.watchdog > 0:
        def deadline():
            # the deadline stretches by whatever init_backend spent waiting
            # out an outage — patience must not convert into a watchdog kill
            t0 = time.monotonic()
            while True:
                remaining = args.watchdog + _init_waited - (time.monotonic() - t0)
                if remaining <= 0:
                    break
                time.sleep(min(remaining, 10.0))
            emit_error(metric_stub,
                       f"watchdog: bench exceeded {args.watchdog:.0f}s "
                       f"(+{_init_waited:.0f}s init wait)",
                       unit=unit, preset=args.preset)
            os._exit(0)
        threading.Thread(target=deadline, daemon=True).start()

    try:
        if args.preset == "data":
            bench_data_pipeline(args)
        elif args.preset == "data_scaling":
            bench_data_scaling(args)
        elif args.preset == "e2e":
            from vitax.platform import force_cpu_if_requested
            force_cpu_if_requested()
            bench_e2e(args, metric_stub)
        else:
            from vitax.platform import force_cpu_if_requested
            force_cpu_if_requested()
            bench_train(args, metric_stub)
    except Exception as e:  # noqa: BLE001 — the JSON contract must always print
        import traceback
        traceback.print_exc(file=sys.stderr)
        emit_error(metric_stub, f"{type(e).__name__}: {e}", unit=unit,
                   preset=args.preset)


if __name__ == "__main__":
    main()
