#!/usr/bin/env python3
"""vitax benchmark: images/sec/chip + MFU for the training step.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Default config is ViT-L/14 (BASELINE.json config 3 shape) sized for one chip;
--preset tiny|l14|10b selects others. FLOP accounting: matmul FLOPs
(patchify + qkv/proj/mlp/head) plus attention score/value einsums, x3 for
fwd+bwd (the standard 6ND convention); remat recompute is NOT counted as
useful work (true MFU).
"""

import argparse
import json
import os
import time

import jax
from vitax.platform import force_cpu_if_requested

force_cpu_if_requested()
import jax.numpy as jnp
import numpy as np

# bf16 peak TFLOP/s per chip by TPU generation (public figures)
PEAK_TFLOPS = {
    "v4": 275.0,
    "v5 lite": 197.0, "v5e": 197.0,
    "v5p": 459.0,
    "v6e": 918.0, "v6 lite": 918.0,
    "cpu": 1.0,
}


def detect_peak_tflops() -> float:
    kind = jax.devices()[0].device_kind.lower()
    for key, val in PEAK_TFLOPS.items():
        if key in kind:
            return val
    return 197.0  # conservative default


def model_flops_per_image(cfg) -> float:
    """Useful matmul FLOPs per image, fwd+bwd (3x forward)."""
    d, L = cfg.embed_dim, cfg.num_blocks
    n = cfg.num_patches
    h = cfg.mlp_hidden_dim
    per_token_block = 2 * (3 * d * d + d * d + d * h + h * d)  # qkv, proj, fc1, fc2
    attn_block = 2 * 2 * n * n * d                             # QK^T and AV
    fwd = L * (per_token_block * n + attn_block)
    fwd += 2 * n * (3 * cfg.patch_size ** 2) * d               # patchify conv
    fwd += 2 * d * cfg.num_classes                             # head
    return 3.0 * fwd


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="l14",
                   choices=["tiny", "b16", "l14", "10b"])
    p.add_argument("--batch_size", type=int, default=0)
    # default resolved per preset below: dots_saveable measured fastest on v5e
    # where activations fit (l14: 164.2 vs 155.8 img/s/chip); the 10B flagship
    # keeps none_saveable (minimal HBM residency is what makes it fit)
    p.add_argument("--remat_policy", default=None,
                   choices=["none_saveable", "dots_saveable"])
    p.add_argument("--no_grad_ckpt", action="store_false", dest="grad_ckpt")
    p.add_argument("--no_flash_attention", action="store_false", dest="use_flash_attention")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--warmup", type=int, default=8)
    args = p.parse_args()

    from vitax.config import Config
    from vitax.models import build_model
    from vitax.parallel.mesh import build_mesh, batch_pspec
    from vitax.train.state import build_optimizer, make_train_state
    from vitax.train.step import make_train_step
    from jax.sharding import NamedSharding

    n_dev = jax.device_count()
    presets = {
        "tiny": dict(image_size=224, patch_size=16, embed_dim=192, num_heads=3,
                     num_blocks=12, batch_size=64 * n_dev),
        # BASELINE.json config 2 shape (ViT-B/16, pure-DP benchmark)
        "b16": dict(image_size=224, patch_size=16, embed_dim=768, num_heads=12,
                    num_blocks=12, batch_size=64 * n_dev),
        "l14": dict(image_size=224, patch_size=14, embed_dim=1024, num_heads=16,
                    num_blocks=24, batch_size=32 * n_dev),
        "10b": dict(image_size=224, patch_size=14, embed_dim=5120, num_heads=32,
                    num_blocks=32, batch_size=8 * n_dev),
    }
    kw = presets[args.preset]
    if args.batch_size:
        kw["batch_size"] = args.batch_size
    if args.remat_policy is None:
        args.remat_policy = "none_saveable" if args.preset == "10b" else "dots_saveable"
    cfg = Config(num_classes=1000, warmup_steps=0, remat_policy=args.remat_policy,
                 grad_ckpt=args.grad_ckpt,
                 use_flash_attention=args.use_flash_attention, **kw).validate()

    mesh = build_mesh(cfg)
    from vitax.ops.attention import make_attention_impl
    model = build_model(cfg, attention_impl=make_attention_impl(cfg, mesh))
    tx, _ = build_optimizer(cfg, max_iteration=10_000)
    state, sspecs, _ = make_train_state(cfg, model, tx, mesh, jax.random.key(0))
    step_fn = make_train_step(cfg, model, tx, mesh, sspecs)

    sh = NamedSharding(mesh, batch_pspec())
    rng = np.random.default_rng(0)
    batch = {
        "image": jax.device_put(jnp.asarray(
            rng.normal(size=(cfg.batch_size, cfg.image_size, cfg.image_size, 3)),
            jnp.float32), sh),
        "label": jax.device_put(jnp.asarray(
            rng.integers(0, cfg.num_classes, size=(cfg.batch_size,)), jnp.int32), sh),
    }
    rng_key = jax.random.key(1)

    # NOTE: sync via device_get, not block_until_ready — some PJRT transports
    # (axon tunnel) return immediately from block_until_ready; fetching the
    # value is the reliable fence.
    for _ in range(max(args.warmup, 1)):  # >=1: compile before the timed loop
        state, metrics = step_fn(state, batch, rng_key)
    float(jax.device_get(metrics["loss"]))

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, metrics = step_fn(state, batch, rng_key)
    final_loss = float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), f"non-finite loss {final_loss}"

    step_time = dt / args.steps
    images_per_sec = cfg.batch_size / step_time
    images_per_sec_chip = images_per_sec / n_dev
    flops_per_image = model_flops_per_image(cfg)
    mfu = (images_per_sec * flops_per_image) / (detect_peak_tflops() * 1e12 * n_dev)

    baseline_file = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "BASELINE_MEASURED.json")
    vs_baseline = 1.0
    if os.path.exists(baseline_file):
        with open(baseline_file) as f:
            base = json.load(f).get(args.preset, {}).get("images_per_sec_chip")
        if base:
            vs_baseline = images_per_sec_chip / base

    result = {
        "metric": f"images/sec/chip (ViT-{args.preset}, train step, "
                  f"{jax.devices()[0].device_kind}, mfu={mfu:.3f}, "
                  f"step_time={step_time * 1e3:.1f}ms, remat={cfg.remat_policy})",
        "value": round(images_per_sec_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs_baseline, 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
