"""AOT-compile the flagship configs against REAL TPU topologies — no hardware.

BASELINE.md's graded configs 4 (ViT-10B, FSDP, v5p-128) and 5 (ViT-60B,
FSDP, v5p-256) match the reference's demonstrated-at-scale claim
(/root/reference/README.md:3,93: 10B on a real v3-128). This host has one
v5e chip, so a pod run is impossible here — but `jax.experimental.topologies`
hands the XLA TPU compiler a real topology description, and the FULL train
step (GSPMD-partitioned, all collectives) compiles for the target platform.
That closes the round-4 daylight between "lowers on a virtual CPU mesh" and
"compiles for the target" (VERDICT r4 missing #2): the per-device
memory_analysis() below is the compiler's own accounting for the pod shape.

Usage:
    JAX_PLATFORMS=cpu python tools/aot_topology.py [--configs 10b 60b]

Writes one JSON object per config with the compiled per-device argument /
temp / output bytes and the HBM bound checked. Run with the CPU host
backend: the topology compile client is independent of the default backend,
and with the axon tunnel down the default (axon) init hangs on the first
concrete array. libtpu allows ONE process at a time (/tmp/libtpu_lockfile)
— don't run two topology compiles concurrently.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V5P_HBM = 95e9  # bytes per v5p chip


def _abstract_key():
    import jax
    return jax.eval_shape(lambda: jax.random.key(0))


def knob_overrides(args) -> dict:
    """Config-kwarg overrides from the shared knob group (+ --preset_file).

    A committed autotune preset applies first (its knobs become the config
    baseline for every topology compiled); explicit CLI knobs override on
    top — the same explicit-wins rule as bench.py. The preset's batch is
    per-chip, so it travels as the special "_batch_per_chip" key and is
    translated once the topology's device count is known."""
    from vitax.tune.knobs import knobs_from_args
    out = {}
    if getattr(args, "preset_file", ""):
        from vitax.tune.preset import config_defaults_from_preset, load_preset
        preset = load_preset(args.preset_file)
        out.update(config_defaults_from_preset(preset))
        out["_batch_per_chip"] = int(preset["knobs"]["batch_per_chip"])
    kn = knobs_from_args(args)
    kn.apply_to_preset_kw(out)  # explicit non-scan knobs (incl. batch_size)
    if kn.batch_size:
        out.pop("_batch_per_chip", None)  # explicit global batch wins
    if args.remat_policy is not None:
        out["remat_policy"] = args.remat_policy
    if args.scan_blocks is not None:
        out["scan_blocks"] = args.scan_blocks
    if args.scan_unroll:
        out["scan_unroll"] = args.scan_unroll
    if args.remat_window >= 0:
        out["remat_window"] = args.remat_window
    if not args.grad_ckpt:
        out["grad_ckpt"] = False
    if not args.use_flash_attention:
        out["use_flash_attention"] = False
    return out


def compile_for_topology(tag: str, topo_name: str, cfg_kw: dict,
                         kernels: bool = False,
                         overrides: dict = None) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import NamedSharding

    from vitax.config import Config
    from vitax.models import build_model, count_params
    from vitax.parallel.mesh import batch_pspec, build_mesh
    from vitax.train.state import build_optimizer, make_train_state
    from vitax.train.step import make_train_step

    td = topologies.get_topology_desc(topo_name, "tpu")
    n_dev = len(td.devices)
    cfg_kw = dict(cfg_kw)
    if overrides:
        ov = dict(overrides)
        bpc = ov.pop("_batch_per_chip", None)
        if bpc:
            cfg_kw["batch_size"] = bpc * n_dev
        cfg_kw.update(ov)
    cfg = Config(num_classes=1000, warmup_steps=0, **cfg_kw).validate()
    mesh = build_mesh(cfg, devices=list(td.devices))
    attention_impl = None
    if kernels:
        # compile the PRODUCTION program: real Mosaic kernels against the
        # TPU target (VITAX_FORCE_MOSAIC set in main; force_tpu_kernels
        # runs the selection logic despite the CPU host backend)
        from vitax.ops.attention import make_attention_impl
        attention_impl = make_attention_impl(cfg, mesh,
                                             force_tpu_kernels=True)
    model = build_model(cfg, attention_impl=attention_impl)
    tx, schedule = build_optimizer(cfg, max_iteration=10_000)
    state, sspecs, _ = make_train_state(
        cfg, model, tx, mesh, jax.random.key(0), materialize=False)
    n_params = count_params(state.params)
    step = make_train_step(cfg, model, tx, mesh, sspecs, schedule=schedule)
    sh = NamedSharding(mesh, batch_pspec())
    batch = {
        "image": jax.ShapeDtypeStruct(
            (cfg.batch_size, cfg.image_size, cfg.image_size, 3),
            jnp.float32, sharding=sh),
        "label": jax.ShapeDtypeStruct((cfg.batch_size,), jnp.int32,
                                      sharding=sh),
    }
    t0 = time.perf_counter()
    lowered = step.lower(state, batch, _abstract_key())
    t_lower = time.perf_counter() - t0
    t0 = time.perf_counter()
    compiled = lowered.compile()
    t_compile = time.perf_counter() - t0
    ma = compiled.memory_analysis()
    state_bytes = sum(x.size * x.dtype.itemsize
                      for x in jax.tree.leaves(state))
    rec = {
        "config": tag,
        "topology": topo_name,
        "kernels": bool(kernels),
        "n_devices": n_dev,
        "device_kind": str(td.devices[0].device_kind),
        "params": n_params,
        "batch_size": cfg.batch_size,
        "global_state_bytes": state_bytes,
        "per_device_argument_bytes": ma.argument_size_in_bytes,
        "per_device_temp_bytes": ma.temp_size_in_bytes,
        "per_device_output_bytes": ma.output_size_in_bytes,
        "alias_bytes": ma.alias_size_in_bytes,
        "hbm_bound_bytes": int(V5P_HBM),
        # donation aliases outputs onto arguments: resident = args + temps
        "per_device_resident_bytes": (ma.argument_size_in_bytes
                                      + ma.temp_size_in_bytes),
        "fits_hbm": (ma.argument_size_in_bytes
                     + ma.temp_size_in_bytes) < V5P_HBM,
        "lower_seconds": round(t_lower, 1),
        "compile_seconds": round(t_compile, 1),
    }
    return rec


CONFIGS = {
    # BASELINE config 4: the 10.078B flagship on a v5p-128 pod, pure ZeRO-3
    "10b": ("v5p:4x4x8", dict(
        image_size=224, patch_size=14, embed_dim=5120, num_heads=32,
        num_blocks=32, batch_size=1024, fsdp_size=-1,
        remat_policy="none_saveable")),
    # BASELINE config 5: ViT-60B (8192d / 80L) on v5p-256
    "60b": ("v5p:8x8x4", dict(
        image_size=224, patch_size=14, embed_dim=8192, num_heads=64,
        num_blocks=80, batch_size=1024, fsdp_size=-1,
        remat_policy="none_saveable")),
    # config 4 variant: pp2 composed with fsdp64 (the GPipe body's gathers)
    "10b_pp": ("v5p:4x4x8", dict(
        image_size=224, patch_size=14, embed_dim=5120, num_heads=32,
        num_blocks=32, batch_size=1024, pp_size=2, fsdp_size=-1, dp_size=1,
        remat_policy="none_saveable")),
    # the rematted 1F1B engine at the 10B shape (pp2 x fsdp4, the round-4
    # "known scale limit" mesh) — compiling for a TPU target is exactly the
    # proof the CPU-only abort kept us from having; temps should land at
    # ~GPipe level, not the ~35 GB gathered-weight residuals
    "10b_1f1b": ("v5p:2x2x2", dict(
        image_size=224, patch_size=14, embed_dim=5120, num_heads=32,
        num_blocks=32, batch_size=64, pp_size=2, fsdp_size=4, dp_size=1,
        pp_schedule="1f1b", remat_policy="none_saveable")),
    # GPipe on the same 8-chip topology — the like-for-like comparator
    "10b_pp8": ("v5p:2x2x2", dict(
        image_size=224, patch_size=14, embed_dim=5120, num_heads=32,
        num_blocks=32, batch_size=64, pp_size=2, fsdp_size=4, dp_size=1,
        remat_policy="none_saveable")),
    # MoE under pp x ep at ViT-L width (round-5 composition): the manual
    # tiled all-to-alls inside the pipeline body must compile for a REAL
    # TPU target, not just the CPU interpret mesh (~1.3B params: dense L/14
    # + 8 experts per block)
    "moe_pp_ep": ("v5p:2x2x2", dict(
        image_size=224, patch_size=14, embed_dim=1024, num_heads=16,
        num_blocks=24, batch_size=64, moe_experts=8, pp_size=2, ep_size=2,
        dp_size=2, fsdp_size=1, remat_policy="none_saveable")),
}

# configs compiled WITH the production Pallas kernels (real Mosaic lowering
# against the TPU target — not interpret mode): --configs entries here get
# kernels=True automatically
KERNEL_CONFIGS = {
    # the 10B flagship's actual production program (4D whole-N kernel at
    # h32/dh160 grouped-padded geometry) on the v5p-128 pod target
    "10b_kernels": ("v5p:4x4x8", dict(
        image_size=224, patch_size=14, embed_dim=5120, num_heads=32,
        num_blocks=32, batch_size=1024, fsdp_size=-1,
        remat_policy="none_saveable")),
    # ring attention over sp with Mosaic block kernels + ppermute ring —
    # the multi-chip Pallas composition the CPU interpret mesh cannot prove
    "l14_ring_sp": ("v5p:2x2x2", dict(
        image_size=224, patch_size=14, embed_dim=1024, num_heads=16,
        num_blocks=24, batch_size=32, sp_size=2, fsdp_size=4, dp_size=1,
        remat_policy="none_saveable")),
    # long-context streaming kernel WITH in-kernel dropout at N=4096 on the
    # v5e target the real bench chip matches — Mosaic-validates the round-5
    # streaming dropout before any chip window
    "longctx_dropout": ("v5e:2x4", dict(
        image_size=896, patch_size=14, embed_dim=1024, num_heads=16,
        num_blocks=4, batch_size=16, att_dropout=0.1, fsdp_size=-1,
        remat_policy="none_saveable")),
    # l14 with the 4D whole-N dropout kernel (the measured -2.9% path)
    "l14_dropout": ("v5e:2x4", dict(
        image_size=224, patch_size=14, embed_dim=1024, num_heads=16,
        num_blocks=24, batch_size=64, att_dropout=0.1, fsdp_size=-1,
        remat_policy="none_saveable")),
    # the rematted 1F1B engine with the production kernels in its stage
    # body (vitax_local_impl) at the 10B shape
    "10b_1f1b_kernels": ("v5p:2x2x2", dict(
        image_size=224, patch_size=14, embed_dim=5120, num_heads=32,
        num_blocks=32, batch_size=64, pp_size=2, fsdp_size=4, dp_size=1,
        pp_schedule="1f1b", remat_policy="none_saveable")),
}
CONFIGS.update(KERNEL_CONFIGS)


def main():
    from vitax.platform import force_cpu_if_requested
    force_cpu_if_requested()
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", nargs="+", default=["10b", "60b"],
                    choices=list(CONFIGS))
    # shared knob group (vitax/tune/knobs.py): A/B a knob or replay a
    # committed autotune preset against a pod topology without editing
    # CONFIGS — explicit flags override each config entry
    from vitax.tune.knobs import add_knob_args
    add_knob_args(ap)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "AOT_TOPOLOGY.json"))
    args = ap.parse_args()
    overrides = knob_overrides(args)
    if overrides:
        print(f"[aot_topology] knob overrides: {overrides}", flush=True)

    results = []
    for tag in args.configs:
        topo, kw = CONFIGS[tag]
        kernels = tag in KERNEL_CONFIGS
        if kernels:
            os.environ["VITAX_FORCE_MOSAIC"] = "1"
        print(f"[aot_topology] compiling {tag} for {topo} "
              f"(kernels={kernels}) ...", flush=True)
        rec = compile_for_topology(tag, topo, kw, kernels=kernels,
                                   overrides=overrides)
        os.environ.pop("VITAX_FORCE_MOSAIC", None)
        print(json.dumps(rec), flush=True)
        results.append(rec)

    existing = {}
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                existing = {r["config"]: r for r in json.load(f)}
        except (json.JSONDecodeError, KeyError, TypeError):
            existing = {}
    for r in results:
        existing[r["config"]] = r
    with open(args.out, "w") as f:
        json.dump(list(existing.values()), f, indent=1)
    print(f"[aot_topology] wrote {args.out}")


if __name__ == "__main__":
    main()
