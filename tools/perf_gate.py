#!/usr/bin/env python3
"""Perf-regression CI gate over the measured trajectory.

Folds the round driver's BENCH_r*.json files and the autotuner's trial JSONL
(kind:"autotune_trial") into per-(model, topology) throughput series, then
fails (exit 1) when the LATEST measured number for a series regresses more
than --threshold_pct below the BEST number ever recorded for that same
series. Outage rounds (value 0.0 + "error", e.g. BENCH_r05's dead tunnel)
are evidence of a dead chip, not a slow program — they are skipped, never
gated on; the gate compares measurements only.

Modes (composable; all requested modes must pass):
  (default)        trajectory regression gate
  --validate       schema-check every BENCH_r*.json + trial JSONL
                   (vitax/telemetry/schema.py)
  --check_ranking  compile-only cost-model sanity: the analytic model must
                   order the known-ordered knob pairs correctly (e.g.
                   gather_overlap off must not out-rank auto on ZeRO-3) —
                   this is the CI arm that needs no hardware at all

--json prints one machine-readable summary object (the CI contract);
exit code is the verdict either way. main(argv) returns the exit code so
tests drive it in-process.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# "images/sec/chip (ViT-l14, train step, TPU v5 lite, mfu=0.62, ...)"
_METRIC_RE = re.compile(r"ViT-(\w+)")
_DEVICE_RE = re.compile(r"(TPU[^,)]*|GPU[^,)]*|cpu)")


def _series_key_from_metric(metric: str):
    m = _METRIC_RE.search(metric or "")
    if not m:
        return None
    dev = _DEVICE_RE.search(metric or "")
    return (m.group(1), dev.group(1).strip() if dev else "unknown")


def load_bench_points(bench_files) -> list:
    """Measured (non-outage) points from BENCH_r*.json, seq-ordered."""
    points = []
    for path in sorted(bench_files):
        try:
            with open(path, encoding="utf-8") as f:
                obj = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        parsed = obj.get("parsed") if isinstance(obj, dict) else None
        if not isinstance(parsed, dict):
            continue
        value = parsed.get("value")
        if parsed.get("error") or not isinstance(value, (int, float)) \
                or value <= 0:
            continue  # outage / unparsable round: never gate on it
        key = _series_key_from_metric(parsed.get("metric", ""))
        if key is None:
            continue
        points.append({"key": key, "seq": (0, int(obj.get("n", 0))),
                       "value": float(value),
                       "knobs": parsed.get("knobs"),
                       "source": os.path.basename(path)})
    return points


def load_trial_points(trial_files) -> list:
    """Measured, unpruned autotune windows as trajectory points."""
    points = []
    for path in trial_files:
        try:
            f = open(path, encoding="utf-8")
        except OSError:
            continue
        with f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if (rec.get("kind") != "autotune_trial"
                        or rec.get("phase") != "measure"
                        or rec.get("pruned_by") is not None
                        or not isinstance(
                            rec.get("images_per_sec_chip"), (int, float))):
                    continue
                points.append({
                    "key": (rec.get("model_preset", "?"),
                            rec.get("topology", "?")),
                    "seq": (1, int(rec.get("trial_id", 0))),
                    "value": float(rec["images_per_sec_chip"]),
                    "knobs": rec.get("knobs"),
                    "source": f"{os.path.basename(path)}"
                              f"#{rec.get('trial_id')}"})
    return points


def gate_trajectory(points, threshold_pct: float) -> list:
    """Per-series verdicts: latest vs best, ok iff within threshold."""
    series = {}
    for p in sorted(points, key=lambda p: p["seq"]):
        series.setdefault(p["key"], []).append(p)
    out = []
    for key, pts in sorted(series.items()):
        best = max(pts, key=lambda p: p["value"])
        latest = pts[-1]
        floor = best["value"] * (1.0 - threshold_pct / 100.0)
        out.append({
            "model": key[0], "topology": key[1], "n_points": len(pts),
            "best": best["value"], "best_source": best["source"],
            "latest": latest["value"], "latest_source": latest["source"],
            "latest_knobs": latest.get("knobs"),
            "regression_pct": round(
                (1.0 - latest["value"] / best["value"]) * 100.0, 3),
            "ok": latest["value"] >= floor,
        })
    return out


def main(argv=None) -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--root", default=root,
                    help="directory holding BENCH_r*.json")
    ap.add_argument("--bench_glob", default="BENCH_r*.json")
    ap.add_argument("--trials", nargs="*", default=None,
                    help="autotune trial JSONL files (default: "
                         "AUTOTUNE_TRIALS.jsonl under --root if present)")
    ap.add_argument("--threshold_pct", type=float, default=5.0,
                    help="max tolerated regression of latest vs best")
    ap.add_argument("--validate", action="store_true",
                    help="schema-check the inputs too")
    ap.add_argument("--check_ranking", action="store_true",
                    help="assert cost-model ordering of known knob pairs")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    bench_files = glob.glob(os.path.join(args.root, args.bench_glob))
    if args.trials is None:
        default_trials = os.path.join(args.root, "AUTOTUNE_TRIALS.jsonl")
        args.trials = [default_trials] if os.path.exists(default_trials) \
            else []

    failures = []
    summary = {"kind": "perf_gate", "threshold_pct": args.threshold_pct,
               "bench_files": sorted(os.path.basename(p)
                                     for p in bench_files),
               "trial_files": list(args.trials)}

    points = load_bench_points(bench_files) + load_trial_points(args.trials)
    series = gate_trajectory(points, args.threshold_pct)
    summary["series"] = series
    for s in series:
        if not s["ok"]:
            failures.append(
                f"{s['model']}@{s['topology']}: latest "
                f"{s['latest']:.2f} ({s['latest_source']}) is "
                f"{s['regression_pct']:.1f}% below best "
                f"{s['best']:.2f} ({s['best_source']}), "
                f"threshold {args.threshold_pct}%")

    if args.validate:
        from vitax.telemetry.schema import (validate_bench_file,
                                            validate_trials_file)
        errors = []
        for path in sorted(bench_files):
            errors.extend(validate_bench_file(path))
        for path in args.trials:
            if os.path.exists(path):
                errors.extend(validate_trials_file(path))
        summary["validate_errors"] = errors
        failures.extend(f"schema: {e}" for e in errors)

    if args.check_ranking:
        from vitax.tune.cost import check_ranking
        ranking = check_ranking()
        summary["ranking"] = ranking
        for r in ranking:
            if not r["ok"]:
                failures.append(f"cost-model ranking violated: {r['name']} "
                                f"({r['why']})")

    summary["failures"] = failures
    summary["ok"] = not failures
    if args.json:
        print(json.dumps(summary, sort_keys=True))
    else:
        for s in series:
            mark = "ok " if s["ok"] else "REGRESSED"
            print(f"[perf_gate] {mark} {s['model']}@{s['topology']}: "
                  f"latest {s['latest']:.2f} vs best {s['best']:.2f} "
                  f"img/s/chip ({s['n_points']} points)")
        if args.check_ranking:
            bad = [r for r in summary["ranking"] if not r["ok"]]
            print(f"[perf_gate] cost-model ranking: "
                  f"{len(summary['ranking']) - len(bad)}/"
                  f"{len(summary['ranking'])} pairs ordered correctly")
        if args.validate:
            print(f"[perf_gate] schema: "
                  f"{len(summary['validate_errors'])} errors")
        for fmsg in failures:
            print(f"[perf_gate] FAIL: {fmsg}", file=sys.stderr)
        print(f"[perf_gate] {'PASS' if not failures else 'FAIL'}")
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
