#!/usr/bin/env bash
# Lint gate: flake8 (settings in .flake8, max-line-length 120) over the
# production tree — vitax/ (including the vitax/telemetry/ observability
# subsystem), tests/, tools/ (including tools/metrics_report.py) and
# bench.py — plus the vitax.analysis source lint and a fast subset of the
# compiled-program invariant checks. tests/test_lint.py runs flake8 as a
# tier-1 guard when flake8 is installed; CI images without flake8 get a
# clean skip here too.
set -u
cd "$(dirname "$0")/.."

# these subsystems and their tools must exist and stay inside the linted
# tree (a rename that drops them out of coverage should fail loudly)
for path in vitax/telemetry tools/metrics_report.py \
            vitax/serve tools/serve_bench.py tests/test_serve.py \
            vitax/serve/fleet tests/test_fleet.py \
            vitax/analysis tools/check_invariants.py tests/test_analysis.py \
            vitax/faults.py vitax/supervise.py tools/supervise.py \
            tests/test_faults.py \
            vitax/data/stream tools/make_shards.py tests/test_stream.py \
            vitax/train/control.py tests/test_control.py \
            vitax/checkpoint/snapshot.py vitax/checkpoint/peer.py \
            tests/test_snapshot.py \
            vitax/analysis/concurrency.py vitax/telemetry/threads.py \
            tests/test_concurrency_lint.py \
            vitax/serve/fleet/breaker.py tests/test_chaos.py \
            vitax/serve/quant.py tests/test_quant.py \
            vitax/ops/fused_optimizer.py tests/test_fused_optimizer.py \
            vitax/ops/dequant_matmul.py tests/test_dequant_matmul.py \
            vitax/serve/fleet/autoscale.py vitax/serve/fleet/placement.py \
            vitax/serve/fleet/agent.py vitax/serve/fleet/cache.py \
            tests/test_cache.py tests/test_autoscale.py \
            vitax/tune vitax/tune/knobs.py vitax/tune/cost.py \
            vitax/tune/driver.py vitax/telemetry/schema.py \
            tools/autotune.py tools/perf_gate.py presets \
            tests/test_autotune.py \
            vitax/arbiter vitax/arbiter/ledger.py vitax/arbiter/policy.py \
            vitax/arbiter/daemon.py tests/test_arbiter.py \
            vitax/programs vitax/programs/registry.py \
            vitax/programs/builder.py vitax/programs/workloads.py \
            vitax/parallel/rules.py tests/test_programs.py; do
    if [ ! -e "$path" ]; then
        echo "lint: expected $path to exist (lint/test coverage guard)" >&2
        exit 1
    fi
done

# AST lint: stdlib-only, always runs (VTX1xx source findings). tools/ is
# in scope too: VTX109 (network calls without timeout=) guards the bench
# and report CLIs as much as the serving tree.
python -m vitax.analysis.ast_lint vitax tools || exit 1

# concurrency lint: per-class thread model + VTX200-series rules over the
# threaded runtime AND its tools. VITAX_LINT_SKIP_CONCURRENCY=1 is the
# escape hatch while triaging a new finding.
if [ "${VITAX_LINT_SKIP_CONCURRENCY:-0}" != "1" ]; then
    python -m vitax.analysis.concurrency vitax tools || exit 1
fi

# compiled-program invariants, fast arm subset (VTX-Rnnn; rules.FAST_ARMS —
# one train arm exercising R001-R005, the fused-optimizer arm for R008,
# the scenario arms (probe/distill) for R010, plus the serve arms:
# full-precision, int8, fp8 (R006/R007) and the forced-fused act-quant arm
# for R009.
# VITAX_LINT_SKIP_INVARIANTS=1 skips on boxes without the jax toolchain.
if [ "${VITAX_LINT_SKIP_INVARIANTS:-0}" != "1" ]; then
    python tools/check_invariants.py \
        --arms zero3_overlap fused probe distill serve serve_quant \
               serve_fp8 serve_actquant || exit 1
fi

# perf-data schema + compile-only cost-model ranking: validates every
# BENCH_r*.json and autotune trial JSONL in the repo, and asserts the cost
# model orders the known-ordered knob pairs (no hardware needed). The
# trajectory regression gate itself runs in CI via the same tool without
# the flags.
python tools/perf_gate.py --validate --check_ranking --json >/dev/null || exit 1

if ! python -m flake8 --version >/dev/null 2>&1; then
    echo "lint: flake8 not installed; skipping (pip install flake8 to enable)"
    exit 0
fi

exec python -m flake8 vitax/ tests/ tools/ bench.py
