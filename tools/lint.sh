#!/usr/bin/env bash
# Lint gate: flake8 (settings in .flake8, max-line-length 120) over the
# production tree. tests/test_lint.py runs this as a tier-1 guard when
# flake8 is installed; CI images without flake8 get a clean skip here too.
set -u
cd "$(dirname "$0")/.."

if ! python -m flake8 --version >/dev/null 2>&1; then
    echo "lint: flake8 not installed; skipping (pip install flake8 to enable)"
    exit 0
fi

exec python -m flake8 vitax/ tests/ tools/ bench.py
