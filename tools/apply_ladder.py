#!/usr/bin/env python3
"""Pick per-preset scan/remat knob defaults from measured ladder results.

Reads LADDER_r04.jsonl (appended by the chip watcher: one line per A/B run,
{"args": "--preset l14 --scan_unroll 2", "result": {bench JSON}}) plus the
default-config rows in BASELINE_MEASURED.json, and flips a preset's default
knobs in TUNED.json ONLY when a ladder winner beats a MEASURED run of the
current default by --min_gain. bench.py's default_scan_blocks /
default_scan_unroll / default_remat_window / default_remat_policy consult
TUNED.json first, so measured winners become the defaults WITHOUT a code
edit — the chip watcher closes the measure->tune loop autonomously even
when the chip returns after a build session ends (VERDICT r3 item 2).

Safety rules (reviewed in round 4):
- never flip away from a default that has no measurement in the candidate
  set (an unmeasured-but-possibly-faster code default must not be replaced
  by a slower measured row);
- rows whose result carries an "error" field are ignored (a watchdog-killed
  partial run must not become the default);
- a row's knob set comes from the bench's OWN "knobs" field in the result
  JSON (ground truth); CLI-flag reconstruction is the legacy fallback.

Usage: python tools/apply_ladder.py [--ladder LADDER_r04.jsonl]
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

KNOB_KEYS = ("scan_blocks", "scan_unroll", "remat_window", "remat_policy",
             "batch_per_chip")  # per-chip batch rides along: img/s/chip
#   from different per-chip batches is not comparable (and per-chip is
#   device-count independent, so multi-chip watcher hosts still match)


def preset_batch_per_chip(preset):
    """The preset's default PER-CHIP batch (train_presets at n_dev=1)."""
    from bench import train_presets
    return train_presets(1).get(preset, {}).get("batch_size")


def parse_preset(args_str: str):
    """Just the --preset value, tolerant of any other flags (rows carrying
    the bench's own "knobs" record stay eligible even when their CLI line
    has non-knob flags like --steps)."""
    toks = args_str.split()
    for i, t in enumerate(toks):
        if t == "--preset" and i + 1 < len(toks):
            return toks[i + 1]
    return None


def parse_knobs(args_str: str) -> dict:
    """Knob dict from a ladder entry's CLI-args string (only knobs that are
    legal bench A/B levers; unknown flags — or a truncated line, e.g. the
    watcher killed mid-append — make the entry ineligible)."""
    toks = args_str.split()
    knobs = {"preset": None, "scan_blocks": None, "scan_unroll": 0,
             "remat_window": 0, "remat_policy": None}
    valued = {"--preset": "preset", "--scan_unroll": "scan_unroll",
              "--remat_window": "remat_window", "--remat_policy": "remat_policy"}
    i = 0
    while i < len(toks):
        t = toks[i]
        if t == "--no_scan_blocks":
            knobs["scan_blocks"] = False; i += 1
        elif t in valued:
            if i + 1 >= len(toks):
                return {}  # truncated line: skip, never crash the tune loop
            val = toks[i + 1]
            knobs[valued[t]] = (int(val) if valued[t] in
                                ("scan_unroll", "remat_window") else val)
            i += 2
        else:
            return {}  # not a pure knob A/B (e.g. --batch_size): skip
    return knobs


def legacy_entry_knobs(knobs: dict) -> dict:
    """Best-effort knob reconstruction for ladder rows WITHOUT the bench's
    "knobs" field (pre-round-4 format). Uses the PRE-TUNED fallbacks
    (allow_tuned=False): these rows predate the knobs field and therefore
    predate any TUNED flip, so the defaults in effect at measurement time
    were the fallbacks — filling with tuned-now defaults would misattribute
    them to post-flip knob sets."""
    from bench import (default_remat_policy, default_scan_blocks,
                       default_scan_unroll)
    sb, su, rw = knobs["scan_blocks"], knobs["scan_unroll"], knobs["remat_window"]
    if rw > 1:
        sb, su = True, 1
    if sb is None:
        sb = (True if su
              else default_scan_blocks(knobs["preset"], allow_tuned=False))
    if not su:
        su = default_scan_unroll(knobs["preset"], allow_tuned=False)
    policy = knobs["remat_policy"] or default_remat_policy(
        knobs["preset"], allow_tuned=False)
    return {"scan_blocks": sb, "scan_unroll": su, "remat_window": rw,
            "remat_policy": policy,
            "batch_per_chip": preset_batch_per_chip(knobs["preset"])}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--ladder", default=os.path.join(REPO, "LADDER_r04.jsonl"))
    p.add_argument("--out", default=os.path.join(REPO, "TUNED.json"))
    p.add_argument("--min_gain", type=float, default=1.02,
                   help="a ladder winner must beat the measured current "
                        "default by this factor to flip it")
    args = p.parse_args()

    sys.path.insert(0, REPO)  # bench.py: shared knob-default semantics
    import bench
    from bench import (default_remat_policy, default_remat_window,
                       default_scan_blocks, default_scan_unroll)
    # the "current default" must consult the SAME file this run writes —
    # a custom --out must not compare against a stale repo TUNED.json
    bench.TUNED_FILE = args.out

    baseline_path = os.path.join(REPO, "BASELINE_MEASURED.json")
    baselines = {}
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baselines = json.load(f)

    candidates = {}  # preset -> list of (img/s, knobs)
    for preset, entry in baselines.items():
        ips = entry.get("images_per_sec_chip") if isinstance(entry, dict) else None
        if ips:
            candidates.setdefault(preset, []).append((ips, {
                "scan_blocks": entry.get("scan_blocks", True),
                "scan_unroll": entry.get("scan_unroll", 1),
                "remat_window": entry.get("remat_window", 0),
                "remat_policy": entry.get("remat_policy",
                                          default_remat_policy(preset)),
                # stored rows record the GLOBAL batch + device count
                "batch_per_chip": (entry["batch_size"] // entry["n_devices"]
                                   if entry.get("batch_size")
                                   and entry.get("n_devices")
                                   else preset_batch_per_chip(preset))}))

    if os.path.exists(args.ladder):
        with open(args.ladder) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                    preset = parse_preset(row["args"])
                    result = row["result"]
                    value = float(result["value"])
                    errored = "error" in result
                except (json.JSONDecodeError, KeyError, TypeError,
                        ValueError, AttributeError):
                    continue  # one malformed line must never kill the loop
                if not preset or value <= 0 or errored:
                    # an "error" row with a positive partial value (e.g. a
                    # watchdog kill mid-run) must never become the default
                    continue
                rec = result.get("knobs")
                try:
                    if isinstance(rec, dict) and all(k in rec for k in KNOB_KEYS):
                        knobs = {k: rec[k] for k in KNOB_KEYS}  # ground truth
                    else:
                        cli = parse_knobs(row["args"])  # legacy pure-knob rows
                        if not cli.get("preset"):
                            continue
                        knobs = legacy_entry_knobs(cli)
                except (KeyError, TypeError, ValueError):
                    continue  # malformed knob values: skip, never crash
                candidates.setdefault(preset, []).append((value, knobs))

    tuned = {}
    if os.path.exists(args.out):  # preserve prior decisions for other presets
        try:
            with open(args.out) as f:
                tuned = json.load(f)
        except (OSError, json.JSONDecodeError):
            tuned = {}

    changed = False
    for preset, rows in sorted(candidates.items()):
        current = {"scan_blocks": default_scan_blocks(preset),
                   "scan_unroll": default_scan_unroll(preset),
                   "remat_window": default_remat_window(preset),
                   "remat_policy": default_remat_policy(preset),
                   "batch_per_chip": preset_batch_per_chip(preset)}
        # challengers at a different per-chip batch are not comparable to
        # the default's img/s/chip — drop them BEFORE the argmax
        rows = [r for r in rows
                if r[1].get("batch_per_chip") == current["batch_per_chip"]]
        if not rows:
            continue
        cur_meas = max((v for v, k in rows if k == current), default=None)
        if cur_meas is None:
            print(f"{preset}: current default {current} has no measurement "
                  f"— keeping it (never flip away from unmeasured)")
            continue
        best_ips, best_knobs = max(rows, key=lambda r: r[0])
        if best_knobs == current or best_ips < args.min_gain * cur_meas:
            print(f"{preset}: default {current} stands at {cur_meas} "
                  f"img/s/chip (best alternative {best_ips})")
            continue
        tuned[preset] = dict(best_knobs, images_per_sec_chip=best_ips,
                             source="ladder")
        changed = True
        print(f"{preset}: FLIP to {best_knobs} @ {best_ips} img/s/chip "
              f"(measured default was {cur_meas})")

    if not changed:
        print("no default flips; TUNED.json unchanged")
        return 0
    tmp = args.out + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(tuned, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, args.out)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
