#!/usr/bin/env python3
"""Self-driving knob search: rank the knob space with the compile-only cost
model, probe the shortlist with AOT compiles, measure on a real chip when one
is up, and commit the winner as presets/<model>_<topology>.json.

Usage (off-TPU, the CI / degraded path — fully deterministic):

    JAX_PLATFORMS=cpu python tools/autotune.py --preset tiny \
        --topologies cpu:1 cpu:8 --compile_only

    # compile-prune against a REAL pod topology, no hardware:
    JAX_PLATFORMS=cpu python tools/autotune.py --preset 10b \
        --topologies v5p:4x4x8 --compile_only --compile_top 2

On a live TPU (`--topologies local`, the default when a chip is up) the
shortlist graduates to short fenced measured windows under successive
halving (vitax/tune/driver.py). Every trial — analytic, compile, measured,
pruned — is one kind:"autotune_trial" JSONL record in --trials, so
tools/perf_gate.py and tools/metrics_report.py can fold the search into the
perf trajectory. libtpu allows ONE process at a time — don't run this
concurrently with bench.py or tools/aot_topology.py.

Off-TPU degradation contract (tests/test_autotune.py): no TPU means
--compile_only is forced (with a printed note), the ranked shortlist and the
emitted preset's knobs are bit-identical run to run, and the exit code is 0.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# cpu:N topologies need N host devices; must be set before jax (which
# vitax.platform imports) first loads — keep this above any vitax import
# that touches jax.
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

# HBM bytes per chip by topology-name prefix (abstract topologies have no
# live memory stats; the bound gates compile_probe's fits_hbm verdict)
HBM_BY_PREFIX = {"v5p": 95e9, "v5e": 16e9, "v6e": 32e9, "v4": 32e9,
                 "v3": 16e9}


def resolve_topology(name: str) -> dict:
    """One topology spec -> devices + accounting constants.

    "local"  : whatever backend is up (the only one that can measure)
    "cpu:N"  : first N forced-host CPU devices (compile-only)
    "v5e:2x4" / "v5p:4x4x8" / ... : jax.experimental.topologies AOT target
    """
    import jax

    from vitax.platform import backend_platform
    from vitax.telemetry.flops import detect_peak_tflops

    if name == "local":
        platform = backend_platform()
        devices = jax.devices(platform)
        kind = devices[0].device_kind
        return {"topology": f"local-{len(devices)}x{kind}".replace(" ", ""),
                "devices": list(devices), "n_dev": len(devices),
                "device_kind": kind,
                "peak_tflops": detect_peak_tflops(kind),
                "hbm_bound_bytes": 0.0,
                "can_measure": platform == "tpu"}
    if name.startswith("cpu:"):
        n = int(name.split(":", 1)[1])
        cpus = jax.devices("cpu")
        assert len(cpus) >= n, (
            f"{name}: only {len(cpus)} host devices (XLA_FLAGS forces 8; "
            f"ask for <= that)")
        return {"topology": name, "devices": cpus[:n], "n_dev": n,
                "device_kind": "cpu", "peak_tflops": 1.0,
                "hbm_bound_bytes": 0.0, "can_measure": False}
    from jax.experimental import topologies
    td = topologies.get_topology_desc(name, "tpu")
    devices = list(td.devices)
    kind = devices[0].device_kind
    prefix = name.split(":", 1)[0]
    return {"topology": name, "devices": devices, "n_dev": len(devices),
            "device_kind": kind,
            "peak_tflops": detect_peak_tflops(kind),
            "hbm_bound_bytes": HBM_BY_PREFIX.get(prefix, 0.0),
            "can_measure": False}


def main(argv=None) -> int:
    from vitax.platform import force_cpu_if_requested
    force_cpu_if_requested()

    import bench
    from vitax.tune.driver import TrialLog, run_search
    from vitax.tune.preset import preset_path, save_preset

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--preset", default="l14",
                    choices=list(bench.train_presets(1)))
    ap.add_argument("--topologies", nargs="+", default=["local"],
                    help='"local", "cpu:N", or an AOT TPU topology like '
                         '"v5e:2x4" / "v5p:4x4x8"')
    ap.add_argument("--compile_only", action="store_true",
                    help="never run measured windows (forced off-TPU)")
    ap.add_argument("--compile_top", type=int, default=0,
                    help="AOT-compile-probe the top K shortlist candidates "
                         "(0 = analytic ranking only; compiles are minutes "
                         "each at pod scale)")
    ap.add_argument("--shortlist", type=int, default=8,
                    help="survivors past the analytic-rank stage")
    ap.add_argument("--max_candidates", type=int, default=0,
                    help="cap the enumerated space (0 = full grid)")
    ap.add_argument("--budget_steps", type=int, default=240,
                    help="total measured steps across all halving rounds")
    ap.add_argument("--min_steps", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--trials", default=os.path.join(
        root, "AUTOTUNE_TRIALS.jsonl"))
    ap.add_argument("--presets_dir", default=os.path.join(root, "presets"))
    ap.add_argument("--no_emit", action="store_true",
                    help="rank only; do not write preset files")
    ap.add_argument("--json", action="store_true",
                    help="print one summary JSON line per topology")
    args = ap.parse_args(argv)

    from vitax.platform import backend_platform
    on_tpu = backend_platform() == "tpu"  # after force_cpu_if_requested
    if not on_tpu and not args.compile_only:
        print("[autotune] no TPU backend — degrading to --compile_only "
              "(deterministic ranked shortlist; measured windows need a "
              "live chip)", flush=True)
        args.compile_only = True

    preset_kw = bench.train_presets(1)[args.preset]
    log = TrialLog(args.trials)
    rc = 0
    try:
        for topo_name in args.topologies:
            topo = resolve_topology(topo_name)
            measure = (not args.compile_only) and topo["can_measure"]
            kw = dict(preset_kw)
            kw.pop("batch_size", None)  # the search owns the batch ladder
            result = run_search(
                args.preset, topo["topology"], kw, topo["n_dev"], log,
                peak_tflops=topo["peak_tflops"], devices=topo["devices"],
                hbm_bound_bytes=topo["hbm_bound_bytes"],
                max_candidates=args.max_candidates,
                shortlist=args.shortlist, compile_top=args.compile_top,
                measure=measure, budget_steps=args.budget_steps,
                min_steps=args.min_steps, warmup=args.warmup)
            out_path = None
            if result["winner"] and not args.no_emit:
                out_path = preset_path(args.presets_dir, args.preset,
                                       topo["topology"])
                save_preset(out_path, result["winner"])
            summary = {
                "kind": "autotune_summary", "model_preset": args.preset,
                "topology": topo["topology"], "n_dev": topo["n_dev"],
                "measured": measure,
                "n_candidates": result["n_candidates"],
                "n_invalid": result["n_invalid"],
                "shortlist": [r["knobs"] for r in result["ranked"]],
                "winner_knobs": (result["winner"] or {}).get("knobs"),
                "preset_file": out_path,
                "trials": args.trials,
            }
            if args.json:
                print(json.dumps(summary, sort_keys=True), flush=True)
            else:
                print(f"[autotune] {args.preset}@{topo['topology']}: "
                      f"{len(result['ranked'])} ranked survivors"
                      + (f", preset -> {out_path}" if out_path else ""),
                      flush=True)
                if result["ranked"]:
                    best = result["ranked"][0]
                    print(f"[autotune]   best knobs: "
                          f"{json.dumps(best['knobs'], sort_keys=True)}",
                          flush=True)
            if not result["ranked"]:
                print(f"[autotune] {args.preset}@{topo['topology']}: no "
                      f"survivors (all pruned)", file=sys.stderr, flush=True)
                rc = 1
    finally:
        log.close()
    return rc


if __name__ == "__main__":
    sys.exit(main())
