#!/usr/bin/env python3
"""Closed-loop load generator for the vitax serving stack (vitax/serve/).

Each worker thread issues POST /predict requests back-to-back (closed loop:
a worker's next request starts when its previous response lands), so
`--concurrency` bounds the in-flight requests and the dynamic batcher's
occupancy. Reports throughput and client-side p50/p95/p99 latency; when the
server ran with --metrics_dir, point --serve_jsonl at its serve.jsonl to
fold in the server-side per-request records (queue wait, engine latency,
batch occupancy) for the same window.

    python tools/serve_bench.py --url http://127.0.0.1:8000 \
        --concurrency 8 --requests 200 --image_size 224
    python tools/serve_bench.py ... --serve_jsonl /runs/s/serve.jsonl --json

stdlib-only (urllib + threading): the bench must run on bare CI hosts.
Exit status: 0 when every request succeeded, 2 otherwise.
"""

from __future__ import annotations

import argparse
import io
import json
import sys
import threading
import time
import urllib.error
import urllib.request


def percentile(sorted_vals, q: float):
    """Linear-interpolated percentile of an ascending list (shared shape
    with tools/metrics_report.py percentile — numpy-free)."""
    if not sorted_vals:
        return None
    if len(sorted_vals) == 1:
        return float(sorted_vals[0])
    pos = (len(sorted_vals) - 1) * q
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac)


def make_image_bytes(image_size: int, seed: int = 0) -> bytes:
    """One PNG request body (random noise — serving cost is content-free)."""
    import numpy as np
    from PIL import Image
    rng = np.random.default_rng(seed)
    arr = rng.integers(0, 256, size=(image_size, image_size, 3), dtype=np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr, "RGB").save(buf, "PNG")
    return buf.getvalue()


def run_worker(url: str, body: bytes, n_requests: int, timeout: float,
               latencies: list, errors: list, lock: threading.Lock) -> None:
    for _ in range(n_requests):
        req = urllib.request.Request(
            url + "/predict", data=body,
            headers={"Content-Type": "image/png"})
        t0 = time.time()
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                payload = json.load(resp)
                assert "classes" in payload and "probs" in payload
            with lock:
                latencies.append(time.time() - t0)
        except Exception as e:  # noqa: BLE001 — count, keep loading
            with lock:
                errors.append(f"{type(e).__name__}: {e}")


def summarize_serve_jsonl(path: str, since: float) -> dict:
    """Server-side view from serve.jsonl: per-request records written by
    vitax/serve/server.py (kind "serve_request") in the bench window."""
    lat, wait, infer, occ = [], [], [], []
    corrupt = 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                corrupt += 1
                continue
            if (not isinstance(rec, dict)
                    or rec.get("kind") != "serve_request"
                    or rec.get("time", 0) < since):
                continue
            lat.append(rec["latency_s"])
            wait.append(rec["queue_wait_s"])
            infer.append(rec["infer_s"])
            occ.append(rec["batch_size"] / max(rec["bucket"], 1))
    lat.sort()
    return {
        "records": len(lat),
        "corrupt_lines": corrupt,
        "latency_s_p50": percentile(lat, 0.50),
        "latency_s_p95": percentile(lat, 0.95),
        "latency_s_p99": percentile(lat, 0.99),
        "queue_wait_s_mean": (round(sum(wait) / len(wait), 6)
                              if wait else None),
        "infer_s_mean": (round(sum(infer) / len(infer), 6)
                         if infer else None),
        "batch_occupancy_mean": (round(sum(occ) / len(occ), 4)
                                 if occ else None),
    }


def run_bench(url: str, concurrency: int, requests_per_worker: int,
              image_size: int, timeout: float,
              serve_jsonl: str = "") -> dict:
    body = make_image_bytes(image_size)
    latencies: list = []
    errors: list = []
    lock = threading.Lock()
    t_start = time.time()
    workers = [threading.Thread(
        target=run_worker,
        args=(url, body, requests_per_worker, timeout, latencies, errors,
              lock), daemon=True)
        for _ in range(concurrency)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    elapsed = time.time() - t_start
    lat = sorted(latencies)
    summary = {
        "url": url,
        "concurrency": concurrency,
        "requests": concurrency * requests_per_worker,
        "completed": len(lat),
        "errors": len(errors),
        "error_samples": errors[:3],
        "elapsed_s": round(elapsed, 3),
        "throughput_rps": round(len(lat) / max(elapsed, 1e-9), 3),
        "latency_s_p50": percentile(lat, 0.50),
        "latency_s_p95": percentile(lat, 0.95),
        "latency_s_p99": percentile(lat, 0.99),
        "latency_s_mean": (round(sum(lat) / len(lat), 6) if lat else None),
    }
    if serve_jsonl:
        summary["server"] = summarize_serve_jsonl(serve_jsonl, since=t_start)
    return summary


def print_human(s: dict) -> None:
    print(f"bench: {s['url']} x{s['concurrency']} closed-loop")
    print(f"  {s['completed']}/{s['requests']} ok ({s['errors']} errors) "
          f"in {s['elapsed_s']:.2f}s -> {s['throughput_rps']:.1f} req/s")
    if s["latency_s_p50"] is not None:
        print(f"  client latency: p50 {1e3 * s['latency_s_p50']:.1f}ms  "
              f"p95 {1e3 * s['latency_s_p95']:.1f}ms  "
              f"p99 {1e3 * s['latency_s_p99']:.1f}ms")
    srv = s.get("server")
    if srv and srv["records"]:
        print(f"  server ({srv['records']} records): "
              f"p50 {1e3 * srv['latency_s_p50']:.1f}ms  "
              f"p99 {1e3 * srv['latency_s_p99']:.1f}ms  "
              f"queue {1e3 * srv['queue_wait_s_mean']:.1f}ms  "
              f"infer {1e3 * srv['infer_s_mean']:.1f}ms  "
              f"occupancy {srv['batch_occupancy_mean']:.2f}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="closed-loop load generator for vitax.serve")
    p.add_argument("--url", type=str, default="http://127.0.0.1:8000")
    p.add_argument("--concurrency", type=int, default=8,
                   help="closed-loop worker threads")
    p.add_argument("--requests", type=int, default=100,
                   help="requests per worker")
    p.add_argument("--image_size", type=int, default=224,
                   help="request image size (must match the served model)")
    p.add_argument("--timeout", type=float, default=90.0,
                   help="per-request client timeout (s)")
    p.add_argument("--serve_jsonl", type=str, default="",
                   help="server's serve.jsonl (--metrics_dir) to fold "
                        "server-side latency/queue/occupancy into the report")
    p.add_argument("--json", action="store_true",
                   help="emit the summary as one JSON object (CI mode)")
    args = p.parse_args(argv)

    summary = run_bench(args.url, args.concurrency, args.requests,
                        args.image_size, args.timeout, args.serve_jsonl)
    if args.json:
        print(json.dumps(summary, sort_keys=True))
    else:
        print_human(summary)
    return 0 if summary["errors"] == 0 and summary["completed"] else 2


if __name__ == "__main__":
    sys.exit(main())
