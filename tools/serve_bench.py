#!/usr/bin/env python3
"""Closed-loop load generator for the vitax serving stack (vitax/serve/).

Each worker thread issues POST /predict requests back-to-back (closed loop:
a worker's next request starts when its previous response lands), so
`--concurrency` bounds the in-flight requests and the dynamic batcher's
occupancy. Reports throughput and client-side p50/p95/p99 latency; when the
server ran with --metrics_dir, point --serve_jsonl at its serve.jsonl to
fold in the server-side per-request records (queue wait, engine latency,
batch occupancy) for the same window.

    python tools/serve_bench.py --url http://127.0.0.1:8000 \
        --concurrency 8 --requests 200 --image_size 224
    python tools/serve_bench.py ... --serve_jsonl /runs/s/serve.jsonl --json

Fleet mode (target = a vitax.serve.fleet router):
- `--target_rps N` paces the closed loop to an offered rate (each worker
  sleeps out the remainder of its share of 1/N between requests) so the
  bench exercises an SLO contract instead of saturating;
- 429 responses (admission sheds) are counted separately from errors —
  they ARE the overload contract — and the worker honors Retry-After
  (capped at 1s so benches stay short);
- `--slo_p99_ms D` adds an SLO verdict to the summary: attained iff the
  client p99 of successful requests is within D and errors == 0;
- `--replicas N` samples the router's /metrics during the run and reports
  rotation (ready_min/ready_end) and replica_restarts — a kill-a-replica
  drill shows up here, not in the error count — plus the containment
  counters (hedged, breaker_opens, degraded_seconds, retry budget) and
  the fleet-growth counters (cache_hits, cache_hit_rate, scale_events,
  ready_max) when the router runs with a cache/autoscaler;
- `--ramp "rps:secs,rps:secs,..."` replaces the fixed request count with
  a staged offered-load profile (each stage paces to its rps for its
  duration) — the autoscale acceptance drill's load shape. The summary
  gains a per-stage breakdown under "ramp";
- errors carry a taxonomy: `errors_by_class` buckets connection_refused /
  reset_mid_body / timeout / http_5xx / other, so a drill can assert
  *which* failure mode leaked to clients, not just how many;
- 503s that carry Retry-After are `unavailable`, not errors: like 429
  sheds they are the fleet's bounded-degradation contract (retry budget
  exhausted, no ready replicas) and the worker honors the backoff.

Chaos mode (`--chaos '<fault plan json>'`): before the burst, POST the
plan to every replica's /chaos endpoint (URLs discovered from the
router's /metrics; replicas must run with --serve_allow_chaos) so a
drill can crash/hang/flap replicas mid-burst and assert the client view
stayed inside the 200/429/503+Retry-After contract. See vitax/faults.py
for the plan grammar and site names.

stdlib-only (urllib + threading): the bench must run on bare CI hosts.
Exit status: 0 when every request succeeded (sheds are not errors),
2 otherwise.
"""

from __future__ import annotations

import argparse
import io
import json
import socket
import sys
import threading
import time
import urllib.error
import urllib.request


def percentile(sorted_vals, q: float):
    """Linear-interpolated percentile of an ascending list (shared shape
    with tools/metrics_report.py percentile — numpy-free)."""
    if not sorted_vals:
        return None
    if len(sorted_vals) == 1:
        return float(sorted_vals[0])
    pos = (len(sorted_vals) - 1) * q
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac)


def make_image_bytes(image_size: int, seed: int = 0) -> bytes:
    """One PNG request body (random noise — serving cost is content-free)."""
    import numpy as np
    from PIL import Image
    rng = np.random.default_rng(seed)
    arr = rng.integers(0, 256, size=(image_size, image_size, 3), dtype=np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr, "RGB").save(buf, "PNG")
    return buf.getvalue()


def classify_error(exc: Exception) -> str:
    """Bucket a client-visible failure for `errors_by_class`: the drill
    question is WHICH mechanism leaked (a refused connect means routing
    sent traffic to a corpse; a reset mid-body means a replica died while
    answering; a timeout means a hang was not contained)."""
    if isinstance(exc, urllib.error.HTTPError):
        return "http_5xx" if exc.code >= 500 else "other"
    if isinstance(exc, (socket.timeout, TimeoutError)):
        return "timeout"
    # urllib wraps socket errors in URLError(reason=<OSError>)
    reason = getattr(exc, "reason", exc)
    if isinstance(reason, ConnectionRefusedError):
        return "connection_refused"
    if isinstance(reason, (ConnectionResetError, ConnectionAbortedError)):
        return "reset_mid_body"
    if isinstance(reason, (socket.timeout, TimeoutError)):
        return "timeout"
    text = str(exc).lower()
    if "refused" in text:
        return "connection_refused"
    if "reset" in text or "aborted" in text:
        return "reset_mid_body"
    if "timed out" in text or "timeout" in text:
        return "timeout"
    return "other"


def _retry_after_s(e: urllib.error.HTTPError) -> float:
    try:
        return float(e.headers.get("Retry-After", "1"))
    except (TypeError, ValueError):
        return 1.0


def parse_ramp(spec: str):
    """"rps:secs,rps:secs,..." -> [(rps, secs), ...] with validation."""
    stages = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            rps_s, secs_s = part.split(":", 1)
            rps, secs = float(rps_s), float(secs_s)
        except ValueError:
            raise ValueError(
                f"bad --ramp stage {part!r}: want 'rps:secs'") from None
        if rps <= 0 or secs <= 0:
            raise ValueError(f"--ramp stage {part!r}: rps and secs must be "
                             f"> 0")
        stages.append((rps, secs))
    if not stages:
        raise ValueError(f"--ramp {spec!r} has no stages")
    return stages


def run_worker(url: str, body: bytes, n_requests: int, timeout: float,
               latencies: list, errors: list, lock: threading.Lock,
               sheds: list = None, interval_s: float = 0.0,
               unavailable: list = None, deadline: float = 0.0) -> None:
    """One closed-loop worker. `interval_s` > 0 paces to an offered rate
    (open-ish loop: sleep out the remainder of the interval after each
    response); `sheds` collects 429 admission responses separately from
    errors — shedding under overload is contract behavior, not failure —
    and `unavailable` likewise collects 503+Retry-After (the fleet's
    bounded-degradation answer: retry budget dry, no ready replicas).
    `errors` entries are (class, detail) pairs — see classify_error.
    `deadline` > 0 switches to time-bounded mode (ramp stages): loop
    until the wall clock passes it, ignoring n_requests."""
    sent = 0
    while ((time.time() < deadline) if deadline > 0
           else (sent < n_requests)):
        sent += 1
        req = urllib.request.Request(
            url + "/predict", data=body,
            headers={"Content-Type": "image/png"})
        t0 = time.time()
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                payload = json.load(resp)
                assert "classes" in payload and "probs" in payload
            with lock:
                latencies.append(time.time() - t0)
        except urllib.error.HTTPError as e:
            if e.code == 429 and sheds is not None:
                retry_after = _retry_after_s(e)
                with lock:
                    sheds.append(retry_after)
                time.sleep(min(max(retry_after, 0.0), 1.0))
            elif (e.code == 503 and unavailable is not None
                    and e.headers is not None
                    and e.headers.get("Retry-After") is not None):
                # contract degradation, not failure: back off as told
                retry_after = _retry_after_s(e)
                with lock:
                    unavailable.append(retry_after)
                time.sleep(min(max(retry_after, 0.0), 1.0))
            else:
                with lock:
                    errors.append((classify_error(e), f"HTTPError: {e.code}"))
        except Exception as e:  # noqa: BLE001 — count, keep loading
            with lock:
                errors.append(
                    (classify_error(e), f"{type(e).__name__}: {e}"))
        if interval_s > 0:
            leftover = interval_s - (time.time() - t0)
            if leftover > 0:
                time.sleep(leftover)


class FleetSampler:
    """Polls the router's GET /metrics during the bench to observe rotation:
    minimum ready count seen (did the fleet lose replicas?), final ready
    count (did they come back?), and restarts performed."""

    def __init__(self, url: str, period_s: float = 0.5):
        self.url = url
        self.period_s = period_s
        self.ready_min = None
        self.ready_max = None
        self.ready_end = None
        self.fleet_size = None
        self.restarts_end = 0
        self.hedged = 0
        self.hedge_wins = 0
        self.breaker_opens = 0
        self.degraded_seconds = 0.0
        self.retry_budget_exhausted = 0
        # fleet-growth counters (PR 17): absent keys stay at their zeros,
        # so benching a cache-less/static fleet still reports cleanly
        self.cache_hits = 0
        self.cache_hit_rate = None
        self.scale_events = 0
        self.scale_out = 0
        self.scale_in = 0
        # _sample runs on both the poll thread and the start/stop callers
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _sample(self) -> None:
        try:
            with urllib.request.urlopen(self.url + "/metrics",
                                        timeout=5.0) as resp:
                snap = json.load(resp)
        except Exception:  # noqa: BLE001 — sampling is best-effort
            return
        fleet = snap.get("fleet") or {}
        ready = fleet.get("ready")
        budget = snap.get("retry_budget") or {}
        with self._lock:
            if ready is not None:
                self.ready_end = ready
                self.ready_min = (ready if self.ready_min is None
                                  else min(self.ready_min, ready))
                self.ready_max = (ready if self.ready_max is None
                                  else max(self.ready_max, ready))
            self.fleet_size = fleet.get("size", self.fleet_size)
            self.restarts_end = fleet.get("replica_restarts",
                                          self.restarts_end)
            # containment counters (monotone on the router; keep the max
            # so a failed final scrape never rolls them back)
            self.hedged = max(self.hedged,
                              snap.get("hedges_total", 0))
            self.hedge_wins = max(self.hedge_wins,
                                  snap.get("hedge_wins_total", 0))
            self.breaker_opens = max(self.breaker_opens,
                                     snap.get("breaker_opens", 0))
            self.degraded_seconds = max(
                self.degraded_seconds,
                float(fleet.get("degraded_seconds") or 0.0))
            self.retry_budget_exhausted = max(
                self.retry_budget_exhausted,
                budget.get("exhausted_total", 0))
            self.cache_hits = max(self.cache_hits,
                                  snap.get("cache_hits", 0))
            rate = snap.get("cache_hit_rate")
            if rate is not None:
                self.cache_hit_rate = rate
            self.scale_events = max(self.scale_events,
                                    snap.get("scale_events", 0))
            auto = snap.get("autoscale") or {}
            self.scale_out = max(self.scale_out,
                                 auto.get("scale_out_total", 0))
            self.scale_in = max(self.scale_in,
                                auto.get("scale_in_total", 0))

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.period_s):
            self._sample()

    def start(self) -> None:
        self._sample()
        self._thread.start()

    def stop(self) -> dict:
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._sample()
        with self._lock:
            return {
                "replicas": self.fleet_size,
                "ready_min": self.ready_min,
                "ready_max": self.ready_max,
                "ready_end": self.ready_end,
                "replica_restarts": self.restarts_end,
                "hedged": self.hedged,
                "hedge_wins": self.hedge_wins,
                "breaker_opens": self.breaker_opens,
                "degraded_seconds": round(self.degraded_seconds, 3),
                "retry_budget_exhausted": self.retry_budget_exhausted,
                "cache_hits": self.cache_hits,
                "cache_hit_rate": self.cache_hit_rate,
                "scale_events": self.scale_events,
                "scale_out": self.scale_out,
                "scale_in": self.scale_in,
            }


def install_chaos(router_url: str, plan_json: str,
                  timeout: float = 5.0) -> dict:
    """Forward a fault plan (vitax/faults.py grammar) to every replica's
    POST /chaos endpoint. Replica URLs come from the router's /metrics
    snapshot; replicas must run with --serve_allow_chaos or they answer
    403. Returns {replica_name: install result or error string}."""
    with urllib.request.urlopen(router_url + "/metrics",
                                timeout=timeout) as resp:
        snap = json.load(resp)
    replicas = snap.get("replicas") or {}
    assert replicas, f"no replicas in {router_url}/metrics — not a fleet?"
    results = {}
    body = plan_json.encode("utf-8")
    for name, info in sorted(replicas.items()):
        url = info.get("url")
        if not url:
            results[name] = "no url in router snapshot"
            continue
        req = urllib.request.Request(
            url + "/chaos", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                results[name] = json.load(resp)
        except Exception as e:  # noqa: BLE001 — report per replica
            results[name] = f"{type(e).__name__}: {e}"
    return results


def summarize_serve_jsonl(path: str, since: float) -> dict:
    """Server-side view from serve.jsonl: per-request records written by
    vitax/serve/server.py (kind "serve_request") in the bench window."""
    lat, wait, infer, occ = [], [], [], []
    corrupt = 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                corrupt += 1
                continue
            if (not isinstance(rec, dict)
                    or rec.get("kind") != "serve_request"
                    or rec.get("time", 0) < since):
                continue
            lat.append(rec["latency_s"])
            wait.append(rec["queue_wait_s"])
            infer.append(rec["infer_s"])
            occ.append(rec["batch_size"] / max(rec["bucket"], 1))
    lat.sort()
    return {
        "records": len(lat),
        "corrupt_lines": corrupt,
        "latency_s_p50": percentile(lat, 0.50),
        "latency_s_p95": percentile(lat, 0.95),
        "latency_s_p99": percentile(lat, 0.99),
        "queue_wait_s_mean": (round(sum(wait) / len(wait), 6)
                              if wait else None),
        "infer_s_mean": (round(sum(infer) / len(infer), 6)
                         if infer else None),
        "batch_occupancy_mean": (round(sum(occ) / len(occ), 4)
                                 if occ else None),
    }


def scrape_weights(url: str, timeout: float = 2.0):
    """Weight-footprint keys from a server or router /metrics: the single
    engine reports weights_dtype/param_bytes at top level, the fleet router
    aggregates them under "fleet" (vitax/serve/quant.py export path). None
    when the endpoint (or an older server) doesn't report them."""
    try:
        with urllib.request.urlopen(url + "/metrics",
                                    timeout=timeout) as resp:
            snap = json.loads(resp.read())
    except Exception:  # noqa: BLE001  scrape is best-effort
        return None
    for scope in (snap, snap.get("fleet") or {}):
        if "param_bytes" in scope:
            return {
                "param_bytes": int(scope["param_bytes"]),
                "weights_dtype": scope.get("weights_dtype",
                                           scope.get("weights_dtypes")),
                "act_quant": scope.get("act_quant",
                                       scope.get("act_quants", "off")),
                "fused_dequant": scope.get("fused_dequant",
                                           scope.get("fused_dequants",
                                                     False)),
            }
    return None


def run_bench(url: str, concurrency: int, requests_per_worker: int,
              image_size: int, timeout: float, serve_jsonl: str = "",
              target_rps: float = 0.0, slo_p99_ms: float = 0.0,
              replicas: int = 0, chaos: str = "", ramp: str = "") -> dict:
    body = make_image_bytes(image_size)
    latencies: list = []
    errors: list = []
    sheds: list = []
    unavailable: list = []
    lock = threading.Lock()
    stages = parse_ramp(ramp) if ramp else []
    # pacing: each of C workers owns 1/C of the offered rate
    interval_s = concurrency / target_rps if target_rps > 0 else 0.0
    chaos_installed = install_chaos(url, chaos) if chaos else None
    sampler = FleetSampler(url) if replicas > 0 else None
    if sampler is not None:
        sampler.start()
    t_start = time.time()
    stage_reports = []
    if stages:
        # staged offered-load profile: each stage paces its own workers
        # against a wall-clock deadline; the aggregate lists span all
        # stages so the overall summary covers the whole profile
        for rps, secs in stages:
            stage_interval = concurrency / rps
            counts0 = (len(latencies), len(sheds), len(unavailable),
                       len(errors))
            deadline = time.time() + secs
            workers = [threading.Thread(
                target=run_worker,
                args=(url, body, 0, timeout, latencies, errors, lock,
                      sheds, stage_interval, unavailable, deadline),
                daemon=True) for _ in range(concurrency)]
            for w in workers:
                w.start()
            for w in workers:
                w.join()
            stage_lat = sorted(latencies[counts0[0]:])
            report = {
                "target_rps": rps,
                "duration_s": secs,
                "completed": len(stage_lat),
                "shed": len(sheds) - counts0[1],
                "unavailable": len(unavailable) - counts0[2],
                "errors": len(errors) - counts0[3],
                "latency_s_p50": percentile(stage_lat, 0.50),
                "latency_s_p99": percentile(stage_lat, 0.99),
            }
            if slo_p99_ms > 0:
                # per-stage SLO verdict: a surge stage that missed while
                # the fleet grew is visible even when the whole-profile
                # aggregate attains (and vice versa)
                stage_p99 = report["latency_s_p99"]
                report["slo_attained"] = bool(
                    stage_lat and report["errors"] == 0
                    and stage_p99 is not None
                    and stage_p99 * 1000.0 <= slo_p99_ms)
            stage_reports.append(report)
    else:
        workers = [threading.Thread(
            target=run_worker,
            args=(url, body, requests_per_worker, timeout, latencies,
                  errors, lock, sheds, interval_s, unavailable),
            daemon=True) for _ in range(concurrency)]
        for w in workers:
            w.start()
        for w in workers:
            w.join()
    elapsed = time.time() - t_start
    lat = sorted(latencies)
    by_class: dict = {}
    for cls, _ in errors:
        by_class[cls] = by_class.get(cls, 0) + 1
    attempted = (len(lat) + len(errors) + len(sheds) + len(unavailable)
                 if stages else concurrency * requests_per_worker)
    summary = {
        "url": url,
        "concurrency": concurrency,
        "requests": attempted,
        "completed": len(lat),
        "errors": len(errors),
        "errors_by_class": by_class,
        "error_samples": [msg for _, msg in errors[:3]],
        "shed": len(sheds),
        "unavailable": len(unavailable),
        "shed_fraction": round(len(sheds) / max(attempted, 1), 4),
        "elapsed_s": round(elapsed, 3),
        "throughput_rps": round(len(lat) / max(elapsed, 1e-9), 3),
        "achieved_rps": round(
            (len(lat) + len(sheds)) / max(elapsed, 1e-9), 3),
        "latency_s_p50": percentile(lat, 0.50),
        "latency_s_p95": percentile(lat, 0.95),
        "latency_s_p99": percentile(lat, 0.99),
        "latency_s_mean": (round(sum(lat) / len(lat), 6) if lat else None),
    }
    if stage_reports:
        summary["ramp"] = stage_reports
    if slo_p99_ms > 0:
        p99 = summary["latency_s_p99"]
        summary["slo"] = {
            "p99_ms": slo_p99_ms,
            "target_rps": target_rps,
            "attained": bool(lat and not errors
                             and p99 is not None
                             and p99 * 1000.0 <= slo_p99_ms),
        }
    if sampler is not None:
        summary["fleet"] = sampler.stop()
    weights = scrape_weights(url, timeout=min(timeout, 5.0))
    if weights is not None:
        summary["weights"] = weights
    if chaos_installed is not None:
        summary["chaos"] = chaos_installed
    if serve_jsonl:
        summary["server"] = summarize_serve_jsonl(serve_jsonl, since=t_start)
    return summary


def print_human(s: dict) -> None:
    print(f"bench: {s['url']} x{s['concurrency']} closed-loop")
    print(f"  {s['completed']}/{s['requests']} ok ({s['errors']} errors, "
          f"{s['shed']} shed, {s['unavailable']} unavailable) in "
          f"{s['elapsed_s']:.2f}s -> {s['throughput_rps']:.1f} req/s")
    if s["errors_by_class"]:
        buckets = "  ".join(f"{k} {v}" for k, v
                            in sorted(s["errors_by_class"].items()))
        print(f"  errors by class: {buckets}")
    if s["latency_s_p50"] is not None:
        print(f"  client latency: p50 {1e3 * s['latency_s_p50']:.1f}ms  "
              f"p95 {1e3 * s['latency_s_p95']:.1f}ms  "
              f"p99 {1e3 * s['latency_s_p99']:.1f}ms")
    slo = s.get("slo")
    if slo:
        print(f"  SLO p99 <= {slo['p99_ms']:.0f}ms: "
              f"{'ATTAINED' if slo['attained'] else 'MISSED'}")
    fleet = s.get("fleet")
    if fleet:
        print(f"  fleet: {fleet['ready_end']}/{fleet['replicas']} ready at "
              f"end (min {fleet['ready_min']}), "
              f"{fleet['replica_restarts']} restarts")
        if (fleet.get("hedged") or fleet.get("breaker_opens")
                or fleet.get("degraded_seconds")
                or fleet.get("retry_budget_exhausted")):
            print(f"  containment: {fleet['hedged']} hedged "
                  f"({fleet['hedge_wins']} wins), "
                  f"{fleet['breaker_opens']} breaker opens, "
                  f"{fleet['retry_budget_exhausted']} budget-exhausted, "
                  f"degraded {fleet['degraded_seconds']:.1f}s")
        if fleet.get("scale_events") or fleet.get("cache_hits"):
            rate = fleet.get("cache_hit_rate")
            print(f"  growth: {fleet.get('scale_events', 0)} scale events "
                  f"({fleet.get('scale_out', 0)} out, "
                  f"{fleet.get('scale_in', 0)} in, ready peaked at "
                  f"{fleet.get('ready_max')}), "
                  f"{fleet.get('cache_hits', 0)} cache hits"
                  + (f" (rate {rate:.2f})" if rate is not None else ""))
    for i, st in enumerate(s.get("ramp") or []):
        p99 = st["latency_s_p99"]
        print(f"  ramp[{i}] {st['target_rps']:g} rps x "
              f"{st['duration_s']:g}s: {st['completed']} ok, "
              f"{st['shed']} shed, {st['unavailable']} unavailable, "
              f"{st['errors']} errors"
              + (f", p99 {1e3 * p99:.1f}ms" if p99 is not None else "")
              + ("" if "slo_attained" not in st else
                 f", slo {'ATTAINED' if st['slo_attained'] else 'MISSED'}"))
    weights = s.get("weights")
    if weights:
        print(f"  weights: {weights['weights_dtype']} "
              f"({weights['param_bytes']:,} B device-resident)  "
              f"act_quant {weights.get('act_quant', 'off')}  "
              f"fused_dequant {weights.get('fused_dequant', False)}")
    srv = s.get("server")
    if srv and srv["records"]:
        print(f"  server ({srv['records']} records): "
              f"p50 {1e3 * srv['latency_s_p50']:.1f}ms  "
              f"p99 {1e3 * srv['latency_s_p99']:.1f}ms  "
              f"queue {1e3 * srv['queue_wait_s_mean']:.1f}ms  "
              f"infer {1e3 * srv['infer_s_mean']:.1f}ms  "
              f"occupancy {srv['batch_occupancy_mean']:.2f}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="closed-loop load generator for vitax.serve")
    p.add_argument("--url", type=str, default="http://127.0.0.1:8000")
    p.add_argument("--concurrency", type=int, default=8,
                   help="closed-loop worker threads")
    p.add_argument("--requests", type=int, default=100,
                   help="requests per worker")
    p.add_argument("--image_size", type=int, default=224,
                   help="request image size (must match the served model)")
    p.add_argument("--timeout", type=float, default=90.0,
                   help="per-request client timeout (s)")
    p.add_argument("--serve_jsonl", type=str, default="",
                   help="server's serve.jsonl (--metrics_dir) to fold "
                        "server-side latency/queue/occupancy into the report")
    p.add_argument("--target_rps", type=float, default=0.0,
                   help="pace the offered load to this rate (0 = saturate)")
    p.add_argument("--slo_p99_ms", type=float, default=0.0,
                   help="add an SLO verdict: attained iff client p99 is "
                        "within this and errors == 0")
    p.add_argument("--replicas", type=int, default=0,
                   help="expected fleet size: sample the router's /metrics "
                        "during the run and report rotation + restarts")
    p.add_argument("--chaos", type=str, default="",
                   help="fault plan JSON (vitax/faults.py grammar) POSTed "
                        "to every replica's /chaos before the burst — "
                        "replicas must run with --serve_allow_chaos")
    p.add_argument("--ramp", type=str, default="",
                   help="staged offered-load profile 'rps:secs,rps:secs,"
                        "...' (replaces --requests/--target_rps; the "
                        "autoscale drill's load shape)")
    p.add_argument("--json", action="store_true",
                   help="emit the summary as one JSON object (CI mode)")
    args = p.parse_args(argv)

    summary = run_bench(args.url, args.concurrency, args.requests,
                        args.image_size, args.timeout, args.serve_jsonl,
                        target_rps=args.target_rps,
                        slo_p99_ms=args.slo_p99_ms, replicas=args.replicas,
                        chaos=args.chaos, ramp=args.ramp)
    if args.json:
        print(json.dumps(summary, sort_keys=True))
    else:
        print_human(summary)
    return 0 if summary["errors"] == 0 and summary["completed"] else 2


if __name__ == "__main__":
    sys.exit(main())
