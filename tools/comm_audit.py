#!/usr/bin/env python3
"""Audit the collectives the compiled train step moves on the wire.

AOT-compiles the train step for the given config (any trainer flag works —
the CLI is the full vitax flag surface plus the audit flags below), dumps the
HLO right after SPMD partitioning, and tabulates every collective op
(all-gather / reduce-scatter / all-reduce / all-to-all / collective-permute):
op count, element type, shape, and bytes per step. This is the artifact that
proves the `--param_gather_dtype bfloat16` policy halves FSDP gather traffic
and guards against precision regressions (tests/test_comm_precision.py).

Why the *post-partitioning* dump and not the final executable HLO: backend
simplification passes may rewrite collective element types after SPMD
partitioning. XLA:CPU's float normalization in particular rewrites every bf16
collective as an f32 collective wrapped in converts, so the final CPU HLO can
never show a bf16 gather no matter what the program asked for. The
post-`spmd-partitioning` module is the backend-independent ground truth for
what dtype each collective moves.

Known result worth recording: under ZeRO-3 (reshard_after_forward) GSPMD sinks
the compute-dtype convert below the per-use gathers, so per-block all-gathers
are bf16 even under the f32 policy — the byte delta of the bf16 policy shows
at the ZeRO-2 step-top gather of the whole param tree (~2x total gather
bytes), plus once-per-step casting and bf16 scan carries instead of per-slice
converts.

Usage:
    python tools/comm_audit.py --embed_dim 1024 --num_blocks 24 [vitax flags]
    python tools/comm_audit.py ... --json          # machine-readable report
    python tools/comm_audit.py ... --compare       # vs the f32 gather policy
"""

import collections
import glob
import json
import os
import re
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# `= bf16[2,32,128]{...} all-gather(` — dtype, shape, op from a partitioned-HLO
# instruction line. `-start` variants cover async collectives; `-done` halves
# carry no shape of their own and are skipped.
COLLECTIVE_RE = re.compile(
    r"= (\w+)\[([\d,]*)\][^ ]* "
    r"((?:all-gather|all-reduce|reduce-scatter|all-to-all|collective-permute)"
    r"(?:-start)?)\(")

DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f16": 2, "bf16": 2, "s16": 2, "u16": 2,
    "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8, "u64": 8,
}


def collect_collectives(hlo_text):
    """Parse a partitioned-HLO module into aggregated collective rows.

    Returns a list of dicts {op, dtype, shape, count, bytes} where `bytes` is
    count * output-shape bytes. Output-shape bytes is the honest per-step
    proxy for wire traffic: an all-gather's output is the gathered tensor
    every participant materializes, an all-reduce/reduce-scatter's output is
    what the reduction moves. (Exact wire bytes carry an extra (n-1)/n ring
    factor that is identical across policies and so cancels in every ratio
    this tool is used for.)
    """
    rows = collections.Counter()
    for m in COLLECTIVE_RE.finditer(hlo_text):
        dtype, shape_s, op = m.groups()
        shape = tuple(int(d) for d in shape_s.split(",") if d)
        rows[(op.replace("-start", ""), dtype, shape)] += 1
    out = []
    for (op, dtype, shape), count in sorted(rows.items()):
        numel = 1
        for d in shape:
            numel *= d
        out.append({
            "op": op, "dtype": dtype, "shape": list(shape), "count": count,
            "numel": numel,
            "bytes": count * numel * DTYPE_BYTES.get(dtype, 4),
        })
    return out


def summarize(rows):
    """Totals per op kind, split by element type."""
    totals = {}
    for r in rows:
        slot = totals.setdefault(r["op"], {"count": 0, "bytes": 0, "by_dtype": {}})
        slot["count"] += r["count"]
        slot["bytes"] += r["bytes"]
        d = slot["by_dtype"].setdefault(r["dtype"], {"count": 0, "bytes": 0})
        d["count"] += r["count"]
        d["bytes"] += r["bytes"]
    return totals


# ops a value may pass through on its way to the while body's ROOT tuple and
# still count as "sitting on the carry": layout/dtype plumbing, not compute.
# A gather whose result reaches ROOT only through these feeds the next
# iteration's prefetch slot; a gather consumed by a dot/fusion first is a
# use-site gather.
_TRIVIAL_OPS = frozenset({
    "copy", "convert", "bitcast", "bitcast-convert", "reshape", "transpose",
    "get-tuple-element", "tuple", "optimization-barrier", "all-gather-done",
})

# `  ROOT name = type op(a, b), attrs...` — name, op, operand list of one
# instruction line. Handles both dump styles: the verbose one (`%name = f32[2]
# add(%a, %b)`) and the terse one XLA emits for pass dumps (`add.3 = f32[2]
# add(p.1, p.2)`); the type may itself be a parenthesised tuple, so the op is
# "the first bare word directly followed by ( after the =".
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*.*?\s([\w\-]+)\((.*)$")
_OPERAND_RE = re.compile(r"%?([\w.\-]+)")


def _split_computations(hlo_text):
    """Split an HLO module dump into {computation_name: [instruction lines]}.

    Computation headers sit at column 0 and end with `{`: terse style is
    `region_0.574_spmd {` / `ENTRY main.1234_spmd {`, verbose style is
    `%fused (p: f32[2]) -> f32[2] {`. Instruction lines are indented and
    contain `=`, which the header pattern excludes."""
    comps = {}
    name, lines = None, []
    header = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\b[^=]*{\s*$")
    for line in hlo_text.splitlines():
        if name is None:
            m = header.match(line)
            if m:
                name, lines = m.group(1), []
        elif line.startswith("}"):
            comps[name] = lines
            name = None
        else:
            lines.append(line)
    return comps


def overlap_verdict(hlo_text):
    """Structural check of the --gather_overlap schedule.

    Locates every while-loop body in the partitioned module and, per body,
    counts its all-gathers and how many of them sit ON THE PREFETCH SLOT:
    their result reaches the body's ROOT tuple (the carry for the next
    iteration) through nothing but layout/dtype plumbing (_TRIVIAL_OPS).
    Use-site gathers — what the plain ZeRO-3 scan has — are consumed by a
    convolution/dot/fusion before any carry, so they never qualify.

    Returns {gathers_in_scan_body, prefetch_slot_gathers,
    per_iteration_gather_count: {body: count}} — the `--json` overlap
    verdict the tier-1 suite asserts on (gather count unchanged between
    off and on; prefetch-slot gathers appear only under on)."""
    comps = _split_computations(hlo_text)
    # first-occurrence order = program order of the while ops: the forward
    # scan's body comes before the backward's, so consumers can key on the
    # first entry for the fwd-schedule invariants
    bodies = list(dict.fromkeys(re.findall(r"body=%?([\w.\-]+)", hlo_text)))

    per_body = {}
    slot_by_body = {}
    for body in bodies:
        lines = comps.get(body)
        if lines is None:
            continue
        instrs = {}   # name -> (op, [operand names])
        root = None
        for line in lines:
            m = _INSTR_RE.match(line)
            if not m:
                continue
            iname, op, rest = m.groups()
            # operand names: %refs up to the closing paren of the operand
            # list (metadata/attrs after it may hold %refs to computations)
            depth, end = 1, len(rest)
            for i, ch in enumerate(rest):
                if ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                    if depth == 0:
                        end = i
                        break
            instrs[iname] = (op, _OPERAND_RE.findall(rest[:end]))
            if line.lstrip().startswith("ROOT"):
                root = iname
        gathers = {n for n, (op, _) in instrs.items()
                   if op in ("all-gather", "all-gather-start")}
        per_body[body] = len(gathers)
        slot_by_body[body] = 0
        if root is None or not gathers:
            continue
        on_slot = set()
        seen = set()
        frontier = [root]
        while frontier:
            n = frontier.pop()
            if n in seen or n not in instrs:
                continue
            seen.add(n)
            op, operands = instrs[n]
            if op in ("all-gather", "all-gather-start"):
                on_slot.add(n)
                continue  # the gather IS the slot value; don't look past it
            if n == root or op in _TRIVIAL_OPS:
                frontier.extend(operands)
        slot_by_body[body] = len(on_slot)

    return {
        "gathers_in_scan_body": sum(per_body.values()),
        "prefetch_slot_gathers": sum(slot_by_body.values()),
        "per_iteration_gather_count": per_body,
        "prefetch_slot_by_body": slot_by_body,
    }


def gather_bytes(rows, dtype=None, min_numel=0):
    """Total all-gather bytes, optionally filtered by dtype / operand size."""
    return sum(r["bytes"] for r in rows
               if r["op"] == "all-gather"
               and (dtype is None or r["dtype"] == dtype)
               and r["numel"] >= min_numel)


def partitioned_hlo_text(cfg, max_iteration=10_000):
    """AOT-lower the train step for `cfg` and return the HLO module text
    captured right after the SPMD partitioner (see module docstring for why
    that stage and not the final executable)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from vitax.models import build_model
    from vitax.ops.attention import make_attention_impl
    from vitax.parallel.mesh import batch_pspec, build_mesh
    from vitax.train.loop import _token_sharding
    from vitax.train.state import build_optimizer, make_train_state
    from vitax.train.step import make_train_step

    mesh = build_mesh(cfg)
    model = build_model(cfg, attention_impl=make_attention_impl(cfg, mesh),
                        token_sharding=_token_sharding(cfg, mesh))
    tx, _ = build_optimizer(cfg, max_iteration=max_iteration)
    state, sspecs, _ = make_train_state(cfg, model, tx, mesh,
                                        jax.random.key(cfg.seed),
                                        materialize=False)
    step = make_train_step(cfg, model, tx, mesh, sspecs)
    sh = NamedSharding(mesh, batch_pspec())
    batch = {
        "image": jax.ShapeDtypeStruct(
            (cfg.batch_size, cfg.image_size, cfg.image_size, 3),
            jnp.float32, sharding=sh),
        "label": jax.ShapeDtypeStruct((cfg.batch_size,), jnp.int32,
                                      sharding=sh),
    }
    dump_dir = tempfile.mkdtemp(prefix="comm_audit_hlo_")
    try:
        step.lower(state, batch, jax.random.key(cfg.seed + 1)).compile(
            compiler_options={"xla_dump_to": dump_dir,
                              "xla_dump_hlo_pass_re": ".*partitioning"})
        dumps = glob.glob(os.path.join(dump_dir, "*after_spmd-partitioning*"))
        preferred = [f for f in dumps if "train_step" in os.path.basename(f)]
        if not preferred:  # fall back to the largest module (the step)
            preferred = sorted(dumps, key=os.path.getsize)[-1:]
        if not preferred:
            if mesh.size == 1:
                # single-device compile: the SPMD partitioner never runs, so
                # there is no dump — and no collectives to audit either
                return ""
            raise RuntimeError(
                f"no post-partitioning HLO dump appeared in {dump_dir}; "
                "this XLA build may not honour per-compile xla_dump_to")
        with open(preferred[0], encoding="utf-8") as f:
            return f.read()
    finally:
        shutil.rmtree(dump_dir, ignore_errors=True)


def audit_config(cfg):
    """Full audit report for one config: collective rows + per-op totals +
    the block-param gather facts the tier-1 test asserts on."""
    hlo_text = partitioned_hlo_text(cfg)
    rows = collect_collectives(hlo_text)
    block_numel = cfg.embed_dim * cfg.embed_dim  # smallest block matmul param
    return {
        "config": {
            "dtype": cfg.dtype,
            "param_gather_dtype": cfg.resolved_param_gather_dtype,
            "grad_reduce_dtype": cfg.grad_reduce_dtype,
            "reshard_after_forward": cfg.reshard_after_forward,
            "run_without_fsdp": cfg.run_without_fsdp,
            "grad_accum_steps": cfg.grad_accum_steps,
            "pp_size": cfg.pp_size,
            "gather_overlap": cfg.gather_overlap,
        },
        "collectives": rows,
        "totals": summarize(rows),
        "all_gather_bytes": gather_bytes(rows),
        "f32_block_param_gathers": [
            r for r in rows
            if r["op"] == "all-gather" and r["dtype"] == "f32"
            and r["numel"] >= block_numel],
        "overlap": overlap_verdict(hlo_text),
    }


def format_report(report):
    lines = []
    c = report["config"]
    lines.append(f"comm_audit: dtype={c['dtype']} "
                 f"param_gather_dtype={c['param_gather_dtype']} "
                 f"grad_reduce_dtype={c['grad_reduce_dtype']}")
    lines.append(f"{'count':>6} {'op':<20} {'dtype':<6} {'bytes':>12}  shape")
    for r in report["collectives"]:
        lines.append(f"{r['count']:>6} {r['op']:<20} {r['dtype']:<6} "
                     f"{r['bytes']:>12,}  {r['shape']}")
    lines.append("-- totals --")
    for op, t in sorted(report["totals"].items()):
        split = ", ".join(f"{d}: {v['bytes']:,}B x{v['count']}"
                          for d, v in sorted(t["by_dtype"].items()))
        lines.append(f"  {op:<20} {t['bytes']:>12,} B/step  ({split})")
    bad = report["f32_block_param_gathers"]
    lines.append(f"  f32 block-param all-gathers: "
                 f"{len(bad)}{' <- POLICY NOT APPLIED' if bad else ''}")
    ov = report.get("overlap")
    if ov is not None:
        lines.append(
            f"  overlap ({c.get('gather_overlap', '?')}): "
            f"{ov['gathers_in_scan_body']} gathers in scan bodies, "
            f"{ov['prefetch_slot_gathers']} on the prefetch slot")
    return "\n".join(lines)


def main(argv=None):
    from vitax.config import build_parser, config_fields_from_namespace

    parser = build_parser()
    aud = parser.add_argument_group("comm_audit")
    aud.add_argument("--json", action="store_true", dest="audit_json",
                     help="emit the audit report as JSON on stdout")
    aud.add_argument("--compare", action="store_true", dest="audit_compare",
                     help="also audit the same config under the f32 gather "
                          "policy and report the gather-byte ratio")
    # audit runs standalone on dev boxes: small default geometry instead of
    # the 10B trainer defaults so `python tools/comm_audit.py` just works
    parser.set_defaults(image_size=224, patch_size=14, embed_dim=1024,
                        num_heads=16, num_blocks=4, num_classes=1000,
                        batch_size=64, warmup_steps=2)
    ns = parser.parse_args(argv)

    from vitax.config import Config
    cfg = Config(**config_fields_from_namespace(ns)).validate()
    report = audit_config(cfg)

    if ns.audit_compare:
        alt = {**config_fields_from_namespace(ns),
               "param_gather_dtype": "float32"}
        f32_report = audit_config(Config(**alt).validate())
        num = f32_report["all_gather_bytes"]
        den = report["all_gather_bytes"]
        report["compare"] = {
            "f32_policy_all_gather_bytes": num,
            "all_gather_bytes_ratio": round(num / den, 3) if den else None,
        }

    if ns.audit_json:
        print(json.dumps(report, indent=2))
    else:
        print(format_report(report))
        if "compare" in report:
            cmp_ = report["compare"]
            print(f"-- vs f32 gather policy --\n"
                  f"  f32-policy all-gather bytes: "
                  f"{cmp_['f32_policy_all_gather_bytes']:,}\n"
                  f"  gather-byte reduction: "
                  f"{cmp_['all_gather_bytes_ratio']}x")
    return report


if __name__ == "__main__":
    main()
