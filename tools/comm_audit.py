#!/usr/bin/env python3
"""Audit the collectives the compiled train step moves on the wire.

AOT-compiles the train step for the given config (any trainer flag works —
the CLI is the full vitax flag surface plus the audit flags below), dumps the
HLO right after SPMD partitioning, and tabulates every collective op
(all-gather / reduce-scatter / all-reduce / all-to-all / collective-permute):
op count, element type, shape, and bytes per step. This is the artifact that
proves the `--param_gather_dtype bfloat16` policy halves FSDP gather traffic
and guards against precision regressions (tests/test_comm_precision.py).

Why the *post-partitioning* dump and not the final executable HLO: backend
simplification passes may rewrite collective element types after SPMD
partitioning. XLA:CPU's float normalization in particular rewrites every bf16
collective as an f32 collective wrapped in converts, so the final CPU HLO can
never show a bf16 gather no matter what the program asked for. The
post-`spmd-partitioning` module is the backend-independent ground truth for
what dtype each collective moves.

Known result worth recording: under ZeRO-3 (reshard_after_forward) GSPMD sinks
the compute-dtype convert below the per-use gathers, so per-block all-gathers
are bf16 even under the f32 policy — the byte delta of the bf16 policy shows
at the ZeRO-2 step-top gather of the whole param tree (~2x total gather
bytes), plus once-per-step casting and bf16 scan carries instead of per-slice
converts.

The HLO/while-body parser lives in vitax.analysis.hlo (it started here and
was generalized for the rule registry in vitax.analysis.rules); this tool is
now a thin CLI over it. The re-exports below keep the historical module-level
API (`from tools.comm_audit import audit_config`, `comm_audit.gather_bytes`)
stable for the tier-1 tests.

Usage:
    python tools/comm_audit.py --embed_dim 1024 --num_blocks 24 [vitax flags]
    python tools/comm_audit.py ... --json          # machine-readable report
    python tools/comm_audit.py ... --compare       # vs the f32 gather policy
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from vitax.analysis.hlo import (  # noqa: E402  (sys.path fix must precede)
    COLLECTIVE_RE,
    DTYPE_BYTES,
    INSTR_RE as _INSTR_RE,
    TRIVIAL_OPS as _TRIVIAL_OPS,
    collect_collectives,
    gather_bytes,
    overlap_verdict,
    partitioned_hlo_text,
    split_computations as _split_computations,
    summarize,
)

__all__ = [
    "COLLECTIVE_RE", "DTYPE_BYTES", "collect_collectives", "summarize",
    "gather_bytes", "overlap_verdict", "partitioned_hlo_text",
    "audit_config", "format_report", "main",
]


def audit_config(cfg):
    """Full audit report for one config: collective rows + per-op totals +
    the block-param gather facts the tier-1 test asserts on."""
    hlo_text = partitioned_hlo_text(cfg)
    rows = collect_collectives(hlo_text)
    block_numel = cfg.embed_dim * cfg.embed_dim  # smallest block matmul param
    return {
        "config": {
            "dtype": cfg.dtype,
            "param_gather_dtype": cfg.resolved_param_gather_dtype,
            "grad_reduce_dtype": cfg.grad_reduce_dtype,
            "reshard_after_forward": cfg.reshard_after_forward,
            "run_without_fsdp": cfg.run_without_fsdp,
            "grad_accum_steps": cfg.grad_accum_steps,
            "pp_size": cfg.pp_size,
            "gather_overlap": cfg.gather_overlap,
        },
        "collectives": rows,
        "totals": summarize(rows),
        "all_gather_bytes": gather_bytes(rows),
        "f32_block_param_gathers": [
            r for r in rows
            if r["op"] == "all-gather" and r["dtype"] == "f32"
            and r["numel"] >= block_numel],
        "overlap": overlap_verdict(hlo_text),
    }


def format_report(report):
    lines = []
    c = report["config"]
    lines.append(f"comm_audit: dtype={c['dtype']} "
                 f"param_gather_dtype={c['param_gather_dtype']} "
                 f"grad_reduce_dtype={c['grad_reduce_dtype']}")
    lines.append(f"{'count':>6} {'op':<20} {'dtype':<6} {'bytes':>12}  shape")
    for r in report["collectives"]:
        lines.append(f"{r['count']:>6} {r['op']:<20} {r['dtype']:<6} "
                     f"{r['bytes']:>12,}  {r['shape']}")
    lines.append("-- totals --")
    for op, t in sorted(report["totals"].items()):
        split = ", ".join(f"{d}: {v['bytes']:,}B x{v['count']}"
                          for d, v in sorted(t["by_dtype"].items()))
        lines.append(f"  {op:<20} {t['bytes']:>12,} B/step  ({split})")
    bad = report["f32_block_param_gathers"]
    lines.append(f"  f32 block-param all-gathers: "
                 f"{len(bad)}{' <- POLICY NOT APPLIED' if bad else ''}")
    ov = report.get("overlap")
    if ov is not None:
        lines.append(
            f"  overlap ({c.get('gather_overlap', '?')}): "
            f"{ov['gathers_in_scan_body']} gathers in scan bodies, "
            f"{ov['prefetch_slot_gathers']} on the prefetch slot")
    return "\n".join(lines)


def main(argv=None):
    from vitax.config import build_parser, config_fields_from_namespace

    parser = build_parser()
    aud = parser.add_argument_group("comm_audit")
    aud.add_argument("--json", action="store_true", dest="audit_json",
                     help="emit the audit report as JSON on stdout")
    aud.add_argument("--compare", action="store_true", dest="audit_compare",
                     help="also audit the same config under the f32 gather "
                          "policy and report the gather-byte ratio")
    # audit runs standalone on dev boxes: small default geometry instead of
    # the 10B trainer defaults so `python tools/comm_audit.py` just works
    parser.set_defaults(image_size=224, patch_size=14, embed_dim=1024,
                        num_heads=16, num_blocks=4, num_classes=1000,
                        batch_size=64, warmup_steps=2)
    ns = parser.parse_args(argv)

    from vitax.config import Config
    cfg = Config(**config_fields_from_namespace(ns)).validate()
    report = audit_config(cfg)

    if ns.audit_compare:
        alt = {**config_fields_from_namespace(ns),
               "param_gather_dtype": "float32"}
        f32_report = audit_config(Config(**alt).validate())
        num = f32_report["all_gather_bytes"]
        den = report["all_gather_bytes"]
        report["compare"] = {
            "f32_policy_all_gather_bytes": num,
            "all_gather_bytes_ratio": round(num / den, 3) if den else None,
        }

    if ns.audit_json:
        print(json.dumps(report, indent=2))
    else:
        print(format_report(report))
        if "compare" in report:
            cmp_ = report["compare"]
            print(f"-- vs f32 gather policy --\n"
                  f"  f32-policy all-gather bytes: "
                  f"{cmp_['f32_policy_all_gather_bytes']:,}\n"
                  f"  gather-byte reduction: "
                  f"{cmp_['all_gather_bytes_ratio']}x")
    return report


if __name__ == "__main__":
    main()
