#!/usr/bin/env python3
"""GPipe vs 1F1B A/B on the virtual CPU mesh: live-buffer (temp) memory and
step time as the microbatch count M grows (VERDICT r3 item 5 done-condition).

The point being measured: GPipe's autodiff backward keeps O(M) microbatch
activations live (every in-flight tick's carry is a saved residual), so the
M knob that shrinks the (S-1)/(M+S-1) bubble buys memory pain; 1F1B's
interleaved schedule bounds live activations at O(S) regardless of M.
XLA's buffer assignment (compiled.memory_analysis().temp_size_in_bytes) is
the ground truth for "live", no chip needed.

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python tools/pp_schedule_ab.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from vitax.platform import force_cpu_if_requested  # noqa: E402

force_cpu_if_requested()


def build(schedule: str, microbatches: int):
    from vitax.config import Config
    from vitax.models import build_model
    from vitax.parallel.mesh import build_mesh, batch_pspec
    from vitax.train.state import build_optimizer, make_train_state
    from vitax.train.step import make_train_step
    from jax.sharding import NamedSharding

    cfg = Config(image_size=32, patch_size=8, embed_dim=256, num_heads=4,
                 num_blocks=4, num_classes=16, batch_size=64, dtype="float32",
                 pp_size=2, dp_size=4, fsdp_size=1, warmup_steps=0,
                 pp_schedule=schedule, pp_microbatches=microbatches,
                 grad_ckpt=True).validate()
    mesh = build_mesh(cfg)
    model = build_model(cfg)
    tx, schedule = build_optimizer(cfg, max_iteration=100)
    state, sspecs, _ = make_train_state(cfg, model, tx, mesh, jax.random.key(0))
    step_fn = make_train_step(cfg, model, tx, mesh, sspecs, schedule=schedule)
    sh = NamedSharding(mesh, batch_pspec())
    rng = np.random.default_rng(0)
    batch = {
        "image": jax.device_put(jnp.asarray(rng.normal(
            size=(cfg.batch_size, 32, 32, 3)), jnp.float32), sh),
        "label": jax.device_put(jnp.asarray(rng.integers(
            0, 16, size=(cfg.batch_size,)), jnp.int32), sh),
    }
    return cfg, state, step_fn, batch


def measure(schedule: str, microbatches: int, steps: int = 5):
    cfg, state, step_fn, batch = build(schedule, microbatches)
    rng = jax.random.key(1)
    lowered = step_fn.lower(state, batch, rng)
    compiled = lowered.compile()
    mem = compiled.memory_analysis()
    temp_mb = getattr(mem, "temp_size_in_bytes", 0) / 2**20
    state, metrics = step_fn(state, batch, rng)  # warm (donated state reuse)
    loss0 = float(jax.device_get(metrics["loss"]))
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, batch, rng)
    loss = float(jax.device_get(metrics["loss"]))
    dt = (time.perf_counter() - t0) / steps
    return {"schedule": schedule, "M": microbatches,
            "temp_mb": round(temp_mb, 2), "step_ms": round(dt * 1e3, 1),
            "loss0": round(loss0, 6), "loss_end": round(loss, 6)}


def main():
    rows = []
    for m in (2, 8, 16):
        for sched in ("gpipe", "1f1b"):
            r = measure(sched, m)
            rows.append(r)
            print(f"{sched:>6} M={m:<3} temp={r['temp_mb']:>8.2f} MB "
                  f"step={r['step_ms']:>7.1f} ms loss0={r['loss0']}",
                  flush=True)
    # loss trajectories must agree per M (same math, different schedule)
    by_m = {}
    for r in rows:
        by_m.setdefault(r["M"], []).append(r)
    for m, pair in by_m.items():
        a, b = pair
        assert abs(a["loss0"] - b["loss0"]) < 2e-4 * abs(a["loss0"]), (m, pair)
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "PP_AB.json")
    device = jax.devices()[0].device_kind  # vtx: ignore[VTX104] CLI entry: labels the benchmarked backend
    with open(out, "w") as f:
        json.dump({"device": device,
                   "config": "embed256 L4 pp2 x dp4 batch64 f32 remat",
                   "rows": rows}, f, indent=2)
        f.write("\n")
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
