#!/usr/bin/env python3
"""On-hardware numerics check for the Pallas attention kernels.

The CPU test suite runs every kernel in Pallas interpret mode, which
faithfully emulates the math but NOT Mosaic's lowering: real-TPU-only
failure modes (tiling legality, layout padding, sublane rules — e.g. the
hb=4 lse block the round-3 10b_slice compile rejected) and real-dtype MXU
behavior are invisible there. This tool compiles and runs each kernel
family on the actual attached TPU against the dense jnp reference, fwd and
backward, in bf16, and fails loudly on divergence.

Usage: python tools/check_kernels_on_chip.py   (needs a TPU; ~1 min)

Shapes cover the three dispatch paths of vitax/ops/attention.py:
- 4D whole-N kernel, full-array head blocks (l14/b16 geometry)
- 4D whole-N kernel, grouped-padded lse (10B-family geometry, hb=4)
- BH relayout kernel (forced)
plus the streaming blocked kernel (vitax/ops/flash_blocked.py) at a
sequence length past MAX_SEQ_IN_VMEM's block sizes.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

# bf16 has ~3 decimal digits; the fused kernels do softmax/accum in f32 so
# outputs agree to bf16 resolution against the (also f32-accumulating) dense
# reference
REL_TOL = 0.06


def check(name, fn, ref, shape, dtype=jnp.bfloat16, seed=0):
    kq, kk, kv, kg = jax.random.split(jax.random.key(seed), 4)
    q = jax.random.normal(kq, shape, dtype)
    k = jax.random.normal(kk, shape, dtype)
    v = jax.random.normal(kv, shape, dtype)
    ct = jax.random.normal(kg, shape, dtype)

    def run(f):
        o, vjp = jax.vjp(lambda a, b, c: f(a, b, c), q, k, v)
        return [np.asarray(x, np.float32) for x in (o, *vjp(ct))]

    got, want = run(fn), run(ref)
    worst = 0.0
    for tag, g, w in zip(("o", "dq", "dk", "dv"), got, want):
        err = float(np.max(np.abs(g - w)) / max(1e-6, np.max(np.abs(w))))
        worst = max(worst, err)
        status = "ok" if err < REL_TOL else "FAIL"
        print(f"  {name:34s} {tag:3s} rel-max-err {err:.4f} {status}")
        if err >= REL_TOL:
            return False
    return True


def main():
    dev = jax.devices()[0]  # vtx: ignore[VTX104] CLI entry point: probes whatever backend the user launched on
    if dev.platform != "tpu":
        print(f"no TPU attached (found {dev.platform}); this tool checks "
              f"real-hardware lowering — run it on a chip", file=sys.stderr)
        return 2

    from vitax.ops.attention import (_heads_per_program, flash_attention,
                                     flash_attention_4d, reference_attention)
    from vitax.ops.flash_blocked import blocked_flash_attention

    print(f"device: {dev.device_kind}")
    ok = True
    # dispatch-path preconditions: if head-grouping selection changed, the
    # labels below would describe the wrong kernel geometry — report, don't
    # assert (python -O must not skip these)
    for shape_args, want_hb, label in [((256, 16, 64, 2), 16, "l14"),
                                       ((256, 32, 160, 2), 4, "10B")]:
        got_hb = _heads_per_program(*shape_args)
        if got_hb != want_hb:
            print(f"  precondition FAIL: {label} geometry picks hb={got_hb}, "
                  f"expected {want_hb} — selection logic changed; update the "
                  f"path labels/shapes in this tool")
            ok = False
    # l14 geometry: full-array head blocks (hb == h)
    ok &= check("4D full-array (l14: h16 dh64)", flash_attention_4d,
                reference_attention, (4, 256, 16, 64))
    # 10B-family geometry: grouped-padded lse (hb=4, P=8)
    ok &= check("4D padded-lse (10B: h32 dh160)", flash_attention_4d,
                reference_attention, (8, 256, 32, 160))
    # BH relayout kernel, forced (the fallback dispatch path)
    ok &= check("BH relayout (h8 dh64)", flash_attention,
                reference_attention, (2, 256, 8, 64))
    # streaming blocked kernel (long-sequence path)
    ok &= check("streaming blocked (n4096)", blocked_flash_attention,
                reference_attention, (1, 4096, 4, 64))

    # dropout variants (round 5): the dense comparator shares the
    # counter-hash mask code, so these check Mosaic's lowering of the
    # uint32 hash + masked-softmax math on real hardware, fwd and bwd
    from vitax.ops.attention import (dropout_keep_mask, flash4_dropout,
                                     flash_bh_dropout, _to_bh, _from_bh)
    from vitax.ops.flash_blocked import blocked_dropout_attention
    seed32, rate = jnp.uint32(2024), 0.2

    def dense_masked(q, k, v):
        b, n, h, dh = q.shape
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * dh ** -0.5
        probs = jax.nn.softmax(s, axis=-1)
        mask = jnp.stack([jnp.stack([
            dropout_keep_mask(seed32, jnp.uint32(bi * h + hi), n, n, rate)
            for hi in range(h)]) for bi in range(b)])
        return jnp.einsum("bhqk,bkhd->bqhd",
                          (probs * mask / (1 - rate)).astype(q.dtype), v)

    ok &= check("4D dropout (l14 geometry)",
                lambda q, k, v: flash4_dropout(
                    q, k, v, seed32, q.shape[-1] ** -0.5, rate),
                dense_masked, (4, 256, 16, 64))
    ok &= check("BH dropout (h8 dh64)",
                lambda q, k, v: _from_bh(flash_bh_dropout(
                    _to_bh(q), _to_bh(k), _to_bh(v), seed32,
                    q.shape[-1] ** -0.5, rate), q.shape),
                dense_masked, (2, 256, 8, 64))
    ok &= check("streaming dropout (n4096)",
                lambda q, k, v: blocked_dropout_attention(
                    q, k, v, seed32, rate),
                dense_masked, (1, 4096, 4, 64))

    ok &= check_fused_optimizer()
    ok &= check_dequant_matmul()
    print("ON-CHIP KERNEL NUMERICS:", "OK" if ok else "FAILED")
    return 0 if ok else 1


def check_fused_optimizer() -> bool:
    """Mosaic-lowered fused clip+AdamW vs the closed-form jnp update.

    The optimizer kernel is f32 elementwise (no MXU, no softmax rescaling),
    so on-chip agreement is tight — 1e-5 relative, not the bf16 attention
    tolerance. States compare directly (no vjp: the optimizer sits outside
    autodiff). Shapes cover a ragged grid row count, a >1-block leaf, a
    vector leaf, and a scalar leaf."""
    from vitax.ops.fused_optimizer import fused_clip_adamw
    from vitax.train.state import ADAMW_HPARAMS
    b1, b2, eps = (ADAMW_HPARAMS[k] for k in ("b1", "b2", "eps"))
    wd, clip, lr = 0.05, 1.0, 3e-4
    shapes = [(2, 37, 96), (70_000, 8), (128,), ()]
    keys = jax.random.split(jax.random.key(7), 3 * len(shapes))
    params = {f"leaf{i}": jax.random.normal(keys[3 * i], s, jnp.float32)
              for i, s in enumerate(shapes)}
    grads = {f"leaf{i}": 4.0 * jax.random.normal(keys[3 * i + 1], s,
                                                 jnp.float32)
             for i, s in enumerate(shapes)}  # norm > clip: clip branch live
    mu = {f"leaf{i}": 0.1 * jax.random.normal(keys[3 * i + 2], s, jnp.float32)
          for i, s in enumerate(shapes)}
    nu = {k: v * v for k, v in mu.items()}
    import optax
    opt_state = (optax.ScaleByAdamState(count=jnp.int32(3), mu=mu, nu=nu),)
    gnorm = optax.global_norm(grads)

    got_p, got_s = jax.jit(lambda g, s, p, n: fused_clip_adamw(
        g, s, p, grad_norm=n, schedule=lambda c: lr, clip_norm=clip,
        weight_decay=wd, b1=b1, b2=b2, eps=eps))(grads, opt_state, params,
                                                 gnorm)

    def closed_form(g, p, m, v):
        g = g * jnp.minimum(1.0, clip / gnorm)
        m2 = (1 - b1) * g + b1 * m
        v2 = (1 - b2) * g * g + b2 * v
        upd = (m2 / (1 - b1 ** 4)) / (jnp.sqrt(v2 / (1 - b2 ** 4)) + eps)
        return p - lr * (upd + wd * p), m2, v2

    ok = True
    for name in params:
        want = closed_form(grads[name], params[name], mu[name], nu[name])
        got = (got_p[name], got_s[0].mu[name], got_s[0].nu[name])
        for tag, g, w in zip(("p", "mu", "nu"), got, want):
            g, w = np.asarray(g, np.float32), np.asarray(w, np.float32)
            err = float(np.max(np.abs(g - w)) / max(1e-6,
                                                    np.max(np.abs(w))))
            status = "ok" if err < 1e-5 else "FAIL"
            print(f"  fused adamw {name:24s} {tag:3s} rel-max-err "
                  f"{err:.2e} {status}")
            if err >= 1e-5:
                ok = False
    if int(got_s[0].count) != 4:
        print(f"  fused adamw count FAIL: {int(got_s[0].count)} != 4")
        ok = False
    return ok


def check_dequant_matmul() -> bool:
    """Mosaic-lowered fused dequant-matmul vs the closed-form numpy math.

    Three modes per the serve paths (vitax/ops/dequant_matmul.py): int8
    weight-only, int8 weights + int8 activations (the MXU i8xi8->i32 path),
    and fp8 weight-only. The kernel's k-loop accumulates in i32 (act) or
    f32 (weight-only) with the scales applied once after — the closed form
    reproduces that exactly, so agreement is tight (1e-5 relative), not an
    accuracy-style tolerance. Shapes cover ragged m/k/n (block padding) and
    an aligned case."""
    import ml_dtypes

    from vitax.ops.dequant_matmul import dequant_matmul, quantize_activations

    rng = np.random.default_rng(11)
    ok = True
    for (m, k, n) in [(64, 128, 256), (130, 257, 96)]:
        x = rng.standard_normal((m, k)).astype(np.float32)
        w = rng.standard_normal((k, n)).astype(np.float32) * 3.0
        scale = (np.abs(w).max(axis=0, keepdims=True) / 127.0).astype(
            np.float32)
        w_i8 = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
        s_fp8 = (np.abs(w).max(axis=0, keepdims=True) / 240.0).astype(
            np.float32)
        w_fp8 = (w / s_fp8).astype(ml_dtypes.float8_e4m3)

        cases = {
            "int8 weight-only": (
                dequant_matmul(x, jnp.asarray(w_i8), jnp.asarray(scale),
                               act=False, fused=True, interpret=False),
                x @ (w_i8.astype(np.float32) * scale)),
            "fp8 weight-only": (
                dequant_matmul(x, jnp.asarray(w_fp8), jnp.asarray(s_fp8),
                               act=False, fused=True, interpret=False),
                x @ (w_fp8.astype(np.float32) * s_fp8)),
        }
        xq, sx = jax.device_get(quantize_activations(jnp.asarray(x)))
        cases["int8 act-quant"] = (
            dequant_matmul(x, jnp.asarray(w_i8), jnp.asarray(scale),
                           act=True, fused=True, interpret=False),
            (xq.astype(np.int32) @ w_i8.astype(np.int32)).astype(np.float32)
            * float(sx) * scale)

        for name, (got, want) in cases.items():
            got = np.asarray(jax.device_get(got), np.float32)
            err = float(np.max(np.abs(got - want))
                        / max(1e-6, float(np.max(np.abs(want)))))
            status = "ok" if err < 1e-5 else "FAIL"
            print(f"  dequant matmul {name:18s} ({m}x{k}x{n}) rel-max-err "
                  f"{err:.2e} {status}")
            if err >= 1e-5:
                ok = False
    return ok


if __name__ == "__main__":
    sys.exit(main())
