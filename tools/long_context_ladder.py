"""Streaming-kernel block-size ladder + long-N frontier, on chip.

Round-4 measured the streaming kernel (vitax/ops/flash_blocked.py) only at
its untuned DEFAULT_BLOCK_Q/K = 512 (BASELINE.md "Long-context on chip").
This ladder sweeps (block_q, block_k) over {256, 512, 1024}^2 at N = 4,096
and N = 9,216, then pushes the max trainable N at ViT-L width with the
winning blocks (16k+). Same end-to-end train-step methodology as round 4:
ViT-L width (1024d/16h), 4 blocks, batch 2, none_saveable remat, N set by
the image size (N = (image/14)^2), single v5e chip.

Usage:
    python tools/long_context_ladder.py [--steps 10] [--out LADDER_LONGCTX.jsonl]

Each row: {"n": N, "block_q": bq, "block_k": bk, "ms_per_step": t | null,
           "error": ...}. The dense arm at N=4,096 re-verifies the round-4
comparison point. tools/apply_ladder.py is NOT involved — the winner is
applied by editing DEFAULT_BLOCK_Q/K with a BASELINE.md note.
"""

from __future__ import annotations

import argparse
import functools
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(n_tokens: int, block_q, block_k, steps: int, dense: bool = False):
    """ms/step for one config in a FRESH subprocess (an OOM must not poison
    the parent or the remaining rows)."""
    code = f"""
import sys, time, json
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
import jax, jax.numpy as jnp, numpy as np
from jax.sharding import NamedSharding
from vitax.config import Config
from vitax.models import build_model
from vitax.parallel.mesh import build_mesh, batch_pspec
from vitax.train.state import build_optimizer, make_train_state
from vitax.train.step import make_train_step

side = 14 * int(round({n_tokens} ** 0.5))
cfg = Config(image_size=side, patch_size=14, embed_dim=1024, num_heads=16,
             num_blocks=4, num_classes=1000, batch_size=2, warmup_steps=0,
             grad_ckpt=True, remat_policy="none_saveable").validate()
assert cfg.num_patches == {n_tokens}, cfg.num_patches
if {dense!r}:
    impl = None
else:
    from vitax.ops.flash_blocked import blocked_flash_attention
    import functools
    impl = functools.partial(blocked_flash_attention,
                             block_q={block_q}, block_k={block_k})
mesh = build_mesh(cfg)
model = build_model(cfg, attention_impl=impl)
tx, schedule = build_optimizer(cfg, max_iteration=100)
state, sspecs, _ = make_train_state(cfg, model, tx, mesh, jax.random.key(0))
step = make_train_step(cfg, model, tx, mesh, sspecs, schedule=schedule)
sh = NamedSharding(mesh, batch_pspec())
rng = np.random.default_rng(0)
batch = {{
    "image": jax.device_put(jnp.asarray(rng.normal(
        size=(cfg.batch_size, side, side, 3)), jnp.float32), sh),
    "label": jax.device_put(jnp.asarray(rng.integers(
        0, 1000, size=(cfg.batch_size,)), jnp.int32), sh),
}}
key = jax.random.key(1)
for _ in range(3):
    state, metrics = step(state, batch, key)
float(jax.device_get(metrics["loss"]))
t0 = time.perf_counter()
for _ in range({steps}):
    state, metrics = step(state, batch, key)
loss = float(jax.device_get(metrics["loss"]))
dt = time.perf_counter() - t0
assert np.isfinite(loss), loss
print("RESULT " + json.dumps({{"ms_per_step": dt / {steps} * 1e3}}))
"""
    import subprocess
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=1200)
    for line in r.stdout.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])["ms_per_step"], None
    err = (r.stderr or "")[-400:]
    return None, err.replace("\n", " ")[-400:]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--blocks", type=int, nargs="+", default=[256, 512, 1024])
    ap.add_argument("--ns", type=int, nargs="+", default=[4096, 9216])
    ap.add_argument("--frontier", type=int, nargs="+", default=[16384, 25600])
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "LADDER_LONGCTX.jsonl"))
    args = ap.parse_args()

    rows = []

    def record(n, bq, bk, dense=False):
        ms, err = measure(n, bq, bk, args.steps, dense=dense)
        row = {"n": n, "block_q": bq, "block_k": bk, "dense": dense,
               "ms_per_step": None if ms is None else round(ms, 1),
               "error": err}
        print(json.dumps(row), flush=True)
        rows.append(row)
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
        return ms

    # dense comparison arm (round-4 point: 224.5 ms at N=4096)
    record(4096, 0, 0, dense=True)
    for n in args.ns:
        for bq in args.blocks:
            for bk in args.blocks:
                record(n, bq, bk)

    done = [r for r in rows if not r["dense"] and r["ms_per_step"]]
    if done:
        best = min(done, key=lambda r: r["ms_per_step"])
        print(f"[ladder] winner at N={best['n']}: "
              f"bq={best['block_q']} bk={best['block_k']} "
              f"{best['ms_per_step']} ms", flush=True)
        # long-N frontier with the winning blocks
        for n in args.frontier:
            side = 14 * int(round(n ** 0.5))
            if (side // 14) ** 2 != n:
                continue
            record(n, best["block_q"], best["block_k"])


if __name__ == "__main__":
    main()
