#!/usr/bin/env python3
"""Pack an ImageFolder tree into `.vtxshard` streaming containers.

    python tools/make_shards.py --src /data/imagenet --dst /data/imagenet-shards
    python tools/make_shards.py --src /data/imagenet --dst ... --shard_size_mb 100

Reads each split (`train/`, `val/` — whichever exist) with the SAME listing
contract as ImageFolderDataset (sorted class subdirectories, sorted os.walk
within; vitax/data/imagefolder.py), so record order is the dataset's index
order and labels are the identical class indices. Payloads are the original
file bytes, verbatim — no re-encode — which is what makes the streaming and
ImageFolder pipelines deliver bit-identical samples (tests/test_stream.py
pins this).

Output per split: size-targeted `shard-NNNNN.vtxshard` files (default ~100
MB), a JSON index per shard, and a `stream_meta.json` manifest
(vitax/data/stream/format.py). Point `--data_dir` at `--dst` with
`--data_format stream` to train from it.

Accelerator-free: imports only vitax.data.stream.format (no jax at work).
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable as `python tools/make_shards.py`
    sys.path.insert(0, _REPO)

from vitax.data.stream.format import DEFAULT_SHARD_SIZE_MB, ShardWriter  # noqa: E402

# the extensions ImageFolderDataset accepts (vitax/data/imagefolder.py)
IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")

SPLITS = ("train", "val")


def list_imagefolder(root: str):
    """(classes, [(path, label), ...]) with ImageFolderDataset's exact
    listing order — record i of the shard stream is sample i of the
    ImageFolder dataset."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    if not classes:
        raise FileNotFoundError(f"no class subdirectories under {root}")
    class_to_idx = {c: i for i, c in enumerate(classes)}
    samples = []
    for cls in classes:
        cls_dir = os.path.join(root, cls)
        for dirpath, _, filenames in sorted(os.walk(cls_dir)):
            for fname in sorted(filenames):
                if fname.lower().endswith(IMG_EXTENSIONS):
                    samples.append((os.path.join(dirpath, fname),
                                    class_to_idx[cls]))
    if not samples:
        raise FileNotFoundError(f"no images found under {root}")
    return classes, samples


def pack_split(src_split: str, dst_split: str,
               shard_size_mb: float = DEFAULT_SHARD_SIZE_MB,
               quiet: bool = False) -> dict:
    """Pack one ImageFolder split directory into shards; returns the split
    manifest (also written as stream_meta.json)."""
    classes, samples = list_imagefolder(src_split)
    writer = ShardWriter(dst_split, classes=classes,
                         shard_size_mb=shard_size_mb)
    for path, label in samples:
        with open(path, "rb") as f:
            writer.add(f.read(), label)
    meta = writer.close()
    if not quiet:
        print(f"{dst_split}: {meta['num_records']} records, "
              f"{len(meta['shards'])} shard(s), "
              f"{len(meta['classes'])} classes")
    return meta


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="pack an ImageFolder tree into .vtxshard streaming "
                    "containers")
    ap.add_argument("--src", required=True,
                    help="ImageFolder root (holds train/ and/or val/)")
    ap.add_argument("--dst", required=True,
                    help="output shard root (mirrors the split layout)")
    ap.add_argument("--shard_size_mb", type=float,
                    default=DEFAULT_SHARD_SIZE_MB,
                    help="target shard size in MB (default %(default)s)")
    ap.add_argument("--splits", nargs="*", default=None,
                    help=f"splits to pack (default: whichever of {SPLITS} "
                         "exist under --src)")
    args = ap.parse_args(argv)

    if args.shard_size_mb <= 0:
        ap.error("--shard_size_mb must be positive")
    splits = args.splits
    if not splits:
        splits = [s for s in SPLITS
                  if os.path.isdir(os.path.join(args.src, s))]
        if not splits:
            ap.error(f"no {'/'.join(SPLITS)} splits under {args.src}")
    for split in splits:
        src_split = os.path.join(args.src, split)
        if not os.path.isdir(src_split):
            ap.error(f"split directory not found: {src_split}")
        pack_split(src_split, os.path.join(args.dst, split),
                   args.shard_size_mb)
    return 0


if __name__ == "__main__":
    sys.exit(main())
