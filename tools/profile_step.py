#!/usr/bin/env python3
"""Capture a jax.profiler trace of the bench train step and print a step-time
breakdown (VERDICT round-2 item 3: account for where the non-MFU time goes).

Usage: python tools/profile_step.py --preset l14 [--steps 8] [--out /tmp/prof]

Parses the xplane via xprof's framework_op_stats converter into a table of
self-time by op category (fusion kinds, custom-call kernels, copies, infeed),
printed as JSON + a human table. This is the measurement side of the
BASELINE.md "where the step time goes" section.
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="l14",
                   choices=["tiny", "b16", "b16_moe", "l14", "10b", "10b_slice"])
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--warmup", type=int, default=3)
    # the shared knob-flag group (vitax/tune/knobs.py): identical surface to
    # bench.py so a trace explains exactly the config the bench measured,
    # --preset_file included (profile a committed autotune winner)
    from vitax.tune.knobs import add_knob_args, knob_payload, knobs_from_args
    add_knob_args(p)
    p.add_argument("--out", default="/tmp/vitax_profile")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding

    from bench import model_flops_per_image, detect_peak_tflops
    from vitax.config import Config
    from vitax.models import build_model
    from vitax.ops.attention import make_attention_impl
    from vitax.parallel.mesh import build_mesh, batch_pspec
    from vitax.train.state import build_optimizer, make_train_state
    from vitax.train.step import make_train_step

    n_dev = jax.device_count()
    device_kind = jax.devices()[0].device_kind  # vtx: ignore[VTX104] CLI entry point: labels the backend being profiled
    # presets and remat defaults come FROM bench.py so traces explain exactly
    # the configs the bench measures
    from bench import apply_preset_file, resolve_bench_knobs, train_presets
    apply_preset_file(args, n_dev)
    kn = knobs_from_args(args)
    kw = kn.apply_to_preset_kw(train_presets(n_dev)[args.preset])
    (args.scan_blocks, args.scan_unroll, args.remat_window,
     args.remat_policy) = resolve_bench_knobs(
        args.scan_blocks, args.scan_unroll, args.remat_window,
        args.remat_policy, args.preset,
        other_explicit=kn.other_explicit())
    cfg = Config(num_classes=1000, warmup_steps=0,
                 remat_policy=args.remat_policy, grad_ckpt=args.grad_ckpt,
                 scan_blocks=args.scan_blocks, scan_unroll=args.scan_unroll,
                 remat_window=args.remat_window,
                 use_flash_attention=args.use_flash_attention, **kw).validate()
    print("knobs:", json.dumps(knob_payload(cfg, n_dev), sort_keys=True))

    mesh = build_mesh(cfg)
    model = build_model(cfg, attention_impl=make_attention_impl(cfg, mesh))
    tx, schedule = build_optimizer(cfg, max_iteration=10_000)
    state, sspecs, _ = make_train_state(cfg, model, tx, mesh, jax.random.key(0))
    step_fn = make_train_step(cfg, model, tx, mesh, sspecs, schedule=schedule)

    sh = NamedSharding(mesh, batch_pspec())
    rng = np.random.default_rng(0)
    batch = {
        "image": jax.device_put(jnp.asarray(
            rng.normal(size=(cfg.batch_size, cfg.image_size, cfg.image_size, 3)),
            jnp.float32), sh),
        "label": jax.device_put(jnp.asarray(
            rng.integers(0, cfg.num_classes, size=(cfg.batch_size,)),
            jnp.int32), sh),
    }
    rng_key = jax.random.key(1)

    for _ in range(args.warmup):
        state, metrics = step_fn(state, batch, rng_key)
    float(jax.device_get(metrics["loss"]))

    import time
    jax.profiler.start_trace(args.out)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, metrics = step_fn(state, batch, rng_key)
    float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0
    jax.profiler.stop_trace()

    step_ms = dt / args.steps * 1e3
    flops = model_flops_per_image(cfg) * cfg.batch_size
    peak = detect_peak_tflops(device_kind)
    mfu = flops / (dt / args.steps) / (peak * 1e12 * n_dev)
    print(f"\n== {args.preset} remat={args.remat_policy} "
          f"batch={cfg.batch_size}: "
          f"{step_ms:.1f} ms/step, MFU {mfu:.3f} ({device_kind}) ==")

    xplanes = sorted(glob.glob(
        os.path.join(args.out, "**", "*.xplane.pb"), recursive=True))
    if not xplanes:
        print("no xplane captured (device tracing unavailable on this "
              "transport); trace dir:", args.out)
        return
    analyze_xplane(xplanes[-1], args.steps, step_ms, peak)


def analyze_xplane(xplane_path: str, n_steps: int, wall_step_ms: float,
                   peak_tflops: float) -> None:
    """Direct xplane parse: device time by HLO category + top ops, with
    per-category achieved FLOP/s and HBM bytes (roofline attribution)."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2 as xpb

    space = xpb.XSpace()
    with open(xplane_path, "rb") as f:
        space.ParseFromString(f.read())
    tpu_planes = [p for p in space.planes if "/device:TPU" in p.name]
    if not tpu_planes:
        print("no TPU device plane in trace; planes:",
              [p.name for p in space.planes])
        return
    plane = tpu_planes[0]
    print(f"xplane: {xplane_path} (plane {plane.name})")

    def md_stat(md, name):
        for s in md.stats:
            if plane.stat_metadata[s.metadata_id].name == name:
                return (s.str_value or s.int64_value or s.uint64_value
                        or s.double_value)
        return None

    ops_lines = [l for l in plane.lines if l.name == "XLA Ops"]
    steps_lines = [l for l in plane.lines if l.name == "Steps"]
    if not ops_lines:
        print("no 'XLA Ops' line; lines:", [l.name for l in plane.lines])
        return

    device_step_ms = None
    if steps_lines and steps_lines[0].events:
        evs = steps_lines[0].events
        device_step_ms = sum(e.duration_ps for e in evs) / len(evs) / 1e9

    by_cat = {}  # cat -> [time_ps, flops, bytes]
    by_op = {}
    for ev in ops_lines[0].events:
        md = plane.event_metadata[ev.metadata_id]
        cat = str(md_stat(md, "hlo_category") or "?")
        flops = float(md_stat(md, "flops") or 0)
        nbytes = float(md_stat(md, "bytes_accessed") or 0)
        slot = by_cat.setdefault(cat, [0.0, 0.0, 0.0])
        slot[0] += ev.duration_ps
        slot[1] += flops
        slot[2] += nbytes
        oslot = by_op.setdefault(md.display_name or md.name,
                                 [0.0, 0.0, 0.0, cat])
        oslot[0] += ev.duration_ps
        oslot[1] += flops
        oslot[2] += nbytes

    total_ps = sum(v[0] for v in by_cat.values())
    busy_ms = total_ps / 1e9 / n_steps
    print(f"\nwall step: {wall_step_ms:.1f} ms | device busy: "
          f"{busy_ms:.1f} ms/step"
          + (f" | device step span: {device_step_ms:.1f} ms" if device_step_ms
             else "")
          + f" | gap (host/dispatch): {wall_step_ms - busy_ms:.1f} ms")
    print(f"\n-- device time by HLO category ({n_steps} steps) --")
    print(f"{'%time':>7} {'ms/step':>9} {'TFLOP/s':>9} {'GB/s':>8}  category")
    for cat, (ps, fl, by) in sorted(by_cat.items(), key=lambda kv: -kv[1][0]):
        sec = ps / 1e12
        print(f"{ps/total_ps*100:6.2f}% {ps/1e9/n_steps:9.2f} "
              f"{fl/sec/1e12 if sec else 0:9.1f} {by/sec/1e9 if sec else 0:8.0f}"
              f"  {cat}")
    print(f"\n-- top 15 ops by device time (peak {peak_tflops:.0f} TF/s) --")
    for name, (ps, fl, by, cat) in sorted(
            by_op.items(), key=lambda kv: -kv[1][0])[:15]:
        sec = ps / 1e12
        print(f"{ps/total_ps*100:6.2f}% {ps/1e9/n_steps:8.2f}ms "
              f"{fl/sec/1e12 if sec else 0:7.1f}TF/s "
              f"{by/sec/1e9 if sec else 0:6.0f}GB/s [{cat[:12]:12}] {name[:70]}")


if __name__ == "__main__":
    main()
