#!/usr/bin/env python3
"""CI gate: verify compiled-program invariants across parallelism arms.

CPU-AOT-lowers the train step for each parallelism arm (dp / zero2 / zero3 /
zero3_overlap / accum / moe — plus warmed-up serve engines, full-precision
and int8-quantized), then runs every
applicable rule from vitax.analysis.rules over the lowered StableHLO and the
post-`spmd-partitioning` HLO. The partitioned module is the real program
(GSPMD lineage): properties like "gathers are bf16", "state buffers are
donated", "no host transfer inside the step" are only checkable there, and
this gate is what keeps future refactors from silently regressing them.

Usage:
    python tools/check_invariants.py                  # all arms, human report
    python tools/check_invariants.py --arms zero3_overlap serve
    python tools/check_invariants.py --json           # machine-readable

JSON contract (schema 1):
    {"schema": 1,
     "arms": {"<arm>": {"ok": bool, "rules_ran": [rule ids],
                        "findings": [{rule, severity, arm, message, details}]}},
     "findings": [...all findings...],
     "errors": {"<arm>": "<traceback tail>"},   # arms that failed to build
     "concurrency": {"ok": bool,                # VTX200-series thread lint
                     "findings": [{code, severity, path, line, message}]},
     "ok": bool}

Exit status: 0 when every requested arm built and produced no ERROR-severity
finding; 1 otherwise. WARN findings are reported but do not fail the gate.
"""

import argparse
import contextlib
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Must precede any jax import: the arms shard over an 8-device host mesh.
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def run(arms, as_json):
    from vitax.analysis import rules as R

    report = {"schema": 1, "arms": {}, "findings": [], "errors": {}, "ok": True}
    for arm in arms:
        t0 = time.time()
        try:
            # library chatter (serve warmup timings) must not precede the
            # JSON document on stdout
            guard = (contextlib.redirect_stdout(sys.stderr) if as_json
                     else contextlib.nullcontext())
            with guard:
                program = R.build_program(arm)
                ran, findings = R.run_rules(program)
        except Exception:
            tb = traceback.format_exc().strip().splitlines()
            report["errors"][arm] = "\n".join(tb[-3:])
            report["ok"] = False
            if not as_json:
                print(f"[{arm}] BUILD FAILED:\n" + "\n".join(tb[-3:]),
                      file=sys.stderr)
            continue
        rows = [f.to_json() for f in findings]
        arm_ok = not any(f.severity == "ERROR" for f in findings)
        report["arms"][arm] = {"ok": arm_ok, "rules_ran": ran,
                               "findings": rows}
        report["findings"].extend(rows)
        report["ok"] = report["ok"] and arm_ok
        if not as_json:
            status = "ok" if arm_ok else "FAIL"
            print(f"[{arm}] {status} ({time.time() - t0:.1f}s) — "
                  f"rules: {', '.join(ran) if ran else 'none applicable'}")
            for f in findings:
                print(f"    {f.rule} [{f.severity}] {f.message}")

    # host-program concurrency discipline (vitax.analysis.concurrency):
    # same gate, different program — the thread model is as much a compiled
    # invariant of this codebase as the HLO properties above
    from vitax.analysis import concurrency as C
    cfinds = C.lint_paths(["vitax", "tools"])
    conc_ok = not cfinds
    report["concurrency"] = {"ok": conc_ok,
                             "findings": [f.to_json() for f in cfinds]}
    report["ok"] = report["ok"] and conc_ok
    if not as_json:
        status = "ok" if conc_ok else "FAIL"
        print(f"[concurrency] {status} — VTX200-series over vitax/ + tools/")
        for f in cfinds:
            print(f"    {f.format()}")
    return report


def main(argv=None):
    from vitax.analysis import rules as R

    parser = argparse.ArgumentParser(
        prog="tools/check_invariants.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--arms", nargs="+", choices=list(R.ALL_ARMS),
                        default=list(R.ALL_ARMS),
                        help="parallelism arms to verify (default: all)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the JSON CI contract on stdout")
    args = parser.parse_args(argv)

    report = run(args.arms, args.as_json)
    if args.as_json:
        print(json.dumps(report, indent=2))
    elif report["ok"]:
        print("check_invariants: all arms clean")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
