#!/usr/bin/env python
"""Launcher shim: `python tools/supervise.py [flags] -- python
run_vit_training.py ...` — see vitax/supervise.py for the restart loop,
exit-code contract, elastic (topology-change) restart detection
(--expect_processes), and flags."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from vitax.supervise import main  # noqa: E402  (sys.path fix must precede)

if __name__ == "__main__":
    sys.exit(main())
