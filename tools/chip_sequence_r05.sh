#!/bin/bash
# Round-5 end-of-round chip sequence: waits for the axon tunnel to return,
# then (1) validates every kernel family incl. the round-5 dropout variants
# on hardware, (2) re-measures every bench preset at HEAD with
# --write_baseline (the scoreboard contract: BENCH_r05 must reflect round-5
# code, VERDICT r4 item 10), (3) takes the e2e feed+train number, and
# (4) probes the tiny preset's batch sensitivity. Logs to WATCHER_R05.log.
set -u
cd "$(dirname "$0")/.."
LOG=WATCHER_R05.log
log() { echo "[$(date +%H:%M:%S)] $*" >> "$LOG"; }

log "watcher started; probing for the chip"
until timeout 120 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" 2>/dev/null; do
  log "chip still down; retrying in 120s"
  sleep 120
done
log "chip is UP — running the sequence"

log "=== check_kernels_on_chip (incl. dropout variants)"
timeout 900 python tools/check_kernels_on_chip.py >> "$LOG" 2>&1
log "kernel check rc=$?"

for preset in tiny b16 b16_moe l14 10b_slice; do
  log "=== bench --preset $preset --write_baseline"
  timeout 900 python bench.py --preset "$preset" --write_baseline 2>>"$LOG" \
    | tail -1 >> "$LOG"
done

log "=== bench --preset data / data_scaling (feed ratios vs fresh numbers)"
timeout 900 python bench.py --preset data --write_baseline 2>>"$LOG" | tail -1 >> "$LOG"
timeout 900 python bench.py --preset data_scaling --write_baseline 2>>"$LOG" | tail -1 >> "$LOG"

log "=== bench --preset e2e (10b_slice feed+train, overlap)"
timeout 1800 python bench.py --preset e2e --write_baseline 2>>"$LOG" | tail -1 >> "$LOG"

log "=== e2e feed-limited arms (l14/b16 on a 1-core host — honest input-bound numbers)"
timeout 1800 python bench.py --preset e2e --e2e_train_preset l14 2>>"$LOG" | tail -1 >> "$LOG"
timeout 1800 python bench.py --preset e2e --e2e_train_preset b16 2>>"$LOG" | tail -1 >> "$LOG"

log "=== tiny batch probe (128, 256 — fixed-overhead amortization)"
timeout 900 python bench.py --preset tiny --batch_size 128 2>>"$LOG" | tail -1 >> "$LOG"
timeout 900 python bench.py --preset tiny --batch_size 256 2>>"$LOG" | tail -1 >> "$LOG"

log "=== l14 att_dropout arm at HEAD (in-kernel path)"
timeout 900 python bench.py --preset l14 --att_dropout 0.1 2>>"$LOG" | tail -1 >> "$LOG"

log "sequence DONE"
