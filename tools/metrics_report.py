#!/usr/bin/env python3
"""Summarize a vitax telemetry JSONL run (vitax/telemetry/, schema 1).

Human mode prints the run at a glance — step range, p50/p95 sec/iter, MFU,
data-wait fraction, checkpoint-stall percentiles, peer-replication volume
and restore path, throughput, a loss sparkline, memory peak, watchdog
events; `--json` emits the same summary as one JSON object for CI.

    python tools/metrics_report.py /runs/exp7/metrics.jsonl
    python tools/metrics_report.py /runs/exp7/metrics.jsonl --json

Accelerator-free: reads only the JSONL file. Corrupt lines (a run killed
mid-write can truncate at most the last one) are counted, never fatal.
Exit status: 0 with >= 1 step record, 2 otherwise.
"""

from __future__ import annotations

import argparse
import json
import sys

SPARK_CHARS = "▁▂▃▄▅▆▇█"


def percentile(sorted_vals, q: float) -> float:
    """Linear-interpolated percentile of an ascending list (numpy-free: the
    report must run on bare CI hosts)."""
    if not sorted_vals:
        return float("nan")
    if len(sorted_vals) == 1:
        return float(sorted_vals[0])
    pos = (len(sorted_vals) - 1) * q
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac)


def sparkline(vals, width: int = 40) -> str:
    """Downsampled unicode sparkline (empty string for < 2 points)."""
    if len(vals) < 2:
        return ""
    if len(vals) > width:  # mean-pool into `width` buckets
        step = len(vals) / width
        vals = [sum(vals[int(i * step):max(int((i + 1) * step), int(i * step) + 1)])
                / max(int((i + 1) * step) - int(i * step), 1)
                for i in range(width)]
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(
        SPARK_CHARS[min(int((v - lo) / span * (len(SPARK_CHARS) - 1)),
                        len(SPARK_CHARS) - 1)]
        for v in vals)


def load_records(path: str):
    """(step_records, event_records, corrupt_line_count). Step records are
    sorted by step; anything with a `kind` tag is an event."""
    steps, events, corrupt = [], [], 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                corrupt += 1
                continue
            if not isinstance(rec, dict):
                corrupt += 1
            elif rec.get("kind"):
                events.append(rec)
            elif "step" in rec and "loss" in rec:
                steps.append(rec)
            else:
                corrupt += 1
    steps.sort(key=lambda r: r["step"])
    return steps, events, corrupt


def summarize(path: str) -> dict:
    steps, events, corrupt = load_records(path)
    summary = {
        "path": path,
        "schema": steps[0].get("schema") if steps else None,
        "records": len(steps),
        "events": len(events),
        "corrupt_lines": corrupt,
        "hang_events": sum(1 for e in events if e.get("kind") == "hang"),
        "fault_events": sum(1 for e in events if e.get("kind") == "fault"),
        "hang_escalations": sum(1 for e in events
                                if e.get("kind") == "hang_escalation"),
        # vitax/telemetry/threads.py excepthook: uncaught background-thread
        # exceptions (healthy runs hold this at 0)
        "thread_crashes": sum(1 for e in events
                              if e.get("kind") == "thread_crash"),
        # fleet serving (vitax/serve/fleet/ writes these into serve.jsonl —
        # point this report at it for the overload/rotation story)
        "admission_shed_count": sum(1 for e in events
                                    if e.get("kind") == "admission"),
        "replica_restarts": sum(1 for e in events
                                if e.get("kind") == "replica_restart"),
        # serve-path chaos layer (vitax/faults.py serve sites + the
        # router's containment: breaker/budget/hedge/brownout events)
        "serve_fault_events": sum(1 for e in events
                                  if e.get("kind") == "serve_fault"),
        "breaker_open_count": sum(
            1 for e in events if e.get("kind") == "breaker"
            and e.get("event") in ("open", "reopen")),
        "retry_budget_exhausted": sum(
            1 for e in events if e.get("kind") == "retry_budget"
            and e.get("event") == "exhausted"),
        "hedge_count": sum(1 for e in events if e.get("kind") == "hedge"
                           and e.get("event") == "fired"),
        "hedge_wins": sum(1 for e in events if e.get("kind") == "hedge"
                          and e.get("event") == "win"),
        # completed brownout episodes only (exit events carry the length;
        # a run killed while degraded under-counts by the live episode)
        "brownout_seconds": round(sum(
            float(e.get("degraded_s", 0.0)) for e in events
            if e.get("kind") == "brownout" and e.get("event") == "exit"), 3),
    }
    # fleet growth (vitax/serve/fleet/autoscale.py): scaling actions by
    # outcome, mirroring the control_events bucket style
    autoscale = [e for e in events if e.get("kind") == "autoscale"]
    summary["autoscale_events"] = {
        "scale_out": sum(1 for e in autoscale
                         if e.get("event") == "scale_out"),
        "scale_in": sum(1 for e in autoscale
                        if e.get("event") == "scale_in"),
        "retires": sum(1 for e in autoscale if e.get("event") == "retire"),
        "scale_out_failures": sum(1 for e in autoscale
                                  if e.get("event") == "scale_out_failed"),
        "forced_drains": sum(1 for e in autoscale
                             if e.get("event") == "scale_in"
                             and e.get("forced")),
        # a maxed-out (or agent-full) fleet asking the chip arbiter for a
        # whole host instead of failing the scale-out
        "escalations": sum(1 for e in autoscale
                           if e.get("event") == "scale_out"
                           and e.get("outcome") == "escalated"),
    }
    # chip arbitration (vitax/arbiter/): borrow/return/deny traffic, with
    # denies bucketed by the policy's reason so hysteresis is visible
    arbiter = [e for e in events if e.get("kind") == "arbiter"]
    deny_reasons: dict = {}
    for e in arbiter:
        if e.get("event") == "deny":
            reason = str(e.get("reason", "unknown"))
            deny_reasons[reason] = deny_reasons.get(reason, 0) + 1
    summary["arbiter_events"] = {
        "requests": sum(1 for e in arbiter if e.get("event") == "request"),
        "borrows": sum(1 for e in arbiter if e.get("event") == "borrow"),
        "returns": sum(1 for e in arbiter if e.get("event") == "return"),
        "borrow_failures": sum(1 for e in arbiter
                               if e.get("event") == "borrow_failed"),
        "return_failures": sum(1 for e in arbiter
                               if e.get("event") == "return_failed"),
        "denies": deny_reasons,
    }
    # prediction cache (vitax/serve/fleet/cache.py): hit events carry
    # running totals, so the LAST one yields the rate (misses are counted
    # router-side but deliberately not emitted per-event)
    cache_events = [e for e in events if e.get("kind") == "cache"]
    if cache_events:
        last_cache = cache_events[-1]
        hits = int(last_cache.get("hits_total", len(cache_events)))
        misses = int(last_cache.get("misses_total", 0))
        summary["cache_hits"] = hits
        summary["cache_hit_rate"] = round(hits / max(hits + misses, 1), 4)
    # batch fill (serve_request events from replicas): how full the padded
    # bucket each request ran in actually was — the continuous-batching
    # acceptance metric (composed dispatch raises the p50)
    fills = sorted(e["batch_size"] / max(e.get("bucket", 1), 1)
                   for e in events
                   if e.get("kind") == "serve_request" and "batch_size" in e)
    if fills:
        summary["batch_fill_p50"] = round(percentile(fills, 0.50), 4)
        summary["batch_fill_p95"] = round(percentile(fills, 0.95), 4)
    # control plane (vitax/train/control.py + the supervisor's elastic
    # restarts): kind:"control" records, bucketed by their `event` field
    control = [e for e in events if e.get("kind") == "control"]
    summary["control_events"] = {
        "agreed_preemptions": sum(1 for e in control
                                  if e.get("event") == "agreed_preempt"),
        "agreed_escalations": sum(1 for e in control
                                  if e.get("event") == "agreed_escalation"),
        "peer_loss_detections": sum(1 for e in control
                                    if e.get("event") == "peer_loss"),
        "topology_changes": sum(1 for e in control
                                if e.get("event") == "topology_change"),
        "elastic_resumes": sum(1 for e in control
                               if e.get("event") == "elastic_resume"),
    }
    # the training pod's process-count history: every topology flip the
    # control plane saw (supervisor/arbiter `topology_change` observations
    # and the loop's own `elastic_resume` actions), in record order — an
    # arbiter borrow/return drill reads N -> N-1 -> N here
    summary["train_topology_timeline"] = [
        {"event": e.get("event"),
         "from_processes": e.get("from_processes"),
         "to_processes": e.get("to_processes")}
        for e in control
        if e.get("event") in ("topology_change", "elastic_resume")]
    summary["hang_hard_exits"] = sum(1 for e in events
                                     if e.get("kind") == "hang_hard_exit")
    # zero-stall checkpointing + peer replication (vitax/checkpoint/
    # snapshot.py + peer.py): replication volume, restore path taken, and
    # whether any peer restore had to fall back to Orbax
    repl = [e for e in events if e.get("kind") == "peer_replication"]
    summary["peer_replication_windows"] = len(repl)
    summary["peer_replication_bytes"] = sum(
        int(e.get("bytes", 0)) for e in repl)
    restores = [e for e in events if e.get("kind") == "restore"]
    summary["peer_restores"] = sum(1 for e in restores
                                   if e.get("path") == "peer")
    summary["restore_path"] = (restores[-1].get("path")
                               if restores else None)
    summary["control_events"]["peer_restore_failures"] = sum(
        1 for e in control if e.get("event") == "peer_restore_failed")
    # supervisor restarts (vitax/supervise.py appends these between child
    # runs, so they interleave with the child's own records)
    restarts = [e for e in events if e.get("kind") == "restart"]
    summary["restart_count"] = len(restarts)
    summary["last_exit_code"] = (restarts[-1].get("exit_code")
                                 if restarts else None)
    evals = [e for e in events if e.get("kind") == "eval"]
    if evals:
        last = max(evals, key=lambda e: (e.get("epoch", 0), e.get("time", 0)))
        summary["eval_last"] = {k: last.get(k)
                                for k in ("epoch", "top1", "top5", "n")}
    # scenario registry (vitax/programs/): finetune warm-start provenance
    # and the distill loss decomposition at the latest log step
    fts = [e for e in events if e.get("kind") == "finetune"]
    if fts:
        last = max(fts, key=lambda e: e.get("time", 0))
        summary["finetune_last"] = {
            k: last.get(k)
            for k in ("init_npz", "loaded", "reinit", "frozen_frac")}
    distills = [e for e in events if e.get("kind") == "distill"]
    if distills:
        last = max(distills, key=lambda e: (e.get("step", 0),
                                            e.get("time", 0)))
        summary["distill_last"] = {
            k: last.get(k)
            for k in ("step", "epoch", "kl", "ce", "teacher_top1",
                      "student_top1", "alpha", "temp")}
    # quantized-serving accuracy gate (vitax/serve/quant.py run_quant_gate):
    # latest quantized-vs-f32 comparison; deltas are in points
    gates = [e for e in events if e.get("kind") == "quant_gate"]
    if gates:
        last = max(gates, key=lambda e: e.get("time", 0))
        summary["quant_gate_last"] = {
            k: last.get(k)
            for k in ("weights_dtype", "baseline_dtype",
                      "act_quant", "fused_dequant",
                      "top1_f32", "top1_quant", "top5_f32", "top5_quant",
                      "delta_top1", "delta_top5", "n")}
    # knob autotuner (tools/autotune.py trial JSONL, vitax/tune/driver.py):
    # point this report at AUTOTUNE_TRIALS.jsonl for the search story —
    # trials by phase, prune reasons, and the measured best/worst spread
    trials = [e for e in events if e.get("kind") == "autotune_trial"]
    if trials:
        pruned = {}
        for t in trials:
            if t.get("pruned_by"):
                pruned[t["pruned_by"]] = pruned.get(t["pruned_by"], 0) + 1
        measured = [t for t in trials if t.get("phase") == "measure"
                    and not t.get("pruned_by")
                    and isinstance(t.get("images_per_sec_chip"),
                                   (int, float))]
        at = {
            "trials": len(trials),
            "analytic": sum(1 for t in trials
                            if t.get("phase") == "analytic"),
            "compiled": sum(1 for t in trials
                            if t.get("phase") == "compile"),
            "measured": len(measured),
            "pruned": pruned,
        }
        if measured:
            best = max(measured, key=lambda t: t["images_per_sec_chip"])
            worst = min(measured, key=lambda t: t["images_per_sec_chip"])
            at["best_images_per_sec_chip"] = round(
                best["images_per_sec_chip"], 2)
            at["worst_images_per_sec_chip"] = round(
                worst["images_per_sec_chip"], 2)
            at["best_mfu"] = (round(best["mfu"], 4)
                              if isinstance(best.get("mfu"), (int, float))
                              else None)
            at["winning_knobs"] = best.get("knobs")
        summary["autotune"] = at
    if not steps:
        return summary

    sec = sorted(r["sec_per_iter"] for r in steps if "sec_per_iter" in r)
    losses = [r["loss"] for r in steps]
    mfus = [r["mfu"] for r in steps if "mfu" in r]
    waits = [r.get("data_wait_s", 0.0) for r in steps]
    stalls = sorted(r["ckpt_stall_s"] for r in steps if "ckpt_stall_s" in r)
    opts = sorted(r["opt_update_s"] for r in steps
                  if r.get("opt_update_s", 0.0) > 0.0)
    # fraction of each recorded step spent waiting on host data (both sides
    # are per-step averages over the same record interval)
    wait_fracs = [r["data_wait_s"] / r["sec_per_iter"] for r in steps
                  if r.get("sec_per_iter") and "data_wait_s" in r]
    summary.update({
        "first_step": steps[0]["step"],
        "last_step": steps[-1]["step"],
        "sec_per_iter_p50": round(percentile(sec, 0.50), 6),
        "sec_per_iter_p95": round(percentile(sec, 0.95), 6),
        "mfu_last": round(mfus[-1], 6) if mfus else None,
        "mfu_max": round(max(mfus), 6) if mfus else None,
        "data_wait_s_mean": round(sum(waits) / len(waits), 6),
        # zero-stall checkpointing acceptance metric: staging time charged
        # to the loop thread per step; ~0 unless a save was synchronous
        "ckpt_stall_s_p50": (round(percentile(stalls, 0.50), 6)
                             if stalls else None),
        "ckpt_stall_s_p95": (round(percentile(stalls, 0.95), 6)
                             if stalls else None),
        # fused-optimizer acceptance metric: fenced wall time of the
        # optimizer-phase probe (records with the probe disabled carry 0
        # and are excluded)
        "opt_update_s_p50": (round(percentile(opts, 0.50), 6)
                             if opts else None),
        "opt_update_s_p95": (round(percentile(opts, 0.95), 6)
                             if opts else None),
        "data_wait_fraction": (round(sum(wait_fracs) / len(wait_fracs), 6)
                               if wait_fracs else None),
        # the streaming data plane's acceptance metric (ROADMAP item 3):
        # fraction of recorded steps that were input-bound — data wait over
        # 10% of the step. A healthy pipeline holds this at ~0.
        "input_bound": (round(sum(1 for w in wait_fracs if w > 0.1)
                              / len(wait_fracs), 6)
                        if wait_fracs else None),
        "loss_first": round(losses[0], 6),
        "loss_last": round(losses[-1], 6),
        "loss_min": round(min(losses), 6),
        "images_per_sec_last": round(steps[-1].get("images_per_sec", 0.0), 2),
        "tokens_per_sec_last": round(steps[-1].get("tokens_per_sec", 0.0), 2),
        "mem_peak_bytes": max((r.get("mem_peak_bytes",
                                     r.get("mem_used_bytes", 0))
                               for r in steps), default=0),
        "loss_curve": [round(v, 4) for v in losses],
    })
    return summary


def print_human(summary: dict) -> None:
    print(f"run: {summary['path']}")
    print(f"  records: {summary['records']} step + {summary['events']} event"
          f" ({summary['corrupt_lines']} corrupt lines skipped), "
          f"schema {summary['schema']}")
    if summary.get("hang_events"):
        print(f"  !! watchdog hang events: {summary['hang_events']}")
    if summary.get("hang_escalations"):
        print(f"  !! watchdog escalations (checkpoint+exit): "
              f"{summary['hang_escalations']}")
    if summary.get("fault_events"):
        print(f"  injected faults fired: {summary['fault_events']}")
    if summary.get("thread_crashes"):
        print(f"  !! background thread crashes: {summary['thread_crashes']}")
    ce = summary.get("control_events") or {}
    if any(ce.values()):
        print(f"  !! control plane: {ce['agreed_preemptions']} agreed "
              f"preemption(s), {ce['agreed_escalations']} agreed "
              f"escalation(s), {ce['peer_loss_detections']} peer loss(es), "
              f"{ce['topology_changes']} topology change(s), "
              f"{ce['elastic_resumes']} elastic resume(s)")
    if ce.get("peer_restore_failures"):
        print(f"  !! peer restores that fell back to Orbax: "
              f"{ce['peer_restore_failures']}")
    if summary.get("peer_replication_windows"):
        print(f"  peer replication: {summary['peer_replication_windows']} "
              f"window(s), "
              f"{summary['peer_replication_bytes'] / 1024 ** 2:.2f} MiB "
              f"mirrored to buddies")
    if summary.get("restore_path"):
        print(f"  restore path: {summary['restore_path']} "
              f"({summary['peer_restores']} peer restore(s))")
    if summary.get("hang_hard_exits"):
        print(f"  !! watchdog hard-deadline exits: "
              f"{summary['hang_hard_exits']}")
    if summary.get("restart_count"):
        print(f"  !! supervisor restarts: {summary['restart_count']} "
              f"(last child exit code {summary['last_exit_code']})")
    if summary.get("admission_shed_count"):
        print(f"  admission sheds (429): {summary['admission_shed_count']}")
    if summary.get("replica_restarts"):
        print(f"  !! fleet replica restarts: {summary['replica_restarts']}")
    if summary.get("serve_fault_events"):
        print(f"  injected serve faults fired: "
              f"{summary['serve_fault_events']}")
    if summary.get("breaker_open_count"):
        print(f"  !! circuit breaker opens: {summary['breaker_open_count']}")
    if summary.get("retry_budget_exhausted"):
        print(f"  !! retry budget exhaustions (fast 503): "
              f"{summary['retry_budget_exhausted']}")
    if summary.get("hedge_count"):
        print(f"  hedged requests: {summary['hedge_count']} "
              f"({summary['hedge_wins']} won)")
    if summary.get("brownout_seconds"):
        print(f"  !! brownout (degraded mode): "
              f"{summary['brownout_seconds']:.1f}s across completed episodes")
    auto = summary.get("autoscale_events") or {}
    if any(auto.values()):
        print(f"  autoscale: {auto['scale_out']} out, {auto['scale_in']} in "
              f"({auto['retires']} retires, {auto['forced_drains']} forced "
              f"drains, {auto['scale_out_failures']} failed provisions, "
              f"{auto.get('escalations', 0)} arbiter escalation(s))")
    arb = summary.get("arbiter_events") or {}
    if any(arb.values()):
        denies = arb.get("denies") or {}
        deny_desc = ", ".join(f"{k}:{v}" for k, v in sorted(denies.items()))
        print(f"  chip arbiter: {arb['borrows']} borrow(s), "
              f"{arb['returns']} return(s), {arb['requests']} capacity "
              f"request(s), {arb['borrow_failures']} failed borrow(s), "
              f"{arb['return_failures']} failed return(s)"
              + (f"; denies {deny_desc}" if denies else ""))
    timeline = summary.get("train_topology_timeline") or []
    if timeline:
        path = " -> ".join(
            [str(timeline[0]["from_processes"])]
            + [str(t["to_processes"]) for t in timeline])
        print(f"  train topology: {path} process(es) across "
              f"{len(timeline)} transition(s)")
    if summary.get("cache_hits") is not None:
        print(f"  prediction cache: {summary['cache_hits']} hits "
              f"(rate {summary['cache_hit_rate']:.2f})")
    if summary.get("batch_fill_p50") is not None:
        print(f"  batch fill: p50 {summary['batch_fill_p50']:.2f}  "
              f"p95 {summary['batch_fill_p95']:.2f} of bucket")
    ev = summary.get("eval_last")
    if ev:
        print(f"  eval (epoch {ev['epoch']}): top1 {ev['top1']:.4f}  "
              f"top5 {ev['top5']:.4f}  (n={ev['n']})")
    ft = summary.get("finetune_last")
    if ft:
        reinit = ft.get("reinit") or []
        print(f"  finetune: {ft['loaded']} leaves from {ft['init_npz']}"
              + (f", head re-initialized ({len(reinit)} leaves)"
                 if reinit else "")
              + (f", frozen frac {ft['frozen_frac']:.3f}"
                 if ft.get("frozen_frac") else ""))
    dl = summary.get("distill_last")
    if dl:
        print(f"  distill (step {dl['step']}): kl {dl['kl']:.4f}  "
              f"ce {dl['ce']:.4f}  teacher top1 {dl['teacher_top1']:.4f}  "
              f"student top1 {dl['student_top1']:.4f}  "
              f"(alpha {dl['alpha']}, T {dl['temp']})")
    qg = summary.get("quant_gate_last")
    if qg:
        print(f"  quant gate ({qg['weights_dtype']} vs "
              f"{qg['baseline_dtype']}, "
              f"act_quant {qg.get('act_quant') or 'off'}, "
              f"fused_dequant {bool(qg.get('fused_dequant'))}): "
              f"top1 {qg['top1_quant']:.4f} "
              f"(delta {qg['delta_top1']:+.2f} pts)  "
              f"top5 {qg['top5_quant']:.4f} "
              f"(delta {qg['delta_top5']:+.2f} pts)  (n={qg['n']})")
    at = summary.get("autotune")
    if at:
        pr = ", ".join(f"{k}:{v}" for k, v in sorted(at["pruned"].items()))
        print(f"  autotune: {at['trials']} trials "
              f"({at['analytic']} analytic, {at['compiled']} compiled, "
              f"{at['measured']} measured"
              + (f"; pruned {pr}" if pr else "") + ")")
        if at.get("best_images_per_sec_chip") is not None:
            print(f"    measured spread: best "
                  f"{at['best_images_per_sec_chip']:.1f} / worst "
                  f"{at['worst_images_per_sec_chip']:.1f} img/s/chip"
                  + (f", best MFU {at['best_mfu']:.3f}"
                     if at.get("best_mfu") is not None else ""))
        if at.get("winning_knobs"):
            print(f"    winning knobs: "
                  f"{json.dumps(at['winning_knobs'], sort_keys=True)}")
    if not summary["records"]:
        print("  no step records — nothing to summarize")
        return
    print(f"  steps {summary['first_step']}..{summary['last_step']}")
    print(f"  sec/iter: p50 {summary['sec_per_iter_p50']:.4f}  "
          f"p95 {summary['sec_per_iter_p95']:.4f}")
    mfu_last = summary["mfu_last"]
    if mfu_last is not None:
        print(f"  MFU: last {mfu_last:.4f}  max {summary['mfu_max']:.4f}")
    if summary["data_wait_fraction"] is not None:
        starved = " (input-bound!)" if summary["data_wait_fraction"] > 0.3 else ""
        print(f"  data wait: {summary['data_wait_s_mean']:.4f}s/step, "
              f"{100 * summary['data_wait_fraction']:.1f}% of step "
              f"time{starved}")
    if summary.get("ckpt_stall_s_p50") is not None:
        print(f"  ckpt stall: p50 {summary['ckpt_stall_s_p50']:.4f}s  "
              f"p95 {summary['ckpt_stall_s_p95']:.4f}s per step")
    if summary.get("opt_update_s_p50") is not None:
        print(f"  opt update: p50 {summary['opt_update_s_p50']:.4f}s  "
              f"p95 {summary['opt_update_s_p95']:.4f}s per step")
    if summary.get("input_bound") is not None:
        flag = " (!!)" if summary["input_bound"] > 0 else ""
        print(f"  input-bound steps (wait > 10% of step): "
              f"{100 * summary['input_bound']:.1f}%{flag}")
    print(f"  throughput: {summary['images_per_sec_last']:.1f} images/s, "
          f"{summary['tokens_per_sec_last']:.0f} tokens/s (last record)")
    if summary["mem_peak_bytes"]:
        print(f"  HBM peak: {summary['mem_peak_bytes'] / 1024 ** 3:.2f} GiB")
    curve = sparkline(summary["loss_curve"])
    print(f"  loss: {summary['loss_first']:.4f} -> {summary['loss_last']:.4f}"
          f" (min {summary['loss_min']:.4f})"
          + (f"  {curve}" if curve else ""))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="summarize a vitax telemetry JSONL run")
    p.add_argument("path", help="metrics.jsonl written by --metrics_dir")
    p.add_argument("--json", action="store_true",
                   help="emit the summary as one JSON object (CI mode; the "
                        "loss_curve field carries the full curve)")
    args = p.parse_args(argv)

    try:
        summary = summarize(args.path)
    except OSError as e:
        print(f"metrics_report: cannot read {args.path}: {e}",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(summary, sort_keys=True))
    else:
        print_human(summary)
    return 0 if summary["records"] else 2


if __name__ == "__main__":
    sys.exit(main())
