"""Peer-replicated state shards (ROADMAP item 3b; Gemini, SOSP'23).

Every `--replicate_steps` window, each host packs its staged state shard
(vitax/checkpoint/snapshot.py HostSnapshot) into one checksummed blob,
versioned by `(epoch, step_in_epoch, topology)`, spills it to its OWN local
peer store, and mirrors it to its RING BUDDY — host i sends to (i+1) % N and
therefore guards (i-1) % N — over the coordination-service KV store (host
TCP; alive exactly when a peer's devices are not). After a lost host, the
restarted pod negotiates a restore FROM the surviving buddies' stores:
shared-storage checkpoint reads stay at ZERO (orbax_io.restore_read_count is
the counter seam the drill asserts), and restore-to-training drops from a
full Orbax round-trip to reading a few local files.

Why a local store and not just KV: the KV namespace dies with the run's
coordination service — a restarted pod starts a FRESH service, so replicas
must live on the surviving hosts' disks (the Gemini design point: peer CPU
memory / local disk, not shared storage). The KV store is only the
transport; PeerStore under `--peer_dir` (default <ckpt_dir>/peerstore,
VITAX_PEER_DIR overrides — per-host scratch in production) is the durable
half. Each process uses the subdirectory p<rank>, so a shared tmpdir in
tests behaves exactly like per-host disks: deleting p<rank> IS the lost
host.

Restore negotiation (`negotiate_restore`): every host publishes what its
store holds, process 0 picks the newest version whose shards cover the full
topology AND beat the Orbax frontier (counting every (src, version) pair a
host reported — one host routinely holds DIFFERENT versions of different
srcs, its fresh self-spill plus a buddy replica one window behind), holders
serve any shard a host lacks (chunked over the same KV seam), and every
host checksum-verifies EVERY copy it already holds — a corrupt local
replica is replaced from the serving holder, or vetoes. The all-hosts gate
is a `BIT_PEER_RESTORE` agreement fold (vitax/train/control.py
agree_peer_restore) — survivors explicitly agree to serve/accept shards
before anyone re-enters the step, so a host whose fetch failed can veto the
peer path and drop the whole pod to the Orbax fallback coherently. The
fold runs AGAIN after the actual load (restore_state_preferring_peers), so
even a failure that only surfaces at restore time moves the whole pod to
Orbax together — never one host on an older epoch while its peers enter
the step on the newer peer version.

Corruption: every blob carries a crc32; `PeerStore.load` verifies it (and
fires the `peer_restore` fault site so drills can inject exactly this) and
a mismatch raises PeerRestoreError — the loop falls back, loudly, to
`restore_state_with_fallback` on the last committed Orbax epoch.
"""

from __future__ import annotations

import base64
import io
import json
import os
import sys
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from vitax import faults
from vitax.telemetry.threads import join_or_warn
from vitax.utils.logging import master_print

PyTree = Any

# raw bytes per KV chunk (base64 inflates 4/3; the coordination service
# handles small values best — a 10B FSDP shard ships in a few hundred)
CHUNK_BYTES = 1 << 18

PEER_KEY_PREFIX = "vitax/peer"          # replication transport
RESTORE_KEY_PREFIX = "vitax/restore"    # negotiation + shard serving

# npz has no bfloat16: stored as uint16 bit-views, dtype restored from the
# per-leaf manifest (same trick as consolidate.save_npz)
_BF16 = "bfloat16"


class PeerRestoreError(RuntimeError):
    """A peer shard is missing, incomplete, or failed its checksum."""


def ring_buddy(process_index: int, process_count: int) -> int:
    """The host that RECEIVES this host's replica: (i + 1) % N."""
    return (process_index + 1) % process_count


def ring_guard(process_index: int, process_count: int) -> int:
    """The host whose replica THIS host stores: (i - 1) % N."""
    return (process_index - 1) % process_count


def default_peer_root(ckpt_dir: str) -> str:
    return os.path.join(os.path.abspath(ckpt_dir), "peerstore")


def resolve_peer_dir(cfg, process_index: Optional[int] = None) -> str:
    """This process's peer-store directory: VITAX_PEER_DIR env (per-host
    scratch) > --peer_dir > <ckpt_dir>/peerstore, always suffixed with
    p<rank> so one shared root still keeps per-host stores distinct."""
    root = (os.environ.get("VITAX_PEER_DIR", "")
            or getattr(cfg, "peer_dir", "")
            or default_peer_root(cfg.ckpt_dir))
    if process_index is None:
        import jax
        process_index = jax.process_index()
    return os.path.join(root, f"p{process_index}")


def progress_key(epoch: int, step_in_epoch: int) -> Tuple[int, int]:
    """Comparable training progress. A boundary save of epoch e (step 0)
    means e is COMPLETE — normalize it to (e + 1, 0) so it beats any
    mid-epoch version (e, s) of the same epoch."""
    epoch, step = int(epoch), int(step_in_epoch)
    return (epoch + 1, 0) if step == 0 else (epoch, step)


# -- pack / unpack ------------------------------------------------------------

def pack_snapshot(snapshot, src: int) -> Tuple[dict, bytes]:
    """HostSnapshot -> (meta, payload). The payload is one in-memory npz of
    this host's unique shards; meta carries the version, the per-leaf shard
    indices (so restore can place them globally), the resume fields the
    elastic planner reads (step_in_epoch / process_count / stream_cursor —
    meta doubles as a resume sidecar), and the payload crc32."""
    arrays: Dict[str, np.ndarray] = {}
    leaves = []
    for leaf_i, spec in enumerate(snapshot.specs):
        bufs = snapshot.buffers(leaf_i)
        shards = []
        for slot, index in enumerate(spec.indices):
            key = f"a{leaf_i}_{slot}"
            arr = bufs[slot]
            arrays[key] = (arr.view(np.uint16) if str(arr.dtype) == _BF16
                           else arr)
            shards.append({"key": key,
                           "index": [[int(a), int(b)] for a, b in index]})
        leaves.append({"path": spec.path,
                       "shape": [int(d) for d in spec.shape],
                       "dtype": str(spec.dtype),
                       "shards": shards})
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    payload = buf.getvalue()
    meta = {
        "version": list(snapshot.version),
        "src": int(src),
        "step_in_epoch": snapshot.step_in_epoch,
        "process_count": snapshot.process_count,
        "leaves": leaves,
        "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
        "nbytes": len(payload),
    }
    if snapshot.stream_cursor is not None:
        meta["stream_cursor"] = snapshot.stream_cursor
    return meta, payload


def unpack_payload(meta: dict, payload: bytes) -> Dict[str, np.ndarray]:
    """payload npz -> {key: array} with bf16 views restored per the meta."""
    import ml_dtypes
    bf16_keys = {sh["key"] for leaf in meta["leaves"]
                 if leaf["dtype"] == _BF16 for sh in leaf["shards"]}
    with np.load(io.BytesIO(payload)) as data:
        return {k: (data[k].view(ml_dtypes.bfloat16) if k in bf16_keys
                    else data[k])
                for k in data.files}


# -- local store --------------------------------------------------------------

class PeerStore:
    """<root>/host_<src>/{meta.json, shard.npz}: the durable replicas this
    host holds — its own shard (self-spill) plus its ring guard's. Writes
    are payload-first then atomic meta rename, so a meta.json always
    describes a fully written payload."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)

    def _dir(self, src: int) -> str:
        return os.path.join(self.root, f"host_{int(src)}")

    def put(self, meta: dict, payload: bytes) -> None:
        d = self._dir(meta["src"])
        os.makedirs(d, exist_ok=True)
        blob = os.path.join(d, "shard.npz")
        tmp = blob + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, blob)
        mpath = os.path.join(d, "meta.json")
        tmp = mpath + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(meta))
        os.replace(tmp, mpath)

    def holdings(self) -> Dict[int, dict]:
        """{src: meta} for every readable replica in the store; unreadable
        entries are skipped (a torn replica is a missing replica)."""
        out: Dict[int, dict] = {}
        if not os.path.isdir(self.root):
            return out
        for name in sorted(os.listdir(self.root)):
            if not name.startswith("host_"):
                continue
            try:
                with open(os.path.join(self.root, name, "meta.json")) as f:
                    meta = json.load(f)
                out[int(meta["src"])] = meta
            except (OSError, ValueError, KeyError, json.JSONDecodeError):
                continue
        return out

    def load(self, src: int,
             expect_version: Optional[Tuple[int, int, int]] = None,
             ) -> Tuple[dict, bytes]:
        """Read + VERIFY one replica. Raises PeerRestoreError on a missing
        file, a version mismatch, or a checksum failure. The `peer_restore`
        fault site fires once per load — drills inject corruption/IO errors
        exactly here."""
        d = self._dir(src)
        try:
            faults.fire("peer_restore", index=int(src))
            with open(os.path.join(d, "meta.json")) as f:
                meta = json.load(f)
            with open(os.path.join(d, "shard.npz"), "rb") as f:
                payload = f.read()
        except (OSError, ValueError, json.JSONDecodeError) as e:
            raise PeerRestoreError(
                f"peer shard for host {src} unreadable at {d}: "
                f"{type(e).__name__}: {e}") from e
        if (expect_version is not None
                and tuple(meta.get("version", ())) != tuple(expect_version)):
            raise PeerRestoreError(
                f"peer shard for host {src} is version "
                f"{meta.get('version')}, wanted {list(expect_version)}")
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        if crc != int(meta.get("crc32", -1)):
            raise PeerRestoreError(
                f"peer shard for host {src} FAILED its checksum "
                f"(crc32 {crc:#x} != recorded {int(meta.get('crc32', 0)):#x})"
                f" — replica at {d} is corrupt")
        return meta, payload


def store_frontier(root: str) -> Tuple[int, int]:
    """NORMALIZED (progress_key) (epoch, step) progress frontier across
    every per-process store under `root`, (0, 0) when empty — a boundary
    version (e, 0) counts as (e + 1, 0), so epoch-completing progress made
    only via peer replication is never outranked by a stale mid-epoch
    version. The supervisor folds this into its crash-loop progress check
    (run_progress, which normalizes the Orbax side the same way) so
    peer-replicated progress counts even when no Orbax commit advanced."""
    best = (0, 0)
    if not os.path.isdir(root):
        return best
    for sub in sorted(os.listdir(root)):
        d = os.path.join(root, sub)
        if not (sub.startswith("p") and os.path.isdir(d)):
            continue
        for src, meta in PeerStore(d).holdings().items():
            v = meta.get("version") or [0, 0, 0]
            if int(v[0]) or int(v[1]):
                best = max(best, progress_key(v[0], v[1]))
    return best


# -- KV transport -------------------------------------------------------------

def _publish_blob(client, prefix: str, meta: dict, payload: bytes,
                  gen: int) -> None:
    """Chunked, base64 KV publication. Chunks land before the meta (the
    receiver's trigger), so a reader never sees a meta whose chunks are
    missing; `gen` versions the chunk keys so a reader mid-fetch of gen k
    can never mix in gen k+1 bytes."""
    chunks = [payload[i:i + CHUNK_BYTES]
              for i in range(0, len(payload), CHUNK_BYTES)] or [b""]
    for i, chunk in enumerate(chunks):
        client.key_value_set(f"{prefix}/g{gen}/c{i}",
                             base64.b64encode(chunk).decode("ascii"),
                             allow_overwrite=True)
    wire = dict(meta, gen=int(gen), n_chunks=len(chunks))
    client.key_value_set(f"{prefix}/meta", json.dumps(wire),
                         allow_overwrite=True)


def _fetch_blob(client, prefix: str, timeout_ms: int,
                min_gen: int = 0) -> Optional[Tuple[dict, bytes]]:
    """Read the newest publication under `prefix`, or None (no meta yet /
    gen not newer than `min_gen`). Raises PeerRestoreError when the chunks
    fail the meta's checksum."""
    try:
        raw = client.blocking_key_value_get(f"{prefix}/meta", timeout_ms)
    except Exception:  # noqa: BLE001 — no publication yet is the common case
        return None
    meta = json.loads(raw)
    gen = int(meta.get("gen", 0))
    if gen <= min_gen:
        return None
    try:
        parts = [client.blocking_key_value_get(f"{prefix}/g{gen}/c{i}",
                                               timeout_ms)
                 for i in range(int(meta["n_chunks"]))]
    except Exception as e:  # noqa: BLE001 — a vanished chunk is a failed fetch, not a crash
        raise PeerRestoreError(
            f"peer transport: chunk fetch under {prefix} gen {gen} failed "
            f"({type(e).__name__}: {e})") from e
    payload = b"".join(base64.b64decode(p) for p in parts)
    if (zlib.crc32(payload) & 0xFFFFFFFF) != int(meta.get("crc32", -1)):
        raise PeerRestoreError(
            f"peer transport: blob under {prefix} gen {gen} failed its "
            f"checksum after reassembly")
    return meta, payload


# -- replication --------------------------------------------------------------

class PeerReplicator:
    """Owns one host's replication duties: self-spill + publish to the ring
    buddy (replicate(), called from the snapshot pipeline's worker thread)
    and a receiver thread that stores the guard's publications. Single
    process degrades to self-spill only — the store still feeds
    single-process peer restore and the supervisor's frontier."""

    def __init__(self, store: PeerStore, process_index: int,
                 process_count: int, client=None, on_event=None,
                 poll_s: Optional[float] = None):
        self.store = store
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.client = client
        self.on_event = on_event
        self.poll_s = (float(poll_s) if poll_s is not None
                       else float(os.environ.get("VITAX_PEER_POLL_S", 2.0)))
        self.buddy = ring_buddy(self.process_index, self.process_count)
        self.guard = ring_guard(self.process_index, self.process_count)
        self.bytes_replicated = 0
        self.windows_replicated = 0
        self._gen = 0
        self._stop = threading.Event()
        self._receiver: Optional[threading.Thread] = None

    def replicate(self, snapshot) -> None:
        """Pack + self-spill + publish one staged snapshot. Runs on the
        snapshot pipeline's worker thread: none of this blocks a step."""
        meta, payload = pack_snapshot(snapshot, src=self.process_index)
        self.store.put(meta, payload)
        if self.process_count > 1 and self.client is not None:
            self._gen += 1
            _publish_blob(self.client,
                          f"{PEER_KEY_PREFIX}/{self.process_index}",
                          meta, payload, self._gen)
        self.bytes_replicated += len(payload)
        self.windows_replicated += 1
        self._emit("peer_replication", bytes=len(payload),
                   version=list(snapshot.version), src=self.process_index,
                   buddy=self.buddy)

    def start_receiver(self) -> bool:
        """Poll the ring guard's publications into the local store. No-op
        (False) single-process or without a KV client."""
        if self.process_count <= 1 or self.client is None:
            return False
        self._receiver = threading.Thread(target=self._receive, daemon=True,
                                          name="vitax-peer-receiver")
        self._receiver.start()
        return True

    def stop(self, drain: bool = True) -> None:
        self._stop.set()
        if self._receiver is not None:
            # bounded: a receiver wedged in a KV fetch must not block
            # process exit — warn loudly and leak it instead
            join_or_warn(self._receiver, timeout=self.poll_s + 1.0)
            self._receiver = None
        if drain and self.process_count > 1 and self.client is not None:
            self._drain_receive()

    def _drain_receive(self) -> None:
        """One final bounded fetch of the guard's newest publication. An
        exit right after a joint preemption save (the arbiter's elastic
        shrink) must not strand the guard's FINAL shard in the KV: the
        polling receiver may simply never wake between the save barrier
        and process exit, and a single-host resume reads only its local
        store. Every host publishes BEFORE the preemption exit barrier,
        so by the time the finally-block runs this fetch, the final
        version is deterministically visible."""
        try:
            got = _fetch_blob(self.client,
                              f"{PEER_KEY_PREFIX}/{self.guard}",
                              timeout_ms=max(
                                  int(min(self.poll_s, 0.5) * 1000), 100))
        except Exception as e:  # noqa: BLE001 — best-effort: the coordinator may already be gone
            print(f"vitax.peer: final receive from host {self.guard} "
                  f"failed ({e}); local store keeps its last pulled "
                  f"version", file=sys.stderr, flush=True)
            return
        if got is not None:
            meta, payload = got
            self.store.put(meta, payload)

    def _receive(self) -> None:
        last_gen = 0
        prefix = f"{PEER_KEY_PREFIX}/{self.guard}"
        timeout_ms = max(int(min(self.poll_s, 0.2) * 1000), 50)
        while not self._stop.wait(self.poll_s):
            try:
                got = _fetch_blob(self.client, prefix, timeout_ms,
                                  min_gen=last_gen)
            except PeerRestoreError as e:
                # a torn mid-publish read: next poll sees the complete gen
                print(f"vitax.peer: receive from host {self.guard} failed "
                      f"({e}); retrying next poll", file=sys.stderr,
                      flush=True)
                continue
            if got is None:
                continue
            meta, payload = got
            last_gen = int(meta.get("gen", last_gen))
            self.store.put(meta, payload)

    def _emit(self, kind: str, **payload) -> None:
        if self.on_event is None:
            return
        try:
            self.on_event(kind, payload)
        except Exception as e:  # noqa: BLE001 — observability must not break replication
            print(f"vitax.peer: event sink failed ({type(e).__name__}: {e})",
                  file=sys.stderr, flush=True)


# -- negotiated restore -------------------------------------------------------

@dataclass(frozen=True)
class RestorePlan:
    """An agreed peer restore: which version to load and the sidecar-shaped
    meta (step_in_epoch / process_count / stream_cursor) the elastic-resume
    planner consumes (control.elastic_resume_plan)."""

    version: Tuple[int, int, int]
    meta: dict

    @property
    def epoch(self) -> int:
        return int(self.version[0])


def _complete_versions(holdings: Dict[int, dict]) -> List[Tuple]:
    """Versions for which `holdings` covers EVERY shard of the version's
    own recorded topology."""
    by_version: Dict[Tuple, set] = {}
    for src, meta in holdings.items():
        v = tuple(int(x) for x in (meta.get("version") or ()))
        if len(v) == 3:
            by_version.setdefault(v, set()).add(int(src))
    return [v for v, srcs in by_version.items()
            if srcs >= set(range(v[2]))]


def negotiate_restore(store: PeerStore, *, process_index: int,
                      process_count: int, client=None, collective=None,
                      orbax_frontier: Tuple[int, int] = (0, 0),
                      timeout_s: float = 30.0,
                      on_event=None) -> Optional[RestorePlan]:
    """Agree (or decline) a restore from peer stores. Returns the agreed
    RestorePlan, or None -> the caller uses the Orbax path.

    Single-process: the newest complete local version beating the Orbax
    frontier, no negotiation. Multi-process: publish holdings, adopt process
    0's candidate, serve/fetch any shard a host lacks over KV, then gate the
    verdict with the BIT_PEER_RESTORE agreement fold — every host enters the
    peer path together or none does."""
    holdings = store.holdings()

    def best(cands: List[Tuple]) -> Optional[Tuple]:
        ahead = [v for v in cands
                 if progress_key(v[0], v[1]) >= tuple(orbax_frontier)]
        return max(ahead, key=lambda v: progress_key(v[0], v[1]),
                   default=None)

    if process_count <= 1:
        # any locally COMPLETE version qualifies, whatever topology wrote
        # it: _complete_versions demands every shard of the version's own
        # recorded process count, and assemble_state rebuilds the full
        # arrays from the shard index ranges. This is what makes an
        # elastic shrink to one host (the arbiter's borrow path) resume
        # from its own store with zero Orbax reads — the survivor holds
        # its self-spill plus its guard's final replica.
        v = best(_complete_versions(holdings))
        if v is None:
            return None
        meta = next(m for m in holdings.values()
                    if tuple(m.get("version", ())) == v)
        return RestorePlan(version=v, meta=meta)

    if client is None:
        return None
    deadline_ms = max(int(timeout_s * 1000), 1000)
    # 1. everyone publishes what it holds
    mine = {src: list(meta.get("version", ()))
            for src, meta in holdings.items()}
    client.key_value_set(f"{RESTORE_KEY_PREFIX}/holdings/{process_index}",
                         json.dumps(mine), allow_overwrite=True)
    # 2. process 0 reads all holdings, picks the candidate, broadcasts it
    if process_index == 0:
        per_host: Dict[int, Dict[int, Tuple]] = {}
        for pid in range(process_count):
            try:
                raw = client.blocking_key_value_get(
                    f"{RESTORE_KEY_PREFIX}/holdings/{pid}", deadline_ms)
                per_host[pid] = {int(s): tuple(int(x) for x in v)
                                 for s, v in json.loads(raw).items()
                                 if len(v) == 3}
            except Exception:  # noqa: BLE001 — a host with no store publishes nothing useful
                per_host[pid] = {}
        # count EVERY (src, version) pair toward coverage: one host
        # routinely holds different versions of different srcs (its own
        # fresh self-spill plus a buddy replica one replication window
        # behind) — flattening to one version per src would mix versions
        # and silently decline a newest version that IS fully covered
        coverage: Dict[Tuple, set] = {}
        for held in per_host.values():
            for src, ver in held.items():
                coverage.setdefault(ver, set()).add(src)
        v = best([ver for ver, srcs in coverage.items()
                  if srcs >= set(range(ver[2]))])
        plan_wire = {"version": list(v) if v else None, "holders": {
            str(src): min(pid for pid, held in per_host.items()
                          if held.get(src) == v)
            for src in (range(v[2]) if v else ())
            if any(held.get(src) == v for held in per_host.values())}}
        client.key_value_set(f"{RESTORE_KEY_PREFIX}/plan",
                             json.dumps(plan_wire), allow_overwrite=True)
    try:
        plan_wire = json.loads(client.blocking_key_value_get(
            f"{RESTORE_KEY_PREFIX}/plan", deadline_ms))
    except Exception:  # noqa: BLE001 — no plan within the deadline -> Orbax path
        plan_wire = {"version": None}
    version = plan_wire.get("version")
    if version is None:
        _agree(False, process_count, collective)
        return None
    version = tuple(int(x) for x in version)
    holders = {int(s): int(p)
               for s, p in (plan_wire.get("holders") or {}).items()}
    # 3. checksum-verify EVERY locally held copy of the candidate — a
    #    corrupt replica must surface NOW, while the serving holder can
    #    still replace it; discovered only at restore time it would strand
    #    this host alone on the Orbax fallback while its peers enter the
    #    step on the peer version. Then serve what this host holds and
    #    others may lack, and fetch what it lacks (or cannot read).
    local_ok = True
    for src in range(version[2]):
        held = tuple(holdings.get(src, {}).get("version", ())) == version
        serving = holders.get(src) == process_index
        verified = False
        if held:
            try:
                meta, payload = store.load(src, expect_version=version)
                verified = True
                if serving:
                    _publish_blob(client, f"{RESTORE_KEY_PREFIX}/data/{src}",
                                  meta, payload, gen=1)
            except PeerRestoreError as e:
                print(f"vitax.peer: locally held shard {src} failed "
                      f"verification: {e}", file=sys.stderr, flush=True)
        if verified:
            continue
        if serving:
            # the designated server cannot read its own copy: no other
            # host will publish this shard — veto the peer path
            local_ok = False
            continue
        try:
            got = _wait_blob(client, f"{RESTORE_KEY_PREFIX}/data/{src}",
                             timeout_s)
            if got is None:
                raise PeerRestoreError(
                    f"shard {src} not served within {timeout_s:g}s")
            store.put(*got)
        except PeerRestoreError as e:
            print(f"vitax.peer: fetch of shard {src} failed: {e}",
                  file=sys.stderr, flush=True)
            local_ok = False
    # 4. the all-hosts gate: everyone enters the peer path, or no one does
    agreed = _agree(local_ok, process_count, collective)
    if on_event is not None:
        try:
            on_event("control", {"event": "peer_restore_negotiated",
                                 "version": list(version),
                                 "agreed": bool(agreed),
                                 "local_ok": bool(local_ok)})
        except Exception as e:  # noqa: BLE001 — observability must not block the restore
            print(f"vitax.peer: restore event sink failed "
                  f"({type(e).__name__}: {e})", file=sys.stderr, flush=True)
    if not agreed:
        return None
    # src 0's meta carries the resume fields (its stream cursor is the one
    # the Orbax sidecar convention records); the store was completed above
    meta = store.holdings().get(0)
    if meta is None or tuple(meta.get("version", ())) != version:
        return None
    return RestorePlan(version=version, meta=meta)


def _wait_blob(client, prefix: str, timeout_s: float):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        got = _fetch_blob(client, prefix, timeout_ms=1000)
        if got is not None:
            return got
        time.sleep(0.1)
    return None


def _agree(local_ok: bool, process_count: int, collective) -> bool:
    from vitax.train.control import agree_peer_restore
    return agree_peer_restore(local_ok, process_count=process_count,
                              collective=collective)


# -- restore ------------------------------------------------------------------

def assemble_state(parts: List[Tuple[dict, bytes]],
                   abstract_state: PyTree) -> PyTree:
    """Rebuild the sharded global state from peer blobs. Every leaf must be
    fully covered by the union of shard indices across `parts` (partial
    coverage raises PeerRestoreError); placement onto devices goes through
    make_array_from_callback against the abstract state's target shardings,
    so restore is topology-aware exactly like the Orbax path."""
    import jax
    import jax.numpy as jnp
    from vitax.checkpoint.snapshot import _path_str
    per_path: Dict[str, Dict[Tuple, np.ndarray]] = {}
    for meta, payload in parts:
        arrays = unpack_payload(meta, payload)
        for leaf in meta["leaves"]:
            dest = per_path.setdefault(leaf["path"], {})
            for sh in leaf["shards"]:
                key = tuple((int(a), int(b)) for a, b in sh["index"])
                dest.setdefault(key, arrays[sh["key"]])
    leaves_kp, treedef = jax.tree_util.tree_flatten_with_path(abstract_state)
    out = []
    for kp, aval in leaves_kp:
        path = _path_str(kp)
        shards = per_path.get(path)
        if shards is None:
            raise PeerRestoreError(f"no peer shard covers leaf {path!r}")
        full = np.zeros(aval.shape, dtype=np.dtype(aval.dtype))
        covered = 0
        for key, arr in shards.items():
            idx = tuple(slice(a, b) for a, b in key)
            full[idx] = arr
            covered += int(np.prod([b - a for a, b in key] or [1]))
        need = int(np.prod(aval.shape or (1,)))
        if covered < need:
            raise PeerRestoreError(
                f"leaf {path!r} only {covered}/{need} elements covered by "
                f"peer shards — a replica is missing")
        # Each shard gets an owned copy (never a view into `full`), and the
        # assembled array is then laundered through a jitted on-device copy
        # below: the CPU backend can zero-copy-adopt aligned host buffers, so
        # without the launder the restored state would be backed by adopted
        # malloc-heap memory that the DONATING train step reuses in place —
        # observed as NaN a few steps after an elastic peer restore plus glibc
        # heap corruption at exit. The launder gives the state fresh
        # XLA-owned buffers, indistinguishable from jit-initialized state.
        out.append(jax.make_array_from_callback(
            aval.shape, aval.sharding,
            lambda idx, _f=full: _f[idx].copy()))
    restored = jax.tree_util.tree_unflatten(treedef, out)
    return jax.tree.map(jax.jit(jnp.copy), restored)


def restore_from_store(store: PeerStore, plan: RestorePlan,
                       abstract_state: PyTree) -> PyTree:
    """Load + verify every shard of the plan's version from the LOCAL store
    and assemble the state. Raises PeerRestoreError on any corruption."""
    parts = [store.load(src, expect_version=plan.version)
             for src in range(plan.version[2])]
    return assemble_state(parts, abstract_state)


def restore_state_preferring_peers(store: PeerStore, plan: RestorePlan,
                                   ckpt_dir: str, orbax_epoch: int,
                                   abstract_state: PyTree,
                                   on_event=None,
                                   process_count: Optional[int] = None,
                                   collective=None) -> Tuple[PyTree, dict]:
    """The loop's restore entry when a peer plan was agreed: peer shards
    first, then a SECOND BIT_PEER_RESTORE agreement fold on the load
    outcome — the negotiation verified what each host held, but the load is
    the final word, and a failure that only surfaces here (a replica gone
    bad between the agreement and the read) must drop the WHOLE pod to the
    Orbax fallback together, never one host alone onto an older epoch while
    its peers enter the step on the peer version. On any PeerRestoreError
    or a peer's veto, fall back LOUDLY to the last committed Orbax epoch
    through restore_state_with_fallback. Returns (state, info) where info
    carries {"path": "peer"|"orbax", "epoch": restored-epoch, ...} for the
    loop's restore telemetry event. `process_count`/`collective` default to
    the live JAX topology (agree_peer_restore)."""
    state, err = None, None
    try:
        state = restore_from_store(store, plan, abstract_state)
    except PeerRestoreError as e:
        err = e
    from vitax.train.control import agree_peer_restore
    agreed = agree_peer_restore(err is None, process_count=process_count,
                                collective=collective)
    if agreed:
        master_print(
            f"restored from PEER shards: version {list(plan.version)} "
            f"({plan.version[2]} replica(s) from {store.root}; zero "
            f"shared-storage checkpoint reads)")
        return state, {"path": "peer", "epoch": plan.epoch,
                       "step_in_epoch": int(plan.version[1])}
    if err is None:
        err = PeerRestoreError(
            "a peer host vetoed after the post-agreement shard load — "
            "dropping to the Orbax fallback with the pod")
    print(f"vitax.peer: PEER RESTORE FAILED ({err}); falling back to the "
          f"last committed Orbax epoch", file=sys.stderr, flush=True)
    if on_event is not None:
        try:
            on_event("control", {"event": "peer_restore_failed",
                                 "version": list(plan.version),
                                 "error": str(err),
                                 "fallback_epoch": int(orbax_epoch)})
        except Exception as sink_err:  # noqa: BLE001 — observability must not mask the fallback
            print(f"vitax.peer: restore event sink failed "
                  f"({type(sink_err).__name__}: {sink_err})",
                  file=sys.stderr, flush=True)
    if orbax_epoch <= 0:
        raise RuntimeError(
            "peer restore failed and no committed Orbax checkpoint "
            "exists to fall back to") from err
    from vitax.checkpoint.orbax_io import restore_state_with_fallback
    state, restored = restore_state_with_fallback(
        ckpt_dir, orbax_epoch, abstract_state)
    return state, {"path": "orbax", "epoch": int(restored),
                   "fallback_from": str(err)}
