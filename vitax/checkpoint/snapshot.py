"""Zero-stall snapshot pipeline (ROADMAP item 3a; CheckFreq, FAST'21).

`save_state` already returns at the device->host snapshot, but that snapshot
itself is synchronous inside Orbax and re-allocates host memory every save —
at 10B scale the train loop still stalls for the full D2H of its shard, and
every emergency save serializes on the loop thread. This module splits the
save into the only part that MUST block the step dispatch and everything
else:

  stage()    synchronous, on the loop thread: fence the state (pipeline
             drain, accounted separately — waiting for step N to finish is
             not snapshot cost) then memcpy each host's unique addressable
             shards into a PREALLOCATED, REUSED staging buffer set. This is
             the only window where the live buffers are read: the moment
             stage() returns, the caller may dispatch step N+1 and donate
             the state. The copy time is the per-step `ckpt_stall_s`
             telemetry (consume_stall_s, same consume contract as the
             loader's data_wait_s) — the acceptance harness pins it ~0.

  worker     one background thread owns EVERYTHING downstream: rebuilding
             device arrays from the staged copies and handing them to
             `orbax_io.save_state` (persist jobs — sharing its retry /
             sidecar / commit / GC machinery), and mirroring the staged
             bytes to the ring-buddy host (replicate jobs,
             vitax/checkpoint/peer.py). One thread, one queue: Orbax's
             async checkpointer is a per-process singleton and two
             concurrent save() calls race its internal state, so when the
             pipeline is on, ALL saves route through it — including the
             wait=True emergency/final paths, which just drain the queue.
             CAVEAT — a persist job rebuilds a TRANSIENT SECOND device copy
             of this host's state shard (device_put of the staged buffers)
             while the next training steps are running; the pre-pipeline
             path saved the live arrays with no extra device allocation.
             rebuild() gates that allocation on available HBM headroom
             (device memory_stats, where the backend exposes them) and
             fails the job with a clear error rather than risk an
             allocator OOM or a defragmentation stall in the middle of a
             dispatched step. VITAX_SNAPSHOT_HBM_CHECK=0 disables the
             gate; VITAX_SNAPSHOT_HBM_WAIT_S (default 10) bounds how long
             the job re-polls for headroom before giving up.

Staging buffers live in a small free-list (at most `max_buffer_sets`,
default 2): steady state allocates nothing and touches the same pages every
snapshot (the host-pinning analog under PJRT — page-warm, allocator-free).
If every set is in flight the next stage() blocks until one frees — that
wait is charged to ckpt_stall_s honestly rather than hidden by unbounded
allocation.

Nothing here traces or compiles: the step program is bit-identical with the
pipeline on or off (pinned by tests/test_snapshot.py).
"""

from __future__ import annotations

import queue
import sys
import threading
import time
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

from vitax.telemetry.threads import join_or_warn

PyTree = Any


def _index_key(index, shape) -> Tuple[Tuple[int, int], ...]:
    """A shard's global placement as a hashable ((start, stop), ...) tuple —
    the dedup key for replicated shards and the serialized form the peer
    protocol ships (vitax/checkpoint/peer.py)."""
    return tuple((int(s.start or 0),
                  int(s.stop if s.stop is not None else dim))
                 for s, dim in zip(index, shape))


def _device_memory_stats(device) -> Optional[dict]:
    """device.memory_stats() as a dict, or None when the backend exposes
    none (CPU, some PJRT plugins). A seam so tests can fake HBM pressure."""
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — stats are best-effort, never fatal
        return None
    return stats if isinstance(stats, dict) else None


def _path_str(key_path) -> str:
    """tree_flatten_with_path key -> stable "/"-joined string (same
    convention as consolidate.flatten_tree, so peer shards and npz
    consolidation name leaves identically)."""
    return "/".join(str(getattr(p, "key", getattr(p, "name",
                                                  getattr(p, "idx", p))))
                    for p in key_path)


class _LeafSpec:
    """Static per-leaf layout, computed once per run (the state structure
    and sharding never change between steps)."""

    __slots__ = ("path", "shape", "dtype", "sharding", "index_slot",
                 "indices", "placements")

    def __init__(self, path, leaf):
        self.path = path
        self.shape = tuple(leaf.shape)
        self.dtype = np.dtype(leaf.dtype)
        self.sharding = leaf.sharding
        self.index_slot = {}
        self.indices: List[Tuple] = []
        self.placements: List[Tuple[Any, int]] = []  # (device, unique slot)
        for sh in leaf.addressable_shards:
            key = _index_key(sh.index, self.shape)
            slot = self.index_slot.get(key)
            if slot is None:
                slot = len(self.indices)
                self.index_slot[key] = slot
                self.indices.append(key)
            self.placements.append((sh.device, slot))


class HostSnapshot:
    """One staged copy of this host's state shards: everything a persist or
    replicate job needs, with zero references to live device buffers."""

    def __init__(self, pipeline, buffer_set, specs, treedef, *, epoch,
                 step_in_epoch, process_count, stream_cursor):
        self._pipeline = pipeline
        self._buffer_set = buffer_set
        self._refs = 1
        self._lock = threading.Lock()
        self.specs = specs
        self.treedef = treedef
        self.epoch = int(epoch)
        self.step_in_epoch = int(step_in_epoch)
        self.process_count = int(process_count)
        self.stream_cursor = stream_cursor
        self.nbytes = sum(b.nbytes for leaf in buffer_set for b in leaf)

    @property
    def version(self) -> Tuple[int, int, int]:
        """(epoch, step_in_epoch, topology) — the replication version tag."""
        return (self.epoch, self.step_in_epoch, self.process_count)

    def buffers(self, leaf_i: int) -> List[np.ndarray]:
        return self._buffer_set[leaf_i]

    def retain(self) -> None:
        with self._lock:
            self._refs += 1

    def release(self) -> None:
        with self._lock:
            self._refs -= 1
            done = self._refs == 0
        if done:
            self._pipeline._return_buffers(self._buffer_set)

    def rebuild(self) -> PyTree:
        """Global device arrays from the staged host copies — what the
        persist job hands Orbax. Each host contributes exactly its
        addressable shards (device_put per placement), so the write path is
        identical to saving the live state.

        This allocates a TRANSIENT SECOND device copy of this host's state
        shard while the next training steps run (it is freed once Orbax's
        own host snapshot is taken and the persist job drops the tree), so
        the allocation is gated on available HBM headroom first — a persist
        job failing with a clear error beats an allocator OOM or a
        defragmentation stall hitting a dispatched step."""
        self._gate_on_hbm()
        leaves = []
        for i, spec in enumerate(self.specs):
            bufs = self.buffers(i)
            # device_put an OWNED copy (.copy(), unconditionally), not the
            # staging buffer itself: the CPU backend may zero-copy-adopt an
            # aligned numpy buffer, and these buffers are RECYCLED — the
            # next stage() would overwrite them under Orbax's still-running
            # async write (torn checkpoint). Same aliasing hazard as
            # peer.assemble_state's restore callback.
            arrays = [jax.device_put(bufs[slot].copy(), device)
                      for device, slot in spec.placements]
            leaves.append(jax.make_array_from_single_device_arrays(
                spec.shape, spec.sharding, arrays))
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def _transient_device_bytes(self) -> dict:
        """{device: bytes rebuild() will place on it} — the extra HBM the
        persist path borrows on top of the live training state."""
        per_dev: dict = {}
        for i, spec in enumerate(self.specs):
            bufs = self.buffers(i)
            for device, slot in spec.placements:
                per_dev[device] = per_dev.get(device, 0) + bufs[slot].nbytes
        return per_dev

    def _gate_on_hbm(self) -> None:
        """Refuse rebuild()'s device allocation when it clearly cannot fit.
        Best-effort: backends without memory_stats (CPU) skip the check;
        headroom is re-polled for a short window first (a running step's
        temporaries come and go). VITAX_SNAPSHOT_HBM_CHECK=0 forces the
        attempt anyway; VITAX_SNAPSHOT_HBM_WAIT_S bounds the re-poll."""
        import os
        if os.environ.get("VITAX_SNAPSHOT_HBM_CHECK", "1") == "0":
            return
        deadline = time.monotonic() + float(
            os.environ.get("VITAX_SNAPSHOT_HBM_WAIT_S", 10.0))
        while True:
            blocked = None
            for device, incoming in self._transient_device_bytes().items():
                stats = _device_memory_stats(device)
                if not stats:
                    continue
                limit = int(stats.get("bytes_limit") or 0)
                free = limit - int(stats.get("bytes_in_use") or 0)
                if limit and incoming > free:
                    blocked = (device, incoming, free, limit)
                    break
            if blocked is None:
                return
            if time.monotonic() >= deadline:
                device, incoming, free, limit = blocked
                raise RuntimeError(
                    f"snapshot persist needs a transient second copy of "
                    f"this host's state shard on {device} "
                    f"({incoming / 2**20:.0f} MiB) but only "
                    f"{max(free, 0) / 2**20:.0f} of {limit / 2**20:.0f} MiB "
                    f"HBM are free — refusing the allocation (an OOM or "
                    f"defrag stall would hit the running step). Free HBM, "
                    f"lower the save/replication cadence, or set "
                    f"VITAX_SNAPSHOT_HBM_CHECK=0 to force the attempt.")
            time.sleep(0.2)


class SnapshotPipeline:
    """stage-on-the-loop-thread, persist/replicate-on-a-worker. See module
    docstring. Thread-safe for the loop's usage: submit()/drain()/close()
    from the loop thread, jobs on the single worker."""

    def __init__(self, max_buffer_sets: int = 2):
        assert max_buffer_sets >= 1, max_buffer_sets
        self.max_buffer_sets = int(max_buffer_sets)
        self._specs: Optional[List[_LeafSpec]] = None
        self._treedef = None
        self._free: List[list] = []
        self._allocated = 0
        self._cond = threading.Condition()
        self._q: queue.Queue = queue.Queue()
        self._errors: List[BaseException] = []
        self._stall_s = 0.0
        self.last_stall_s = 0.0
        self.last_fence_s = 0.0
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="vitax-snapshot-writer")
        self._worker.start()
        self._closed = False

    # -- staging (loop thread; the only part that may stall the step) -------
    def stage(self, state: PyTree, *, epoch: int, step_in_epoch: int = 0,
              stream_cursor: Optional[dict] = None) -> HostSnapshot:
        self.raise_pending()
        leaves_kp, treedef = jax.tree_util.tree_flatten_with_path(state)
        leaves = [leaf for _, leaf in leaves_kp]
        if self._specs is None:
            self._specs = [_LeafSpec(_path_str(kp), leaf)
                           for kp, leaf in leaves_kp]
            self._treedef = treedef
        # fence OUTSIDE the stall clock: step N must complete before its
        # result can be copied — that wait is pipeline drain the loop would
        # pay at the next fence anyway, not snapshot cost
        t_fence = time.perf_counter()
        jax.block_until_ready(leaves)
        self.last_fence_s = time.perf_counter() - t_fence

        t0 = time.perf_counter()
        buffer_set = self._acquire_buffers()
        # overlap the D2H transfers across leaves before the blocking copies
        for leaf, spec in zip(leaves, self._specs):
            seen = set()
            for sh in leaf.addressable_shards:
                key = _index_key(sh.index, spec.shape)
                if key in seen:
                    continue
                seen.add(key)
                start = getattr(sh.data, "copy_to_host_async", None)
                if start is not None:
                    start()
        for leaf_i, (leaf, spec) in enumerate(zip(leaves, self._specs)):
            bufs = buffer_set[leaf_i]
            filled = set()
            for sh in leaf.addressable_shards:
                slot = spec.index_slot[_index_key(sh.index, spec.shape)]
                if slot in filled:
                    continue
                filled.add(slot)
                # an explicit copy INTO the owned buffer: np.asarray of a
                # host-committed jax array may be a zero-copy view of
                # memory the next train step will donate and overwrite
                np.copyto(bufs[slot], np.asarray(sh.data))
        snapshot = HostSnapshot(
            self, buffer_set, self._specs, self._treedef, epoch=epoch,
            step_in_epoch=step_in_epoch, process_count=jax.process_count(),
            stream_cursor=stream_cursor)
        self.last_stall_s = time.perf_counter() - t0
        self._stall_s += self.last_stall_s
        return snapshot

    def consume_stall_s(self) -> float:
        """Accumulated staging stall since the last call (the loop divides
        by its record window — same contract as loader.consume_wait_s)."""
        s, self._stall_s = self._stall_s, 0.0
        return s

    # -- dispatch ------------------------------------------------------------
    def submit(self, state: PyTree, *, epoch: int, step_in_epoch: int = 0,
               stream_cursor: Optional[dict] = None,
               persist_to: Optional[str] = None, keep: int = 0,
               extra_meta: Optional[dict] = None,
               replicator=None, wait: bool = False) -> HostSnapshot:
        """stage() + enqueue the requested background jobs. `persist_to`
        writes an Orbax checkpoint for `epoch` through orbax_io.save_state
        (retries, sidecar, GC included); `replicator` mirrors the staged
        bytes to the ring buddy. wait=True (or VITAX_CKPT_SYNC=1) drains the
        queue before returning — the final/emergency save semantics."""
        import os
        wait = wait or os.environ.get("VITAX_CKPT_SYNC", "") == "1"
        snapshot = self.stage(state, epoch=epoch,
                              step_in_epoch=step_in_epoch,
                              stream_cursor=stream_cursor)
        jobs = []
        if persist_to is not None:
            jobs.append(lambda: self._persist(snapshot, persist_to,
                                              keep=keep,
                                              extra_meta=extra_meta,
                                              wait=wait))
        if replicator is not None:
            jobs.append(lambda: replicator.replicate(snapshot))
        for _ in jobs[1:]:
            snapshot.retain()
        if not jobs:
            snapshot.release()
            return snapshot
        for job in jobs:
            self._q.put((job, snapshot))
        if wait:
            self.drain()
        return snapshot

    @staticmethod
    def _persist(snapshot: HostSnapshot, ckpt_dir: str, *, keep: int,
                 extra_meta: Optional[dict], wait: bool) -> None:
        from vitax.checkpoint import orbax_io
        tree = snapshot.rebuild()
        orbax_io.save_state(  # vtx: ignore[VTX108] the worker thread IS the zero-stall path, off the step loop
            ckpt_dir, snapshot.epoch, tree, wait=wait,
            step_in_epoch=snapshot.step_in_epoch or None,
            stream_cursor=snapshot.stream_cursor, keep=keep,
            extra_meta=extra_meta)

    def drain(self) -> None:
        """Block until every queued job ran; surface any worker error."""
        self._q.join()
        self.raise_pending()

    def raise_pending(self) -> None:
        with self._cond:  # vs the worker's append in _run
            err = self._errors.pop(0) if self._errors else None
        if err is not None:
            raise RuntimeError(
                "snapshot pipeline: a background save/replicate job "
                "failed") from err

    def close(self) -> None:
        """Drain and stop the worker. Never raises (callers sit in finally
        blocks); pending errors are printed — the wait=True paths already
        surfaced anything fatal."""
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        join_or_warn(self._worker, timeout=60.0)
        with self._cond:
            errors = list(self._errors)
        for err in errors:
            print(f"vitax.snapshot: background job failed "
                  f"({type(err).__name__}: {err})", file=sys.stderr,
                  flush=True)

    # -- internals -----------------------------------------------------------
    def _acquire_buffers(self) -> list:
        with self._cond:
            while not self._free and self._allocated >= self.max_buffer_sets:
                # every set is in flight: wait for the worker to finish one.
                # Counted inside the stall clock — honest backpressure.
                self._cond.wait(timeout=1.0)
            if self._free:
                return self._free.pop()
            self._allocated += 1
        return [[np.empty(tuple(stop - start for start, stop in key),
                          dtype=spec.dtype)
                 for key in spec.indices]
                for spec in self._specs]

    def _return_buffers(self, buffer_set: list) -> None:
        with self._cond:
            self._free.append(buffer_set)
            self._cond.notify()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            job, snapshot = item
            try:
                job()
            except BaseException as e:  # noqa: BLE001 — surfaced at the next submit/drain, never lost
                with self._cond:
                    self._errors.append(e)
                print(f"vitax.snapshot: background job failed "
                      f"({type(e).__name__}: {e})", file=sys.stderr,
                      flush=True)
            finally:
                snapshot.release()
                self._q.task_done()
