"""Offline checkpoint consolidation: sharded epoch checkpoint -> one .npz file.

Parity with `python3 -m torch_xla.distributed.fsdp.consolidate_sharded_ckpts`
(cited at reference utils.py:27-29): produces a single-file, framework-neutral
export of the full (unsharded) parameters for serving/analysis.

Unlike the reference's tool, no shard metadata is needed — Orbax checkpoints are
already topology-independent; this tool simply restores on host and flattens.

The export is the direct input to the serving stack:
`vitax.serve.InferenceEngine.from_npz` restores the exact param tree from it
via the shared `flatten_tree` / `unflatten_tree` helpers below (see the
README "Serving" section and vitax/serve/engine.py).

Usage:
    python -m vitax.checkpoint.consolidate --ckpt_dir /path --epoch 10 --out full.npz
    python -m vitax.checkpoint.consolidate ... --params_only
    python -m vitax.checkpoint.consolidate ... --dtype bfloat16   # half-size export
"""

from __future__ import annotations

import argparse
from typing import Dict, Optional

import numpy as np

from vitax.checkpoint.orbax_io import epoch_ckpt_path

# npz has no native bfloat16: bf16 arrays are stored as uint16 bit-views and
# their keys recorded under this manifest entry, so load_npz can restore the
# exact dtype. The key cannot collide with a param path ("/"-joined names).
BF16_MANIFEST_KEY = "__bfloat16_keys__"


def flatten_tree(tree, sep: str = "/") -> Dict[str, np.ndarray]:
    """Flatten a (nested-dict) param tree to {"a/b/c": np.ndarray}.

    The inverse of `unflatten_tree`: consolidate writes with this and
    `InferenceEngine.from_npz` reads with that, so the two sides share one
    key convention by construction."""
    import jax
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = sep.join(
            str(getattr(p, "key", getattr(p, "name", getattr(p, "idx", p))))
            for p in path)
        out[key] = np.asarray(leaf)
    return out


def unflatten_tree(flat: Dict[str, np.ndarray], sep: str = "/") -> dict:
    """Rebuild the nested dict tree from flatten_tree's "/"-joined keys."""
    tree: dict = {}
    for key, leaf in flat.items():
        parts = key.split(sep)
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return tree


def save_npz(out: str, flat: Dict[str, np.ndarray],
             dtype: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Write a flat tree as .npz, optionally casting every float array.

    dtype "bfloat16" halves the export; bf16 has no npz dtype, so those
    arrays are stored as uint16 bit-views plus a key manifest
    (BF16_MANIFEST_KEY) that load_npz uses to restore them exactly."""
    import ml_dtypes
    if dtype:
        target = (ml_dtypes.bfloat16 if dtype == "bfloat16"
                  else np.dtype(dtype))
        flat = {k: v.astype(target) if np.issubdtype(v.dtype, np.floating)
                or v.dtype == ml_dtypes.bfloat16 else v
                for k, v in flat.items()}
    bf16_keys = sorted(k for k, v in flat.items()
                       if v.dtype == ml_dtypes.bfloat16)
    payload = {k: (v.view(np.uint16) if k in bf16_keys else v)
               for k, v in flat.items()}
    if bf16_keys:
        payload[BF16_MANIFEST_KEY] = np.asarray(bf16_keys)
    np.savez(out, **payload)
    return flat


def load_npz(path: str) -> Dict[str, np.ndarray]:
    """Read a save_npz export back to {key: array}, restoring bf16 views."""
    import ml_dtypes
    with np.load(path) as data:
        bf16 = (set(str(k) for k in data[BF16_MANIFEST_KEY])
                if BF16_MANIFEST_KEY in data.files else set())
        return {k: (data[k].view(ml_dtypes.bfloat16) if k in bf16
                    else data[k])
                for k in data.files if k != BF16_MANIFEST_KEY}


def consolidate(ckpt_dir: str, epoch: int, out: str, params_only: bool = True,
                dtype: Optional[str] = None) -> dict:
    import jax
    import orbax.checkpoint as ocp

    from vitax.checkpoint.orbax_io import wait_until_finished
    wait_until_finished()  # same-process async save of this epoch must commit
    path = epoch_ckpt_path(ckpt_dir, epoch)
    # Restore every leaf as a plain numpy array (restore_type=np.ndarray).
    # A targetless restore would instead rebuild the SAVED device mesh from
    # the sharding file — impossible on this host for a checkpoint written
    # by a multi-host run (its device ids don't exist here). Consolidation
    # must work from any single machine regardless of save topology.
    with ocp.PyTreeCheckpointer() as ckptr:
        meta = ckptr.metadata(path)
        restore_args = jax.tree.map(
            lambda _: ocp.RestoreArgs(restore_type=np.ndarray), meta)
        state = ckptr.restore(path, restore_args=restore_args)
    tree = state["params"] if params_only and "params" in state else state
    flat = save_npz(out, flatten_tree(tree), dtype=dtype)
    total = sum(v.size for v in flat.values())
    print(f"consolidated {len(flat)} arrays ({total:,} elements"
          + (f", cast to {dtype}" if dtype else "")
          + f") from {path} -> {out}")
    return flat


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--ckpt_dir", type=str, required=True)
    p.add_argument("--epoch", type=int, required=True)
    p.add_argument("--out", type=str, required=True)
    p.add_argument("--full_state", action="store_false", dest="params_only",
                   help="include optimizer state and step, not just params")
    p.add_argument("--dtype", type=str, default=None,
                   choices=["float32", "bfloat16"],
                   help="cast float arrays for the export (default: keep "
                        "the stored dtype). bfloat16 halves the file — the "
                        "serving engine computes in bf16 anyway "
                        "(vitax/serve/engine.py from_npz)")
    args = p.parse_args(argv)
    consolidate(args.ckpt_dir, args.epoch, args.out, args.params_only,
                dtype=args.dtype)


if __name__ == "__main__":
    main()
