"""Offline checkpoint consolidation: sharded epoch checkpoint -> one .npz file.

Parity with `python3 -m torch_xla.distributed.fsdp.consolidate_sharded_ckpts`
(cited at reference utils.py:27-29): produces a single-file, framework-neutral
export of the full (unsharded) parameters for serving/analysis.

Unlike the reference's tool, no shard metadata is needed — Orbax checkpoints are
already topology-independent; this tool simply restores on host and flattens.

Usage:
    python -m vitax.checkpoint.consolidate --ckpt_dir /path --epoch 10 --out full.npz
    python -m vitax.checkpoint.consolidate ... --params_only
"""

from __future__ import annotations

import argparse

import numpy as np

from vitax.checkpoint.orbax_io import epoch_ckpt_path


def _flatten(tree, prefix=""):
    import jax
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(
            str(getattr(p, "key", getattr(p, "name", getattr(p, "idx", p)))) for p in path)
        out[key] = np.asarray(leaf)
    return out


def consolidate(ckpt_dir: str, epoch: int, out: str, params_only: bool = True) -> dict:
    import orbax.checkpoint as ocp

    from vitax.checkpoint.orbax_io import wait_until_finished
    wait_until_finished()  # same-process async save of this epoch must commit
    path = epoch_ckpt_path(ckpt_dir, epoch)
    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore(path)  # host restore: full numpy arrays
    tree = state["params"] if params_only and "params" in state else state
    flat = _flatten(tree)
    np.savez(out, **flat)
    total = sum(v.size for v in flat.values())
    print(f"consolidated {len(flat)} arrays ({total:,} elements) from {path} -> {out}")
    return flat


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--ckpt_dir", type=str, required=True)
    p.add_argument("--epoch", type=int, required=True)
    p.add_argument("--out", type=str, required=True)
    p.add_argument("--full_state", action="store_false", dest="params_only",
                   help="include optimizer state and step, not just params")
    args = p.parse_args(argv)
    consolidate(args.ckpt_dir, args.epoch, args.out, args.params_only)


if __name__ == "__main__":
    main()
